"""Decode-engine throughput probe for real hardware.

Times the full continuous-batching engine loop against the HBM roofline
across (slots, cache length, chunk) points — the knobs that matter for
serving. PD_SIZE=350m for a smaller model; PD_SPEC=1 adds a chunked
speculative run on repetitive prompts.

Measurement notes learned the hard way (r5):
- On the tunneled PJRT backend ``jax.block_until_ready`` does NOT block;
  sync by fetching a scalar (the engine's own host loop does this
  naturally).
- Per-dispatch tunnel RTT is ~4 ms; only in-jit loops (the engine's
  ``steps_per_call`` chunking) measure device time. For sub-step
  breakdowns, time a lax.scan of K steps at two K values and use the
  slope.
- Run-to-run variance on the shared chip is +-1.5 ms/step; use min over
  several runs for A/B decisions.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from paddle_tpu.models import gpt
from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)


def run_engine(model, slots=8, s_pf=128, n_new=128, chunk=64, spec_k=0):
    cfg = model.cfg
    eng = DecodeEngine(model, max_slots=slots,
                       max_len=s_pf + n_new + (128 + spec_k if spec_k
                                               else 0),
                       steps_per_call=chunk, speculative_k=spec_k)
    rs = np.random.RandomState(1)
    if spec_k:   # repetition-heavy prompts: the regime spec serves
        loops = [list(rs.randint(0, cfg.vocab_size, 8))
                 for _ in range(slots)]
        prompts = [(lp * (s_pf // 8 + 1))[:s_pf] for lp in loops]
    else:
        prompts = [rs.randint(0, cfg.vocab_size, s_pf)
                   for _ in range(slots)]
    for p in prompts:
        eng.submit(p, max_new_tokens=2)
    eng.run()  # warm compile
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.step()
    pre = sum(len(r.tokens) for r in reqs)
    d0 = eng.steps
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs) - pre
    dispatches = eng.steps - d0
    eng.kc = eng.vc = eng._stacked = None
    del eng
    return toks / dt, dispatches


def main():
    size = os.environ.get("PD_SIZE", "1p3b")
    cfg = (gpt.gpt3_1p3b(max_seq_len=2048) if size == "1p3b"
           else gpt.gpt3_350m(max_seq_len=1024))
    print("building model", size, flush=True)
    model = gpt.GPT(cfg, seed=0)
    dev = jax.devices()[0]
    print("device:", dev, flush=True)

    from paddle_tpu.cost_model import _peak
    hbm = _peak(dev)[1] / 1e9

    for slots, s_pf, n_new in ((8, 128, 128), (16, 128, 128)):
        roof = decode_roofline_tokens_per_sec(
            cfg, slots, s_pf + n_new // 2, hbm)
        tps, disp = run_engine(model, slots=slots, s_pf=s_pf, n_new=n_new)
        print(f"slots={slots} ctx={s_pf}+{n_new}: {tps:.1f} tok/s "
              f"({disp} dispatches) roofline={roof:.0f} "
              f"ratio={tps / roof:.3f}", flush=True)

    if os.environ.get("PD_SPEC", "0") == "1":
        roof = decode_roofline_tokens_per_sec(cfg, 8, 192, hbm)
        tps, disp = run_engine(model, chunk=16, spec_k=4)
        print(f"spec k=4 chunk=16: {tps:.1f} tok/s ({disp} dispatches) "
              f"vs roofline={roof:.0f} ratio={tps / roof:.3f}", flush=True)


if __name__ == "__main__":
    main()
