"""Decode-engine throughput probe for real hardware.

Times the full continuous-batching engine loop against the HBM roofline
across (slots, cache length, chunk) points — the knobs that matter for
serving. PD_SIZE=350m for a smaller model; PD_SPEC=1 adds a chunked
speculative run on repetitive prompts; PD_SECTIONS=engine,paged,prof
picks report sections; PD_PREFIX=1 adds the repeated-system-prompt
sweep (cold vs warm radix-cache admission, asserted — the `tools/ci.sh
paged` smoke gate); PD_SECTIONS=prof runs the ISSUE 15 device-time
attribution sweep (roofline fraction, launch tax, step decomposition
per decode path across PD_LENGTHS prompt lengths — the `tools/ci.sh
prof` gate); PD_SECTIONS=mega runs the ISSUE 19 launches/step report
(jaxpr pallas-launch count, AOT HLO custom-call count and the
serve/dispatch_launches window delta for the megakernel vs per-layer
paged paths — the `tools/ci.sh mega` gate).

Measurement notes learned the hard way (r5):
- On the tunneled PJRT backend ``jax.block_until_ready`` does NOT block;
  sync by fetching a scalar (the engine's own host loop does this
  naturally).
- Per-dispatch tunnel RTT is ~4 ms; only in-jit loops (the engine's
  ``steps_per_call`` chunking) measure device time. For sub-step
  breakdowns, time a lax.scan of K steps at two K values and use the
  slope.
- Run-to-run variance on the shared chip is +-1.5 ms/step; use min over
  several runs for A/B decisions.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from paddle_tpu.models import gpt
from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)


def release_engine(eng):
    """Drop an engine's big device buffers — the donor weight stack and
    whichever KV pool attributes the engine variant carries — so the next
    engine built in this process doesn't OOM against the last one's
    arrays. The ONE definition (was copy-pasted at three sites): tolerant
    of attrs a variant lacks and of the sharded stacked state (a pytree
    of per-device arrays nulls the same way a single-chip stack does)."""
    for attr in ("kc", "vc", "kp", "vp", "_stacked"):
        if hasattr(eng, attr):
            setattr(eng, attr, None)


def pipeline_report(eng):
    """ISSUE 4: in-flight depth, per-step host gap, and dispatch/harvest
    overlap, measured from the trace ring + stats histograms of the run
    just finished. 'overlap' = fraction of harvests that blocked while
    at least one younger dispatch was already enqueued (the lag-one
    win); 'host_gap' = host-side bubble between consecutive dispatch
    enqueues — what the device idles on at depth 1."""
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    snap = stats.snapshot("serve/")
    evs, _ = trace.events()
    spans = [e for e in evs if e is not None]
    disp = [e for e in spans if e[0] == "serve/dispatch"]
    harv = [e for e in spans if e[0] == "serve/harvest"]
    # overlap over DECODE harvests only (prefill records are admission
    # plumbing): the fraction whose blocking readback ran while a
    # younger dispatch was already keeping the device busy
    dec = [e for e in harv if (e[6] or {}).get("kind") != "prefill"]
    overlapped = sum(1 for e in dec
                     if (e[6] or {}).get("inflight", 0) >= 1)
    return {
        "depth": eng.depth,
        "host_gap_p50_ms": snap.get("serve/host_gap_s.p50", 0) * 1e3,
        "host_gap_p99_ms": snap.get("serve/host_gap_s.p99", 0) * 1e3,
        "dispatch_ms": sum(e[2] for e in disp) / 1e6,
        "harvest_ms": sum(e[2] for e in harv) / 1e6,
        "overlap": overlapped / max(1, len(dec)),
    }


def run_engine(model, slots=8, s_pf=128, n_new=128, chunk=64, spec_k=0,
               inflight=None, warmup=False):
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    cfg = model.cfg
    eng = DecodeEngine(model, max_slots=slots,
                       max_len=s_pf + n_new + (128 + spec_k if spec_k
                                               else 0),
                       steps_per_call=chunk, speculative_k=spec_k,
                       inflight=inflight, warmup=warmup)
    rs = np.random.RandomState(1)
    if spec_k:   # repetition-heavy prompts: the regime spec serves
        loops = [list(rs.randint(0, cfg.vocab_size, 8))
                 for _ in range(slots)]
        prompts = [(lp * (s_pf // 8 + 1))[:s_pf] for lp in loops]
    else:
        prompts = [rs.randint(0, cfg.vocab_size, s_pf)
                   for _ in range(slots)]
    for p in prompts:
        eng.submit(p, max_new_tokens=2)
    eng.run()  # warm compile (no-op with warmup=True)
    stats.reset("serve/")
    trace.clear(capacity=65536)
    trace.enable()          # in-memory ring only: no file unless asked
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.step()
    pre = sum(len(r.tokens) for r in reqs)
    d0 = eng.steps
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs) - pre
    dispatches = eng.steps - d0
    rep = pipeline_report(eng)
    trace.disable()
    trace.clear()
    release_engine(eng)
    del eng
    return toks / dt, dispatches, rep


def run_paged(model, prompts, n_new=128, chunk=64, inflight=None,
              n_pages=None, max_slots=None):
    """Paged-engine drain timing (ISSUE 6): submit `prompts`, time the
    drain, and return (tok/s, dispatches, pipeline report, prefix
    stats). The engine keeps the prefix radix cache at its default
    (on), so repeated calls against the same engine measure warm-cache
    admission; pass fresh random prompts for a cold decode number."""
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    page = 128
    slots = max_slots or len(prompts)
    if n_pages is None:
        need = max(len(p) + n_new for p in prompts)
        n_pages = slots * ((need + page - 1) // page + 1) + 4
    eng = PagedDecodeEngine(model, n_pages=n_pages, max_slots=slots,
                            page_size=page, steps_per_call=chunk,
                            inflight=inflight)
    # warm the compiles on DISJOINT prompts of the same lengths so the
    # timed round's trie lookups miss (its tok/s stays a decode number)
    rs = np.random.RandomState(4242)
    vocab = eng.cfg.vocab_size
    for p in prompts:
        eng.submit(list(rs.randint(0, vocab, len(p))), max_new_tokens=2)
    eng.run()
    stats.reset("serve/")
    trace.clear(capacity=65536)
    trace.enable()
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.step()
    pre = sum(len(r.tokens) for r in reqs)
    d0 = eng.steps
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs) - pre
    dispatches = eng.steps - d0
    rep = pipeline_report(eng)
    snap = stats.snapshot("serve/")
    n_prompt = sum(len(p) for p in prompts)
    pfx = {
        "hit_tokens": int(snap.get("serve/prefix_hit_tokens", 0)),
        "lookups": int(snap.get("serve/prefix_lookup", 0)),
        "hit_rate": snap.get("serve/prefix_hit_tokens", 0)
        / max(1, n_prompt),
        "pool_free": int(snap.get("serve/pool_pages_free", 0)),
        "pool_shared": int(snap.get("serve/pool_pages_shared", 0)),
    }
    trace.disable()
    trace.clear()
    release_engine(eng)
    del eng
    return toks / dt, dispatches, rep, pfx


def prefix_sweep(model, slots, shared_len, tail_len, n_new, chunk):
    """PD_PREFIX=1: repeated-system-prompt sweep. Round 1 submits
    `slots` prompts sharing one page-aligned `shared_len`-token system
    prefix (cold: registers the chain); round 2 submits NEW tails
    behind the same prefix (warm: must prefill only the tails). Prints
    admission+drain wall time and hit tokens for both rounds and
    asserts the warm round actually hit — `tools/ci.sh paged` relies
    on that assert as its regression gate."""
    from paddle_tpu import stats
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    cfg = model.cfg
    page = 128
    assert shared_len % page == 0, "system prefix must be page-aligned"
    rs = np.random.RandomState(7)
    shared = list(rs.randint(0, cfg.vocab_size, shared_len))
    need = shared_len + tail_len + n_new
    n_pages = 2 * (shared_len // page) + slots * (
        (need + page - 1) // page + 1) + 4
    eng = PagedDecodeEngine(model, n_pages=n_pages, max_slots=slots,
                            page_size=page, steps_per_call=chunk)
    # compile warm-up on a TRIE-DISJOINT prefix at the exact timed
    # geometry: first submit traces the full prefill (the cold round's
    # shape), the second — same warm prefix, new tail — traces the
    # suffix prefill (the warm round's shape). The timed rounds then
    # measure prefill/decode work, not jit compilation.
    warm_pfx = list(rs.randint(0, cfg.vocab_size, shared_len))
    for _ in range(2):
        eng.submit(warm_pfx + list(rs.randint(0, cfg.vocab_size,
                                              tail_len)),
                   max_new_tokens=n_new)
        eng.run()

    def round_(label):
        stats.reset("serve/prefix")
        prompts = [shared + list(rs.randint(0, cfg.vocab_size, tail_len))
                   for _ in range(slots)]
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        eng.run()
        dt = time.perf_counter() - t0
        snap = stats.snapshot("serve/prefix")
        hits = int(snap.get("serve/prefix_hit_tokens", 0))
        toks = sum(len(r.tokens) for r in reqs)
        print(f"  {label}: {dt * 1e3:.1f}ms wall "
              f"({toks} new tokens, {slots}x({shared_len}+{tail_len}) "
              f"prompt) prefix_hit_tokens={hits}", flush=True)
        return hits

    print(f"prefix sweep: shared system prompt {shared_len} tokens, "
          f"{slots} slots", flush=True)
    cold = round_("cold")
    warm = round_("warm")
    # the warm round must hit at least one full shared page per slot —
    # the submit path then prefills only the suffix tokens
    assert warm >= slots * page, (
        f"warm shared-prefix round hit only {warm} tokens "
        f"(expected >= {slots * page}): prefix cache regressed")
    assert warm > cold, "warm round should out-hit the cold round"
    release_engine(eng)
    del eng


def _prof_run(eng, prompts, n_new):
    """One timed drain for the prof section: warm on trie-disjoint
    prompts of the same lengths, then measure tokens / wall /
    dispatch-launch count / step decomposition over the timed window
    (stats + trace ring reset at its start)."""
    from paddle_tpu import stats
    from paddle_tpu.observability import devprof, trace
    rs = np.random.RandomState(99)
    for p in prompts:
        eng.submit(list(rs.randint(0, eng.cfg.vocab_size, len(p))),
                   max_new_tokens=2)
    eng.run()
    stats.reset("serve/")
    trace.clear(capacity=65536)
    trace.enable()
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    t0 = time.perf_counter()
    eng.run()
    wall = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs)
    launches = int(stats.get("serve/dispatch_launches", 0))
    frac = devprof.step_fractions()
    trace.disable()
    trace.clear()
    return toks, wall, launches, frac


def prof_section(model, size):
    """ISSUE 15 tentpole report: device-time attribution per decode
    path (contiguous + paged) across a prompt-length sweep. Each row
    prints measured tok/s vs the AOT cost-analysis roofline tok/s, the
    roofline fraction, dispatch launches per token, and the launch-tax
    fraction of token time — the 'one-pallas-launch-per-layer at short
    lengths' hypothesis as a number. PD_LENGTHS overrides the sweep
    (>=3 lengths keep the tax-vs-length curve readable). The asserts
    are the `tools/ci.sh prof` smoke gate."""
    from paddle_tpu.observability import devprof
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    cfg = model.cfg
    tiny = size == "tiny"
    default = "32,64,128" if tiny else "128,512,1024"
    lengths = [int(x) for x in os.environ.get(
        "PD_LENGTHS", default).split(",") if x.strip()]
    slots, n_new = (4, 16) if tiny else (8, 64)
    chunk = 4 if tiny else 32
    page = 128
    tax = devprof.launch_tax_s()
    ptax = devprof.pallas_launch_tax_s()
    line = f"launch tax: jit no-op {tax * 1e6:.0f}us/dispatch"
    if ptax is not None:
        line += (f", pallas no-op {ptax * 1e6:.0f}us/launch "
                 f"(x{cfg.n_layers} layers/dispatch on the fused "
                 f"paged path)")
    print(line, flush=True)
    rs = np.random.RandomState(13)
    donor = None
    for path in ("contiguous", "paged"):
        for s_pf in lengths:
            if path == "contiguous":
                eng = DecodeEngine(
                    model if donor is None else None, max_slots=slots,
                    max_len=s_pf + n_new, steps_per_call=chunk,
                    share_weights_with=donor)
                if donor is None:
                    donor = eng
            else:
                n_pages = slots * ((s_pf + n_new + page - 1) // page
                                   + 1) + 4
                eng = PagedDecodeEngine(
                    None, n_pages=n_pages, max_slots=slots,
                    page_size=page, steps_per_call=chunk,
                    share_weights_with=donor)
            prompts = [list(rs.randint(0, cfg.vocab_size, s_pf))
                       for _ in range(slots)]
            toks, wall, launches, frac = _prof_run(eng, prompts, n_new)
            name = f"{path}_{s_pf}"
            cap = eng.dispatch_cost(name=name)
            aroof = devprof.roofline_tokens_per_sec(
                cap, toks / max(1, launches))
            rfrac = devprof.record_roofline(name, toks / wall, aroof)
            lt = devprof.launch_tax_fraction(launches, wall, name=name)
            print(f"prof {path} len={s_pf}: {toks / wall:.1f} tok/s "
                  f"vs roofline {aroof:.1f} (frac {rfrac:.3f}) "
                  f"launches/token={launches / max(1, toks):.3f} "
                  f"launch_tax_frac={lt:.3f} "
                  f"flops/dispatch={cap.flops:.3g} "
                  f"hbm_bytes/dispatch={cap.hbm_bytes:.3g}",
                  flush=True)
            if frac:
                print(f"  step split: device={frac['device_frac']:.0%} "
                      f"queue={frac['queue_frac']:.0%} "
                      f"host={frac['host_frac']:.0%}"
                      + ("  [HOST-BOUND]" if frac["host_bound"]
                         else ""), flush=True)
            # `tools/ci.sh prof` gate: the capture must be real and the
            # tax fraction a sane fraction of the wall
            assert cap.flops > 0 and cap.hbm_bytes > 0, (
                f"{name}: cost_analysis returned no flops/bytes")
            assert 0 < lt <= 1.0, f"{name}: launch_tax_frac {lt}"
            assert launches > 0 and toks > 0
            if eng is not donor:
                release_engine(eng)
            del eng
    release_engine(donor)


def mega_section(model, size):
    """ISSUE 19 launches/step report: the single-dispatch decode claim
    as numbers. For each paged path (megakernel, per-layer reference,
    megakernel+spec) prints

    - pallas launches per engine step, counted from the dispatch
      program's jaxpr (scan-trip weighted — backend-independent, no
      execution);
    - the HLO custom-call count from the AOT lowering (on TPU each
      pallas launch compiles to one custom-call; in CPU interpret mode
      pallas lowers to inline HLO, so the count reads 0);
    - the ``serve/dispatch_launches`` window delta over a short timed
      drain (host dispatches actually issued).

    The asserts are the `tools/ci.sh mega` CPU smoke gate: the
    megakernel steps in <= 2 launches (layer-folded kernel + fused
    sampling epilogue) on the plain AND speculative paths, while the
    per-layer reference pays one paged launch per layer."""
    from paddle_tpu import stats
    from paddle_tpu.observability import devprof
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    cfg = model.cfg
    if cfg.n_layers < 3:
        # at L=2 the megakernel's 2 launches and one-per-layer coincide;
        # the distinguishing count needs >= 3 layers (cheap at tiny dims)
        cfg = gpt.GPTConfig(vocab_size=cfg.vocab_size, max_seq_len=256,
                            d_model=cfg.d_model, n_layers=3,
                            n_heads=cfg.n_heads, dtype=cfg.dtype)
        model = gpt.GPT(cfg, seed=0)
        print(f"mega section: rebuilt at n_layers=3 (launch counts at "
              f"L=2 cannot distinguish folding)", flush=True)
    tiny = size == "tiny" or cfg.d_model <= 64
    slots, s_pf, n_new = (2, 16, 8) if tiny else (8, 128, 64)
    chunk = 2 if tiny else 16
    page = 128
    n_pages = slots * ((s_pf + n_new + 4) // page + 2) + 2
    rs = np.random.RandomState(5)
    counts = {}
    for label, kw in (("mega", dict(mega=True)),
                      ("per_layer", dict(mega=False)),
                      ("mega_spec", dict(mega=True, speculative_k=3))):
        eng = PagedDecodeEngine(model, n_pages=n_pages, max_slots=slots,
                                page_size=page, steps_per_call=chunk,
                                **kw)
        assert eng.fused, "mega section needs the fused paged path"
        prompts = [list(rs.randint(0, cfg.vocab_size, s_pf))
                   for _ in range(slots)]
        for p in prompts:   # warm compiles + establish live geometry
            eng.submit(p, max_new_tokens=2)
        eng.run()
        fn, fargs = eng.dispatch_fn_args()
        lpc = devprof.count_pallas_launches(fn, *fargs)
        per_step = lpc / chunk
        hlo_cc = devprof.count_hlo_custom_calls(fn, *fargs)
        d0 = int(stats.get("serve/dispatch_launches", 0))
        reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        eng.run()
        host_disp = int(stats.get("serve/dispatch_launches", 0)) - d0
        toks = sum(len(r.tokens) for r in reqs)
        counts[label] = per_step
        print(f"mega {label}: launches/step={per_step:g} "
              f"(jaxpr, {lpc} per {chunk}-step dispatch) "
              f"hlo_custom_calls="
              f"{'n/a' if hlo_cc is None else hlo_cc} "
              f"dispatch_launches_delta={host_disp} "
              f"({toks} tokens)", flush=True)
        assert host_disp > 0 and toks > 0
        release_engine(eng)
        del eng
    # the `tools/ci.sh mega` gate: single-dispatch decode, by count
    assert counts["mega"] <= 2, counts
    assert counts["mega_spec"] <= 2, counts
    assert counts["per_layer"] == cfg.n_layers, (counts, cfg.n_layers)
    print(f"mega gate: mega {counts['mega']:g} <= 2, spec "
          f"{counts['mega_spec']:g} <= 2, per-layer reference "
          f"{counts['per_layer']:g} == n_layers={cfg.n_layers}",
          flush=True)


def main():
    size = os.environ.get("PD_SIZE", "1p3b")
    cfg = (gpt.gpt3_1p3b(max_seq_len=2048) if size == "1p3b"
           else gpt.gpt_tiny(max_seq_len=512) if size == "tiny"
           else gpt.gpt3_350m(max_seq_len=1024))
    print("building model", size, flush=True)
    model = gpt.GPT(cfg, seed=0)
    dev = jax.devices()[0]
    print("device:", dev, flush=True)

    from paddle_tpu.cost_model import _peak
    hbm = _peak(dev)[1] / 1e9

    def show(label, tps, disp, roof, rep):
        print(f"{label}: {tps:.1f} tok/s ({disp} dispatches) "
              f"roofline={roof:.0f} ratio={tps / roof:.3f}", flush=True)
        print(f"  pipeline: depth={rep['depth']} "
              f"host_gap p50={rep['host_gap_p50_ms']:.2f}ms "
              f"p99={rep['host_gap_p99_ms']:.2f}ms "
              f"dispatch={rep['dispatch_ms']:.1f}ms "
              f"harvest={rep['harvest_ms']:.1f}ms "
              f"overlap={rep['overlap']:.0%}", flush=True)

    # PD_INFLIGHT sweeps explicit depths (e.g. PD_INFLIGHT=1,2,4) to
    # A/B the pipeline against the synchronous baseline; unset uses the
    # engine default (PT_SERVE_INFLIGHT or 2). PD_SECTIONS picks which
    # report sections run ("engine,paged" default; `tools/ci.sh paged`
    # runs sections=paged on the tiny model as its CPU smoke).
    sweep = [int(x) for x in os.environ.get("PD_INFLIGHT", "").split(",")
             if x.strip()] or [None]
    sections = {s.strip() for s in os.environ.get(
        "PD_SECTIONS", "engine,paged").split(",") if s.strip()}

    if "engine" in sections:
        for slots, s_pf, n_new in ((8, 128, 128), (16, 128, 128)):
            roof = decode_roofline_tokens_per_sec(
                cfg, slots, s_pf + n_new // 2, hbm)
            for depth in sweep:
                tps, disp, rep = run_engine(model, slots=slots,
                                            s_pf=s_pf, n_new=n_new,
                                            inflight=depth)
                show(f"slots={slots} ctx={s_pf}+{n_new}", tps, disp,
                     roof, rep)

    if os.environ.get("PD_SPEC", "0") == "1" and "engine" in sections:
        roof = decode_roofline_tokens_per_sec(cfg, 8, 192, hbm)
        for depth in sweep:
            tps, disp, rep = run_engine(model, chunk=16, spec_k=4,
                                        inflight=depth)
            show("spec k=4 chunk=16", tps, disp, roof, rep)

    if "paged" in sections:
        # paged decode vs the SAME analytic HBM roofline the contiguous
        # engine is scored against (decode is bandwidth-bound; paging
        # changes layout, not bytes-that-must-move) — the gap between
        # the two ratios is the paged kernel's overhead. Fresh random
        # prompts per depth keep the timed round prefix-cold so the
        # tok/s is a decode number, not an admission number.
        tiny = size == "tiny"
        slots, s_pf, n_new = (4, 128, 16) if tiny else (8, 128, 128)
        chunk = 8 if tiny else 64
        roof = decode_roofline_tokens_per_sec(
            cfg, slots, s_pf + n_new // 2, hbm)
        rs = np.random.RandomState(11)
        for depth in sweep:
            prompts = [list(rs.randint(0, cfg.vocab_size, s_pf))
                       for _ in range(slots)]
            tps, disp, rep, pfx = run_paged(model, prompts, n_new=n_new,
                                            chunk=chunk, inflight=depth)
            show(f"paged slots={slots} ctx={s_pf}+{n_new}", tps, disp,
                 roof, rep)
            print(f"  prefix: hit_rate={pfx['hit_rate']:.0%} "
                  f"hit_tokens={pfx['hit_tokens']} "
                  f"lookups={pfx['lookups']} "
                  f"pool free={pfx['pool_free']} "
                  f"shared={pfx['pool_shared']}", flush=True)

        if os.environ.get("PD_PREFIX", "0") == "1":
            prefix_sweep(model, slots=slots,
                         shared_len=256 if not tiny else 128,
                         tail_len=32, n_new=8 if tiny else 32,
                         chunk=chunk)

    if "prof" in sections:
        prof_section(model, size)

    if "mega" in sections:
        mega_section(model, size)


if __name__ == "__main__":
    main()
