"""Decode-engine throughput probe for real hardware.

Times the full continuous-batching engine loop against the HBM roofline
across (slots, cache length, chunk) points — the knobs that matter for
serving. PD_SIZE=350m for a smaller model; PD_SPEC=1 adds a chunked
speculative run on repetitive prompts.

Measurement notes learned the hard way (r5):
- On the tunneled PJRT backend ``jax.block_until_ready`` does NOT block;
  sync by fetching a scalar (the engine's own host loop does this
  naturally).
- Per-dispatch tunnel RTT is ~4 ms; only in-jit loops (the engine's
  ``steps_per_call`` chunking) measure device time. For sub-step
  breakdowns, time a lax.scan of K steps at two K values and use the
  slope.
- Run-to-run variance on the shared chip is +-1.5 ms/step; use min over
  several runs for A/B decisions.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from paddle_tpu.models import gpt
from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)


def pipeline_report(eng):
    """ISSUE 4: in-flight depth, per-step host gap, and dispatch/harvest
    overlap, measured from the trace ring + stats histograms of the run
    just finished. 'overlap' = fraction of harvests that blocked while
    at least one younger dispatch was already enqueued (the lag-one
    win); 'host_gap' = host-side bubble between consecutive dispatch
    enqueues — what the device idles on at depth 1."""
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    snap = stats.snapshot("serve/")
    evs, _ = trace.events()
    spans = [e for e in evs if e is not None]
    disp = [e for e in spans if e[0] == "serve/dispatch"]
    harv = [e for e in spans if e[0] == "serve/harvest"]
    # overlap over DECODE harvests only (prefill records are admission
    # plumbing): the fraction whose blocking readback ran while a
    # younger dispatch was already keeping the device busy
    dec = [e for e in harv if (e[6] or {}).get("kind") != "prefill"]
    overlapped = sum(1 for e in dec
                     if (e[6] or {}).get("inflight", 0) >= 1)
    return {
        "depth": eng.depth,
        "host_gap_p50_ms": snap.get("serve/host_gap_s.p50", 0) * 1e3,
        "host_gap_p99_ms": snap.get("serve/host_gap_s.p99", 0) * 1e3,
        "dispatch_ms": sum(e[2] for e in disp) / 1e6,
        "harvest_ms": sum(e[2] for e in harv) / 1e6,
        "overlap": overlapped / max(1, len(dec)),
    }


def run_engine(model, slots=8, s_pf=128, n_new=128, chunk=64, spec_k=0,
               inflight=None, warmup=False):
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    cfg = model.cfg
    eng = DecodeEngine(model, max_slots=slots,
                       max_len=s_pf + n_new + (128 + spec_k if spec_k
                                               else 0),
                       steps_per_call=chunk, speculative_k=spec_k,
                       inflight=inflight, warmup=warmup)
    rs = np.random.RandomState(1)
    if spec_k:   # repetition-heavy prompts: the regime spec serves
        loops = [list(rs.randint(0, cfg.vocab_size, 8))
                 for _ in range(slots)]
        prompts = [(lp * (s_pf // 8 + 1))[:s_pf] for lp in loops]
    else:
        prompts = [rs.randint(0, cfg.vocab_size, s_pf)
                   for _ in range(slots)]
    for p in prompts:
        eng.submit(p, max_new_tokens=2)
    eng.run()  # warm compile (no-op with warmup=True)
    stats.reset("serve/")
    trace.clear(capacity=65536)
    trace.enable()          # in-memory ring only: no file unless asked
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.step()
    pre = sum(len(r.tokens) for r in reqs)
    d0 = eng.steps
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs) - pre
    dispatches = eng.steps - d0
    rep = pipeline_report(eng)
    trace.disable()
    trace.clear()
    eng.kc = eng.vc = eng._stacked = None
    del eng
    return toks / dt, dispatches, rep


def main():
    size = os.environ.get("PD_SIZE", "1p3b")
    cfg = (gpt.gpt3_1p3b(max_seq_len=2048) if size == "1p3b"
           else gpt.gpt_tiny(max_seq_len=512) if size == "tiny"
           else gpt.gpt3_350m(max_seq_len=1024))
    print("building model", size, flush=True)
    model = gpt.GPT(cfg, seed=0)
    dev = jax.devices()[0]
    print("device:", dev, flush=True)

    from paddle_tpu.cost_model import _peak
    hbm = _peak(dev)[1] / 1e9

    def show(label, tps, disp, roof, rep):
        print(f"{label}: {tps:.1f} tok/s ({disp} dispatches) "
              f"roofline={roof:.0f} ratio={tps / roof:.3f}", flush=True)
        print(f"  pipeline: depth={rep['depth']} "
              f"host_gap p50={rep['host_gap_p50_ms']:.2f}ms "
              f"p99={rep['host_gap_p99_ms']:.2f}ms "
              f"dispatch={rep['dispatch_ms']:.1f}ms "
              f"harvest={rep['harvest_ms']:.1f}ms "
              f"overlap={rep['overlap']:.0%}", flush=True)

    # PD_INFLIGHT sweeps explicit depths (e.g. PD_INFLIGHT=1,2,4) to
    # A/B the pipeline against the synchronous baseline; unset uses the
    # engine default (PT_SERVE_INFLIGHT or 2)
    sweep = [int(x) for x in os.environ.get("PD_INFLIGHT", "").split(",")
             if x.strip()] or [None]

    for slots, s_pf, n_new in ((8, 128, 128), (16, 128, 128)):
        roof = decode_roofline_tokens_per_sec(
            cfg, slots, s_pf + n_new // 2, hbm)
        for depth in sweep:
            tps, disp, rep = run_engine(model, slots=slots, s_pf=s_pf,
                                        n_new=n_new, inflight=depth)
            show(f"slots={slots} ctx={s_pf}+{n_new}", tps, disp, roof,
                 rep)

    if os.environ.get("PD_SPEC", "0") == "1":
        roof = decode_roofline_tokens_per_sec(cfg, 8, 192, hbm)
        for depth in sweep:
            tps, disp, rep = run_engine(model, chunk=16, spec_k=4,
                                        inflight=depth)
            show("spec k=4 chunk=16", tps, disp, roof, rep)


if __name__ == "__main__":
    main()
