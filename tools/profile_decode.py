"""Decode-engine A/B harness for real hardware.

Times the full engine loop for the bench workload (1.3B, 8 slots, T=256,
chunk=64) with the flash-decode kernel enabled and disabled, against the
HBM roofline. PD_SIZE=350m for a smaller model.

Measurement notes learned the hard way (r5):
- On the tunneled PJRT backend ``jax.block_until_ready`` does NOT block;
  sync by fetching a scalar (the engine's own host loop does this
  naturally).
- Per-dispatch tunnel RTT is ~4 ms; only in-jit loops (the engine's
  ``steps_per_call`` chunking) measure device time. For sub-step
  breakdowns, time a lax.scan of K steps at two K values and use the
  slope.
- Run-to-run variance on the shared chip is +-1.5 ms/step; use min over
  several runs for A/B decisions.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from paddle_tpu import flags
from paddle_tpu.models import gpt
from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)


def run_engine(model, use_kernel: bool, chunk: int = 64, slots: int = 8,
               s_pf: int = 128, n_new: int = 128):
    flags.set_flags({"use_pallas_kernels": use_kernel})
    cfg = model.cfg
    eng = DecodeEngine(model, max_slots=slots, max_len=s_pf + n_new,
                       steps_per_call=chunk)
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, cfg.vocab_size, s_pf) for _ in range(slots)]
    for p in prompts:
        eng.submit(p, max_new_tokens=2)
    eng.run()  # warm compile
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.step()
    pre = sum(len(r.tokens) for r in reqs)
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in reqs) - pre
    eng.kc = eng.vc = eng._stacked = None
    del eng
    return toks / dt, dt, toks


def main():
    size = os.environ.get("PD_SIZE", "1p3b")
    cfg = (gpt.gpt3_1p3b(max_seq_len=2048) if size == "1p3b"
           else gpt.gpt3_350m(max_seq_len=1024))
    print("building model", size, flush=True)
    model = gpt.GPT(cfg, seed=0)
    dev = jax.devices()[0]
    print("device:", dev, flush=True)

    from paddle_tpu.cost_model import _peak
    hbm = _peak(dev)[1] / 1e9
    roof = decode_roofline_tokens_per_sec(cfg, 8, 192, hbm)
    print(f"roofline @ctx192 b8: {roof:.1f} tok/s (hbm {hbm:.0f} GB/s)",
          flush=True)

    for use_kernel in (False, True):
        tps, dt, toks = run_engine(model, use_kernel)
        print(f"kernel={use_kernel}: {tps:.1f} tok/s "
              f"({toks} toks in {dt:.2f}s) vs_roofline={tps / roof:.3f}",
          flush=True)


if __name__ == "__main__":
    main()
