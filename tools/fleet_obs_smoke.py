"""Fleet-observability smoke (tools/ci.sh fleetobs, ISSUE 13): one
prefill + one decode replica — REAL processes through the
distributed/launch.py CLI — behind the role-aware router, with the
whole telemetry plane switched on (~1 min):

- every request's spans carry ONE trace context across router,
  prefill, wire, and decode; the stitched timeline
  (observability/merge.stitch_trace_files) shows all four on-device
  segments (queue-wait, prefill, kv-transfer, decode) for at least one
  request, and their durations SUM to the client-observed latency
  (the serve/route span) within 10%;
- the fleet /statsz serves the MERGED registry: its serve/ttft_s p99
  equals the FleetStats-merged histogram's p99;
- one injected stall (SIGSTOP the decode replica mid-request) raises
  EXACTLY one fleet/alert_stalled_replica naming the replica;
- the JSONL telemetry file grew.

Exit 0 + "FLEETOBS SMOKE OK" on success; any divergence asserts.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PT_KV_WIRE"] = "fp32"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.observability import merge, trace  # noqa: E402
from paddle_tpu.serving import Router  # noqa: E402

WORKER = os.path.join(REPO, "tests", "_disagg_worker.py")


def _spawn(store_port, rid, role, launch_port, trace_file):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               FLEETOBS_TRACE_FILE=trace_file, PT_TRACE_FLUSH_S="0.5")
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         WORKER, str(store_port), rid, role],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def main():
    tdir = tempfile.mkdtemp(prefix="fleetobs_")
    trace.enable(os.path.join(tdir, "trace_router.json"))
    rs = np.random.RandomState(7)
    prompts = [[int(x) for x in rs.randint(0, 96, size=n)]
               for n in (40, 150, 90, 200, 60, 120)]
    budgets = [12, 16, 14, 12, 18, 14]

    router = Router(port=0, dead_after=20.0)
    procs = [_spawn(router.store.port, "pf0", "prefill", 8885,
                    os.path.join(tdir, "trace_pf0.json")),
             _spawn(router.store.port, "dc0", "decode", 8886,
                    os.path.join(tdir, "trace_dc0.json"))]
    try:
        router.wait_replicas(2, timeout=90)

        # -- phase A: the stitched-timeline workload --------------------
        t_client = {}
        ids = []
        for p, b in zip(prompts, budgets):
            q = router.submit(p, max_new_tokens=b)
            t_client[q] = time.perf_counter()
            ids.append(q)
        results = router.drain(timeout=180)
        for q in ids:
            t_client[q] = time.perf_counter() - t_client[q]
        assert all(results[q]["status"] == "done" for q in ids), results
        assert stats.get("serve/router_prefill_handoffs") > 0, \
            "no prefill->decode handoffs: the workload never crossed " \
            "the wire"
        print(f"  phase A: {len(ids)} requests served "
              f"prefill->wire->decode", flush=True)

        # -- fleet stats: merged /statsz + telemetry --------------------
        jsonl = os.path.join(tdir, "fleet.jsonl")
        fleet = router.enable_fleet_stats(
            refresh_s=0.25, stall_after_s=2.0, jsonl_path=jsonl)
        srv = fleet.serve_statsz(0, host="127.0.0.1")
        fleet.poll()
        merged = fleet.merged()
        hist = merged.histogram("serve/ttft_s")
        assert hist is not None and hist.count > 0, \
            "no decode-side TTFT samples reached the fleet merge"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/statsz", timeout=5) as r:
            served = json.load(r)
        shist = served["histograms"].get("serve/ttft_s")
        assert shist is not None and shist["count"] == hist.count, \
            "fleet /statsz did not serve the merged TTFT histogram"
        from paddle_tpu.stats import _Histogram
        p99_srv = _Histogram.from_dict(shist).percentile(99)
        assert abs(p99_srv - hist.percentile(99)) < 1e-12
        # role-tagging: the prefill replica's samples live in their own
        # histogram, never in the fleet TTFT
        assert merged.histogram("serve/prefill_s") is not None, \
            "prefill replica exported no serve/prefill_s"
        print(f"  fleet /statsz: merged p99 TTFT "
              f"{p99_srv * 1e3:.1f}ms over {hist.count} samples",
              flush=True)

        # -- injected stall: SIGSTOP the decode replica mid-request -----
        victim_pid = router.directory.members()["dc0"]["pid"]
        tok0 = (router.directory.load("dc0") or {}).get("tokens", 0)
        rq = router.submit(prompts[1], max_new_tokens=64)
        # wait until the decode replica is busy AND has made token
        # progress on THIS request (a zero-progress busy stretch from
        # some unrelated hiccup must not pre-consume the alert edge)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.poll()
            load = router.directory.load("dc0") or {}
            if (load.get("busy_slots", 0) > 0
                    and load.get("tokens", 0) > tok0):
                break
            time.sleep(0.05)
        load = router.directory.load("dc0") or {}
        assert load.get("busy_slots", 0) > 0, \
            "decode replica never went busy"
        os.kill(victim_pid, signal.SIGSTOP)
        try:
            fired = []
            deadline = time.monotonic() + 12
            while time.monotonic() < deadline and not fired:
                fired = [a for a in fleet.poll()
                         if a == "stalled_replica"]
                time.sleep(0.2)
        finally:
            os.kill(victim_pid, signal.SIGCONT)
        assert fired, "anomaly watch never flagged the SIGSTOP'd " \
            "replica within the window"
        n_alerts = int(stats.get("fleet/alert_stalled_replica"))
        assert n_alerts == 1, \
            f"expected exactly one stall alert, got {n_alerts}"
        named = [a["msg"] for a in fleet.alerts
                 if a["kind"] == "stalled_replica"]
        assert named and "dc0" in named[0], named
        print(f"  stall: one alert, names the replica ({named[0][:60]}"
              f"...)", flush=True)
        results = router.drain(timeout=180)
        assert results[rq]["status"] == "done", results[rq]
        assert os.path.exists(jsonl) and os.path.getsize(jsonl) > 0, \
            "fleet JSONL telemetry never appended"
    finally:
        router.shutdown()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        router.close()

    # -- stitch: one timeline, four segments, 10% latency sum ----------
    trace.export()
    trace.disable()
    paths = [os.path.join(tdir, f"trace_{n}.json")
             for n in ("router", "pf0", "dc0")]
    for p in paths:
        assert os.path.exists(p), f"missing trace file {p}"
    out, summary = merge.stitch_trace_files(
        paths, os.path.join(tdir, "trace_stitched.json"))
    need = ("queue-wait", "prefill", "kv-transfer", "decode")
    full = {rid: info for rid, info in summary.items()
            if all(s in info["segments"] for s in need)
            and info["client_us"]}
    assert full, f"no request stitched with all four segments: " \
        f"{ {r: sorted(i['segments']) for r, i in summary.items()} }"
    ok_sum = []
    for rid, info in full.items():
        seg_sum = sum(dur for name, (_, dur) in info["segments"].items()
                      if name in need)
        rel = abs(seg_sum - info["client_us"]) / info["client_us"]
        # the residual is the stream segment (decode end -> router
        # pickup) plus clock-rebase error
        if rel <= 0.10:
            ok_sum.append((rid, seg_sum, info["client_us"], rel))
    assert ok_sum, \
        "no stitched request's segment sum landed within 10% of its " \
        "client-observed latency: " + str(
            {r: (sum(d for n, (_, d) in i["segments"].items()
                     if n in need), i["client_us"])
             for r, i in full.items()})
    rid, seg_sum, client, rel = ok_sum[0]
    # cross-process: the stitched request's spans span >= 3 lanes
    assert len(full[rid]["pids"]) >= 3, full[rid]
    print(f"  stitch: {len(full)}/{len(summary)} requests carry all "
          f"four segments; {rid} sums {seg_sum / 1e3:.1f}ms vs client "
          f"{client / 1e3:.1f}ms ({100 * rel:.1f}% off) across "
          f"{len(full[rid]['pids'])} process lanes -> {out}",
          flush=True)
    print("FLEETOBS SMOKE OK", flush=True)


if __name__ == "__main__":
    main()
