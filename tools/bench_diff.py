#!/usr/bin/env python
"""bench_diff — machine-checked BENCH/MULTICHIP snapshot comparison
(ISSUE 15 regression sentinel).

    python tools/bench_diff.py BASELINE.json NEW.json [--rtol 0.10]
    python tools/bench_diff.py --selftest BENCH_r05.json

Until now every recapture verdict ("within ~1.5x of contiguous?", "did
the fused kernel help?") was an eyeball diff of two JSON blobs; r05's
RESOURCE_EXHAUSTED silently dropped the bert/resnet/ppyoloe rows and
nothing flagged it. This tool compares two snapshots row by row:

- **direction-aware**: tok/s-like rows regress DOWN, ms/latency-like
  rows regress UP; config echoes (batch, seq, dispatch counts, ...)
  are informational and never fail the diff.
- **noise-aware**: per-row relative tolerance — a global ``--rtol``
  floor (default 10%) widened per row family by the built-in noise
  table (serving p99 tails swing harder than steady-state tok/s).
- **missing rows fail**: a numeric baseline row that vanished (or came
  back as ``<row>_error``) is a regression — exactly the r05 failure
  mode. New rows are reported, never failed.
- **schema-checked**: mismatched headline metrics or provenance schema
  versions exit 2 (the diff would be meaningless), not 1.
- prints the **paged-vs-contiguous ratio** against the ROADMAP item 1
  flip criterion (paged within 1.5x of contiguous) whenever both rows
  are present in the NEW snapshot.

Exit status: 0 clean (improvements/new rows included), 1 regression(s)
— each named —, 2 schema mismatch or unreadable input. ``--selftest``
proves the sentinel alive: self-diff must be clean AND a synthetic 20%
tok/s regression must be caught by name (wired as ``tools/ci.sh
benchdiff`` in the default gate).

Accepts both snapshot shapes: the driver wrapper ``{"parsed": {...}}``
(BENCH_rNN.json) and bench.py's raw result line ``{"metric": ...,
"extra": {...}}``.
"""

import argparse
import copy
import json
import sys

# (substring, rtol) — first match wins; rows matching no entry use the
# --rtol floor. Tails and churn measurements are intrinsically noisier
# than steady-state throughput (PR 9/14 smoke de-flaking history).
NOISE_TABLE = (
    ("p99", 0.25),
    ("p50", 0.20),
    ("churn", 0.25),
    ("goodput", 0.20),
    ("loss_delta", None),   # parity deltas compare vs thresholds, not
    ("_frac", 0.25),        # each other; fractions swing with load
)

# direction classification: +1 = higher is better, -1 = lower is
# better, 0 = informational (config echo / identity — never a failure).
# _INFO wins first: it exists only for rows a generic fragment below
# would otherwise misclassify (autotune sweep timings carry _ms).
_INFO = ("schema", "vs_baseline", "provenance", "skipped",
         "loss_delta", "autotune", "cache_hit",
         "scan_layers", "captured_unix", "republished")
_HIGHER = ("tokens_per_sec", "tok_s", "goodput", "mfu", "hw_util",
           "tokens_per_step", "agreement", "cosine", "hit_rate",
           "hit_tokens", "roofline_frac", "vs_roofline",
           "overlap_frac", "compression_ratio", "wire_ratio",
           "completed", "ips")
_LOWER = ("_ms", "ttft", "tpot", "latency", "_tax_frac", "exposed_s",
          "peak_mb", "rejects", "evictions", "spawn_timeouts",
          "host_gap", "recovery_s", "overhead_frac")
# checked BEFORE _HIGHER: rows whose name embeds a higher-is-better
# fragment but measure a cost (the drain bench's goodput_dip_frac
# contains "goodput" yet a bigger dip is a worse drain; the kernel
# launch accounting — launches_per_token / launches_per_step, the
# single-dispatch megakernel guard — regresses UP, ISSUE 19)
_LOWER_FIRST = ("goodput_dip", "fallbacks", "migrate_failed",
                "launches_per_")


def direction(row: str) -> int:
    low = row.lower()
    for frag in _INFO:
        if frag in low:
            return 0
    for frag in _LOWER_FIRST:
        if frag in low:
            return -1
    for frag in _HIGHER:
        if frag in low:
            return 1
    for frag in _LOWER:
        if frag in low:
            return -1
    return 0   # unclassified: report drift, never fail on it


def row_rtol(row: str, floor: float) -> float:
    low = row.lower()
    for frag, tol in NOISE_TABLE:
        if frag in low:
            return floor if tol is None else max(floor, tol)
    return floor


def load_bench(path: str) -> dict:
    """The bench result dict from either snapshot shape. Raises
    ValueError on files that hold neither."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError(f"{path}: neither a driver snapshot "
                         f"({{'parsed': ...}}) nor a bench result line "
                         f"({{'metric': ...}})")
    return doc


def flatten_rows(result: dict) -> dict:
    """``{row_name: value}`` over the headline metric + extra, nested
    dicts dotted (``flash_autotune.blocks.0``). Numeric leaves become
    rows; string leaves keep only the ``*_error`` / ``*_skipped``
    markers (they testify a row DIED — the r05 signature)."""
    rows = {}
    if isinstance(result.get("value"), (int, float)):
        rows[str(result.get("metric", "metric"))] = float(result["value"])

    def walk(prefix, v):
        if isinstance(v, bool):
            rows[prefix] = float(v)
        elif isinstance(v, (int, float)):
            rows[prefix] = float(v)
        elif isinstance(v, dict):
            for k, sub in v.items():
                walk(f"{prefix}.{k}" if prefix else str(k), sub)
        elif isinstance(v, (list, tuple)):
            for i, sub in enumerate(v):
                walk(f"{prefix}.{i}", sub)
        elif isinstance(v, str) and (prefix.endswith("_error")
                                     or prefix.endswith("_skipped")):
            rows[prefix] = v

    walk("", {k: v for k, v in result.get("extra", {}).items()
              if k != "provenance"})
    return rows


def schema_check(base: dict, new: dict):
    """None when comparable, else the human reason they are not."""
    if base.get("metric") != new.get("metric"):
        return (f"headline metric mismatch: {base.get('metric')!r} vs "
                f"{new.get('metric')!r}")
    bs = (base.get("provenance") or base.get("extra", {})
          .get("provenance") or {}).get("schema_version")
    ns = (new.get("provenance") or new.get("extra", {})
          .get("provenance") or {}).get("schema_version")
    if bs is not None and ns is not None and bs != ns:
        return f"provenance schema_version mismatch: {bs} vs {ns}"
    if base.get("unit") and new.get("unit") \
            and base["unit"] != new["unit"]:
        return (f"headline unit mismatch: {base['unit']!r} vs "
                f"{new['unit']!r}")
    return None


def _death_marker(row: str, nrows: dict):
    """The ``<section>_error`` / ``<section>_skipped`` string covering a
    vanished ``row``, if any: bench.py marks a dead SECTION (e.g.
    ``decode_engine_error``) while the rows it killed carry longer
    names (``decode_engine_tokens_per_sec``) — so match markers whose
    stem prefixes the row, not the reverse."""
    for r, v in nrows.items():
        if not isinstance(v, str):
            continue
        stem = r.rsplit("_", 1)[0]   # strip _error / _skipped
        if row.startswith(stem):
            return v
    return None


def compare(base: dict, new: dict, rtol: float = 0.10,
            atol: float = 1e-6) -> dict:
    """Row-by-row verdicts: ``regressions`` / ``improvements`` /
    ``within_noise`` / ``missing`` / ``added`` / ``info_drift``, each a
    list of (row, detail) tuples. ``atol`` floors the comparison for
    (near-)zero baselines: an exactly-0.0 row (overlap's pinned
    exposed_s) drifting by micro-units must not read as an infinite
    relative regression."""
    brows, nrows = flatten_rows(base), flatten_rows(new)
    out = {k: [] for k in ("regressions", "improvements",
                           "within_noise", "missing", "added",
                           "info_drift")}
    for row in sorted(brows):
        bv = brows[row]
        if isinstance(bv, str):   # baseline row was already dead
            continue
        d = direction(row)
        if row not in nrows:
            err = _death_marker(row, nrows)
            if d == 0:
                out["missing"].append((row, "informational row gone"))
            else:
                detail = f"row vanished (baseline {bv:g})"
                if isinstance(err, str):
                    detail = f"row died: {err[:80]}"
                out["regressions"].append((row, detail))
            continue
        nv = nrows[row]
        if isinstance(nv, str):
            out["regressions"].append((row, f"row died: {nv[:80]}"))
            continue
        if abs(nv - bv) <= atol:
            rel = 0.0   # absolute floor: 0.0 -> 1e-7 is not a signal
        elif bv == 0:
            rel = (1.0 if nv > 0 else -1.0) * float("inf")
        else:
            rel = (nv - bv) / abs(bv)
        tol = row_rtol(row, rtol)
        detail = f"{bv:g} -> {nv:g} ({rel:+.1%}, tol {tol:.0%})"
        if d == 0:
            if rel:
                out["info_drift"].append((row, detail))
            continue
        worse = -rel * d
        if worse > tol:
            out["regressions"].append((row, detail))
        elif -worse > tol:
            out["improvements"].append((row, detail))
        else:
            out["within_noise"].append((row, detail))
    for row in sorted(set(nrows) - set(brows)):
        if isinstance(nrows[row], str):
            continue
        out["added"].append((row, f"{nrows[row]:g}"))
    return out


def paged_flip_report(new: dict, criterion: float = 1.5):
    """ROADMAP item 1: contiguous/paged tok/s ratio vs the flip
    criterion. Returns the printed lines (empty when rows absent)."""
    rows = flatten_rows(new)
    contig = rows.get("decode_engine_tokens_per_sec")
    paged = rows.get("decode_engine_paged_tokens_per_sec")
    if not isinstance(contig, float) or not isinstance(paged, float) \
            or paged <= 0:
        return []
    ratio = contig / paged
    verdict = ("PASS — flip paged to the default serving path"
               if ratio <= criterion else
               f"not yet — paged must close {ratio / criterion:.2f}x")
    return [f"paged flip criterion: contiguous {contig:g} tok/s / "
            f"paged {paged:g} tok/s = {ratio:.2f}x "
            f"(criterion <= {criterion}x): {verdict}"]


def _print_report(verdicts, show_all=False):
    order = ("regressions", "missing", "improvements", "added",
             "info_drift", "within_noise")
    for kind in order:
        items = verdicts[kind]
        if not items or (not show_all and kind == "within_noise"):
            if kind == "within_noise" and items:
                print(f"within noise: {len(items)} row(s)")
            continue
        print(f"{kind.replace('_', ' ')} ({len(items)}):")
        for row, detail in items:
            print(f"  {row}: {detail}")


def selftest(path: str, rtol: float) -> int:
    """The sentinel's own aliveness check: (a) self-diff is clean, (b)
    a synthetic 20% regression on every tok/s row is caught by name."""
    base = load_bench(path)
    clean = compare(base, base, rtol)
    if clean["regressions"] or clean["missing"]:
        print("selftest FAIL: self-diff not clean", file=sys.stderr)
        _print_report(clean)
        return 1
    wounded = copy.deepcopy(base)
    hit = []

    def maim(d, prefix=""):
        for k, v in list(d.items()):
            name = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                maim(v, name)
            elif isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and direction(k) == 1 and "tokens_per_sec" in k:
                d[k] = v * 0.8
                hit.append(name)

    maim(wounded.get("extra", {}))
    if isinstance(wounded.get("value"), (int, float)) \
            and "tokens_per_sec" in str(wounded.get("metric", "")):
        wounded["value"] = wounded["value"] * 0.8
        hit.append(str(wounded["metric"]))
    if not hit:
        print(f"selftest SKIP: {path} carries no tok/s rows to maim "
              f"(headline-only snapshot) — self-diff was clean")
        return 0
    v = compare(base, wounded, rtol)
    caught = {row for row, _ in v["regressions"]}
    missed = [h for h in hit if not any(h in c or c in h
                                        for c in caught)]
    if missed:
        print(f"selftest FAIL: 20% regression in {missed} not caught",
              file=sys.stderr)
        return 1
    print(f"selftest OK: self-diff clean; synthetic 20% tok/s "
          f"regression caught on {len(caught)} row(s) "
          f"(e.g. {sorted(caught)[0]})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff",
        description="machine-checked BENCH snapshot comparison")
    ap.add_argument("baseline", help="baseline snapshot JSON")
    ap.add_argument("new", nargs="?", default=None,
                    help="new snapshot JSON (omit with --selftest)")
    ap.add_argument("--rtol", type=float, default=0.10,
                    help="relative-tolerance floor per row "
                         "(default 0.10; noise table may widen)")
    ap.add_argument("--atol", type=float, default=1e-6,
                    help="absolute-drift floor: |new-base| at or below "
                         "this is within noise regardless of ratio "
                         "(protects exactly-zero baselines)")
    ap.add_argument("--flip-criterion", type=float, default=1.5,
                    help="paged-vs-contiguous flip threshold")
    ap.add_argument("--all", action="store_true",
                    help="print within-noise rows too")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdicts on stdout")
    ap.add_argument("--selftest", action="store_true",
                    help="self-diff + synthetic-regression aliveness "
                         "check on BASELINE")
    args = ap.parse_args(argv)

    try:
        base = load_bench(args.baseline)
        if args.selftest:
            return selftest(args.baseline, args.rtol)
        if args.new is None:
            ap.error("NEW snapshot required (or --selftest)")
        new = load_bench(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    reason = schema_check(base, new)
    if reason:
        print(f"bench_diff: snapshots not comparable: {reason}",
              file=sys.stderr)
        return 2

    verdicts = compare(base, new, args.rtol, args.atol)
    if args.json:
        print(json.dumps({k: [list(t) for t in v]
                          for k, v in verdicts.items()}, indent=1))
    else:
        _print_report(verdicts, show_all=args.all)
        for line in paged_flip_report(new, args.flip_criterion):
            print(line)
    n_reg = len(verdicts["regressions"])
    if n_reg:
        print(f"bench_diff: {n_reg} regression(s)", file=sys.stderr)
        return 1
    print(f"bench_diff: clean ({len(verdicts['within_noise'])} within "
          f"noise, {len(verdicts['improvements'])} improved, "
          f"{len(verdicts['added'])} new)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
