#!/usr/bin/env python
"""ptgeom CLI — static TPU kernel-geometry verification (ISSUE 20).

    python tools/ptgeom.py                       # sweep + table + gate
    python tools/ptgeom.py --geoms r06           # one ladder rung
    python tools/ptgeom.py --kernels mega_decode_layers,mega_logits_sample
    python tools/ptgeom.py --extra my_kernels.py # off-tree registry
    python tools/ptgeom.py --write-baseline

Drives every registered Pallas kernel wrapper (``ptgeom_cases()`` hooks
in ``paddle_tpu/ops/pallas/``) under ``jax.eval_shape`` at the bench
model ladder x the autotune key space, harvests one
:class:`~paddle_tpu.analysis.kernelmodel.KernelSpec` per launch, and
runs the PT006–PT009 geometry rules over them through the ptlint
engine — same suppressions, same baseline machinery, different facts.

Unlike ptlint this needs jax importable (tracing, never executing:
CPU-only CI shards run it fine). Exit status: 0 clean, 1 on
non-baselined findings, 2 on usage errors or cases that failed to
harvest (a kernel whose trace crashes was NOT verified — that must not
read as green).

Env: ``PTGEOM_GEOMS`` presets ``--geoms``; ``PT_VMEM_BUDGET_MB`` sets
the PT006 budget (see docs/static-analysis.md).
"""

import argparse
import importlib.util
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "ptgeom_baseline.json")


def _load_extra(path: str):
    """Import an off-tree registry module (must define
    ``ptgeom_cases()``); its launch sites join the project like any
    on-tree file."""
    name = "_ptgeom_extra_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _table(specs, km):
    budget = km.vmem_budget_bytes()
    worst = {}
    for s in specs:
        est = km.vmem_estimate(s)
        key = (s.kernel, f"{s.path}:{s.line}")
        if key not in worst or est > worst[key][0]:
            worst[key] = (est, s.geometry, s.config, s.grid,
                          len(s.aliases))
    rows = [("kernel", "site", "worst vmem", "of budget", "geometry",
             "config", "grid", "aliases")]
    for (kern, site), (est, g, c, grid, na) in sorted(worst.items()):
        rows.append((kern, site, f"{est / 2**20:.2f} MiB",
                     f"{est / budget * 100:5.1f}%", g, c,
                     "x".join(map(str, grid)), str(na)))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for i, r in enumerate(rows):
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            print("  ".join("-" * w for w in widths))
    print(f"budget: {budget / 2**20:.2f} MiB usable "
          f"(PT_VMEM_BUDGET_MB={os.environ.get('PT_VMEM_BUDGET_MB', '16')}"
          f" minus reserve), double-buffer factor {km.DOUBLE_BUFFER}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptgeom",
        description="static VMEM/tiling/aliasing verification of every "
                    "registered Pallas launch")
    ap.add_argument("--geoms", default=os.environ.get("PTGEOM_GEOMS"),
                    help="comma-set of ladder geometries "
                         "(tiny,350m,r06); default: all")
    ap.add_argument("--kernels", default=None,
                    help="comma-set of kernel names to sweep "
                         "(default: every registered kernel)")
    ap.add_argument("--extra", action="append", default=[],
                    help="extra registry module (a .py file defining "
                         "ptgeom_cases()); repeatable")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default tools/"
                         "ptgeom_baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the current findings as the baseline")
    ap.add_argument("--error-on-new", action="store_true",
                    help="exit 1 on non-baselined findings (default)")
    ap.add_argument("--no-error", action="store_true",
                    help="report only; always exit 0")
    ap.add_argument("--stats", action="store_true",
                    help="print findings-per-rule totals")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (e.g. PT006,PT009)")
    ap.add_argument("--no-table", action="store_true",
                    help="skip the per-kernel VMEM/tiling table")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import (baseline, engine, kernelmodel,
                                     rules_tpu)

    geoms = None
    if args.geoms:
        geoms = tuple(g.strip() for g in args.geoms.split(",")
                      if g.strip())
        unknown = set(geoms) - set(kernelmodel.LADDER)
        if unknown:
            print(f"ptgeom: unknown geometries {sorted(unknown)} "
                  f"(have {sorted(kernelmodel.LADDER)})",
                  file=sys.stderr)
            return 2
    kernels = None
    if args.kernels:
        kernels = {k.strip() for k in args.kernels.split(",")
                   if k.strip()}
    extra_modules = [_load_extra(p) for p in args.extra]

    cases = kernelmodel.iter_cases(kernels, geoms, extra_modules)
    if not cases:
        print("ptgeom: no cases matched the filters", file=sys.stderr)
        return 2
    specs, errors = kernelmodel.sweep(cases, root=ROOT)
    for case, err in errors:
        print(f"ptgeom: harvest failed for {case.kernel} "
              f"[{case.geometry}/{case.config}]: {err}",
              file=sys.stderr)

    project = engine.load_project(
        sorted({s.abspath for s in specs}), root=ROOT)
    project.geom_specs = specs
    rules = rules_tpu.geom_rules()
    if args.rules:
        keep = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in keep]
        if not rules:
            print(f"ptgeom: no such rules {sorted(keep)}",
                  file=sys.stderr)
            return 2
    findings = engine.run(project, rules)

    if args.write_baseline:
        if errors:
            print("ptgeom: refusing to write a baseline from a sweep "
                  "with harvest errors", file=sys.stderr)
            return 2
        baseline.write(args.baseline, findings)
        print(f"ptgeom: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(args.baseline, ROOT)}")
        return 0

    known_map = baseline.load(args.baseline)
    new, known = baseline.partition(findings, known_map)

    if args.format == "json":
        print(json.dumps(
            {"new": [vars(f) for f in new],
             "baselined": [vars(f) for f in known],
             "specs": [
                 {"name": s.name(), "site": f"{s.path}:{s.line}",
                  "vmem_bytes": kernelmodel.vmem_estimate(s)}
                 for s in specs]}, indent=2))
    else:
        if not args.no_table:
            _table(specs, kernelmodel)
        for f in new:
            print(f.format())
        if known:
            print(f"ptgeom: {len(known)} baselined finding(s) "
                  f"suppressed (see "
                  f"{os.path.relpath(args.baseline, ROOT)})")

    if args.stats:
        per_rule = {}
        for f in findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        print("ptgeom stats (baselined included):")
        for rule in sorted(set(list(per_rule) +
                               [r.id for r in rules])):
            print(f"  {rule}: {per_rule.get(rule, 0)}")
        print(f"  specs: {len(specs)}  total: {len(findings)}  "
              f"new: {len(new)}  baselined: {len(known)}")

    if new:
        print(f"ptgeom: {len(new)} new finding(s)", file=sys.stderr)
        return 0 if args.no_error else 1
    if errors and not args.no_error:
        # an unharvestable case means that geometry was NOT verified —
        # a green exit would pass CI on exactly the kernels whose
        # tracing is broken
        print(f"ptgeom: {len(errors)} case(s) could not be harvested",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
