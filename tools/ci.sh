#!/usr/bin/env bash
# CI driver (≙ reference paddle/scripts/paddle_build.sh test shards): run the
# full suite — including the bench smoke tests that execute every bench_*
# code path on tiny shapes — and fail on any red. Run this before every
# snapshot/commit ritual.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

python -m pytest tests/ -q --durations=15 "$@"
