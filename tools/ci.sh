#!/usr/bin/env bash
# CI driver (≙ reference paddle/scripts/paddle_build.sh test shards): run the
# full suite — including the bench smoke tests that execute every bench_*
# code path on tiny shapes — and fail on any red. Run this before every
# snapshot/commit ritual.
#
#   tools/ci.sh            ptlint gate, then the full suite
#   tools/ci.sh lint       static analysis only: tools/ptlint.py over the
#                          package, failing on any non-baselined finding
#                          (add --stats to print findings-per-rule for
#                          BENCH tracking)
#   tools/ci.sh faults     fast fault-injection smoke: only the resilience /
#                          fault-injection tests (pytest -m faults), tier-1
#                          compatible (CPU, 'not slow') — proves every
#                          recovery path still recovers in a couple minutes
#   tools/ci.sh obs        observability smoke: runs a traced mini
#                          train+decode+checkpoint step and asserts a
#                          non-empty schema-valid trace file, serving
#                          percentiles, and a live statsz endpoint
#   tools/ci.sh serve      pipelined-serving smoke: decode under fault
#                          injection at in-flight depth 1 vs 3 must
#                          produce byte-identical survivor streams on
#                          every path (plain/chunked/spec/paged)
#   tools/ci.sh front      serving front-end smoke: fixed-seed load
#                          generator through the scheduler on a tiny
#                          model — stream bit-identity vs direct
#                          submission, nonzero backfill events, the
#                          fed-occupancy floor, and the queue-deadline
#                          reject path (~2 min)
#   tools/ci.sh paged      paged-serving smoke: tiny-model fused
#                          append+attend decode end to end on CPU plus
#                          the PD_PREFIX repeated-system-prompt sweep —
#                          fails if a warm shared-prefix submit() stops
#                          hitting the radix cache
#   tools/ci.sh comm       quantized-collective smoke: tiny host-platform
#                          mesh runs the int8/fp8 wire — convergence
#                          parity vs fp32, ≥3.5x bytes_wire cut, stage-3
#                          gather tolerance, the bitflipped-scale
#                          fail-loud guard, plus the overlap sweep below
#   tools/ci.sh overlap    overlap-scheduler smoke: 4-device CPU sweep of
#                          the bucketed train step — overlap on/off must
#                          leave params BIT-identical after 3 steps, the
#                          prefetch toggle inside a float-ulp envelope,
#                          and the overlap-on lowering must carry >1
#                          reduce-scatter (one per bucket, interleaved
#                          into backward) instead of one fused tail
#                          collective
#   tools/ci.sh fleetobs   fleet-observability smoke: one prefill + one
#                          decode replica (real processes) under load —
#                          the stitched per-request timeline carries all
#                          four segments summing to the client latency
#                          within 10%, the fleet /statsz serves the
#                          merged p99, and one injected SIGSTOP stall
#                          raises exactly one alert (~1 min)
#   tools/ci.sh disagg     disaggregated-serving smoke: one prefill + one
#                          decode replica (real processes via
#                          distributed/launch.py) behind the role-aware
#                          router — fixed-seed streams bit-identical to
#                          single-replica serving on the fp32 KV wire,
#                          fleet prefix-hit counter nonzero on a
#                          repeated-system-prompt workload (~1 min)
#   tools/ci.sh ha         control-plane HA smoke (~1 min): SIGKILL
#                          the router mid-traffic — the successor
#                          generation replays the request journal, the
#                          replicas reconnect via the endpoint file,
#                          and the client sees every request id with
#                          streams byte-identical to an undisturbed
#                          control fleet
#   tools/ci.sh elastic    elastic-fleet smoke (~90s): the controller
#                          spawns a 2-replica fleet under Poisson load,
#                          a SIGKILLed replica is healed with zero
#                          request-id loss and an idle drain retires the
#                          surplus gracefully; then a 4->2 worker
#                          reshape (PT_ELASTIC_RESHAPE) resumes training
#                          from the newest VERIFIED epoch on the
#                          re-planned mesh
#   tools/ci.sh reshard    live-reshard + drain-migration smoke (~2
#                          min): an in-process 4->2 ElasticTrainer
#                          reshape must move live state in HBM with a
#                          loss trajectory identical to the
#                          checkpoint-path control, and a drained
#                          serving replica must MIGRATE its in-flight
#                          decode requests to the survivor with zero id
#                          loss and byte-identical streams
#   tools/ci.sh numerics   training-numerics smoke (~1 min): tiny CPU
#                          train run with a scripted mid-run grad
#                          poison (PT_FAULTS step= rule) — the
#                          provenance header must name the planted
#                          layer + leaf family, EXACTLY one
#                          num/alert_nonfinite fires, and the
#                          auto-dumped flight record holds the clean
#                          pre-spike snapshots
#   tools/ci.sh benchdiff  bench regression sentinel: the checked-in
#                          BENCH_r05.json snapshot must self-diff
#                          clean and bench_diff's synthetic 20% tok/s
#                          regression must be caught by row name
#                          (seconds; also part of the default gate)
#   tools/ci.sh geom       kernel-geometry gate (ISSUE 20): sweep every
#                          registered Pallas launch at the bench ladder
#                          under jax.eval_shape (CPU, no execution) and
#                          fail on any non-baselined PT006–PT009
#                          finding — a kernel whose worst autotune
#                          geometry stops fitting VMEM fails in seconds
#   tools/ci.sh mega       single-dispatch-decode smoke (~1 min):
#                          tiny-model CPU run of profile_decode's
#                          PD_SECTIONS=mega launches/step report — the
#                          paged megakernel (plain AND speculative)
#                          must step in <= 2 pallas launches while the
#                          per-layer reference pays one per layer,
#                          counted from the dispatch program's jaxpr
#                          plus the AOT HLO custom-call count and the
#                          serve/dispatch_launches window delta
#   tools/ci.sh prof       device-time-attribution smoke (~1 min):
#                          tiny-model CPU prompt-length sweep through
#                          tools/profile_decode.py PD_SECTIONS=prof —
#                          roofline capture must produce nonzero
#                          flops/bytes per dispatch, the launch-tax
#                          fraction must land in (0,1], and the
#                          benchdiff sentinel must round-trip clean
#   tools/ci.sh shard      sharded-stacked smoke: 4-device CPU mesh runs
#                          the pre-stacked scan-over-layers train step
#                          under fsdp×tp (loss parity vs per-layer,
#                          stacked leaves provably sharded) plus the
#                          stacked↔per-layer checkpoint-reshard round
#                          trips — tier-1 fast
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

if [[ "${1:-}" == "lint" ]]; then
    shift
    exec python tools/ptlint.py paddle_tpu tools --error-on-new "$@"
fi

if [[ "${1:-}" == "faults" ]]; then
    shift
    exec python -m pytest tests/ -q -m "faults and not slow" \
        --durations=10 -p no:cacheprovider "$@"
fi

if [[ "${1:-}" == "obs" ]]; then
    shift
    exec python tools/obs_smoke.py "$@"
fi

if [[ "${1:-}" == "serve" ]]; then
    shift
    exec python tools/serve_smoke.py "$@"
fi

if [[ "${1:-}" == "front" ]]; then
    shift
    exec python tools/front_smoke.py "$@"
fi

if [[ "${1:-}" == "paged" ]]; then
    shift
    PD_SIZE=tiny PD_SECTIONS=paged PD_PREFIX=1 \
        exec python tools/profile_decode.py "$@"
fi

if [[ "${1:-}" == "comm" ]]; then
    shift
    # comm_smoke forces its own 4-device host platform before importing jax
    exec python tools/comm_smoke.py "$@"
fi

if [[ "${1:-}" == "overlap" ]]; then
    shift
    # just the ISSUE-11 overlap sweep (bit-parity + interleaved lowering)
    exec python tools/comm_smoke.py --overlap "$@"
fi

if [[ "${1:-}" == "disagg" ]]; then
    shift
    exec python tools/disagg_smoke.py "$@"
fi

if [[ "${1:-}" == "fleetobs" ]]; then
    shift
    exec python tools/fleet_obs_smoke.py "$@"
fi

if [[ "${1:-}" == "ha" ]]; then
    shift
    exec python tools/ha_smoke.py "$@"
fi

if [[ "${1:-}" == "elastic" ]]; then
    shift
    exec python tools/elastic_smoke.py "$@"
fi

if [[ "${1:-}" == "reshard" ]]; then
    shift
    exec python tools/reshard_smoke.py "$@"
fi

if [[ "${1:-}" == "numerics" ]]; then
    shift
    exec python tools/numerics_smoke.py "$@"
fi

if [[ "${1:-}" == "benchdiff" ]]; then
    shift
    python tools/bench_diff.py BENCH_r05.json BENCH_r05.json "$@"
    exec python tools/bench_diff.py --selftest BENCH_r05.json
fi

if [[ "${1:-}" == "geom" ]]; then
    shift
    exec python tools/ptgeom.py --error-on-new --stats "$@"
fi

if [[ "${1:-}" == "mega" ]]; then
    shift
    # the megakernel's VMEM geometry is statically gated before the
    # runtime smoke: an over-budget slab/tile fails here by name
    python tools/ptgeom.py --error-on-new \
        --kernels mega_decode_layers,mega_logits_sample
    PD_SIZE=tiny PD_SECTIONS=mega \
        exec python tools/profile_decode.py "$@"
fi

if [[ "${1:-}" == "prof" ]]; then
    shift
    PD_SIZE=tiny PD_SECTIONS=prof python tools/profile_decode.py "$@"
    python tools/bench_diff.py BENCH_r05.json BENCH_r05.json
    exec python tools/bench_diff.py --selftest BENCH_r05.json
fi

if [[ "${1:-}" == "shard" ]]; then
    shift
    # the acceptance topology: a 4-device host-platform mesh (the tests
    # carve their meshes from devices[:4], so the tier-1 8-device run
    # exercises the same paths)
    export XLA_FLAGS="--xla_force_host_platform_device_count=4"
    exec python -m pytest tests/test_sharded_stacked.py \
        tests/test_reshard.py -q -p no:cacheprovider "$@"
fi

# lint gate runs BEFORE the test shards: a host-sync or env-contract
# regression fails in seconds, not after a 30-minute suite
python tools/ptlint.py paddle_tpu tools --error-on-new
# bench regression sentinel (ISSUE 15): the checked-in baseline
# snapshot must self-diff clean and the synthetic-regression detector
# must fire — seconds, and it guards every future BENCH comparison
python tools/bench_diff.py BENCH_r05.json BENCH_r05.json
python tools/bench_diff.py --selftest BENCH_r05.json
python -m pytest tests/ -q --durations=15 "$@"
