"""Disaggregated-serving smoke (tools/ci.sh disagg, ISSUE 12): one
prefill + one decode replica — REAL processes through the
distributed/launch.py CLI — behind the role-aware router on CPU,
proving end to end (~1 min):

- a fixed-seed workload routed prefill→wire→decode returns streams
  BIT-IDENTICAL to single-replica serving (PT_KV_WIRE=fp32 for the
  identity phase; every decode phase ran on the decode replica and
  every handoff was counted);
- the KV wire actually moved bytes (replica-side counters ride the
  heartbeat load gauges, so the router process can assert them);
- a repeated-system-prompt workload hits the FLEET prefix directory:
  the decode replica's `serve/fleet_prefix_hit_tokens` goes nonzero
  (pages published by one admission served another replica's prefill)
  and the router skips the prefill tier once coverage is complete
  (serve/router_prefill_skipped).

Exit 0 + "DISAGG SMOKE OK" on success; any divergence asserts.
"""
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PT_KV_WIRE"] = "fp32"      # the bit-identity contract
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.inference.paged_engine import PagedDecodeEngine  # noqa: E402
from paddle_tpu.serving import FrontEnd, Router  # noqa: E402

WORKER = os.path.join(REPO, "tests", "_disagg_worker.py")


def _spawn(store_port, rid, role, launch_port):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         WORKER, str(store_port), rid, role],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def main():
    import _disagg_worker
    rs = np.random.RandomState(0)
    sysprompt = [int(x) for x in rs.randint(0, 96, size=260)]
    uniques = [[int(x) for x in rs.randint(0, 96, size=n)]
               for n in (9, 40, 140)]
    # repeated-system-prompt tail: same 2 warm pages + unique suffixes
    warm = [sysprompt + [int(x) for x in rs.randint(0, 96, size=6)]
            for _ in range(4)]
    prompts = uniques + [sysprompt] + warm
    budgets = [5, 6, 7, 4, 4, 4, 4, 4]
    n_cold = len(uniques) + 1

    # single-replica oracle (identical model builder as the workers)
    eng = PagedDecodeEngine(_disagg_worker.build_model(), n_pages=48,
                            max_slots=2, page_size=128)
    fe = FrontEnd(eng)
    oracle = [fe.submit(p, max_new_tokens=b)
              for p, b in zip(prompts, budgets)]
    fe.run()
    want = [r.tokens for r in oracle]
    print(f"  oracle: {len(want)} streams on one replica", flush=True)

    router = Router(port=0, dead_after=15.0)
    procs = [_spawn(router.store.port, "pf0", "prefill", 8865),
             _spawn(router.store.port, "dc0", "decode", 8866),
             _spawn(router.store.port, "dc1", "decode", 8867)]
    try:
        router.wait_replicas(3, timeout=90)
        # phase 1 (cold): every prompt goes prefill->wire->decode;
        # the sysprompt's pages get published to the fleet directory
        t0 = time.perf_counter()
        ids = [router.submit(p, max_new_tokens=b)
               for p, b in zip(prompts[:n_cold], budgets[:n_cold])]
        results = router.drain(timeout=180)
        # phase 2 (warm): the directory now covers the system prompt's
        # full pages — the router skips the prefill tier. A FRESH
        # decode replica joins first (most free pages → placement
        # prefers it): it has no local cache, so serving the warm
        # requests forces a fleet fetch — the cross-replica hit the
        # smoke exists to prove
        procs.append(_spawn(router.store.port, "dc2", "decode", 8868))
        router.wait_replicas(4, timeout=90)
        ids2 = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts[n_cold:], budgets[n_cold:])]
        results = router.drain(timeout=180)
        wall = time.perf_counter() - t0
        all_ids = ids + ids2
        assert sorted(results) == sorted(all_ids)
        got = [results[q]["tokens"] for q in all_ids]
        assert got == want, "disaggregated streams diverged from " \
            "single-replica serving on the fp32 wire"
        assert all(results[q]["status"] == "done" for q in all_ids)
        assert {results[q]["replica"] for q in all_ids} <= \
            {"dc0", "dc1", "dc2"}
        print(f"  bit-identity: {len(all_ids)} streams equal through "
              f"prefill->wire->decode ({wall:.1f}s)", flush=True)

        handoffs = stats.get("serve/router_prefill_handoffs")
        skipped = stats.get("serve/router_prefill_skipped")
        assert handoffs > 0, "no prefill->decode handoffs happened"
        assert skipped > 0, "fleet coverage never skipped the " \
            "prefill tier"
        # replica-side counters ride the heartbeat load gauges: the
        # prefill replica moved wire bytes, and SOME decode replica
        # fetched fleet pages
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            pf = router.directory.load("pf0") or {}
            hits = max((router.directory.load(r) or {}).get(
                "fleet_hit_tokens", 0)
                for r in ("dc0", "dc1", "dc2"))
            if hits and pf.get("kv_transfer_bytes_wire"):
                break
            time.sleep(0.2)
        assert pf.get("kv_transfer_bytes_wire", 0) > 0, pf
        assert hits > 0, \
            "repeated-system-prompt workload never hit the fleet " \
            "prefix directory"
        print(f"  fleet: hit_tokens={hits} on a decode replica, "
              f"router handoffs={int(handoffs)}, "
              f"prefill skipped={int(skipped)}", flush=True)
    finally:
        router.shutdown()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        router.close()
    print("DISAGG SMOKE OK", flush=True)


if __name__ == "__main__":
    main()
