"""Training-numerics smoke (tools/ci.sh numerics, ISSUE 18): a tiny
CPU train run with a SCRIPTED mid-run gradient poison, end to end
through the whole numerics plane (~1 min):

- the overlap/quantized train step builds with PT_NUMERICS_EVERY=1 and
  returns ONE packed stats vector per step (one host transfer each);
- PT_FAULTS="train.grad_poison:nan:layer=1,key=blocks.w2,step=6" arms
  the in-graph poison through the ENV path (one compilation — the
  step gate is traced, not re-armed per step);
- steps 0..5 harvest clean; step 6's provenance header names the
  planted layer AND leaf family; EXACTLY one num/alert_nonfinite
  fires (the step-7 NaN cascade must not re-fire the edge trigger);
- the auto-dumped flight record (PT_NUMERICS_DIR) holds the clean
  pre-spike snapshots;
- the quantization-error gauges are live (int8 wire: nonzero rel_err)
  and the num/ registry keys are set for /statsz export.

Exit 0 + "NUMERICS SMOKE OK" on success; any divergence asserts.
"""
import glob
import json
import math
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ["PT_NUMERICS_EVERY"] = "1"
DUMP_DIR = tempfile.mkdtemp(prefix="numerics_smoke_")
os.environ["PT_NUMERICS_DIR"] = DUMP_DIR
PLANT_STEP, PLANT_LAYER, PLANT_KEY = 6, 1, "blocks.w2"
os.environ["PT_FAULTS"] = (
    f"train.grad_poison:nan:layer={PLANT_LAYER},key={PLANT_KEY},"
    f"step={PLANT_STEP}")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu import optimizer as optim  # noqa: E402
from paddle_tpu import stats  # noqa: E402
from paddle_tpu.distributed import mesh as mesh_lib  # noqa: E402
from paddle_tpu.distributed import overlap as OV  # noqa: E402
from paddle_tpu.observability import numerics as nm  # noqa: E402
from paddle_tpu.testing import faults  # noqa: E402


def main():
    n_rules = faults.install_from_env()
    assert n_rules == 1, f"PT_FAULTS installed {n_rules} rules"

    topo = mesh_lib.init_mesh(fsdp=4, devices=jax.devices()[:4],
                              set_global=False)
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 16), jnp.float32)
    y = jnp.asarray(rs.randn(16, 8), jnp.float32)
    sp, st, step = OV.overlap_parallel(
        dict(params), emb, blk, lf, optim.SGD(learning_rate=0.05),
        topo.mesh, stacked, comm_quant="int8", bucket_mb=1e-4)
    mon = nm.Monitor.for_step(step)

    snaps = []
    for i in range(10):
        out = step(sp, st, x, y)
        (sp, st, loss), packed = nm.split_out(out)
        snaps.append(mon.ingest(packed, step=i))

    # -- pre-spike steps harvested clean, one transfer each -------------
    pre = snaps[:PLANT_STEP]
    assert all(s is not None for s in pre), "missed samples"
    assert all(s["nonfinite"] == 0 for s in pre), "early nonfinite"
    assert all(math.isfinite(s["loss"]) for s in pre)
    assert all(s["quant_rel_err_max"] > 0 for s in pre), \
        "int8 wire must show nonzero quantization error"

    # -- the plant localizes: layer AND leaf family ---------------------
    bad = snaps[PLANT_STEP]
    assert bad["nonfinite"] > 0, "plant did not fire"
    assert bad["first_bad_layer"] == PLANT_LAYER, bad["first_bad_layer"]
    assert bad["first_bad_family_name"] == f"grad/{PLANT_KEY}", \
        bad["first_bad_family_name"]
    assert bad["alerts"] == ["nonfinite"]

    # -- EXACTLY one alert: the NaN cascade must not re-fire ------------
    assert stats.get("num/alert_nonfinite") == 1, \
        stats.get("num/alert_nonfinite")
    assert all(s["alerts"] == [] for s in snaps[PLANT_STEP + 1:])

    # -- auto-dump holds the clean pre-spike history --------------------
    files = glob.glob(os.path.join(DUMP_DIR,
                                   f"numerics_{PLANT_STEP}.*.json"))
    assert len(files) == 1, files
    doc = json.loads(open(files[0]).read())
    assert doc["reason"] == "nonfinite"
    pre_dumped = [s for s in doc["snapshots"] if s["step"] < PLANT_STEP]
    assert len(pre_dumped) >= 3, len(pre_dumped)
    assert all(s["nonfinite"] == 0 for s in pre_dumped)

    # -- the registry carries the num/ plane for /statsz ----------------
    snap = stats.snapshot(prefix="num/")
    for key in ("num/loss", "num/grad_rms", "num/quant_rel_err",
                "num/first_bad_layer", "num/samples", "num/dumps"):
        assert key in snap, (key, sorted(snap))

    print(f"plant step={PLANT_STEP} -> layer={bad['first_bad_layer']} "
          f"family={bad['first_bad_family_name']}; "
          f"alerts={stats.get('num/alert_nonfinite')}; "
          f"dump={os.path.basename(files[0])} "
          f"({len(pre_dumped)} pre-spike snapshots)")
    print("NUMERICS SMOKE OK")


if __name__ == "__main__":
    main()
