"""Serving front-end smoke (tools/ci.sh front, ISSUE 10): the
deterministic load generator drives a tiny model through the
continuous-batching scheduler on CPU and proves, end to end:

- every request the fixed-seed Poisson load offers completes, and each
  greedy stream is BYTE-IDENTICAL to submitting the same prompt
  directly to a fresh engine (the scheduler reorders admissions, never
  per-slot math) — checked on the contiguous and the paged engine;
- retirements backfill (serve/queue_backfill > 0) and the pipeline
  stays fed under backlog (fed-occupancy above the trickling floor);
- a deadline that expires in the queue is rejected with the distinct
  queue-reject status/counter and never reaches a prefill.

Exit 0 + "FRONT SMOKE OK" on success; any divergence asserts. ~2 min.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.models import gpt  # noqa: E402
from paddle_tpu.inference import (  # noqa: E402
    DecodeEngine, default_engine_kind, make_engine)
from paddle_tpu.serving import FrontEnd, loadgen  # noqa: E402

SLOTS = 4


def _model():
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _engines(model):
    # the front-end ladder builds through the factory: paged is the
    # serving default (PT_SERVE_ENGINE), contiguous kept behind the flag
    assert default_engine_kind() == "paged", "serving default changed"
    return {
        "contiguous": lambda: make_engine(model, "contiguous",
                                          max_slots=SLOTS, max_len=96,
                                          steps_per_call=2),
        "paged": lambda: make_engine(model, n_pages=40,
                                     max_slots=SLOTS,
                                     steps_per_call=2),
    }


def _run_load(make_engine, trace):
    """Fixed-seed load through the scheduler; returns the requests."""
    stats.reset("serve/")
    fe = FrontEnd(make_engine())
    reqs = loadgen.replay(
        trace,
        submit=lambda a: fe.submit(a.prompt,
                                   max_new_tokens=a.max_new_tokens),
        pump=fe.step, speed=4.0)
    fe.run()
    return fe, reqs


def main():
    model = _model()
    seed = loadgen.default_seed()
    # 24 requests through 4 slots at a rate that builds a backlog
    trace = loadgen.poisson_trace(24, qps=150.0, seed=seed, vocab=96,
                                  prompt_len=(4, 24), new_tokens=(6, 14))
    for name, make_engine in _engines(model).items():
        # direct-submission reference: same prompts, fresh engine
        direct = make_engine()
        refs = [direct.submit(a.prompt,
                              max_new_tokens=a.max_new_tokens)
                for a in trace]
        direct.run()
        ref_tokens = [list(r.tokens) for r in refs]

        fe, reqs = _run_load(make_engine, trace)
        assert all(r.status == "done" for r in reqs), \
            [(r.status, r.error) for r in reqs if r.status != "done"]
        got = [list(r.tokens) for r in reqs]
        assert got == ref_tokens, \
            f"{name}: scheduler streams diverged from direct submission"

        backfills = int(stats.get("serve/queue_backfill"))
        assert backfills > 0, f"{name}: no backfill events"
        snap = stats.snapshot("serve/")
        fed_n = snap.get("serve/fed_occupancy.count", 0)
        assert fed_n > 0, f"{name}: backlog never sampled"
        fed = snap.get("serve/fed_occupancy.sum", 0) / fed_n
        assert fed >= 0.5, (
            f"{name}: fed occupancy {fed:.2f} — scheduler is "
            f"trickling singletons (floor 1/slots = {1 / SLOTS})")
        print(f"  {name}: 24/24 streams bit-identical, "
              f"{backfills} backfills, fed occupancy {fed:.2f}",
              flush=True)

    # queue-deadline reject path: expires while queued, never prefills
    stats.reset("serve/")
    fe = FrontEnd(DecodeEngine(model, max_slots=1, max_len=96),
                  admit_ahead=0)
    blocker = fe.submit(trace[0].prompt, max_new_tokens=10)
    doomed = fe.submit(trace[1].prompt, max_new_tokens=10,
                       deadline_s=1e-4)
    fe.run()
    assert blocker.status == "done"
    assert doomed.status == "rejected-deadline" and doomed.tokens == []
    assert stats.get("serve/queue_deadline_rejects") == 1
    assert stats.get("serve/deadline_evictions") == 0
    print("  deadline: queued expiry rejected pre-prefill "
          "(distinct counter)", flush=True)

    print(stats.table("serve/queue"))
    print("FRONT SMOKE OK", flush=True)


if __name__ == "__main__":
    main()
