"""Dev helper: report registry oracle coverage on the CPU platform."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import paddle_tpu  # noqa: E402,F401
from paddle_tpu.ops import oracles  # noqa: E402

oracles.attach_all()
from paddle_tpu.ops.registry import all_ops  # noqa: E402

ops = all_ops()
have = [o for o in ops if o.np_ref is not None and o.sample_args is not None]
aliases = [o for o in ops if o.alias_of is not None]
print("total", len(ops), "have", len(have), "aliases", len(aliases))
missing = [o.name for o in ops
           if (o.np_ref is None or o.sample_args is None)
           and o.alias_of is None]
print("missing (incl random):", len(missing))
print(missing)
