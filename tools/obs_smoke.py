#!/usr/bin/env python
"""Observability smoke (tools/ci.sh obs): run a traced mini train step +
decode request end to end, then assert the pipeline delivered —

- a non-empty, schema-valid Chrome-trace file (every X event carries
  name/ts/dur/pid/tid) including train, serve, and checkpoint spans;
- ``stats.table()`` percentiles for ``serve/ttft_s`` and
  ``train/step_s``;
- a statsz endpoint serving the live snapshot.

Exit 0 = the observability subsystem observes; anything else is red.
"""

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    tmp = tempfile.mkdtemp(prefix="pt_obs_smoke_")
    os.environ["PT_TRACE_DIR"] = tmp

    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as optim, stats
    from paddle_tpu.observability import trace, start_statsz, stop_statsz
    from paddle_tpu.inference.decode_engine import DecodeEngine
    from paddle_tpu.models import gpt
    from paddle_tpu.distributed import checkpoint as ckpt

    trace.enable(os.path.join(tmp, "trace_rank0.json"), capacity=8192)

    # -- traced mini train loop --------------------------------------------
    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc(x)

    m = pt.Model(Net())
    m.prepare(optim.SGD(learning_rate=0.1), nn.CrossEntropyLoss())
    x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (8, 1)).astype(np.int64)
    m.fit(list(zip(x.reshape(2, 4, 4), y.reshape(2, 4, 1))), epochs=1,
          verbose=0)

    # -- traced checkpoint save/verify -------------------------------------
    cdir = os.path.join(tmp, "ckpt")
    ckpt.save_state({"w": jnp.ones((4, 4))}, cdir)
    ok, reason = ckpt.verify_checkpoint(cdir)
    assert ok, reason

    # -- traced decode request ----------------------------------------------
    cfg = gpt.GPTConfig(vocab_size=64, max_seq_len=64, d_model=16,
                        n_layers=1, n_heads=2, dtype=jnp.float32)
    eng = DecodeEngine(gpt.GPT(cfg, seed=0), max_slots=2, max_len=64,
                       buckets=(16,))
    req = eng.submit([1, 2, 3], max_new_tokens=4)
    eng.run()
    assert req.done and not req.failed

    # -- assertions ----------------------------------------------------------
    snap = stats.snapshot()
    for key in ("serve/ttft_s.p50", "serve/ttft_s.p99",
                "train/step_s.p50", "ckpt/save_s.count"):
        assert key in snap, f"missing stat {key}"
    assert snap["serve/ttft_s.count"] >= 1
    table = stats.table("serve/")
    assert "serve/ttft_s.p99" in table

    srv = start_statsz(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/statsz", timeout=5) as r:
        live = json.load(r)
    assert "serve/ttft_s" in live["histograms"]
    stop_statsz()

    path = trace.export()
    with open(path) as f:
        doc = json.load(f)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evs, "trace file has no events"
    for e in evs:
        for k in ("name", "ts", "dur", "pid", "tid"):
            assert k in e, f"event missing {k}: {e}"
    names = {e["name"] for e in evs}
    for want in ("train/step", "serve/step", "serve/request",
                 "ckpt/save", "ckpt/verify"):
        assert want in names, f"missing span {want} (got {sorted(names)})"
    print(f"obs smoke OK: {len(evs)} spans in {path}, "
          f"ttft p50={snap['serve/ttft_s.p50'] * 1e3:.2f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
