#!/usr/bin/env python
"""Offline merge of per-rank trace files into one Perfetto timeline.

The launcher merges automatically on exit (PT_TRACE_DIR); this CLI is
for the multi-host case — scp every host's ``trace_rank*.json`` into
one directory, merge, and open the result at https://ui.perfetto.dev
(or chrome://tracing). Ranks appear as process lanes.

    python tools/trace_merge.py LOGDIR                 # -> LOGDIR/trace_merged.json
    python tools/trace_merge.py -o out.json a.json b.json
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="trace_merge",
        description="merge per-rank Chrome-trace JSON files into one "
                    "Perfetto timeline (rank -> process lane)")
    p.add_argument("inputs", nargs="+",
                   help="trace_rank*.json files, or ONE directory "
                        "containing them")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: trace_merged.json next "
                        "to the inputs)")
    args = p.parse_args(argv)

    from paddle_tpu.observability import merge

    if len(args.inputs) == 1 and os.path.isdir(args.inputs[0]):
        out = merge.merge_rank_traces(args.inputs[0], args.out)
        if out is None:
            print(f"no trace_rank*.json under {args.inputs[0]}",
                  file=sys.stderr)
            return 1
    else:
        out = merge.merge_trace_files(
            args.inputs,
            args.out or os.path.join(
                os.path.dirname(os.path.abspath(args.inputs[0])),
                merge.MERGED_NAME))
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
