#!/usr/bin/env python
"""Offline merge of per-rank trace files into one Perfetto timeline.

The launcher merges automatically on exit (PT_TRACE_DIR); this CLI is
for the multi-host case — scp every host's ``trace_rank*.json`` into
one directory, merge, and open the result at https://ui.perfetto.dev
(or chrome://tracing). Ranks appear as process lanes.

    python tools/trace_merge.py LOGDIR                 # -> LOGDIR/trace_merged.json
    python tools/trace_merge.py -o out.json a.json b.json

``--stitch`` joins per-REPLICA serving traces instead (ISSUE 13): one
lane per input file, plus a synthetic ``requests`` process whose
thread lanes show each request's cross-process phase segments
(queue-wait → prefill → kv-transfer → decode → stream), derived from
the ``args.rid`` trace context every serving span carries. Prints a
per-request segment summary next to the output path.

    python tools/trace_merge.py --stitch LOGDIR        # -> LOGDIR/trace_stitched.json
    python tools/trace_merge.py --stitch -o out.json router.json pf0.json dc0.json
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="trace_merge",
        description="merge per-rank Chrome-trace JSON files into one "
                    "Perfetto timeline (rank -> process lane)")
    p.add_argument("inputs", nargs="+",
                   help="trace_rank*.json files, or ONE directory "
                        "containing them")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: trace_merged.json next "
                        "to the inputs; trace_stitched.json with "
                        "--stitch)")
    p.add_argument("--stitch", action="store_true",
                   help="stitch per-replica serving traces into "
                        "per-request lanes (rid trace context) instead "
                        "of a plain per-rank merge")
    args = p.parse_args(argv)

    from paddle_tpu.observability import merge

    one_dir = len(args.inputs) == 1 and os.path.isdir(args.inputs[0])
    if args.stitch:
        inputs = (merge.discover_trace_files(args.inputs[0])
                  if one_dir else args.inputs)
        if not inputs:
            print(f"no trace_*.json under {args.inputs[0]}",
                  file=sys.stderr)
            return 1
        out, summary = merge.stitch_trace_files(
            inputs,
            args.out or os.path.join(
                os.path.dirname(os.path.abspath(inputs[0])),
                merge.STITCHED_NAME))
        if not summary:
            print("no rid-tagged spans to stitch (serving traces "
                  "carry args.rid)", file=sys.stderr)
            return 1
        for rid, info in summary.items():
            segs = " ".join(
                f"{name}={dur / 1e3:.1f}ms"
                for name, (_, dur) in info["segments"].items())
            print(f"# {rid}: {segs}", file=sys.stderr)
        print(out)
        return 0
    if one_dir:
        out = merge.merge_rank_traces(args.inputs[0], args.out)
        if out is None:
            print(f"no trace_rank*.json under {args.inputs[0]}",
                  file=sys.stderr)
            return 1
    else:
        out = merge.merge_trace_files(
            args.inputs,
            args.out or os.path.join(
                os.path.dirname(os.path.abspath(args.inputs[0])),
                merge.MERGED_NAME))
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
