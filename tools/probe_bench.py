"""Opportunistic TPU bench capture (VERDICT r4 item 1).

Rounds 3 and 4 produced zero hardware numbers because the axon tunnel was
down whenever the single end-of-round bench ran. This prober decouples
capture from the driver's schedule: it loops all round, probing the tunnel
with a short, hard-killed device check; the moment the tunnel answers it
runs the full ``bench.py`` and records the result, then keeps re-benching
periodically so later code improvements (decode engine, fused CE) are
reflected in the freshest capture.

Artifacts:
  - ``PROBE_LOG_r05.jsonl``  — one line per probe attempt (timestamped trail;
    proves the tunnel state over the whole round even if it never rises).
  - ``BENCH_r05_probe.json`` — the latest successful full-bench JSON line,
    wrapped with capture metadata.

Run detached:  ``python tools/probe_bench.py &``  (stdout/err to probe log).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIL = os.path.join(REPO, "PROBE_LOG_r05.jsonl")
RESULT = os.path.join(REPO, "BENCH_r05_probe.json")

PROBE_TIMEOUT_S = int(os.environ.get("PT_PROBE_TIMEOUT_S", 150))
DOWN_INTERVAL_S = int(os.environ.get("PT_PROBE_INTERVAL_S", 1200))
UP_REBENCH_S = int(os.environ.get("PT_REBENCH_INTERVAL_S", 4800))

_PROBE_CODE = (
    "import jax; d = jax.devices()[0]; "
    "print(d.platform, getattr(d, 'device_kind', ''))"
)


def _log(entry):
    entry["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(TRAIL, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(json.dumps(entry), flush=True)


def probe() -> str:
    """Return the device kind if a non-CPU device answers, else ''."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE], capture_output=True,
            text=True, timeout=PROBE_TIMEOUT_S, cwd=REPO)
    except subprocess.TimeoutExpired:
        _log({"event": "probe", "up": False, "reason": "timeout"})
        return ""
    line = (out.stdout.strip().splitlines() or [""])[-1]
    up = out.returncode == 0 and line and not line.startswith("cpu")
    _log({"event": "probe", "up": bool(up),
          "device": line if up else "",
          "reason": "" if up else (out.stderr.strip()[-200:] or "rc=%d"
                                   % out.returncode)})
    return line if up else ""


def _run_one(env, label, timeout):
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        _log({"event": "bench", "phase": label, "ok": False,
              "reason": f"{timeout}s timeout"})
        return None
    parsed = None
    for ln in reversed(out.stdout.strip().splitlines()):
        try:
            parsed = json.loads(ln)
            break
        except ValueError:
            continue
    ok = (out.returncode == 0 and parsed
          and parsed.get("metric") != "bench_failed")
    _log({"event": "bench", "phase": label, "ok": bool(ok),
          "rc": out.returncode, "secs": round(time.time() - t0, 1),
          "metric": (parsed or {}).get("metric"),
          "stderr_tail": out.stderr.strip()[-300:] if not ok else ""})
    return parsed if ok else None


def _existing_is_full():
    """True when BENCH_r05_probe.json already holds a flagship capture
    (metric is a real headline, not the cheap-phase partial_bench)."""
    try:
        with open(RESULT) as f:
            return json.load(f)["result"]["metric"] != "partial_bench"
    except Exception:
        return False


def _write_result(device, parsed, note):
    with open(RESULT, "w") as f:
        json.dump({"captured_at":
                   time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                   "device": device, "rc": 0, "result": parsed,
                   "note": note}, f, indent=1)


def run_bench(device: str):
    """Two-phase capture, NEW information first: the flagship + decode
    + longctx phase (the round's changed code paths) runs the moment
    the tunnel answers and lands on disk immediately; the cheap
    BASELINE rows (already banked at round start in
    BENCH_r05_roundstart.json) refresh second and merge in. A
    partial result never overwrites an earlier FULL capture."""
    env = dict(os.environ)
    # The tunnel just answered, so a wedged acquisition now means it died
    # mid-bench — fail fast enough to resume probing.
    env.setdefault("PT_DEVICE_TIMEOUT_S", "300")

    # phase budget strictly below the subprocess kill timeout, so
    # bench.py's graceful budget truncation (partial rows + JSON line)
    # engages before the hard kill would discard everything — clamped
    # even when the operator exports a larger PT_BENCH_BUDGET_S
    def _budget(cap):
        try:
            return str(min(int(float(env.get("PT_BENCH_BUDGET_S", cap))),
                           cap))
        except ValueError:
            return str(cap)

    env_a = dict(env, PT_BENCH_ONLY="gpt,decode,longctx",
                 PT_BENCH_BUDGET_S=_budget(4500))
    flag = _run_one(env_a, "flagship", 5400)
    if flag is not None:
        _write_result(device, flag, "flagship + decode + longctx; cheap "
                      "rows phase pending")

    env_b = dict(env, PT_BENCH_ONLY="bert,resnet50,ppyoloe,pp",
                 PT_BENCH_BUDGET_S=_budget(1500))
    cheap = _run_one(env_b, "cheap-rows", 1800)
    if cheap is not None:
        if flag is not None:
            merged_extra = dict(flag.get("extra", {}))
            merged_extra.update(cheap.get("extra", {}))
            _write_result(device, dict(flag, extra=merged_extra),
                          "flagship + decode + longctx merged with "
                          "same-session cheap rows")
        elif not _existing_is_full():
            _write_result(device, cheap, "cheap BASELINE rows only "
                          "(flagship phase failed this cycle)")
    # flagship missing => retry on the short DOWN interval
    return flag is not None


def main():
    _log({"event": "start", "pid": os.getpid(),
          "probe_timeout_s": PROBE_TIMEOUT_S,
          "down_interval_s": DOWN_INTERVAL_S})
    while True:
        device = probe()
        if device:
            ok = run_bench(device)
            time.sleep(UP_REBENCH_S if ok else DOWN_INTERVAL_S)
        else:
            time.sleep(DOWN_INTERVAL_S)


if __name__ == "__main__":
    main()
