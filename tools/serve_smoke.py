"""Pipelined-serving smoke (tools/ci.sh serve, ISSUE 4): run a
pipelined decode UNDER FAULT INJECTION on CPU and prove, end to end,

- byte-identical survivor streams at in-flight depth 1 vs 3 on the
  plain, chunked and speculative paths (contiguous engine) and the
  paged engine, plain and speculative (single-dispatch megakernel);
- a nan-poisoned request is evicted alone, at harvest, on every path;
- a queued deadline_s=0 request is evicted without touching peers;
- the pipeline actually pipelines (serve/host_gap_s samples recorded,
  serve/inflight returns to 0) and the serve/ stats surface is live.

Exit code 0 + "SERVE SMOKE OK" on success; any divergence asserts.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.models import gpt  # noqa: E402
from paddle_tpu.inference import make_engine  # noqa: E402
from paddle_tpu.inference.decode_engine import DecodeEngine  # noqa: E402
from paddle_tpu.testing import faults  # noqa: E402


def _model():
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=256, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _serve(make_engine, depth):
    """One faulted serving episode; returns the survivors' streams."""
    faults.clear()
    stats.reset("serve/")
    eng = make_engine(depth)
    rs = np.random.RandomState(0)
    ok = [list(rs.randint(0, 96, size=n)) for n in (5, 17)]
    poisoned = list(rs.randint(0, 96, size=7))
    r_ok = [eng.submit(p, max_new_tokens=8) for p in ok]
    r_poi = eng.submit(poisoned, max_new_tokens=8)   # slot 2
    r_dead = eng.submit([1, 2, 3], max_new_tokens=8, deadline_s=0.0)
    eng.step()
    with faults.inject("engine.poison_logits", "nan", slot=2, count=1):
        eng.step()
    eng.run()
    assert r_poi.failed and r_poi.error == "non-finite logits", \
        "poisoned request not evicted"
    assert r_dead.failed and "deadline" in r_dead.error
    assert all(r.done and not r.failed for r in r_ok)
    assert stats.get("serve/nonfinite_evictions") == 1
    # queued expiry lands on the queue-reject counter (distinct from
    # mid-decode serve/deadline_evictions — no device work was wasted)
    assert stats.get("serve/queue_deadline_rejects") == 1
    assert stats.get("serve/deadline_evictions") == 0
    assert stats.get("serve/inflight") == 0
    if depth > 1:
        assert stats.snapshot("serve/").get(
            "serve/host_gap_s.count", 0) >= 1, "pipeline never measured"
    return [list(r.tokens) for r in r_ok]


def main():
    model = _model()
    cases = {
        "plain": lambda d: DecodeEngine(
            model, max_slots=3, max_len=128, inflight=d),
        "chunked": lambda d: DecodeEngine(
            model, max_slots=3, max_len=128, steps_per_call=4,
            inflight=d),
        "speculative": lambda d: DecodeEngine(
            model, max_slots=3, max_len=128, speculative_k=3,
            steps_per_call=2, inflight=d),
        # the serving default (factory → paged, megakernel step)
        "paged": lambda d: make_engine(
            model, n_pages=24, max_slots=3, steps_per_call=2,
            inflight=d),
        "paged_spec": lambda d: make_engine(
            model, n_pages=24, max_slots=3, steps_per_call=2,
            speculative_k=3, inflight=d),
    }
    for name, make in cases.items():
        base = _serve(make, 1)
        piped = _serve(make, 3)
        assert piped == base, \
            f"{name}: depth-3 streams diverged from depth-1"
        print(f"  {name}: depth1 == depth3 "
              f"({sum(len(s) for s in base)} survivor tokens)",
              flush=True)
    print(stats.table("serve/"))
    print("SERVE SMOKE OK", flush=True)


if __name__ == "__main__":
    main()
