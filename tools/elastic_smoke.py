"""Elastic-fleet smoke (tools/ci.sh elastic, ISSUE 14; ~90s):

Phase 1 — serving autoscale + heal: the FleetController spawns a
2-replica decode fleet (floor=2) through the real launch CLI, Poisson
load flows through the router, and one replica is SIGKILLed
mid-traffic. Asserts: the controller replaces it (fleet converges back
to the floor), EVERY submitted request id completes (zero loss,
at-least-once), the replacement actually serves (goodput recovers),
and the post-load idle stretch triggers one graceful scale-down drain
(replica exits ``drained``, rc 0).

Phase 2 — preemption-tolerant training: a 4-worker static launch under
PT_ELASTIC_RESHAPE=1; two workers die once epoch 1 commits. Asserts:
the launcher reshapes the group 4→2 exporting the new world size, the
trainer re-plans its mesh and restore_resharded-resumes from the
newest VERIFIED epoch (epochs continue, never restart from 0), and
the job finishes all epochs at world 2.

Exit 0 + "ELASTIC SMOKE OK" on success; any divergence asserts.
"""
import json
import os
import signal
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.fleet import (FleetController, TierSpec,  # noqa: E402
                              TargetOccupancyPolicy, launch_spawn)
from paddle_tpu.serving import Router, loadgen  # noqa: E402

SERVE_WORKER = os.path.join(REPO, "tests", "_serve_worker.py")
TRAIN_WORKER = os.path.join(REPO, "tests", "_elastic_train_worker.py")


def phase_serving():
    stats.reset("fleet/controller")
    stats.reset("serve/router")
    router = Router(port=0, dead_after=3.0)
    ctl = FleetController(
        router,
        launch_spawn(SERVE_WORKER, router.store.port, pass_role=False),
        tiers=[TierSpec("both", min_replicas=2, max_replicas=3,
                        policy=TargetOccupancyPolicy(
                            down_sustain_s=4.0))],
        cooldown_s=1.0, drain_grace_s=15.0)
    try:
        ctl.step()                       # heal empty fleet up to floor
        rids = router.wait_replicas(2, timeout=120)
        print(f"  phase 1: controller spawned the floor fleet {rids}",
              flush=True)

        rs = np.random.RandomState(11)
        trace = loadgen.poisson_trace(28, qps=3.0, seed=7, vocab=96,
                                      prompt_len=(6, 24),
                                      new_tokens=(6, 16))
        ids, arrivals = [], iter(trace)
        nxt = next(arrivals)
        t0 = time.monotonic()
        victim = rids[0]
        victim_pid = router.directory.members()[victim]["pid"]
        killed = [False]

        def tick():
            nonlocal nxt
            while nxt is not None and \
                    time.monotonic() - t0 >= nxt.t:
                ids.append(router.submit(
                    nxt.prompt, max_new_tokens=nxt.max_new_tokens))
                nxt = next(arrivals, None)
            if not killed[0] and len(ids) >= 8:
                killed[0] = True
                os.kill(victim_pid, signal.SIGKILL)
                print(f"  phase 1: SIGKILLed {victim} "
                      f"(pid {victim_pid}) mid-traffic", flush=True)

        ctl.pump(14.0, interval_s=0.15, extra=tick)
        while nxt is not None:           # drain any un-submitted tail
            ids.append(router.submit(nxt.prompt,
                                     max_new_tokens=nxt.max_new_tokens))
            nxt = next(arrivals, None)
        results = router.drain(timeout=120)

        # zero request-id loss: every submitted id completed
        missing = sorted(set(ids) - set(results))
        assert not missing, f"lost request ids: {missing}"
        assert all(results[q]["status"] == "done" for q in ids), \
            {q: results[q] for q in ids
             if results[q]["status"] != "done"}
        # the controller replaced the victim: >= 3 spawns (2 floor +
        # >= 1 heal) and the fleet is back at the floor
        n_up = int(stats.get("fleet/controller_scale_ups"))
        assert n_up >= 3, f"controller never healed (scale_ups={n_up})"
        alive = router.wait_replicas(2, timeout=60)
        assert victim not in alive, alive
        print(f"  phase 1: {len(ids)} requests, zero loss through the "
              f"kill; fleet converged to {alive}", flush=True)

        # goodput recovery: a post-heal wave is served by the healed
        # fleet, INCLUDING the replacement replica
        wave2 = [router.submit(list(rs.randint(0, 96, size=10)),
                               max_new_tokens=8) for _ in range(10)]
        results = router.drain(timeout=120)
        assert all(results[q]["status"] == "done" for q in wave2)
        served_by = {results[q]["replica"] for q in wave2}
        replacement = [r for r in alive if r not in rids]
        assert replacement and any(r in served_by for r in replacement), \
            f"replacement {replacement} never served: {served_by}"
        print(f"  phase 1: post-heal wave served by {sorted(served_by)}"
              f" (goodput recovered)", flush=True)

        # graceful retirement: drop the ceiling to 1 — the controller
        # drains the emptier replica, which finishes, publishes
        # 'drained', and exits on its own
        ctl.tiers[0].min_replicas = 1
        ctl.tiers[0].max_replicas = 1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                not stats.get("fleet/controller_drains_completed"):
            ctl.pump(0.5, interval_s=0.25)
        n_drained = int(stats.get("fleet/controller_drains_completed"))
        assert n_drained >= 1, "ceiling drop never drained a replica"
        assert int(stats.get("fleet/controller_kills")) == 0, \
            "graceful drain escalated to SIGKILL"
        print(f"  phase 1: ceiling drop drained {n_drained} replica(s) "
              f"gracefully (no kill)", flush=True)
    finally:
        router.shutdown()
        ctl.shutdown()
        router.close()


def phase_training(workdir):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_ELASTIC_RESHAPE="1", ET_DIE_RANKS="2,3",
               ET_DIE_WORLD="4", ET_DIE_AFTER_EPOCH="1",
               ET_DIE_SIGNAL="kill")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--max_restarts", "2",
         "--master", f"127.0.0.1:{7941 + os.getpid() % 500}",
         TRAIN_WORKER, workdir, "6"],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])
    assert "reshaping local group 4->2" in r.stderr, r.stderr[-2000:]
    assert "reshaped 4->2 devices" in r.stderr, r.stderr[-2000:]
    log = [json.loads(line) for line in
           open(os.path.join(workdir, "loss_log.jsonl"))]
    v1 = [e for e in log if e["world"] == 4]
    v2 = [e for e in log if e["world"] == 2]
    assert v1 and v2, log
    # resumed from the newest VERIFIED epoch: epochs continue
    assert v2[0]["epoch"] <= v1[-1]["epoch"] + 1, (v1[-1], v2[0])
    assert max(e["epoch"] for e in log) == 5, log
    # the resumed trajectory continues the optimum, not from scratch
    assert v2[0]["loss"] <= log[0]["loss"] + 0.05, (v2[0], log[0])
    print(f"  phase 2: SIGKILL-preempted 4->2 reshape resumed at "
          f"epoch {v2[0]['epoch']} (loss {v2[0]['loss']:.4f}), "
          f"finished all 6 epochs at world 2", flush=True)


def main():
    import tempfile
    t0 = time.perf_counter()
    phase_serving()
    phase_training(tempfile.mkdtemp(prefix="elastic_smoke_"))
    print(f"ELASTIC SMOKE OK ({time.perf_counter() - t0:.0f}s)",
          flush=True)


if __name__ == "__main__":
    main()
