"""Live-reshard + drain-migration smoke (tools/ci.sh reshard,
ISSUE 16; ~2 min):

Phase A — in-HBM training reshape: an ElasticTrainer on 4 virtual CPU
devices requests a cooperative 4->2 reshape mid-run. The in-HBM
redistribute path (PT_RESHARD_INPLACE=1) must produce the SAME loss
trajectory as the checkpoint round trip it replaces
(PT_RESHARD_INPLACE=0 control), observe ``fleet/reshard_inplace_s``,
and take zero fallbacks.

Phase B — drain-with-migration serving: a router + two real replica
processes under Poisson load; one replica is marked draining
mid-decode. Its in-flight requests must MIGRATE to the survivor
(``serve/router_migrated`` > 0), the drain must complete in seconds
(bounded by migration, not the longest request), every request id must
complete, and every token stream must be byte-identical to a no-drain
control fleet run of the same trace.

Exit 0 + "RESHARD SMOKE OK" on success; any divergence asserts.
"""
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from paddle_tpu import stats  # noqa: E402
from paddle_tpu.serving import Router, loadgen  # noqa: E402

WORKER = os.path.join(REPO, "tests", "_serve_worker.py")


def phase_train(workdir):
    import jax.numpy as jnp
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.fleet import ElasticTrainer, plan_topology
    from paddle_tpu.fleet.elastic_train import synthetic_data
    from paddle_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)

    def run(tag):
        mesh_lib.set_topology(None)
        trainer = ElasticTrainer(
            gpt.GPT(cfg, seed=0), optim.SGD(learning_rate=0.05),
            os.path.join(workdir, tag), n_epochs=4,
            mesh=plan_topology(gpt.GPT(cfg, seed=0), n_devices=4),
            data_fn=synthetic_data(cfg.vocab_size, 12,
                                   cfg.max_seq_len))
        trainer.on_epoch = (
            lambda rec: trainer.request_reshape(2)
            if rec["epoch"] == 1 else None)
        try:
            return trainer.run()
        finally:
            mesh_lib.set_topology(None)

    stats.reset("fleet/")
    t0 = time.perf_counter()
    recs = run("inplace")
    snap = stats.snapshot("fleet/")
    assert [r["devices"] for r in recs] == [4, 4, 2, 2], recs
    assert stats.get("fleet/reshard_fallbacks") == 0, \
        "in-HBM reshard fell back on a healthy run"
    inplace_s = snap.get("fleet/reshard_inplace_s.sum", 0.0)
    assert snap.get("fleet/reshard_inplace_s.count", 0) >= 1
    print(f"  phase A: in-HBM 4->2 reshard in {inplace_s:.3f}s, "
          f"zero fallbacks ({time.perf_counter() - t0:.0f}s)",
          flush=True)

    os.environ["PT_RESHARD_INPLACE"] = "0"
    try:
        control = run("ckpt")
    finally:
        del os.environ["PT_RESHARD_INPLACE"]
    for a, b in zip(recs, control):
        assert abs(a["loss"] - b["loss"]) < 1e-6, \
            f"in-HBM trajectory diverged from checkpoint path: {a} {b}"
    print("  phase A: loss trajectory identical to the checkpoint-path "
          "control (bit-parity oracle holds)", flush=True)


def _spawn(store_port, rid, launch_port):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         WORKER, str(store_port), rid],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _run_fleet(trace, drain_at=None):
    """One 2-replica fleet run of ``trace``; optionally mark rep0
    draining ``drain_at`` seconds in. Returns (results by submit
    order, drain latency seconds or None, router_migrated count)."""
    stats.reset("serve/")
    base = 9100 + (os.getpid() + (0 if drain_at is None else 50)) % 400
    router = Router(port=0, dead_after=20.0)
    procs = [_spawn(router.store.port, f"rep{i}", base + i)
             for i in range(2)]
    try:
        router.wait_replicas(2, timeout=120)
        ids = []
        t0 = time.monotonic()
        drained = [None]

        def _drain_now():
            td = time.monotonic()
            router.mark_draining("rep0")
            while router.directory.state("rep0") != "drained":
                router.poll()
                time.sleep(0.02)
            drained[0] = time.monotonic() - td
            print(f"  phase B: rep0 drained in {drained[0]:.2f}s "
                  f"mid-traffic", flush=True)

        for a in trace:
            while time.monotonic() - t0 < a.t:
                if drain_at is not None and \
                        time.monotonic() - t0 >= drain_at:
                    drain_at = None
                    _drain_now()
                router.poll()
                time.sleep(0.01)
            ids.append(router.submit(a.prompt,
                                     max_new_tokens=a.max_new_tokens))
        if drain_at is not None:
            _drain_now()
        results = router.drain(timeout=120)
        drained_in = drained[0]
        migrated = int(stats.get("serve/router_migrated"))
        return [results[q] for q in ids], drained_in, migrated
    finally:
        router.shutdown()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        router.close()


def phase_serve():
    trace = loadgen.poisson_trace(10, qps=4.0, seed=7, vocab=96,
                                  prompt_len=(6, 24),
                                  new_tokens=(24, 48))
    control, _none, _m = _run_fleet(trace)
    assert all(r["status"] == "done" for r in control), control
    drained, drain_s, migrated = _run_fleet(trace, drain_at=1.0)
    assert all(r["status"] == "done" for r in drained), \
        [r for r in drained if r["status"] != "done"]    # zero id loss
    assert migrated > 0, \
        "drain never migrated an in-flight request mid-decode"
    assert drain_s is not None and drain_s < 30.0, drain_s
    # byte-identical streams: migration must not fork any stream
    for i, (a, b) in enumerate(zip(control, drained)):
        assert a["tokens"] == b["tokens"], \
            (i, a["tokens"], b["tokens"])
    print(f"  phase B: {len(drained)} requests, {migrated} migrated "
          f"mid-decode, all streams byte-identical to the no-drain "
          f"control", flush=True)


def main():
    import tempfile
    t0 = time.perf_counter()
    phase_train(tempfile.mkdtemp(prefix="reshard_smoke_"))
    phase_serve()
    print(f"RESHARD SMOKE OK ({time.perf_counter() - t0:.0f}s)",
          flush=True)


if __name__ == "__main__":
    main()
