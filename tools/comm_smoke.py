#!/usr/bin/env python
"""Quantized-collective CPU smoke (``tools/ci.sh comm``).

A tiny 2-device host-platform mesh runs the whole quantized wire in a
couple of seconds and fails loudly on any of the ISSUE-7 acceptance
regressions:

- the compressed dp step (int8 AND fp8) converges at parity with the
  fp32 step on the same seed, with error feedback engaged;
- ``comm/bytes_wire`` shows ≥3.5x reduction vs ``comm/bytes_logical``
  for int8 at block 256;
- the stage-3 quantized weight all-gather reproduces the fp32 gather
  inside the per-block half-step bound;
- a bitflipped block scale makes the step RAISE, not drift.

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys

# must precede the jax import: force a small host-platform mesh whatever
# the caller's XLA_FLAGS said
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu  # noqa: F401 -- installs the jax.shard_map shim
    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu import stats
    from paddle_tpu.distributed import compression as C
    from paddle_tpu.testing import faults

    assert len(jax.devices()) >= 2, jax.devices()
    out = {"devices": len(jax.devices())}
    topo = dist.init_mesh(dp=2, set_global=False)

    rs = np.random.RandomState(0)
    w_true = rs.randn(8, 4).astype(np.float32)
    x = rs.randn(64, 8).astype(np.float32)
    y = x @ w_true
    batch = (jnp.asarray(x), jnp.asarray(y))

    def loss_fn(p, b):
        xb, yb = b
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    def run(method):
        params = {"w": jnp.zeros((8, 4), jnp.float32)}
        opt = optim.SGD(learning_rate=0.1)
        st = opt.init(params)
        ef = C.init_error_feedback(params, topo.mesh) if method else ()
        step = C.build_compressed_dp_step(loss_fn, opt, topo.mesh, method)
        for _ in range(50):
            params, st, ef, loss = step(params, st, ef, batch)
        return float(loss), ef

    base, _ = run(None)
    out["fp32_loss"] = round(base, 6)
    for method in ("int8", "fp8"):
        loss, ef = run(method)
        out[f"{method}_loss"] = round(loss, 6)
        assert loss <= base * 1.5 + 1e-4, (method, loss, base)
        assert float(jnp.max(jnp.abs(ef["w"]))) > 0, \
            f"{method}: error feedback never engaged"

    # wire-volume acceptance: int8 at block 256 moves <= 2/7 of fp32
    stats.reset("comm/")

    def sync(g, e):
        m, ef, ok = C.compressed_mean_allgather(
            {"w": g[0]}, {"w": e[0]}, "dp", "int8", block=256)
        return m["w"], ef["w"][None], ok

    sm = shard_map(sync, mesh=topo.mesh, in_specs=(P("dp"), P("dp")),
                   out_specs=(P(), P("dp"), P()), check_vma=False)
    g = jnp.zeros((2, 64, 256), jnp.float32)
    jax.jit(sm).lower(g, jnp.zeros_like(g))
    ratio = stats.get("comm/bytes_logical") / stats.get("comm/bytes_wire")
    out["int8_wire_ratio"] = round(ratio, 3)
    assert ratio >= 3.5, ratio

    # stage-3 weight gather parity vs the fp32 gather
    w = jnp.asarray(rs.randn(16, 64).astype(np.float32))

    def gather(shard):
        q, ok = C.quantized_all_gather_dequant(shard, "dp", "int8",
                                               block=64, dim=0)
        return q, lax.all_gather(shard, "dp", axis=0, tiled=True), ok

    gm = shard_map(gather, mesh=topo.mesh, in_specs=(P("dp"),),
                   out_specs=(P(), P(), P()), check_vma=False)
    q, f, ok = jax.jit(gm)(w)
    assert bool(ok)
    err = float(jnp.max(jnp.abs(q - f)))
    bound = float(jnp.max(jnp.abs(w))) * 0.5 / 127 + 1e-7
    out["stage3_gather_err"] = round(err, 7)
    assert err <= bound, (err, bound)

    # fail-loud: a bitflipped block scale must raise, not steer
    with faults.inject("collective.quant_payload", "bitflip", bit=30):
        params = {"w": jnp.zeros((8, 4), jnp.float32)}
        opt = optim.SGD(learning_rate=0.1)
        st = opt.init(params)
        ef = C.init_error_feedback(params, topo.mesh)
        step = C.build_compressed_dp_step(loss_fn, opt, topo.mesh, "int8")
        try:
            step(params, st, ef, batch)
            raise AssertionError("bitflipped scale did NOT raise")
        except RuntimeError:
            out["bitflip_raises"] = True
    faults.clear()

    print(json.dumps({"comm_smoke": "ok", **out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
