#!/usr/bin/env python
"""Quantized-collective CPU smoke (``tools/ci.sh comm``).

A tiny 2-device host-platform mesh runs the whole quantized wire in a
couple of seconds and fails loudly on any of the ISSUE-7 acceptance
regressions:

- the compressed dp step (int8 AND fp8) converges at parity with the
  fp32 step on the same seed, with error feedback engaged;
- ``comm/bytes_wire`` shows ≥3.5x reduction vs ``comm/bytes_logical``
  for int8 at block 256;
- the stage-3 quantized weight all-gather reproduces the fp32 gather
  inside the per-block half-step bound;
- a bitflipped block scale makes the step RAISE, not drift;
- (ISSUE 11, ``tools/ci.sh overlap`` / ``--overlap``) the overlap
  scheduler's 4-device sweep: toggling overlap on/off (prefetch pinned)
  leaves the parameters BIT-identical after 3 steps, the prefetch
  toggle stays inside a float-ulp envelope, and the overlap-on lowered
  HLO carries more than one reduce-scatter (one per bucket riding the
  all-to-all wire) instead of a single fused tail collective.

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys

# must precede the jax import: force a small host-platform mesh whatever
# the caller's XLA_FLAGS said
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=4")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu  # noqa: F401 -- installs the jax.shard_map shim
    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu import stats
    from paddle_tpu.distributed import compression as C
    from paddle_tpu.testing import faults

    assert len(jax.devices()) >= 4, jax.devices()
    out = {"devices": len(jax.devices())}
    # the quantized-wire checks keep their original 2-device dp mesh;
    # the overlap sweep uses all 4 (its acceptance topology)
    topo = dist.init_mesh(dp=2, devices=jax.devices()[:2],
                          set_global=False)

    rs = np.random.RandomState(0)
    w_true = rs.randn(8, 4).astype(np.float32)
    x = rs.randn(64, 8).astype(np.float32)
    y = x @ w_true
    batch = (jnp.asarray(x), jnp.asarray(y))

    def loss_fn(p, b):
        xb, yb = b
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    def run(method):
        params = {"w": jnp.zeros((8, 4), jnp.float32)}
        opt = optim.SGD(learning_rate=0.1)
        st = opt.init(params)
        ef = C.init_error_feedback(params, topo.mesh) if method else ()
        step = C.build_compressed_dp_step(loss_fn, opt, topo.mesh, method)
        for _ in range(50):
            params, st, ef, loss = step(params, st, ef, batch)
        return float(loss), ef

    base, _ = run(None)
    out["fp32_loss"] = round(base, 6)
    for method in ("int8", "fp8"):
        loss, ef = run(method)
        out[f"{method}_loss"] = round(loss, 6)
        assert loss <= base * 1.5 + 1e-4, (method, loss, base)
        assert float(jnp.max(jnp.abs(ef["w"]))) > 0, \
            f"{method}: error feedback never engaged"

    # wire-volume acceptance: int8 at block 256 moves <= 2/7 of fp32
    stats.reset("comm/")

    def sync(g, e):
        m, ef, ok = C.compressed_mean_allgather(
            {"w": g[0]}, {"w": e[0]}, "dp", "int8", block=256)
        return m["w"], ef["w"][None], ok

    sm = shard_map(sync, mesh=topo.mesh, in_specs=(P("dp"), P("dp")),
                   out_specs=(P(), P("dp"), P()), check_vma=False)
    g = jnp.zeros((2, 64, 256), jnp.float32)
    jax.jit(sm).lower(g, jnp.zeros_like(g))
    ratio = stats.get("comm/bytes_logical") / stats.get("comm/bytes_wire")
    out["int8_wire_ratio"] = round(ratio, 3)
    assert ratio >= 3.5, ratio

    # stage-3 weight gather parity vs the fp32 gather
    w = jnp.asarray(rs.randn(16, 64).astype(np.float32))

    def gather(shard):
        q, ok = C.quantized_all_gather_dequant(shard, "dp", "int8",
                                               block=64, dim=0)
        return q, lax.all_gather(shard, "dp", axis=0, tiled=True), ok

    gm = shard_map(gather, mesh=topo.mesh, in_specs=(P("dp"),),
                   out_specs=(P(), P(), P()), check_vma=False)
    q, f, ok = jax.jit(gm)(w)
    assert bool(ok)
    err = float(jnp.max(jnp.abs(q - f)))
    bound = float(jnp.max(jnp.abs(w))) * 0.5 / 127 + 1e-7
    out["stage3_gather_err"] = round(err, 7)
    assert err <= bound, (err, bound)

    # fail-loud: a bitflipped block scale must raise, not steer
    with faults.inject("collective.quant_payload", "bitflip", bit=30):
        params = {"w": jnp.zeros((8, 4), jnp.float32)}
        opt = optim.SGD(learning_rate=0.1)
        st = opt.init(params)
        ef = C.init_error_feedback(params, topo.mesh)
        step = C.build_compressed_dp_step(loss_fn, opt, topo.mesh, "int8")
        try:
            step(params, st, ef, batch)
            raise AssertionError("bitflipped scale did NOT raise")
        except RuntimeError:
            out["bitflip_raises"] = True
    faults.clear()

    out.update(overlap_sweep(emit=False))
    print(json.dumps({"comm_smoke": "ok", **out}))
    return 0


def overlap_sweep(emit: bool = True) -> dict:
    """ISSUE 11 acceptance sweep on the full 4-device mesh: overlap
    on/off bit-parity after 3 steps, prefetch ulp envelope, and >1
    reduce-scatter in the overlap-on lowered HLO."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu  # noqa: F401
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import overlap as OV

    out = {}
    topo = dist.init_mesh(fsdp=4, set_global=False)
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(16, 16), jnp.float32)
    y = jnp.asarray(rs.randn(16, 8), jnp.float32)

    def run(overlap, prefetch):
        sp, st, step = OV.overlap_parallel(
            dict(params), emb, blk, lf, optim.SGD(learning_rate=0.05),
            topo.mesh, stacked, comm_quant="int8", overlap=overlap,
            prefetch=prefetch, bucket_mb=1e-4)
        lowered = step.lower(sp, st, x, y).as_text()
        for _ in range(3):
            sp, st, loss = step(sp, st, x, y)
        return {k: np.asarray(v) for k, v in
                jax.device_get(sp).items()}, float(loss), lowered

    p_on, l_on, hlo_on = run(True, False)
    p_off, l_off, _ = run(False, False)
    # bit-parity: toggling overlap alone moves ONLY collective placement
    for k in p_on:
        assert np.array_equal(p_on[k], p_off[k]), \
            f"overlap on/off params diverged at {k!r}"
    out["overlap_bit_parity"] = True
    out["overlap_on_loss"] = round(l_on, 6)
    # >1 reduce-scatter in the lowered HLO: each bucket rides its own
    # all-to-all exchange instead of one fused tail collective. Count
    # only int8-PAYLOAD all_to_alls (each bucket also moves an fp32
    # scales exchange, so a raw op count could pass with every bucket
    # fused into one tail exchange). Lowered text is StableHLO
    # ("all_to_all", one op per line, i8 element type in the signature).
    import re
    n_a2a = len([ln for ln in hlo_on.splitlines()
                 if re.search(r"all[_-]to[_-]all", ln)
                 and "xi8>" in ln])
    out["overlap_on_hlo_int8_all_to_all"] = n_a2a
    assert n_a2a > 1, f"expected >1 int8 reduce-scatter, HLO has {n_a2a}"
    # prefetch toggle: float-ulp envelope (the double-buffered carry
    # legitimately changes matmul layouts — see overlap.py docstring)
    p_pf, l_pf, _ = run(True, True)
    delta = max(float(np.max(np.abs(p_pf[k] - p_on[k]))) for k in p_on)
    out["prefetch_max_delta"] = delta
    assert delta <= 1e-6, f"prefetch toggle drifted {delta}"
    if emit:   # standalone (--overlap) path prints its own one line
        print(json.dumps({"overlap_sweep": "ok", **out}))
    return out


if __name__ == "__main__":
    if "--overlap" in sys.argv[1:]:
        overlap_sweep()
        sys.exit(0)
    sys.exit(main())
