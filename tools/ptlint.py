#!/usr/bin/env python
"""ptlint CLI — lint the tree with paddle_tpu.analysis.

    python tools/ptlint.py [paths ...]       # default: paddle_tpu tools
    python tools/ptlint.py paddle_tpu --stats     # findings per rule
    python tools/ptlint.py paddle_tpu --write-baseline
    python tools/ptlint.py paddle_tpu --error-on-new   # (the default)

Exit status: 0 when every finding is suppressed or baselined, 1 when
NEW findings exist (use --no-error to always exit 0), 2 on usage/parse
errors. ``--stats`` prints per-rule totals (baselined included) so
BENCH runs can track the count trending to zero.

The analysis package is loaded standalone (no ``import paddle_tpu``),
so linting works — and stays fast — even when jax or the accelerator
stack is broken.
"""

import argparse
import importlib.util
import json
import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "ptlint_baseline.json")


def _load_analysis():
    """Import paddle_tpu.analysis WITHOUT executing paddle_tpu/__init__
    (which drags in jax). Falls back to the normal import when the
    package is already loaded."""
    if "paddle_tpu.analysis" in sys.modules:
        return sys.modules["paddle_tpu.analysis"]
    pkg_dir = os.path.join(ROOT, "paddle_tpu", "analysis")
    if "paddle_tpu" not in sys.modules:
        stub = types.ModuleType("paddle_tpu")
        stub.__path__ = [os.path.join(ROOT, "paddle_tpu")]
        sys.modules["paddle_tpu"] = stub
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptlint", description="TPU-aware static analysis")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddle_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON (default tools/"
                         "ptlint_baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the current findings as the baseline")
    ap.add_argument("--error-on-new", action="store_true",
                    help="exit 1 on non-baselined findings (default)")
    ap.add_argument("--no-error", action="store_true",
                    help="report only; always exit 0")
    ap.add_argument("--stats", action="store_true",
                    help="print findings-per-rule totals")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (e.g. "
                         "PT001,PT005)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    analysis = _load_analysis()
    paths = args.paths or [os.path.join(ROOT, "paddle_tpu"),
                           os.path.join(ROOT, "tools")]
    project = analysis.load_project(paths, root=ROOT)
    parse_errors = list(getattr(project, "parse_errors", []))
    for rel, err in parse_errors:
        print(f"ptlint: skipped {rel}: {err}", file=sys.stderr)

    rules = analysis.default_rules()
    if args.rules:
        keep = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in keep]
        if not rules:
            print(f"ptlint: no such rules {sorted(keep)}",
                  file=sys.stderr)
            return 2
    findings = analysis.run(project, rules)

    if args.write_baseline:
        if parse_errors:
            print("ptlint: refusing to write a baseline from a tree "
                  "with parse errors", file=sys.stderr)
            return 2
        analysis.baseline.write(args.baseline, findings)
        print(f"ptlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(args.baseline, ROOT)}")
        return 0

    known_map = analysis.baseline.load(args.baseline)
    new, known = analysis.baseline.partition(findings, known_map)

    if args.format == "json":
        print(json.dumps(
            {"new": [vars(f) for f in new],
             "baselined": [vars(f) for f in known]}, indent=2))
    else:
        for f in new:
            print(f.format())
        if known:
            print(f"ptlint: {len(known)} baselined finding(s) "
                  f"suppressed (see {os.path.relpath(args.baseline, ROOT)})")

    if args.stats:
        per_rule = {}
        for f in findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        print("ptlint stats (baselined included):")
        for rule in sorted(set(list(per_rule) +
                               [r.id for r in rules])):
            print(f"  {rule}: {per_rule.get(rule, 0)}")
        print(f"  total: {len(findings)}  new: {len(new)}  "
              f"baselined: {len(known)}")

    if new:
        print(f"ptlint: {len(new)} new finding(s)", file=sys.stderr)
        return 0 if args.no_error else 1
    if parse_errors and not args.no_error:
        # an unparseable file means the tree was NOT actually checked —
        # a green exit here would let the CI lint gate pass on exactly
        # the most broken trees
        print(f"ptlint: {len(parse_errors)} file(s) could not be "
              "parsed", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
