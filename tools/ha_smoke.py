"""Control-plane HA smoke (tools/ci.sh ha, ISSUE 17): SIGKILL the
router process mid-traffic — REAL processes end to end — and prove
the failover contract in about a minute on CPU:

- a successor router generation (same request journal, same endpoint
  file) recovers the intake via journal replay and re-places every
  outstanding request (``recovered`` > 0 enforced by construction:
  the kill lands while the journal holds submits without results);
- the replicas reconnect through the endpoint file, re-announce, and
  republish retained results to the new generation's store;
- ZERO request-id loss: the successor's result set is exactly the
  full workload, every stream ``done`` — and byte-identical to an
  undisturbed control fleet (greedy decode, same weights), run first.

Exit 0 + "HA SMOKE OK" on success; any divergence asserts. The
fuller (slower) acceptance matrix — SIGSTOP partitions, disagg
store-chaos — lives in tests/test_router_failover.py (-m slow).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

from paddle_tpu.serving.router import read_endpoint_file  # noqa: E402

ROUTER_WORKER = os.path.join(REPO, "tests", "_router_worker.py")
SERVE_WORKER = os.path.join(REPO, "tests", "_serve_worker.py")

WORKLOAD = 10
SEED = 3


def _free_port():
    """An unused launch-master port (fixed ladders collide with
    orphans from earlier failed runs)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_router(ep, journal, res, extra=()):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, ROUTER_WORKER, "--endpoint-file", ep,
         "--journal", journal, "--results", res,
         "--workload", str(WORKLOAD), "--seed", str(SEED), *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        start_new_session=True)


def _spawn_replica(store_port, rid, ep):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_ROUTER_ENDPOINT_FILE=ep)
    # own process group so cleanup can reach the serve-worker
    # grandchildren, not just the launch parent
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{_free_port()}",
         SERVE_WORKER, str(store_port), rid],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        start_new_session=True)


def _wait_file(path, timeout, what):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        assert time.monotonic() < deadline, \
            f"{what} {path} absent after {timeout}s"
        time.sleep(0.05)


def _journal_counts(path):
    s = r = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                if '"kind": "submit"' in line:
                    s += 1
                elif '"kind": "result"' in line:
                    r += 1
    except OSError:
        pass
    return s, r


def _kill_group(p):
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        try:
            p.kill()
        except OSError:
            pass


def _reap(procs, timeout=40):
    for p in procs:
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _kill_group(p)
            p.wait(timeout=10)


def _run(tag, tmp, kill_mid_traffic):
    ep = os.path.join(tmp, f"{tag}.ep")
    journal = os.path.join(tmp, f"{tag}.jsonl")
    res = os.path.join(tmp, f"{tag}.results.json")
    gen1 = _spawn_router(ep, journal, res,
                         extra=["--interval-ms", "30"])
    procs, gen2 = [], None
    try:
        _wait_file(ep, 60, "endpoint file")
        port = read_endpoint_file(ep)["port"]
        procs = [_spawn_replica(port, f"{tag}-r0", ep),
                 _spawn_replica(port, f"{tag}-r1", ep)]
        if kill_mid_traffic:
            deadline = time.monotonic() + 90
            while True:
                s, r = _journal_counts(journal)
                if s >= WORKLOAD // 2 and s > r:
                    break
                assert time.monotonic() < deadline, \
                    "router never reached mid-traffic"
                assert gen1.poll() is None, "router died on its own"
                time.sleep(0.02)
            os.kill(gen1.pid, signal.SIGKILL)
            gen1.wait(timeout=10)
            print(f"  killed gen-1 router at "
                  f"{_journal_counts(journal)[0]}/{WORKLOAD} submits",
                  flush=True)
            gen2 = _spawn_router(ep, journal, res)
        _wait_file(res, 180, "results file")
        with open(res, encoding="utf-8") as f:
            out = json.load(f)
        _reap(([gen2] if gen2 else [gen1]) + procs)
        return out
    except BaseException:
        for p in [gen1, *procs] + ([gen2] if gen2 else []):
            if p.poll() is None:
                _kill_group(p)
        raise


def main():
    t0 = time.monotonic()
    all_ids = {f"rq-{i:06d}" for i in range(1, WORKLOAD + 1)}
    with tempfile.TemporaryDirectory(prefix="pt-ha-smoke-") as tmp:
        control = _run("ctrl", tmp, kill_mid_traffic=False)
        assert set(control["results"]) == all_ids
        print(f"  control: {WORKLOAD} streams, one generation",
              flush=True)
        out = _run("ha", tmp, kill_mid_traffic=True)
        assert out["generation"] == 2, out["generation"]
        assert out["recovered"] >= 1, \
            "journal replay recovered nothing"
        assert set(out["results"]) == all_ids, \
            sorted(all_ids - set(out["results"]))
        assert all(v["status"] == "done"
                   for v in out["results"].values())
        diverged = [q for q in sorted(all_ids)
                    if out["results"][q]["tokens"]
                    != control["results"][q]["tokens"]]
        assert not diverged, f"streams diverged: {diverged}"
        print(f"  failover: gen-2 recovered {out['recovered']} "
              f"outstanding, {WORKLOAD}/{WORKLOAD} ids, "
              f"byte-identical", flush=True)
    print(f"HA SMOKE OK ({time.monotonic() - t0:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
