"""Unified tracing + metrics pipeline (ISSUE 3): span nesting and ring
overflow, Chrome-trace/Perfetto schema, histogram percentile math vs
numpy, registry dedup, cross-rank export/merge, the statsz endpoint,
and the trace-merge CLI."""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from paddle_tpu import stats
from paddle_tpu.observability import (span, begin, end, complete, trace,
                                      merge_trace_files,
                                      merge_rank_traces, start_statsz,
                                      stop_statsz)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    trace.clear()
    yield
    trace.disable()
    trace.clear()
    stats.reset()


def _export_events(tmp_path, name="t.json"):
    path = trace.export(str(tmp_path / name))
    with open(path) as f:
        doc = json.load(f)
    return doc, [e for e in doc["traceEvents"] if e.get("ph") == "X"]


# -- spans -------------------------------------------------------------------

def test_span_nesting_parent_ids(tmp_path):
    trace.enable(str(tmp_path))
    with span("outer", kind="test"):
        with span("mid") as sp:
            sp.attrs["bytes"] = 42
            with span("leaf"):
                pass
        with span("mid2"):
            pass
    doc, evs = _export_events(tmp_path)
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"outer", "mid", "mid2", "leaf"}
    outer = by_name["outer"]["args"]["span_id"]
    assert by_name["mid"]["args"]["parent_id"] == outer
    assert by_name["mid2"]["args"]["parent_id"] == outer
    assert by_name["leaf"]["args"]["parent_id"] == \
        by_name["mid"]["args"]["span_id"]
    assert by_name["outer"]["args"]["parent_id"] == 0
    assert by_name["mid"]["args"]["bytes"] == 42
    # children nest inside the parent's interval (1us slack: exported
    # timestamps are wall-rebased floats with ~sub-us rounding)
    for child in ("mid", "leaf"):
        assert by_name[child]["ts"] >= by_name["outer"]["ts"] - 1
        assert (by_name[child]["ts"] + by_name[child]["dur"]
                <= by_name["outer"]["ts"] + by_name["outer"]["dur"] + 1)


def test_span_decorator_and_disabled_noop(tmp_path):
    calls = []

    @span("deco/fn", tag=1)
    def fn(v):
        calls.append(v)
        return v * 2

    assert fn(3) == 6          # disabled: still runs, records nothing
    assert trace.events()[0] == []
    trace.enable(str(tmp_path))
    assert fn(4) == 8
    evs, dropped = trace.events()
    assert [e[0] for e in evs] == ["deco/fn"] and dropped == 0
    assert calls == [3, 4]


def test_async_begin_end_and_complete(tmp_path):
    trace.enable(str(tmp_path))
    tok = begin("async/op", job=7)
    done = threading.Event()

    def other_thread():
        end(tok, ok=True)
        done.set()

    threading.Thread(target=other_thread).start()
    done.wait(5)
    import time
    t0 = time.perf_counter() - 0.25
    complete("late/interval", t0, tokens=3)
    doc, evs = _export_events(tmp_path)
    by_name = {e["name"]: e for e in evs}
    assert by_name["async/op"]["args"]["job"] == 7
    assert by_name["async/op"]["args"]["ok"] is True
    assert by_name["late/interval"]["dur"] >= 0.2e6  # ~250ms in us
    assert by_name["late/interval"]["args"]["tokens"] == 3


def test_ring_buffer_overflow_keeps_newest(tmp_path):
    trace.enable(str(tmp_path), capacity=8)
    for i in range(20):
        with span(f"s{i}"):
            pass
    evs, dropped = trace.events()
    assert len(evs) == 8 and dropped == 12
    assert [e[0] for e in evs] == [f"s{i}" for i in range(12, 20)]
    doc, x = _export_events(tmp_path)
    assert doc["otherData"]["dropped"] == 12
    assert len(x) == 8


def test_perfetto_schema(tmp_path):
    trace.enable(str(tmp_path))
    with span("a", x=1):
        pass
    doc, evs = _export_events(tmp_path)
    assert isinstance(doc["traceEvents"], list)
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert any(m["name"] == "process_name" for m in metas)
    for e in evs:
        for key in ("name", "ph", "ts", "dur", "pid", "tid", "args"):
            assert key in e
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["ph"] == "X"
    # round-trips through json (Perfetto's minimum bar)
    json.dumps(doc)


def test_trace_file_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PT_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("PT_PROCESS_ID", "3")
    assert trace.trace_file_from_env() == \
        str(tmp_path / "trace_rank3.json")
    monkeypatch.setenv("PT_TRACE_FILE", str(tmp_path / "me.json"))
    assert trace.trace_file_from_env() == str(tmp_path / "me.json")


# -- histograms ---------------------------------------------------------------

def test_histogram_percentiles_against_numpy():
    rs = np.random.RandomState(7)
    vals = rs.lognormal(mean=-5.0, sigma=1.5, size=4000)
    r = stats.StatRegistry()
    for v in vals:
        r.observe("lat_s", float(v))
    snap = r.snapshot()
    assert snap["lat_s.count"] == 4000
    assert snap["lat_s.sum"] == pytest.approx(vals.sum(), rel=1e-9)
    assert snap["lat_s.max"] == pytest.approx(vals.max())
    # log-bucketed with growth 2^(1/4): quantile estimates are within
    # half a bucket (~9%) of the exact value
    for q in (50, 90, 99):
        exact = np.percentile(vals, q)
        est = snap[f"lat_s.p{q}"]
        assert abs(est - exact) / exact < 0.12, (q, est, exact)
    assert "lat_s.p99" in r.table("lat_s.")


def test_histogram_edge_cases():
    r = stats.StatRegistry()
    r.observe("h", 0.0)          # underflow bucket
    r.observe("h", -1.0)         # negative → underflow, min tracked
    r.observe("h", 5.0)
    snap = r.snapshot("h.")
    assert snap["h.count"] == 3
    assert snap["h.max"] == 5.0
    assert snap["h.p99"] <= 5.0
    # single-sample histogram: every percentile is that sample
    r2 = stats.StatRegistry()
    r2.observe("one", 0.25)
    s2 = r2.snapshot()
    assert s2["one.p50"] == pytest.approx(0.25, rel=0.1)
    assert s2["one.p99"] == pytest.approx(0.25, rel=0.1)


# -- reset prefix fix ---------------------------------------------------------

def test_reset_prefix_matches_timer_and_histogram_derived_names():
    r = stats.StatRegistry()
    with r.timer("p2p/send"):
        pass
    r.observe("serve/ttft_s", 0.1)
    r.add("p2p/send_msgs")
    assert "p2p/send.total_s" in r.snapshot()
    r.reset("p2p/send.")             # derived-name prefix: clears timer
    snap = r.snapshot()
    assert "p2p/send.total_s" not in snap
    assert snap["p2p/send_msgs"] == 1   # counter prefix-distinct, kept
    r.reset("serve/ttft_s.p9")       # derived histogram name
    assert "serve/ttft_s.p50" not in r.snapshot()


# -- registry dedup -----------------------------------------------------------

def test_profiler_registry_is_stats_registry():
    from paddle_tpu import profiler
    from paddle_tpu.profiler import statistic
    assert profiler.stat_registry is stats.default_registry()
    assert statistic.StatRegistry is stats.StatRegistry
    profiler.stat_add("dedup/x", 2)
    assert stats.get("dedup/x") == 2
    assert stats.snapshot()["dedup/x"] == 2
    stats.add("dedup/x", 1)
    assert profiler.stat_get("dedup/x") == 3


# -- export / merge -----------------------------------------------------------

def test_export_merge_sums_counters_and_merges_histograms():
    a = stats.StatRegistry()
    b = stats.StatRegistry()
    for reg, scale in ((a, 1.0), (b, 2.0)):
        reg.add("steps", 5)
        reg.set_value("mfu", 0.3 * scale)
        with reg.timer("io"):
            pass
        for i in range(100):
            reg.observe("lat_s", scale * (i + 1) / 100.0)
    merged = stats.merge([a.export(rank=0), b.export(rank=1)])
    snap = merged.snapshot()
    assert snap["steps"] == 10
    assert snap["lat_s.count"] == 200
    assert snap["io.count"] == 2
    # gauges are rank-namespaced, not clobbered
    assert snap["rank0/mfu"] == pytest.approx(0.3)
    assert snap["rank1/mfu"] == pytest.approx(0.6)
    assert "mfu" not in snap
    # merged p50 sits between the two ranks' medians
    assert 0.5 < snap["lat_s.p50"] < 1.1
    # round-trips through json (statsz / sidecar files)
    stats.merge([json.loads(json.dumps(a.export(rank=0)))])


def test_snapshot_tag_rank(monkeypatch):
    monkeypatch.setenv("PT_PROCESS_ID", "2")
    r = stats.StatRegistry()
    r.add("c", 1)
    assert r.snapshot(tag_rank=True) == {"rank2/c": 1}


# -- statsz -------------------------------------------------------------------

def test_statsz_server_serves_live_snapshot():
    stats.add("statsz/hits", 3)
    stats.observe("statsz/lat_s", 0.5)
    srv = start_statsz(0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/statsz", timeout=5) as r:
            doc = json.load(r)
        assert doc["counters"]["statsz/hits"] == 3
        assert doc["histograms"]["statsz/lat_s"]["count"] == 1
        assert "rank" in doc
        with urllib.request.urlopen(base + "/statsz?flat=1",
                                    timeout=5) as r:
            flat = json.load(r)
        assert flat["statsz/hits"] == 3 and "statsz/lat_s.p50" in flat
        with urllib.request.urlopen(base + "/", timeout=5) as r:
            text = r.read().decode()
        assert "statsz/hits" in text
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        stop_statsz()


def test_metricsz_prometheus_exposition_valid():
    """ISSUE 15 satellite: /metricsz serves Prometheus text exposition
    (0.0.4) of the live registry — every line a TYPE comment or a
    ``name[{labels}] value`` sample, counters suffixed _total,
    histograms as summaries with quantile samples."""
    import re
    from paddle_tpu.observability import StatszServer
    stats.add("promz/hits", 2)
    stats.set_value("promz/depth", 1.5)
    for v in (0.1, 0.2, 0.4):
        stats.observe("promz/lat_s", v)
    with stats.default_registry().timer("promz/phase"):
        pass
    srv = StatszServer(0, host="127.0.0.1")
    try:
        url = f"http://127.0.0.1:{srv.port}/metricsz"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
    finally:
        srv.stop()
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"\})?'
        r' (NaN|[+-]Inf|-?[0-9][0-9.e+-]*)$')
    meta = re.compile(r'^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* '
                      r'(counter|gauge|summary|histogram)$')
    lines = body.strip().splitlines()
    assert lines, "empty exposition"
    for ln in lines:
        assert sample.match(ln) or meta.match(ln), f"invalid line: {ln}"
    assert "# TYPE pt_promz_hits_total counter" in lines
    assert "pt_promz_hits_total 2.0" in lines
    assert "# TYPE pt_promz_depth gauge" in lines
    assert 'pt_promz_lat_s{quantile="0.5"}' in body
    assert "pt_promz_lat_s_count 3.0" in lines
    assert "pt_promz_phase_seconds_count 1.0" in lines
    # a declared TYPE precedes every sample of its metric
    typed = {ln.split()[2] for ln in lines if ln.startswith("# TYPE")}
    for ln in lines:
        if not ln.startswith("#"):
            name = ln.split("{")[0].split(" ")[0]
            base_ = re.sub(r"_(total|sum|count)$", "", name)
            assert name in typed or base_ in typed, ln


# -- trace merging ------------------------------------------------------------

def _fake_rank_trace(tmp_path, rank, names):
    evs = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"rank{rank}"}}]
    evs += [{"name": n, "ph": "X", "ts": 1.0 * i, "dur": 0.5,
             "pid": rank, "tid": 1, "args": {}}
            for i, n in enumerate(names)]
    p = tmp_path / f"trace_rank{rank}.json"
    p.write_text(json.dumps({"traceEvents": evs}))
    return str(p)


def test_merge_rank_traces_distinct_lanes(tmp_path):
    _fake_rank_trace(tmp_path, 0, ["a", "b"])
    _fake_rank_trace(tmp_path, 1, ["c"])
    out = merge_rank_traces(str(tmp_path))
    assert out.endswith("trace_merged.json")
    with open(out) as f:
        doc = json.load(f)
    x = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in x} == {0, 1}
    metas = [e for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(metas) == 2
    (tmp_path / "sub").mkdir()
    assert merge_rank_traces(str(tmp_path / "sub")) is None


def test_trace_merge_cli(tmp_path):
    a = _fake_rank_trace(tmp_path, 0, ["x"])
    b = _fake_rank_trace(tmp_path, 1, ["y"])
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         "-o", str(out), a, b],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert {e["pid"] for e in doc["traceEvents"]
            if e["ph"] == "X"} == {0, 1}
    # dir mode
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(tmp_path / "trace_merged.json")


def test_multiprocess_trace_merge_via_spawn(tmp_path):
    """Two spawned workers (the _mh_worker harness: PT_* env contract,
    CPU pinned at module import) each export a rank trace + a stats
    sidecar; the parent merges the traces into one timeline with
    distinct rank lanes and folds the stats exports into one view."""
    import _mh_worker
    import paddle_tpu.distributed as dist

    dist.spawn(_mh_worker.obs_worker, args=(str(tmp_path),), nprocs=2,
               join=True)
    out = merge_rank_traces(str(tmp_path))
    assert out is not None
    with open(out) as f:
        doc = json.load(f)
    x = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in x} == {0, 1}
    names = {e["name"] for e in x}
    assert {"mh/work", "mh/inner"} <= names
    # nested span survives per rank
    for rank in (0, 1):
        lane = {e["name"]: e for e in x if e["pid"] == rank}
        assert lane["mh/inner"]["args"]["parent_id"] == \
            lane["mh/work"]["args"]["span_id"]
    # launch-side stats aggregation from the worker sidecars
    exports = []
    for rank in (0, 1):
        with open(tmp_path / f"stats_{rank}.json") as f:
            exports.append(json.load(f))
    merged = stats.merge(exports)
    assert merged.snapshot()["mh/latency_s.count"] == 2
