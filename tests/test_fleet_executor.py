"""Cross-host pipeline runtime (FleetExecutor analog) end-to-end: three OS
processes, one pipeline stage each, activations/cotangents over the native
P2P transport; per-stage grads + loss checked against a single-process
full-model autodiff oracle.

Reference analog: fleet_executor tests
(test_fleet_executor_multi_devices.py pattern) — here the oracle check is
stronger than the reference's smoke run: exact gradient parity."""

import multiprocessing as mp

import numpy as np
import pytest

from paddle_tpu import native

import _fe_worker


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
@pytest.mark.parametrize("schedule", ["fthenb", "1f1b"])
def test_pipeline_grads_match_oracle(schedule, tmp_path):
    port = 23700 + {"fthenb": 0, "1f1b": 10}[schedule]
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_fe_worker.worker,
                         args=(s, port, schedule, str(tmp_path)))
             for s in range(3)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=180)
    for s, p in enumerate(procs):
        assert p.exitcode == 0, f"stage {s} exited {p.exitcode}"

    ref_loss, ref_grads = _fe_worker.reference_grads()
    for step in range(2):
        for s in range(3):
            z = np.load(tmp_path / f"stage{s}_step{step}.npz")
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    z[f"g_{k}"], ref_grads[s][k], atol=1e-5, rtol=1e-5,
                    err_msg=f"stage {s} grad {k} step {step}")
            if s == 2:
                np.testing.assert_allclose(z["loss"], ref_loss, atol=1e-6)


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
@pytest.mark.parametrize("schedule", ["fthenb", "1f1b"])
def test_interleaved_grads_match_oracle(schedule, tmp_path):
    """V=2 chunks per rank over 2 ranks (4 global stages): exact gradient
    parity with the single-process oracle (≙ interleave correctness,
    pipeline_parallel.py:457)."""
    port = 23800 + {"fthenb": 0, "1f1b": 10}[schedule]
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_fe_worker.worker_vpp,
                         args=(r, port, schedule, str(tmp_path)))
             for r in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=180)
    for r, p in enumerate(procs):
        assert p.exitcode == 0, f"rank {r} exited {p.exitcode}"

    ref_loss, ref_grads = _fe_worker.reference_grads_vpp()
    S = _fe_worker.N_STAGES_V
    for step in range(2):
        for r in range(2):
            z = np.load(tmp_path / f"vpp2_rank{r}_step{step}.npz")
            for v in range(_fe_worker.N_VIRTUAL):
                g = v * S + r  # chunk v on rank r = global stage g
                for k in ("w", "b"):
                    np.testing.assert_allclose(
                        z[f"g{v}_{k}"], ref_grads[g][k], atol=1e-5,
                        rtol=1e-5, err_msg=f"rank {r} chunk {v} {k}")
            if r == 1:
                np.testing.assert_allclose(z["loss"], ref_loss, atol=1e-6)


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_interleaved_bubble_reduction(tmp_path):
    """Measured wall-clock: the interleaved schedule's bubble is smaller.
    Both runs do identical numeric+sleep work per rank; V=1 pays
    (S-1)·T_stage of bubble, V=2 pays (S-1)·T_stage/V
    (≙ the bubble claim of pipeline_parallel.py:457). With sleep-dominated
    stages the expected walls are 10τ vs 9τ (m=4, S=2, τ=0.3; the τ-scale
    margin rides out per-unit jax.vjp re-trace overhead under CI load)."""
    ctx = mp.get_context("spawn")
    walls = {}
    for nv, port in ((1, 23860), (2, 23870)):
        procs = [ctx.Process(target=_fe_worker.worker_vpp,
                             args=(r, port, "1f1b", str(tmp_path), nv, 0.3))
                 for r in range(2)]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=180)
        for r, p in enumerate(procs):
            assert p.exitcode == 0, f"V={nv} rank {r} exited {p.exitcode}"
        walls[nv] = min(
            float(np.load(tmp_path / f"vpp{nv}_rank0_step{s}.npz")["wall"])
            for s in range(2))
    # sanity: the V=1 wall is at least the zero-bubble lower bound m·2τ
    assert walls[1] > 2.3
    # the interleaved run must recover most of the predicted
    # τ·(S-1)·(1-1/V) = 150ms saving; 80ms margin rides out CI jitter
    # and the extra per-unit vjp re-traces the V=2 schedule pays
    assert walls[2] < walls[1] - 0.08, walls
