"""Cross-host pipeline runtime (FleetExecutor analog) end-to-end: three OS
processes, one pipeline stage each, activations/cotangents over the native
P2P transport; per-stage grads + loss checked against a single-process
full-model autodiff oracle.

Reference analog: fleet_executor tests
(test_fleet_executor_multi_devices.py pattern) — here the oracle check is
stronger than the reference's smoke run: exact gradient parity."""

import multiprocessing as mp

import numpy as np
import pytest

from paddle_tpu import native

import _fe_worker


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
@pytest.mark.parametrize("schedule", ["fthenb", "1f1b"])
def test_pipeline_grads_match_oracle(schedule, tmp_path):
    port = 23700 + (hash(schedule) % 50)
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_fe_worker.worker,
                         args=(s, port, schedule, str(tmp_path)))
             for s in range(3)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=180)
    for s, p in enumerate(procs):
        assert p.exitcode == 0, f"stage {s} exited {p.exitcode}"

    ref_loss, ref_grads = _fe_worker.reference_grads()
    for step in range(2):
        for s in range(3):
            z = np.load(tmp_path / f"stage{s}_step{step}.npz")
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    z[f"g_{k}"], ref_grads[s][k], atol=1e-5, rtol=1e-5,
                    err_msg=f"stage {s} grad {k} step {step}")
            if s == 2:
                np.testing.assert_allclose(z["loss"], ref_loss, atol=1e-6)
