"""Sharded scan-over-layers (ISSUE 8): init_train_state(stacked=True) on
a multi-device mesh must (a) match the per-layer-sharded loss trajectory
bit-for-bit at fixed seed, (b) place every stacked leaf by its
layer-leading PARTITION_RULES spec — no tensor-sized replicated block
weights, (c) keep apply_decay_param_fun working via the broadcast layer
mask, and (d) give BERT the same pre-stacked path (no more in-trace
stack_block_weights copy every step)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu import optimizer as optim
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.models import bert, gpt


def _mesh4(**kw):
    """4-device CPU mesh carved out of the 8 virtual devices the test
    harness forces (the sharded-stacked acceptance topology)."""
    kw = kw or {"fsdp": 2, "tp": 2}
    return mesh_lib.init_mesh(devices=jax.devices()[:4], **kw)


def _cfg(**kw):
    d = dict(vocab_size=128, max_seq_len=16, d_model=32, n_layers=3,
             n_heads=2, dtype=jnp.float32)
    d.update(kw)
    return gpt.GPTConfig(**d)


def _run_gpt(model, mesh, stacked, n_steps=3, opt_kw=None):
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01,
                      **(opt_kw or {}))
    params, opt_state = gpt.init_train_state(model, opt, mesh,
                                             stacked=stacked)
    step = gpt.build_train_step(model, opt, mesh)
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 16)), jnp.int32)
    losses = []
    for i in range(n_steps):
        params, opt_state, loss = step(params, opt_state, toks,
                                       jax.random.PRNGKey(i))
        losses.append(float(loss))
    return params, losses


def test_sharded_stacked_matches_per_layer_sharded():
    """Fixed-seed loss-trajectory parity: the stacked fast path under an
    fsdp×tp mesh is the SAME program as the per-layer sharded state."""
    topo = _mesh4()
    model = gpt.GPT(_cfg(), seed=0)
    _, per_layer = _run_gpt(model, topo.mesh, stacked=False)
    _, stacked = _run_gpt(model, topo.mesh, stacked=True)
    np.testing.assert_allclose(stacked, per_layer, rtol=1e-6, atol=1e-6)


def test_sharded_stacked_matches_single_chip_stacked():
    """Mesh vs no-mesh stacked trajectories agree (the scan program is
    numerically the same computation, just partitioned)."""
    model = gpt.GPT(_cfg(), seed=0)
    _, single = _run_gpt(model, None, stacked=True)
    topo = _mesh4()
    _, sharded = _run_gpt(model, topo.mesh, stacked=True)
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-5)


def test_stacked_leaves_carry_fsdp_tp_specs():
    """Every stacked leaf is placed by LAYOUT.stacked(PARTITION_RULES):
    layer axis replicated, trailing dims on fsdp/tp — and no matrix-rank
    stacked leaf is fully replicated (the failure mode the old
    single-chip guard hid)."""
    topo = _mesh4()
    model = gpt.GPT(_cfg(), seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = gpt.init_train_state(model, opt, topo.mesh,
                                             stacked=True)
    st = params["_stacked_blocks"]
    id2name = {id(v): n for n, v in model.blocks[0].named_parameters()}
    tleaves = jax.tree_util.tree_leaves(model.blocks[0])
    sleaves = jax.tree_util.tree_leaves(st)
    by_name = {id2name[id(t)]: s for t, s in zip(tleaves, sleaves)}
    assert by_name["wqkv"].sharding.spec == P(None, "fsdp", "tp")
    assert by_name["wo"].sharding.spec == P(None, "tp", "fsdp")
    assert by_name["wup"].sharding.spec == P(None, "fsdp", "tp")
    assert by_name["wdown"].sharding.spec == P(None, "tp", "fsdp")
    for name, leaf in by_name.items():
        assert len(leaf.sharding.device_set) == 4, name
        if leaf.ndim >= 3:  # (L, d_in, d_out) weights must actually shard
            shard = leaf.sharding.shard_shape(leaf.shape)
            assert shard != leaf.shape, \
                f"{name} fully replicated: {leaf.shape}"

    # the compiled step preserves the layout: after one donated step the
    # new stacked leaves carry the same specs (the scanned program
    # sharded rather than replicating-and-resharding)
    step = gpt.build_train_step(model, opt, topo.mesh)
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 16)), jnp.int32)
    new_params, _, _ = step(params, opt_state, toks, jax.random.PRNGKey(0))
    new_leaves = jax.tree_util.tree_leaves(new_params["_stacked_blocks"])
    for old, new in zip(sleaves, new_leaves):
        assert new.sharding.spec == old.sharding.spec


def test_stacked_jaxpr_has_no_replicated_block_constraint():
    """The traced loss re-asserts layer-leading fsdp/tp constraints on
    the stacked weights: the jaxpr of the step must contain sharding
    constraints naming the stacked specs (proof the scan body sees them,
    not just the input placement)."""
    topo = _mesh4()
    model = gpt.GPT(_cfg(), seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = gpt.init_train_state(model, opt, topo.mesh,
                                             stacked=True)
    step = gpt.build_train_step(model, opt, topo.mesh)
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 16)), jnp.int32)
    jaxpr = str(jax.make_jaxpr(
        lambda p, s, t, r: step.__wrapped__(p, s, t, r))(
            params, opt_state, toks, jax.random.PRNGKey(0)))
    assert "sharding_constraint" in jaxpr
    assert "'fsdp', 'tp'" in jaxpr or "\"fsdp\", \"tp\"" in jaxpr


def test_stacked_decay_mask_matches_per_layer():
    """apply_decay_param_fun under the stacked layout (used to raise):
    the mask resolved against the block template and broadcast along the
    layer axis reproduces the per-layer trajectory exactly — including a
    LAYER-DEPENDENT decay fn, which exercises the per-row mask."""
    def no_bias_no_ln(name):
        leaf = name.split(".")[-1]
        return not (leaf.startswith("b") or "ln" in leaf or "_bias" in leaf)

    def layer_dependent(name):
        # decay only even layers' weights (plus all non-block params)
        import re
        m = re.search(r"blocks\.item_(\d+)\.", name)
        return no_bias_no_ln(name) and (m is None or int(m.group(1)) % 2
                                        == 0)

    for fn in (no_bias_no_ln, layer_dependent):
        model = gpt.GPT(_cfg(), seed=0)
        _, per_layer = _run_gpt(model, None, stacked=False,
                                opt_kw={"apply_decay_param_fun": fn})
        _, stacked = _run_gpt(model, None, stacked=True,
                              opt_kw={"apply_decay_param_fun": fn})
        np.testing.assert_allclose(stacked, per_layer, rtol=1e-6,
                                   atol=1e-6)
        # and the mask must actually matter: decaying everything shifts
        # the trajectory (visibly from step 2, once decayed params bite)
        _, all_decay = _run_gpt(model, None, stacked=True,
                                opt_kw={"apply_decay_param_fun":
                                        lambda n: True})
        assert stacked[-1] != all_decay[-1]


def test_stacked_decay_mask_on_mesh():
    fn = lambda n: not n.split(".")[-1].startswith("b")
    topo = _mesh4()
    model = gpt.GPT(_cfg(), seed=0)
    _, per_layer = _run_gpt(model, topo.mesh, stacked=False,
                            opt_kw={"apply_decay_param_fun": fn})
    _, stacked = _run_gpt(model, topo.mesh, stacked=True,
                          opt_kw={"apply_decay_param_fun": fn})
    np.testing.assert_allclose(stacked, per_layer, rtol=1e-6, atol=1e-6)


def test_stacked_state_still_decodes():
    """merge_params on the sharded stacked state rebinds per-layer views:
    generate() must see the TRAINED weights, not init-time ones."""
    topo = _mesh4()
    model = gpt.GPT(_cfg(), seed=0)
    params, _ = _run_gpt(model, topo.mesh, stacked=True, n_steps=1)
    merged = model.merge_params(params)
    toks = jnp.asarray(
        np.random.RandomState(3).randint(0, 128, (4, 4)), jnp.int32)
    out = gpt.generate(merged, toks, max_new_tokens=4, max_len=16)
    assert out.shape == (4, 8)


# -- BERT satellite ----------------------------------------------------------

def _bert_batch(rs, cfg, b=4, s=32):
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    types = jnp.zeros_like(toks)
    mask = jnp.ones_like(toks)
    labels = jnp.asarray(
        np.where(rs.rand(b, s) < 0.15, np.asarray(toks), -100), jnp.int32)
    nsp = jnp.asarray(rs.randint(0, 2, (b,)), jnp.int32)
    return toks, types, mask, labels, nsp


def _run_bert(model, mesh, stacked, n_steps=3):
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
    params, opt_state = bert.init_train_state(model, opt, mesh,
                                              stacked=stacked)
    step = bert.build_pretrain_step(model, opt, mesh)
    batch = _bert_batch(np.random.RandomState(0), model.cfg)
    losses = []
    for i in range(n_steps):
        params, opt_state, loss = step(params, opt_state, *batch,
                                       jax.random.PRNGKey(i))
        losses.append(float(loss))
    return params, losses


def test_bert_prestacked_matches_plain():
    model = bert.BertForPretraining(bert.bert_tiny(n_layers=3), seed=0)
    _, plain = _run_bert(model, None, stacked=False)
    params, stacked = _run_bert(model, None, stacked=True)
    assert "bert._stacked_layers" in params
    assert not any(k.startswith("bert.layers.") for k in params)
    np.testing.assert_allclose(stacked, plain, rtol=1e-6, atol=1e-6)


def test_bert_prestacked_sharded():
    topo = _mesh4()
    model = bert.BertForPretraining(bert.bert_tiny(n_layers=3), seed=0)
    _, per_layer = _run_bert(model, topo.mesh, stacked=False)
    params, stacked = _run_bert(model, topo.mesh, stacked=True)
    np.testing.assert_allclose(stacked, per_layer, rtol=1e-6, atol=1e-6)
    # stacked encoder weights provably sharded
    for leaf in jax.tree_util.tree_leaves(params["bert._stacked_layers"]):
        assert len(leaf.sharding.device_set) == 4
        if leaf.ndim >= 3:
            assert leaf.sharding.shard_shape(leaf.shape) != leaf.shape


def test_bert_prestacked_state_dict_rebinds():
    """merge_params on the stacked BERT state rebinds layer views so
    state_dict exports the trained weights."""
    model = bert.BertForPretraining(bert.bert_tiny(n_layers=2), seed=0)
    params, _ = _run_bert(model, None, stacked=True, n_steps=1)
    merged = model.merge_params(params)
    got = np.asarray(merged.bert.layers[0].wqkv)
    want = np.asarray(
        jax.tree_util.tree_map(lambda x: x[0],
                               params["bert._stacked_layers"]).wqkv)
    np.testing.assert_array_equal(got, want)
    # and it differs from the init weights (training moved them)
    init = np.asarray(
        bert.BertForPretraining(bert.bert_tiny(n_layers=2),
                                seed=0).bert.layers[0].wqkv)
    assert not np.array_equal(got, init)


def test_moe_stack_still_refuses():
    moe_cfg = gpt.GPTConfig(vocab_size=64, max_seq_len=8, d_model=16,
                            n_layers=2, n_heads=2, dtype=jnp.float32,
                            moe_experts=2)
    with pytest.raises(ValueError, match="dense"):
        gpt.init_train_state(gpt.GPT(moe_cfg, seed=0), optim.AdamW(),
                             stacked=True)
