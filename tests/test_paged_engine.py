"""Paged continuous-batching engine: serving over a shared page pool.

The invariants: greedy output BIT-IDENTICAL to gpt.generate whatever the
page/chunk geometry; pages allocate on demand, free at retirement, and
get reused; a too-small pool fails loudly instead of wedging."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.models import gpt


def _model(max_seq=512, heads=4):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=max_seq, d_model=32,
                        n_layers=2, n_heads=heads, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _assert_pool_drained(eng, n_pages):
    """After every request retires, each pool page is either on the
    allocator free list or warm in the prefix cache at refcount ZERO
    (reclaimable) — never still mapped into a slot."""
    cached = eng._prefix.cached_pages if eng._prefix is not None else 0
    shared = eng._prefix.shared_pages if eng._prefix is not None else 0
    assert eng.free_pages + cached == n_pages
    assert shared == 0


def _reference(model, prompt, n_new, eos=None):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new, eos_id=eos)
    got = list(np.asarray(out)[0, len(prompt):])
    if eos is not None and eos in got:
        got = got[:got.index(eos) + 1]
    return got


def test_paged_parity_with_generate_mixed_lengths():
    model = _model()
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 170, 23)]
    eng = PagedDecodeEngine(model, n_pages=12, max_slots=2,
                            steps_per_call=4)
    reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
    eng.step()
    eng.run()
    for req, p in zip(reqs, prompts):
        assert req.tokens == _reference(model, p, 9), len(p)
    # everything retired -> every page free or warm in the prefix
    # cache at refcount zero (nothing still mapped)
    _assert_pool_drained(eng, 12)


def test_paged_pages_allocated_on_demand_and_reused():
    model = _model()
    rs = np.random.RandomState(1)
    eng = PagedDecodeEngine(model, n_pages=4, max_slots=1,
                            steps_per_call=8)
    # 120-token prompt + 20 new tokens: 1 page -> grows to 2
    p1 = list(rs.randint(0, 96, size=120))
    r1 = eng.submit(p1, max_new_tokens=20)
    eng.run()
    assert r1.tokens == _reference(model, p1, 20)
    assert eng.free_pages == 4
    # the next sequence reuses the freed pages
    p2 = list(rs.randint(0, 96, size=100))
    r2 = eng.submit(p2, max_new_tokens=5)
    eng.run()
    assert r2.tokens == _reference(model, p2, 5)
    assert eng.free_pages == 4


def test_paged_eos_and_gqa():
    model = _model(heads=4)
    # GQA variant
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                        n_layers=2, n_heads=4, n_kv_heads=2,
                        dtype=jnp.float32)
    gqa = gpt.GPT(cfg, seed=0)
    prompt = [3, 4] * 10
    ref = _reference(gqa, prompt, 12)
    eos = ref[3]
    want = _reference(gqa, prompt, 12, eos=eos)
    eng = PagedDecodeEngine(gqa, n_pages=6, max_slots=1,
                            steps_per_call=4)
    r = eng.submit(prompt, max_new_tokens=12, eos_id=eos)
    eng.run()
    assert r.done and r.tokens == want


def test_paged_pool_too_small_fails_loudly():
    model = _model()
    eng = PagedDecodeEngine(model, n_pages=1, max_slots=2,
                            page_size=128)
    eng.submit(list(range(90)) * 2, max_new_tokens=4)  # 180 tok: 2 pages
    with pytest.raises(MemoryError):
        eng.run()


def test_paged_admission_waits_for_pages():
    """Admission blocks on pool pressure and resumes after retirement
    instead of failing, as long as something is decoding."""
    model = _model()
    rs = np.random.RandomState(2)
    eng = PagedDecodeEngine(model, n_pages=3, max_slots=2,
                            steps_per_call=4)
    p1 = list(rs.randint(0, 96, size=200))   # 2 pages
    p2 = list(rs.randint(0, 96, size=120))   # needs 1+ page
    r1 = eng.submit(p1, max_new_tokens=6)
    r2 = eng.submit(p2, max_new_tokens=6)
    eng.run()
    assert r1.tokens == _reference(model, p1, 6)
    assert r2.tokens == _reference(model, p2, 6)
    _assert_pool_drained(eng, 3)


def test_idle_slot_never_corrupts_live_pages():
    """Code-review regression (confirmed by repro): an idle slot's
    padded page table points at pool page 0; its per-step write must go
    to the scratch page, not clobber the live sequence that owns page 0.
    One request in a 2-slot engine (slot 1 idle the whole run) must
    match gpt.generate exactly."""
    model = _model()
    rs = np.random.RandomState(9)
    prompt = list(rs.randint(0, 96, size=140))   # owns pages 0..1
    eng = PagedDecodeEngine(model, n_pages=6, max_slots=2,
                            steps_per_call=4)
    r = eng.submit(prompt, max_new_tokens=16)
    eng.run()
    assert r.tokens == _reference(model, prompt, 16)


def test_page_size_must_divide_buckets():
    model = _model()
    with pytest.raises(ValueError):
        PagedDecodeEngine(model, n_pages=4, max_slots=1, page_size=384)


@pytest.mark.parametrize("depth", [2, 3])
def test_paged_pipelined_depths_bit_identical(depth):
    """ISSUE 4: the pipelined paged engine (lag-one harvest, one packed
    transfer per dispatch) serves byte-identical streams to depth=1,
    with every page back in the pool at drain."""
    model = _model()
    rs = np.random.RandomState(6)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 170, 23)]

    def run(d):
        eng = PagedDecodeEngine(model, n_pages=12, max_slots=2,
                                steps_per_call=4, inflight=d)
        reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
        eng.step()
        eng.run()
        _assert_pool_drained(eng, 12)
        assert all(r.done and not r.failed for r in reqs)
        return [list(r.tokens) for r in reqs]

    base = run(1)
    for got, p in zip(base, prompts):
        assert got == _reference(model, p, 9), len(p)
    assert run(depth) == base


def test_paged_warmup_pretraces():
    model = _model()
    eng = PagedDecodeEngine(model, n_pages=8, max_slots=2,
                            steps_per_call=2, buckets=(16, 32),
                            warmup=True)
    assert eng._prefill_fn._cache_size() == 2
    assert eng._multi_fn._cache_size() == 1
    rs = np.random.RandomState(7)
    p = list(rs.randint(0, 96, size=20))
    r = eng.submit(p, max_new_tokens=6)
    eng.run()
    assert r.tokens == _reference(model, p, 6)
    assert eng._prefill_fn._cache_size() == 2, "serving recompiled"
    assert eng._multi_fn._cache_size() == 1, "serving recompiled"


def test_fused_vs_scatter_bit_identical_and_no_scatter_dispatch():
    """ISSUE 6 tentpole: the fused append+attend engine (default) must
    serve byte-identical streams to the PT_PAGED_FUSED=0 scatter
    formulation it replaces — and the per-token scatter
    (`_write_token_rows`) must be GONE from the fused dispatch path
    (the CPU-verifiable proxy for the removed pool traffic)."""
    model = _model()
    rs = np.random.RandomState(20)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (7, 170, 40)]

    def run(fused):
        eng = PagedDecodeEngine(model, n_pages=12, max_slots=2,
                                steps_per_call=4, fused=fused)
        assert eng.fused is fused
        if fused:
            def boom(*a, **k):
                raise AssertionError(
                    "fused dispatch called the per-token scatter")
            eng._write_token_rows = boom
        reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
        eng.run()
        assert all(r.done and not r.failed for r in reqs)
        return [list(r.tokens) for r in reqs]

    want = run(False)
    for got, p in zip(want, prompts):
        assert got == _reference(model, p, 9), len(p)
    assert run(True) == want


def test_warm_prefix_hit_prefills_only_suffix():
    """Acceptance: a warm shared-prefix submit must route through the
    SUFFIX prefill only (the full-prompt prefill is never dispatched)
    and account every cached token in serve/prefix_hit_tokens."""
    from paddle_tpu import stats

    model = _model()
    rs = np.random.RandomState(21)
    sys_prompt = list(rs.randint(0, 96, size=290))   # 2 full pages + 34
    tail_a = list(rs.randint(0, 96, size=11))
    tail_b = list(rs.randint(0, 96, size=17))
    eng = PagedDecodeEngine(model, n_pages=16, max_slots=1,
                            steps_per_call=4)
    calls = {"full": 0, "sfx": 0}
    full_fn, sfx_fn = eng._prefill_fn, eng._prefill_sfx_fn
    eng._prefill_fn = (lambda *a: (calls.__setitem__(
        "full", calls["full"] + 1), full_fn(*a))[1])
    eng._prefill_sfx_fn = (lambda *a: (calls.__setitem__(
        "sfx", calls["sfx"] + 1), sfx_fn(*a))[1])

    r1 = eng.submit(sys_prompt + tail_a, max_new_tokens=8)
    eng.run()
    assert calls == {"full": 1, "sfx": 0}      # cold: full prefill
    h0 = stats.get("serve/prefix_hit_tokens")

    r2 = eng.submit(sys_prompt + tail_b, max_new_tokens=8)
    eng.run()
    assert calls == {"full": 1, "sfx": 1}      # warm: suffix ONLY
    # both full pages (256 tokens) served from cache
    assert stats.get("serve/prefix_hit_tokens") - h0 == 256
    assert r1.tokens == _reference(model, sys_prompt + tail_a, 8)
    assert r2.tokens == _reference(model, sys_prompt + tail_b, 8)


def test_shared_prefix_pages_read_only_and_divergence():
    """Refcount/COW correctness: the cached prefix pages a second
    request maps must stay BIT-IDENTICAL to the cold prefill that wrote
    them (read-only mapping — the sharer's suffix and decode appends
    land in private pages), while the streams diverge after the shared
    point exactly as the dense reference does."""
    model = _model()
    rs = np.random.RandomState(22)
    shared = list(rs.randint(0, 96, size=256))       # exactly 2 pages
    pa = shared + list(rs.randint(0, 96, size=30))
    pb = shared + list(rs.randint(0, 96, size=45))
    eng = PagedDecodeEngine(model, n_pages=16, max_slots=1,
                            steps_per_call=4)
    ra = eng.submit(pa, max_new_tokens=8)
    eng.run()
    pids = [eng._prefix._nodes[d] for d in eng._prefix.chain(shared)]
    assert len(pids) == 2
    L, P = eng.cfg.n_layers, eng.P
    ids = np.add.outer(np.arange(L) * P, pids).ravel()
    kp_before = np.asarray(eng.kp[ids])
    vp_before = np.asarray(eng.vp[ids])

    rb = eng.submit(pb, max_new_tokens=8)
    eng.run()
    np.testing.assert_array_equal(np.asarray(eng.kp[ids]), kp_before)
    np.testing.assert_array_equal(np.asarray(eng.vp[ids]), vp_before)
    assert ra.tokens == _reference(model, pa, 8)
    assert rb.tokens == _reference(model, pb, 8)


def test_eviction_returns_only_refcount_zero_pages():
    """Retirement of ONE sharer must not free (or make reclaimable) the
    prefix pages the other sharer still maps; reclaim frees only
    refcount-zero pages, and only under explicit pressure."""
    model = _model()
    rs = np.random.RandomState(23)
    shared = list(rs.randint(0, 96, size=256))
    pa = shared + [1, 2, 3]
    pb = shared + [4, 5]
    eng = PagedDecodeEngine(model, n_pages=16, max_slots=2,
                            steps_per_call=2)
    ra = eng.submit(pa, max_new_tokens=24)   # long: retires last
    rb = eng.submit(pb, max_new_tokens=2)    # short: retires first
    while not rb.done:
        eng.step()
    eng.drain()
    pids = [eng._prefix._nodes[d] for d in eng._prefix.chain(shared)]
    assert not ra.done
    # b retired: the shared pages are still mapped by a (refcount 1) —
    # neither free nor reclaimable
    assert eng._prefix._refs[pids[0]] == 1
    assert eng._prefix.reclaimable_pages == 0
    assert all(p not in eng._alloc._free for p in pids)
    assert eng._prefix.reclaim(8) == 0       # nothing at refcount zero

    eng.run()
    assert ra.done and ra.tokens == _reference(model, pa, 24)
    # a retired too: refcount zero, reclaimable, but still warm (NOT on
    # the allocator free list) until reclaim is asked for them
    assert eng._prefix._refs[pids[0]] == 0
    assert all(p not in eng._alloc._free for p in pids)
    free0 = eng.free_pages
    assert eng._prefix.reclaim(1) == 1       # LRU-oldest only
    assert eng.free_pages == free0 + 1


def test_stale_invalidate_keeps_reregistered_chain():
    """A dead page's SECOND invalidation (a late sharer failing after
    the poisoned prompt was already re-registered with healthy pages)
    must not de-canonicalize the new copy's trie node, and a later
    reclaim of the healthy page must not crash on the missing node."""
    from paddle_tpu.inference.prefix_cache import PrefixCache
    from paddle_tpu.ops.pallas.paged_attention import PageAllocator

    alloc = PageAllocator(8, 128)
    pc = PrefixCache(alloc, 128)
    toks = list(range(128))
    tab = alloc.reserve([], 128)
    pc.register(toks, tab)             # slot A registers: refs=1
    old = tab[0]
    pc.ref(old)                        # slot B maps it too: refs=2
    assert pc.invalidate(old) is None  # A nan-fails: node gone, dead
    assert pc.lookup(toks) == []       # no longer canonical
    assert pc.unref(old) is None       # A releases: refs=1 (B holds)
    tab2 = alloc.reserve([], 128)
    pc.register(toks, tab2)            # healthy re-registration
    new = tab2[0]
    assert pc.invalidate(old) is None  # B fails later: STALE pid
    got = pc.lookup(toks)
    assert got == [new], "stale invalidate de-canonicalized the chain"
    pc.unref(new)                      # drop lookup's ref
    pc.unref(new)                      # registrant retires: warm LRU
    assert pc.unref(old) == old        # B releases: dead page freed
    assert old in alloc._free
    assert pc.reclaim(8) == 1          # healthy page reclaims cleanly
    assert new in alloc._free
    assert pc.lookup(toks) == []


def test_poisoned_shared_page_fails_every_sharer_loudly():
    """Blast-radius probe for prefix sharing: one poisoned shared page
    must fail EVERY request that has it mapped via the non-finite-logit
    guard (failed=True, never silent corruption), while a request that
    shares nothing decodes normally. The poison must NOT outlive its
    sharers: the eviction drops the prefix's trie nodes and scrubs the
    freed pages, so the next submit of the same (popular) prompt
    prefills cold into clean pages and succeeds — one bad page is a
    loud transient, not a permanent DoS of that prompt."""
    from paddle_tpu import stats
    from paddle_tpu.testing import faults

    model = _model()
    rs = np.random.RandomState(24)
    shared = list(rs.randint(0, 96, size=256))
    cold = list(rs.randint(0, 96, size=40))
    eng = PagedDecodeEngine(model, n_pages=24, max_slots=2,
                            steps_per_call=2)
    r0 = eng.submit(shared + [7], max_new_tokens=4)
    eng.run()                                # establishes the cache
    assert not r0.failed

    with faults.inject("paged.shared_page", "nan", n=64):
        # two slots: rb and rc BOTH map the poisoned shared pages
        # before either harvest detects the damage
        rb = eng.submit(shared + [8, 9], max_new_tokens=6)
        rc = eng.submit(shared + [10], max_new_tokens=6)
        rd = eng.submit(cold, max_new_tokens=6)
        eng.run()
    assert rb.failed and rc.failed           # every sharer fails LOUDLY
    assert rb.error and "non-finite" in rb.error
    assert rc.error and "non-finite" in rc.error
    assert not rd.failed                     # non-sharer unaffected
    assert rd.tokens == _reference(model, cold, 6)

    # self-heal: the fault is gone, the poisoned trie nodes are
    # invalidated and their pages scrubbed — the SAME prompt recovers
    # after one cold prefill (no hit) ...
    h0 = stats.get("serve/prefix_hit_tokens")
    re_ = eng.submit(shared + [11], max_new_tokens=4)
    eng.run()
    assert not re_.failed
    assert re_.tokens == _reference(model, shared + [11], 4)
    assert stats.get("serve/prefix_hit_tokens") == h0   # cold re-prefill
    # ... and its healthy copy is canonical again: the next sharer hits
    rf = eng.submit(shared + [12], max_new_tokens=4)
    eng.run()
    assert not rf.failed
    assert rf.tokens == _reference(model, shared + [12], 4)
    assert stats.get("serve/prefix_hit_tokens") - h0 == 256


def test_bitflip_on_shared_page_corrupts_visibly():
    """The bitflip payload variant of the blast-radius probe: a single
    flipped bit in a shared K page must visibly corrupt the sharer's
    stream (diverging from the clean reference) — shared-prefix KV is
    load-bearing state, not a soft hint."""
    from paddle_tpu.testing import faults

    model = _model()
    rs = np.random.RandomState(25)
    shared = list(rs.randint(0, 96, size=256))
    eng = PagedDecodeEngine(model, n_pages=16, max_slots=1,
                            steps_per_call=2)
    eng.submit(shared + [7], max_new_tokens=4)
    eng.run()
    pids = [eng._prefix._nodes[d] for d in eng._prefix.chain(shared)]
    before = np.asarray(eng.kp[pids[0]])

    # flip the sign/exponent bit of a mid-page element on every layer's
    # view of the first shared page
    with faults.inject("paged.shared_page", "bitflip", offset=2048,
                       bit=7):
        eng.submit(shared + [8], max_new_tokens=4)
        eng.run()
    after = np.asarray(eng.kp[pids[0]])
    assert (before != after).any(), "bitflip never landed in the pool"


def test_prefix_cache_off_restores_free_everything():
    """PT_PAGED_PREFIX=0 restores the pre-ISSUE-6 lifecycle: no trie,
    retirement frees every page straight back to the allocator."""
    model = _model()
    rs = np.random.RandomState(26)
    p = list(rs.randint(0, 96, size=200))
    eng = PagedDecodeEngine(model, n_pages=6, max_slots=1,
                            steps_per_call=4, prefix=False)
    assert eng._prefix is None
    r1 = eng.submit(p, max_new_tokens=6)
    eng.run()
    assert eng.free_pages == 6
    r2 = eng.submit(p, max_new_tokens=6)
    eng.run()
    assert r1.tokens == r2.tokens == _reference(model, p, 6)
    assert eng.free_pages == 6


def test_pool_pressure_reclaims_warm_prefix_pages():
    """Admission under pool pressure reclaims LRU refcount-zero prefix
    pages instead of failing: a pool exactly big enough for one
    resident request must still serve a second, different prompt after
    the first retires (its warm pages get reclaimed)."""
    model = _model()
    rs = np.random.RandomState(27)
    pa = list(rs.randint(0, 96, size=256))
    pb = list(rs.randint(0, 96, size=256))
    eng = PagedDecodeEngine(model, n_pages=3, max_slots=1,
                            steps_per_call=2)
    ra = eng.submit(pa, max_new_tokens=4)
    eng.run()
    assert eng._prefix.cached_pages == 2     # pa's pages warm
    rb = eng.submit(pb, max_new_tokens=4)    # needs reclaim to fit
    eng.run()
    assert ra.tokens == _reference(model, pa, 4)
    assert rb.tokens == _reference(model, pb, 4)
    # and a warm resubmit of pb still hits whatever stayed cached
    r2 = eng.submit(pb, max_new_tokens=4)
    eng.run()
    assert r2.tokens == rb.tokens


def test_paged_share_weights_with_decode_engine_donor():
    """The bench path: a PagedDecodeEngine built from a DecodeEngine's
    stacked weights (no model, no duplicate copy) serves identically."""
    from paddle_tpu.inference.decode_engine import DecodeEngine

    model = _model()
    rs = np.random.RandomState(4)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (9, 130)]
    donor = DecodeEngine(model, max_slots=2, max_len=192)
    r_ref = [donor.submit(p, max_new_tokens=8) for p in prompts]
    donor.run()

    eng = PagedDecodeEngine(None, n_pages=8, max_slots=2,
                            steps_per_call=3, share_weights_with=donor)
    assert eng._stacked is donor._stacked
    r = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run()
    for a, b in zip(r_ref, r):
        assert a.tokens == b.tokens
