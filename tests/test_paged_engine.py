"""Paged continuous-batching engine: serving over a shared page pool.

The invariants: greedy output BIT-IDENTICAL to gpt.generate whatever the
page/chunk geometry; pages allocate on demand, free at retirement, and
get reused; a too-small pool fails loudly instead of wedging."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.models import gpt


def _model(max_seq=512, heads=4):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=max_seq, d_model=32,
                        n_layers=2, n_heads=heads, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _reference(model, prompt, n_new, eos=None):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new, eos_id=eos)
    got = list(np.asarray(out)[0, len(prompt):])
    if eos is not None and eos in got:
        got = got[:got.index(eos) + 1]
    return got


def test_paged_parity_with_generate_mixed_lengths():
    model = _model()
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 170, 23)]
    eng = PagedDecodeEngine(model, n_pages=12, max_slots=2,
                            steps_per_call=4)
    reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
    eng.step()
    eng.run()
    for req, p in zip(reqs, prompts):
        assert req.tokens == _reference(model, p, 9), len(p)
    # everything retired -> every page back in the pool
    assert eng.free_pages == 12


def test_paged_pages_allocated_on_demand_and_reused():
    model = _model()
    rs = np.random.RandomState(1)
    eng = PagedDecodeEngine(model, n_pages=4, max_slots=1,
                            steps_per_call=8)
    # 120-token prompt + 20 new tokens: 1 page -> grows to 2
    p1 = list(rs.randint(0, 96, size=120))
    r1 = eng.submit(p1, max_new_tokens=20)
    eng.run()
    assert r1.tokens == _reference(model, p1, 20)
    assert eng.free_pages == 4
    # the next sequence reuses the freed pages
    p2 = list(rs.randint(0, 96, size=100))
    r2 = eng.submit(p2, max_new_tokens=5)
    eng.run()
    assert r2.tokens == _reference(model, p2, 5)
    assert eng.free_pages == 4


def test_paged_eos_and_gqa():
    model = _model(heads=4)
    # GQA variant
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                        n_layers=2, n_heads=4, n_kv_heads=2,
                        dtype=jnp.float32)
    gqa = gpt.GPT(cfg, seed=0)
    prompt = [3, 4] * 10
    ref = _reference(gqa, prompt, 12)
    eos = ref[3]
    want = _reference(gqa, prompt, 12, eos=eos)
    eng = PagedDecodeEngine(gqa, n_pages=6, max_slots=1,
                            steps_per_call=4)
    r = eng.submit(prompt, max_new_tokens=12, eos_id=eos)
    eng.run()
    assert r.done and r.tokens == want


def test_paged_pool_too_small_fails_loudly():
    model = _model()
    eng = PagedDecodeEngine(model, n_pages=1, max_slots=2,
                            page_size=128)
    eng.submit(list(range(90)) * 2, max_new_tokens=4)  # 180 tok: 2 pages
    with pytest.raises(MemoryError):
        eng.run()


def test_paged_admission_waits_for_pages():
    """Admission blocks on pool pressure and resumes after retirement
    instead of failing, as long as something is decoding."""
    model = _model()
    rs = np.random.RandomState(2)
    eng = PagedDecodeEngine(model, n_pages=3, max_slots=2,
                            steps_per_call=4)
    p1 = list(rs.randint(0, 96, size=200))   # 2 pages
    p2 = list(rs.randint(0, 96, size=120))   # needs 1+ page
    r1 = eng.submit(p1, max_new_tokens=6)
    r2 = eng.submit(p2, max_new_tokens=6)
    eng.run()
    assert r1.tokens == _reference(model, p1, 6)
    assert r2.tokens == _reference(model, p2, 6)
    assert eng.free_pages == 3


def test_idle_slot_never_corrupts_live_pages():
    """Code-review regression (confirmed by repro): an idle slot's
    padded page table points at pool page 0; its per-step write must go
    to the scratch page, not clobber the live sequence that owns page 0.
    One request in a 2-slot engine (slot 1 idle the whole run) must
    match gpt.generate exactly."""
    model = _model()
    rs = np.random.RandomState(9)
    prompt = list(rs.randint(0, 96, size=140))   # owns pages 0..1
    eng = PagedDecodeEngine(model, n_pages=6, max_slots=2,
                            steps_per_call=4)
    r = eng.submit(prompt, max_new_tokens=16)
    eng.run()
    assert r.tokens == _reference(model, prompt, 16)


def test_page_size_must_divide_buckets():
    model = _model()
    with pytest.raises(ValueError):
        PagedDecodeEngine(model, n_pages=4, max_slots=1, page_size=384)


@pytest.mark.parametrize("depth", [2, 3])
def test_paged_pipelined_depths_bit_identical(depth):
    """ISSUE 4: the pipelined paged engine (lag-one harvest, one packed
    transfer per dispatch) serves byte-identical streams to depth=1,
    with every page back in the pool at drain."""
    model = _model()
    rs = np.random.RandomState(6)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 170, 23)]

    def run(d):
        eng = PagedDecodeEngine(model, n_pages=12, max_slots=2,
                                steps_per_call=4, inflight=d)
        reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
        eng.step()
        eng.run()
        assert eng.free_pages == 12
        assert all(r.done and not r.failed for r in reqs)
        return [list(r.tokens) for r in reqs]

    base = run(1)
    for got, p in zip(base, prompts):
        assert got == _reference(model, p, 9), len(p)
    assert run(depth) == base


def test_paged_warmup_pretraces():
    model = _model()
    eng = PagedDecodeEngine(model, n_pages=8, max_slots=2,
                            steps_per_call=2, buckets=(16, 32),
                            warmup=True)
    assert eng._prefill_fn._cache_size() == 2
    assert eng._multi_fn._cache_size() == 1
    rs = np.random.RandomState(7)
    p = list(rs.randint(0, 96, size=20))
    r = eng.submit(p, max_new_tokens=6)
    eng.run()
    assert r.tokens == _reference(model, p, 6)
    assert eng._prefill_fn._cache_size() == 2, "serving recompiled"
    assert eng._multi_fn._cache_size() == 1, "serving recompiled"


def test_paged_share_weights_with_decode_engine_donor():
    """The bench path: a PagedDecodeEngine built from a DecodeEngine's
    stacked weights (no model, no duplicate copy) serves identically."""
    from paddle_tpu.inference.decode_engine import DecodeEngine

    model = _model()
    rs = np.random.RandomState(4)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (9, 130)]
    donor = DecodeEngine(model, max_slots=2, max_len=192)
    r_ref = [donor.submit(p, max_new_tokens=8) for p in prompts]
    donor.run()

    eng = PagedDecodeEngine(None, n_pages=8, max_slots=2,
                            steps_per_call=3, share_weights_with=donor)
    assert eng._stacked is donor._stacked
    r = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run()
    for a, b in zip(r_ref, r):
        assert a.tokens == b.tokens
