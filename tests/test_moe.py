"""MoE layer tests (≙ the reference's
python/paddle/fluid/tests/unittests/collective/test_moe_api style checks +
numpy-oracle gating semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.incubate.moe import MoELayer, top_k_gating


def test_gating_semantics():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.normal(size=(32, 4)), jnp.float32)
    cap = 16
    combine, dispatch, aux = top_k_gating(logits, k=2, capacity=cap)
    c = np.asarray(combine)
    d = np.asarray(dispatch)
    # each token occupies at most k slots, each slot at most once
    assert d.sum(axis=(1, 2)).max() <= 2
    # per (expert, slot) at most one token
    assert d.sum(axis=0).max() <= 1
    # capacity respected
    assert d.sum(axis=(0, 2)).max() <= cap
    # kept tokens' combine weights sum to ~1 (renormalized top-2)
    tok_w = c.sum(axis=(1, 2))
    kept = d.sum(axis=(1, 2)) == 2
    np.testing.assert_allclose(tok_w[kept], 1.0, atol=1e-5)
    assert float(aux) > 0


def test_switch_gate_keeps_raw_prob():
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.normal(size=(16, 4)), jnp.float32)
    probs = np.asarray(jax.nn.softmax(logits, -1))
    combine, dispatch, _ = top_k_gating(logits, k=1, capacity=16)
    c = np.asarray(combine)
    top1 = probs.argmax(-1)
    for t in range(16):
        np.testing.assert_allclose(c[t].sum(), probs[t, top1[t]], atol=1e-5)


def test_single_expert_equals_dense_ffn():
    """num_experts=1 with ample capacity reduces to a plain FFN."""
    moe = MoELayer(8, 16, num_experts=1, gate="switch",
                   capacity_factor=4.0, jitter_eps=0.0, seed=0)
    x = jnp.asarray(np.random.RandomState(2).normal(size=(2, 5, 8)),
                    jnp.float32)
    y, aux = moe(x)
    ref = jax.nn.gelu(x @ moe.moe_w1[0] + moe.moe_b1[0]) @ moe.moe_w2[0] \
        + moe.moe_b2[0]
    # switch with E=1: gate prob is 1.0 (softmax over one logit)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)


def test_forward_backward_finite():
    moe = MoELayer(8, 16, num_experts=4, gate="gshard", seed=1)
    x = jnp.asarray(np.random.RandomState(3).normal(size=(4, 8, 8)),
                    jnp.float32)

    def loss(params, x):
        m = moe.merge_params(params)
        y, aux = m(x)
        return jnp.mean(y ** 2) + 0.01 * aux

    params, _ = moe.split_params()
    val, grads = jax.value_and_grad(loss)(params, x)
    assert np.isfinite(float(val))
    for k, g in grads.items():
        assert np.all(np.isfinite(np.asarray(g))), k
    # gate weights receive gradient (routing is differentiable via probs)
    assert float(jnp.abs(grads["gate_w"]).sum()) > 0


def test_expert_parallel_matches_single_device():
    """ep=8 sharded dispatch == unsharded (the all-to-all is lossless)."""
    moe = MoELayer(8, 16, num_experts=8, gate="gshard", seed=2)
    params, _ = moe.split_params()
    x = jnp.asarray(np.random.RandomState(4).normal(size=(4, 16, 8)),
                    jnp.float32)

    def f(p, x):
        y, aux = moe.merge_params(p)(x)
        return y, aux

    mesh_lib.set_topology(None)
    y_ref, aux_ref = f(params, x)

    dist.init_mesh(ep=8)
    y_ep, aux_ep = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux_ep), float(aux_ref), atol=1e-6)


def test_capacity_drops_tokens():
    """With capacity 4 and 32 tokens routed to few experts, some tokens get
    zero output (residual passthrough is the caller's job)."""
    moe = MoELayer(4, 8, num_experts=2, gate="switch",
                   capacity_factor=0.25, jitter_eps=0.0, seed=3)
    x = jnp.asarray(np.random.RandomState(5).normal(size=(1, 32, 4)),
                    jnp.float32)
    y, _ = moe(x)
    zero_rows = np.asarray(jnp.sum(jnp.abs(y[0]), axis=-1)) == 0.0
    assert zero_rows.any()


def test_bad_gate_raises():
    with pytest.raises(ValueError, match="unknown gate"):
        MoELayer(8, 16, num_experts=2, gate="topk9000")


def test_gpt_moe_trains():
    """GPT-MoE flagship variant: loss decreases, aux loss flows, ep mesh."""
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import gpt
    topo = dist.init_mesh(dp=2, tp=2, ep=2)
    cfg = gpt.gpt_tiny(max_seq_len=32, moe_experts=4, moe_every=2,
                       dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = gpt.init_train_state(model, opt, topo.mesh)
    step = gpt.build_train_step(model, opt, topo.mesh)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 32)), jnp.int32)
    rng = jax.random.PRNGKey(0)
    losses = []
    for i in range(5):
        params, opt_state, loss = step(params, opt_state, tokens,
                                       jax.random.fold_in(rng, i))
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # moe params exist and were sharded over ep
    moe_w1 = params["blocks.item_1.moe.moe_w1"]
    assert "ep" in str(moe_w1.sharding.spec)


def test_gpt_moe_rejects_pipeline_and_remat():
    from paddle_tpu.models import gpt
    with pytest.raises(ValueError, match="remat"):
        gpt.GPT(gpt.gpt_tiny(moe_experts=2, remat=True), seed=0)
    model = gpt.GPT(gpt.gpt_tiny(moe_experts=2, dtype=jnp.float32), seed=0)
    with pytest.raises(ValueError, match="homogeneous"):
        gpt.stack_blocks(model, 2)
