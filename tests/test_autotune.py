"""Kernel block-size autotune (VERDICT r3 item 8).

Reference analog: phi/kernels/autotune tests (auto_tune_test.cu pattern —
pick-best over measured candidates + cache hit on the second query)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas import autotune as at
from paddle_tpu.ops.pallas.flash_attention import (_DEFAULT_BLOCKS,
                                                   _tune_key,
                                                   flash_attention,
                                                   tune_flash_attention)


@pytest.fixture
def cache(tmp_path, monkeypatch):
    c = at.AutotuneCache(path=str(tmp_path / "autotune.json"))
    monkeypatch.setattr(at, "_GLOBAL", c)
    return c


def test_tune_picks_argmin_and_caches(cache):
    calls = []

    def build_and_run(cfg):
        calls.append(cfg)
        import time
        time.sleep({"slow": 0.01, "fast": 0.0, "bad": 0.0}[cfg])
        if cfg == "bad":
            raise ValueError("unsupported config")

    best, timings = at.tune("k", "key1", ["slow", "bad", "fast"],
                            build_and_run, warmup=0, iters=2,
                            cache=cache)
    assert best == "fast"
    assert "bad" not in timings
    n = len(calls)

    # second query: cache hit, no measurement
    best2, timings2 = at.tune("k", "key1", ["slow", "fast"],
                              build_and_run, cache=cache)
    assert best2 == best
    assert timings2 == {} and len(calls) == n


def test_cache_persists_across_instances(tmp_path):
    c1 = at.AutotuneCache(path=str(tmp_path / "t.json"))
    c1.put("k|a=1", (128, 256))
    c2 = at.AutotuneCache(path=str(tmp_path / "t.json"))
    assert c2.get("k|a=1") == (128, 256)
    assert c2.get("k|a=2") is None


def test_every_candidate_failing_raises(cache):
    def boom(cfg):
        raise RuntimeError("no")

    with pytest.raises(ValueError, match="every candidate failed"):
        at.tune("k", "key2", [1, 2], boom, cache=cache)


def test_flash_attention_reads_tuned_blocks(cache, monkeypatch):
    """A cache entry for the exact shape key changes the blocks the kernel
    traces with; absent an entry, the measured defaults apply."""
    import sys
    fa = sys.modules["paddle_tpu.ops.pallas.flash_attention"]

    b, s, h, d = 2, 256, 2, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)

    seen = {}
    real = fa._flash

    def spy(*args, **kw):
        # (..., block_q, block_k, ...) positional: capture via the two
        # ints right after the scale argument
        seen["blocks"] = (args[8], args[9])
        return real(*args, **kw)

    monkeypatch.setattr(fa, "_flash", spy)
    flash_attention(q, q, q, causal=True)
    # 256-length seq clamps the default (256, 512) → (256, 256)
    assert seen["blocks"] == (min(_DEFAULT_BLOCKS[0], 256),
                              min(_DEFAULT_BLOCKS[1], 256))

    key = _tune_key(b, s, s, h, h, d, q.dtype, True, False, False, False)
    cache.put(key, (128, 128))
    flash_attention(q, q, q, causal=True)
    assert seen["blocks"] == (128, 128)

    # explicit blocks always win over the cache
    flash_attention(q, q, q, causal=True, block_q=256, block_k=128)
    assert seen["blocks"] == (256, 128)


def test_tune_flash_attention_end_to_end(cache):
    """Eager sweep on CPU (interpret mode): winner persisted under the key
    flash_attention's trace-time lookup uses."""
    b, s, h, d = 1, 128, 1, 8
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    best, timings = tune_flash_attention(
        q, q, q, causal=True, candidates=[(128, 128)], include_bwd=False,
        iters=1)
    assert best == (128, 128) and timings
    key = _tune_key(b, s, s, h, h, d, q.dtype, True, False, False, False)
    assert cache.get(key) == (128, 128)
    # numerics with the tuned blocks still match the XLA reference
    out = flash_attention(q, q, q, causal=True)
    ref = jax.nn.softmax(
        jnp.where(jnp.tril(jnp.ones((s, s), bool)),
                  (q[:, :, 0] @ q[:, :, 0].transpose(0, 2, 1))
                  / np.sqrt(d), -1e30)) @ q[:, :, 0]
    np.testing.assert_allclose(np.asarray(out[:, :, 0]), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
