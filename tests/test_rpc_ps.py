"""RPC + parameter-server end-to-end over the native P2P transport:
three OS processes (two servers, one trainer) exercising rpc_sync/
rpc_async/exception propagation and dense + sharded-sparse tables.

Reference analog: test_rpc_base.py / the fleet PS-mode tests — with the
id-sharded sparse pull/push checked for exact adagrad semantics."""

import multiprocessing as mp

import pytest

from paddle_tpu import native

import _rpc_worker


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_rpc_and_parameter_server(tmp_path):
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_rpc_worker.worker,
                         args=(r, 3, 23761, str(tmp_path)))
             for r in range(3)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=180)
    for r, p in enumerate(procs):
        assert p.exitcode == 0, f"rank {r} exited {p.exitcode}"
    assert (tmp_path / "ok_trainer").exists()
