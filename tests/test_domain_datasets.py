"""Domain datasets: ImageNet-style folder loading + augmentation pipeline,
text and audio loaders (VERDICT r2 item 10; ref python/paddle/{vision,
text,audio}/datasets)."""

import os

import numpy as np
import pytest

from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder, ImageNet
from paddle_tpu.text import Imdb, UCIHousing, Conll05st
from paddle_tpu.audio import ESC50, TESS, MelSpectrogram
from paddle_tpu.io import DataLoader


def _make_imagenet_tree(root, classes=("n01440764", "n01443537"), n=3):
    from PIL import Image
    for split in ("train", "val"):
        for ci, c in enumerate(classes):
            d = os.path.join(root, split, c)
            os.makedirs(d)
            for i in range(n):
                arr = np.full((8, 8, 3), 40 * ci + i, np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"img_{i}.png"))


def test_dataset_folder_and_imagenet(tmp_path):
    _make_imagenet_tree(str(tmp_path))
    ds = ImageNet(str(tmp_path), mode="train")
    assert len(ds) == 6
    assert ds.classes == ["n01440764", "n01443537"]
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and label == 0
    img, label = ds[5]
    assert label == 1

    flat = ImageFolder(str(tmp_path / "val"))
    assert len(flat) == 6
    (img,) = flat[0]
    assert img.shape == (8, 8, 3)

    with pytest.raises(RuntimeError, match="no class folders"):
        empty = tmp_path / "empty"
        empty.mkdir()
        DatasetFolder(str(empty))


def test_imagenet_augmentation_pipeline(tmp_path):
    """Real training pipeline: folder → augment → normalized CHW batch
    through the DataLoader."""
    _make_imagenet_tree(str(tmp_path))
    pipe = T.Compose([
        T.RandomResizedCrop(8),
        T.RandomHorizontalFlip(),
        T.ColorJitter(0.4, 0.4, 0.4, 0.1),
        T.RandomRotation(10),
        T.ToTensor(),
        T.Normalize([0.485, 0.456, 0.406], [0.229, 0.224, 0.225]),
        T.RandomErasing(prob=1.0),
    ])
    ds = ImageNet(str(tmp_path), mode="train", transform=pipe)
    loader = DataLoader(ds, batch_size=3, shuffle=True, drop_last=True)
    batches = list(loader)
    assert len(batches) == 2
    x, y = batches[0]
    assert np.asarray(x).shape == (3, 3, 8, 8)
    assert np.isfinite(np.asarray(x, np.float32)).all()


def test_text_datasets():
    tr = Imdb(mode="train", num_samples=64, seq_len=32)
    te = Imdb(mode="test", num_samples=32, seq_len=32)
    doc, label = tr[0]
    assert doc.shape == (32,) and label in (0, 1)
    assert len(tr) == 64 and len(te) == 32
    # learnable signal: positive docs over-sample the first vocab decile
    pos = tr.docs[tr.labels == 1]
    neg = tr.docs[tr.labels == 0]
    assert (pos < 500).mean() > (neg < 500).mean() + 0.1

    h = UCIHousing(mode="train")
    f, t = h[0]
    assert f.shape == (13,) and t.shape == (1,)

    c = Conll05st(mode="train", num_samples=16, seq_len=24)
    w, p, l = c[0]
    assert w.shape == p.shape == l.shape == (24,)
    assert p.sum() == 1  # exactly one predicate


def test_audio_datasets_with_features():
    mel = MelSpectrogram(sr=16000, n_fft=256, n_mels=32)
    ds = ESC50(mode="train", num_samples=8, feature_fn=mel)
    feat, label = ds[0]
    assert feat.shape[0] == 32 and 0 <= label < 50
    t = TESS(mode="dev", num_samples=4)
    w, label = t[0]
    assert w.shape == (16000,) and 0 <= label < 7
    # class-dependent fundamentals: different classes differ spectrally
    d0 = [np.asarray(mel(ds.waves[i])) for i in range(4)]
    assert all(np.isfinite(x).all() for x in d0)
