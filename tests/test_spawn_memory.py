"""spawn API (ref distributed/spawn.py:482) + device-memory observability
(ref memory/stats.h + mem_tracing.h; VERDICT r2 missing 10)."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu import profiler


def _worker_writes(tmpdir):
    import os as _os
    rank = _os.environ["PT_PROCESS_ID"]
    world = _os.environ["PT_NUM_PROCESSES"]
    with open(_os.path.join(tmpdir, f"w_{rank}"), "w") as f:
        f.write(world)


def _worker_fails():
    raise ValueError("worker boom")


def test_spawn_runs_workers_with_env_contract(tmp_path):
    dist.spawn(_worker_writes, args=(str(tmp_path),), nprocs=3)
    for r in range(3):
        assert (tmp_path / f"w_{r}").read_text() == "3"


def test_spawn_propagates_worker_failure():
    with pytest.raises(RuntimeError, match="worker boom"):
        dist.spawn(_worker_fails, nprocs=2)


def test_spawn_nonjoining_context(tmp_path):
    ctx = dist.spawn(_worker_writes, args=(str(tmp_path),), nprocs=2,
                     join=False)
    assert len(ctx.processes) == 2
    assert ctx.join()
    assert (tmp_path / "w_0").exists() and (tmp_path / "w_1").exists()


def test_memory_stats_surface():
    x = jnp.ones((256, 256), jnp.float32)  # keep a live array around
    s = profiler.device_memory_stats()
    assert s["bytes_in_use"] >= x.nbytes
    assert profiler.memory_allocated() == s["bytes_in_use"]
    assert profiler.max_memory_allocated() >= 0
    rec = profiler.record_memory_stats()
    assert profiler.stat_registry.stats()["mem/bytes_in_use"] == \
        int(rec["bytes_in_use"])
    text = profiler.memory_summary()
    assert "bytes_in_use" in text and "GiB" in text
    del x


def test_profiler_summary_includes_memory_block():
    p = profiler.Profiler(timer_only=True)
    p.start()
    with profiler.RecordEvent("span"):
        pass
    p.stop()
    assert "Device memory:" in p.summary()
    assert "Device memory:" not in p.summary(memory=False)
