"""Recompute API (≙ fleet/recompute/recompute.py:386; VERDICT r1 item 7).

The memory assertion reads the compiled executable's analysis (temp-buffer
bytes) rather than device allocator stats — deterministic on CPU."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.distributed import (recompute, recompute_sequential,
                                    checkpoint_name)
from paddle_tpu.distributed.recompute import recompute_wrapper, POLICIES


def _mlp_stack(n, d, key):
    ws = [jax.random.normal(jax.random.fold_in(key, i), (d, d)) / np.sqrt(d)
          for i in range(n)]
    return ws


def test_recompute_values_and_grads_match():
    d = 16
    ws = _mlp_stack(4, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def net(ws, x):
        h = x
        for w in ws:
            h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    def net_rc(ws, x):
        def body(h, w):
            return jnp.tanh(h @ w)
        h = x
        for w in ws:
            h = recompute(body, h, w)
        return jnp.sum(h ** 2)

    l0, g0 = jax.value_and_grad(net)(ws, x)
    l1, g1 = jax.value_and_grad(net_rc)(ws, x)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_recompute_reduces_saved_residuals():
    """jax's own AD accounting (ad_checkpoint.saved_residuals): the remat
    version must carry strictly fewer live-residual bytes from forward to
    backward — the memory saving that motivates the API."""
    try:
        from jax.ad_checkpoint import saved_residuals
    except ImportError:  # only under jax._src in this jax version
        from jax._src.ad_checkpoint import saved_residuals

    d, n, batch = 256, 8, 256
    ws = _mlp_stack(n, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))

    def loss_plain(ws, x):
        h = x
        for w in ws:
            h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    def loss_rc(ws, x):
        def seg(h, w):
            return jnp.tanh(h @ w)
        h = x
        for w in ws:
            h = recompute(seg, h, w)
        return jnp.sum(h ** 2)

    def residual_bytes(f):
        res = saved_residuals(f, ws, x)
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a, _ in res if hasattr(a, "shape"))

    plain, rc = residual_bytes(loss_plain), residual_bytes(loss_rc)
    assert rc < plain, (rc, plain)


def test_recompute_sequential_segments():
    d = 16
    ws = _mlp_stack(6, d, jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, d))
    fns = [lambda h, w=w: jnp.tanh(h @ w) for w in ws]
    ref = x
    for f in fns:
        ref = f(ref)
    for k in (1, 2, 3, 6):
        out = recompute_sequential(fns, x, segments=k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)


def test_policies_and_selective_names():
    d = 16
    w = jax.random.normal(jax.random.PRNGKey(4), (d, d))
    x = jax.random.normal(jax.random.PRNGKey(5), (4, d))

    def f(w, x):
        h = checkpoint_name(jnp.tanh(x @ w), "h1")
        return jnp.sum(h @ w)

    for pol in list(POLICIES) + [["h1"]]:
        g = jax.grad(lambda w: recompute(f, w, x, policy=pol))(w)
        g_ref = jax.grad(lambda w: f(w, x))(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-5)
    with pytest.raises(ValueError, match="unknown recompute policy"):
        recompute(f, w, x, policy="bogus")


def test_wrapper_decorator():
    @recompute_wrapper
    def f(x):
        return jnp.sum(jnp.sin(x) ** 2)

    x = jnp.arange(4.0)
    np.testing.assert_allclose(float(jax.grad(f)(x)[0]),
                               float(jax.grad(
                                   lambda x: jnp.sum(jnp.sin(x) ** 2))(x)[0]),
                               rtol=1e-6)
