"""Multi-replica serving router (paddle_tpu/serving/router.py, ISSUE
10): real replica processes spawned through the distributed/launch.py
CLI, TCPStore membership, least-outstanding placement, and —
the acceptance case — killing one replica under fault injection loses
no queued request (request-id accounting proves redistribution)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu import native, stats
from paddle_tpu.serving import Router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_serve_worker.py")

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason="native TCPStore unavailable")


def _spawn_replica(store_port: int, rid: str, launch_port: int,
                   extra_env=None):
    """One replica process via the launch CLI (one launch per replica,
    nproc_per_node=1, so a fault-injected kill of one replica cannot
    take its peers' launcher down with it)."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         WORKER, str(store_port), rid],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _cleanup(router, procs):
    router.shutdown()
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)
    router.close()


def test_router_round_trip_two_replicas():
    """Requests spread over two real replica processes come back
    complete and correct; placement is least-outstanding (both
    replicas serve some share). ``dead_after`` is generous here: a
    loaded CI host can stall an idle replica's heartbeat for seconds,
    and a false death would legitimately shift all work to one replica
    (that behavior is the NEXT test's job)."""
    router = Router(port=0, dead_after=15.0)   # ephemeral store port
    procs = [_spawn_replica(router.store.port, f"rep{i}", 8875 + i)
             for i in range(2)]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(0)
        ids = [router.submit(list(rs.randint(0, 96, size=7)),
                             max_new_tokens=6) for _ in range(8)]
        # an INFEASIBLE request (prompt beyond the replica engines'
        # cache) must come back as a rejected RESULT — an uncaught
        # raise would kill the replica and the router would cascade the
        # poison payload through the whole fleet (regression)
        bad = router.submit([3] * 140, max_new_tokens=16)
        results = router.drain(timeout=120)
        assert sorted(results) == sorted(ids + [bad])
        assert results[bad]["status"] == "rejected-invalid"
        assert "exceed cache length" in results[bad]["error"]
        assert all(results[q]["status"] == "done"
                   and len(results[q]["tokens"]) == 6 for q in ids)
        served_by = {results[q]["replica"] for q in ids}
        assert served_by == {"rep0", "rep1"}, served_by
        assert len(router.replicas()) == 2   # nobody died of it
    finally:
        _cleanup(router, procs)


def test_replica_death_redistributes_queued_work(tmp_path):
    """Acceptance: SIGKILL one replica with requests outstanding —
    every submitted request id still completes (redistributed to the
    survivor), counted on serve/router_redistributed. The victim runs
    TRACED with a fast periodic flush (ISSUE 13): its last flushed
    spans must survive the SIGKILL and still stitch by request id."""
    stats.reset("serve/router")
    victim_trace = str(tmp_path / "trace_rep0.json")
    router = Router(port=0, dead_after=2.5)
    procs = [_spawn_replica(
                 router.store.port, f"rep{i}", 8885 + i,
                 extra_env=({"FLEETOBS_TRACE_FILE": victim_trace,
                             "PT_TRACE_FLUSH_S": "0.2"}
                            if i == 0 else None))
             for i in range(2)]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(1)
        # enough decode work that the victim dies mid-flight
        ids = [router.submit(list(rs.randint(0, 96, size=9)),
                             max_new_tokens=24) for _ in range(10)]
        victim = "rep0"
        victim_reqs = [q for q, r in router._assigned.items()
                       if r == victim]
        assert victim_reqs, "least-outstanding never placed on rep0?"
        # give the victim time to admit (and flush) before the kill —
        # a SIGKILL mid-serve is exactly the case the flush exists for
        time.sleep(1.0)
        pid = router.directory.members()[victim]["pid"]
        os.kill(pid, signal.SIGKILL)
        results = router.drain(timeout=120)
        # request-id accounting: nothing lost, first result wins
        assert sorted(results) == sorted(ids)
        assert all(r["status"] == "done"
                   for r in results.values()), results
        assert stats.get("serve/router_redistributed") > 0
        # whatever the victim hadn't finished was re-served by rep1
        # (the counter may exceed it if host load false-positived rep1
        # dead for a moment too — at-least-once makes that harmless)
        redone = [q for q in victim_reqs
                  if results[q]["replica"] == "rep1"]
        assert len(redone) <= stats.get("serve/router_redistributed")
    finally:
        _cleanup(router, procs)
    # the SIGKILLed replica left a complete (atomically flushed) trace
    # whose request-tagged spans still stitch
    from _fleetobs import assert_flushed_trace_stitches
    assert_flushed_trace_stitches(victim_trace, ids)


def test_least_outstanding_placement_deterministic():
    """Placement policy in isolation (no replica processes): with two
    alive replicas and no completions, submissions alternate; results
    landing rebalance toward the drained replica."""
    from paddle_tpu.serving.router import _publish

    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        router = Router(store=store)
        router.directory.announce("a", {})
        router.directory.announce("b", {})
        router.directory.alive = lambda rid, dead_after=0: True
        ids = [router.submit([1, 2, 3], max_new_tokens=2)
               for _ in range(4)]
        assert [router._assigned[q] for q in ids] == ["a", "b", "a", "b"]
        # 'a' drains both its requests -> next two land on 'a' first
        for q in ids[::2]:
            _publish(store, "a", q, {"id": q, "tokens": [],
                                     "status": "done", "error": None,
                                     "replica": "a"})
        router.poll()
        more = [router.submit([1, 2, 3], max_new_tokens=2)
                for _ in range(2)]
        assert [router._assigned[q] for q in more] == ["a", "a"]
    finally:
        store.close()


def test_membership_alive_judges_progress():
    """ReplicaDirectory liveness: progress-based, observer-clocked."""
    from paddle_tpu.distributed.membership import ReplicaDirectory
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        d_rep = ReplicaDirectory(store)
        d_obs = ReplicaDirectory(store)
        assert d_obs.members() == {}
        assert not d_obs.alive("ghost", dead_after=0.1)
        d_rep.announce("r0", {"slots": 2})
        assert d_obs.members() == {"r0": {"slots": 2}}
        assert d_obs.alive("r0", dead_after=0.2)
        time.sleep(0.05)
        d_rep.heartbeat("r0")
        assert d_obs.alive("r0", dead_after=0.2)   # progressed
        time.sleep(0.3)
        assert not d_obs.alive("r0", dead_after=0.2)  # stalled
        d_rep.heartbeat("r0")
        assert d_obs.alive("r0", dead_after=0.2)   # resurrected
    finally:
        store.close()
