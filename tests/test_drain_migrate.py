"""In-flight request migration for draining replicas (ISSUE 16): a
slot-holding decode request detaches from one front-end
(``detach_migrate``), crosses the fp32 KV wire, re-admits on a
survivor (``submit_handoff``) and finishes with a stream
byte-identical to uninterrupted serving — on both engines, across
engine kinds, with zero request-id loss. Chaos at the documented
``drain.migrate`` site must fall back to finish-in-place (sender) or
``handoff-failed`` re-placement (receiver), never a corrupt stream."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu import stats
from paddle_tpu.distributed.membership import ReplicaDirectory
from paddle_tpu.inference.decode_engine import DecodeEngine
from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.models import gpt
from paddle_tpu.serving import FrontEnd, kv_transfer
from paddle_tpu.serving.router import (Router, _install_handoff,
                                       _migrate_open_requests)
from paddle_tpu.testing import faults

CFG = gpt.GPTConfig(vocab_size=96, max_seq_len=256, d_model=32,
                    n_layers=2, n_heads=4, dtype=jnp.float32)
MODEL = gpt.GPT(CFG, seed=0)
PROMPTS = [[int(x) for x in np.random.RandomState(7).randint(0, 96, n)]
           for n in (7, 19, 33)]
MAX_NEW = 24


def _fe(kind):
    if kind == "paged":
        return FrontEnd(PagedDecodeEngine(MODEL, n_pages=12,
                                          max_slots=4))
    return FrontEnd(DecodeEngine(MODEL, max_slots=4, max_len=96))


def _baseline(kind):
    fe = _fe(kind)
    reqs = [fe.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    fe.run()
    return [list(r.tokens) for r in reqs]


def _wire_roundtrip(got):
    """The migration wire: fp32 encode -> decode, as the router ships
    it (whole-blob digest verified on decode)."""
    meta = got["meta"]
    hdr, blob = kv_transfer.encode_kv_pages(
        got["k"], got["v"], n_tokens=meta["n_tokens"], wire="fp32")
    k, v = kv_transfer.decode_kv_pages(hdr, blob)
    return dict(meta, wire=hdr["wire"]), k, v


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    yield
    faults.clear()


@pytest.mark.parametrize("src_kind,dst_kind", [
    ("dense", "dense"), ("paged", "paged"),
    ("dense", "paged"), ("paged", "dense")])
def test_migrated_stream_byte_identity(src_kind, dst_kind):
    """Mid-decode migration, all four engine pairings: every stream
    finishes on the survivor byte-identical to uninterrupted serving
    (fp32 wire + handoff re-emitting the sender's last token)."""
    want = _baseline(src_kind)
    src, dst = _fe(src_kind), _fe(dst_kind)
    reqs = [src.submit(p, max_new_tokens=MAX_NEW) for p in PROMPTS]
    while not all(r.tokens or r.done for r in reqs):
        src.step()                       # mid-decode, tokens in flight
    moved, migrated_kv = [], 0
    for sreq in reqs:
        got = src.detach_migrate(sreq)
        while got is None and sreq.status != "done":
            src.step()                   # mid-prefill: pump and retry
            got = src.detach_migrate(sreq)
        if got is None:
            moved.append(sreq)           # finished before it could move
            continue
        assert sreq.status == "migrated"
        if got["kv"]:
            migrated_kv += 1
            meta, k, v = _wire_roundtrip(got)
            moved.append(dst.submit_handoff(meta, k, v))
        else:
            moved.append(dst.submit(sreq.prompt,
                                    max_new_tokens=MAX_NEW))
    assert migrated_kv > 0               # the interesting path ran
    dst.run()
    assert [list(r.tokens) for r in moved] == want


def test_migrate_queued_and_completed_requests():
    """The two non-KV detach outcomes: a still-queued request leaves as
    a bare id ({'kv': False}); a completed one refuses to move (None)
    and keeps its finished stream."""
    fe = FrontEnd(DecodeEngine(MODEL, max_slots=2, max_len=96))
    # 5 requests into 2 slots: one lands in the engine's staging
    # deque, the tail stays in the front-end queue — BOTH leave as
    # bare ids (no device state yet)
    reqs = [fe.submit(p, max_new_tokens=MAX_NEW)
            for p in (PROMPTS * 2)[:5]]
    fe.step()
    queued = next(r for r in reqs if r.status == "queued")
    staged = next(r for r in reqs if r.status == "admitted"
                  and r.engine_req in fe.engine._waiting)
    for victim in (queued, staged):
        got = fe.detach_migrate(victim)
        assert got == {"kv": False} and victim.status == "migrated"
    fe.run()
    done = next(r for r in reqs if r.status == "done")
    toks = list(done.tokens)
    assert fe.detach_migrate(done) is None
    assert list(done.tokens) == toks


def test_drain_migrate_fault_falls_back_finish_in_place():
    """The sending half under chaos: a raise at the ``drain.migrate``
    site leaves every request finishing IN PLACE (the PR 14 drain),
    counted on serve/drain_migrate_failed — zero id loss; with the
    fault lifted the same loop migrates the remainder."""
    stats.reset("serve/")
    router = Router(port=0)
    try:
        store = router.store
        fe = _fe("dense")
        reqs = {f"r{i}": fe.submit(p, max_new_tokens=MAX_NEW)
                for i, p in enumerate(PROMPTS)}
        while not all(r.tokens for r in reqs.values()):
            fe.step()                   # mid-decode: all hold slots
        open_reqs = dict(reqs)
        with faults.inject("drain.migrate", "raise"):
            _migrate_open_requests(store, "rep0", fe, open_reqs)
        # nothing moved, nothing lost — all three still finish here
        assert set(open_reqs) == set(reqs)
        assert stats.get("serve/drain_migrate_failed") == 3
        # fault lifted: the retry loop empties the replica
        while open_reqs:
            _migrate_open_requests(store, "rep0", fe, open_reqs)
            fe.step()
        assert stats.get("serve/drain_migrated") == 3
        for rid_, sreq in reqs.items():
            assert sreq.status == "migrated"
            res = json.loads(store.get(f"serve/done/{rid_}",
                                       timeout=1.0))
            assert res["status"] == "migrated" and res["kv"] is True
            # ...and the published blob re-admits on a survivor,
            # byte-identical to the no-drain baseline
        want = _baseline("dense")
        dst = _fe("dense")
        directory = ReplicaDirectory(store)
        directory.announce("rep1", {})
        moved = [_install_handoff(store, "rep1", directory, dst,
                                  {"id": rid_}) for rid_ in reqs]
        assert all(m is not None for m in moved)
        dst.run()
        assert [list(m.tokens) for m in moved] == want
    finally:
        router.shutdown()


def test_drain_migrate_bitflip_becomes_handoff_failed():
    """In-transit corruption (bitflip at ``drain.migrate``): the
    receiver's whole-blob digest check refuses the install and
    publishes retryable ``handoff-failed`` — corrupted KV rows are
    NEVER admitted."""
    stats.reset("serve/")
    router = Router(port=0)
    try:
        store = router.store
        fe = _fe("dense")
        sreq = fe.submit(PROMPTS[0], max_new_tokens=MAX_NEW)
        while not sreq.tokens:
            fe.step()
        open_reqs = {"rx": sreq}
        # fire() consumes index 0; transform() hits index 1
        with faults.inject("drain.migrate", "bitflip", after=1):
            _migrate_open_requests(store, "rep0", fe, open_reqs)
        assert not open_reqs and sreq.status == "migrated"
        dst = _fe("dense")
        directory = ReplicaDirectory(store)
        directory.announce("rep1", {})
        assert _install_handoff(store, "rep1", directory, dst,
                                {"id": "rx"}) is None
        res = json.loads(store.get("serve/done/rx", timeout=1.0))
        assert res["status"] == "handoff-failed"
        assert "digest" in res["error"] or "corrupt" in res["error"]
        assert int(np.asarray(dst.engine.active).sum()) == 0
    finally:
        router.shutdown()
