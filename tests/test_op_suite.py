"""OpTest harness (ref: python/paddle/fluid/tests/unittests/op_test.py:333 —
one numpy oracle × N execution modes). Here the modes are eager (op-by-op
XLA) and jit (traced), checked against the registered numpy reference;
gradients checked against finite differences for differentiable ops."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu  # noqa: F401  (populates the registry)
from paddle_tpu.ops.registry import all_ops

ORACLE_OPS = [op for op in all_ops()
              if op.np_ref is not None and op.sample_args is not None]


@pytest.mark.parametrize("op", ORACLE_OPS, ids=lambda o: o.name)
def test_eager_matches_numpy(op):
    args, kwargs = op.sample_args()
    got = op.fn(*args, **kwargs)
    want = op.np_ref(*[np.asarray(a) for a in args])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("op", ORACLE_OPS, ids=lambda o: o.name)
def test_jit_matches_eager(op):
    args, kwargs = op.sample_args()
    eager = op.fn(*args, **kwargs)
    jitted = jax.jit(lambda *a: op.fn(*a, **kwargs))(*args)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                               rtol=1e-6, atol=1e-6)


GRAD_OPS = [op for op in ORACLE_OPS if op.differentiable]


@pytest.mark.parametrize("op", GRAD_OPS, ids=lambda o: o.name)
def test_grad_matches_finite_difference(op):
    """≙ OpTest.check_grad (op_test.py:2131): analytic vs numeric grads."""
    args, kwargs = op.sample_args()
    if not args or not np.issubdtype(np.asarray(args[0]).dtype,
                                     np.floating):
        pytest.skip("non-float primary input")

    def scalar_fn(x0):
        out = op.fn(x0, *args[1:], **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return jnp.sum(jnp.asarray(out) ** 2) / 2

    analytic = np.asarray(jax.grad(scalar_fn)(jnp.asarray(args[0])))
    x = np.asarray(args[0], np.float32)
    eps = 1e-3
    flat = x.reshape(-1)
    # probe a handful of coordinates (full FD is O(n) evaluations)
    idxs = np.linspace(0, flat.size - 1, min(5, flat.size)).astype(int)
    for i in idxs:
        xp = flat.copy()
        xm = flat.copy()
        xp[i] += eps
        xm[i] -= eps
        fp = float(scalar_fn(jnp.asarray(xp.reshape(x.shape))))
        fm = float(scalar_fn(jnp.asarray(xm.reshape(x.shape))))
        numeric = (fp - fm) / (2 * eps)
        got = analytic.reshape(-1)[i]
        np.testing.assert_allclose(got, numeric, rtol=3e-2, atol=3e-3,
                                   err_msg=f"op={op.name} coord={i}")
