"""OpTest harness (ref: python/paddle/fluid/tests/unittests/op_test.py:333 —
one numpy oracle × N execution modes). Here the modes are eager (op-by-op
XLA) and jit (traced), checked against the registered numpy reference;
gradients checked against finite differences for differentiable ops.
Random ops are checked statistically (shape/dtype/moments/bounds) instead
of by value. A completeness gate asserts no registered op escapes both."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu  # noqa: F401  (populates the registry + attaches oracles)
import paddle_tpu.tensor as T
from paddle_tpu.ops.registry import all_ops

# value-oracle tests don't apply to sampling ops: these get the
# distribution tests at the bottom of this file
RANDOM_OPS = {"rand", "uniform", "randn", "normal", "standard_normal",
              "randint", "randint_like", "randperm", "shuffle",
              "multinomial", "bernoulli", "poisson", "exponential_",
              "binomial", "gaussian"}

ORACLE_OPS = [op for op in all_ops()
              if op.np_ref is not None and op.sample_args is not None]


def test_every_op_is_tested():
    """Completeness gate (VERDICT r2 item 2): every registered op either
    has a value oracle or is a random op with a distribution test."""
    untested = [op.name for op in all_ops()
                if (op.np_ref is None or op.sample_args is None)
                and op.name not in RANDOM_OPS and op.alias_of is None]
    assert not untested, f"ops without oracle: {untested}"
    registered = {op.name for op in all_ops()}
    stale = RANDOM_OPS - registered
    assert not stale, f"RANDOM_OPS not in registry: {stale}"
    # exact partition: every registered op is an oracle op, a random op,
    # or an alias of one — no fourth bucket
    n_alias = sum(1 for op in all_ops() if op.alias_of is not None)
    n_random = sum(1 for op in all_ops()
                   if op.name in RANDOM_OPS and op.alias_of is None)
    assert len(ORACLE_OPS) + n_random + n_alias == len(all_ops())
    assert len(ORACLE_OPS) >= 294, (
        f"oracle coverage regressed: {len(ORACLE_OPS)}")


@pytest.mark.parametrize("op", ORACLE_OPS, ids=lambda o: o.name)
def test_eager_matches_numpy(op):
    args, kwargs = op.sample_args()
    fn = op.test_fn or op.fn
    got = fn(*args, **kwargs)
    want = op.np_ref(*[np.asarray(a) for a in args])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


JIT_OPS = [op for op in ORACLE_OPS if op.jit_ok]


@pytest.mark.parametrize("op", JIT_OPS, ids=lambda o: o.name)
def test_jit_matches_eager(op):
    args, kwargs = op.sample_args()
    fn = op.test_fn or op.fn
    eager = fn(*args, **kwargs)
    jitted = jax.jit(lambda *a: fn(*a, **kwargs))(*args)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                               rtol=1e-6, atol=1e-6)


GRAD_OPS = [op for op in ORACLE_OPS if op.differentiable]


@pytest.mark.parametrize("op", GRAD_OPS, ids=lambda o: o.name)
def test_grad_matches_finite_difference(op):
    """≙ OpTest.check_grad (op_test.py:2131): analytic vs numeric grads."""
    args, kwargs = op.sample_args()
    fn = op.test_fn or op.fn
    if not args or not np.issubdtype(np.asarray(args[0]).dtype,
                                     np.floating):
        pytest.skip("non-float primary input")

    def scalar_fn(x0):
        out = fn(x0, *args[1:], **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return jnp.sum(jnp.asarray(out) ** 2) / 2

    analytic = np.asarray(jax.grad(scalar_fn)(jnp.asarray(args[0])))
    x = np.asarray(args[0], np.float32)
    eps = 1e-3
    flat = x.reshape(-1)
    # probe a handful of coordinates (full FD is O(n) evaluations)
    idxs = np.linspace(0, flat.size - 1, min(5, flat.size)).astype(int)
    for i in idxs:
        xp = flat.copy()
        xm = flat.copy()
        xp[i] += eps
        xm[i] -= eps
        fp = float(scalar_fn(jnp.asarray(xp.reshape(x.shape))))
        fm = float(scalar_fn(jnp.asarray(xm.reshape(x.shape))))
        numeric = (fp - fm) / (2 * eps)
        got = analytic.reshape(-1)[i]
        np.testing.assert_allclose(got, numeric, rtol=3e-2, atol=3e-3,
                                   err_msg=f"op={op.name} coord={i}")


# ---------------------------------------------------------------------------
# Random-op distribution tests (≙ unittests/test_uniform_random_op.py
# pattern: moments + bounds on large samples, not per-value equality)
# ---------------------------------------------------------------------------

N = 20000


def test_rand_uniform():
    x = np.asarray(T.rand((N,)))
    assert x.shape == (N,) and (x >= 0).all() and (x < 1).all()
    assert abs(x.mean() - 0.5) < 0.02 and abs(x.std() - 0.2887) < 0.02
    y = np.asarray(T.uniform((N,), min=-2.0, max=4.0))
    assert (y >= -2).all() and (y < 4).all()
    assert abs(y.mean() - 1.0) < 0.1


def test_randn_normal():
    for fn in (lambda: T.randn((N,)), lambda: T.standard_normal((N,))):
        x = np.asarray(fn())
        assert abs(x.mean()) < 0.03 and abs(x.std() - 1.0) < 0.03
    y = np.asarray(T.normal(mean=3.0, std=0.5, shape=(N,)))
    assert abs(y.mean() - 3.0) < 0.03 and abs(y.std() - 0.5) < 0.03
    g = np.asarray(T.gaussian((N,), mean=-1.0, std=2.0))
    assert abs(g.mean() + 1.0) < 0.1 and abs(g.std() - 2.0) < 0.1


def test_randint_and_like():
    x = np.asarray(T.randint(2, 9, (N,)))
    assert ((x >= 2) & (x < 9)).all()
    assert set(np.unique(x)) == set(range(2, 9))
    y = np.asarray(T.randint_like(jnp.zeros((N,), jnp.int32), 0, 5))
    assert ((y >= 0) & (y < 5)).all()


def test_randperm_shuffle():
    p = np.sort(np.asarray(T.randperm(257)))
    np.testing.assert_array_equal(p, np.arange(257))
    x = jnp.arange(257)
    s = np.asarray(T.shuffle(x))
    assert not np.array_equal(s, np.arange(257))
    np.testing.assert_array_equal(np.sort(s), np.arange(257))


def test_bernoulli_multinomial():
    p = jnp.full((N,), 0.3)
    b = np.asarray(T.bernoulli(p))
    assert set(np.unique(b)) <= {0.0, 1.0}
    assert abs(b.mean() - 0.3) < 0.02
    probs = jnp.asarray([0.1, 0.2, 0.7])
    m = np.asarray(T.multinomial(probs, num_samples=N, replacement=True))
    frac = np.bincount(m, minlength=3) / N
    np.testing.assert_allclose(frac, [0.1, 0.2, 0.7], atol=0.03)


def test_poisson_exponential_binomial():
    lam = jnp.full((N,), 4.0)
    x = np.asarray(T.poisson(lam))
    assert abs(x.mean() - 4.0) < 0.1 and abs(x.var() - 4.0) < 0.3
    e = np.asarray(T.exponential_(jnp.zeros((N,)), lam=2.0))
    assert (e >= 0).all() and abs(e.mean() - 0.5) < 0.02
    bn = np.asarray(T.binomial(jnp.full((N,), 10.0),
                               jnp.full((N,), 0.4)))
    assert abs(bn.mean() - 4.0) < 0.1
    assert (bn >= 0).all() and (bn <= 10).all()


def test_inplace_aliases_share_base_fn():
    """Every op_ alias dispatches the exact base implementation (the
    OpTest oracle covers the base; identity covers the alias)."""
    from paddle_tpu.ops.registry import get_op
    aliases = [op for op in all_ops() if op.alias_of is not None]
    assert len(aliases) >= 24
    for op in aliases:
        assert op.fn is get_op(op.alias_of).fn, (op.name, op.alias_of)
