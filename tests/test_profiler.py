"""Profiler statistics + StatRegistry counters (VERDICT r1 item 10;
≙ profiler_statistic.py tables + platform/monitor.h StatRegistry)."""

import time

import numpy as np
import jax.numpy as jnp

from paddle_tpu import profiler
from paddle_tpu.profiler import (Profiler, RecordEvent, stat_add, stat_get,
                                 stat_registry)


def test_summary_table_of_named_spans():
    p = Profiler(timer_only=True)
    p.start()
    for i in range(3):
        with RecordEvent("forward"):
            time.sleep(0.003)
        with RecordEvent("backward"):
            time.sleep(0.006)
        p.step()
    p.stop()
    table = p.summary()
    assert "forward" in table and "backward" in table
    assert "Calls" in table and "Ratio%" in table
    assert "steps: 3" in table
    # backward rows dominate forward in total time → sorted first
    assert table.index("backward") < table.index("forward")
    lines = [l for l in table.splitlines() if l.startswith("forward")]
    assert lines and int(lines[0].split()[1]) == 3  # 3 calls


def test_spans_not_recorded_outside_profiler():
    from paddle_tpu.profiler.statistic import _get_active
    assert _get_active() is None
    with RecordEvent("orphan"):
        pass  # must not crash without an active collector
    p = Profiler(timer_only=True)
    p.start()
    with RecordEvent("inside"):
        pass
    p.stop()
    assert "orphan" not in p.summary()
    assert "inside" in p.summary()


def test_stat_registry_counters():
    stat_registry.reset()
    assert stat_get("io/batches") == 0
    stat_add("io/batches")
    stat_add("io/batches", 4)
    assert stat_get("io/batches") == 5
    stat_registry.set("mem/peak", 123)
    assert stat_registry.stats() == {"io/batches": 5, "mem/peak": 123}
    stat_registry.reset("io/batches")
    assert stat_get("io/batches") == 0 and stat_get("mem/peak") == 123
    stat_registry.reset()


def _row_order(table, names):
    """First-column span names in table-row order (not raw substring
    search — 'a' would match the 'Name' header; VERDICT r2 weak 2)."""
    order = []
    for line in table.splitlines():
        cols = line.split()
        if cols and cols[0] in names:
            order.append(cols[0])
    return order


def test_sorted_by_options():
    p = Profiler(timer_only=True)
    p.start()
    with RecordEvent("span_slow"):
        time.sleep(0.002)
    for _ in range(5):
        with RecordEvent("span_freq"):
            pass
    p.stop()
    names = {"span_slow", "span_freq"}
    assert _row_order(p.summary(sorted_by="count"), names) == \
        ["span_freq", "span_slow"]
    assert _row_order(p.summary(sorted_by="total"), names) == \
        ["span_slow", "span_freq"]
