"""Distributed checkpoint tests: sharded save, resharding-on-load, version
gate, auto-checkpoint resume (≙ SURVEY §5.4: dist_saver/converter +
auto_checkpoint.py TrainEpochRange)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import (AutoCheckpoint, load_state,
                                               save_state)
from paddle_tpu.models import gpt


def test_roundtrip_single_device(tmp_path):
    state = {"w": jnp.arange(12.0).reshape(3, 4),
             "nested": {"b": jnp.ones((5,), jnp.bfloat16),
                        "step": jnp.asarray(7, jnp.int32)},
             "scalar": 3, "name": "adam"}
    save_state(state, str(tmp_path / "ck"))
    out = load_state(str(tmp_path / "ck"))
    np.testing.assert_array_equal(out["w"], state["w"])
    assert out["nested"]["b"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["nested"]["b"], np.float32),
        np.asarray(state["nested"]["b"], np.float32))
    assert int(out["nested"]["step"]) == 7
    assert out["scalar"] == 3 and out["name"] == "adam"


def test_sharded_save_writes_one_copy_per_shard(tmp_path):
    topo = dist.init_mesh(fsdp=8)
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(topo.mesh, P("fsdp", None)))
    rep = jax.device_put(jnp.ones((4,)),
                         NamedSharding(topo.mesh, P()))
    save_state({"x": x, "rep": rep}, str(tmp_path / "ck"))
    files = os.listdir(tmp_path / "ck" / "data")
    x_files = [f for f in files if f.startswith("ARRAY_1")
               or f.startswith("ARRAY_0")]
    # 8 shard files for x, 1 for the replicated array
    assert len(files) == 9, files


def test_reshard_on_load(tmp_path):
    """Save on fsdp=8, load on dp=2 x fsdp=2 x tp=2 with different specs."""
    topo_a = dist.init_mesh(fsdp=8)
    w = jax.device_put(
        jnp.arange(256.0, dtype=jnp.float32).reshape(16, 16),
        NamedSharding(topo_a.mesh, P("fsdp", None)))
    save_state({"w": w}, str(tmp_path / "ck"))

    topo_b = dist.init_mesh(dp=2, fsdp=2, tp=2)
    new_shard = NamedSharding(topo_b.mesh, P("tp", "fsdp"))
    out = load_state(str(tmp_path / "ck"), template={"w": new_shard})
    assert out["w"].sharding.spec == P("tp", "fsdp")
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(256.0).reshape(16, 16))


def test_reshard_full_train_state(tmp_path):
    """GPT params+opt state saved sharded, restored on a different mesh and
    training continues bit-exactly vs an uninterrupted run."""
    from paddle_tpu import optimizer as optim
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (8, 32)),
                         jnp.int32)
    rng = jax.random.PRNGKey(0)

    def run(n_steps, params, opt_state, step_fn):
        for i in range(n_steps):
            params, opt_state, loss = step_fn(
                params, opt_state, tokens, jax.random.fold_in(rng, i))
        return params, opt_state, float(loss)

    topo_a = dist.init_mesh(dp=2, fsdp=4)
    cfg = gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-2)
    params, opt_state = gpt.init_train_state(model, opt, topo_a.mesh)
    step = gpt.build_train_step(model, opt, topo_a.mesh, donate=False)
    params, opt_state, _ = run(2, params, opt_state, step)
    save_state({"params": params, "opt": opt_state}, str(tmp_path / "ck"))
    # uninterrupted continuation (oracle)
    _, _, loss_ref = run(2, params, opt_state, step)

    # restore onto a different mesh layout
    topo_b = dist.init_mesh(tp=2, fsdp=2, dp=2)
    shardings = {
        "params": {k: NamedSharding(topo_b.mesh, gpt.partition_spec(k))
                   for k in params},
        "opt": jax.tree_util.tree_map(
            lambda _: None, opt_state,
            is_leaf=lambda x: isinstance(x, jax.Array)),
    }
    restored = load_state(str(tmp_path / "ck"), shardings=shardings)
    step_b = gpt.build_train_step(model, opt, topo_b.mesh, donate=False)
    _, _, loss_b = run(2, restored["params"], restored["opt"], step_b)
    np.testing.assert_allclose(loss_b, loss_ref, atol=1e-5, rtol=1e-5)


def test_version_gate(tmp_path):
    save_state({"x": jnp.ones(3)}, str(tmp_path / "ck"))
    import json
    mp = tmp_path / "ck" / "meta.json"
    meta = json.loads(mp.read_text())
    meta["format_version"] = 999
    mp.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="format_version"):
        load_state(str(tmp_path / "ck"))


def test_auto_checkpoint_resume(tmp_path):
    ck = AutoCheckpoint(str(tmp_path), job_id="job1", keep=2)
    assert ck.restore() is None and ck.next_epoch == 0
    state = {"w": jnp.zeros((4,)), "epoch": 0}
    for epoch in ck.epochs(ck.next_epoch, 3):
        state = {"w": state["w"] + 1.0, "epoch": epoch}
        ck.save(state, epoch)
    # simulate preemption: new AutoCheckpoint instance
    ck2 = AutoCheckpoint(str(tmp_path), job_id="job1", keep=2)
    assert ck2.next_epoch == 3
    restored = ck2.restore()
    np.testing.assert_array_equal(restored["w"], np.full((4,), 3.0))
    assert restored["epoch"] == 2
    # keep=2 pruned epoch_0
    assert sorted(ck2._epochs_on_disk()) == [1, 2]


def test_missing_shard_raises(tmp_path):
    """ADVICE r1: a deleted/partial shard file must raise, never restore
    uninitialized-memory garbage."""
    import os
    save_state({"x": jnp.arange(8.0)}, str(tmp_path / "ck"))
    data_dir = tmp_path / "ck" / "data"
    for f in os.listdir(data_dir):
        os.unlink(data_dir / f)
    with pytest.raises(ValueError, match="missing"):
        load_state(str(tmp_path / "ck"))


def test_incomplete_coverage_raises(tmp_path):
    """Shards present but not covering the full array must raise."""
    import json
    save_state({"x": jnp.arange(8.0)}, str(tmp_path / "ck"))
    mp = tmp_path / "ck" / "meta.json"
    meta = json.loads(mp.read_text())
    (name, entry), = meta["arrays"].items()
    # shrink the recorded range so the saved shard no longer covers [0,8)
    entry["shards"][0]["range"] = [[0, 4]]
    mp.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="do not cover"):
        load_state(str(tmp_path / "ck"))


def _corrupt(path, mode):
    """Damage a checkpoint dir the three ways ISSUE 2 names."""
    import glob
    if mode == "truncate":
        shard = sorted(glob.glob(os.path.join(path, "data", "*.npy")))[0]
        with open(shard, "r+b") as f:
            f.truncate(max(1, os.path.getsize(shard) // 2))
    elif mode == "bitflip":
        shard = sorted(glob.glob(os.path.join(path, "data", "*.npy")))[0]
        with open(shard, "r+b") as f:
            data = bytearray(f.read())
            data[-1] ^= 0x40
            f.seek(0)
            f.write(data)
    elif mode == "no_commit":
        os.unlink(os.path.join(path, "COMMIT"))
    else:
        raise ValueError(mode)


@pytest.mark.faults
def test_save_writes_v2_checksums_and_commit(tmp_path):
    from paddle_tpu.distributed.checkpoint import (FORMAT_VERSION,
                                                   verify_checkpoint)
    import json
    save_state({"x": jnp.arange(8.0)}, str(tmp_path / "ck"))
    assert FORMAT_VERSION == 2
    meta = json.loads((tmp_path / "ck" / "meta.json").read_text())
    assert meta["format_version"] == 2
    (fn, digest), = meta["checksums"].items()
    assert fn.endswith(".npy") and len(digest) == 64
    commit = json.loads((tmp_path / "ck" / "COMMIT").read_text())
    assert commit["format_version"] == 2
    assert verify_checkpoint(str(tmp_path / "ck")) == (True, "ok")


@pytest.mark.faults
@pytest.mark.parametrize("mode", ["truncate", "bitflip", "no_commit"])
def test_restore_falls_back_to_previous_verified(tmp_path, mode):
    """Truncated shard / checksum mismatch / missing COMMIT on the
    newest epoch each fall back to the previous verified one."""
    from paddle_tpu import stats
    stats.reset("ckpt/")
    ck = AutoCheckpoint(str(tmp_path), job_id="j", keep=4)
    state = {"w": jnp.zeros((4,))}
    for epoch in range(3):
        state = {"w": state["w"] + 1.0}
        ck.save(state, epoch)
    _corrupt(str(tmp_path / "j" / "epoch_2"), mode)
    ck2 = AutoCheckpoint(str(tmp_path), job_id="j", keep=4)
    restored = ck2.restore()
    np.testing.assert_array_equal(restored["w"], np.full((4,), 2.0))
    assert ck2.next_epoch == 2        # the damaged epoch gets re-trained
    assert stats.get("ckpt/restore_fallbacks") >= 1


@pytest.mark.faults
def test_injected_shard_corruption_caught_by_verify(tmp_path):
    """The ckpt.shard fault site corrupts bytes AFTER the checksum is
    recorded — exactly the disk-rot scenario verification must catch."""
    from paddle_tpu.distributed.checkpoint import verify_checkpoint
    from paddle_tpu.testing import faults
    with faults.inject("ckpt.shard", "bitflip"):
        save_state({"x": jnp.arange(16.0)}, str(tmp_path / "ck"))
    ok, reason = verify_checkpoint(str(tmp_path / "ck"))
    assert not ok and "checksum mismatch" in reason
    with pytest.raises(ValueError, match="checksum mismatch"):
        load_state(str(tmp_path / "ck"), verify=True)


@pytest.mark.faults
def test_reshard_on_restore_fsdp4_to_single_chip(tmp_path):
    """ISSUE 2 satellite: save under an fsdp=4 mesh, restore with no
    mesh at all (1 chip) — the v2 meta (checksums + COMMIT) must verify
    and the resharded values round-trip exactly."""
    from paddle_tpu.distributed.checkpoint import verify_checkpoint
    import json
    topo = dist.init_mesh(dp=2, fsdp=4)
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(topo.mesh, P("fsdp", None)))
    ck = AutoCheckpoint(str(tmp_path), job_id="r", keep=2)
    ck.save({"w": w, "step": 7}, 0)
    ep = str(tmp_path / "r" / "epoch_0")
    meta = json.loads(open(os.path.join(ep, "meta.json")).read())
    assert meta["format_version"] == 2
    assert len(meta["checksums"]) == 4        # one per fsdp shard
    assert verify_checkpoint(ep) == (True, "ok")
    # fresh AutoCheckpoint, no shardings → single-device restore
    out = AutoCheckpoint(str(tmp_path), job_id="r", keep=2).restore()
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(64.0).reshape(8, 8))
    assert out["step"] == 7


@pytest.mark.faults
def test_v1_checkpoint_still_loads(tmp_path):
    """Back-compat: a v1 directory (no checksums, no COMMIT) must load
    and verify (existence-only) under the v2 reader."""
    from paddle_tpu.distributed.checkpoint import verify_checkpoint
    import glob
    import json
    save_state({"x": jnp.arange(6.0)}, str(tmp_path / "ck"))
    # strip the v2 artifacts: what a v1 writer produced
    os.unlink(tmp_path / "ck" / "COMMIT")
    for f in glob.glob(str(tmp_path / "ck" / "checksums.*.json")):
        os.unlink(f)
    mp = tmp_path / "ck" / "meta.json"
    meta = json.loads(mp.read_text())
    meta["format_version"] = 1
    meta.pop("checksums", None)
    mp.write_text(json.dumps(meta))
    assert verify_checkpoint(str(tmp_path / "ck")) == (True, "ok")
    out = load_state(str(tmp_path / "ck"), verify=True)
    np.testing.assert_array_equal(out["x"], np.arange(6.0))


def test_boxes_cover_unit():
    from paddle_tpu.distributed.checkpoint import _boxes_cover
    t = [(0, 8), (0, 4)]
    assert _boxes_cover([((0, 8), (0, 4))], t)
    assert _boxes_cover([((0, 4), (0, 4)), ((4, 8), (0, 4))], t)
    assert not _boxes_cover([((0, 4), (0, 4))], t)
    # partial overlap → coordinate-compression path
    assert _boxes_cover([((0, 6), (0, 4)), ((3, 8), (0, 4))], t)
    assert not _boxes_cover([((0, 6), (0, 4)), ((3, 8), (0, 3))], t)
