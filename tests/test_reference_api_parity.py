"""Automated API-parity gate against the reference tree (round 4): the
public tensor API, nn.functional, paddle.distributed __all__, and the
top-level paddle __all__ must every one diff EMPTY against this package.

The reference is scanned textually (its python/ tree imports CUDA-bound
extensions we neither have nor want); einsum-planner internals and
underscore names are excluded as non-public."""

import os
import re

import pytest

REF = "/root/reference/python/paddle"

pytestmark = pytest.mark.skipif(not os.path.isdir(REF),
                                reason="reference tree unavailable")


def _ref_defs(*relpaths):
    out = set()
    for rel in relpaths:
        path = os.path.join(REF, rel)
        if os.path.isdir(path):
            files = [os.path.join(path, f) for f in os.listdir(path)
                     if f.endswith(".py")]
        else:
            files = [path]
        for f in files:
            src = open(f).read()
            out |= set(re.findall(r"^def ([a-z][a-z0-9_]*)\(", src, re.M))
    return out


def _ref_all(relpath):
    src = open(os.path.join(REF, relpath)).read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    return {x.strip().strip("'\"")
            for x in m.group(1).replace("# noqa", "").split(",")
            if x.strip()}


_EINSUM_INTERNALS = {
    "build_global_shape", "build_global_view", "build_view",
    "diagonalize", "einsum_v2", "gen_einsum_op",
    "gen_equation_for_opteinsum", "has_duplicated_labels",
    "infer_broadcast_shape", "non_negative_axis", "parse_fake_shape",
    "parse_labels", "parse_op_labels", "plan_broadcast", "plan_einsum",
    "plan_matmul", "plan_reduce", "plan_scalar_prod", "plan_summation",
    "preprocess", "rearrange", "rhs_inference", "validate_rhs",
}


def test_tensor_api_parity():
    from paddle_tpu.ops.registry import _OPS
    have = {n.split(".")[-1] for n in _OPS}
    ref = _ref_defs("tensor/math.py", "tensor/manipulation.py",
                    "tensor/linalg.py", "tensor/search.py",
                    "tensor/logic.py", "tensor/creation.py",
                    "tensor/stat.py", "tensor/random.py",
                    "tensor/attribute.py", "tensor/einsum.py")
    missing = sorted(n for n in ref - have - _EINSUM_INTERNALS
                     if not n.endswith("_"))
    assert not missing, missing


def test_nn_functional_parity():
    from paddle_tpu.nn import functional as F
    have = {n for n in dir(F) if not n.startswith("_")}
    ref = _ref_defs("nn/functional")
    missing = sorted(ref - have)
    assert not missing, missing


def test_distributed_all_parity():
    import paddle_tpu.distributed as D
    ref = _ref_all("distributed/__init__.py")
    missing = sorted(n for n in ref if not hasattr(D, n))
    assert not missing, missing


def test_top_level_all_parity():
    import paddle_tpu as pt
    ref = _ref_all("__init__.py")
    missing = sorted(n for n in ref if not hasattr(pt, n))
    assert not missing, missing


def test_vision_ops_parity():
    from paddle_tpu.vision import ops as V
    src = open(os.path.join(REF, "vision/ops.py")).read()
    ref = set(re.findall(r"^def ([a-z][a-z0-9_]*)\(", src, re.M))
    ref |= set(re.findall(r"^class ([A-Z]\w*)\(", src, re.M))
    missing = sorted(n for n in ref if not hasattr(V, n))
    assert not missing, missing


def test_nn_layer_parity():
    import paddle_tpu.nn as nn
    classes = set()
    base = os.path.join(REF, "nn/layer")
    for f in os.listdir(base):
        if f.endswith(".py"):
            src = open(os.path.join(base, f)).read()
            classes |= set(re.findall(r"^class ([A-Z]\w*)\(", src, re.M))
    missing = sorted(c for c in classes
                     if not c.startswith("_") and not hasattr(nn, c))
    assert not missing, missing


@pytest.mark.parametrize("rel,modpath", [
    ("optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("io/__init__.py", "paddle_tpu.io"),
    ("metric/__init__.py", "paddle_tpu.metric"),
    ("jit/__init__.py", "paddle_tpu.jit"),
    ("amp/__init__.py", "paddle_tpu.amp"),
    ("nn/__init__.py", "paddle_tpu.nn"),
    ("vision/__init__.py", "paddle_tpu.vision"),
    ("signal.py", "paddle_tpu.signal"),
    ("sparse/__init__.py", "paddle_tpu.sparse"),
    ("incubate/__init__.py", "paddle_tpu.incubate"),
    ("distribution/__init__.py", "paddle_tpu.distribution"),
    ("linalg.py", "paddle_tpu.linalg"),
])
def test_module_all_parity(rel, modpath):
    import importlib
    mod = importlib.import_module(modpath)
    ref = _ref_all(rel)
    missing = sorted(n for n in ref
                     if "'" not in n and "\\n" not in n
                     and not hasattr(mod, n))
    assert not missing, f"{rel}: {missing}"
