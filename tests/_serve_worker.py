"""Serving-replica worker for test_serve_router (and the front smoke's
router phase): one process = one replica, spawned through the real
``distributed/launch.py`` CLI. Pins the CPU platform at module level —
the launcher imports this before any jax backend initializes.

Usage (as the launch CLI's training script):
    python -m paddle_tpu.distributed.launch --nproc_per_node 1 \
        tests/_serve_worker.py STORE_PORT REPLICA_ID [MAX_NEW_CAP]
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# one replica needs one device; conftest's 8-virtual-device XLA_FLAGS
# would leak in through the environment and slow startup
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
# per-replica trace file for the fleet-observability tests: translated
# HERE (before the paddle_tpu import) so only the WORKER traces
import _fleetobs
_fleetobs.adopt_replica_trace_env()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    port = int(sys.argv[1])
    rid = sys.argv[2]
    import jax.numpy as jnp
    from paddle_tpu import native
    from paddle_tpu.models import gpt
    from paddle_tpu.inference.decode_engine import DecodeEngine
    from paddle_tpu.serving import FrontEnd, serve_replica
    from paddle_tpu.testing import faults

    # PT_FAULTS plumbing (the fleet chaos tests kill a replica
    # mid-serve with serve.loop:kill and assert the controller heals)
    faults.install_from_env()

    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    store = native.TCPStore("127.0.0.1", port)
    fe = FrontEnd(DecodeEngine(model, max_slots=2, max_len=96))
    try:
        serve_replica(store, rid, fe, max_idle_s=120.0)
    finally:
        store.close()


if __name__ == "__main__":
    main()
