"""Portable checkpoint resharding (ISSUE 8): a v2 checkpoint saved on
mesh A — in either block layout — restores onto mesh B's exact layout and
shardings, bit-exact, via per-host sharded reads (checkpoint.load_resharded).
The round trips exercise fsdp4 → tp2 → single-chip and stacked ↔
per-layer, gated by the v2 sha256 sidecars."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as optim
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.distributed.checkpoint import load_resharded, name_leaves
from paddle_tpu.models import gpt


def _cfg():
    return gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                         n_layers=3, n_heads=2, dtype=jnp.float32)


def _train_state(model, mesh, stacked, n_steps=1):
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
    params, opt_state = gpt.init_train_state(model, opt, mesh,
                                             stacked=stacked)
    step = gpt.build_train_step(model, opt, mesh)
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 16)), jnp.int32)
    for i in range(n_steps):
        params, opt_state, _ = step(params, opt_state, toks,
                                    jax.random.PRNGKey(i))
    return {"params": params, "opt_state": opt_state}


def _leaves_by_name(state):
    return {n: np.asarray(v) for n, v in name_leaves(state).items()
            if hasattr(v, "shape")}


def _assert_equivalent(state_a, state_b):
    """Bit-exact equality across layouts: every per-layer leaf of one
    side must equal the matching layer slice of the other's stack."""
    import re
    a, b = _leaves_by_name(state_a), _leaves_by_name(state_b)

    def canon(leaves):
        out = {}
        for n, v in leaves.items():
            m = re.match(r"^(.*?)([A-Za-z0-9]+)\.item_(\d+)\.(.+)$", n)
            if m:
                pfx, lst, l, rest = m.groups()
                out.setdefault(f"{pfx}_stacked_{lst}.{rest}", {})[
                    int(l)] = v
            else:
                out[n] = v
        for n, v in list(out.items()):
            if isinstance(v, dict):
                out[n] = np.stack([v[l] for l in sorted(v)])
        return out

    ca, cb = canon(a), canon(b)
    assert set(ca) == set(cb), set(ca) ^ set(cb)
    for n in ca:
        np.testing.assert_array_equal(ca[n], cb[n], err_msg=n)


def _mesh(**kw):
    n = 1
    for v in kw.values():
        n *= v
    return mesh_lib.init_mesh(devices=jax.devices()[:n], **kw)


def test_reshard_chain_fsdp4_tp2_single_chip():
    """fsdp4(stacked) → tp2(per-layer) → single-chip(stacked): every hop
    loads the previous hop's checkpoint onto a different mesh AND layout,
    verified (v2 sidecars) and bit-exact at the end of the chain."""
    model = gpt.GPT(_cfg(), seed=0)
    tmp = os.environ.get("PYTEST_TMP") or None
    import tempfile
    root = tempfile.mkdtemp(dir=tmp)

    topo_a = _mesh(fsdp=4)
    state_a = _train_state(model, topo_a.mesh, stacked=True)
    ckpt.save_state(state_a, f"{root}/a")
    ok, reason = ckpt.verify_checkpoint(f"{root}/a")
    assert ok, reason

    mesh_lib.set_topology(None)
    topo_b = _mesh(tp=2)
    opt = optim.AdamW(learning_rate=1e-3)
    pb, sb = gpt.init_train_state(model, opt, topo_b.mesh)
    state_b = load_resharded(f"{root}/a",
                             {"params": pb, "opt_state": sb})
    _assert_equivalent(state_a, state_b)
    # target shardings honored: per-layer wqkv on the tp mesh
    assert len(state_b["params"]["blocks.item_0.wqkv"]
               .sharding.device_set) == 2
    ckpt.save_state(state_b, f"{root}/b")

    mesh_lib.set_topology(None)
    opt = optim.AdamW(learning_rate=1e-3)
    pc, sc = gpt.init_train_state(model, opt, stacked=True)
    state_c = load_resharded(f"{root}/b",
                             {"params": pc, "opt_state": sc})
    _assert_equivalent(state_a, state_c)
    # step counter rode along
    assert int(state_c["opt_state"]["step"]) == int(
        state_a["opt_state"]["step"])

    # resumed training stays finite on the new layout
    opt = optim.AdamW(learning_rate=1e-3)
    gpt.init_train_state(model, opt, stacked=True)  # rebind templates
    step = gpt.build_train_step(model, opt)
    toks = jnp.asarray(
        np.random.RandomState(2).randint(0, 128, (4, 16)), jnp.int32)
    _, _, loss = step(state_c["params"], state_c["opt_state"], toks,
                      jax.random.PRNGKey(9))
    assert np.isfinite(float(loss))


def test_reshard_per_layer_to_stacked_and_back(tmp_path):
    model = gpt.GPT(_cfg(), seed=0)
    state_a = _train_state(model, None, stacked=False)
    ckpt.save_state(state_a, str(tmp_path / "a"))

    opt = optim.AdamW(learning_rate=1e-3)
    ps, ss = gpt.init_train_state(model, opt, stacked=True)
    stacked = load_resharded(str(tmp_path / "a"),
                             {"params": ps, "opt_state": ss})
    _assert_equivalent(state_a, stacked)
    ckpt.save_state(stacked, str(tmp_path / "b"))

    opt = optim.AdamW(learning_rate=1e-3)
    pp, sp = gpt.init_train_state(model, opt)
    back = load_resharded(str(tmp_path / "b"),
                          {"params": pp, "opt_state": sp})
    for name, v in _leaves_by_name(state_a).items():
        np.testing.assert_array_equal(
            v, _leaves_by_name(back)[name], err_msg=name)


def test_reshard_verify_rejects_corruption(tmp_path):
    model = gpt.GPT(_cfg(), seed=0)
    state = _train_state(model, None, stacked=True)
    d = str(tmp_path / "ck")
    ckpt.save_state(state, d)
    # flip one byte in a shard: the sha256 sidecar must veto the load
    import glob
    victim = sorted(glob.glob(os.path.join(d, "data", "*.npy")))[0]
    with open(victim, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    opt = optim.AdamW(learning_rate=1e-3)
    p, s = gpt.init_train_state(model, opt, stacked=True)
    with pytest.raises(ValueError, match="checksum|verification"):
        load_resharded(d, {"params": p, "opt_state": s})


def test_reshard_missing_layer_raises(tmp_path):
    """A per-layer checkpoint missing a layer must fail loudly when a
    stacked target asks for it, naming the gap."""
    model = gpt.GPT(_cfg(), seed=0)
    state = _train_state(model, None, stacked=False)
    state["params"] = {k: v for k, v in state["params"].items()
                      if not k.startswith("blocks.item_2.")}
    state["opt_state"]["slots"] = {
        k: v for k, v in state["opt_state"]["slots"].items()
        if not k.startswith("blocks.item_2.")}
    d = str(tmp_path / "ck")
    ckpt.save_state(state, d)
    opt = optim.AdamW(learning_rate=1e-3)
    p, s = gpt.init_train_state(model, opt, stacked=True)
    with pytest.raises(ValueError, match="lacks layers"):
        load_resharded(d, {"params": p, "opt_state": s})


def test_autocheckpoint_restore_resharded(tmp_path):
    """Elastic resume across a layout change: AutoCheckpoint saved the
    stacked state; the restarted job builds per-layer on a different
    mesh and restores via restore_resharded."""
    model = gpt.GPT(_cfg(), seed=0)
    topo = _mesh(fsdp=2)
    state = _train_state(model, topo.mesh, stacked=True)
    ck = ckpt.AutoCheckpoint(str(tmp_path), job_id="elastic", keep=2)
    ck.save(state, epoch=0)

    mesh_lib.set_topology(None)
    ck2 = ckpt.AutoCheckpoint(str(tmp_path), job_id="elastic", keep=2)
    opt = optim.AdamW(learning_rate=1e-3)
    p, s = gpt.init_train_state(model, opt)
    restored = ck2.restore_resharded({"params": p, "opt_state": s})
    assert restored is not None
    _assert_equivalent(state, restored)

    # and onto ANOTHER mesh, mesh-normalized (jit-created optimizer
    # leaves can be committed to one device in the fresh template; the
    # restore_like policy replicates them so the donating step accepts
    # the restored state), then actually train on it
    mesh_lib.set_topology(None)
    topo2 = _mesh(tp=2)
    mesh_lib.set_topology(topo2)
    opt2 = optim.AdamW(learning_rate=1e-3)
    p2, s2 = gpt.init_train_state(model, opt2, topo2.mesh)
    ck3 = ckpt.AutoCheckpoint(str(tmp_path), job_id="elastic", keep=2)
    restored2 = ck3.restore_resharded({"params": p2, "opt_state": s2},
                                      mesh=topo2.mesh)
    step = gpt.build_train_step(model, opt2, topo2.mesh)
    toks = jnp.asarray(
        np.random.RandomState(5).randint(0, 128, (4, 16)), jnp.int32)
    _, _, loss = step(restored2["params"], restored2["opt_state"], toks,
                      jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
