"""Named-counter observability (VERDICT §5.5: StatRegistry analog —
ref: paddle/fluid/platform/monitor.h StatRegistry + STAT_ADD macros)."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import stats
from paddle_tpu.stats import StatRegistry


@pytest.fixture(autouse=True)
def _clean():
    stats.reset()
    yield
    stats.reset()


def test_counters_gauges_timers():
    r = StatRegistry()
    assert r.add("io/reads", 3) == 3
    assert r.add("io/reads") == 4
    r.set_value("mem/hbm_frac", 0.7)
    r.set_value("mem/hbm_frac", 0.8)  # last-value-wins
    with r.timer("step"):
        time.sleep(0.01)
    snap = r.snapshot()
    assert snap["io/reads"] == 4
    assert snap["mem/hbm_frac"] == 0.8
    assert snap["step.count"] == 1 and snap["step.total_s"] >= 0.01
    assert "io/reads" in r.table() and "step.mean_s" in r.table()


def test_reset_by_prefix():
    r = StatRegistry()
    r.add("a/x")
    r.add("b/y")
    r.reset("a/")
    assert r.get("a/x") == 0 and r.get("b/y") == 1


def test_thread_safety():
    r = StatRegistry()

    def work():
        for _ in range(1000):
            r.add("n")

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert r.get("n") == 8000


def test_module_level_default_registry():
    stats.add("x", 2)
    stats.set_value("g", 1.5)
    assert stats.get("x") == 2 and stats.get("g") == 1.5
    assert stats.snapshot()["x"] == 2
    assert pt.stats is stats  # exported on the package


def test_hapi_fit_records_stats():
    import jax.numpy as jnp
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.hapi import Model

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc(x)

    m = Model(Net())
    m.prepare(optim.SGD(learning_rate=0.1),
              nn.CrossEntropyLoss())
    x = np.random.RandomState(0).rand(16, 4).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (16, 1)).astype(np.int64)
    m.fit(list(zip(x.reshape(4, 4, 4), y.reshape(4, 4, 1))), epochs=2,
          verbose=0)
    assert stats.get("hapi/train_steps") == 8
    assert stats.get("hapi/train_samples") == 32
    assert isinstance(stats.get("hapi/last_loss"), float)


def test_benchmark_publishes_stats():
    from paddle_tpu.profiler.timer import Benchmark
    b = Benchmark(flops_per_step=1e9, peak_flops=1e12)
    b.begin()
    for _ in range(3):
        time.sleep(0.002)
        b.step(num_samples=4)
    rep = b.report()
    assert stats.get("benchmark/ips") == rep["ips"]
    assert stats.get("benchmark/mfu") == rep["mfu"]
