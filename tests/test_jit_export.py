""".ptexport version stamping (VERDICT r4 item 10)
≙ paddle/fluid/framework/op_version_registry.h:397 + op_version.yaml:
artifacts carry {format_version, package_version, op registry hash};
load gates on the readable range with a clear error."""

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit as ptjit
from paddle_tpu.static import InputSpec


def _export(tmp_path, name="m"):
    def fn(x):
        return jnp.tanh(x) * 2.0 + 1.0

    path = str(tmp_path / name)
    out = ptjit.save(fn, path,
                     input_spec=[InputSpec([None, 4], "float32")])
    return fn, out


def test_roundtrip_and_stamp(tmp_path):
    fn, p = _export(tmp_path)
    with open(p, "rb") as f:
        bundle = pickle.load(f)
    assert bundle["format_version"] == ptjit.FORMAT_VERSION
    assert bundle["package_version"] == pt.__version__
    assert len(bundle["op_registry_hash"]) == 16

    loaded = ptjit.load(p)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(loaded(x)),
                               np.asarray(fn(jnp.asarray(x))),
                               rtol=1e-6)


def test_unstamped_legacy_artifact_loads_with_warning(tmp_path):
    """A pre-versioning bundle (no format_version key) has the identical
    layout — it must LOAD, with a provenance warning, not break users'
    existing exports."""
    fn, p = _export(tmp_path)
    with open(p, "rb") as f:
        bundle = pickle.load(f)
    del bundle["format_version"]
    with open(p, "wb") as f:
        pickle.dump(bundle, f)
    with pytest.warns(UserWarning, match="predates"):
        loaded = ptjit.load(p)
    assert np.isfinite(
        np.asarray(loaded(np.ones((2, 4), np.float32)))).all()


def test_below_range_format_rejected(tmp_path):
    """A STAMPED version below the readable floor (a synthetically old
    artifact) must fail with a clear error naming the range."""
    fn, p = _export(tmp_path)
    with open(p, "rb") as f:
        bundle = pickle.load(f)
    bundle["format_version"] = ptjit.MIN_READABLE_FORMAT - 1
    with open(p, "wb") as f:
        pickle.dump(bundle, f)
    with pytest.raises(ValueError, match="re-export"):
        ptjit.load(p)


def test_future_format_rejected(tmp_path):
    fn, p = _export(tmp_path)
    with open(p, "rb") as f:
        bundle = pickle.load(f)
    bundle["format_version"] = ptjit.FORMAT_VERSION + 7
    bundle["package_version"] = "99.0.0"
    with open(p, "wb") as f:
        pickle.dump(bundle, f)
    with pytest.raises(ValueError, match="99.0.0"):
        ptjit.load(p)


def test_registry_drift_warns_but_loads(tmp_path):
    fn, p = _export(tmp_path)
    with open(p, "rb") as f:
        bundle = pickle.load(f)
    bundle["op_registry_hash"] = "0" * 16
    with open(p, "wb") as f:
        pickle.dump(bundle, f)
    with pytest.warns(UserWarning, match="different op registry"):
        loaded = ptjit.load(p)
    x = np.ones((2, 4), np.float32)
    assert np.isfinite(np.asarray(loaded(x))).all()
