"""Vocab-parallel embedding + cross-entropy (≙ the reference's
VocabParallelEmbedding mp_layers.py:37 / c_softmax_with_cross_entropy
c_softmax_with_cross_entropy_op.cu) — verified against dense oracles on an
8-virtual-device mesh, including the HLO-level guarantee that no full-vocab
tensor is ever materialized."""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.mp_ops import (parallel_cross_entropy,
                                           vocab_parallel_embedding)
from paddle_tpu.distributed import mesh as mesh_lib


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_lib.set_topology(None)


def _dense_ce(logits, labels):
    x = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(x, axis=-1)
    pick = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]
    return logz - pick


def test_parallel_ce_matches_dense_loss_and_grads():
    topo = dist.init_mesh(dp=2, tp=4)
    mesh = topo.mesh
    B, S, V = 4, 8, 64
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(B, S, V), jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (B, S)), jnp.int32)

    sharded = jax.device_put(logits, NamedSharding(mesh, P("dp", None, "tp")))

    def loss_tp(lg):
        return jnp.mean(parallel_cross_entropy(
            lg, labels, mesh=mesh, batch_axes=("dp",), seq_axis=None))

    def loss_dense(lg):
        return jnp.mean(_dense_ce(lg, labels))

    l_tp, g_tp = jax.jit(jax.value_and_grad(loss_tp))(sharded)
    l_d, g_d = jax.jit(jax.value_and_grad(loss_dense))(logits)
    np.testing.assert_allclose(float(l_tp), float(l_d), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g_tp), np.asarray(g_d),
                               rtol=1e-5, atol=1e-6)


def test_parallel_ce_ignore_index():
    topo = dist.init_mesh(tp=8)
    mesh = topo.mesh
    B, S, V = 2, 4, 32
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(B, S, V), jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (B, S)), jnp.int32)
    labels = labels.at[0, 0].set(-1)
    tok = parallel_cross_entropy(logits, labels, mesh=mesh, batch_axes=(),
                                 seq_axis=None, ignore_index=-1)
    dense = _dense_ce(logits, jnp.maximum(labels, 0))
    np.testing.assert_allclose(np.asarray(tok)[0, 0], 0.0)
    np.testing.assert_allclose(np.asarray(tok)[0, 1:],
                               np.asarray(dense)[0, 1:], rtol=1e-5)


def test_vocab_parallel_embedding_matches_dense():
    topo = dist.init_mesh(tp=4, fsdp=2)
    mesh = topo.mesh
    V, D, B, S = 32, 8, 2, 4
    rs = np.random.RandomState(2)
    table = jnp.asarray(rs.randn(V, D), jnp.float32)
    tokens = jnp.asarray(rs.randint(0, V, (B, S)), jnp.int32)
    tbl = jax.device_put(table, NamedSharding(mesh, P("tp", "fsdp")))

    def fwd(t):
        return vocab_parallel_embedding(
            t, tokens, mesh=mesh, shard_axes=("fsdp",), batch_axes=(),
            seq_axis=None)

    out = jax.jit(fwd)(tbl)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, tokens, axis=0)),
                               rtol=1e-6)
    # grads: d/dtable of sum(embed) == scatter-add of ones
    g = jax.jit(jax.grad(lambda t: jnp.sum(fwd(t))))(tbl)
    g_ref = jax.grad(lambda t: jnp.sum(jnp.take(t, tokens, axis=0)))(table)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-6)


def test_gpt_tp_loss_matches_dense_and_no_full_vocab_in_hlo():
    """End-to-end: gpt train step under dp2×tp2×fsdp2 — loss/grads match the
    single-device dense oracle, and the compiled HLO contains NO tensor of
    the full (B, S, V) logits shape (the all-gather the reference avoids
    with c_softmax_with_cross_entropy)."""
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import gpt

    cfg = gpt.gpt_tiny()  # vocab=256, S=64, d=64, heads=2
    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.0)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 64)),
        jnp.int32)
    rng = jax.random.PRNGKey(0)

    # dense single-device oracle
    mesh_lib.set_topology(None)
    params_d, opt_d = gpt.init_train_state(model, opt)
    step_d = gpt.build_train_step(model, opt, donate=False)
    _, _, loss_d = step_d(params_d, opt_d, tokens, rng)

    # tp-sharded
    topo = dist.init_mesh(dp=2, tp=2, fsdp=2)
    params_t, opt_t = gpt.init_train_state(model, opt, topo.mesh)
    step_t = gpt.build_train_step(model, opt, topo.mesh, donate=False)
    _, _, loss_t = step_t(params_t, opt_t, tokens, rng)
    np.testing.assert_allclose(float(loss_t), float(loss_d),
                               rtol=2e-5, atol=2e-5)

    hlo = step_t.lower(params_t, opt_t, tokens, rng).compile().as_text()
    b, s, v = 4, 64, cfg.vocab_size
    full_shapes = [f"{b},{s},{v}", f"{b * s},{v}"]
    for pat in full_shapes:
        assert not re.search(rf"\[{pat}\]", hlo), (
            f"full-vocab tensor [{pat}] materialized in compiled HLO — "
            f"vocab-parallel CE/embedding not in effect")
