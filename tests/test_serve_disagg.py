"""Disaggregated prefill/decode serving (ISSUE 12): the KV-page wire
(block-scaled int8/fp8, fp32 bit-identity opt-out, fail-loud scale
guard), prefill→transfer→decode handoff through the paged engines, the
fleet-wide prefix directory lifecycle (cross-replica hit, eviction /
poison invalidation, mid-fetch withdraw race), the TTFT-EMA cold-start
fix, and the role-aware router — in-process where possible, real
replica processes (launch CLI) for the round-trip and the
SIGKILL-mid-transfer acceptance case."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest
import jax.numpy as jnp

from paddle_tpu import native, stats
from paddle_tpu.models import gpt
from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.serving import FrontEnd, kv_transfer as kt
from paddle_tpu.serving.disagg import FleetPrefixDirectory
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_disagg_worker.py")


def _model(seed=0):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=seed)


def _engine(model, **kw):
    kw.setdefault("n_pages", 32)
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 128)
    return PagedDecodeEngine(model, **kw)


def _prefill_one(eng, prompt, max_new_tokens=12, eos_id=2):
    """Run one prompt through a prefill_only engine to the detach
    point."""
    r = eng.submit(prompt, max_new_tokens=max_new_tokens, eos_id=eos_id)
    while not r.tokens and not r.done and not r.failed:
        eng.step()
    eng.drain()
    return eng.detach_handoff(r)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_wire_codec_fp32_roundtrip_exact():
    rs = np.random.RandomState(0)
    k = rs.randn(2, 3, 4, 128, 8).astype(np.float32)
    v = rs.randn(2, 3, 4, 128, 8).astype(np.float32)
    h, blob = kt.encode_kv_pages(k.copy(), v.copy(), 300, wire="fp32")
    k2, v2 = kt.decode_kv_pages(h, blob)
    # tail rows past n_tokens are zeroed on the wire (recycled-pool
    # garbage must not cross replicas); all real rows are bit-exact
    kz, vz = k.copy(), v.copy()
    kz[:, 2, :, 300 - 256:, :] = 0
    vz[:, 2, :, 300 - 256:, :] = 0
    assert np.array_equal(k2, kz) and np.array_equal(v2, vz)
    assert h["bytes_wire"] == k.nbytes + v.nbytes


@pytest.mark.parametrize("wire", ["int8", "fp8"])
def test_wire_codec_quant_ratio_and_bound(wire):
    rs = np.random.RandomState(1)
    k = rs.randn(2, 2, 4, 128, 8).astype(np.float32)
    v = rs.randn(2, 2, 4, 128, 8).astype(np.float32)
    h, blob = kt.encode_kv_pages(k.copy(), v.copy(), 256, wire=wire)
    kq, vq = kt.decode_kv_pages(h, blob)
    ratio = h["bytes_logical"] / h["bytes_wire"]
    assert ratio >= 3.5, ratio        # the acceptance floor
    for a, b, name in ((k, kq, "k"), (v, vq, "v")):
        if wire == "int8":
            # per-element error ≤ the block half step; every block's
            # scale is ≤ amax/qmax, so amax/(2*qmax) bounds all of it
            bound = 0.5 * h["amax"][name] / h["qmax"] + 1e-6
        else:
            # e4m3 rounding is RELATIVE (3 mantissa bits): half an ulp
            # is ≤ |v|/16, so amax/16 bounds the whole tensor
            bound = h["amax"][name] / 16.0 + 1e-6
        assert float(np.max(np.abs(a - b))) <= bound


def test_wire_codec_store_chunking():
    """Blobs larger than one store value round-trip through the
    chunked publish/fetch protocol; delete removes every key."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        rs = np.random.RandomState(2)
        k = rs.randn(2, 4, 4, 128, 8).astype(np.float32)
        v = rs.randn(2, 4, 4, 128, 8).astype(np.float32)
        h, blob = kt.encode_kv_pages(k, v, 512, wire="fp32")
        kt.publish_blob(store, "t/kv", h, blob)
        h2, blob2 = kt.fetch_blob(store, "t/kv")
        assert blob2 == blob and h2["n_tokens"] == 512
        kt.delete_blob(store, "t/kv")
        with pytest.raises(TimeoutError):
            kt.fetch_blob(store, "t/kv", timeout=0.05)
    finally:
        store.close()


@pytest.mark.faults
def test_wire_guard_bitflipped_scale_fails_loud():
    """The acceptance contract: a flipped block-scale bit between
    encode and the wire must fail the decode LOUDLY — corrupted KV
    never installs as plausible pages."""
    rs = np.random.RandomState(3)
    k = rs.randn(2, 1, 4, 128, 8).astype(np.float32)
    v = rs.randn(2, 1, 4, 128, 8).astype(np.float32)
    # flip the exponent MSB of the first block scale (fp32 high byte,
    # bit 6): the scale leaves the amax envelope by ~2^128
    with faults.inject("kv_transfer.payload", "bitflip", offset=3,
                       bit=6):
        h, blob = kt.encode_kv_pages(k.copy(), v.copy(), 128,
                                     wire="int8")
    with pytest.raises(RuntimeError, match="scale-integrity"):
        kt.decode_kv_pages(h, blob)
    # strict=False: the poison surfaces as NaN pages (the engine's own
    # non-finite eviction path) instead of a raise
    kp, vp = kt.decode_kv_pages(h, blob, strict=False)
    assert np.all(np.isnan(kp))


@pytest.mark.faults
def test_wire_guard_payload_flip_bounded_not_detected():
    """A flipped PAYLOAD byte is a valid in-envelope code the guard
    cannot distinguish — its damage is bounded by the block's own
    scale (the PR 7 contract, same here)."""
    rs = np.random.RandomState(4)
    k = rs.randn(2, 1, 4, 128, 8).astype(np.float32)
    v = rs.randn(2, 1, 4, 128, 8).astype(np.float32)
    clean_h, clean = kt.encode_kv_pages(k.copy(), v.copy(), 128,
                                        wire="int8")
    with faults.inject("kv_transfer.payload", "bitflip", offset=7,
                       bit=6, target="payload"):
        h, blob = kt.encode_kv_pages(k.copy(), v.copy(), 128,
                                     wire="int8")
    assert blob != clean
    k2, v2 = kt.decode_kv_pages(h, blob)      # no raise
    kc, vc = kt.decode_kv_pages(clean_h, clean)
    # damage bounded: one element moved, by at most 2*qmax*scale
    diff = np.abs(k2.astype(np.float64) - kc.astype(np.float64))
    assert np.count_nonzero(diff) <= 1
    assert float(diff.max()) <= 2.0 * h["amax"]["k"] + 1e-6


# ---------------------------------------------------------------------------
# prefill→transfer→decode handoff
# ---------------------------------------------------------------------------

def test_disagg_fp32_wire_bit_identical():
    """Acceptance: decode output on a disaggregated request is
    BIT-identical to same-replica serving with the fp32 KV wire —
    across page-boundary prompt lengths, an eos stop, and a budget-1
    request (which finishes on the prefill replica)."""
    model = _model()
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n))
               for n in (7, 128, 130, 256, 300)]

    ref = _engine(model)
    refs = [ref.submit(p, max_new_tokens=12, eos_id=2)
            for p in prompts]
    ref.run()

    pe = _engine(model, prefill_only=True)
    de = _engine(model)
    outs = []
    for p in prompts:
        meta, k, v = _prefill_one(pe, p)
        h, blob = kt.encode_kv_pages(k, v, meta["n_tokens"],
                                     wire="fp32")
        k2, v2 = kt.decode_kv_pages(h, blob)
        outs.append(de.submit_handoff(meta, k2, v2))
    de.run()
    for a, b in zip(refs, outs):
        assert a.tokens == b.tokens
        assert b.error is None

    # budget-1: retires at the prefill harvest; no handoff phase
    pe2 = _engine(model, prefill_only=True)
    r1 = pe2.submit(prompts[0], max_new_tokens=1)
    while not r1.done:
        pe2.step()
    pe2.drain()
    ref1 = _engine(model).submit(prompts[0], max_new_tokens=1)
    e = _engine(model)
    r2 = e.submit(prompts[0], max_new_tokens=1)
    e.run()
    assert r1.tokens == r2.tokens and len(r1.tokens) == 1


def test_disagg_int8_wire_bounded_and_serves():
    """The int8 wire: installed pool pages stay within the block
    half-step of the exact pages, the transfer compresses ≥3.5x, and
    decode completes the full budget."""
    model = _model()
    rs = np.random.RandomState(5)
    prompt = list(rs.randint(0, 96, size=300))
    pe = _engine(model, prefill_only=True)
    meta, k, v = _prefill_one(pe, prompt)
    h, blob = kt.encode_kv_pages(k.copy(), v.copy(),
                                 meta["n_tokens"], wire="int8")
    assert h["bytes_logical"] / h["bytes_wire"] >= 3.5
    kq, vq = kt.decode_kv_pages(h, blob)
    kz = k.copy()
    kz[:, -1, :, 300 % 128:, :] = 0
    assert float(np.max(np.abs(kq.astype(np.float32) - kz))) <= \
        0.5 * h["amax"]["k"] / h["qmax"] + 1e-6
    de = _engine(model)
    r = de.submit_handoff(meta, kq, vq)
    de.run()
    assert r.error is None and len(r.tokens) == 12


def test_handoff_rides_frontend_queue_and_streams():
    """FrontEnd.submit_handoff: the handoff waits for a slot like any
    admission, streams through on_token, and retires via on_retire."""
    model = _model()
    pe = _engine(model, prefill_only=True)
    rs = np.random.RandomState(6)
    prompt = list(rs.randint(0, 96, size=40))
    meta, k, v = _prefill_one(pe, prompt, max_new_tokens=6)
    fe = FrontEnd(_engine(model))
    sreq = fe.submit_handoff(meta, k, v)
    got = list(sreq.stream())
    assert sreq.status == "done"
    assert got == sreq.tokens and len(got) == 6


# ---------------------------------------------------------------------------
# fleet prefix directory lifecycle
# ---------------------------------------------------------------------------

def _fleet_pair(store, model):
    a = _engine(model, max_slots=2)
    a.attach_fleet(FleetPrefixDirectory(store, "A", wire="fp32"))
    b = _engine(model, max_slots=2)
    b.attach_fleet(FleetPrefixDirectory(store, "B", wire="fp32"))
    return a, b


def test_fleet_cross_replica_hit_serves_suffix_only():
    """A warm prefix registered on replica A is hit from replica B:
    B fetches A's published pages, prefills ONLY the suffix
    (serve/fleet_prefix_hit_tokens > 0 and the local hit counter shows
    the adopted pages), and produces A's exact tokens."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        model = _model()
        a, b = _fleet_pair(store, model)
        rs = np.random.RandomState(7)
        prompt = list(rs.randint(0, 96, size=300))   # 2 full pages
        stats.reset("serve/fleet")
        ra = a.submit(prompt, max_new_tokens=8)
        a.run()
        assert stats.get("serve/fleet_prefix_published") == 2
        stats.reset("serve/fleet")
        stats.reset("serve/prefix_")
        rb = b.submit(prompt, max_new_tokens=8)
        b.run()
        assert rb.tokens == ra.tokens
        assert stats.get("serve/fleet_prefix_lookup") >= 1
        assert stats.get("serve/fleet_prefix_hit_tokens") == 256
        # the adopted pages made it a LOCAL suffix-only prefill
        assert stats.get("serve/prefix_hit_tokens") == 256
    finally:
        store.close()


def test_fleet_eviction_invalidates_fleet_wide():
    """LRU reclaim on the owning replica withdraws the digests; a new
    replica's lookup misses (cold prefill, same output)."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        model = _model()
        a, b = _fleet_pair(store, model)
        rs = np.random.RandomState(8)
        prompt = list(rs.randint(0, 96, size=300))
        ra = a.submit(prompt, max_new_tokens=8)
        a.run()
        w0 = stats.get("serve/fleet_prefix_withdrawn")
        assert a._prefix.reclaim(100) == 2
        assert stats.get("serve/fleet_prefix_withdrawn") - w0 == 2
        stats.reset("serve/fleet_prefix_hit_tokens")
        rb = b.submit(prompt, max_new_tokens=8)
        b.run()
        assert stats.get("serve/fleet_prefix_hit_tokens") == 0
        assert rb.tokens == ra.tokens      # cold prefill, same math
    finally:
        store.close()


@pytest.mark.faults
def test_fleet_poison_invalidates_before_remap():
    """Non-finite eviction on the owning replica drops the local trie
    nodes AND withdraws fleet-wide — a later submit on another replica
    must prefill cold (never map the poisoned pages)."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        model = _model()
        a, b = _fleet_pair(store, model)
        rs = np.random.RandomState(9)
        prompt = list(rs.randint(0, 96, size=300))
        ra = a.submit(prompt, max_new_tokens=8)
        a.run()
        assert stats.get("serve/fleet_prefix_published") >= 2
        # second submit on A shares the pages, then goes non-finite
        w0 = stats.get("serve/fleet_prefix_withdrawn")
        with faults.inject("engine.poison_logits", "nan", slot=0):
            r2 = a.submit(prompt, max_new_tokens=8)
            a.run()
        assert r2.failed
        assert stats.get("serve/fleet_prefix_withdrawn") - w0 >= 2
        stats.reset("serve/fleet_prefix_hit_tokens")
        rb = b.submit(prompt, max_new_tokens=8)
        b.run()
        assert stats.get("serve/fleet_prefix_hit_tokens") == 0
        assert rb.tokens == ra.tokens
    finally:
        store.close()


def test_fleet_extend_revives_stale_descendant():
    """Reclaim drops one trie node and leaves its CHILDREN canonical-
    but-unreachable; when the missing parent is refetched from the
    fleet, the surviving child page must resume service locally
    (adopt on a still-canonical digest was a replica-killing
    KeyError). Only the refetched parent counts as a fleet hit."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        model = _model()
        a, b = _fleet_pair(store, model)
        rs = np.random.RandomState(15)
        prompt = list(rs.randint(0, 96, size=300))   # 2 full pages
        ra = a.submit(prompt, max_new_tokens=8)
        a.run()
        rb = b.submit(prompt, max_new_tokens=8)      # adopts both
        b.run()
        # LRU-oldest refcount-zero page on B is the PARENT (table
        # order); reclaiming exactly one leaves the child stale
        assert b._prefix.reclaim(1) == 1
        stats.reset("serve/fleet_prefix_hit_tokens")
        r2 = b.submit(prompt, max_new_tokens=8)
        b.run()
        assert r2.tokens == ra.tokens == rb.tokens
        # one page refetched from the fleet, one revived locally
        assert stats.get("serve/fleet_prefix_hit_tokens") == 128
        assert stats.get("serve/prefix_hit_tokens") >= 256
    finally:
        store.close()


def test_fleet_fetch_discards_on_mid_fetch_withdraw(monkeypatch):
    """The invalidation-vs-fetch race: a withdraw landing between the
    payload read and the entry re-check makes the fetch a MISS — no
    sharer can install a page whose invalidation already committed."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        owner = FleetPrefixDirectory(store, "own", wire="fp32")
        reader = FleetPrefixDirectory(store, "rdr", wire="fp32")
        k = np.zeros((2, 1, 4, 128, 8), np.float32)
        digest = b"\x01" * 20
        owner.publish(digest, k, k)
        assert reader.fetch(digest) is not None
        orig = kt.fetch_blob

        def race(store_, key, timeout=5.0):
            out = orig(store_, key, timeout=timeout)
            owner.withdraw(digest)          # lands mid-fetch
            return out

        monkeypatch.setattr(kt, "fetch_blob", race)
        assert reader.fetch(digest) is None
    finally:
        store.close()


def test_fleet_lease_defers_chunk_delete():
    """An outstanding fetch lease keeps the payload chunks readable
    through a withdraw (the entry vanishes immediately — no NEW
    fetchers — but the in-flight read completes before discarding)."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        owner = FleetPrefixDirectory(store, "own", wire="fp32")
        k = np.zeros((2, 1, 4, 128, 8), np.float32)
        digest = b"\x02" * 20
        owner.publish(digest, k, k)
        gen = owner._published[digest]
        store.add(f"fleetpfx/l/{digest.hex()}", 1)   # fetcher mid-read
        owner.withdraw(digest)
        # entry gone, payload still readable for the leased reader
        with pytest.raises(TimeoutError):
            store.get(owner._ekey(digest), timeout=0.05)
        kt.fetch_blob(store, owner._pkey(digest, gen), timeout=0.5)
    finally:
        store.close()


def test_handoff_geometry_mismatch_rejected_at_submit():
    """A handoff from a differently-configured fleet must fail at
    submit time (ValueError the serve loop turns into a per-request
    result) — NOT as a shape error inside a later engine.step() that
    would kill the replica and its other in-flight requests. A
    mismatched PAGE SIZE alone is fine since the drain-migration work
    (page-agnostic repack: the rows are identical, only the blocking
    differs) — infeasible means wrong (n_layers, kv_heads, head_dim)
    or fewer rows than ``n_tokens``."""
    model = _model()
    pe = _engine(model, prefill_only=True)
    rs = np.random.RandomState(13)
    meta, k, v = _prefill_one(pe, list(rs.randint(0, 96, size=40)))
    de = _engine(model)
    with pytest.raises(ValueError, match="geometry"):
        # 16 rows < n_tokens=40: the pages cannot hold the state
        de.submit_handoff(meta, k[:, :, :, :16, :], v[:, :, :, :16, :])
    with pytest.raises(ValueError, match="geometry"):
        # kv_heads mismatch
        de.submit_handoff(meta, k[:, :, :2], v[:, :, :2])
    # a smaller sender page size holding every live row is ACCEPTED
    # (repacked into this pool's blocking) and serves to completion
    r2 = de.submit_handoff(dict(meta), k[:, :, :, :64, :],
                           v[:, :, :, :64, :])
    # the engine stays fully serviceable afterwards
    r = de.submit_handoff(meta, k, v)
    de.run()
    assert r2.error is None and len(r2.tokens) == 12
    assert r.error is None and len(r.tokens) == 12


def test_lossy_wire_pages_never_republished():
    """Pages installed from an int8/fp8 wire serve and share locally
    but are NEVER re-published under the original content digest —
    re-quantizing quantized KV would compound the half-step error
    across hops without bound. fp32-wire pages stay publishable."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        model = _model()
        pe = _engine(model, prefill_only=True)
        rs = np.random.RandomState(14)
        prompt = list(rs.randint(0, 96, size=300))
        meta, k, v = _prefill_one(pe, prompt)
        for wire, want_published in (("int8", 0), ("fp32", 2)):
            h, blob = kt.encode_kv_pages(k.copy(), v.copy(),
                                         meta["n_tokens"], wire=wire)
            kq, vq = kt.decode_kv_pages(h, blob)
            de = _engine(model)
            de.attach_fleet(FleetPrefixDirectory(
                store, f"dc-{wire}", wire=wire))
            stats.reset("serve/fleet_prefix_published")
            r = de.submit_handoff(dict(meta, wire=wire), kq, vq)
            de.run()
            assert r.error is None
            assert stats.get("serve/fleet_prefix_published") == \
                want_published, wire
            # cleanup so the fp32 round starts from an empty directory
            for dg in list(de.fleet._published):
                de.fleet.withdraw(dg)
    finally:
        store.close()


def test_handoff_failed_result_is_rerouted_not_terminal():
    """A decode replica that cannot fetch the handoff blob publishes
    'handoff-failed'; the router re-places the request from scratch
    instead of surfacing a terminal rejection."""
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.router import _publish
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        router = Router(store=store)
        d = router.directory
        d.announce("p0", {"role": "prefill", "page": 128,
                          "max_bucket": 512})
        d.announce("d0", {"role": "decode", "page": 128,
                          "max_bucket": 512})
        router.directory.alive = lambda rid, dead_after=0: True
        q = router.submit([1] * 200, max_new_tokens=4)
        assert router._assigned[q] == "p0"
        _publish(store, "p0", q, {"id": q, "tokens": [],
                                  "status": "prefill-done",
                                  "error": None, "replica": "p0"})
        router.poll()
        assert router._assigned[q] == "d0"
        _publish(store, "d0", q, {"id": q, "tokens": [],
                                  "status": "handoff-failed",
                                  "error": "meta timed out",
                                  "replica": "d0"})
        router.poll()
        # NOT terminal: re-placed (prefill tier again, from scratch)
        assert q not in router.results
        assert router._assigned[q] == "p0"
        assert stats.get("serve/router_handoff_retries") >= 1
    finally:
        store.close()


# ---------------------------------------------------------------------------
# FrontEnd TTFT-EMA cold start (satellite)
# ---------------------------------------------------------------------------

def test_hopeless_cold_start_seeds_from_projection():
    """Before any TTFT observation the hopeless screen judges against
    projected_ttft of the smallest covering bucket: a generous
    deadline is admitted (no spurious cold reject), an impossible one
    is rejected for free (the old cold-start bypass let it reach
    prefill and be evicted mid-flight)."""
    from paddle_tpu.serving.scheduler import projected_ttft
    model = _model()
    # hopeless_factor scales the bar: 100x the cold projection
    # (~0.26s here) lets the doomed deadline be generous enough
    # (0.05s) that it cannot EXPIRE in the submit->feed gap under
    # suite load — the hopeless screen, not the expiry sweep, must
    # reject it (the distinction this satellite exists for)
    fe = FrontEnd(_engine(model), hopeless_factor=100.0)
    assert fe._ttft_ema is None
    rs = np.random.RandomState(10)
    prompt = list(rs.randint(0, 96, size=20))
    # direction 1: generous deadline, cold -> served, never rejected
    ok = fe.submit(prompt, max_new_tokens=4, deadline_s=30.0)
    # direction 2: below the scaled projection, cold -> hopeless, zero
    # device work (rejected at the queue->engine boundary)
    floor = projected_ttft(fe.engine, 20, 32)
    assert 0.05 < 100.0 * floor < 30.0
    h0 = stats.get("serve/queue_hopeless_rejects")
    bad = fe.submit(prompt, max_new_tokens=4, deadline_s=0.05)
    fe.run()
    assert ok.status == "done" and len(ok.tokens) == 4
    assert bad.status == "rejected-deadline"
    assert "projected TTFT" in bad.error
    assert stats.get("serve/queue_hopeless_rejects") - h0 == 1
    # once observations exist, the EMA takes over
    assert fe._ttft_ema is not None
    assert fe._ttft_estimate(ok) == fe._ttft_ema


# ---------------------------------------------------------------------------
# membership load gauges (satellite)
# ---------------------------------------------------------------------------

def test_heartbeat_refreshes_load_gauges():
    from paddle_tpu.distributed.membership import ReplicaDirectory
    from paddle_tpu.serving.disagg import replica_load
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        rep = ReplicaDirectory(store)
        obs = ReplicaDirectory(store)
        rep.announce("r0", {"role": "decode", "page": 128})
        assert obs.load("r0") is None
        eng = _engine(_model(), max_slots=2)
        rep.heartbeat("r0", load=replica_load(eng, "decode", queued=3))
        load = obs.load("r0")
        assert load["role"] == "decode" and load["queued"] == 3
        assert load["free_pages"] == 32 and load["kv_bytes"] == 0
        r = eng.submit(list(range(1, 200)), max_new_tokens=4)
        eng.step()
        rep.heartbeat("r0", load=replica_load(eng, "decode"))
        assert obs.load("r0")["kv_bytes"] > 0
        eng.run()
        assert r.tokens
    finally:
        store.close()


# ---------------------------------------------------------------------------
# role-aware router placement (in-process)
# ---------------------------------------------------------------------------

def test_router_role_aware_placement_and_handoff_phase():
    """Placement policy without processes: prefill goes to the fitting
    least-queued prefill replica; a prefill-done result moves the
    request to the decode replica with the least outstanding KV bytes;
    with no prefill replica the request falls back to whole-request
    serving on a decode replica."""
    from paddle_tpu.serving import Router
    from paddle_tpu.serving.router import _publish
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        router = Router(store=store)
        d = router.directory
        d.announce("p0", {"role": "prefill", "page": 128,
                          "max_bucket": 512})
        d.announce("p1", {"role": "prefill", "page": 128,
                          "max_bucket": 128})
        d.announce("d0", {"role": "decode", "page": 128,
                          "max_bucket": 512})
        d.announce("d1", {"role": "decode", "page": 128,
                          "max_bucket": 512})
        router.directory.alive = lambda rid, dead_after=0: True
        d.heartbeat("p0", load={"role": "prefill", "queued": 5})
        d.heartbeat("p1", load={"role": "prefill", "queued": 0})
        d.heartbeat("d0", load={"role": "decode", "kv_bytes": 999,
                                "free_pages": 10})
        d.heartbeat("d1", load={"role": "decode", "kv_bytes": 1,
                                "free_pages": 40})
        # short prompt fits p1 (least queued); long prompt only p0
        q_short = router.submit([1] * 50, max_new_tokens=4)
        q_long = router.submit([1] * 200, max_new_tokens=4)
        assert router._assigned[q_short] == "p1"
        assert router._assigned[q_long] == "p0"
        assert router._phase[q_short] == "prefill"
        # prefill-done -> decode phase on the least-KV-bytes replica
        _publish(store, "p1", q_short, {
            "id": q_short, "tokens": [], "status": "prefill-done",
            "error": None, "replica": "p1"})
        router.poll()
        assert router._phase[q_short] == "decode"
        assert router._assigned[q_short] == "d1"
        n = native.decode_counter(store.get("serve/mbox_n/d1"))
        msg = json.loads(store.get(f"serve/mbox/d1/{n}"))
        assert msg["kind"] == "handoff" and msg["id"] == q_short
        assert stats.get("serve/router_prefill_handoffs") >= 1
        # prefill tier gone -> whole-request fallback on decode
        router.directory.alive = \
            lambda rid, dead_after=0: rid.startswith("d")
        q_fb = router.submit([1] * 50, max_new_tokens=4)
        # fallback is the PR 9 least-outstanding policy: d0 has no
        # router-tracked in-flight work, d1 holds the handoff
        assert router._assigned[q_fb] == "d0"
        assert router._phase[q_fb] == "serve"
        n = native.decode_counter(store.get("serve/mbox_n/d0"))
        msg = json.loads(store.get(f"serve/mbox/d0/{n}"))
        assert msg["kind"] == "req" and msg["prompt"] == [1] * 50
    finally:
        store.close()


# ---------------------------------------------------------------------------
# real replica processes (launch CLI) — round trip + SIGKILL acceptance
# ---------------------------------------------------------------------------

pytestmark_proc = pytest.mark.skipif(
    not native.is_available(), reason="native TCPStore unavailable")


def _spawn(store_port, rid, role, launch_port, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_KV_WIRE="fp32")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         WORKER, str(store_port), rid, role],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _cleanup(router, procs):
    router.shutdown()
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)
    router.close()


def _reference_tokens(prompts, budgets):
    """Single-replica serving of the identical workload — the
    bit-identity oracle (same model builder as the workers)."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import _disagg_worker
    eng = PagedDecodeEngine(_disagg_worker.build_model(), n_pages=48,
                            max_slots=2, page_size=128)
    fe = FrontEnd(eng)
    reqs = [fe.submit(p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    fe.run()
    return [r.tokens for r in reqs]


@pytestmark_proc
def test_disagg_router_round_trip_bit_identical():
    """Acceptance: one prefill + one decode replica serve a mixed
    workload through the role-aware router; every stream is
    bit-identical to single-replica serving on the fp32 wire, and the
    decode phase actually ran on the decode replica (handoffs
    counted)."""
    from paddle_tpu.serving import Router
    stats.reset("serve/router")
    router = Router(port=0, dead_after=15.0)
    procs = [_spawn(router.store.port, "pf0", "prefill", 8895),
             _spawn(router.store.port, "dc0", "decode", 8896)]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(11)
        prompts = [list(rs.randint(0, 96, size=n))
                   for n in (9, 40, 140, 260)]
        budgets = [6, 5, 7, 6]
        ids = [router.submit(p, max_new_tokens=b)
               for p, b in zip(prompts, budgets)]
        results = router.drain(timeout=180)
        assert sorted(results) == sorted(ids)
        assert all(results[q]["status"] == "done" for q in ids)
        # decode ran on the decode replica
        assert {results[q]["replica"] for q in ids} == {"dc0"}
        assert stats.get("serve/router_prefill_handoffs") == len(ids)
        got = [results[q]["tokens"] for q in ids]
        assert got == _reference_tokens(prompts, budgets)
    finally:
        _cleanup(router, procs)


@pytestmark_proc
def test_disagg_prefill_death_reroutes_clean(tmp_path):
    """Acceptance: SIGKILL the only prefill replica with requests
    outstanding — every request id still completes (the router
    degrades them to whole-request serving on the decode replica,
    which stays clean), nothing lost. The victim runs TRACED with a
    fast periodic flush (ISSUE 13): the dead prefill replica's last
    flushed spans must survive the SIGKILL and still stitch by
    request id."""
    from paddle_tpu.serving import Router
    stats.reset("serve/router")
    victim_trace = str(tmp_path / "trace_pf0.json")
    router = Router(port=0, dead_after=2.5)
    procs = [_spawn(router.store.port, "pf0", "prefill", 8897,
                    extra_env={"FLEETOBS_TRACE_FILE": victim_trace,
                               "PT_TRACE_FLUSH_S": "0.2"}),
             _spawn(router.store.port, "dc0", "decode", 8898)]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(12)
        # wave A: served normally — proves the victim admitted (and
        # flushed) request-tagged spans before dying, the thing the
        # periodic flush exists to save
        ids = [router.submit(list(rs.randint(0, 96, size=150)),
                             max_new_tokens=16) for _ in range(6)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll()
            if stats.get("serve/router_prefill_handoffs") > 0:
                break
            time.sleep(0.02)
        assert stats.get("serve/router_prefill_handoffs") > 0, \
            "victim never prefilled anything"
        time.sleep(0.4)       # one flush period past the admissions
        # freeze the victim, then land wave B on it while its
        # heartbeat still looks alive: those requests are GUARANTEED
        # unfinished at the kill, so the death sweep always has
        # orphans to redistribute. (The old fixed-sleep kill raced box
        # speed: a fast victim finished every prefill before the
        # SIGKILL landed and the sweep had nothing to redistribute.)
        victim_pid = router.directory.members()["pf0"]["pid"]
        os.kill(victim_pid, signal.SIGSTOP)
        ids += [router.submit(list(rs.randint(0, 96, size=150)),
                              max_new_tokens=16) for _ in range(6)]
        assert any(router._assigned[q] == "pf0" for q in ids), \
            "no request was ever placed on the prefill replica"
        os.kill(victim_pid, signal.SIGKILL)
        results = router.drain(timeout=180)
        assert sorted(results) == sorted(ids)
        assert all(r["status"] == "done" for r in results.values())
        # everything that completed, completed on the survivor
        assert {r["replica"] for r in results.values()} == {"dc0"}
        assert stats.get("serve/router_redistributed") > 0
    finally:
        _cleanup(router, procs)
    from _fleetobs import assert_flushed_trace_stitches
    assert_flushed_trace_stitches(victim_trace, ids)
