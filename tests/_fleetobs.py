"""Shared fleet-observability test plumbing (tests + tools/smokes):
the per-replica trace-env translation the worker scripts run before
importing paddle_tpu, and the flushed-trace-stitches assertion the
SIGKILL acceptance tests share."""

import json
import os


def adopt_replica_trace_env():
    """Translate FLEETOBS_TRACE_FILE -> PT_TRACE_FILE. The test/smoke
    sets the non-PT name in the launch env so ONLY the worker traces —
    the launcher inherits the same env, and with PT_TRACE_FILE set its
    own atexit export would clobber the worker's file. Must run BEFORE
    ``import paddle_tpu`` (trace._init_from_env reads the env at
    import)."""
    tf = os.environ.get("FLEETOBS_TRACE_FILE")
    if tf:
        os.environ["PT_TRACE_FILE"] = tf


def assert_flushed_trace_stitches(path, req_ids):
    """The SIGKILLed replica's periodically-flushed trace file must
    exist, be a complete (atomically rewritten) JSON document with
    spans, and stitch by request id against the run's ids."""
    from paddle_tpu.observability import merge
    assert os.path.exists(path), \
        f"SIGKILLed replica left no flushed trace file at {path}"
    with open(path) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert spans, "flushed trace carries no spans"
    summary = merge.request_segments(spans)
    assert set(summary) & set(req_ids), \
        "no request id from this run stitches out of the dead " \
        "replica's flushed spans"
    return summary
