"""Weight-only int8 PTQ (VERDICT r2 item 7; ref slim/quantization
post_training_quantization.py): quantized serving must track the float
model closely (cosine similarity of logits) and run the full generate/
Predictor paths transparently."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import quantization as quant
from paddle_tpu.models import gpt


def _cos(a, b):
    a = np.asarray(a, np.float64).reshape(-1)
    b = np.asarray(b, np.float64).reshape(-1)
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


def _model():
    cfg = gpt.GPTConfig(vocab_size=256, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def test_quant_tensor_roundtrip():
    w = jnp.asarray(np.random.RandomState(0).randn(64, 128), jnp.float32)
    qt = quant.quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 128)
    deq = np.asarray(qt.dequantize())
    # per-channel absmax: error bounded by scale/2 per element
    bound = np.asarray(qt.scale) * 0.5 + 1e-6
    assert (np.abs(deq - np.asarray(w)) <= bound).all()
    # array protocol
    x = jnp.ones((4, 64), jnp.float32)
    np.testing.assert_allclose(np.asarray(x @ qt), np.asarray(x @ deq),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(qt.T), deq.T, rtol=1e-6)
    assert qt.shape == (64, 128) and qt.ndim == 2


def test_quantized_model_logits_cosine():
    model = _model()
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 256, (2, 32)), jnp.int32)
    ref = model(tokens)
    qmodel = quant.quantize_for_inference(model, min_size=256)
    qp, _ = qmodel.split_params()
    assert any(isinstance(v, quant.QuantTensor) for v in qp.values())
    # embeddings stay float (lookup semantics)
    assert not isinstance(qp["wte"], quant.QuantTensor)
    out = qmodel(tokens)
    assert _cos(out, ref) > 0.999, _cos(out, ref)


def test_quantized_generate_matches_float_greedy():
    model = _model()
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, 256, (2, 8)), jnp.int32)
    ref = np.asarray(model.generate(tokens, max_new_tokens=8))
    qmodel = quant.quantize_for_inference(model, min_size=256)
    out = np.asarray(qmodel.generate(tokens, max_new_tokens=8))
    # greedy decode over a near-identical distribution: most GENERATED
    # tokens equal (prompt excluded — it is verbatim in both)
    agree = (out[:, 8:] == ref[:, 8:]).mean()
    assert agree >= 0.8, agree


def test_quantized_predictor_runs():
    from paddle_tpu.inference import Predictor
    model = _model()
    qmodel = quant.quantize_for_inference(model, min_size=256)
    pred = Predictor(lambda t: qmodel(t), batch_size=2)
    toks = np.random.RandomState(3).randint(0, 256, (5, 16)).astype(np.int32)
    out = pred.run(toks)
    assert out.shape == (5, 16, 256)


def test_dequantize_params_roundtrip():
    model = _model()
    qmodel = quant.quantize_for_inference(model, min_size=256)
    qp, _ = qmodel.split_params()
    deq = quant.dequantize_params(qp)
    assert all(not isinstance(v, quant.QuantTensor) for v in deq.values())
    fp, _ = model.split_params()
    for k in fp:
        assert deq[k].shape == fp[k].shape


def test_include_regex_and_empty_error():
    import pytest
    model = _model()
    qmodel = quant.quantize_for_inference(model, include=r"wqkv$")
    qp, _ = qmodel.split_params()
    assert isinstance(qp["blocks.item_0.wqkv"], quant.QuantTensor)
    assert not isinstance(qp["blocks.item_0.wup"], quant.QuantTensor)
    with pytest.raises(ValueError, match="no weight"):
        quant.quantize_for_inference(model, include=r"nomatch_xyz")


def test_rmatmul_dispatch_not_bypassed():
    """review r3: without __jax_array__ jax defers, so x @ qt must reach
    QuantTensor.__rmatmul__ (the Pallas int8 route on TPU)."""
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu import quantization as quant
    w = jnp.asarray(np.random.RandomState(0).normal(size=(32, 16)),
                    jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).normal(size=(4, 32)),
                    jnp.float32)
    qt = quant.quantize_tensor(w)
    called = {}
    orig = quant.QuantTensor.__rmatmul__
    try:
        def spy(self, other):
            called["hit"] = True
            return orig(self, other)
        quant.QuantTensor.__rmatmul__ = spy
        out = x @ qt
    finally:
        quant.QuantTensor.__rmatmul__ = orig
    assert called.get("hit"), "x @ QuantTensor bypassed __rmatmul__"
    np.testing.assert_allclose(out, x @ qt.dequantize(), atol=1e-5)
