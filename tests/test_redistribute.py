"""Live in-HBM resharding (ISSUE 16): distributed/redistribute.py
lowers (old mesh/layout -> new mesh/layout) pairs into transfer
schedules executed on LIVE arrays — bit-identical to the checkpoint
round trip (save -> load_resharded) it replaces, which stays wired as
both the fallback and the parity oracle. Chaos at the
``redistribute.schedule`` site must degrade loudly to that fallback,
never corrupt train state."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as optim
from paddle_tpu import stats
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.distributed import redistribute as redist
from paddle_tpu.distributed.checkpoint import load_resharded, name_leaves
from paddle_tpu.models import gpt
from paddle_tpu.testing import faults


def _cfg():
    return gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                         n_layers=3, n_heads=2, dtype=jnp.float32)


def _train_state(model, mesh, stacked, n_steps=1):
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
    params, opt_state = gpt.init_train_state(model, opt, mesh,
                                             stacked=stacked)
    step = gpt.build_train_step(model, opt, mesh)
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 16)), jnp.int32)
    for i in range(n_steps):
        params, opt_state, _ = step(params, opt_state, toks,
                                    jax.random.PRNGKey(i))
    return {"params": params, "opt_state": opt_state}


def _template(model, mesh, stacked):
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
    p, s = gpt.init_train_state(model, opt, mesh, stacked=stacked)
    return {"params": p, "opt_state": s}


def _leaves(state):
    return {n: np.asarray(v) for n, v in name_leaves(state).items()
            if hasattr(v, "shape")}


def _assert_bitwise(state_a, state_b):
    a, b = _leaves(state_a), _leaves(state_b)
    assert set(a) == set(b), set(a) ^ set(b)
    for n in a:
        np.testing.assert_array_equal(a[n], b[n], err_msg=n)


def _mesh(**kw):
    n = 1
    for v in kw.values():
        n *= v
    return mesh_lib.init_mesh(devices=jax.devices()[:n], **kw)


@pytest.fixture(autouse=True)
def _clean_topology():
    prev = mesh_lib.get_topology()
    mesh_lib.set_topology(None)
    faults.clear()
    yield
    faults.clear()
    mesh_lib.set_topology(prev)


def test_redistribute_chain_matches_checkpoint_oracle(tmp_path):
    """fsdp4(stacked) -> tp2(per-layer) -> single-chip(stacked): every
    hop moves the LIVE state in HBM; a parallel checkpoint round trip
    of the same hop is the bit-parity oracle."""
    model = gpt.GPT(_cfg(), seed=0)
    topo_a = _mesh(fsdp=4)
    state_a = _train_state(model, topo_a.mesh, stacked=True)
    ckpt.save_state(state_a, str(tmp_path / "a"))

    # hop 1: fsdp4 stacked -> tp2 per-layer
    mesh_lib.set_topology(None)
    topo_b = _mesh(tp=2)
    plan = redist.plan_redistribute(state_a, _template(
        model, topo_b.mesh, stacked=False), mesh=topo_b.mesh)
    names = {t.name for t in plan}
    assert any(t.op == "all-to-all" for t in plan), plan[:4]
    assert any(t.layout == "unstack" for t in plan), plan[:4]
    # every weight leaf of the target is covered by the schedule
    tmpl = _template(model, topo_b.mesh, stacked=False)
    assert names == set(_leaves(tmpl))

    live_b = redist.redistribute(state_a, tmpl, mesh=topo_b.mesh)
    oracle_b = load_resharded(str(tmp_path / "a"),
                              _template(model, topo_b.mesh,
                                        stacked=False))
    _assert_bitwise(live_b, oracle_b)
    # target shardings honored on the live path too
    assert len(live_b["params"]["blocks.item_0.wqkv"]
               .sharding.device_set) == 2
    ckpt.save_state(oracle_b, str(tmp_path / "b"))

    # hop 2: tp2 per-layer -> single-chip stacked, from the LIVE result
    mesh_lib.set_topology(None)
    live_c = redist.redistribute(live_b,
                                 _template(model, None, stacked=True))
    oracle_c = load_resharded(str(tmp_path / "b"),
                              _template(model, None, stacked=True))
    _assert_bitwise(live_c, oracle_c)
    # the step counter rode the whole chain
    assert int(live_c["opt_state"]["step"]) == int(
        state_a["opt_state"]["step"])

    # resumed training stays finite on the final layout
    opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
    gpt.init_train_state(model, opt, stacked=True)
    step = gpt.build_train_step(model, opt)
    toks = jnp.asarray(
        np.random.RandomState(2).randint(0, 128, (4, 16)), jnp.int32)
    _, _, loss = step(live_c["params"], live_c["opt_state"], toks,
                      jax.random.PRNGKey(9))
    assert np.isfinite(float(loss))


def test_redistribute_per_layer_stacked_roundtrip():
    """Pure layout conversion (no mesh): per-layer -> stacked ->
    per-layer returns the original bits."""
    model = gpt.GPT(_cfg(), seed=0)
    state = _train_state(model, None, stacked=False)
    stacked = redist.redistribute(state,
                                  _template(model, None, stacked=True))
    back = redist.redistribute(stacked,
                               _template(model, None, stacked=False))
    for name, v in _leaves(state).items():
        np.testing.assert_array_equal(v, _leaves(back)[name],
                                      err_msg=name)


def test_plan_unprovable_source_raises():
    """A source missing a layer is an unprovable plan: the planner (and
    the mover) raise RedistributeError naming the gap — the caller's
    cue to degrade to the checkpoint path."""
    model = gpt.GPT(_cfg(), seed=0)
    state = _train_state(model, None, stacked=False)
    state["params"] = {k: v for k, v in state["params"].items()
                      if not k.startswith("blocks.item_2.")}
    state["opt_state"]["slots"] = {
        k: v for k, v in state["opt_state"]["slots"].items()
        if not k.startswith("blocks.item_2.")}
    tmpl = _template(model, None, stacked=True)
    with pytest.raises(redist.RedistributeError, match="lacks layers"):
        redist.plan_redistribute(state, tmpl)
    with pytest.raises(redist.RedistributeError, match="lacks layers"):
        redist.redistribute(state, tmpl)


def test_redistribute_chaos_raise_and_bitflip():
    """Both fault shapes at the documented ``redistribute.schedule``
    site fail LOUDLY: a raise at plan time surfaces as-is, an
    in-transit bitflip trips the PT_RESHARD_VERIFY digest — and the
    source state is intact after either failure."""
    model = gpt.GPT(_cfg(), seed=0)
    state = _train_state(model, None, stacked=True)
    before = _leaves(state)
    tmpl = _template(model, None, stacked=False)

    with faults.inject("redistribute.schedule", "raise"):
        with pytest.raises(TimeoutError):
            redist.redistribute(state, tmpl)

    # index 0 is the plan-time fire; leaf k is transform index k
    with faults.inject("redistribute.schedule", "bitflip", after=1,
                       count=1):
        with pytest.raises(redist.RedistributeError,
                           match="digest mismatch"):
            redist.redistribute(state, tmpl)

    for n, v in _leaves(state).items():
        np.testing.assert_array_equal(v, before[n], err_msg=n)


def _run_trainer(tmp_path, tag, n_epochs=4, reshape_at=1, target=2):
    """One ElasticTrainer run on 4 virtual devices that requests a
    same-process reshape to ``target`` devices after ``reshape_at``."""
    from paddle_tpu.fleet import ElasticTrainer, plan_topology
    from paddle_tpu.fleet.elastic_train import synthetic_data
    cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    trainer = ElasticTrainer(
        gpt.GPT(cfg, seed=0), optim.SGD(learning_rate=0.05),
        str(tmp_path / tag), n_epochs=n_epochs,
        mesh=plan_topology(gpt.GPT(cfg, seed=0), n_devices=4),
        data_fn=synthetic_data(cfg.vocab_size, 12, cfg.max_seq_len))
    trainer.on_epoch = (
        lambda rec: trainer.request_reshape(target)
        if rec["epoch"] == reshape_at else None)
    return trainer.run()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_elastic_reshape_inplace_parity_and_chaos_fallback(
        tmp_path, monkeypatch):
    """The tentpole acceptance: a cooperative 4 -> 2 reshape mid-run
    via the in-HBM path produces the SAME loss trajectory as the
    checkpoint path (both restart from the committed epoch,
    bit-identical state); with chaos injected the trainer degrades to
    the fallback — same trajectory, fleet/reshard_fallbacks counted."""
    stats.reset("fleet/")
    recs_inplace = _run_trainer(tmp_path, "inplace")
    assert stats.get("fleet/reshard_fallbacks") == 0
    assert stats.snapshot("fleet/").get(
        "fleet/reshard_inplace_s.count", 0) >= 1
    assert [r["devices"] for r in recs_inplace] == [4, 4, 2, 2]

    stats.reset("fleet/")
    monkeypatch.setenv("PT_RESHARD_INPLACE", "0")
    recs_ckpt = _run_trainer(tmp_path, "ckpt")
    monkeypatch.delenv("PT_RESHARD_INPLACE")
    assert [r["devices"] for r in recs_ckpt] == [4, 4, 2, 2]
    for a, b in zip(recs_inplace, recs_ckpt):
        assert abs(a["loss"] - b["loss"]) < 1e-6, (a, b)

    stats.reset("fleet/")
    with faults.inject("redistribute.schedule", "raise"):
        recs_chaos = _run_trainer(tmp_path, "chaos")
    assert stats.get("fleet/reshard_fallbacks") >= 1
    assert [r["devices"] for r in recs_chaos] == [4, 4, 2, 2]
    for a, b in zip(recs_inplace, recs_chaos):
        assert abs(a["loss"] - b["loss"]) < 1e-6, (a, b)
