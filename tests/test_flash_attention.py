"""Flash-attention Pallas kernel vs the XLA reference path.

Mirrors the reference's fused-attention op tests
(python/paddle/fluid/tests/unittests/test_fused_attention_op.py pattern: a
numpy/naive oracle checked against the fused kernel for output AND grads).
Runs in Pallas interpret mode on the CPU test platform.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn.functional.attention import attention_reference
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _rand_qkv(b, s, h, d, dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.normal(size=(b, s, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 64), (2, 256, 2, 32)])
def test_forward_matches_reference(causal, shape):
    q, k, v = _rand_qkv(*shape)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = attention_reference(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_unpadded_seq():
    # seq not a multiple of the block: exercises KV-padding masking
    q, k, v = _rand_qkv(1, 100, 2, 64, seed=3)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_cross_attention_different_kv_len():
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.normal(size=(1, 64, 2, 64)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(1, 200, 2, 64)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(1, 200, 2, 64)), jnp.float32)
    out = flash_attention(q, k, v, causal=False, interpret=True)
    ref = attention_reference(q, k, v, is_causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = _rand_qkv(1, 128, 2, 64, seed=1)
    cot = jnp.asarray(np.random.RandomState(2).normal(size=q.shape),
                      jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, is_causal=causal) * cot)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(gf, gr, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_grads_unpadded_seq():
    q, k, v = _rand_qkv(1, 100, 1, 32, seed=4)
    cot = jnp.asarray(np.random.RandomState(5).normal(size=q.shape),
                      jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=True, interpret=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        attention_reference(*a, is_causal=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


def test_bfloat16_forward():
    q, k, v = _rand_qkv(1, 128, 2, 64, dtype=jnp.bfloat16, seed=6)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, is_causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_jit_compiles():
    q, k, v = _rand_qkv(1, 128, 1, 64, seed=8)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                interpret=True))
    out = f(q, k, v)
    assert out.shape == q.shape
