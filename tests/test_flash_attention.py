"""Flash-attention Pallas kernel vs the XLA reference path.

Mirrors the reference's fused-attention op tests
(python/paddle/fluid/tests/unittests/test_fused_attention_op.py pattern: a
numpy/naive oracle checked against the fused kernel for output AND grads).
Runs in Pallas interpret mode on the CPU test platform.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn.functional.attention import attention_reference
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _rand_qkv(b, s, h, d, dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.normal(size=(b, s, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 64), (2, 256, 2, 32)])
def test_forward_matches_reference(causal, shape):
    q, k, v = _rand_qkv(*shape)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = attention_reference(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_unpadded_seq():
    # seq not a multiple of the block: exercises KV-padding masking
    q, k, v = _rand_qkv(1, 100, 2, 64, seed=3)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_cross_attention_different_kv_len():
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.normal(size=(1, 64, 2, 64)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(1, 200, 2, 64)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(1, 200, 2, 64)), jnp.float32)
    out = flash_attention(q, k, v, causal=False, interpret=True)
    ref = attention_reference(q, k, v, is_causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = _rand_qkv(1, 128, 2, 64, seed=1)
    cot = jnp.asarray(np.random.RandomState(2).normal(size=q.shape),
                      jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, is_causal=causal) * cot)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(gf, gr, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_grads_unpadded_seq():
    q, k, v = _rand_qkv(1, 100, 1, 32, seed=4)
    cot = jnp.asarray(np.random.RandomState(5).normal(size=q.shape),
                      jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=True, interpret=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        attention_reference(*a, is_causal=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


def test_bfloat16_forward():
    q, k, v = _rand_qkv(1, 128, 2, 64, dtype=jnp.bfloat16, seed=6)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, is_causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_jit_compiles():
    q, k, v = _rand_qkv(1, 128, 1, 64, seed=8)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                interpret=True))
    out = f(q, k, v)
    assert out.shape == q.shape


# ---------------------------------------------------------------------------
# v2: kv_lens padding masks, additive bias, deterministic dropout, GQA
# ---------------------------------------------------------------------------


def _padding_bias(kv_lens, sk):
    """(B,) lengths -> additive (B, 1, 1, Sk) -inf mask for the oracle."""
    col = np.arange(sk)[None, :]
    mask = col < np.asarray(kv_lens)[:, None]
    return jnp.asarray(np.where(mask, 0.0, -1e30)[:, None, None, :],
                       jnp.float32)


def test_kv_lens_padding_mask():
    q, k, v = _rand_qkv(3, 160, 2, 64, seed=10)
    kv_lens = jnp.asarray([160, 90, 17], jnp.int32)
    out = flash_attention(q, k, v, kv_lens=kv_lens, interpret=True)
    ref = attention_reference(q, k, v, mask=_padding_bias(kv_lens, 160))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_kv_lens_grads():
    q, k, v = _rand_qkv(2, 128, 2, 32, seed=11)
    kv_lens = jnp.asarray([128, 50], jnp.int32)
    cot = jnp.asarray(np.random.RandomState(12).normal(size=q.shape),
                      jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, kv_lens=kv_lens, interpret=True) * cot), argnums=(0, 1, 2))(
        q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(attention_reference(
        *a, mask=_padding_bias(kv_lens, 128)) * cot), argnums=(0, 1, 2))(
        q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("bias_shape", [(1, 1, 128, 128), (2, 1, 128, 128),
                                        (1, 2, 128, 128), (2, 2, 128, 128)])
def test_additive_bias_broadcast_modes(bias_shape):
    q, k, v = _rand_qkv(2, 128, 2, 32, seed=13)
    bias = jnp.asarray(
        np.random.RandomState(14).normal(size=bias_shape), jnp.float32)
    out = flash_attention(q, k, v, bias=bias, interpret=True)
    ref = attention_reference(q, k, v, mask=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bias_with_causal_and_grads():
    q, k, v = _rand_qkv(1, 128, 2, 32, seed=15)
    bias = jnp.asarray(
        np.random.RandomState(16).normal(size=(1, 2, 128, 128)),
        jnp.float32)
    cot = jnp.asarray(np.random.RandomState(17).normal(size=q.shape),
                      jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, bias=bias, interpret=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(attention_reference(
        *a, is_causal=True, mask=bias) * cot), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("h_q,h_kv", [(4, 2), (4, 1)])
def test_gqa_forward_and_grads(h_q, h_kv):
    rs = np.random.RandomState(18)
    b, s, d = 2, 128, 32
    q = jnp.asarray(rs.normal(size=(b, s, h_q, d)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(b, s, h_kv, d)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(b, s, h_kv, d)), jnp.float32)
    group = h_q // h_kv
    k_rep = jnp.repeat(k, group, axis=2)
    v_rep = jnp.repeat(v, group, axis=2)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k_rep, v_rep, is_causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    cot = jnp.asarray(rs.normal(size=out.shape), jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, interpret=True) * cot), argnums=(0, 1, 2))(q, k, v)

    def ref_loss(q, k, v):
        kr = jnp.repeat(k, group, axis=2)
        vr = jnp.repeat(v, group, axis=2)
        return jnp.sum(attention_reference(q, kr, vr, is_causal=True) * cot)

    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_dropout_deterministic_and_unbiased():
    q, k, v = _rand_qkv(1, 128, 2, 32, seed=19)
    o1 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=42,
                         interpret=True)
    o2 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=42,
                         interpret=True)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=43,
                         interpret=True)
    assert np.abs(np.asarray(o1) - np.asarray(o3)).max() > 1e-4
    # E[dropout(attn)] == attn: mean over many seeds approaches no-dropout
    outs = [flash_attention(q, k, v, dropout_p=0.3, dropout_seed=s,
                            interpret=True) for s in range(24)]
    mean = np.mean([np.asarray(o, np.float64) for o in outs], axis=0)
    base = np.asarray(flash_attention(q, k, v, interpret=True), np.float64)
    assert np.abs(mean - base).mean() < 0.05


def test_dropout_grads_finite_and_match_mask():
    """Backward regenerates the identical keep mask: grads of sum(out)
    computed with dropout must be finite and differ from no-dropout."""
    q, k, v = _rand_qkv(1, 128, 1, 32, seed=20)
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, dropout_p=0.25, dropout_seed=7, interpret=True)))(q)
    assert np.isfinite(np.asarray(g)).all()
    g0 = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, interpret=True)))(q)
    assert np.abs(np.asarray(g) - np.asarray(g0)).max() > 1e-6


def test_dropout_seed_traced_no_retrace():
    """Seed is a traced scalar: changing it must not retrigger compilation
    (the training loop changes it every step)."""
    q, k, v = _rand_qkv(1, 128, 1, 32, seed=21)
    calls = []

    @jax.jit
    def f(q, k, v, seed):
        calls.append(1)
        return flash_attention(q, k, v, dropout_p=0.1, dropout_seed=seed,
                               interpret=True)

    f(q, k, v, jnp.int32(1))
    f(q, k, v, jnp.int32(2))
    assert len(calls) == 1


def test_kvlen_zero_row_no_nan():
    q, k, v = _rand_qkv(2, 128, 1, 32, seed=22)
    kv_lens = jnp.asarray([128, 0], jnp.int32)
    out = flash_attention(q, k, v, kv_lens=kv_lens, interpret=True)
    assert np.isfinite(np.asarray(out[0])).all()
    np.testing.assert_array_equal(np.asarray(out[1]), 0.0)
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, kv_lens=kv_lens, interpret=True)))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_key_only_bias_not_materialized():
    """(B,1,1,Sk) key-padding bias: correct results, and the jaxpr must not
    contain a broadcast to (B, 1, Sq, Sk)."""
    q, k, v = _rand_qkv(2, 128, 2, 32, seed=23)
    bias = jnp.asarray(
        np.where(np.arange(128) < 70, 0.0, -1e30)[None, None, None, :],
        jnp.float32)
    out = flash_attention(q, k, v, bias=bias, interpret=True)
    ref = attention_reference(q, k, v, mask=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # grads through the sq1 bias path
    cot = jnp.asarray(np.random.RandomState(24).normal(size=q.shape),
                      jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, bias=bias, interpret=True) * cot), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(attention_reference(
        *a, mask=bias) * cot), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)
    # the full (B, H, Sq, Sk) tensor must not appear in the lowered HLO
    txt = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, bias=bias, interpret=True)).lower(q, k, v).as_text()
    assert "2x2x128x128" not in txt and "1x1x128x128" not in txt


def test_sdpa_fallback_honors_kv_lens():
    """scaled_dot_product_attention must apply kv_lens on the XLA fallback
    path too (CPU here), not only in the Pallas kernel."""
    from paddle_tpu.nn.functional.attention import (
        scaled_dot_product_attention)
    q, k, v = _rand_qkv(2, 64, 2, 32, seed=25)
    kv_lens = jnp.asarray([64, 20], jnp.int32)
    out = scaled_dot_product_attention(q, k, v, kv_lens=kv_lens)
    ref = attention_reference(q, k, v, mask=_padding_bias(kv_lens, 64))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
