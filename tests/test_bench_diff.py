"""bench_diff regression sentinel (ISSUE 15): direction-aware
row-by-row comparison of BENCH snapshots — improvements pass,
regressions fail by name, vanished rows fail (the r05
RESOURCE_EXHAUSTED signature), schema mismatches refuse to compare,
and the checked-in r05 snapshot self-diffs clean.
"""

import copy
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_diff", os.path.join(REPO, "tools", "bench_diff.py"))
bd = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bd)


def _doc(**extra):
    base = {"decode_engine_tokens_per_sec": 1000.0,
            "decode_engine_paged_tokens_per_sec": 400.0,
            "step_ms": 50.0,
            "decode_batch": 8}
    base.update(extra)
    return {"metric": "gpt_tokens_per_sec", "value": 100.0,
            "unit": "tokens/s", "vs_baseline": 1.0, "extra": base}


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


# -- verdict classes ----------------------------------------------------------

def test_within_noise_is_clean():
    new = _doc(decode_engine_tokens_per_sec=1030.0, step_ms=51.0)
    v = bd.compare(_doc(), new)
    assert v["regressions"] == [] and v["improvements"] == []
    assert any(r == "decode_engine_tokens_per_sec"
               for r, _ in v["within_noise"])


def test_tok_s_regression_named():
    new = _doc(decode_engine_tokens_per_sec=800.0)   # -20%
    v = bd.compare(_doc(), new)
    rows = [r for r, _ in v["regressions"]]
    assert rows == ["decode_engine_tokens_per_sec"]


def test_tok_s_improvement_passes():
    v = bd.compare(_doc(), _doc(decode_engine_tokens_per_sec=1300.0))
    assert v["regressions"] == []
    assert any(r == "decode_engine_tokens_per_sec"
               for r, _ in v["improvements"])


def test_ms_direction_inverted():
    assert [r for r, _ in
            bd.compare(_doc(), _doc(step_ms=70.0))["regressions"]] \
        == ["step_ms"]
    assert [r for r, _ in
            bd.compare(_doc(), _doc(step_ms=30.0))["improvements"]] \
        == ["step_ms"]


def test_missing_numeric_row_is_regression():
    new = _doc()
    del new["extra"]["decode_engine_tokens_per_sec"]
    v = bd.compare(_doc(), new)
    assert any(r == "decode_engine_tokens_per_sec"
               and "vanished" in d for r, d in v["regressions"])


def test_row_died_with_error_marker_is_regression():
    """A SECTION marker (decode_engine_error) must be attributed to the
    longer-named rows it killed — the exact r05 signature."""
    new = _doc()
    del new["extra"]["decode_engine_tokens_per_sec"]
    new["extra"]["decode_engine_error"] = "RESOURCE_EXHAUSTED: boom"
    v = bd.compare(_doc(), new)
    hits = [(r, d) for r, d in v["regressions"]
            if r == "decode_engine_tokens_per_sec"]
    assert hits and "row died" in hits[0][1] \
        and "RESOURCE_EXHAUSTED" in hits[0][1]


def test_zero_baseline_micro_drift_within_noise():
    """An exactly-0.0 baseline row (overlap's pinned exposed_s) that
    drifts by micro-units must not read as an infinite regression —
    but a real regrowth past atol still fails."""
    v = bd.compare(_doc(train_overlap_exposed_s=0.0),
                   _doc(train_overlap_exposed_s=1e-7))
    assert v["regressions"] == []
    v = bd.compare(_doc(train_overlap_exposed_s=0.0),
                   _doc(train_overlap_exposed_s=0.002))
    assert any(r == "train_overlap_exposed_s"
               for r, _ in v["regressions"])


def test_missing_informational_row_not_regression():
    new = _doc()
    del new["extra"]["decode_batch"]
    v = bd.compare(_doc(), new)
    assert v["regressions"] == []
    assert any(r == "decode_batch" for r, _ in v["missing"])


def test_new_rows_reported_never_failed():
    v = bd.compare(_doc(), _doc(brand_new_tokens_per_sec=10.0))
    assert v["regressions"] == []
    assert any(r == "brand_new_tokens_per_sec" for r, _ in v["added"])


def test_goodput_dip_is_lower_is_better():
    """The drain bench's goodput_dip_frac row embeds the "goodput"
    fragment but measures a COST — a bigger dip must regress, a
    smaller one improve (ISSUE 16 direction tagging)."""
    assert bd.direction("fleet_churn_drain_goodput_dip_frac") == -1
    v = bd.compare(_doc(fleet_churn_drain_goodput_dip_frac=0.10),
                   _doc(fleet_churn_drain_goodput_dip_frac=0.40))
    assert any(r == "fleet_churn_drain_goodput_dip_frac"
               for r, _ in v["regressions"])
    v = bd.compare(_doc(fleet_churn_drain_goodput_dip_frac=0.40),
                   _doc(fleet_churn_drain_goodput_dip_frac=0.10))
    assert v["regressions"] == []
    # ...while plain goodput rows keep their higher-is-better sense
    assert bd.direction("fleet_churn_drain_goodput_tokens_per_sec") == 1
    # fault-path counters introduced by the live-reshard/drain paths
    assert bd.direction("fleet_reshard_fallbacks") == -1
    assert bd.direction("serve_drain_migrate_failed") == -1


def test_launch_rows_are_lower_is_better():
    """The kernel-launch accounting rows (ISSUE 19): launches per
    token/step guard the single-dispatch megakernel — MORE launches is
    a regression (a fall back to one-launch-per-layer), fewer is the
    win. The row name must not be swallowed by the higher-is-better
    token fragments."""
    assert bd.direction("decode_engine_paged_launches_per_token") == -1
    assert bd.direction("decode_spec_paged_launches_per_step") == -1
    v = bd.compare(_doc(decode_engine_paged_launches_per_step=2.0),
                   _doc(decode_engine_paged_launches_per_step=24.0))
    assert any(r == "decode_engine_paged_launches_per_step"
               for r, _ in v["regressions"])
    v = bd.compare(_doc(decode_engine_paged_launches_per_step=24.0),
                   _doc(decode_engine_paged_launches_per_step=2.0))
    assert v["regressions"] == []


def test_spec_paged_row_death_guarded_by_name():
    """The revived paged-spec bench row must die LOUDLY: a vanished
    decode_spec_paged_* row with its section error marker (the r05
    RESOURCE_EXHAUSTED signature) is a named regression, never a
    silent drop."""
    base = _doc(decode_spec_paged_tokens_per_sec=900.0)
    new = _doc()
    new["extra"]["decode_spec_paged_error"] = "RESOURCE_EXHAUSTED: oom"
    v = bd.compare(base, new)
    hits = [(r, d) for r, d in v["regressions"]
            if r == "decode_spec_paged_tokens_per_sec"]
    assert hits and "RESOURCE_EXHAUSTED" in hits[0][1]


def test_failover_rows_direction_tagged():
    """The router-failover bench rows (ISSUE 17): recovery time is a
    cost, republished-result counts are informational (they scale
    with where the kill lands, not with quality), and the failover
    goodput/dip rows inherit the drain phase's tagging."""
    assert bd.direction("fleet_churn_failover_recovery_s") == -1
    v = bd.compare(_doc(fleet_churn_failover_recovery_s=0.01),
                   _doc(fleet_churn_failover_recovery_s=0.50))
    assert any(r == "fleet_churn_failover_recovery_s"
               for r, _ in v["regressions"])
    v = bd.compare(_doc(fleet_churn_failover_recovery_s=0.50),
                   _doc(fleet_churn_failover_recovery_s=0.01))
    assert v["regressions"] == []
    assert bd.direction("fleet_churn_failover_republished") == 0
    v = bd.compare(_doc(fleet_churn_failover_republished=6),
                   _doc(fleet_churn_failover_republished=0))
    assert v["regressions"] == []
    assert bd.direction(
        "fleet_churn_failover_goodput_tokens_per_sec") == 1
    assert bd.direction("fleet_churn_failover_goodput_dip_frac") == -1


def test_noise_table_widens_p99():
    # 20% swing on a p99 row sits inside the 25% noise band...
    v = bd.compare(_doc(serve_p99_ttft_ms=100.0),
                   _doc(serve_p99_ttft_ms=120.0))
    assert v["regressions"] == []
    # ...but a 40% swing does not
    v = bd.compare(_doc(serve_p99_ttft_ms=100.0),
                   _doc(serve_p99_ttft_ms=140.0))
    assert any(r == "serve_p99_ttft_ms" for r, _ in v["regressions"])


# -- schema / CLI -------------------------------------------------------------

def test_schema_mismatch_exits_2(tmp_path, capsys):
    a = _write(tmp_path, "a.json", _doc())
    other = _doc()
    other["metric"] = "bert_tokens_per_sec"
    b = _write(tmp_path, "b.json", other)
    assert bd.main([a, b]) == 2
    assert "not comparable" in capsys.readouterr().err


def test_provenance_schema_version_mismatch_exits_2(tmp_path):
    da, db = _doc(), _doc()
    da["provenance"] = {"schema_version": 1}
    db["provenance"] = {"schema_version": 2}
    a = _write(tmp_path, "a.json", da)
    b = _write(tmp_path, "b.json", db)
    assert bd.main([a, b]) == 2


def test_cli_regression_exit_1_names_row(tmp_path, capsys):
    a = _write(tmp_path, "a.json", _doc())
    b = _write(tmp_path, "b.json",
               _doc(decode_engine_tokens_per_sec=800.0))
    assert bd.main([a, b]) == 1
    assert "decode_engine_tokens_per_sec" in capsys.readouterr().out


def test_cli_unreadable_input_exits_2(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    assert bd.main([str(p), str(p)]) == 2
    q = tmp_path / "shape.json"
    q.write_text(json.dumps({"rows": []}))
    assert bd.main([str(q), str(q)]) == 2


def test_driver_wrapper_shape_accepted(tmp_path):
    wrapped = {"n": 5, "cmd": "python bench.py", "rc": 0, "tail": "",
               "parsed": _doc()}
    a = _write(tmp_path, "a.json", wrapped)
    b = _write(tmp_path, "b.json", _doc())
    assert bd.main([a, b]) == 0


def test_checked_in_r05_self_diff_clean(capsys):
    path = os.path.join(REPO, "BENCH_r05.json")
    assert bd.main([path, path]) == 0
    assert "clean" in capsys.readouterr().out


def test_selftest_catches_synthetic_regression(tmp_path, capsys):
    a = _write(tmp_path, "a.json", _doc())
    assert bd.main(["--selftest", a]) == 0
    assert "caught" in capsys.readouterr().out
    # and the harness itself: a maimed copy really exits 1
    wounded = copy.deepcopy(_doc())
    wounded["extra"]["decode_engine_tokens_per_sec"] *= 0.8
    b = _write(tmp_path, "b.json", wounded)
    assert bd.main([a, b]) == 1


def test_paged_flip_report():
    lines = bd.paged_flip_report(_doc())   # 1000/400 = 2.5x
    assert lines and "2.50x" in lines[0] and "not yet" in lines[0]
    ok = bd.paged_flip_report(
        _doc(decode_engine_paged_tokens_per_sec=900.0))
    assert ok and "PASS" in ok[0]
    assert bd.paged_flip_report({"extra": {}}) == []
