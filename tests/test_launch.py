"""Launcher CLI + elastic tests, driven through real subprocesses — the
reference's own pattern (test_parallel_dygraph_dataparallel.py:155 shells
out through the launcher; bash_test_modules in unittests/CMakeLists)."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

import numpy as np

import paddle_tpu.distributed.launch as launch_mod
from paddle_tpu import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(args, script_body, tmp_path, name="train.py"):
    script = tmp_path / name
    script.write_text(textwrap.dedent(script_body))
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         *args, str(script)],
        env=env, capture_output=True, text=True, timeout=120)


def test_launch_sets_env_contract(tmp_path):
    body = f"""
    import os
    rank = os.environ["PT_PROCESS_ID"]
    with open(r"{tmp_path}/rank_" + rank, "w") as f:
        f.write(":".join([os.environ["PT_NUM_PROCESSES"],
                          os.environ["PT_LOCAL_RANK"],
                          os.environ["PT_COORDINATOR"],
                          os.environ["PT_NNODES"]]))
    """
    r = _run_launch(["--nproc_per_node", "2", "--master", "127.0.0.1:7777"],
                    body, tmp_path)
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "rank_0").read_text() == "2:0:127.0.0.1:7777:1"
    assert (tmp_path / "rank_1").read_text() == "2:1:127.0.0.1:7777:1"


def test_launch_node_rank_offsets_global_rank(tmp_path):
    body = f"""
    import os
    with open(r"{tmp_path}/g_" + os.environ["PT_LOCAL_RANK"], "w") as f:
        f.write(os.environ["PT_PROCESS_ID"])
    """
    r = _run_launch(["--nproc_per_node", "2", "--nnodes", "2",
                     "--node_rank", "1"], body, tmp_path)
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "g_0").read_text() == "2"
    assert (tmp_path / "g_1").read_text() == "3"


def test_launch_propagates_failure_exit_code(tmp_path):
    body = """
    import os, sys
    sys.exit(3 if os.environ["PT_PROCESS_ID"] == "1" else 0)
    """
    r = _run_launch(["--nproc_per_node", "2"], body, tmp_path)
    assert r.returncode == 3, (r.returncode, r.stderr)


def test_launch_elastic_restart_recovers(tmp_path):
    body = f"""
    import os, sys
    marker = r"{tmp_path}/attempted"
    if not os.path.exists(marker):
        open(marker, "w").close()
        sys.exit(101)   # fail the first attempt
    open(r"{tmp_path}/ok_" + os.environ["PT_PROCESS_ID"], "w").close()
    """
    r = _run_launch(["--nproc_per_node", "2", "--max_restarts", "1"],
                    body, tmp_path)
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "ok_0").exists() and (tmp_path / "ok_1").exists()
    assert "restart 1/1" in r.stderr


def test_launch_writes_worker_logs(tmp_path):
    body = """
    import os
    print("hello from rank", os.environ["PT_PROCESS_ID"], flush=True)
    """
    r = _run_launch(["--nproc_per_node", "2", "--log_dir",
                     str(tmp_path / "logs")], body, tmp_path)
    assert r.returncode == 0, r.stderr
    assert "rank 0" in (tmp_path / "logs" / "workerlog.0").read_text()
    assert "rank 1" in (tmp_path / "logs" / "workerlog.1").read_text()


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_elastic_manager_detects_dead_peer():
    from paddle_tpu.distributed.elastic import ElasticManager
    master = native.TCPStore(is_master=True)
    try:
        s0 = native.TCPStore(port=master.port)
        s1 = native.TCPStore(port=master.port)
        events = []
        m0 = ElasticManager(s0, rank=0, world_size=2, ttl=1.0,
                            interval=0.1,
                            on_change=lambda dead: events.append(dead))
        m1 = ElasticManager(s1, rank=1, world_size=2, ttl=1.0, interval=0.1)
        m0.start()
        m1.start()
        time.sleep(0.5)
        assert events == []  # both alive
        m1.stop()            # rank 1 "dies" (heartbeat stops)
        deadline = time.time() + 5
        while not events and time.time() < deadline:
            time.sleep(0.1)
        assert events and events[0] == [1]
        m0.stop()
        s0.close()
        s1.close()
    finally:
        master.close()


def test_check_nan_inf_sweep():
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.debug import check_nan_inf, nan_inf_stats

    clean = {"a": jnp.ones((3,)), "b": (jnp.zeros((2,)), jnp.ones(()))}
    assert check_nan_inf(clean) is clean
    stats = nan_inf_stats({"x": jnp.asarray([1.0, np.nan, np.inf])})
    assert int(stats["x"]) == 2
    with pytest.raises(FloatingPointError, match="bad.*non-finite"):
        check_nan_inf({"bad": jnp.asarray([np.nan]), "ok": jnp.ones(2)})

    # hapi integration via the flag
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.nn.module import Parameter

    class Blowup(nn.Module):
        def __init__(self):
            super().__init__()
            self.w = Parameter(jnp.asarray([[np.inf]], jnp.float32))

        def forward(self, x):
            return x @ self.w

    m = pt.Model(Blowup())
    m.prepare(optimizer=optim.SGD(learning_rate=1.0), loss=nn.MSELoss())
    pt.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            m.train_batch([np.ones((2, 1), np.float32)],
                          [np.ones((2, 1), np.float32)])
    finally:
        pt.set_flags({"check_nan_inf": False})


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_elastic_registry_reforms_rank_table():
    """Two 'node launchers' (threads) negotiate a rank table; round 2 has
    one fewer worker on node 1 → table re-forms at world 3 (≙ HTTPMaster /
    ETCDMaster membership, launch/controllers/master.py:66/:178)."""
    from paddle_tpu.distributed.elastic import ElasticRegistry
    import threading

    master_store = native.TCPStore(is_master=True)
    try:
        peer_store = native.TCPStore(port=master_store.port)
        master = ElasticRegistry(master_store, node_rank=0, is_master=True)
        peer = ElasticRegistry(peer_store, node_rank=1)

        results = {}

        def peer_round(version, n):
            peer.publish(version, n)
            results[version] = peer.wait_table(version, timeout=10.0)

        # round 1: 2 + 2 workers
        t = threading.Thread(target=peer_round, args=(1, 2))
        t.start()
        master.publish(1, 2)
        table, world = master.form_table(1, nnodes=2, grace=2.0)
        t.join()
        assert world == 4
        assert table == {0: (0, 2), 1: (2, 2)}
        assert results[1] == (table, 4)

        # round 2: node 1 lost a worker → world 3, contiguous ranks
        t = threading.Thread(target=peer_round, args=(2, 1))
        t.start()
        master.publish(2, 2)
        table2, world2 = master.form_table(2, nnodes=2, grace=2.0)
        t.join()
        assert world2 == 3
        assert table2 == {0: (0, 2), 1: (2, 1)}

        # round 3: node 1 gone entirely (never announces) → dropped after
        # the grace window
        master.publish(3, 2)
        table3, world3 = master.form_table(3, nnodes=2, grace=0.5)
        assert world3 == 2 and 1 not in table3
        peer_store.close()
    finally:
        master_store.close()


def test_checked_jit_catches_in_jit_nan_and_oob():
    """In-jit checkify (VERDICT 5.2: host sweep sees only outputs; this
    catches the producing primitive inside XLA, ≙ nan_inf_utils_detail)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.framework.debug import checked_jit, check_in_jit

    def bad_log(x):
        return jnp.sum(jnp.log(x))  # NaN for negative input

    f = checked_jit(bad_log)
    assert np.isfinite(float(f(jnp.ones(3))))
    with pytest.raises(Exception, match="nan"):
        f(-jnp.ones(3))

    def oob(x, i):
        return x[i]

    g = checked_jit(oob)
    with pytest.raises(Exception, match="out-of-bounds|index"):
        g(jnp.arange(4.0), jnp.int32(9))

    def guarded(x):
        check_in_jit(jnp.all(x > 0), "x must be positive")
        return jnp.sqrt(x)

    from jax.experimental import checkify as _ck
    h = checked_jit(guarded, errors=_ck.user_checks)
    float(h(jnp.ones(2))[0])
    with pytest.raises(Exception, match="positive"):
        h(-jnp.ones(2))
    # under PLAIN jit the guard fails fast at trace time with a pointer
    # to the functionalizing wrapper, instead of silently dropping
    with pytest.raises(ValueError, match="checkify"):
        jax.jit(guarded)(-jnp.ones(2))
