"""Router-hosting worker for the failover acceptance tests
(tests/test_router_failover.py) and tools/ha_smoke.py: one process =
one router GENERATION. The driver SIGKILLs/SIGSTOPs this process and
spawns a successor pointed at the SAME --endpoint-file and --journal;
the successor recovers the intake from the journal, re-places
outstanding work, finishes the deterministic workload, and writes the
final results JSON atomically to --results.

The workload is regenerated from --seed every generation (submission
order IS the request-id sequence), so a successor resumes submitting
exactly where the journal's high-water mark says the dead generation
stopped — request id ``rq-%06d`` maps to the same prompt in every
generation.

Usage:
    python tests/_router_worker.py --endpoint-file EP --journal J \
        --results OUT [--workload N] [--replicas K] [--seed S] \
        [--max-new T] [--interval-ms MS] [--wait-file TOKEN] \
        [--no-shutdown]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def workload_prompts(seed: int, n: int, vocab: int = 90):
    """The deterministic workload: prompt i is the same in every
    process that asks for (seed, n) — the control run, every router
    generation, and the test's own expectations."""
    import numpy as np
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab,
                         size=int(rng.integers(4, 12))).tolist()
            for _ in range(n)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint-file", required=True)
    ap.add_argument("--journal", required=True)
    ap.add_argument("--results", required=True)
    ap.add_argument("--workload", type=int, default=12)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--interval-ms", type=float, default=120.0)
    ap.add_argument("--drain-timeout", type=float, default=150.0)
    ap.add_argument("--wait-file", default=None,
                    help="warm-standby contract: block until this "
                         "token file exists before binding the store")
    ap.add_argument("--no-shutdown", action="store_true",
                    help="leave the replicas running on exit")
    args = ap.parse_args()

    if args.wait_file:
        while not os.path.exists(args.wait_file):
            time.sleep(0.02)

    from paddle_tpu.serving import Router

    router = Router(port=0, dead_after=15.0,
                    endpoint_file=args.endpoint_file,
                    journal=args.journal)
    recovered = router.recover()
    try:
        router.wait_replicas(args.replicas, timeout=90.0)
        prompts = workload_prompts(args.seed, args.workload)
        # the journal restored _seq to the dead generation's high-water
        # mark — resume the submission schedule from there
        for i in range(router._seq, args.workload):
            router.submit(prompts[i], max_new_tokens=args.max_new)
            router.poll()
            time.sleep(args.interval_ms / 1000.0)
        results = router.drain(timeout=args.drain_timeout)
        out = {"generation": router.generation,
               "recovered": recovered,
               "results": results}
        tmp = f"{args.results}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(out, f)
        os.replace(tmp, args.results)
        if not args.no_shutdown:
            router.shutdown()
            # hold the store open until every replica has seen the
            # shutdown key and drained — closing immediately would
            # strand them in partition mode waiting on a successor
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    states = [router.directory.state(rid)
                              for rid in router.directory.members()]
                except Exception:
                    break
                if all(s != "up" for s in states):
                    break
                time.sleep(0.1)
    finally:
        router.close()


if __name__ == "__main__":
    main()
