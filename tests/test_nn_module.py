"""Module system tests: pytree behavior, state_dict, containers, Context."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def test_parameters_and_state_dict():
    m = MLP()
    names = [n for n, _ in m.named_parameters()]
    assert names == ["fc1.bias", "fc1.weight", "fc2.bias", "fc2.weight"]
    sd = m.state_dict()
    assert sd["fc1.weight"].shape == (4, 8)


def test_module_is_pytree():
    m = MLP()
    leaves = jax.tree_util.tree_leaves(m)
    assert len(leaves) == 4
    m2 = jax.tree_util.tree_map(lambda x: x * 0, m)
    assert float(jnp.sum(jnp.abs(m2.fc1.weight))) == 0.0
    # original untouched
    assert float(jnp.sum(jnp.abs(m.fc1.weight))) > 0.0


def test_jit_over_module():
    m = MLP()

    @jax.jit
    def f(model, x):
        return model(x)

    x = jnp.ones((3, 4))
    np.testing.assert_allclose(np.asarray(f(m, x)), np.asarray(m(x)),
                               rtol=1e-6)


def test_split_merge_params():
    m = MLP()
    params, _ = m.split_params()
    params2 = jax.tree_util.tree_map(lambda p: p + 1.0, params)
    m2 = m.merge_params(params2)
    np.testing.assert_allclose(np.asarray(m2.fc1.weight),
                               np.asarray(m.fc1.weight) + 1.0)


def test_grad_through_module():
    m = MLP()
    params, _ = m.split_params()
    x = jnp.ones((5, 4))
    y = jnp.zeros((5,), jnp.int32)

    def loss_fn(p):
        out = m.merge_params(p)(x)
        return nn.functional.cross_entropy(out, y)

    g = jax.grad(loss_fn)(params)
    assert set(g) == set(params)
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())


def test_sequential_and_layerlist():
    s = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    assert len(s) == 3
    out = s(jnp.ones((2, 4)))
    assert out.shape == (2, 2)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(list(ll.named_parameters())) == 6


def test_dropout_training_vs_eval():
    d = nn.Dropout(0.5)
    x = jnp.ones((100, 100))
    with nn.stateful(training=True, rng=jax.random.key(0)):
        out_t = d(x)
    with nn.stateful(training=False):
        out_e = d(x)
    assert float(jnp.mean((out_t == 0).astype(jnp.float32))) > 0.3
    np.testing.assert_array_equal(np.asarray(out_e), np.asarray(x))


def test_batchnorm_context_updates():
    bn = nn.BatchNorm2D(3).tag_paths()
    x = jax.random.normal(jax.random.key(0), (8, 3, 4, 4)) * 2 + 1
    with nn.stateful(training=True) as ctx:
        out = bn(x)
    assert "_mean" in "".join(ctx.updates)
    bn2 = bn.apply_updates(ctx.updates)
    # running mean moved toward batch mean
    assert float(jnp.sum(jnp.abs(bn2._mean))) > 0
    # eval mode uses running stats, no updates recorded
    with nn.stateful(training=False) as ctx2:
        bn2(x)
    assert not ctx2.updates


def test_state_dict_roundtrip(tmp_path):
    m = MLP()
    pt.save(m.state_dict(), str(tmp_path / "m.pdparams"))
    m2 = MLP()
    m2.set_state_dict(pt.load(str(tmp_path / "m.pdparams")))
    np.testing.assert_allclose(np.asarray(m2.fc1.weight),
                               np.asarray(m.fc1.weight))


def test_astype_casts_params():
    m = MLP().astype("bfloat16")
    assert m.fc1.weight.dtype == jnp.bfloat16


def test_per_module_train_eval_mode():
    """Two models in one process hold independent modes (VERDICT r1 weak 7:
    train()/eval() must not flip a process-global)."""
    import numpy as np
    from paddle_tpu import nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.drop = nn.Dropout(0.5)

        def forward(self, x):
            return self.drop(x)

    a, b = Net().train(), Net().eval()
    x = jnp.ones((512,))
    ya = np.asarray(a(x))
    yb = np.asarray(b(x))
    assert (ya == 0).any(), "train-mode model must drop"
    np.testing.assert_array_equal(yb, np.ones((512,)))  # eval: identity
    # flipping one does not affect the other
    a.eval()
    np.testing.assert_array_equal(np.asarray(a(x)), np.ones((512,)))
    b.train()
    assert (np.asarray(b(x)) == 0).any()
    assert a.training is False and b.training is True


def test_static_hash_stable_for_unhashable_attrs():
    from paddle_tpu.nn.module import _Static
    import numpy as np
    a = _Static((("k", [1, 2, 3]), ("m", {"x": 1})))
    b = _Static((("k", [1, 2, 3]), ("m", {"x": 1})))
    assert a == b and hash(a) == hash(b)
    c = _Static((("arr", np.arange(3)),))
    d = _Static((("arr", np.arange(3)),))
    assert c == d and hash(c) == hash(d)
    e = _Static((("arr", np.arange(4)),))
    assert c != e


class TestRound3Layers:
    """The seven classes closing the nn inventory gap (VERDICT r2 §2.3
    'nn 96 vs ~131')."""

    def test_softmax2d(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        x = jnp.asarray(np.random.RandomState(0).normal(size=(2, 3, 4, 4)),
                        jnp.float32)
        out = nn.Softmax2D()(x)
        np.testing.assert_allclose(np.sum(np.asarray(out), axis=1),
                                   np.ones((2, 4, 4)), atol=1e-5)

    def test_pairwise_distance(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        rs = np.random.RandomState(1)
        a = jnp.asarray(rs.normal(size=(5, 8)), jnp.float32)
        b = jnp.asarray(rs.normal(size=(5, 8)), jnp.float32)
        out = nn.PairwiseDistance(p=2.0)(a, b)
        ref = np.linalg.norm(np.asarray(a) - np.asarray(b) + 1e-6, axis=-1)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_max_unpool_1d_3d_roundtrip(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        x = jnp.asarray(np.random.RandomState(2).normal(size=(1, 2, 8)),
                        jnp.float32)
        pooled, idx = F.max_pool1d(x, 2, stride=2, return_mask=True)
        up = nn.MaxUnPool1D(2, stride=2)(pooled, idx)
        assert up.shape == x.shape
        # every pooled max lands back somewhere; scattered values == pooled
        nz = np.asarray(up).ravel()
        nz = nz[nz != 0.0]
        np.testing.assert_allclose(np.sort(nz),
                                   np.sort(np.asarray(pooled).ravel()))
        x3 = jnp.asarray(np.random.RandomState(3).normal(size=(1, 1, 4, 4, 4)),
                         jnp.float32)
        pooled3, idx3 = F.max_pool3d(x3, 2, stride=2, return_mask=True)
        up3 = nn.MaxUnPool3D(2, stride=2)(pooled3, idx3)
        assert up3.shape == x3.shape
        nz3 = np.asarray(up3).ravel()
        nz3 = nz3[nz3 != 0.0]
        np.testing.assert_allclose(np.sort(nz3),
                                   np.sort(np.asarray(pooled3).ravel()))

    def test_multi_margin_loss(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.normal(size=(4, 5)), jnp.float32)
        y = jnp.asarray([0, 2, 4, 1])
        out = nn.MultiMarginLoss()(x, y)
        xn = np.asarray(x)
        ref = 0.0
        for i, t in enumerate([0, 2, 4, 1]):
            ref += np.mean([max(0.0, 1.0 - xn[i, t] + xn[i, j]) if j != t
                            else 0.0 for j in range(5)])
        np.testing.assert_allclose(out, ref / 4, atol=1e-5, rtol=1e-5)

    def test_triplet_margin_with_distance(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        rs = np.random.RandomState(5)
        a = jnp.asarray(rs.normal(size=(6, 8)), jnp.float32)
        p = jnp.asarray(rs.normal(size=(6, 8)), jnp.float32)
        n = jnp.asarray(rs.normal(size=(6, 8)), jnp.float32)
        l1 = nn.TripletMarginWithDistanceLoss()(a, p, n)
        # custom distance callable is honored
        l2 = nn.TripletMarginWithDistanceLoss(
            distance_function=lambda u, v:
                __import__("jax.numpy", fromlist=["sum"]).sum(
                    abs(u - v), axis=-1))(a, p, n)
        assert float(l1) >= 0 and float(l2) >= 0 and float(l1) != float(l2)

    def test_hsigmoid_probabilities_normalize(self):
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        rs = np.random.RandomState(6)
        x = jnp.asarray(rs.normal(size=(3, 6)), jnp.float32)
        layer = nn.HSigmoidLoss(6, 10)
        total = np.zeros((3,), np.float64)
        for c in range(10):
            loss = F.hsigmoid_loss(x, jnp.full((3,), c, jnp.int32), 10,
                                   layer.weight.value
                                   if hasattr(layer.weight, "value")
                                   else layer.weight,
                                   layer.bias if layer.bias is None
                                   else (layer.bias.value
                                         if hasattr(layer.bias, "value")
                                         else layer.bias),
                                   reduction="none")
            total += np.exp(-np.asarray(loss, np.float64))
        np.testing.assert_allclose(total, 1.0, atol=1e-4)
        out = layer(x, jnp.asarray([1, 2, 3]))
        assert np.isfinite(float(out))

    def test_max_unpool_nonzero_padding(self):
        # review r3: int padding must apply to the length dim only
        import jax.numpy as jnp
        import numpy as np
        import paddle_tpu.nn.functional as F
        x = jnp.asarray(np.random.RandomState(7).normal(size=(1, 1, 6)),
                        jnp.float32)
        pooled, idx = F.max_pool1d(x, 2, stride=2, return_mask=True)
        up = F.max_unpool1d(pooled, idx, 2, stride=2, padding=1)
        assert up.shape == (1, 1, 4)  # (3-1)*2 + 2 - 2*1
        x3 = jnp.asarray(
            np.random.RandomState(8).normal(size=(1, 1, 4, 4, 4)),
            jnp.float32)
        p3, i3 = F.max_pool3d(x3, 2, stride=2, return_mask=True)
        up3 = F.max_unpool3d(p3, i3, 2, stride=2, padding=(1, 1, 1))
        assert up3.shape == (1, 1, 2, 2, 2)
