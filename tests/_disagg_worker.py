"""Disaggregated-serving replica worker for tests/test_serve_disagg.py
and tools/disagg_smoke.py: one process = one replica, spawned through
the real ``distributed/launch.py`` CLI, role picked by argv (or
``PT_SERVE_ROLE``). Pins the CPU platform at module level — the
launcher imports this before any jax backend initializes.

Usage (as the launch CLI's training script):
    python -m paddle_tpu.distributed.launch --nproc_per_node 1 \
        tests/_disagg_worker.py STORE_PORT REPLICA_ID ROLE
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# one replica needs one device; conftest's 8-virtual-device XLA_FLAGS
# would leak in through the environment and slow startup
os.environ["XLA_FLAGS"] = " ".join(
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f)
# per-replica trace file for the fleet-observability tests/smoke:
# translated HERE (before the paddle_tpu import) so only the WORKER
# traces
import _fleetobs
_fleetobs.adopt_replica_trace_env()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_model():
    """The ONE model every replica (and the single-replica bit-identity
    reference) builds — weights must agree bit-for-bit fleet-wide."""
    import jax.numpy as jnp
    from paddle_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def main():
    port = int(sys.argv[1])
    rid = sys.argv[2]
    role = sys.argv[3] if len(sys.argv) > 3 else \
        os.environ.get("PT_SERVE_ROLE", "both")
    from paddle_tpu import native
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    from paddle_tpu.serving import FrontEnd
    from paddle_tpu.serving.disagg import (FleetPrefixDirectory,
                                           serve_prefill_replica,
                                           serve_decode_replica,
                                           fleet_enabled)
    from paddle_tpu.testing import faults

    # PT_FAULTS plumbing (the store-partition chaos tests drop this
    # replica's control-plane ops mid-handoff and assert it degrades
    # instead of dying)
    faults.install_from_env()

    model = build_model()
    store = native.TCPStore("127.0.0.1", port)
    try:
        if role == "prefill":
            eng = PagedDecodeEngine(model, n_pages=48, max_slots=2,
                                    page_size=128, prefill_only=True)
            if fleet_enabled():
                eng.attach_fleet(FleetPrefixDirectory(store, rid))
            serve_prefill_replica(store, rid, eng, max_idle_s=120.0)
        else:
            eng = PagedDecodeEngine(model, n_pages=48, max_slots=2,
                                    page_size=128)
            if fleet_enabled():
                eng.attach_fleet(FleetPrefixDirectory(store, rid))
            fe = FrontEnd(eng)
            serve_decode_replica(store, rid, fe, max_idle_s=120.0)
    finally:
        store.close()


if __name__ == "__main__":
    main()
