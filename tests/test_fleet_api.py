"""fleet namespace: init from DistributedStrategy → distributed_model →
distributed_optimizer train step on the 8-device mesh (ref:
test_fleet_base.py / test_fleet_hybrid_* pattern)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 64)
        self.fc2 = nn.Linear(64, 8)

    def forward(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def test_fleet_init_strategy_and_hybrid_group():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 2}
    topo = fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    # dp world in the ZeRO sense: dp x sharding replicas of the params
    assert hcg.get_data_parallel_world_size() == 4
    assert hcg.get_sharding_parallel_world_size() == 2
    assert fleet.worker_num() >= 1 and fleet.is_first_worker()


def test_fleet_dp_minus_one_absorbs_remainder():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": -1, "mp_degree": 4}
    topo = fleet.init(strategy=strategy)
    assert topo.get_data_parallel_world_size() == 2  # 8 devices / tp4


def test_fleet_distributed_model_and_optimizer():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "sharding_degree": 2}
    fleet.init(strategy=strategy)
    net = fleet.distributed_model(Net().tag_paths())
    params, _ = net.split_params()
    # a plain MLP (no repeated blocks) gets ZeRO-style fsdp sharding from
    # the structural planner; tp engages on transformer-shaped models
    assert any("fsdp" in str(p.sharding.spec) for p in params.values())

    opt = fleet.distributed_optimizer(
        pt.optimizer.AdamW(learning_rate=1e-2), strategy)
    state = opt.init(params)
    x = jnp.asarray(np.random.RandomState(0).normal(size=(8, 16)),
                    jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 8, (8,)), jnp.int32)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            return nn.functional.cross_entropy(net.merge_params(p)(x), y)
        loss, g = jax.value_and_grad(loss_fn)(params)
        p2, s2 = opt.update(g, state, params)
        return p2, s2, loss

    losses = []
    for _ in range(4):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_fleet_gradient_merge():
    fleet.init(strategy=fleet.DistributedStrategy())
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2}
    opt = fleet.distributed_optimizer(
        pt.optimizer.SGD(learning_rate=1.0), strategy)
    params = {"w": jnp.asarray([1.0])}
    state = opt.init(params)
    p1, state = opt.update({"w": jnp.asarray([0.5])}, state, params)
    np.testing.assert_allclose(p1["w"], [1.0])  # accumulated, no step
    p2, state = opt.update({"w": jnp.asarray([1.5])}, state, p1)
    np.testing.assert_allclose(p2["w"], [0.0])  # stepped with mean grad 1.0


def test_fleet_gradient_merge_bound_step():
    """review r3: the paddle-style bound step() must honor merge too."""
    fleet.init(strategy=fleet.DistributedStrategy())
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2}
    inner = pt.optimizer.SGD(learning_rate=1.0,
                             parameters={"w": jnp.asarray([1.0])})
    opt = fleet.distributed_optimizer(inner, strategy)
    p1 = opt.step({"w": jnp.asarray([0.5])})
    np.testing.assert_allclose(p1["w"], [1.0])   # accumulated only
    p2 = opt.step({"w": jnp.asarray([1.5])})
    np.testing.assert_allclose(p2["w"], [0.0])   # mean grad 1.0 applied


def test_fleet_utils_localfs(tmp_path):
    fs = fleet.utils.LocalFS()
    d = str(tmp_path / "ckpt")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    fs.touch(d + "/a.txt")
    fs.mv(d + "/a.txt", d + "/b.txt")
    assert fs.is_file(d + "/b.txt")
    dirs, files = fs.ls_dir(d)
    assert files == ["b.txt"]
    assert fs.cat(d + "/b.txt") == b""
    fs.delete(d)
    assert not fs.is_exist(d)
    with pytest.raises(RuntimeError):
        fleet.utils.HDFSClient()


def test_fleet_gradient_merge_under_jit():
    """review r3: merge state must live in the state pytree — a Python
    counter would freeze at trace time and silently stop training."""
    fleet.init(strategy=fleet.DistributedStrategy())
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2}
    opt = fleet.distributed_optimizer(
        pt.optimizer.SGD(learning_rate=1.0), strategy)
    params = {"w": jnp.asarray([0.0])}
    state = opt.init(params)

    @jax.jit
    def step(p, s, g):
        return opt.update({"w": g}, s, p)

    p, state = step(params, state, jnp.asarray([0.5]))
    np.testing.assert_allclose(p["w"], [0.0])      # accumulate
    p, state = step(p, state, jnp.asarray([1.5]))
    np.testing.assert_allclose(p["w"], [-1.0])     # mean 1.0 applied
    p, state = step(p, state, jnp.asarray([1.0]))
    np.testing.assert_allclose(p["w"], [-1.0])     # accumulate again
    p, state = step(p, state, jnp.asarray([3.0]))
    np.testing.assert_allclose(p["w"], [-3.0])     # mean 2.0 applied


def test_fleet_bound_step_checkpoint_restore():
    """review r3: set_state_dict between bound steps must be honored."""
    fleet.init(strategy=fleet.DistributedStrategy())
    inner = pt.optimizer.SGD(learning_rate=1.0,
                             parameters={"w": jnp.asarray([1.0])})
    opt = fleet.distributed_optimizer(inner, fleet.DistributedStrategy())
    opt.step({"w": jnp.asarray([0.25])})
    ckpt = opt.state_dict()
    opt.step({"w": jnp.asarray([0.25])})
    opt.set_state_dict(ckpt)
    opt.step({"w": jnp.asarray([0.0])})
    assert int(opt.state_dict()["state"]["step"]) == 2  # 1 (ckpt) + 1
