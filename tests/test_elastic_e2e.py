"""Elastic membership end-to-end (VERDICT r2 item 5): kill 1 of 4 local
workers → the launcher RE-FORMS the job at world 3 (not a same-size
restart) → rank 0 resumes from AutoCheckpoint through the resharding
loader onto the smaller mesh → loss continues from where it left off.

Reference analog: fleet/elastic/manager.py:128 (etcd membership watch +
relaunch) and launch/controllers/master.py:66 — driven through real
subprocesses like the reference's elastic CLI tests."""

import re
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = """
import json, os, sys, time

rank = int(os.environ["PT_PROCESS_ID"])
world = int(os.environ["PT_NUM_PROCESSES"])
version = int(os.environ["PT_ELASTIC_VERSION"])
workdir = r"{workdir}"
done_file = os.path.join(workdir, "done")
log_file = os.path.join(workdir, "loss_log.jsonl")

if rank != 0:
    # rank 2 dies once while the job is at world 4, after rank 0 has
    # written at least one checkpoint epoch
    if rank == 2 and world == 4:
        for _ in range(600):
            if any(d.startswith("epoch_") for d in
                   os.listdir(os.path.join(workdir, "ckpt", "job"))
                   ) if os.path.isdir(os.path.join(workdir, "ckpt",
                                                   "job")) else False:
                break
            time.sleep(0.1)
        os._exit(3)
    while not os.path.exists(done_file):
        time.sleep(0.2)
    sys.exit(0)

# ---- rank 0: train on a dp=<world> virtual mesh with AutoCheckpoint ----
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count="
                           + str(world))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import AutoCheckpoint
from paddle_tpu import optimizer as optim
from paddle_tpu.models import gpt

topo = dist.init_mesh(dp=world)
cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                    n_layers=2, n_heads=2, dtype=jnp.float32)
model = gpt.GPT(cfg, seed=0)
opt = optim.SGD(learning_rate=0.05)
params, opt_state = gpt.init_train_state(model, opt, topo.mesh)
step = gpt.build_train_step(model, opt, topo.mesh)

ck = AutoCheckpoint(os.path.join(workdir, "ckpt"), job_id="job", keep=3)
# resharding restore: saved under dp=4, loaded directly onto this round's
# dp=world mesh via the fresh state's shardings
fresh = {{"params": params, "opt": opt_state,
          "epoch": jnp.zeros((), jnp.int32)}}
state = ck.restore_like(fresh, mesh=topo.mesh)
if state is not None:
    params, opt_state = state["params"], state["opt"]
    start_epoch = int(state["epoch"]) + 1
else:
    start_epoch = 0

tokens = jnp.asarray(np.random.RandomState(0).randint(
    0, cfg.vocab_size, (12, cfg.max_seq_len)), jnp.int32)
rng = jax.random.PRNGKey(0)
for epoch in range(start_epoch, 6):
    params, opt_state, loss = step(params, opt_state, tokens, rng)
    with open(log_file, "a") as f:
        f.write(json.dumps({{"version": version, "world": world,
                             "epoch": epoch, "loss": float(loss)}}) + "\\n")
    ck.save({{"params": params, "opt": opt_state,
              "epoch": jnp.asarray(epoch, jnp.int32)}}, epoch)

open(done_file, "w").close()
"""


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_kill_worker_reform_smaller_resume(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(SCRIPT.format(workdir=str(tmp_path))))
    env = dict(os.environ, PYTHONPATH=REPO,
               PT_FLAGS_STATS_AT_EXIT="1")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--master", "127.0.0.1:7811",
         "--elastic", "--max_restarts", "2", str(script)],
        env=env, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])

    # §5.5 observability: the launcher's exit dump must carry the re-form
    # counters (VERDICT r4 item 8; ≙ platform/monitor.h scrape)
    assert "[paddle_tpu.stats]" in r.stderr, r.stderr[-2000:]
    m = re.search(r"launch/reforms\s+(\d+)", r.stderr)
    assert m and int(m.group(1)) >= 1, r.stderr[-2000:]
    m = re.search(r"launch/rounds\s+(\d+)", r.stderr)
    assert m and int(m.group(1)) >= 2, r.stderr[-2000:]

    log = [json.loads(line) for line in
           (tmp_path / "loss_log.jsonl").read_text().splitlines()]
    worlds = {e["world"] for e in log}
    assert worlds == {4, 3}, f"expected re-formation 4→3, got {worlds}"
    # round 2 announced by the controller
    assert "elastic round 2: world=3" in r.stderr, r.stderr[-2000:]

    v1 = [e for e in log if e["world"] == 4]
    v2 = [e for e in log if e["world"] == 3]
    assert v1 and v2
    # resumed from checkpoint: epochs continue (no restart from 0) and the
    # loss picks up from the saved optimum, not from scratch
    assert v2[0]["epoch"] == v1[-1]["epoch"] + 1 or \
        v2[0]["epoch"] <= v1[-1]["epoch"]  # last epoch may re-run if the
    # crash landed between save and log append
    first_loss = log[0]["loss"]
    resume_loss = v2[0]["loss"]
    last_pre = v1[-1]["loss"]
    assert resume_loss < first_loss, (resume_loss, first_loss)
    assert resume_loss <= last_pre * 1.10 + 1e-3, (resume_loss, last_pre)
    # training completed all 6 epochs
    assert max(e["epoch"] for e in log) == 5


SCRIPT_GROW = """
import json, os, sys, time

rank = int(os.environ["PT_PROCESS_ID"])
world = int(os.environ["PT_NUM_PROCESSES"])
version = int(os.environ["PT_ELASTIC_VERSION"])
workdir = r"{workdir}"
done_file = os.path.join(workdir, "done")
log_file = os.path.join(workdir, "loss_log.jsonl")

if rank != 0:
    while not os.path.exists(done_file):
        time.sleep(0.2)
    sys.exit(0)

# ---- rank 0: train on a dp=<world> virtual mesh with AutoCheckpoint ----
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count="
                           + str(world))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import AutoCheckpoint
from paddle_tpu import optimizer as optim
from paddle_tpu.models import gpt

topo = dist.init_mesh(dp=world)
cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                    n_layers=2, n_heads=2, dtype=jnp.float32)
model = gpt.GPT(cfg, seed=0)
opt = optim.SGD(learning_rate=0.05)
params, opt_state = gpt.init_train_state(model, opt, topo.mesh)
step = gpt.build_train_step(model, opt, topo.mesh)

ck = AutoCheckpoint(os.path.join(workdir, "ckpt"), job_id="job", keep=3)
fresh = {{"params": params, "opt": opt_state,
          "epoch": jnp.zeros((), jnp.int32)}}
state = ck.restore_like(fresh, mesh=topo.mesh)
if state is not None:
    params, opt_state = state["params"], state["opt"]
    start_epoch = int(state["epoch"]) + 1
else:
    start_epoch = 0

tokens = jnp.asarray(np.random.RandomState(0).randint(
    0, cfg.vocab_size, (12, cfg.max_seq_len)), jnp.int32)
rng = jax.random.PRNGKey(0)
for epoch in range(start_epoch, 8):
    params, opt_state, loss = step(params, opt_state, tokens, rng)
    with open(log_file, "a") as f:
        f.write(json.dumps({{"version": version, "world": world,
                             "epoch": epoch, "loss": float(loss)}}) + "\\n")
    ck.save({{"params": params, "opt": opt_state,
              "epoch": jnp.asarray(epoch, jnp.int32)}}, epoch)
    # at world 2 the job idles after epoch 3 until the joining node's
    # re-form kills this process group — the world-2 run must not finish
    # before the (slow to start) joiner lands; at world 3 run to the end
    while world == 2 and epoch >= 3:
        time.sleep(0.2)

open(done_file, "w").close()
"""


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_join_node_reform_larger_resume(tmp_path):
    """Scale-UP: a 2-worker job re-forms at world 3 when a node JOINS
    (≙ fleet/elastic/manager.py:128 node-join watch), resuming from the
    resharding checkpoint onto the larger mesh."""
    import time

    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(
        SCRIPT_GROW.format(workdir=str(tmp_path))))
    env = dict(os.environ, PYTHONPATH=REPO)
    # pid-derived port: a previous aborted run's orphaned launcher must
    # never squat this run's registry port
    port = 7911 + (os.getpid() % 500) * 2
    base = [sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--master", f"127.0.0.1:{port}", "--elastic",
            "--nnodes", "1:2", "--max_restarts", "2",
            "--elastic_grace", "3"]
    master = joiner = None
    try:
        master = subprocess.Popen(
            base + ["--nproc_per_node", "2", "--node_rank", "0",
                    str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

        # wait until the world-2 job has trained (and checkpointed)
        log_path = tmp_path / "loss_log.jsonl"
        deadline = time.time() + 120
        while time.time() < deadline:
            if log_path.exists() and \
                    len(log_path.read_text().splitlines()) >= 2:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("world-2 training never produced a log")

        joiner = subprocess.Popen(
            base + ["--nproc_per_node", "1", "--node_rank", "1",
                    str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

        m_out, m_err = master.communicate(timeout=300)
        j_out, j_err = joiner.communicate(timeout=60)
    finally:
        # the launcher's children run in their own sessions: on any abort,
        # reap launchers AND their spawned trainers or they hold the port
        for p in (master, joiner):
            if p is not None and p.poll() is None:
                p.kill()
        subprocess.run(["pkill", "-9", "-f", str(script)], check=False)
    assert master.returncode == 0, (master.returncode, m_err[-3000:])
    assert joiner.returncode == 0, (joiner.returncode, j_err[-3000:])

    log = [json.loads(line) for line in
           log_path.read_text().splitlines()]
    worlds = {e["world"] for e in log}
    assert worlds == {2, 3}, f"expected re-formation 2→3, got {worlds}"
    assert "requesting re-form" in j_err, j_err[-2000:]

    v1 = [e for e in log if e["world"] == 2]
    v2 = [e for e in log if e["world"] == 3]
    assert v1 and v2
    # resumed from checkpoint onto the LARGER mesh: epochs continue
    assert v1[-1]["epoch"] >= v2[0]["epoch"] - 1
    assert v2[0]["epoch"] >= 1
    assert v2[0]["loss"] <= log[0]["loss"], (v2[0], log[0])
    assert max(e["epoch"] for e in log) == 7
