"""Training-numerics observability plane (ISSUE 18): in-graph tensor
stats, NaN provenance, quantization-error drift watch.

The load-bearing assertions:

- ONE packed f32 vector per sampled step (`Layout.size` elements), with
  the cadence cond zeroing off-cadence steps in-graph;
- planted `train.grad_poison` faults localize — the provenance header
  names the planted layer AND leaf family — on the plain sharded step
  (PR 7 builder) and the overlap-scheduled step (PR 11 builder);
- quantization-error gauges follow the wire: ~0 on fp32, within the
  block half-step bound on int8, nonzero on fp8 — and survive the
  overlap on/off scan restructure bit-identically;
- parity stays bitwise with numerics ENABLED: the stats ride outside
  the pinned subgraphs;
- detector auto-dump: the flight-recorder file holds pre-spike
  snapshots from before the planted step.
"""

import glob
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu import stats
from paddle_tpu.distributed import overlap as OV
from paddle_tpu.distributed.sharding import (
    attach_comm_ef, build_group_sharded_step, init_group_sharded_state)
from paddle_tpu.observability import numerics as nm
from paddle_tpu.testing import faults


@pytest.fixture
def fsdp_mesh():
    topo = dist.init_mesh(fsdp=4, devices=jax.devices()[:4],
                          set_global=False)
    yield topo
    from paddle_tpu.distributed import mesh as mesh_lib
    mesh_lib.set_topology(None)


@pytest.fixture(autouse=True)
def _clean_stats():
    stats.reset("num/")
    yield
    stats.reset("num/")


def _batch(seed=0, b=16, d=16, k=8):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(b, d), jnp.float32),
            jnp.asarray(rs.randn(b, k), jnp.float32))


def _ov_step(mesh, **kw):
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    kw.setdefault("bucket_mb", 1e-4)
    return OV.overlap_parallel(
        dict(params), emb, blk, lf, optim.SGD(learning_rate=0.05),
        mesh, stacked, **kw)


def _flat_step(mesh, comm_quant=None):
    """PR 7 builder (build_group_sharded_step) on the same stacked
    model, numerics keyed to the stacked leaves."""
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    specs = OV.overlap_group_specs(dict(params), mesh, stacked)

    def flat_loss(p, xb, yb):
        h = emb(p, xb, yb)
        for l in range(3):
            h = blk({k: p[k][l] for k in stacked}, h)
        return lf(p, h, xb, yb)

    opt = optim.SGD(learning_rate=0.05)
    sp, st = init_group_sharded_state(dict(params), opt, specs)
    if comm_quant:
        st = attach_comm_ef(dict(params), st, specs)
    step = build_group_sharded_step(flat_loss, opt, specs,
                                    comm_quant=comm_quant,
                                    stacked_keys=stacked)
    return sp, st, step


def _drive(step, sp, st, batch, n, monitor=None):
    snaps = []
    for i in range(n):
        out = step(sp, st, *batch)
        (sp, st, loss), packed = nm.split_out(out)
        if monitor is not None:
            snaps.append(monitor.ingest(packed, step=i))
    return sp, st, snaps


# -- packed layout / provenance / cadence (engine-light) ---------------------

def test_packer_layout_roundtrip():
    pk = nm.Packer()
    g = jnp.asarray(np.linspace(-1.0, 1.0, 24), jnp.float32)
    pk.family("grad/blk", nm.stacked_raw(g.reshape(3, 8)), 8)
    pk.leaf("grad/(rest)", g)
    pk.quant("rs", jnp.asarray([[0.04, 4.0, 4.0]], jnp.float32))
    pk.scalar("extra", 7.0)
    packed = pk.pack(loss=0.5)
    lay = pk.layout()
    assert packed.shape == (lay.size,)
    snap = lay.unpack(np.asarray(packed))
    assert snap["loss"] == pytest.approx(0.5)
    assert snap["first_bad_layer"] == -1
    assert set(snap["families"]) == {"grad/blk", "grad/(rest)"}
    want = math.sqrt(float(jnp.sum(g[:8] ** 2)) / 8)
    assert snap["families"]["grad/blk"]["rms"][0] == pytest.approx(
        want, rel=1e-5)
    assert snap["quant"]["rs"]["rel_err"][0] == pytest.approx(0.1)
    assert snap["quant"]["rs"]["ef_ratio"][0] == pytest.approx(0.1)
    assert snap["scalars"]["extra"] == pytest.approx(7.0)


def test_provenance_first_bad_is_layer_major():
    """A NaN in (layer 1, family B) beats (layer 2, family A): the
    argmax runs layer-major so the EARLIEST bad layer wins, ties
    breaking toward the earlier-registered family."""
    pk = nm.Packer()
    a = np.zeros((3, 4), np.float32)
    b = np.zeros((3, 4), np.float32)
    a[2, 0] = np.nan
    b[1, 0] = np.nan
    pk.family("grad/a", nm.stacked_raw(jnp.asarray(a)), 4)
    pk.family("grad/b", nm.stacked_raw(jnp.asarray(b)), 4)
    snap = pk.layout().unpack(np.asarray(pk.pack(loss=1.0)))
    assert snap["first_bad_layer"] == 1
    assert snap["first_bad_family_name"] == "grad/b"
    assert snap["nonfinite"] == 2.0


def test_cond_every_zeroes_off_cadence_steps():
    def make(step_count):
        return nm.cond_every(
            step_count, 4,
            lambda: jnp.arange(1.0, 6.0, dtype=jnp.float32))

    f = jax.jit(make)
    assert np.asarray(f(jnp.int32(0)))[0] == 1.0
    assert np.all(np.asarray(f(jnp.int32(3))) == 0.0)
    assert np.asarray(f(jnp.int32(8)))[0] == 1.0


def test_split_out_shapes():
    assert nm.split_out((1, 2, 3)) == ((1, 2, 3), None)
    assert nm.split_out((1, 2, 3, "pk")) == ((1, 2, 3), "pk")


def test_dtype_overflow_underflow_fractions():
    x = jnp.asarray([3.3e38, 1.0, 1e-40, 0.0],
                    jnp.float32).reshape(1, 4)
    raw = np.asarray(nm.stacked_raw(x))
    assert raw[0, 3] == 1.0      # one overflow-at-risk value
    assert raw[0, 4] == 1.0      # one subnormal (0.0 doesn't count)


# -- watch detectors / recorder (host plane) ---------------------------------

def _snap(loss=1.0, grad_rms=0.1, nonfinite=0.0, overflow=0.0,
          ef=None, step=0):
    return {"loss": loss, "nonfinite": nonfinite, "grad_rms": grad_rms,
            "first_bad_layer": -1, "first_bad_family_name": None,
            "overflow_frac_max": overflow, "ef_ratio_max": ef,
            "quant_rel_err_max": None, "families": {}, "quant": {},
            "step": step}


def test_watch_loss_spike_edge_triggered(capsys):
    w = nm.NumericsWatch(window=8, z=6.0)
    for i in range(8):
        assert w.observe(_snap(loss=1.0 + 0.01 * (i % 3), step=i)) == []
    assert "loss_spike" in w.observe(_snap(loss=50.0, step=8))
    # still high: no re-fire (edge-triggered)
    assert w.observe(_snap(loss=50.0, step=9)) == []
    err = capsys.readouterr().err
    assert err.count("ALERT loss_spike") == 1


def test_watch_overflow_and_ef_runaway():
    w = nm.NumericsWatch(window=4)
    assert "overflow" in w.observe(_snap(overflow=0.5))
    assert "ef_runaway" in w.observe(_snap(ef=99.0, step=1))


def test_watch_nonfinite_names_layer_and_family():
    w = nm.NumericsWatch()
    s = _snap(nonfinite=3.0)
    s["first_bad_layer"] = 2
    s["first_bad_family_name"] = "grad/blocks.w2"
    assert "nonfinite" in w.observe(s)
    assert stats.get("num/alert_nonfinite") == 1


def test_recorder_ring_and_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("PT_NUMERICS_DIR", str(tmp_path))
    rec = nm.NumericsRecorder(capacity=2)
    for i in range(4):
        rec.append(_snap(step=i))
    assert len(rec) == 2
    rec.dump("test_reason", step=3)
    files = glob.glob(str(tmp_path / "numerics_3.*.json"))
    assert len(files) == 1
    doc = json.loads(open(files[0]).read())
    assert doc["reason"] == "test_reason"
    assert [s["step"] for s in doc["snapshots"]] == [2, 3]


# -- plain sharded (PR 7) builder --------------------------------------------

def test_flat_step_numerics_families_and_parity(fsdp_mesh, monkeypatch):
    """The PR 7 builder with numerics ENABLED: per-layer families over
    the stacked leaves, one packed vector, and the SAME parameters as
    the numerics-off build (stats never feed back)."""
    batch = _batch()
    sp0, st0, step0 = _flat_step(fsdp_mesh.mesh)
    sp0, st0, _ = _drive(step0, sp0, st0, batch, 3)

    monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
    sp, st, step = _flat_step(fsdp_mesh.mesh)
    mon = nm.Monitor.for_step(step)
    sp, st, snaps = _drive(step, sp, st, batch, 3, monitor=mon)
    for k in sp0:
        np.testing.assert_array_equal(np.asarray(sp0[k]),
                                      np.asarray(sp[k]), err_msg=k)
    snap = snaps[-1]
    fams = snap["families"]
    for k in ("grad/blocks.w1", "grad/blocks.b1", "grad/blocks.w2"):
        assert len(fams[k]["rms"]) == 3, k
        assert all(v > 0 for v in fams[k]["rms"]), k
    assert snap["first_bad_layer"] == -1
    assert snap["grad_rms"] > 0


def test_flat_step_localizes_planted_fault(fsdp_mesh, monkeypatch):
    monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
    with faults.inject("train.grad_poison", "nan", layer=1,
                       key="blocks.w1"):
        sp, st, step = _flat_step(fsdp_mesh.mesh, comm_quant="int8")
        mon = nm.Monitor.for_step(step)
        _, _, snaps = _drive(step, sp, st, _batch(), 1, monitor=mon)
    snap = snaps[0]
    assert snap["first_bad_layer"] == 1
    assert snap["first_bad_family_name"] == "grad/blocks.w1"
    assert "nonfinite" in snap["alerts"]


# -- overlap (PR 11) builder -------------------------------------------------

def test_overlap_numerics_parity_and_quant_gauges(fsdp_mesh,
                                                  monkeypatch):
    """Numerics ENABLED on the overlap step: overlap on/off stays
    BIT-identical (params AND the packed vector — the stats read the
    same barriered grads), fp32 reports ~0 wire error, int8 a nonzero
    error within the block half-step bound."""
    batch = _batch()
    monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
    packs = {}
    for on in (True, False):
        sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant="int8",
                                overlap=on, prefetch=False)
        out = step(sp, st, *batch)
        (sp2, _, _), packed = nm.split_out(out)
        packs[on] = (jax.device_get(sp2), np.asarray(packed),
                     nm.Monitor.for_step(step).ingest(packed, 0))
    for k in packs[True][0]:
        np.testing.assert_array_equal(packs[True][0][k],
                                      packs[False][0][k], err_msg=k)
    np.testing.assert_array_equal(packs[True][1], packs[False][1])

    snap = packs[True][2]
    rel = snap["quant"]["blk"]["rel_err"]
    assert all(r > 0 for r in rel)
    # block half-step bound: per element |q(x)-x| <= amax/(2*127) with
    # the per-layer family amax bounding every block's scale source
    fams = snap["families"]
    params, stacked, *_ = OV.mlp_block_model(n_layers=3)
    specs = OV.overlap_group_specs(dict(params), fsdp_mesh.mesh,
                                   stacked)
    sdim = OV._shard_dims(specs)
    rs = [k for k in stacked if k in sdim]
    buckets = OV.partition_buckets(
        [(k, 4 * int(np.prod(params[k].shape[1:]))) for k in rs],
        bucket_mb=1e-4, reverse=True)
    assert len(rel) == len(buckets)
    for row, b in zip(rel, buckets):
        num = den = 0.0
        for k in b:
            n = int(np.prod(params[k].shape[1:]))
            f = fams[f"grad/{k}"]
            num += sum(n * (a / 254.0) ** 2 for a in f["amax"])
            den += sum(n * r * r for r in f["rms"])
        assert row <= math.sqrt(num / den) * 1.05 + 1e-9, (b, row)

    # fp32 wire: exactly-representable exchange, error ~0
    sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant=None)
    out = step(sp, st, *batch)
    snap32 = nm.Monitor.for_step(step).ingest(out[3], 0)
    assert snap32["quant_rel_err_max"] < 1e-7
    # fp8 wire: nonzero, bounded
    sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant="fp8")
    out = step(sp, st, *batch)
    snap8 = nm.Monitor.for_step(step).ingest(out[3], 0)
    assert 0 < snap8["quant_rel_err_max"] < 0.2


def test_overlap_cadence_only_sampled_steps(fsdp_mesh, monkeypatch):
    monkeypatch.setenv("PT_NUMERICS_EVERY", "2")
    sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant="int8")
    mon = nm.Monitor.for_step(step)
    _, _, snaps = _drive(step, sp, st, _batch(), 4, monitor=mon)
    assert [s is not None for s in snaps] == [True, False, True, False]


def test_overlap_localizes_planted_fault_with_autodump(fsdp_mesh,
                                                       monkeypatch,
                                                       tmp_path):
    """ACCEPTANCE: a scripted mid-run poison (step=2 rule, ONE compile)
    on the overlap/quantized builder is localized by the provenance
    header, fires exactly one nonfinite alert, and the auto-dumped
    flight record holds the CLEAN pre-spike snapshots."""
    monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
    monkeypatch.setenv("PT_NUMERICS_DIR", str(tmp_path))
    with faults.inject("train.grad_poison", "nan", layer=2,
                       key="blocks.w2", step=2):
        sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant="int8")
        mon = nm.Monitor.for_step(step)
        sp, st, snaps = _drive(step, sp, st, _batch(), 4, monitor=mon)
    # steps 0/1 clean; step 2 carries the plant (step 3 legitimately
    # cascades — NaN grads poisoned the update, like a real blow-up)
    assert [s["nonfinite"] > 0 for s in snaps[:3]] == [False, False,
                                                       True]
    bad = snaps[2]
    assert bad["first_bad_layer"] == 2
    assert bad["first_bad_family_name"] == "grad/blocks.w2"
    assert bad["alerts"] == ["nonfinite"]
    # edge-triggered: the step-3 cascade does NOT re-fire
    assert snaps[3]["alerts"] == []
    assert stats.get("num/alert_nonfinite") == 1
    files = glob.glob(str(tmp_path / "numerics_2.*.json"))
    assert len(files) == 1
    doc = json.loads(open(files[0]).read())
    assert doc["reason"] == "nonfinite"
    pre = [s for s in doc["snapshots"] if s["step"] < 2]
    assert len(pre) == 2 and all(s["nonfinite"] == 0 for s in pre)


def test_overlap_tail_sync_build_localizes_too(fsdp_mesh, monkeypatch):
    """The poison site lives in the backward scan body of EVERY
    schedule variant — the tail-sync baseline localizes the same."""
    monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
    with faults.inject("train.grad_poison", "nan", layer=0,
                       key="blocks.b1"):
        sp, st, step = _ov_step(fsdp_mesh.mesh, comm_quant="int8",
                                overlap=False, prefetch=False)
        mon = nm.Monitor.for_step(step)
        _, _, snaps = _drive(step, sp, st, _batch(), 1, monitor=mon)
    assert snaps[0]["first_bad_layer"] == 0
    assert snaps[0]["first_bad_family_name"] == "grad/blocks.b1"


# -- model steps (gpt) -------------------------------------------------------

@pytest.mark.slow
def test_gpt_step_numerics_and_localization(monkeypatch):
    from paddle_tpu.models import gpt
    topo = dist.init_mesh(dp=2, fsdp=2, devices=jax.devices()[:4],
                          set_global=False)
    try:
        monkeypatch.setenv("PT_NUMERICS_EVERY", "1")
        cfg = gpt.gpt_tiny(max_seq_len=16, dtype=jnp.float32)
        model = gpt.GPT(cfg, seed=0)
        opt = optim.AdamW(learning_rate=1e-3)
        tokens = jnp.zeros((4, 16), jnp.int32)
        rng = jax.random.PRNGKey(0)
        with faults.inject("train.grad_poison", "nan", layer=1,
                           key="_stacked_blocks"):
            params, opt_state = gpt.init_train_state(model, opt,
                                                     topo.mesh,
                                                     stacked=True)
            step = gpt.build_train_step(model, opt, topo.mesh,
                                        donate=False)
            mon = nm.Monitor.for_step(step)
            out = step(params, opt_state, tokens, rng)
            (_, _, _), packed = nm.split_out(out)
            snap = mon.ingest(packed, 0)
        assert snap["first_bad_layer"] == 1
        assert "_stacked_blocks" in snap["first_bad_family_name"]
        assert snap["update_rms"] is not None
    finally:
        from paddle_tpu.distributed import mesh as mesh_lib
        mesh_lib.set_topology(None)
