"""incubate Fused* layers (VERDICT r2 weak 9): the cached decode path of
FusedMultiTransformer must reproduce the full forward incrementally
(≙ fused_multi_transformer_op.cu CacheKV decode), and the fused layers
must match their unfused equivalents."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.incubate.nn import (FusedMultiHeadAttention,
                                    FusedFeedForward,
                                    FusedTransformerEncoderLayer,
                                    FusedMultiTransformer)


def test_fused_mha_runs_and_shapes():
    m = FusedMultiHeadAttention(32, 4, attn_dropout_rate=0.0,
                                dropout_rate=0.0)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 32), jnp.float32)
    out = m(x)
    assert out.shape == (2, 6, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_fused_ffn_pre_post_norm():
    x = jnp.asarray(np.random.RandomState(1).randn(2, 5, 16), jnp.float32)
    for pre in (False, True):
        ffn = FusedFeedForward(16, 64, dropout_rate=0.0,
                               normalize_before=pre).eval()
        out = ffn(x)
        assert out.shape == x.shape
        assert np.isfinite(np.asarray(out)).all()


def test_fused_multi_transformer_cached_decode_matches_full():
    model = FusedMultiTransformer(32, 4, 64, dropout_rate=0.0,
                                  normalize_before=True,
                                  num_layers=3).eval()
    x = jnp.asarray(np.random.RandomState(2).randn(2, 7, 32), jnp.float32)

    # full forward (no causal mask: encoder-style layers attend to all)
    full = model(x)
    assert full.shape == (2, 7, 32)

    # incremental: feed one position at a time through the KV caches.
    # Without causality the attention context differs mid-sequence, so
    # compare the FINAL position, whose cached context equals the full
    # context... only for the last layer when inputs match. Instead prime
    # the cache with the full prefix then decode the last token:
    caches = model.gen_cache(x)
    out_prefix, caches = model(x[:, :6], caches=caches)
    np.testing.assert_allclose(np.asarray(out_prefix),
                               np.asarray(model(x[:, :6])),
                               rtol=1e-5, atol=1e-5)
    out_last, caches = model(x[:, 6:7], caches=caches)
    assert out_last.shape == (2, 1, 32)
    for (k, v) in caches:
        assert k.shape[1] == 7 and v.shape[1] == 7


def test_fused_encoder_layer_alias():
    layer = FusedTransformerEncoderLayer(16, 2, 32, dropout=0.0).eval()
    x = jnp.asarray(np.random.RandomState(3).randn(1, 4, 16), jnp.float32)
    assert layer(x).shape == (1, 4, 16)


def test_dynamic_batcher_serves_concurrent_requests():
    """DynamicBatcher (VERDICT r2 weak 10): concurrent submits coalesce
    into padded batches; every future resolves with its own row."""
    import threading as th
    from paddle_tpu.inference import Predictor, DynamicBatcher

    calls = []

    def fn(x):
        calls.append(int(x.shape[0]))
        return x * 2.0

    batcher = DynamicBatcher(Predictor(fn, batch_size=4), max_delay_ms=30)
    try:
        futs = []

        def client(i):
            futs.append((i, batcher.submit(
                np.full((3,), float(i), np.float32))))

        threads = [th.Thread(target=client, args=(i,)) for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, fut in futs:
            out = fut.result(timeout=10)
            np.testing.assert_allclose(out, np.full((3,), 2.0 * i))
        assert all(c == 4 for c in calls)  # padded to the compiled batch
    finally:
        batcher.close()


def test_dynamic_batcher_queue_bound_and_close():
    from paddle_tpu.inference import Predictor, DynamicBatcher
    import pytest as _pytest
    b = DynamicBatcher(Predictor(lambda x: x, batch_size=2),
                       max_delay_ms=1, max_queue=2)
    b.close()
    with _pytest.raises(RuntimeError, match="closed"):
        b.submit(np.zeros((1,), np.float32))
    with _pytest.raises(ValueError, match="batch_size"):
        DynamicBatcher(Predictor(lambda x: x))


class TestFusedBiasDropoutResidualLayerNorm:
    def test_eval_matches_plain_ln(self):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu import incubate
        from paddle_tpu.nn.functional import layer_norm
        layer = incubate.nn.FusedBiasDropoutResidualLayerNorm(
            128, dropout_rate=0.3)
        layer.eval()
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.normal(size=(2, 4, 128)), jnp.float32)
        res = jnp.asarray(rs.normal(size=(2, 4, 128)), jnp.float32)
        out = layer(x, res)
        ref = layer_norm(x + res, (128,))
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_train_drops(self):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu import incubate
        layer = incubate.nn.FusedBiasDropoutResidualLayerNorm(
            128, dropout_rate=0.5)
        layer.train()
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.normal(size=(4, 128)), jnp.float32)
        res = jnp.zeros((4, 128), jnp.float32)
        a = layer(x, res, dropout_seed=3)
        b = layer(x, res, dropout_seed=3)
        np.testing.assert_array_equal(a, b)  # deterministic replay
        c = layer(x, res, dropout_seed=4)
        assert not np.allclose(a, c)

    def test_functional_form(self):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.incubate.nn import functional as IF
        from paddle_tpu.nn.functional import layer_norm
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.normal(size=(3, 128)), jnp.float32)
        res = jnp.asarray(rs.normal(size=(3, 128)), jnp.float32)
        out = IF.fused_bias_dropout_residual_layer_norm(
            x, res, dropout_rate=0.0)
        ref = layer_norm(x + res, (128,))
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
        y = IF.fused_matmul_bias(x, jnp.ones((128, 16)),
                                 jnp.zeros((16,)))
        np.testing.assert_allclose(y, x @ jnp.ones((128, 16)), atol=1e-5)


# ---------------------------------------------------------------------------
# Functional fused transformer ops (round 4: no longer NotImplemented —
# ref: incubate/nn/functional/fused_transformer.py:31/:462)
# ---------------------------------------------------------------------------

def _ln_np(x, scale, bias, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale + bias


def test_fused_multi_head_attention_matches_unfused():
    from paddle_tpu.incubate.nn import functional as IF
    rs = np.random.RandomState(0)
    b, s, h, dh = 2, 8, 2, 4
    d = h * dh
    x = rs.randn(b, s, d).astype(np.float32)
    qkv_w = rs.randn(3, h, dh, d).astype(np.float32) * 0.2
    qkv_b = rs.randn(3, h, dh).astype(np.float32) * 0.1
    lin_w = rs.randn(d, d).astype(np.float32) * 0.2
    lin_b = rs.randn(d).astype(np.float32) * 0.1
    ln_s = np.ones(d, np.float32)
    ln_b = np.zeros(d, np.float32)

    out = IF.fused_multi_head_attention(
        jnp.asarray(x), jnp.asarray(qkv_w), jnp.asarray(lin_w),
        qkv_bias=jnp.asarray(qkv_b), linear_bias=jnp.asarray(lin_b),
        ln_scale=jnp.asarray(ln_s), ln_bias=jnp.asarray(ln_b),
        dropout_rate=0.0, attn_dropout_rate=0.0, training=False)

    # numpy oracle: the unfused composition
    qkv = np.einsum("bsd,thed->bsthe", x, qkv_w) + qkv_b[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scores = np.einsum("bqhe,bkhe->bhqk", q, k) / np.sqrt(dh)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    attn = np.einsum("bhqk,bkhe->bqhe", p, v).reshape(b, s, d)
    want = _ln_np(x + attn @ lin_w + lin_b, ln_s, ln_b)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-4)

    # pre-LN variant skips the post-LN
    out_pre = IF.fused_multi_head_attention(
        jnp.asarray(x), jnp.asarray(qkv_w), jnp.asarray(lin_w),
        pre_layer_norm=True, pre_ln_scale=jnp.asarray(ln_s),
        pre_ln_bias=jnp.asarray(ln_b), dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)
    xn = _ln_np(x, ln_s, ln_b)
    qkv = np.einsum("bsd,thed->bsthe", xn, qkv_w)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scores = np.einsum("bqhe,bkhe->bhqk", q, k) / np.sqrt(dh)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    attn = np.einsum("bhqk,bkhe->bqhe", p, v).reshape(b, s, d)
    want_pre = x + attn @ lin_w
    np.testing.assert_allclose(np.asarray(out_pre), want_pre,
                               rtol=2e-4, atol=2e-4)


def test_fused_feedforward_matches_unfused():
    from paddle_tpu.incubate.nn import functional as IF
    rs = np.random.RandomState(1)
    b, s, d, f = 2, 4, 8, 16
    x = rs.randn(b, s, d).astype(np.float32)
    w1 = rs.randn(d, f).astype(np.float32) * 0.2
    b1 = rs.randn(f).astype(np.float32) * 0.1
    w2 = rs.randn(f, d).astype(np.float32) * 0.2
    b2 = rs.randn(d).astype(np.float32) * 0.1
    ln_s = np.ones(d, np.float32)
    ln_b = np.zeros(d, np.float32)
    out = IF.fused_feedforward(
        jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2),
        linear1_bias=jnp.asarray(b1), linear2_bias=jnp.asarray(b2),
        ln2_scale=jnp.asarray(ln_s), ln2_bias=jnp.asarray(ln_b),
        dropout1_rate=0.0, dropout2_rate=0.0, training=False)
    h = np.maximum(x @ w1 + b1, 0.0)
    want = _ln_np(x + h @ w2 + b2, ln_s, ln_b)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-4)


def test_fused_ops_dropout_and_jit():
    from paddle_tpu.incubate.nn import functional as IF
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(2, 4, 8), jnp.float32)
    w1 = jnp.asarray(rs.randn(8, 16) * 0.2, jnp.float32)
    w2 = jnp.asarray(rs.randn(16, 8) * 0.2, jnp.float32)
    f = jax.jit(lambda xx, key: IF.fused_feedforward(
        xx, w1, w2, dropout1_rate=0.5, training=True, rng_key=key))
    a = f(x, jax.random.PRNGKey(0))
    b = f(x, jax.random.PRNGKey(1))
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_fused_mha_cache_kv_incremental_matches_full():
    """cache_kv decode (ref fused_transformer.py:462 CacheKV form):
    feeding tokens one at a time through the growing cache must match
    the full causal-masked run position by position."""
    from paddle_tpu.incubate.nn import functional as IF
    rs = np.random.RandomState(3)
    b, s, h, dh = 2, 6, 2, 4
    d = h * dh
    x = rs.randn(b, s, d).astype(np.float32)
    qkv_w = rs.randn(3, h, dh, d).astype(np.float32) * 0.2
    lin_w = rs.randn(d, d).astype(np.float32) * 0.2
    ln_s = np.ones(d, np.float32)
    ln_b = np.zeros(d, np.float32)
    kw = dict(dropout_rate=0.0, attn_dropout_rate=0.0, training=False,
              ln_scale=jnp.asarray(ln_s), ln_bias=jnp.asarray(ln_b))

    causal = np.triu(np.full((s, s), -np.inf, np.float32), 1)[None, None]
    full = IF.fused_multi_head_attention(
        jnp.asarray(x), jnp.asarray(qkv_w), jnp.asarray(lin_w),
        attn_mask=jnp.asarray(causal), **kw)

    cache = jnp.zeros((2, b, h, 0, dh), jnp.float32)
    outs = []
    for t in range(s):
        out_t, cache = IF.fused_multi_head_attention(
            jnp.asarray(x[:, t:t + 1]), jnp.asarray(qkv_w),
            jnp.asarray(lin_w), cache_kv=cache, **kw)
        outs.append(np.asarray(out_t)[:, 0])
    got = np.stack(outs, axis=1)
    assert cache.shape == (2, b, h, s, dh)
    np.testing.assert_allclose(got, np.asarray(full), rtol=2e-4,
                               atol=2e-4)
