"""Spawned-worker module for test_fleet_executor: one pipeline stage per
OS process over the native P2P transport. CPU platform pinned at module
level (spawn start-method imports this before jax can initialize)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

D, H, K = 8, 16, 4
N_MICRO, B = 4, 2


def make_data():
    rs = np.random.RandomState(99)
    x = rs.normal(size=(N_MICRO, B, D)).astype(np.float32)
    y = rs.normal(size=(N_MICRO, B, K)).astype(np.float32)
    return x, y


def make_params(stage):
    rs = np.random.RandomState(stage)
    if stage == 0:
        return {"w": rs.normal(size=(D, H)).astype(np.float32) * 0.3,
                "b": np.zeros((H,), np.float32)}
    if stage == 1:
        return {"w": rs.normal(size=(H, H)).astype(np.float32) * 0.3,
                "b": np.zeros((H,), np.float32)}
    return {"w": rs.normal(size=(H, K)).astype(np.float32) * 0.3,
            "b": np.zeros((K,), np.float32)}


def stage_fn(stage):
    import jax.numpy as jnp

    if stage == 2:
        def last(params, x, label):
            pred = x @ params["w"] + params["b"]
            return jnp.mean(jnp.square(pred - label))
        return last

    def mid(params, x):
        return jnp.maximum(x @ params["w"] + params["b"], 0.0)
    return mid


def reference_grads():
    """Single-process full-model autodiff oracle."""
    import jax
    import jax.numpy as jnp
    x, y = make_data()
    ps = [make_params(s) for s in range(3)]

    def loss_fn(ps):
        total = 0.0
        for mb in range(N_MICRO):
            h = jnp.maximum(x[mb] @ ps[0]["w"] + ps[0]["b"], 0.0)
            h = jnp.maximum(h @ ps[1]["w"] + ps[1]["b"], 0.0)
            pred = h @ ps[2]["w"] + ps[2]["b"]
            total = total + jnp.mean(jnp.square(pred - y[mb]))
        return total / N_MICRO

    loss = loss_fn(ps)
    grads = jax.grad(loss_fn)(ps)
    return float(loss), grads


def worker(stage, store_port, schedule, tmpdir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu import native
    from paddle_tpu.distributed.fleet_executor import (FleetExecutor,
                                                       rendezvous_endpoints)

    store = native.TCPStore("127.0.0.1", store_port,
                            is_master=(stage == 0), timeout=60.0)
    ep, peers = rendezvous_endpoints(store, stage, 3)
    fe = FleetExecutor(stage_fn(stage), stage, 3, ep, peers,
                       schedule=schedule)
    x, y = make_data()

    for step in range(2):  # two steps: step-tag separation must hold
        grads, loss = fe.run(
            make_params(stage),
            microbatches=list(x) if stage == 0 else None,
            labels=list(y) if stage == 2 else None,
            n_micro=N_MICRO)
        out = {f"g_{k}": np.asarray(v) for k, v in grads.items()}
        if loss is not None:
            out["loss"] = np.float32(loss)
        np.savez(os.path.join(tmpdir, f"stage{stage}_step{step}.npz"),
                 **out)
    ep.close()
    store.close()
