"""Spawned-worker module for test_fleet_executor: one pipeline stage per
OS process over the native P2P transport. CPU platform pinned at module
level (spawn start-method imports this before jax can initialize)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

D, H, K = 8, 16, 4
N_MICRO, B = 4, 2


def make_data():
    rs = np.random.RandomState(99)
    x = rs.normal(size=(N_MICRO, B, D)).astype(np.float32)
    y = rs.normal(size=(N_MICRO, B, K)).astype(np.float32)
    return x, y


def make_params(stage):
    rs = np.random.RandomState(stage)
    if stage == 0:
        return {"w": rs.normal(size=(D, H)).astype(np.float32) * 0.3,
                "b": np.zeros((H,), np.float32)}
    if stage == 1:
        return {"w": rs.normal(size=(H, H)).astype(np.float32) * 0.3,
                "b": np.zeros((H,), np.float32)}
    return {"w": rs.normal(size=(H, K)).astype(np.float32) * 0.3,
            "b": np.zeros((K,), np.float32)}


def stage_fn(stage):
    import jax.numpy as jnp

    if stage == 2:
        def last(params, x, label):
            pred = x @ params["w"] + params["b"]
            return jnp.mean(jnp.square(pred - label))
        return last

    def mid(params, x):
        return jnp.maximum(x @ params["w"] + params["b"], 0.0)
    return mid


def reference_grads():
    """Single-process full-model autodiff oracle."""
    import jax
    import jax.numpy as jnp
    x, y = make_data()
    ps = [make_params(s) for s in range(3)]

    def loss_fn(ps):
        total = 0.0
        for mb in range(N_MICRO):
            h = jnp.maximum(x[mb] @ ps[0]["w"] + ps[0]["b"], 0.0)
            h = jnp.maximum(h @ ps[1]["w"] + ps[1]["b"], 0.0)
            pred = h @ ps[2]["w"] + ps[2]["b"]
            total = total + jnp.mean(jnp.square(pred - y[mb]))
        return total / N_MICRO

    loss = loss_fn(ps)
    grads = jax.grad(loss_fn)(ps)
    return float(loss), grads


def worker(stage, store_port, schedule, tmpdir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu import native
    from paddle_tpu.distributed.fleet_executor import (FleetExecutor,
                                                       rendezvous_endpoints)

    store = native.TCPStore("127.0.0.1", store_port,
                            is_master=(stage == 0), timeout=60.0)
    ep, peers = rendezvous_endpoints(store, stage, 3)
    fe = FleetExecutor(stage_fn(stage), stage, 3, ep, peers,
                       schedule=schedule)
    x, y = make_data()

    for step in range(2):  # two steps: step-tag separation must hold
        grads, loss = fe.run(
            make_params(stage),
            microbatches=list(x) if stage == 0 else None,
            labels=list(y) if stage == 2 else None,
            n_micro=N_MICRO)
        out = {f"g_{k}": np.asarray(v) for k, v in grads.items()}
        if loss is not None:
            out["loss"] = np.float32(loss)
        np.savez(os.path.join(tmpdir, f"stage{stage}_step{step}.npz"),
                 **out)
    # distributed-layer observability (VERDICT r4 item 8): the runtime
    # must have recorded its traffic
    from paddle_tpu import stats
    assert stats.get("fleet_executor/microbatch_fwd") >= 2 * N_MICRO
    assert stats.get("fleet_executor/microbatch_bwd") >= 2 * N_MICRO
    if stage < 2:
        assert stats.get("fleet_executor/send_msgs") > 0
        assert stats.get("fleet_executor/send_bytes") > 0
    if stage > 0:
        assert stats.get("fleet_executor/recv_msgs") > 0
        assert stats.snapshot().get(
            "fleet_executor/recv_wait.count", 0) > 0
    ep.close()
    store.close()


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) pipeline: 4 global stages over 2 ranks, V=2
# (≙ PipelineParallelWithInterleave, pipeline_parallel.py:457)
# ---------------------------------------------------------------------------

N_STAGES_V, N_VIRTUAL = 2, 2
G = N_STAGES_V * N_VIRTUAL  # 4 global stages


def make_params_g(g):
    rs = np.random.RandomState(100 + g)
    din = D if g == 0 else H
    dout = K if g == G - 1 else H
    return {"w": rs.normal(size=(din, dout)).astype(np.float32) * 0.3,
            "b": np.zeros((dout,), np.float32)}


def chunk_fn(g, sleep_s=0.0):
    import time

    import jax.numpy as jnp

    if g == G - 1:
        def last(params, x, label):
            if sleep_s:
                time.sleep(sleep_s)
            pred = x @ params["w"] + params["b"]
            return jnp.mean(jnp.square(pred - label))
        return last

    def mid(params, x):
        if sleep_s:
            time.sleep(sleep_s)
        return jnp.maximum(x @ params["w"] + params["b"], 0.0)
    return mid


def reference_grads_vpp():
    import jax
    import jax.numpy as jnp
    x, y = make_data()
    ps = [make_params_g(g) for g in range(G)]

    def loss_fn(ps):
        total = 0.0
        for mb in range(N_MICRO):
            h = x[mb]
            for g in range(G - 1):
                h = jnp.maximum(h @ ps[g]["w"] + ps[g]["b"], 0.0)
            pred = h @ ps[G - 1]["w"] + ps[G - 1]["b"]
            total = total + jnp.mean(jnp.square(pred - y[mb]))
        return total / N_MICRO

    return float(loss_fn(ps)), jax.grad(loss_fn)(ps)


def worker_vpp(rank, store_port, schedule, tmpdir, n_virtual=N_VIRTUAL,
               sleep_s=0.0):
    """One rank owning n_virtual chunks; with n_virtual=1 the same 4-layer
    model runs as a 2-deep pipeline of 2-layer stages (for the bubble
    comparison both variants do identical numeric work)."""
    import time

    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu import native
    from paddle_tpu.distributed.fleet_executor import (FleetExecutor,
                                                       rendezvous_endpoints)

    S = N_STAGES_V
    store = native.TCPStore("127.0.0.1", store_port,
                            is_master=(rank == 0), timeout=60.0)
    ep, peers = rendezvous_endpoints(store, rank, S)
    x, y = make_data()

    if n_virtual > 1:
        fns = [chunk_fn(v * S + rank, sleep_s) for v in range(n_virtual)]
        params = [make_params_g(v * S + rank) for v in range(n_virtual)]
    else:
        # rank owns global stages [2r, 2r+1] fused into one callable
        import jax.numpy as jnp
        gs = [rank * 2, rank * 2 + 1]

        def fused(params, x, label=None):
            if sleep_s:
                time.sleep(2 * sleep_s)  # same total work as two chunks
            h = jnp.maximum(x @ params[0]["w"] + params[0]["b"], 0.0)
            if rank == S - 1:
                pred = h @ params[1]["w"] + params[1]["b"]
                return jnp.mean(jnp.square(pred - label))
            return jnp.maximum(h @ params[1]["w"] + params[1]["b"], 0.0)
        fns = fused
        params = [make_params_g(g) for g in gs]

    fe = FleetExecutor(fns, rank, S, ep, peers, schedule=schedule,
                       n_virtual=n_virtual)

    walls = []
    for step in range(2):
        t0 = time.perf_counter()
        grads, loss = fe.run(
            params,
            microbatches=list(x) if rank == 0 else None,
            labels=list(y) if rank == S - 1 else None,
            n_micro=N_MICRO)
        walls.append(time.perf_counter() - t0)
        out = {}
        # V>1: grads is a per-chunk list of dicts; V==1 fused: grads
        # mirrors params (a 2-list of dicts) — same enumeration either way
        for i, gp in enumerate(grads):
            for k, v in gp.items():
                out[f"g{i}_{k}"] = np.asarray(v)
        if loss is not None:
            out["loss"] = np.float32(loss)
        out["wall"] = np.float64(walls[-1])
        np.savez(os.path.join(tmpdir, f"vpp{n_virtual}_rank{rank}_"
                                      f"step{step}.npz"), **out)
    fe.close()
    ep.close()
    store.close()
