"""Flagship GPT model family tests (SURVEY §4 OpTest idea: one numpy/dense
oracle, checked across execution modes — here dense vs pipelined-SPMD)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.models import gpt


def _tiny(**kw):
    d = dict(vocab_size=64, max_seq_len=16, d_model=32, n_layers=4,
             n_heads=2, dtype=jnp.float32)
    d.update(kw)
    return gpt.GPTConfig(**d)


def _tokens(cfg, b=4, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(
        0, cfg.vocab_size, (b, cfg.max_seq_len)), jnp.int32)


class TestForward:
    def test_logits_shape(self):
        cfg = _tiny()
        model = gpt.GPT(cfg, seed=0)
        logits = model(_tokens(cfg))
        assert logits.shape == (4, cfg.max_seq_len, cfg.vocab_size)

    def test_loss_near_uniform_at_init(self):
        cfg = _tiny()
        model = gpt.GPT(cfg, seed=0)
        loss = gpt.lm_loss(model(_tokens(cfg)), _tokens(cfg))
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 0.5

    def test_remat_matches_plain(self):
        cfg = _tiny()
        toks = _tokens(cfg)
        out_plain = gpt.GPT(cfg, seed=0)(toks)
        out_remat = gpt.GPT(_tiny(remat=True), seed=0)(toks)
        np.testing.assert_allclose(np.asarray(out_plain),
                                   np.asarray(out_remat), rtol=1e-5)

    def test_param_count_formula(self):
        cfg = _tiny()
        model = gpt.GPT(cfg, seed=0)
        params, _ = model.split_params()
        total = sum(int(np.prod(v.shape)) for v in params.values())
        assert total == cfg.num_params()


class TestTrainStep:
    def test_loss_decreases(self):
        cfg = _tiny(n_layers=2)
        model = gpt.GPT(cfg, seed=0)
        opt = optim.AdamW(learning_rate=1e-3)
        params, opt_state = gpt.init_train_state(model, opt)
        step = gpt.build_train_step(model, opt)
        toks = _tokens(cfg)
        rng = jax.random.PRNGKey(0)
        losses = []
        for i in range(8):
            params, opt_state, loss = step(params, opt_state, toks, rng)
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.3, losses


class TestPipeline:
    def test_stack_unstack_roundtrip(self):
        cfg = _tiny(n_layers=4)
        model = gpt.GPT(cfg, seed=0)
        stacked = gpt.stack_blocks(model, 2)
        blocks = gpt.unstack_blocks(stacked, 4)
        orig = model.blocks[1]
        np.testing.assert_array_equal(np.asarray(blocks[1].wqkv),
                                      np.asarray(orig.wqkv))

    def test_pipelined_matches_dense(self, mesh8):
        """GPipe-in-SPMD output == plain layer loop (same weights)."""
        # mesh8: dp=2, tp=2, fsdp=2 — reinit with pp for this test
        topo = dist.init_mesh(pp=2, dp=2, tp=2)
        cfg = _tiny(n_layers=4)
        model = gpt.GPT(cfg, seed=0)
        n_micro, mb = 4, 2
        toks = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)

        # dense oracle
        dense = jax.vmap(lambda t: model(t))(toks)

        x = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
        x = x.reshape(n_micro, mb, cfg.max_seq_len, -1)
        stacked = gpt.stack_blocks(model, 2)
        y = gpt.pipelined_apply(stacked, x, 2)
        piped = model.head(
            y.reshape(n_micro * mb, cfg.max_seq_len, -1)).reshape(
            dense.shape)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(piped),
                                   rtol=2e-4, atol=2e-4)

    def test_pipelined_train_step_runs(self):
        topo = dist.init_mesh(pp=2, dp=2, fsdp=2)
        cfg = _tiny(n_layers=4)
        model = gpt.GPT(cfg, seed=0)
        opt = optim.AdamW(learning_rate=1e-3)
        emb_p, stacked, opt_state = gpt.init_pipelined_state(
            model, opt, topo.mesh, 2)
        step = gpt.build_pipelined_train_step(model, opt, topo.mesh, 2, 4)
        toks = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (4, 2, cfg.max_seq_len)), jnp.int32)
        rng = jax.random.PRNGKey(0)
        l0 = None
        for i in range(4):
            emb_p, stacked, opt_state, loss = step(emb_p, stacked,
                                                   opt_state, toks, rng)
            if i == 0:
                l0 = float(loss)
        assert float(loss) < l0, (float(loss), l0)
        assert np.isfinite(float(loss))


class TestPartitionRules:
    def test_specs(self):
        from jax.sharding import PartitionSpec as P
        assert gpt.partition_spec("blocks.item_0.wqkv") == P("fsdp", "tp")
        assert gpt.partition_spec("blocks.item_3.wo") == P("tp", "fsdp")
        assert gpt.partition_spec("wte") == P("tp", "fsdp")
        assert gpt.partition_spec("lnf_scale") == P(None)

    def test_pipeline_spec(self):
        from jax.sharding import PartitionSpec as P
        assert gpt.pipeline_partition_spec("wqkv") == \
            P("pp", None, "fsdp", "tp")


class TestShardedTrainStep:
    def test_tp_fsdp_matches_single(self):
        """Same seed/data: sharded GSPMD step == single-device step."""
        cfg = _tiny(n_layers=2)
        model = gpt.GPT(cfg, seed=0)
        opt = optim.AdamW(learning_rate=1e-3)
        toks = _tokens(cfg)
        rng = jax.random.PRNGKey(0)

        params1, st1 = gpt.init_train_state(model, opt)
        step1 = gpt.build_train_step(model, opt)
        _, _, loss_single = step1(params1, st1, toks, rng)

        topo = dist.init_mesh(dp=2, tp=2, fsdp=2)
        params2, st2 = gpt.init_train_state(model, opt, topo.mesh)
        step2 = gpt.build_train_step(model, opt, topo.mesh)
        _, _, loss_sharded = step2(params2, st2, toks, rng)
        np.testing.assert_allclose(float(loss_single),
                                   float(loss_sharded), rtol=1e-5)


def test_pipelined_remat_stages_matches_no_remat():
    """remat_stages changes memory, not math: identical loss trajectory."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import gpt

    topo = dist.init_mesh(pp=2, dp=4)
    cfg = gpt.gpt_tiny(max_seq_len=16, n_layers=4, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, 4, 16)), jnp.int32)
    rng = jax.random.PRNGKey(0)

    losses = {}
    for remat in (False, True):
        model = gpt.GPT(cfg, seed=0)
        opt = optim.AdamW(learning_rate=1e-3)
        emb_p, stacked, st = gpt.init_pipelined_state(model, opt,
                                                      topo.mesh, 2)
        step = gpt.build_pipelined_train_step(model, opt, topo.mesh, 2, 4,
                                              remat_stages=remat)
        for i in range(2):
            emb_p, stacked, st, loss = step(emb_p, stacked, st, tokens,
                                            jax.random.fold_in(rng, i))
        losses[remat] = float(loss)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)


def test_pipelined_uneven_stages_matches_dense():
    """L % n_stages != 0 → padded slots masked off; output must still equal
    the dense layer loop (VERDICT r1 item 9: uneven stage support)."""
    import paddle_tpu.distributed as dist
    topo = dist.init_mesh(pp=2, dp=2, tp=2)
    cfg = _tiny(n_layers=5)
    model = gpt.GPT(cfg, seed=0)
    n_micro, mb = 4, 2
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
    dense = jax.vmap(lambda t: model(t))(toks)

    x = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
    x = x.reshape(n_micro, mb, cfg.max_seq_len, -1)
    stacked, mask = gpt.stack_blocks_uneven(model, 2)
    assert mask is not None and mask.shape == (2, 3)
    y = gpt.pipelined_apply(stacked, x, 2, layer_mask=mask)
    piped = model.head(
        y.reshape(n_micro * mb, cfg.max_seq_len, -1)).reshape(dense.shape)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(piped),
                               rtol=2e-4, atol=2e-4)
    # stack_blocks (even-only API) must refuse
    with pytest.raises(ValueError, match="not divisible"):
        gpt.stack_blocks(model, 2)


def test_moe_pipeline_trains():
    """MoE×PP lifted restriction (VERDICT r1 item 5): all-MoE stack over
    pp×ep×dp trains with finite loss and the aux loss reaches the total."""
    import paddle_tpu.distributed as dist
    topo = dist.init_mesh(pp=2, ep=2, dp=2)
    cfg = _tiny(n_layers=4, moe_experts=4, moe_every=1)
    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    n_micro, mb = 4, 2
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
    emb_p, stacked, opt_state = gpt.init_pipelined_state(
        model, opt, topo.mesh, 2)
    step = gpt.build_pipelined_train_step(model, opt, topo.mesh, 2, n_micro)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(3):
        emb_p, stacked, opt_state, loss = step(emb_p, stacked, opt_state,
                                               toks, rng)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_moe_mixed_stack_rejected():
    cfg = _tiny(n_layers=4, moe_experts=2, moe_every=2)  # alternating
    model = gpt.GPT(cfg, seed=0)
    with pytest.raises(ValueError, match="homogeneous"):
        gpt.stack_blocks_uneven(model, 2)


def test_pipeline_moe_aux_masked_in_bubble():
    """The accumulated aux must equal the per-microbatch dense aux sum —
    i.e. bubble rows contribute nothing."""
    import paddle_tpu.distributed as dist
    dist.mesh.set_topology(None)
    cfg = _tiny(n_layers=2, moe_experts=2, moe_every=1)
    model = gpt.GPT(cfg, seed=0)
    n_micro, mb = 3, 2
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
    x = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
    x = x.reshape(n_micro, mb, cfg.max_seq_len, -1)
    stacked, _ = gpt.stack_blocks_uneven(model, 2)
    y, aux = gpt.pipelined_apply(stacked, x, 2, collect_aux=True)
    # dense oracle: sum of per-microbatch aux
    ref = 0.0
    for i in range(n_micro):
        _, a = model(toks[i], return_aux=True)
        ref += float(a)
    np.testing.assert_allclose(float(aux), ref, rtol=1e-4)


def test_pipeline_skip_dead_rows_parity():
    """Dead-row skip (lax.cond per stage row; VERDICT r2 item 9) must be
    bit-compatible with the vmapped SPMD schedule, for values AND grads."""
    cfg = _tiny(n_layers=4)
    model = gpt.GPT(cfg, seed=0)
    n_micro, mb = 3, 2
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
    x = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
    x = x.reshape(n_micro, mb, cfg.max_seq_len, -1)
    stacked = gpt.stack_blocks(model, 2)

    y_skip = gpt.pipelined_apply(stacked, x, 2, skip_dead_rows=True)
    y_vmap = gpt.pipelined_apply(stacked, x, 2, skip_dead_rows=False)
    np.testing.assert_allclose(np.asarray(y_skip), np.asarray(y_vmap),
                               rtol=1e-5, atol=1e-5)

    def loss(stacked, skip):
        return jnp.sum(gpt.pipelined_apply(stacked, x, 2,
                                           skip_dead_rows=skip) ** 2)

    g_skip = jax.grad(lambda s: loss(s, True))(stacked)
    g_vmap = jax.grad(lambda s: loss(s, False))(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_skip),
                    jax.tree_util.tree_leaves(g_vmap)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) pipeline (VERDICT r3 item 6;
# ≙ PipelineParallelWithInterleave, pipeline_parallel.py:457)
# ---------------------------------------------------------------------------

def test_interleaved_stacking_covers_all_layers():
    cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                        n_layers=8, n_heads=2, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    stacked, mask = gpt.stack_blocks_interleaved(model, 2, 2)
    leaf = jax.tree_util.tree_leaves(stacked)[0]
    assert leaf.shape[:3] == (2, 2, 2)  # (V, S, layers_per_global_stage)
    assert mask is None  # 8 layers / 4 global stages divide evenly
    # chunk (v, r) holds global stage v*S+r's layers: check weight identity
    w0 = dict(model.blocks[0].named_parameters())["wqkv"]
    got = getattr(stacked, "wqkv")[0, 0, 0]
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(got))
    w_last = dict(model.blocks[7].named_parameters())["wqkv"]
    got_last = getattr(stacked, "wqkv")[1, 1, 1]
    np.testing.assert_array_equal(np.asarray(w_last), np.asarray(got_last))


def test_interleaved_matches_dense(mesh8):
    """vpp=2 output == dense layer loop (same weights), even + uneven."""
    topo = dist.init_mesh(pp=2, dp=2, tp=2)
    for n_layers in (8, 6):  # 6 over 4 global stages → uneven, masked
        cfg = _tiny(n_layers=n_layers)
        model = gpt.GPT(cfg, seed=0)
        n_micro, mb = 4, 2
        toks = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
        dense = jax.vmap(lambda t: model(t))(toks)
        x = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
        x = x.reshape(n_micro, mb, cfg.max_seq_len, -1)
        stacked, mask = gpt.stack_blocks_interleaved(model, 2, 2)
        y = gpt.pipelined_apply_interleaved(stacked, x, 2, 2,
                                            layer_mask=mask)
        piped = model.head(
            y.reshape(n_micro * mb, cfg.max_seq_len, -1)).reshape(
            dense.shape)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(piped),
                                   rtol=2e-4, atol=2e-4)


def test_interleaved_train_step_runs(mesh8):
    topo = dist.init_mesh(pp=2, tp=2, fsdp=2)
    cfg = _tiny(n_layers=8)
    model = gpt.GPT(cfg, seed=0)
    from paddle_tpu import optimizer as optim
    opt = optim.AdamW(learning_rate=1e-3)
    emb_p, stacked, opt_state = gpt.init_pipelined_state(
        model, opt, topo.mesh, 2, n_virtual=2)
    leaf = jax.tree_util.tree_leaves(stacked)[0]
    assert leaf.shape[0] == 2 and leaf.shape[1] == 2
    step = gpt.build_pipelined_train_step(model, opt, topo.mesh, 2, 4,
                                          n_virtual=2)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, 2, cfg.max_seq_len)), jnp.int32)
    emb_p, stacked, opt_state, loss = step(emb_p, stacked, opt_state, toks,
                                           jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_interleaved_grads_match_dense(mesh8):
    """Gradients through the virtual-stage schedule equal the dense-loop
    gradients for the same loss (the adjoint of the interleaved roll)."""
    topo = dist.init_mesh(pp=2, dp=4)
    cfg = _tiny(n_layers=4)
    model = gpt.GPT(cfg, seed=0)
    n_micro, mb = 4, 2
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(n_micro, mb, cfg.max_seq_len, cfg.d_model),
                    jnp.float32)
    stacked, _ = gpt.stack_blocks_interleaved(model, 2, 2)

    def loss_vpp(blocks):
        y = gpt.pipelined_apply_interleaved(blocks, x, 2, 2)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def loss_dense(blocks):
        h = x.reshape(n_micro * mb, cfg.max_seq_len, -1)
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((4,) + a.shape[3:]), blocks)

        def body(hh, blk):
            return blk(hh), None
        h, _ = jax.lax.scan(body, h, flat)
        return jnp.sum(h.astype(jnp.float32) ** 2)

    g_vpp = jax.grad(loss_vpp)(stacked)
    g_dense = jax.grad(loss_dense)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_vpp),
                    jax.tree_util.tree_leaves(g_dense)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)


def test_interleaved_moe_pipeline_trains(mesh8):
    """MoE stack through the virtual-stage pipeline: aux loss collected
    across chunks, bubble rows contribute zero, step trains finite."""
    topo = dist.init_mesh(pp=2, ep=2, dp=2)
    cfg = gpt.GPTConfig(vocab_size=64, max_seq_len=16, d_model=32,
                        n_layers=4, n_heads=2, dtype=jnp.float32,
                        moe_experts=2, moe_every=1)
    model = gpt.GPT(cfg, seed=0)
    from paddle_tpu import optimizer as optim
    opt = optim.AdamW(learning_rate=1e-3)
    emb_p, stacked, opt_state = gpt.init_pipelined_state(
        model, opt, topo.mesh, 2, n_virtual=2)
    step = gpt.build_pipelined_train_step(model, opt, topo.mesh, 2, 4,
                                          n_virtual=2)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, 2, cfg.max_seq_len)), jnp.int32)
    emb_p, stacked, opt_state, loss = step(emb_p, stacked, opt_state, toks,
                                           jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))

    # aux parity vs the dense (unpipelined) MoE aux on identical inputs
    x = model.embed(toks.reshape(8, cfg.max_seq_len)).reshape(
        4, 2, cfg.max_seq_len, -1)
    stacked_v, mask = gpt.stack_blocks_interleaved(model, 2, 2)
    y, aux_vpp = gpt.pipelined_apply_interleaved(
        stacked_v, x, 2, 2, layer_mask=mask, collect_aux=True)
    stacked_p, mask_p = gpt.stack_blocks_uneven(model, 2)
    y_p, aux_p = gpt.pipelined_apply(stacked_p, x, 2, layer_mask=mask_p,
                                     collect_aux=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_p),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(aux_vpp), float(aux_p),
                               rtol=1e-4, atol=1e-5)


def test_scan_layers_matches_unrolled_loop():
    """The default lax.scan layer loop and the scan_layers=False
    unrolled escape hatch must train identically — including remat and
    per-layer dropout rng (fold_in by layer index in both paths)."""
    from paddle_tpu import flags, optimizer as optim

    for remat, dropout in ((False, 0.0), (True, 0.0), (False, 0.1)):
        cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                            n_layers=3, n_heads=2, dtype=jnp.float32,
                            remat=remat, dropout=dropout)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 128, (2, 16)), jnp.int32)
        losses = {}
        for scan in (True, False):
            flags.set_flags({"scan_layers": scan})
            try:
                model = gpt.GPT(cfg, seed=0)
                opt = optim.AdamW(learning_rate=1e-3)
                params, opt_state = gpt.init_train_state(model, opt)
                step = gpt.build_train_step(model, opt)
                ls = []
                for i in range(3):
                    params, opt_state, loss = step(
                        params, opt_state, toks, jax.random.PRNGKey(i))
                    ls.append(float(loss))
                losses[scan] = ls
            finally:
                flags.set_flags({"scan_layers": True})
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-6, atol=1e-6)


def test_stacked_train_state_matches_plain():
    """init_train_state(stacked=True) pre-stacks block weights so the
    scan consumes the state with no in-trace stack (the in-program copy
    + its grad-unstack transpose is what pushed the 1.3B step past 16GB
    HBM on hardware). Training must be numerically identical to the
    plain per-layer state, including remat and per-layer dropout rng."""
    from paddle_tpu import optimizer as optim

    for remat, dropout in ((False, 0.0), (True, 0.1)):
        cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                            n_layers=3, n_heads=2, dtype=jnp.float32,
                            remat=remat, dropout=dropout)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 128, (2, 16)), jnp.int32)
        model = gpt.GPT(cfg, seed=0)
        losses = {}
        for stacked in (False, True):
            opt = optim.AdamW(learning_rate=1e-3, weight_decay=0.01)
            params, opt_state = gpt.init_train_state(model, opt,
                                                     stacked=stacked)
            assert ("_stacked_blocks" in params) == stacked
            step = gpt.build_train_step(model, opt)
            ls = []
            for i in range(3):
                params, opt_state, loss = step(
                    params, opt_state, toks, jax.random.PRNGKey(i))
                ls.append(float(loss))
            losses[stacked] = ls
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-6, atol=1e-6)

    # merge_params on a stacked state must leave NO stale per-layer
    # weights: the decode path reads self.blocks, not the scan stack
    cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                        n_layers=3, n_heads=2, dtype=jnp.float32)
    toks = jnp.asarray(
        np.random.RandomState(2).randint(0, 128, (2, 16)), jnp.int32)
    model = gpt.GPT(cfg, seed=0)
    merged = {}
    for stacked in (False, True):
        opt = optim.AdamW(learning_rate=1e-2)
        params, opt_state = gpt.init_train_state(model, opt,
                                                 stacked=stacked)
        step = gpt.build_train_step(model, opt)
        params, opt_state, _ = step(params, opt_state, toks,
                                    jax.random.PRNGKey(0))
        merged[stacked] = model.merge_params(params)
    out_p = gpt.generate(merged[False], toks[:, :4], max_new_tokens=6,
                         max_len=16)
    out_s = gpt.generate(merged[True], toks[:, :4], max_new_tokens=6,
                         max_len=16)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))

    # guardrail: MoE stacks are heterogeneous and refuse the layout
    moe_cfg = gpt.GPTConfig(vocab_size=64, max_seq_len=8, d_model=16,
                            n_layers=2, n_heads=2, dtype=jnp.float32,
                            moe_experts=2)
    with pytest.raises(ValueError, match="dense"):
        gpt.init_train_state(gpt.GPT(moe_cfg, seed=0), optim.AdamW(),
                             stacked=True)
    # apply_decay_param_fun no longer refuses: the mask is resolved
    # against the block template and broadcast along the layer axis
    # (parity-tested in tests/test_sharded_stacked.py)
    opt = optim.AdamW(apply_decay_param_fun=lambda n: True)
    params, _ = gpt.init_train_state(model, opt, stacked=True)
    assert "_stacked_blocks" in opt._decay_masks
