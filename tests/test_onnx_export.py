"""ONNX export parity (ref: python/paddle/onnx/export.py; the reference
delegates to paddle2onnx, ours writes ModelProto wire format directly).

The load-bearing check: the exported FILE, parsed back and executed by an
independent numpy interpreter that follows the ONNX operator spec
(paddle_tpu/onnx/_numpy_eval.py), must match ``layer(x)`` numerically.
A wrong attribute (pads order, Gemm transB, BN epsilon), wrong weight
layout, or a mis-encoded initializer all surface as numeric mismatches
here, not just structural ones.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import export, load_model
from paddle_tpu.onnx._numpy_eval import run_model


def _roundtrip(model, x, tmp_path, name):
    path = export(model, str(tmp_path / name),
                  input_spec=(None,) + x.shape[1:])
    parsed = load_model(path)
    got = run_model(parsed, {"input": x})[0]
    want = np.asarray(model(x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    return parsed


def test_mlp_gemm_and_activations(tmp_path):
    model = nn.Sequential(
        nn.Linear(12, 32), nn.GELU(), nn.Linear(32, 16), nn.Tanh(),
        nn.Linear(16, 8), nn.LeakyReLU(0.1), nn.Dropout(0.5),
        nn.Linear(8, 5), nn.Softmax())
    model.eval()
    x = np.random.RandomState(0).randn(4, 12).astype(np.float32)
    parsed = _roundtrip(model, x, tmp_path, "mlp")
    ops = [n["op_type"] for n in parsed["graph"]["nodes"]]
    assert ops.count("Gemm") == 4
    assert "Erf" in ops            # exact-GELU decomposition
    assert "Identity" in ops       # inference Dropout
    # header sanity: spec-required fields present and ours
    assert parsed["ir_version"] == 8
    assert parsed["opset"] == 13
    assert parsed["producer_name"] == "paddle_tpu"


def test_lenet_conv_pool_flatten(tmp_path):
    model = nn.Sequential(
        nn.Conv2D(1, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 16, 5), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(16 * 5 * 5, 120), nn.ReLU(),
        nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, 10))
    model.eval()
    x = np.random.RandomState(1).randn(2, 1, 28, 28).astype(np.float32)
    parsed = _roundtrip(model, x, tmp_path, "lenet")
    g = parsed["graph"]
    # symbolic batch dim survives the round trip on both graph ends
    assert g["inputs"][0]["shape"] == ["N", 1, 28, 28]
    assert g["outputs"][0]["shape"] == ["N", 10]
    # weights ride as initializers with their true values
    w0 = g["initializers"]["w_0"]
    np.testing.assert_array_equal(w0, np.asarray(model[0].weight, np.float32))


def test_convnet_bn_stride_groups_avgpool(tmp_path):
    model = nn.Sequential(
        nn.Conv2D(4, 8, 3, stride=2, padding=1), nn.BatchNorm2D(8),
        nn.ReLU(), nn.Conv2D(8, 8, 3, padding=1, groups=2), nn.ReLU(),
        nn.AvgPool2D(2, 2), nn.AdaptiveAvgPool2D(1), nn.Flatten(),
        nn.Linear(8, 3))
    # non-trivial running stats so BatchNormalization attrs are exercised
    bn = model[1]
    rs = np.random.RandomState(2)
    bn.register_buffer("_mean", rs.randn(8).astype(np.float32) * 0.3)
    bn.register_buffer("_variance",
                       (0.5 + rs.rand(8)).astype(np.float32))
    model.eval()
    x = rs.randn(2, 4, 16, 16).astype(np.float32)
    parsed = _roundtrip(model, x, tmp_path, "convbn")
    ops = [n["op_type"] for n in parsed["graph"]["nodes"]]
    assert "BatchNormalization" in ops and "GlobalAveragePool" in ops
    conv2 = [n for n in parsed["graph"]["nodes"]
             if n["op_type"] == "Conv"][1]
    assert conv2["attrs"]["group"] == 2


def test_unsupported_layer_raises_with_guidance(tmp_path):
    model = nn.Sequential(nn.Linear(4, 4), nn.LSTM(4, 4)) \
        if hasattr(nn, "LSTM") else nn.Sequential(nn.Bilinear(3, 3, 2))
    with pytest.raises((NotImplementedError, ValueError)) as e:
        export(model, str(tmp_path / "bad"), input_spec=(1, 4))
    assert "jit.save" in str(e.value)


def test_guards_reject_silently_wrong_exports(tmp_path):
    # NHWC batch norm: ONNX BatchNormalization always normalizes axis 1
    with pytest.raises(ValueError, match="channel-first"):
        export(nn.BatchNorm2D(4, data_format="NHWC"),
               str(tmp_path / "bn"), input_spec=(None, 8, 8, 4))
    # pre-13 opsets change Softmax semantics
    with pytest.raises(ValueError, match="opset"):
        export(nn.Linear(3, 2), str(tmp_path / "old"),
               input_spec=(None, 3), opset_version=9)
    # non-batch dynamic dims would corrupt shape propagation
    with pytest.raises(ValueError, match="batch dim"):
        export(nn.Conv2D(3, 4, 3), str(tmp_path / "dyn"),
               input_spec=(None, 3, None, None))
    # options with no ONNX analog refuse instead of exporting wrong math
    with pytest.raises(ValueError, match="divisor_override"):
        export(nn.AvgPool2D(2, 2, divisor_override=3),
               str(tmp_path / "dv"), input_spec=(None, 2, 8, 8))
    with pytest.raises(ValueError, match="return_mask"):
        export(nn.MaxPool2D(2, 2, return_mask=True),
               str(tmp_path / "rm"), input_spec=(None, 2, 8, 8))
    with pytest.raises(ValueError, match="padding"):
        export(nn.Conv2D(3, 4, 3, padding="SAME"),
               str(tmp_path / "sp"), input_spec=(None, 3, 8, 8))


def test_input_spec_list_forms_and_degenerate_graph(tmp_path):
    model = nn.Linear(3, 2)
    # one-element list of a shape tuple (reference-style call) unwraps
    p = export(model, str(tmp_path / "l1"), input_spec=[(None, 3)])
    x = np.random.RandomState(4).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(
        run_model(load_model(p), {"input": x})[0], np.asarray(model(x)),
        rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="single-input"):
        export(model, str(tmp_path / "l2"),
               input_spec=[(None, 3), (None, 9)])
    with pytest.raises(ValueError, match="no ONNX nodes"):
        export(nn.Sequential(), str(tmp_path / "l3"), input_spec=(None, 3))


def test_intermediate_value_info_keeps_symbolic_batch(tmp_path):
    model = nn.Sequential(nn.Linear(6, 4), nn.ReLU(), nn.Linear(4, 2))
    path = export(model, str(tmp_path / "vi"), input_spec=(None, 6))
    g = load_model(path)["graph"]
    shapes = [vi["shape"] for vi in g["value_info"]]
    assert shapes and all(s[0] == "N" for s in shapes), shapes


def test_export_appends_extension_and_accepts_inputspec(tmp_path):
    model = nn.Linear(3, 2)
    spec = paddle.static.InputSpec(shape=(None, 3))
    path = export(model, str(tmp_path / "lin"), input_spec=spec)
    assert path.endswith("lin.onnx")
    parsed = load_model(path)
    x = np.random.RandomState(3).randn(5, 3).astype(np.float32)
    got = run_model(parsed, {"input": x})[0]
    np.testing.assert_allclose(got, np.asarray(model(x)), rtol=1e-5,
                               atol=1e-6)
