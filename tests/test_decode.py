"""Beam-search decoding (ref: python/paddle/nn/decode.py —
BeamSearchDecoder/dynamic_decode; oracle: exhaustive search over all
token sequences of a tiny deterministic 'grammar' cell)."""

import itertools

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn


class _BigramCell(nn.Module):
    """Deterministic cell: logits depend only on the previous token
    (a bigram LM), state = previous token one-hot."""

    def __init__(self, table):
        super().__init__()
        self.table = nn.Parameter(jnp.asarray(table, jnp.float32))

    def forward(self, ids, states):
        logits = self.table[ids]
        return logits, states


def _exhaustive_best(table, start, T):
    """Highest-log-prob token sequence of length T under the bigram LM."""
    v = table.shape[0]
    lsm = np.asarray(jax.nn.log_softmax(jnp.asarray(table), -1))
    best_lp, best_seq = -1e18, None
    for seq in itertools.product(range(v), repeat=T):
        lp, prev = 0.0, start
        for t in seq:
            lp += lsm[prev][t]
            prev = t
        if lp > best_lp:
            best_lp, best_seq = lp, seq
    return best_lp, best_seq


def test_beam_search_finds_exhaustive_optimum():
    rs = np.random.RandomState(0)
    v, T = 5, 4
    table = rs.randn(v, v).astype(np.float32) * 2.0
    cell = _BigramCell(table)
    # end_token outside the active vocab: no early stopping in this test
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=v - 1,
                               beam_size=5)
    table2 = table.copy()
    table2[:, v - 1] = -100.0  # make end token never optimal
    cell2 = _BigramCell(table2)
    dec = nn.BeamSearchDecoder(cell2, start_token=0, end_token=v - 1,
                               beam_size=5)
    states = jnp.zeros((1, 1), jnp.float32)
    seqs, lps = nn.dynamic_decode(dec, states, max_step_num=T)
    assert seqs.shape == (1, T, 5) and lps.shape == (1, 5)
    got = tuple(int(t) for t in np.asarray(seqs)[0, :, 0])
    want_lp, want = _exhaustive_best(table2, 0, T)
    assert got == want, (got, want)
    np.testing.assert_allclose(float(lps[0, 0]), want_lp, rtol=1e-5)
    # beams are sorted best-first
    assert np.all(np.diff(np.asarray(lps)[0]) <= 1e-6)


def test_beam_search_end_token_freezes_beam():
    v = 4
    table = np.full((v, v), -5.0, np.float32)
    table[0, 3] = 5.0   # start → end immediately is the best move
    table[3, 1] = 5.0   # would extend if not finished
    cell = _BigramCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=3,
                               beam_size=2)
    seqs, lps = nn.dynamic_decode(dec, jnp.zeros((1, 1)), max_step_num=3)
    top = np.asarray(seqs)[0, :, 0]
    # once finished, the beam keeps emitting end_token at zero cost
    assert top[0] == 3 and (top[1:] == 3).all(), top


def test_batch_independence():
    rs = np.random.RandomState(1)
    v, T = 4, 3
    table = rs.randn(v, v).astype(np.float32)
    cell = _BigramCell(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=v - 1,
                               beam_size=3)
    one, lp1 = nn.dynamic_decode(dec, jnp.zeros((1, 1)), max_step_num=T)
    two, lp2 = nn.dynamic_decode(dec, jnp.zeros((2, 1)), max_step_num=T)
    np.testing.assert_array_equal(np.asarray(two)[0], np.asarray(one)[0])
    np.testing.assert_array_equal(np.asarray(two)[1], np.asarray(one)[0])
