"""Extension functionals closing the nn.functional parity gap
(ref: nn/functional/extension.py, vision.py, loss.py:472/:1841,
common.py:2008). Oracles: reference docstring examples + numpy DP."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.nn import functional as F


def test_sequence_mask():
    m = F.sequence_mask(jnp.asarray([3, 1, 0]), maxlen=4)
    assert np.asarray(m).tolist() == [[1, 1, 1, 0], [1, 0, 0, 0],
                                      [0, 0, 0, 0]]
    # maxlen=None → max(x); reference docstring example
    m2 = F.sequence_mask(jnp.asarray([10, 9, 8]))
    assert m2.shape == (3, 10)
    assert np.asarray(m2).sum() == 27


def test_gather_tree_reference_example():
    ids = jnp.asarray([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                       [[0, 1], [9, 0]]])
    par = jnp.asarray([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                       [[0, 0], [0, 1]]])
    out = F.gather_tree(ids, par)
    assert np.asarray(out).tolist() == [[[2, 2], [1, 6]], [[3, 3], [6, 1]],
                                        [[0, 1], [9, 0]]]


def _ed_np(a, b):
    m, n = len(a), len(b)
    D = np.zeros((m + 1, n + 1), int)
    D[:, 0] = range(m + 1)
    D[0, :] = range(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            D[i, j] = min(D[i - 1, j] + 1, D[i, j - 1] + 1,
                          D[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return D[m, n]


def test_edit_distance_matches_numpy_dp():
    inp = jnp.asarray([[1, 2, 3], [4, 5, 6], [4, 4, 4], [1, 1, 1]])
    lab = jnp.asarray([[1, 3, 4, 1], [4, 5, 8, 1], [7, 7, 7, 1],
                       [1, 1, 1, 1]])
    d, n = F.edit_distance(inp, lab, normalized=False)
    want = [float(_ed_np([int(v) for v in inp[i]],
                         [int(v) for v in lab[i]])) for i in range(4)]
    assert np.asarray(d).ravel().tolist() == want
    assert float(n[0]) == 4.0
    # partial lengths
    d2, _ = F.edit_distance(inp, lab, normalized=False,
                            input_length=jnp.asarray([2, 3, 1, 3]),
                            label_length=jnp.asarray([2, 2, 3, 4]))
    want2 = [float(_ed_np([int(v) for v in inp[i][:l1]],
                          [int(v) for v in lab[i][:l2]]))
             for i, (l1, l2) in enumerate([(2, 2), (3, 2), (1, 3), (3, 4)])]
    assert np.asarray(d2).ravel().tolist() == want2
    # normalization divides by label length
    dn, _ = F.edit_distance(inp, lab)
    np.testing.assert_allclose(np.asarray(dn).ravel(),
                               np.asarray(want) / 4.0)


def test_temporal_shift():
    x = jnp.asarray(np.arange(2 * 4 * 2 * 2, dtype=np.float32)
                    .reshape(2, 4, 2, 2))
    out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
    assert out.shape == x.shape
    # channel 0 shifts t-1→t: segment 0 gets zeros, segment 1 gets seg 0
    x5 = np.asarray(x).reshape(1, 2, 4, 2, 2)
    o5 = np.asarray(out).reshape(1, 2, 4, 2, 2)
    assert (o5[0, 0, 0] == 0).all()
    np.testing.assert_array_equal(o5[0, 1, 0], x5[0, 0, 0])
    # channel 1 shifts t+1→t; channels 2+ stay
    np.testing.assert_array_equal(o5[0, 0, 1], x5[0, 1, 1])
    assert (o5[0, 1, 1] == 0).all()
    np.testing.assert_array_equal(o5[..., 2:, :, :], x5[..., 2:, :, :])


def test_diag_embed():
    de = F.diag_embed(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(de), np.diag([1.0, 2.0, 3.0]))
    de2 = F.diag_embed(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]), offset=1)
    assert de2.shape == (2, 3, 3)
    assert float(de2[0, 0, 1]) == 1.0 and float(de2[1, 1, 2]) == 4.0
    de3 = F.diag_embed(jnp.asarray([1.0, 2.0]), offset=-1)
    assert float(de3[1, 0]) == 1.0


def test_affine_grid_and_grid_sample_identity():
    theta = jnp.asarray([[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]])
    g = F.affine_grid(theta, [1, 1, 3, 3], align_corners=True)
    assert g.shape == (1, 3, 3, 2)
    np.testing.assert_allclose(np.asarray(g)[0, 0, 0], [-1, -1])
    np.testing.assert_allclose(np.asarray(g)[0, 2, 2], [1, 1])
    x = jnp.asarray(np.random.RandomState(0).rand(1, 2, 5, 5), jnp.float32)
    out = F.grid_sample(x, F.affine_grid(theta, [1, 2, 5, 5]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)
    # nearest + border modes run; shifted grid actually shifts
    shift = jnp.asarray([[[1.0, 0.0, 0.5], [0.0, 1.0, 0.0]]])
    out2 = F.grid_sample(x, F.affine_grid(shift, [1, 2, 5, 5]),
                         mode="nearest", padding_mode="border")
    assert out2.shape == x.shape
    assert not np.allclose(np.asarray(out2), np.asarray(x))


def test_grid_sample_zero_padding_outside():
    x = jnp.ones((1, 1, 4, 4), jnp.float32)
    grid = jnp.full((1, 2, 2, 2), 3.0)  # far outside [-1, 1]
    out = F.grid_sample(x, grid)
    assert np.allclose(np.asarray(out), 0.0)


def test_bilinear():
    rs = np.random.RandomState(0)
    x1 = jnp.asarray(rs.rand(3, 4), jnp.float32)
    x2 = jnp.asarray(rs.rand(3, 5), jnp.float32)
    w = jnp.asarray(rs.rand(6, 4, 5), jnp.float32)
    b = jnp.asarray(rs.rand(6), jnp.float32)
    out = F.bilinear(x1, x2, w, b)
    want = np.einsum("ni,oij,nj->no", x1, w, x2) + np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_margin_cross_entropy_zero_margin_is_ce():
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.uniform(-1, 1, (4, 10)), jnp.float32)
    y = jnp.asarray([1, 2, 3, 4], jnp.int32)
    mce = F.margin_cross_entropy(logits, y, margin1=1.0, margin2=0.0,
                                 margin3=0.0, scale=1.0)
    np.testing.assert_allclose(float(mce), float(F.cross_entropy(logits, y)),
                               atol=1e-5)
    # arcface margin increases the loss on the true class
    mce2 = F.margin_cross_entropy(logits, y)
    assert float(mce2) > float(mce)
    loss, sm = F.margin_cross_entropy(logits, y, return_softmax=True)
    assert sm.shape == logits.shape
    np.testing.assert_allclose(np.asarray(sm).sum(-1), 1.0, atol=1e-5)


def test_class_center_sample():
    rl, sampled = F.class_center_sample(jnp.asarray([2, 5, 7]), 10, 5)
    sampled = np.asarray(sampled)
    assert len(sampled) == 5 and len(set(sampled.tolist())) == 5
    assert {2, 5, 7} <= set(sampled.tolist())
    # positives remap to their index in the sampled list
    rl = np.asarray(rl)
    for lab, r in zip([2, 5, 7], rl):
        assert sampled[r] == lab


def test_sparse_attention_shim():
    offs = jnp.asarray([0, 2, 4, 6, 8])
    cols = jnp.asarray([0, 1, 0, 1, 2, 3, 2, 3])
    q = jnp.asarray(np.random.RandomState(0).rand(1, 1, 4, 8), jnp.float32)
    out = F.sparse_attention(q, q, q, offs, cols)
    assert out.shape == q.shape
    assert np.isfinite(np.asarray(out)).all()


def test_inplace_aliases_and_rnnbase():
    import paddle_tpu.nn as nn
    assert F.relu_ is F.relu and F.elu_ is F.elu and F.softmax_ is F.softmax
    assert issubclass(nn.LSTM, nn.RNNBase)


def test_batch_norm_training_torch_parity_with_dc_offset():
    """Shifted one-pass BN moments: parity with torch even when the
    activations carry a large DC offset (the naive E[x^2]-E[x]^2 form
    cancels catastrophically there) — including the cold-start case
    where the running mean has not caught up."""
    import torch
    from paddle_tpu.nn import functional as F

    rs = np.random.RandomState(0)
    for offset in (0.0, 1000.0):
        x = (rs.randn(4, 8, 5, 5).astype(np.float32) * 0.1 + offset)
        w = rs.randn(8).astype(np.float32)
        b = rs.randn(8).astype(np.float32)
        rm = np.zeros(8, np.float32)   # cold start
        rv = np.abs(rs.randn(8)).astype(np.float32) + 0.5
        out, nm, nv = F.batch_norm(
            jnp.asarray(x), jnp.asarray(rm), jnp.asarray(rv),
            jnp.asarray(w), jnp.asarray(b), training=True, momentum=0.9)
        rm_t = torch.tensor(rm)
        rv_t = torch.tensor(rv)
        want = torch.nn.functional.batch_norm(
            torch.tensor(x), rm_t, rv_t, torch.tensor(w),
            torch.tensor(b), training=True, momentum=0.1)
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(nv), rv_t.numpy(),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(nm), rm_t.numpy(),
                                   rtol=1e-4, atol=1e-4)
