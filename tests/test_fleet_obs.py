"""Fleet observability plane (ISSUE 13): cross-process trace stitch,
FleetStats merge vs single-registry ground truth, the SLO/anomaly
watch (incl. a real SIGSTOP'd replica), the per-request flight
recorder, and the trace-flush-on-hard-kill fix."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu import native, stats
from paddle_tpu.observability import flight, merge, trace
from paddle_tpu.observability.fleet import FleetStats
from paddle_tpu.stats import StatRegistry, _Histogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(not native.is_available(),
                                  reason="native TCPStore unavailable")


@pytest.fixture(autouse=True)
def _clean():
    trace.disable()
    trace.clear()
    flight.reset()
    yield
    trace.disable()
    trace.clear()
    flight.reset()
    stats.reset()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_fifo_bound_and_event_cap():
    rec = flight.FlightRecorder(capacity=2, max_events=3)
    rec.record("a", "submit", x=1)
    rec.record("b", "submit")
    for i in range(5):
        rec.record("b", f"e{i}")
    rec.record("c", "submit")          # evicts the OLDEST request (a)
    assert rec.events("a") == []
    assert rec.dropped == 1
    # per-request cap keeps only the newest max_events
    assert [e["event"] for e in rec.events("b")] == ["e2", "e3", "e4"]
    assert rec.events("c")[0]["event"] == "submit"
    # capacity 0 disables recording entirely
    off = flight.FlightRecorder(capacity=0)
    off.record("x", "submit")
    assert off.events("x") == [] and not off.enabled


def test_flight_dump_writes_json_and_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("PT_FLIGHT_DIR", str(tmp_path))
    flight.record("rq-9", "submit", prompt=4)
    flight.record("rq-9", "evicted", reason="deadline")
    rec = flight.dump("rq-9", "deadline exceeded")
    # pid-suffixed: router and replicas share the dump dir and both
    # may dump the SAME rid — their views must not clobber each other
    assert rec["path"] == str(
        tmp_path / f"flight_rq-9.{os.getpid()}.json")
    on_disk = json.load(open(rec["path"]))
    assert on_disk["reason"] == "deadline exceeded"
    assert [e["event"] for e in on_disk["events"]] == ["submit",
                                                       "evicted"]
    assert stats.get("serve/flight_dumps") == 1
    # nothing tracked -> no dump, no counter
    assert flight.dump("unknown", "x") is None
    assert stats.get("serve/flight_dumps") == 1


def test_flight_dump_on_deadline_eviction_contains_handoff_hop(
        tmp_path, monkeypatch):
    """A handed-off request deadline-evicted on the decode side dumps a
    flight record whose timeline still shows the handoff hop — the
    postmortem needs no re-run under tracing."""
    import jax.numpy as jnp
    from paddle_tpu.models import gpt
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    from paddle_tpu.serving import FrontEnd
    monkeypatch.setenv("PT_FLIGHT_DIR", str(tmp_path))
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    rs = np.random.RandomState(3)
    prompt = [int(x) for x in rs.randint(0, 96, size=150)]
    pe = PagedDecodeEngine(model, n_pages=48, max_slots=2,
                           prefill_only=True)
    r = pe.submit(prompt, max_new_tokens=8, req_id="rq-hop")
    while not r.tokens:
        pe.step()
    meta, k, v = pe.detach_handoff(r)
    assert meta["rid"] == "rq-hop"     # trace context rides the meta
    de = FrontEnd(PagedDecodeEngine(model, n_pages=48, max_slots=2))
    sreq = de.submit_handoff(meta, k, v, deadline_s=1e-4,
                             req_id="rq-hop")
    time.sleep(0.02)                   # expire while queued
    de.step()
    assert sreq.done and sreq.failed, (sreq.status, sreq.error)
    path = tmp_path / f"flight_rq-hop.{os.getpid()}.json"
    assert path.exists(), "deadline eviction did not dump the record"
    events = [e["event"] for e in json.load(open(path))["events"]]
    assert "handoff-detach" in events, events
    assert "handoff-admitted" in events, events
    assert "evicted" in events, events


# ---------------------------------------------------------------------------
# FleetStats: merge + watch
# ---------------------------------------------------------------------------

def test_fleetstats_hist_merge_matches_union_ground_truth():
    """Acceptance: the FleetStats-merged p99 TTFT equals the p99 of
    the union of per-replica raw samples within one histogram bucket
    (growth 2^1/4) — bucket-wise merge is exact, so it is EQUAL."""
    rs = np.random.RandomState(0)
    regs = [StatRegistry() for _ in range(3)]
    truth = StatRegistry()
    for i, reg in enumerate(regs):
        for v in rs.lognormal(mean=-3.0 + i * 0.5, sigma=0.7,
                              size=400):
            reg.observe("serve/ttft_s", float(v))
            truth.observe("serve/ttft_s", float(v))
        reg.add("serve/queue_backfill", 10 * (i + 1))
    fleet = FleetStats()
    for i, reg in enumerate(regs):
        fleet.ingest(f"r{i}", export=reg.export(rank=0))
    merged = fleet.merged()
    mh, th = (merged.histogram("serve/ttft_s"),
              truth.histogram("serve/ttft_s"))
    assert mh.count == th.count == 1200
    for q in (50, 90, 99):
        assert mh.percentile(q) == th.percentile(q)
        # and the (weaker) acceptance bound: within one 2^1/4 bucket
        assert (max(mh.percentile(q), 1e-12)
                / max(th.percentile(q), 1e-12)) <= _Histogram.GROWTH
    # counters sum; per-replica gauges namespace by rid
    assert merged.get("serve/queue_backfill") == 60
    # latest-export-wins: re-ingesting a newer snapshot REPLACES, so
    # cumulative exports never double-count
    regs[0].add("serve/queue_backfill", 5)
    fleet.ingest("r0", export=regs[0].export(rank=0))
    assert fleet.merged().get("serve/queue_backfill") == 65


def test_fleetstats_stall_alert_edge_triggered_names_replica():
    fleet = FleetStats(stall_after_s=5.0)
    busy = {"queued": 1, "busy_slots": 1, "tokens": 100}
    fleet.ingest("r0", load=dict(busy), alive=True, now=0.0)
    assert fleet.watch(now=1.0) == []
    # tokens frozen past the window while busy and alive -> one alert
    fleet.ingest("r0", load=dict(busy), alive=True, now=6.0)
    assert fleet.watch(now=6.0) == ["stalled_replica"]
    assert "r0" in fleet.alerts[-1]["msg"]
    assert stats.get("fleet/alert_stalled_replica") == 1
    # edge-triggered: same incident never re-fires
    assert fleet.watch(now=8.0) == []
    assert stats.get("fleet/alert_stalled_replica") == 1
    # progress clears the incident...
    fleet.ingest("r0", load=dict(busy, tokens=150), now=9.0)
    assert fleet.watch(now=9.0) == []
    # ...and a NEW stall re-arms and fires again
    fleet.ingest("r0", load=dict(busy, tokens=150), now=20.0)
    assert fleet.watch(now=20.0) == ["stalled_replica"]
    assert stats.get("fleet/alert_stalled_replica") == 2
    # an IDLE replica with frozen tokens is not stalled
    fleet2 = FleetStats(stall_after_s=1.0)
    idle = {"queued": 0, "busy_slots": 0, "tokens": 7}
    fleet2.ingest("r1", load=dict(idle), now=0.0)
    fleet2.ingest("r1", load=dict(idle), now=10.0)
    assert fleet2.watch(now=10.0) == []
    # the stall-presence horizon always covers the stall window — a
    # tight membership dead_after (Router's 2s default) must never
    # make the stalled detector unfireable (a SIGSTOP'd replica stops
    # heartbeating too)
    f3 = FleetStats(dead_after=2.0, stall_after_s=5.0)
    assert f3._stall_horizon > f3.stall_after_s
    # a replica gone beyond even the stall horizon is DEAD (the death
    # sweep's business), not stalled
    f3.ingest("r9", load=dict(busy), alive=False, present=False,
              now=0.0)
    f3.ingest("r9", load=dict(busy), alive=False, present=False,
              now=10.0)
    assert f3.watch(now=10.0) == []
    # idle→busy edge re-anchors the progress clock: a long-idle
    # replica receiving its first request must NOT alert on the
    # minutes-old frozen token counter — only stall_after of busy
    # zero-progress counts
    f4 = FleetStats(stall_after_s=5.0)
    idle = {"queued": 0, "busy_slots": 0, "tokens": 42}
    f4.ingest("r0", load=dict(idle), now=0.0)
    f4.ingest("r0", load=dict(idle, queued=1, busy_slots=1), now=60.0)
    assert f4.watch(now=60.0) == []           # just went busy
    assert f4.watch(now=64.0) == []           # 4s busy < 5s window
    f4.ingest("r0", load=dict(idle, queued=1, busy_slots=1), now=66.0)
    assert f4.watch(now=66.0) == ["stalled_replica"]
    # a dead replica's frozen queue_age/pool load never alerts, and a
    # previously-active incident clears instead of sticking forever
    f5 = FleetStats(slo={"queue_age_s": 1.0})
    hot = {"queued": 3, "busy_slots": 1, "tokens": 1,
           "queue_age_s": 9.0}
    f5.ingest("rX", load=dict(hot), now=0.0)
    assert "queue_age" in f5.watch(now=0.0)
    f5.ingest("rX", load=dict(hot), alive=False, present=False,
              now=5.0)
    assert f5.watch(now=5.0) == []
    assert not f5._active                     # incident cleared


def test_fleetstats_queue_age_and_pool_alerts():
    fleet = FleetStats(slo={"queue_age_s": 2.0})
    fleet.ingest("r0", load={"queued": 3, "busy_slots": 1, "tokens": 1,
                             "queue_age_s": 5.0}, now=0.0)
    assert "queue_age" in fleet.watch(now=0.0)
    assert stats.get("fleet/alert_queue_age") == 1
    # pool exhaustion needs an actual paged pool (total_pages > 0)
    fleet.ingest("r1", load={"queued": 2, "busy_slots": 1, "tokens": 1,
                             "total_pages": 16, "free_pages": 0},
                 now=0.1)
    assert "pool_exhausted" in fleet.watch(now=0.2)
    # a pageless (contiguous) engine reporting free_pages 0 never fires
    fleet.ingest("r2", load={"queued": 2, "busy_slots": 1, "tokens": 1,
                             "total_pages": 0, "free_pages": 0},
                 now=0.3)
    before = stats.get("fleet/alert_pool_exhausted")
    fleet.watch(now=0.4)
    assert stats.get("fleet/alert_pool_exhausted") == before


def test_fleetstats_slo_ttft_burn_and_goodput():
    fleet = FleetStats(slo={"ttft_p99_ms": 10.0, "goodput": 100.0})
    reg = StatRegistry()
    for _ in range(50):
        reg.observe("serve/ttft_s", 0.05)      # 50ms >> 10ms target
    busy = {"queued": 1, "busy_slots": 1}
    fleet.ingest("r0", export=reg.export(rank=0),
                 load=dict(busy, tokens=0), now=0.0)
    fired = fleet.watch(now=0.0)
    assert "slo_ttft" in fired
    assert stats.get("fleet/slo_ttft_burn") > 1.0
    assert stats.get("fleet/alert_slo_ttft") == 1
    assert fleet.watch(now=0.5) == []          # edge
    # goodput: 10 tokens over 2s = 5 tok/s < the 100 floor while busy
    fleet.ingest("r0", load=dict(busy, tokens=10), now=2.0)
    fired = fleet.watch(now=2.0)
    assert "slo_goodput" in fired
    assert 0 < stats.get("fleet/goodput_tokens_per_s") < 100.0
    # WINDOWED burn: a recovered window (fast fresh samples) drops the
    # burn below 1 and re-arms the edge — the lifetime-cumulative p99
    # could never come back down after an incident
    for _ in range(30):
        reg.observe("serve/ttft_s", 0.001)
    fleet.ingest("r0", export=reg.export(rank=0), now=3.0)
    assert fleet.watch(now=3.0) == []
    assert stats.get("fleet/slo_ttft_burn") < 1.0
    # ...and a NEW degraded window fires a second alert
    for _ in range(25):
        reg.observe("serve/ttft_s", 0.08)
    fleet.ingest("r0", export=reg.export(rank=0), now=4.0)
    assert "slo_ttft" in fleet.watch(now=4.0)
    assert stats.get("fleet/alert_slo_ttft") == 2
    # a restarted replica's reset token counter clamps to zero
    # contribution — never a negative fleet rate / spurious alert
    fleet.ingest("r0", load=dict(busy, tokens=2), now=6.0)
    fleet.watch(now=6.0)
    assert stats.get("fleet/goodput_tokens_per_s") >= 0.0
    # a restart also shrinks the merged TTFT census (the replica's
    # cumulative export is replaced by a near-empty one): the window
    # RE-ANCHORS instead of disarming on a negative delta, so the
    # next degraded window still alerts
    fresh = StatRegistry()
    for _ in range(2):
        fresh.observe("serve/ttft_s", 0.09)
    fleet.ingest("r0", export=fresh.export(rank=0), now=7.0)
    fleet.watch(now=7.0)               # census shrank: re-anchor
    assert fleet._ttft_window[0] == 2
    for _ in range(25):
        fresh.observe("serve/ttft_s", 0.09)
    fleet.ingest("r0", export=fresh.export(rank=0), now=8.0)
    fleet.watch(now=8.0)               # post-restart window judged
    assert fleet._ttft_window[0] == 27
    assert stats.get("fleet/slo_ttft_burn") > 1.0


def test_fleet_statsz_serves_merged_registry():
    import urllib.request
    reg = StatRegistry()
    reg.add("serve/queue_backfill", 3)
    reg.observe("serve/ttft_s", 0.01)
    fleet = FleetStats()
    fleet.ingest("r0", export=reg.export(rank=0))
    srv = fleet.serve_statsz(0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/statsz", timeout=5) as r:
            doc = json.load(r)
        assert doc["counters"]["serve/queue_backfill"] == 3
        assert doc["histograms"]["serve/ttft_s"]["count"] == 1
        # the per-process default registry is NOT what this serves
        assert "fleet_probe_counter" not in doc["counters"]
        stats.add("fleet_probe_counter")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/statsz?flat=1",
                timeout=5) as r:
            flat = json.load(r)
        assert "fleet_probe_counter" not in flat
    finally:
        fleet._statsz = None
        srv.stop()


def test_fleetstats_jsonl_telemetry(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    fleet = FleetStats(jsonl_path=path)
    reg = StatRegistry()
    reg.observe("serve/ttft_s", 0.02)
    fleet.ingest("r0", export=reg.export(rank=0),
                 load={"queued": 0, "busy_slots": 0, "tokens": 5})
    fleet.append_jsonl()
    fleet.append_jsonl()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["loads"]["r0"]["tokens"] == 5
    assert "serve/ttft_s.p99" in lines[0]["stats"]


# ---------------------------------------------------------------------------
# stitch: request segments from rid-tagged spans
# ---------------------------------------------------------------------------

def _mk_span(name, pid, ts, dur, rid="rq-1", **extra):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": pid,
            "tid": 1, "args": dict({"rid": rid}, **extra)}


def test_request_segments_tile_the_route_span():
    """Boundary-derived segments TILE the client window: queue-wait +
    prefill + kv-transfer + decode + stream == serve/route exactly."""
    evs = [
        _mk_span("serve/route", 0, 1000.0, 900.0, status="done"),
        _mk_span("serve/queue", 1, 1010.0, 180.0),
        _mk_span("serve/admit", 1, 1200.0, 250.0),
        _mk_span("serve/kv_publish", 1, 1430.0, 15.0),
        _mk_span("serve/kv_transfer", 2, 1560.0, 30.0),
        _mk_span("serve/decode", 2, 1600.0, 250.0),
        # an unrelated request must not leak in
        _mk_span("serve/admit", 1, 5000.0, 10.0, rid="rq-2"),
    ]
    summary = merge.request_segments(evs)
    assert set(summary) == {"rq-1", "rq-2"}
    segs = summary["rq-1"]["segments"]
    assert set(segs) == set(merge.REQUEST_SEGMENTS)
    assert segs["queue-wait"] == (1000.0, 200.0)
    assert segs["prefill"] == (1200.0, 250.0)
    assert segs["kv-transfer"] == (1450.0, 150.0)
    assert segs["decode"] == (1600.0, 250.0)
    assert segs["stream"] == (1850.0, 50.0)
    total = sum(d for _, d in segs.values())
    assert total == summary["rq-1"]["client_us"] == 900.0
    assert summary["rq-1"]["pids"] == [0, 1, 2]
    # no kv span -> no kv-transfer segment (same-replica request)
    local = [e for e in evs[:3]] + [
        _mk_span("serve/decode", 1, 1460.0, 300.0)]
    segs2 = merge.request_segments(local)["rq-1"]["segments"]
    assert "kv-transfer" not in segs2


def test_stitch_trace_files_lanes_and_request_process(tmp_path):
    def write(name, events):
        p = tmp_path / name
        with open(p, "w") as f:
            json.dump({"traceEvents": events}, f)
        return str(p)

    paths = [
        write("trace_router.json",
              [_mk_span("serve/route", 0, 100.0, 500.0)]),
        write("trace_pf0.json",
              [_mk_span("serve/admit", 0, 150.0, 100.0)]),
        write("trace_dc0.json",
              [_mk_span("serve/kv_transfer", 0, 270.0, 10.0),
               _mk_span("serve/decode", 0, 300.0, 200.0)]),
    ]
    out, summary = merge.stitch_trace_files(
        paths, str(tmp_path / "stitched.json"))
    assert set(summary["rq-1"]["segments"]) == set(
        merge.REQUEST_SEGMENTS)
    doc = json.load(open(out))
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert lanes == {"router", "pf0", "dc0", "requests"}
    req_events = [e for e in doc["traceEvents"]
                  if e.get("ph") == "X" and e["pid"] == 9999]
    assert {e["name"] for e in req_events} == set(
        merge.REQUEST_SEGMENTS)
    # colliding pids across files (all rank 0) got distinct lanes
    pids = {e["pid"] for e in doc["traceEvents"]
            if e.get("ph") == "X" and e["pid"] != 9999}
    assert len(pids) == 3


def test_trace_flush_survives_sigkill(tmp_path):
    """Satellite: the ring exports only via atexit, so a SIGKILL'd
    process (exactly the interesting one) used to leave NO trace file —
    the periodic flush keeps a partial, loadable export on disk."""
    path = tmp_path / "trace_victim.json"
    script = (
        "import os, signal, time\n"
        "from paddle_tpu.observability import trace\n"
        "trace.complete('serve/decode', time.perf_counter() - 0.01,"
        " rid='rq-k')\n"
        "time.sleep(1.2)\n"
        "os.kill(os.getpid(), signal.SIGKILL)  # atexit never runs\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_TRACE_FILE=str(path), PT_TRACE_FLUSH_S="0.2")
    rc = subprocess.run([sys.executable, "-c", script], env=env,
                        timeout=60).returncode
    assert rc == -signal.SIGKILL
    doc = json.load(open(path))       # atomic rewrite -> valid JSON
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert any(e["name"] == "serve/decode"
               and e["args"].get("rid") == "rq-k" for e in spans)
    # and the flushed file stitches
    summary = merge.request_segments(spans)
    assert "rq-k" in summary


# ---------------------------------------------------------------------------
# real launch-spawned replicas: cross-process stitch + SIGSTOP anomaly
# ---------------------------------------------------------------------------

def _spawn_disagg(store_port, rid, role, launch_port, trace_file=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_KV_WIRE="fp32")
    if trace_file:
        env["FLEETOBS_TRACE_FILE"] = trace_file
        env["PT_TRACE_FLUSH_S"] = "0.25"
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         os.path.join(REPO, "tests", "_disagg_worker.py"),
         str(store_port), rid, role],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _cleanup(router, procs):
    router.shutdown()
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)
    router.close()


@needs_native
def test_cross_process_stitch_real_replicas(tmp_path):
    """Acceptance: one request served through real launch-spawned
    router+prefill+decode processes leaves spans in THREE trace files
    that share its trace id and stitch into one ordered timeline (the
    per-process wall-clock rebase makes the boundaries comparable)."""
    from paddle_tpu.serving import Router
    trace.enable(str(tmp_path / "trace_router.json"))
    router = Router(port=0, dead_after=20.0)
    procs = [
        _spawn_disagg(router.store.port, "pf0", "prefill", 8905,
                      str(tmp_path / "trace_pf0.json")),
        _spawn_disagg(router.store.port, "dc0", "decode", 8906,
                      str(tmp_path / "trace_dc0.json")),
    ]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(5)
        ids = [router.submit(list(rs.randint(0, 96, size=n)),
                             max_new_tokens=8) for n in (150, 60)]
        results = router.drain(timeout=180)
        assert all(results[q]["status"] == "done" for q in ids)
    finally:
        _cleanup(router, procs)
    trace.export()
    trace.disable()
    paths = [str(tmp_path / f"trace_{n}.json")
             for n in ("router", "pf0", "dc0")]
    for p in paths:
        assert os.path.exists(p), p
    out, summary = merge.stitch_trace_files(
        paths, str(tmp_path / "stitched.json"))
    stitched = {q: summary[q] for q in ids if q in summary}
    assert stitched, summary.keys()
    full = {q: i for q, i in stitched.items()
            if {"queue-wait", "prefill", "kv-transfer",
                "decode"} <= set(i["segments"])}
    assert full, {q: sorted(i["segments"]) for q, i in stitched.items()}
    for q, info in full.items():
        # spans for ONE request came from all three processes
        assert len(info["pids"]) >= 3, info
        segs = info["segments"]
        # ordered after clock rebase: queue-wait <= prefill <=
        # kv-transfer <= decode <= stream starts
        starts = [segs[s][0] for s in ("queue-wait", "prefill",
                                       "kv-transfer", "decode",
                                       "stream")]
        assert starts == sorted(starts), segs


@needs_native
def test_anomaly_watch_flags_sigstop_replica():
    """Acceptance: SIGSTOP a busy replica — the stalled-replica
    detector fires within its window, exactly once, NAMING the
    replica (its heartbeat is still inside the generous membership
    dead_after, so the death sweep has not noticed)."""
    from paddle_tpu.serving import Router
    router = Router(port=0, dead_after=25.0)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--master", "127.0.0.1:8907",
         os.path.join(REPO, "tests", "_serve_worker.py"),
         str(router.store.port), "rep0"],
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        router.wait_replicas(1, timeout=90)
        # enough queued decode work that the replica is mid-flight
        # (and stays busy) whenever the SIGSTOP lands
        rqs = [router.submit([1, 2, 3, 4, 5], max_new_tokens=80)
               for _ in range(6)]
        # wait for BUSY + TOKEN PROGRESS before arming the watch: the
        # replica's first-request jit compile is itself a multi-second
        # zero-progress stretch, and an alert fired for it would
        # consume the edge the injected stall is supposed to trip
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll()
            load = router.directory.load("rep0") or {}
            if load.get("busy_slots", 0) > 0 and load.get("tokens",
                                                          0) > 0:
                break
            time.sleep(0.05)
        assert (router.directory.load("rep0") or {}).get("tokens",
                                                         0) > 0
        fleet = router.enable_fleet_stats(refresh_s=0.2,
                                          stall_after_s=1.5)
        fleet.poll()                  # seed progress state pre-stall
        victim_pid = router.directory.members()["rep0"]["pid"]
        os.kill(victim_pid, signal.SIGSTOP)
        try:
            fired = []
            deadline = time.monotonic() + 12
            while time.monotonic() < deadline and not fired:
                fired = [a for a in fleet.poll()
                         if a == "stalled_replica"]
                time.sleep(0.2)
        finally:
            os.kill(victim_pid, signal.SIGCONT)
        assert fired, "detector never flagged the SIGSTOP'd replica"
        assert stats.get("fleet/alert_stalled_replica") == 1
        msg = [a["msg"] for a in fleet.alerts
               if a["kind"] == "stalled_replica"][0]
        assert "rep0" in msg, msg
        results = router.drain(timeout=120)
        assert all(results[q]["status"] == "done" for q in rqs)
    finally:
        _cleanup(router, [proc])
