"""BASELINE row 1 — "LeNet on MNIST: loss convergence parity".

The strongest form of that check available hermetically: the SAME LeNet
(weights copied layer-for-layer) trained on the SAME batches with plain
SGD in paddle_tpu and in torch (CPU), loss curves compared step-by-step.
Any divergence in conv/pool/linear forward, cross-entropy, autodiff, or
the SGD update shows up as a growing gap within a few steps.

ref: python/paddle/vision/models/lenet.py (architecture),
python/paddle/fluid/tests/unittests/test_mnist*.py (the reference's own
convergence tests, which assert loss decrease rather than parity —
torch-parity is a stricter gate available here because torch-cpu is in
the environment)."""

import numpy as np
import jax.numpy as jnp
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu import optimizer as optim  # noqa: E402
from paddle_tpu.vision.models import LeNet  # noqa: E402


class TorchLeNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.features = torch.nn.Sequential(
            torch.nn.Conv2d(1, 6, 3, stride=1, padding=1),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(2, 2),
            torch.nn.Conv2d(6, 16, 5, stride=1, padding=0),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(2, 2))
        self.fc = torch.nn.Sequential(
            torch.nn.Linear(400, 120),
            torch.nn.Linear(120, 84),
            torch.nn.Linear(84, 10))

    def forward(self, x):
        x = self.features(x)
        return self.fc(torch.flatten(x, 1))


def _copy_weights(model, tmodel):
    """paddle_tpu → torch: conv (O,I,H,W) matches; linear (in,out) → t()."""
    with torch.no_grad():
        for src, dst in ((model.features[0], tmodel.features[0]),
                         (model.features[3], tmodel.features[3])):
            dst.weight.copy_(torch.from_numpy(np.asarray(src.weight)))
            dst.bias.copy_(torch.from_numpy(np.asarray(src.bias)))
        for i in range(3):
            src, dst = model.fc[i], tmodel.fc[i]
            dst.weight.copy_(
                torch.from_numpy(np.asarray(src.weight)).t().contiguous())
            dst.bias.copy_(torch.from_numpy(np.asarray(src.bias)))


def test_lenet_losses_match_torch_step_for_step():
    rs = np.random.RandomState(0)
    steps, batch, lr = 8, 32, 0.1
    xs = rs.rand(steps, batch, 1, 28, 28).astype(np.float32)
    ys = rs.randint(0, 10, (steps, batch)).astype(np.int64)

    model = LeNet()
    tmodel = TorchLeNet()
    _copy_weights(model, tmodel)

    # forward parity before any training
    out_p = np.asarray(model(jnp.asarray(xs[0])))
    out_t = tmodel(torch.from_numpy(xs[0])).detach().numpy()
    np.testing.assert_allclose(out_p, out_t, rtol=1e-4, atol=1e-4)

    # paddle_tpu side: functional SGD train loop
    import jax
    opt = optim.SGD(learning_rate=lr)
    params, _ = model.split_params()
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            out = model.merge_params(p)(x)
            return F.cross_entropy(out, y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(grads, opt_state, params)
        return params, opt_state, loss

    topt = torch.optim.SGD(tmodel.parameters(), lr=lr)
    ce = torch.nn.CrossEntropyLoss()

    losses_p, losses_t = [], []
    for i in range(steps):
        params, opt_state, lp = step(params, opt_state,
                                     jnp.asarray(xs[i]),
                                     jnp.asarray(ys[i].astype(np.int32)))
        losses_p.append(float(lp))
        topt.zero_grad()
        lt = ce(tmodel(torch.from_numpy(xs[i])),
                torch.from_numpy(ys[i]))
        lt.backward()
        topt.step()
        losses_t.append(float(lt))

    np.testing.assert_allclose(losses_p, losses_t, rtol=2e-3, atol=2e-3)
    # and training actually trains
    assert losses_p[-1] < losses_p[0]
