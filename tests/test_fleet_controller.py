"""Elastic fleet controller (ISSUE 14): shared progress-judged
liveness core, the drain protocol (a draining replica finishes every
request id and never receives a new placement), the autoscaler's
heal/scale decisions, and the preemption-tolerant reshape path
(launch --max_restarts + PT_ELASTIC_RESHAPE resumes training on the
surviving topology via restore_resharded, loss-trajectory parity
pinned)."""

import json
import os
import signal
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from paddle_tpu import native, stats
from paddle_tpu.distributed.liveness import ProgressJudge
from paddle_tpu.distributed.membership import ReplicaDirectory
from paddle_tpu.serving import Router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_WORKER = os.path.join(REPO, "tests", "_serve_worker.py")
TRAIN_WORKER = os.path.join(REPO, "tests", "_elastic_train_worker.py")

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason="native TCPStore unavailable")


# ---------------------------------------------------------------------------
# the shared liveness core
# ---------------------------------------------------------------------------

def test_progress_judge_core():
    j = ProgressJudge()
    assert not j.has("a")
    assert j.stalled_for("a") is None
    # first observation (even of None) counts as progress
    assert j.update("a", 1, now=10.0)
    assert not j.update("a", 1, now=11.0)          # frozen counter
    assert j.stalled_for("a", now=12.0) == 2.0
    assert j.update("a", 2, now=12.0)              # progressed
    assert j.alive("a", ttl=1.0, now=12.5)
    assert not j.alive("a", ttl=1.0, now=14.0)
    # a None read never counts as progress, never resets the clock
    assert not j.update("a", None, now=13.0)
    assert j.stalled_for("a", now=13.0) == 1.0
    j.forget("a")
    assert not j.has("a")


def test_replica_directory_uses_shared_core():
    """The dedupe satellite: ReplicaDirectory's liveness bookkeeping
    IS a ProgressJudge (one implementation, two public surfaces) and
    the progress semantics survived the refactor."""
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        d = ReplicaDirectory(store)
        assert isinstance(d._judge, ProgressJudge)
        obs = ReplicaDirectory(store)
        assert not obs.alive("ghost", dead_after=0.1)
        d.announce("r0", {})
        assert obs.alive("r0", dead_after=0.2)
        time.sleep(0.3)
        assert not obs.alive("r0", dead_after=0.2)  # stalled
        d.heartbeat("r0")
        assert obs.alive("r0", dead_after=0.2)      # resurrected
    finally:
        store.close()


def test_elastic_manager_uses_shared_core():
    """ElasticManager's peer watch runs on the same core: a peer whose
    counter stops progressing is reported dead once; resumption
    re-arms the report."""
    from paddle_tpu.distributed.elastic import ElasticManager
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    deaths = []
    mgr = None
    try:
        mgr = ElasticManager(store, rank=0, world_size=2, ttl=0.3,
                             interval=0.05,
                             on_change=lambda dead: deaths.append(dead))
        store.add("elastic/hb/1", 1)      # peer 1 heartbeats once
        mgr.start()
        deadline = time.monotonic() + 5
        while not deaths and time.monotonic() < deadline:
            time.sleep(0.05)
        assert deaths == [[1]], deaths
    finally:
        if mgr is not None:
            mgr.stop()
        store.close()


# ---------------------------------------------------------------------------
# lifecycle state + drain-aware routing
# ---------------------------------------------------------------------------

def test_replica_lifecycle_state():
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        d = ReplicaDirectory(store)
        assert d.state("r0") == "up"          # never published = up
        d.set_state("r0", "draining")
        assert d.state("r0") == "draining"
        d.set_state("r0", "drained")
        assert d.state("r0") == "drained"
        with pytest.raises(ValueError):
            d.set_state("r0", "retired")
    finally:
        store.close()


def test_router_never_places_on_draining_replica():
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        router = Router(store=store)
        router.directory.announce("a", {})
        router.directory.announce("b", {})
        router.directory.alive = lambda rid, dead_after=0: True
        ids = [router.submit([1, 2, 3], max_new_tokens=2)
               for _ in range(2)]
        assert {router._assigned[q] for q in ids} == {"a", "b"}
        router.mark_draining("a")
        assert "a" not in router.replicas()
        more = [router.submit([1, 2, 3], max_new_tokens=2)
                for _ in range(4)]
        assert all(router._assigned[q] == "b" for q in more)
    finally:
        store.close()


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

def _sig(**kw):
    base = dict(n_alive=2, queued=0, busy_slots=0, total_slots=4,
                occupancy=0.0, queue_age_s=0.0, free_pages=0,
                total_pages=0, ttft_burn=0.0, goodput=0.0)
    base.update(kw)
    return base


def test_target_occupancy_policy_hysteresis():
    from paddle_tpu.fleet import TargetOccupancyPolicy
    p = TargetOccupancyPolicy(low=0.25, high=0.85, up_sustain_s=1.0,
                              down_sustain_s=5.0, queue_age_s=5.0)
    # inside the band: hold forever
    assert p.decide(_sig(occupancy=0.5, busy_slots=2), now=0.0)[0] == 0
    assert p.decide(_sig(occupancy=0.5, busy_slots=2), now=99.0)[0] == 0
    # pressure must SUSTAIN before firing
    assert p.decide(_sig(occupancy=0.95, busy_slots=4), now=100.0)[0] == 0
    delta, reason = p.decide(_sig(occupancy=0.95, busy_slots=4),
                             now=101.5)
    assert delta == 1 and "occupancy" in reason
    # a blip back into the band resets the anchor
    p.reset()
    assert p.decide(_sig(occupancy=0.95, busy_slots=4), now=200.0)[0] == 0
    assert p.decide(_sig(occupancy=0.5, busy_slots=2), now=200.5)[0] == 0
    assert p.decide(_sig(occupancy=0.95, busy_slots=4), now=201.0)[0] == 0
    # queue age and TTFT burn are scale-up pressure too
    p2 = TargetOccupancyPolicy(up_sustain_s=0.0)
    assert p2.decide(_sig(queue_age_s=9.0), now=0.0)[0] == 1
    p2.reset()
    assert p2.decide(_sig(ttft_burn=1.4), now=0.0)[0] == 1
    p2.reset()
    assert p2.decide(_sig(total_pages=8, free_pages=0, queued=3),
                     now=0.0)[0] == 1
    # scale-down needs a LONG idle stretch with empty queues
    assert p.decide(_sig(occupancy=0.1, busy_slots=0), now=300.0)[0] == 0
    assert p.decide(_sig(occupancy=0.1, busy_slots=0), now=304.0)[0] == 0
    assert p.decide(_sig(occupancy=0.1, busy_slots=0),
                    now=305.5)[0] == -1
    # queued work vetoes idleness
    p.reset()
    assert p.decide(_sig(occupancy=0.1, queued=1), now=400.0)[0] == 0
    assert p.decide(_sig(occupancy=0.1, queued=1), now=999.0)[0] == 0


def test_fleet_signals_role_view():
    from paddle_tpu.observability.fleet import FleetStats
    fs = FleetStats(directory=None)
    fs.ingest("pf0", load={"role": "prefill", "queued": 3,
                           "busy_slots": 1, "free_slots": 1,
                           "queue_age_s": 2.0, "tokens": 10})
    fs.ingest("dc0", load={"role": "decode", "queued": 1,
                           "busy_slots": 2, "free_slots": 0,
                           "free_pages": 4, "total_pages": 16,
                           "queue_age_s": 7.5, "tokens": 99})
    fs.ingest("dead", load={"role": "decode", "queued": 9},
              alive=False, present=False)
    pf = fs.signals("prefill")
    assert pf["n_alive"] == 1 and pf["queued"] == 3
    assert pf["occupancy"] == 0.5
    dc = fs.signals("decode")
    assert dc["replicas"] == ["dc0"]      # dead replica excluded
    assert dc["occupancy"] == 1.0 and dc["queue_age_s"] == 7.5
    assert dc["free_pages"] == 4 and dc["total_pages"] == 16
    both = fs.signals(None)
    assert both["n_alive"] == 2 and both["queued"] == 4
    assert both["total_slots"] == 4 and both["busy_slots"] == 3


# ---------------------------------------------------------------------------
# controller (in-process, fake spawn)
# ---------------------------------------------------------------------------

def test_controller_heals_below_floor_then_drains():
    from paddle_tpu.fleet import FleetController, ScalePolicy, TierSpec
    stats.reset("fleet/controller")
    store = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        router = Router(store=store, dead_after=30.0)
        spawned = []

        def spawn(role, rid):
            spawned.append((role, rid))
            return types.SimpleNamespace()

        class Hold(ScalePolicy):
            def __init__(self):
                self.delta = 0

            def decide(self, sig, now=None):
                return self.delta, "forced"

        policy = Hold()
        ctl = FleetController(
            router, spawn,
            tiers=[TierSpec("both", min_replicas=1, max_replicas=2,
                            policy=policy)],
            cooldown_s=0.0, drain_grace_s=60.0)
        # empty fleet: heal up to the floor, exactly once (the pending
        # spawn counts until it announces — no double-spawn)
        ctl.step()
        assert len(spawned) == 1 and spawned[0][0] == "both"
        ctl.step()
        assert len(spawned) == 1
        assert stats.get("fleet/controller_scale_ups") == 1
        # the spawned replica announces -> alive, pending cleared
        rid = spawned[0][1]
        d = ReplicaDirectory(store)
        d.announce(rid, {"pid": 0})
        d.heartbeat(rid, load={"role": "both", "busy_slots": 0,
                               "free_slots": 2, "tokens": 0})
        out = ctl.step()
        assert out["both"]["alive"] == 1 and out["both"]["pending"] == 0
        # a second replica joins; forced scale-down drains ONE victim
        d.announce("extra", {"pid": 0})
        d.heartbeat("extra", load={"role": "both", "busy_slots": 1,
                                   "free_slots": 1, "tokens": 5})
        ctl.step()
        policy.delta = -1
        out = ctl.step()
        assert out["both"]["action"] == "scale-down"
        # victim is the emptier replica (rid: 0 busy slots)
        assert d.state(rid) == "draining"
        assert stats.get("fleet/controller_scale_downs") == 1
        # while draining it is not routable and not counted alive
        assert rid not in router.replicas()
        policy.delta = 0
        # the replica acks the drain -> drain-complete
        d.set_state(rid, "drained")
        ctl.step()
        assert stats.get("fleet/controller_drains_completed") == 1
        assert ctl._draining == {}
        # flight recorder carries the controller's actions
        from paddle_tpu.observability import flight
        evs = [e["event"] for e in flight.events("fleet")]
        assert ("scale-up" in evs and "drain-start" in evs
                and "drain-complete" in evs), evs
    finally:
        store.close()


# ---------------------------------------------------------------------------
# drain protocol, real replica processes
# ---------------------------------------------------------------------------

def _spawn_replica(store_port: int, rid: str, launch_port: int,
                   extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1",
         "--master", f"127.0.0.1:{launch_port}",
         SERVE_WORKER, str(store_port), rid],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def test_drain_replica_completes_streams_no_new_placements():
    """The drain acceptance: a draining replica under active streams
    completes (or redistributes) EVERY request id, never receives a
    new placement, publishes ``drained``, and its process exits on its
    own — zero request-id loss, no SIGKILL needed on the happy path.

    This test pins the PR 14 FINISH-IN-PLACE drain contract (the
    "finished ON rep0" assert below), so the replicas opt out of the
    PR 16 ``PT_DRAIN_MIGRATE`` default — under migration rep0 hands
    its streams to rep1 and the assert can never hold (and the
    sender's KV endpoint may already be gone by the time the survivor
    fetches, demoting the handoff to a from-scratch re-place). The
    migrate path has its own acceptance: test_reshard.py and
    ``tools/ci.sh reshard``."""
    stats.reset("serve/router")
    router = Router(port=0, dead_after=15.0)
    procs = [_spawn_replica(router.store.port, f"rep{i}", 8845 + i,
                            extra_env={"PT_DRAIN_MIGRATE": "0"})
             for i in range(2)]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(3)
        ids = [router.submit(list(rs.randint(0, 96, size=9)),
                             max_new_tokens=24) for _ in range(8)]
        victim_reqs = [q for q, r in router._assigned.items()
                       if r == "rep0"]
        assert victim_reqs, "least-outstanding never placed on rep0?"
        # drain rep0 while its streams are active
        router.mark_draining("rep0")
        post = [router.submit(list(rs.randint(0, 96, size=9)),
                              max_new_tokens=6) for _ in range(6)]
        assert all(router._assigned[q] == "rep1" for q in post), \
            "a draining replica received a new placement"
        results = router.drain(timeout=120)
        assert sorted(results) == sorted(ids + post)
        assert all(r["status"] == "done" for r in results.values())
        # rep0's in-flight work finished ON rep0 (drain ≠ eviction)
        assert any(results[q]["replica"] == "rep0"
                   for q in victim_reqs)
        # the replica published its drain and exited without shutdown
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (router.directory.state("rep0") == "drained"
                    and procs[0].poll() is not None):
                break
            time.sleep(0.1)
        assert router.directory.state("rep0") == "drained"
        assert procs[0].poll() == 0, procs[0].poll()
    finally:
        router.shutdown()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        router.close()


# ---------------------------------------------------------------------------
# reshape: launch-driven preemption tolerance (the training half)
# ---------------------------------------------------------------------------

def test_static_launch_reshape_resumes_resharded(tmp_path):
    """Kill 2 of 4 workers mid-training under PT_ELASTIC_RESHAPE=1:
    the launcher relaunches the group at the surviving count,
    exporting the NEW world size (the env-contract satellite), and the
    trainer replans its mesh + restore_resharded-resumes from the
    newest VERIFIED epoch. The whole trajectory is parity-pinned
    against an uninterrupted single-process reference run."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_ELASTIC_RESHAPE="1", ET_DIE_RANKS="2,3",
               ET_DIE_WORLD="4", ET_DIE_AFTER_EPOCH="1",
               PT_FLAGS_STATS_AT_EXIT="1")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--max_restarts", "2",
         "--master", "127.0.0.1:7921", TRAIN_WORKER,
         str(tmp_path), "6"],
        env=env, capture_output=True, text=True, timeout=360)
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])
    assert "reshaping local group 4->2" in r.stderr, r.stderr[-2000:]
    assert "reshaped 4->2 devices" in r.stderr, r.stderr[-2000:]

    log = [json.loads(line) for line in
           (tmp_path / "loss_log.jsonl").read_text().splitlines()]
    worlds = [e["world"] for e in log]
    assert set(worlds) == {4, 2}, worlds
    v1 = [e for e in log if e["world"] == 4]
    v2 = [e for e in log if e["world"] == 2]
    # resumed one past the newest VERIFIED epoch — never from scratch
    assert v2[0]["epoch"] == v1[-1]["epoch"] + 1 or \
        v2[0]["epoch"] <= v1[-1]["epoch"]
    assert max(e["epoch"] for e in log) == 5

    # loss-trajectory parity: an uninterrupted reference run over the
    # SAME per-epoch data (deterministic synthetic_data) on the final
    # 2-device topology must match every logged epoch's loss
    import jax.numpy as jnp
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.fleet import ElasticTrainer, plan_topology
    from paddle_tpu.fleet.elastic_train import synthetic_data
    from paddle_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    prev_topo = mesh_lib.get_topology()
    try:
        ref = ElasticTrainer(
            gpt.GPT(cfg, seed=0), optim.SGD(learning_rate=0.05),
            str(tmp_path / "ref_ckpt"), n_epochs=6,
            mesh=plan_topology(gpt.GPT(cfg, seed=0), n_devices=2),
            data_fn=synthetic_data(cfg.vocab_size, 12,
                                   cfg.max_seq_len)).run()
    finally:
        mesh_lib.set_topology(prev_topo)
    by_epoch = {e["epoch"]: e["loss"] for e in log}
    for rec in ref:
        assert abs(by_epoch[rec["epoch"]] - rec["loss"]) < 5e-3, (
            rec, by_epoch)


# ---------------------------------------------------------------------------
# chaos: kill a replica AND a trainer; the fleet self-heals
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_kill_replica_and_trainer_fleet_converges(tmp_path):
    """The chaos gate (also run as tools/ci.sh elastic): under live
    traffic a serving replica dies via the testing/faults.py
    serve.loop kill site — the controller heals the fleet back to the
    floor and every request id completes; separately a trainer is
    killed mid-step via the train.step site and the reshape path
    resumes it at the surviving world size."""
    from paddle_tpu.fleet import (FleetController, TierSpec,
                                  launch_spawn)
    stats.reset("fleet/controller")
    router = Router(port=0, dead_after=3.0)
    # replica ctl-both-1 dies after ~150 serve-loop ticks (mid-traffic)
    spawn = launch_spawn(SERVE_WORKER, router.store.port,
                         pass_role=False)
    first = {"env": {"PT_FAULTS": "serve.loop:kill:after=150"}}

    def chaos_spawn(role, rid):
        env = first.pop("env", None)
        s = (launch_spawn(SERVE_WORKER, router.store.port,
                          extra_env=env, pass_role=False)
             if env else spawn)
        return s(role, rid)

    ctl = FleetController(
        router, chaos_spawn,
        tiers=[TierSpec("both", min_replicas=2, max_replicas=3)],
        cooldown_s=1.0, drain_grace_s=10.0)
    try:
        ctl.step()                      # heal 0 -> 2 (first is doomed)
        router.wait_replicas(2, timeout=120)
        rs = np.random.RandomState(5)
        ids = []

        def feed():
            if len(ids) < 30:
                ids.append(router.submit(
                    list(rs.randint(0, 96, size=8)),
                    max_new_tokens=12))

        ctl.pump(25.0, interval_s=0.2, extra=feed)
        results = router.drain(timeout=180)
        assert set(ids) <= set(results)
        assert all(results[q]["status"] == "done" for q in ids)
        # the doomed replica died and was replaced: ≥3 spawns total
        # (2 heal + ≥1 replacement), and the fleet converged to ≥2
        assert stats.get("fleet/controller_scale_ups") >= 3
        assert len(router.replicas()) >= 2
    finally:
        router.shutdown()
        ctl.shutdown()
        router.close()

    # -- trainer half: faults-killed mid-step, reshape resumes --------
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_ELASTIC_RESHAPE="1",
               PT_FAULTS="train.step:kill:after=3")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--max_restarts", "2",
         "--master", "127.0.0.1:7927", TRAIN_WORKER,
         str(tmp_path), "6"],
        env=env, capture_output=True, text=True, timeout=360)
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])
    log = [json.loads(line) for line in
           (tmp_path / "loss_log.jsonl").read_text().splitlines()]
    assert sorted({e["world"] for e in log}) == [3, 4]
    assert max(e["epoch"] for e in log) == 5
