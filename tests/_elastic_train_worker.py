"""Elastic-training worker for tests/test_fleet_controller.py and
tools/elastic_smoke.py: a preemption-tolerant trainer driven through
the real ``distributed/launch.py`` CLI.

Rank 0 trains a tiny GPT through ``fleet.ElasticTrainer`` on a virtual
host-platform mesh of PT_NUM_PROCESSES devices (the single-process
stand-in for one-device-per-rank, same idiom as test_elastic_e2e);
other ranks idle — except the ranks named in ``ET_DIE_RANKS`` while
the world equals ``ET_DIE_WORLD``, which exit(3) as soon as the
trainer has committed ``ET_DIE_AFTER_EPOCH``. With PT_ELASTIC_RESHAPE=1
the launcher then relaunches the group at the surviving worker count
and the trainer replans its mesh + restore_resharded-resumes.

Usage (as the launch CLI's training script):
    ET_DIE_RANKS=2,3 ET_DIE_WORLD=4 PT_ELASTIC_RESHAPE=1 \
    python -m paddle_tpu.distributed.launch --nproc_per_node 4 \
        --max_restarts 2 tests/_elastic_train_worker.py WORKDIR [EPOCHS]
"""

import json
import os
import sys
import time

rank = int(os.environ.get("PT_PROCESS_ID", "0"))
world = int(os.environ.get("PT_NUM_PROCESSES", "1"))
workdir = sys.argv[1]
n_epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 6
done_file = os.path.join(workdir, "done")
job_dir = os.path.join(workdir, "ckpt", "job")

die_ranks = {int(r) for r in
             os.environ.get("ET_DIE_RANKS", "").split(",") if r}
die_world = int(os.environ.get("ET_DIE_WORLD", "0"))
die_after = int(os.environ.get("ET_DIE_AFTER_EPOCH", "1"))


def _epoch_committed(epoch: int) -> bool:
    d = os.path.join(job_dir, f"epoch_{epoch}")
    return os.path.exists(os.path.join(d, "meta.json"))


if rank != 0:
    if rank in die_ranks and world == die_world:
        # die (preemption stand-in) once the trainer has committed the
        # trigger epoch — both die-ranks poll the same fs condition, so
        # they exit together and the launcher reshapes in ONE relaunch.
        # ET_DIE_SIGNAL=kill makes it a hard SIGKILL (the chaos gate's
        # preemption shape) instead of a clean nonzero exit.
        for _ in range(2400):
            if _epoch_committed(die_after):
                break
            time.sleep(0.05)
        if os.environ.get("ET_DIE_SIGNAL") == "kill":
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        os._exit(3)
    while not os.path.exists(done_file):
        time.sleep(0.2)
    sys.exit(0)

# ---- rank 0: ElasticTrainer on a <world>-device virtual mesh ----------
os.environ["XLA_FLAGS"] = (
    " ".join(f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f)
    + f" --xla_force_host_platform_device_count={world}")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu import optimizer as optim  # noqa: E402
from paddle_tpu.fleet import ElasticTrainer, plan_topology  # noqa: E402
from paddle_tpu.fleet.elastic_train import synthetic_data  # noqa: E402
from paddle_tpu.models import gpt  # noqa: E402
from paddle_tpu.testing import faults  # noqa: E402

# PT_FAULTS plumbing (the chaos gate kills the trainer mid-step with
# train.step:kill): only rank 0 installs — the idle ranks must survive
# to be SIGTERMed as healthy group members
faults.install_from_env()

cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                    n_layers=2, n_heads=2, dtype=jnp.float32)
model = gpt.GPT(cfg, seed=0)
opt = optim.SGD(learning_rate=0.05)

epoch_sleep = float(os.environ.get("ET_EPOCH_SLEEP", "0.3"))

trainer = ElasticTrainer(
    model, opt, os.path.join(workdir, "ckpt"), job_id="job",
    n_epochs=n_epochs, keep=3,
    mesh=plan_topology(model, n_devices=world),
    # batch 12 divides every reshape size in 4..1, so dp re-planning
    # never strands a ragged batch shard
    data_fn=synthetic_data(cfg.vocab_size, 12, cfg.max_seq_len),
    log_path=os.path.join(workdir, "loss_log.jsonl"),
    # pace the epochs so the die-ranks' exit lands mid-run, before the
    # world-<die_world> generation can finish on its own
    on_epoch=lambda rec: time.sleep(
        epoch_sleep if world == die_world else 0.0))
records = trainer.run()
with open(os.path.join(workdir, f"records_w{world}.json"), "w") as f:
    json.dump(records, f)
open(done_file, "w").close()
