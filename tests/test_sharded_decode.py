"""TP/DP-sharded KV-cache decode == dense decode (VERDICT r2 item 4a).

Reference analog: HybridParallelInferenceHelper serving TP inference
(fleet/utils/hybrid_parallel_inference.py:23). Here the decode jit runs
with the KV cache sharded P(L, dp, tp, T, D) and block weights constrained
by PARTITION_RULES; on the 8-virtual-device CPU mesh the sharded program
must reproduce the dense program's tokens exactly (greedy, fp32)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.models import gpt


def _model_and_prompt():
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 8)),
        jnp.int32)
    return model, tokens


def test_tp_sharded_decode_matches_dense():
    model, tokens = _model_and_prompt()
    dense = np.asarray(model.generate(tokens, max_new_tokens=12))

    topo = dist.init_mesh(dp=2, tp=4)
    try:
        params, _ = model.split_params()
        sharded_model = model.merge_params(
            gpt.shard_params(params, topo.mesh))
        out = np.asarray(sharded_model.generate(tokens, max_new_tokens=12))
    finally:
        mesh_lib.set_topology(None)
    np.testing.assert_array_equal(out, dense)


def test_sharded_decode_cache_actually_sharded():
    """The decode executable must hold a tp-sharded cache, not a
    replicated one: check the compiled HLO places a sharded zeros cache."""
    model, tokens = _model_and_prompt()
    topo = dist.init_mesh(dp=2, tp=4)
    try:
        params, _ = model.split_params()
        sharded_model = model.merge_params(
            gpt.shard_params(params, topo.mesh))
        b, s0 = tokens.shape
        lowered = jax.jit(lambda p, t, r: gpt._generate_impl(
            sharded_model, b, s0, 64, 4, 0.0, 1.0, 0, None, p, t, r)).lower(
            gpt.shard_params(params, topo.mesh),
            tokens, jax.random.PRNGKey(0))
        txt = lowered.as_text()
        # the (L,B,H,T,D) cache tensor must carry the dp/tp sharding
        # constraint, and block weights must be tp-constrained
        assert any(
            "sharding_constraint" in line and "2x4x4x64x8" in line
            and '"tp"' in line and '"dp"' in line
            for line in txt.splitlines()), "no sharded KV cache in HLO"
        assert any(
            "sharding_constraint" in line and '"tp"' in line
            and "2x32x96" in line          # stacked wqkv (L, d, 3d)
            for line in txt.splitlines()), "block weights not tp-sharded"
    finally:
        mesh_lib.set_topology(None)
