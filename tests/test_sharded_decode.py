"""TP/DP-sharded KV-cache decode == dense decode (VERDICT r2 item 4a).

Reference analog: HybridParallelInferenceHelper serving TP inference
(fleet/utils/hybrid_parallel_inference.py:23). Here the decode jit runs
with the KV cache sharded P(L, dp, tp, T, D) and block weights constrained
by PARTITION_RULES; on the 8-virtual-device CPU mesh the sharded program
must reproduce the dense program's tokens exactly (greedy, fp32)."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.models import gpt


def _model_and_prompt():
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 8)),
        jnp.int32)
    return model, tokens


def test_tp_sharded_decode_matches_dense():
    model, tokens = _model_and_prompt()
    dense = np.asarray(model.generate(tokens, max_new_tokens=12))

    topo = dist.init_mesh(dp=2, tp=4)
    try:
        params, _ = model.split_params()
        sharded_model = model.merge_params(
            gpt.shard_params(params, topo.mesh))
        out = np.asarray(sharded_model.generate(tokens, max_new_tokens=12))
    finally:
        mesh_lib.set_topology(None)
    np.testing.assert_array_equal(out, dense)


def _constraint_tilings(txt, shape):
    """Sharding annotations attached to ``shape``-d tensors in lowered
    text, as per-dim tile counts. Two lowering forms exist: older jax
    prints a named ``sharding_constraint`` op carrying axis names; jax
    0.4.37 lowers straight to ``stablehlo.custom_call @Sharding`` with
    the RESOLVED assignment (``mhlo.sharding = "{devices=[1,2,4,1,1]
    <=[8]}"``) — axis names are gone, so the test checks the tiling
    itself. ``last_tile_dim_replicate`` appends a replication factor
    beyond the tensor rank; returning the raw list and letting callers
    index real dims handles both."""
    import re
    out = []
    for line in txt.splitlines():
        if shape not in line:
            continue
        if "sharding_constraint" not in line and "@Sharding" not in line:
            continue
        m = re.search(r"devices=\[([0-9,]+)\]", line)
        if m:
            out.append([int(x) for x in m.group(1).split(",")])
        elif '"tp"' in line or '"dp"' in line:
            out.append(["named", line])
    return out


def test_sharded_decode_cache_actually_sharded():
    """The decode executable must hold a dp/tp-sharded cache, not a
    replicated one: check the lowered program constrains the zeros
    cache (batch dim over dp, head dim over tp) and the stacked block
    weights (tp on the output channels)."""
    model, tokens = _model_and_prompt()
    topo = dist.init_mesh(dp=2, tp=4)
    try:
        params, _ = model.split_params()
        sharded_model = model.merge_params(
            gpt.shard_params(params, topo.mesh))
        b, s0 = tokens.shape
        lowered = jax.jit(lambda p, t, r: gpt._generate_impl(
            sharded_model, b, s0, 64, 4, 0.0, 1.0, 0, None, p, t, r)).lower(
            gpt.shard_params(params, topo.mesh),
            tokens, jax.random.PRNGKey(0))
        txt = lowered.as_text()
        # the (L,B,H,T,D) cache: dp=2 tiles the batch dim, tp=4 the
        # head dim (resolved form: devices=[1,2,4,1,1])
        cache = _constraint_tilings(txt, "2x4x4x64x8")
        assert any(
            t[1] > 1 and t[2] > 1 if t[0] != "named"
            else ('"tp"' in t[1] and '"dp"' in t[1])
            for t in cache), f"no dp/tp-sharded KV cache in HLO: {cache}"
        # stacked wqkv (L, d, 3d): tp must tile the output-channel dim
        wqkv = _constraint_tilings(txt, "2x32x96")
        assert any(
            t[2] > 1 if t[0] != "named" else '"tp"' in t[1]
            for t in wqkv), f"block weights not tp-sharded: {wqkv}"
    finally:
        mesh_lib.set_topology(None)


def test_tp_sharded_decode_engine_matches_dense():
    """Continuous-batching engine on a tp mesh: weights placed by
    PARTITION_RULES, caches head-sharded — the greedy streams must equal
    the single-device engine's exactly (fp32). Mid-flight admission
    keeps working across the sharded prefill."""
    from paddle_tpu.inference.decode_engine import DecodeEngine

    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=8, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 11, 7)]

    eng = DecodeEngine(model, max_slots=2, max_len=48)
    r_dense = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()

    topo = dist.init_mesh(tp=8)
    try:
        eng_tp = DecodeEngine(model, max_slots=2, max_len=48,
                              mesh=topo.mesh)
        assert "tp" in str(eng_tp.kc.sharding.spec)
        r_tp = [eng_tp.submit(p, max_new_tokens=10) for p in prompts]
        eng_tp.step()  # the third request joins mid-flight
        eng_tp.run()
    finally:
        mesh_lib.set_topology(None)
    for a, b in zip(r_dense, r_tp):
        assert a.tokens == b.tokens, (a.tokens, b.tokens)


def test_engine_mesh_rejects_non_tp_axes():
    from paddle_tpu.inference.decode_engine import DecodeEngine

    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    topo = dist.init_mesh(dp=2, tp=4)
    try:
        try:
            DecodeEngine(model, max_slots=2, max_len=48, mesh=topo.mesh)
            raised = False
        except ValueError as e:
            raised = "tp axis only" in str(e)
    finally:
        mesh_lib.set_topology(None)
    assert raised


def test_tp_sharded_chunked_speculative_engine_lossless():
    """TP mesh x speculative_k x steps_per_call: the full composition
    stays bit-identical to the single-device greedy engine."""
    from paddle_tpu.inference.decode_engine import DecodeEngine

    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                        n_layers=2, n_heads=8, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    prompt = [7, 21, 3] * 8
    ref = DecodeEngine(model, max_slots=1, max_len=128)
    r0 = ref.submit(prompt, max_new_tokens=12)
    ref.run()

    topo = dist.init_mesh(tp=8)
    try:
        sp = DecodeEngine(model, max_slots=1, max_len=128,
                          speculative_k=4, steps_per_call=3,
                          mesh=topo.mesh)
        r1 = sp.submit(prompt, max_new_tokens=12)
        sp.run()
    finally:
        mesh_lib.set_topology(None)
    assert r0.tokens == r1.tokens
