"""audio.features / audio.functional vs reference semantics (ref test
pattern: test_audio_functions.py — librosa-oracle checks; here closed-form
properties + shape/energy oracles, no external deps)."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu import audio
from paddle_tpu.audio import functional as AF


def test_mel_hz_roundtrip_both_scales():
    f = jnp.asarray([0.0, 200.0, 999.0, 1000.0, 4000.0, 8000.0])
    for htk in (False, True):
        back = AF.mel_to_hz(AF.hz_to_mel(f, htk=htk), htk=htk)
        np.testing.assert_allclose(back, f, atol=1e-2, rtol=1e-4)


def test_fbank_matrix_properties():
    fb = np.asarray(AF.compute_fbank_matrix(sr=16000, n_fft=512, n_mels=40))
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has support; slaney-normalized peaks < 1
    assert (fb.sum(axis=1) > 0).all()


def test_window_families():
    for name in ("hann", "hamming", "blackman", "triang", "bartlett",
                 "boxcar"):
        w = np.asarray(AF.get_window(name, 64))
        assert w.shape == (64,) and np.isfinite(w).all()
    with pytest.raises(ValueError):
        AF.get_window("nope", 64)


def test_power_to_db_top_db_floor():
    s = jnp.asarray([1.0, 1e-6, 1e-12])
    db = np.asarray(AF.power_to_db(s, top_db=80.0))
    assert db[0] == 0.0
    assert db.min() >= db.max() - 80.0


def test_spectrogram_parseval_sine():
    # a pure tone concentrates energy at its bin
    sr, n_fft = 16000, 512
    t = np.arange(sr, dtype=np.float32) / sr
    wave = np.sin(2 * np.pi * 1000.0 * t)
    spec = audio.Spectrogram(n_fft=n_fft, hop_length=256, power=2.0)(wave)
    spec = np.asarray(spec)
    assert spec.shape[0] == n_fft // 2 + 1
    peak_bin = spec.mean(axis=1).argmax()
    expect = round(1000.0 * n_fft / sr)
    assert abs(int(peak_bin) - expect) <= 1


def test_mel_and_mfcc_shapes_and_finiteness():
    wave = np.random.RandomState(0).normal(size=(2, 8000)).astype(np.float32)
    mel = audio.MelSpectrogram(sr=16000, n_fft=512, hop_length=256,
                               n_mels=40)(wave)
    assert mel.shape[:2] == (2, 40)
    logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, hop_length=256,
                                     n_mels=40)(wave)
    assert np.isfinite(np.asarray(logmel)).all()
    mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, hop_length=256,
                      n_mels=40)(wave)
    assert mfcc.shape[:2] == (2, 13)
    assert np.isfinite(np.asarray(mfcc)).all()


def test_dct_orthonormal():
    d = np.asarray(AF.create_dct(13, 40, norm="ortho"))
    gram = d.T @ d
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)
