"""Resilience subsystem: RetryPolicy/Deadline semantics, fault-injection
harness determinism, deadline-guarded store ops, collective watchdog
stall detection, and p2p recv timeout rollback (ISSUE 2 tentpole)."""

import threading
import time

import pytest

from paddle_tpu import native, stats
from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.resilience import (
    CollectiveStallError, CollectiveWatchdog, Deadline, DeadlineExceeded,
    RetryPolicy, store_get, with_deadline)
from paddle_tpu.testing import faults

pytestmark = pytest.mark.faults

needs_native = pytest.mark.skipif(not native.is_available(),
                                  reason="native toolchain unavailable")


# -- Deadline / RetryPolicy --------------------------------------------------

def test_deadline_budget_and_expiry():
    dl = Deadline(0.05)
    assert dl.budget(10.0) <= 0.05
    assert not dl.expired
    time.sleep(0.06)
    assert dl.expired
    with pytest.raises(DeadlineExceeded, match="frob"):
        dl.check("frob")
    # unbounded deadline never expires and passes the want through
    un = Deadline(None)
    assert un.remaining() is None and not un.expired
    assert un.budget(7.0) == 7.0


def test_retry_policy_recovers_after_transient_failures():
    stats.reset("resilience/")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.001, jitter=0.0,
                        deadline=5.0)
    assert policy.run(flaky, op="unit") == "ok"
    assert calls["n"] == 3
    assert stats.get("resilience/retries") == 2
    assert stats.get("resilience/unit/retries") == 2


def test_retry_policy_exhausts_attempts():
    policy = RetryPolicy(max_attempts=3, base_delay=0.001, deadline=5.0)
    with pytest.raises(TimeoutError):
        policy.run(lambda: (_ for _ in ()).throw(TimeoutError("always")),
                   op="unit")
    assert stats.get("resilience/retries_exhausted") >= 1


def test_retry_policy_absolute_deadline_beats_attempts():
    """With a tiny deadline the policy must give up long before
    max_attempts of backoff, raising DeadlineExceeded."""
    policy = RetryPolicy(max_attempts=1000, base_delay=0.02, jitter=0.0,
                        deadline=0.1)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        policy.run(lambda: (_ for _ in ()).throw(TimeoutError("x")),
                   op="unit")
    assert time.monotonic() - t0 < 2.0


def test_with_deadline_wrapper():
    calls = {"n": 0}

    def sometimes(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("first try fails")
        return x * 2

    guarded = with_deadline(sometimes, seconds=5.0, op="wrapped",
                            policy=RetryPolicy(base_delay=0.001))
    assert guarded(21) == 42


# -- fault-injection harness -------------------------------------------------

def test_faults_deterministic_after_count_window():
    seen = []
    with faults.inject("unit.site", "drop", after=1, count=2):
        for _ in range(5):
            seen.append(faults.fire("unit.site"))
    assert seen == [None, "drop", "drop", None, None]
    assert faults.fire("unit.site") is None  # rule removed with the ctx


def test_inject_replays_identically_without_manual_clear():
    """PR 4 footgun: per-site call indices used to persist across
    inject blocks, so a second identical plan fired at shifted indices
    unless the test remembered faults.clear(). inject() now resets the
    counters on entry; fresh=False restores the accumulating behavior."""
    def plan():
        seen = []
        with faults.inject("unit.replay", "drop", after=1, count=1):
            for _ in range(3):
                seen.append(faults.fire("unit.replay"))
        return seen

    first = plan()
    assert first == [None, "drop", None]
    assert plan() == first          # no faults.clear() in between
    # opt-out: counters accumulate, so the window never re-fires
    with faults.inject("unit.replay", "drop", after=1, count=1,
                       fresh=False):
        assert [faults.fire("unit.replay") for _ in range(3)] == \
            [None] * 3


def test_nested_inject_keeps_other_sites_counters():
    """Entry resets only the entered site: a nested inject for a
    different site must not rewind the outer rule's after= window."""
    seen = []
    with faults.inject("unit.outer", "drop", after=2, count=1):
        seen.append(faults.fire("unit.outer"))     # idx 0
        seen.append(faults.fire("unit.outer"))     # idx 1
        with faults.inject("unit.inner", "delay", seconds=0.0):
            seen.append(faults.fire("unit.outer"))  # idx 2 -> fires
    assert seen == [None, None, "drop"]


def test_faults_raise_and_env_parsing():
    n = faults.install_from_env(
        {"PT_FAULTS": "a.b:raise:exc=ConnectionError,after=1;c.d:delay"})
    assert n == 2
    assert faults.fire("a.b") is None        # index 0 < after
    with pytest.raises(ConnectionError, match="injected"):
        faults.fire("a.b")
    faults.clear()
    assert faults.fire("a.b") is None


def test_faults_transform_corruptions():
    payload = bytes(range(64))
    with faults.inject("t.bits", "bitflip", offset=3, bit=2):
        out = faults.transform("t.bits", payload)
    assert out[3] == payload[3] ^ 4 and len(out) == 64
    with faults.inject("t.cut", "truncate", keep=10):
        assert faults.transform("t.cut", payload) == payload[:10]
    import numpy as np
    with faults.inject("t.nan", "nan"):
        arr = faults.transform("t.nan", np.ones(4, np.float32))
    assert np.isnan(arr).any()


def test_faults_slot_mask():
    import numpy as np
    with faults.inject("t.slots", "nan", slot=2, count=1):
        m1 = faults.slot_mask("t.slots", 4)
        m2 = faults.slot_mask("t.slots", 4)
    np.testing.assert_array_equal(m1, [False, False, True, False])
    assert not m2.any()                      # count=1: one dispatch only


def test_faults_corrupt_file(tmp_path):
    p = tmp_path / "blob"
    p.write_bytes(bytes(100))
    with faults.inject("t.file", "truncate", keep=7):
        faults.corrupt_file("t.file", str(p))
    assert p.stat().st_size == 7


# -- deadline-guarded store ops ---------------------------------------------

@needs_native
def test_store_get_deadline_exceeded_names_key():
    master = native.TCPStore(is_master=True)
    try:
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded, match="never/set"):
            store_get(master, "never/set", deadline=0.3)
        assert time.monotonic() - t0 < 5.0
    finally:
        master.close()


@needs_native
def test_store_get_retries_injected_transient_error():
    master = native.TCPStore(is_master=True)
    try:
        master.set("k", b"v")
        policy = RetryPolicy(max_attempts=5, base_delay=0.001,
                             deadline=5.0)
        with faults.inject("store.get", "raise", exc="ConnectionError",
                           count=2):
            assert store_get(master, "k", deadline=5.0,
                             policy=policy) == b"v"
    finally:
        master.close()


# -- collective watchdog -----------------------------------------------------

@needs_native
def test_watchdog_all_ranks_arrive():
    master = native.TCPStore(is_master=True)
    try:
        stores = [native.TCPStore(port=master.port) for _ in range(2)]
        wds = [CollectiveWatchdog(s, rank=r, world_size=2, group="g1",
                                  deadline=10.0, poll=0.02)
               for r, s in enumerate(stores)]
        errs = []

        def run(r):
            try:
                for _ in range(3):
                    with wds[r].guard("allreduce"):
                        pass
            except Exception as e:        # surfaced to the main thread
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        assert not errs
        assert stats.get("resilience/watchdog_syncs") >= 6
        for s in stores:
            s.close()
    finally:
        master.close()


@needs_native
def test_watchdog_names_stalled_rank():
    """Rank 1 never enters the guarded collective: rank 0 must raise
    CollectiveStallError naming rank 1 within the deadline instead of
    hanging (the acceptance criterion)."""
    master = native.TCPStore(is_master=True)
    try:
        s0 = native.TCPStore(port=master.port)
        wd0 = CollectiveWatchdog(s0, rank=0, world_size=2, group="g2",
                                 deadline=1.0, poll=0.02)
        t0 = time.monotonic()
        with pytest.raises(CollectiveStallError) as ei:
            with wd0.guard("barrier"):
                pass
        assert time.monotonic() - t0 < 10.0
        assert ei.value.stalled_ranks == (1,)
        assert "rank(s) [1]" in str(ei.value)
        assert stats.get("resilience/watchdog_stalls") >= 1
        s0.close()
    finally:
        master.close()


@needs_native
def test_watchdog_straggler_within_deadline_passes():
    """A rank delayed (injected straggle) but inside the deadline must
    NOT trip the watchdog."""
    master = native.TCPStore(is_master=True)
    try:
        stores = [native.TCPStore(port=master.port) for _ in range(2)]
        wds = [CollectiveWatchdog(s, rank=r, world_size=2, group="g3",
                                  deadline=10.0, poll=0.02)
               for r, s in enumerate(stores)]
        errs = []

        def slow_rank():
            try:
                time.sleep(0.3)
                with wds[1].guard("ar"):
                    pass
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=slow_rank)
        t.start()
        with wds[0].guard("ar"):
            pass
        t.join(timeout=30)
        assert not errs
        for s in stores:
            s.close()
    finally:
        master.close()


# -- p2p recv timeout rollback (satellite regression) ------------------------

@needs_native
def test_p2p_recv_timeout_rolls_back_and_recovers(tmp_path):
    """A timed-out recv must roll its sequence claim back (stat bumped
    exactly once) and a subsequent recv still receives messages in
    order — exercised over real processes via tests/_fault_worker.py."""
    import multiprocessing as mp
    import os
    import _fault_worker

    ctx = mp.get_context("spawn")
    # pid-derived: a previous aborted run's TIME_WAIT socket must not
    # collide with this run's store port
    port = 25300 + (os.getpid() % 400) * 2
    procs = [ctx.Process(target=_fault_worker.recv_timeout_worker,
                         args=(r, port, str(tmp_path)))
             for r in range(2)]
    try:
        [p.start() for p in procs]
        [p.join(timeout=120) for p in procs]
        assert all(p.exitcode == 0 for p in procs), \
            [(p.pid, p.exitcode) for p in procs]
        assert os.path.exists(tmp_path / "ok0")
        assert os.path.exists(tmp_path / "ok1")
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
