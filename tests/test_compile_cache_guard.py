"""Persistent-compilation-cache hardening (ISSUE 4 satellite): flaky
cache entries (BENCH r05's RESOURCE_EXHAUSTED read warnings) must be
COUNTED into serve/compile_cache_errors and printed once, never spam or
abort a serving process; enabling a broken cache falls back to cold
compiles instead of raising."""

import warnings

import pytest

from paddle_tpu import compile_cache, stats


@pytest.fixture
def fresh_guard(monkeypatch):
    """Reinstall the guard over a recording stub so the test sees what
    would reach the user, regardless of prior installs in-process."""
    shown = []
    monkeypatch.setattr(
        warnings, "showwarning",
        lambda message, *a, **k: shown.append(str(message)))
    monkeypatch.setattr(compile_cache, "_hook", None)
    monkeypatch.setattr(compile_cache, "_printed", False)
    compile_cache.guard()
    return shown


def test_cache_warnings_counted_and_printed_once(fresh_guard):
    shown = fresh_guard
    stats.reset("serve/compile_cache_errors")
    msg = ("Error reading persistent compilation cache entry for "
           "'jit_convert_element_type': JaxRuntimeError: "
           "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted).")
    for _ in range(3):
        warnings.warn(msg)
    assert stats.get("serve/compile_cache_errors") == 3
    assert sum("persistent compilation cache" in s for s in shown) == 1

    # unrelated warnings pass through untouched and uncounted
    warnings.warn("something else entirely", stacklevel=1)
    assert any("something else" in s for s in shown)
    assert stats.get("serve/compile_cache_errors") == 3


def test_failure_records_class_and_disabled_gauge(fresh_guard):
    """ISSUE 15 satellite: a cache failure is triageable from /statsz —
    per-exception-class counter, the prof/compile_cache_disabled gauge
    latched, and status() carries the class for bench provenance."""
    stats.reset("serve/compile_cache_errors")
    stats.reset("prof/compile_cache_disabled")
    warnings.warn("Error reading persistent compilation cache entry "
                  "for 'jit_x': JaxRuntimeError: RESOURCE_EXHAUSTED: "
                  "TPU backend error (ResourceExhausted).")
    assert stats.get("serve/compile_cache_errors") == 1
    assert stats.get(
        "serve/compile_cache_errors/JaxRuntimeError") == 1
    assert stats.get("prof/compile_cache_disabled") == 1.0
    st = compile_cache.status()
    assert st["disabled"] and st["errors"] == 1
    assert st["last_error_class"] == "JaxRuntimeError"
    # a classless message still counts, under "unknown"
    warnings.warn("Error writing persistent compilation cache entry "
                  "for 'jit_y': disk full")
    assert stats.get("serve/compile_cache_errors/unknown") == 1
    assert compile_cache.status()["errors"] == 2


def test_enable_failure_latches_gauge(fresh_guard, monkeypatch):
    import jax
    stats.reset("serve/compile_cache_errors")
    stats.reset("prof/compile_cache_disabled")

    def boom(*a, **k):
        raise RuntimeError("cache backend unavailable")

    monkeypatch.setattr(jax.config, "update", boom)
    assert compile_cache.enable("/nonexistent/cache/dir") is False
    assert stats.get("serve/compile_cache_errors/RuntimeError") == 1
    assert stats.get("prof/compile_cache_disabled") == 1.0
    assert compile_cache.status()["last_error_class"] == "RuntimeError"


def test_guard_is_idempotent(fresh_guard):
    hook = warnings.showwarning
    compile_cache.guard()
    compile_cache.guard()
    assert warnings.showwarning is hook


def test_guard_reinstalls_after_displacement(fresh_guard):
    """A warnings.catch_warnings() exit (or any library swapping
    showwarning) displaces the hook; the next guard() call — every
    engine construction — must re-install it."""
    displaced = []
    warnings.showwarning = lambda message, *a, **k: \
        displaced.append(str(message))
    compile_cache.guard()
    assert warnings.showwarning is compile_cache._hook
    from paddle_tpu import stats
    stats.reset("serve/compile_cache_errors")
    warnings.warn("Error reading persistent compilation cache entry")
    assert stats.get("serve/compile_cache_errors") == 1
    assert displaced   # chained through to the displaced hook


def test_guard_env_opt_out(fresh_guard, monkeypatch):
    monkeypatch.setenv("PT_COMPILE_CACHE_GUARD", "0")
    hook = warnings.showwarning
    warnings.showwarning = hook2 = lambda *a, **k: None
    compile_cache.guard()
    assert warnings.showwarning is hook2   # untouched
    warnings.showwarning = hook


def test_enable_falls_back_instead_of_raising(fresh_guard, monkeypatch):
    import jax

    stats.reset("serve/compile_cache_errors")

    def boom(*a, **k):
        raise RuntimeError("cache backend unavailable")

    monkeypatch.setattr(jax.config, "update", boom)
    assert compile_cache.enable("/nonexistent/cache/dir") is False
    assert stats.get("serve/compile_cache_errors") == 1


def test_engines_install_guard(monkeypatch):
    import jax.numpy as jnp
    from paddle_tpu.inference.decode_engine import DecodeEngine
    from paddle_tpu.models import gpt

    monkeypatch.setattr(compile_cache, "_hook", None)
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    DecodeEngine(gpt.GPT(cfg, seed=0), max_slots=1, max_len=64)
    assert (compile_cache._hook is not None
            and warnings.showwarning is compile_cache._hook)
