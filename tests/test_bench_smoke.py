"""Execute every ``bench_*`` function in bench.py on tiny CPU shapes.

VERDICT r2 weak 1: ``bench_resnet50`` crashed on the driver's TPU run
because it called an API whose contract had drifted, and no test could
catch it — the function returned ``{}`` early on CPU. These smoke tests run
the SAME code paths (split_params/merge_params/stateful-context/optimizer/
compile) with smoke=True so API drift fails here first.
"""

import sys
import os

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

PEAK = 1e12  # nominal; only affects reported ratios, not execution


def test_bench_gpt_cpu_path():
    res = bench.bench_gpt(jax, jnp, PEAK)
    assert res["metric"] != "bench_failed", res.get("error")
    assert res["value"] > 0
    # bench_decode depends on this attribute being set
    assert getattr(bench.bench_gpt, "model", None) is not None


def test_bench_decode_smoke():
    if getattr(bench.bench_gpt, "model", None) is None:
        bench.bench_gpt(jax, jnp, PEAK)
    out = bench.bench_decode(jax, jnp, PEAK, smoke=True)
    assert any(k.startswith("decode_") and k.endswith("_tokens_per_sec")
               for k in out), out
    # the continuous-batching engine path must run clean in smoke mode
    assert "decode_engine_tokens_per_sec" in out, out
    assert out.get("decode_engine_vs_roofline", 0) > 0, out
    # ...and so must the speculative path (its own try/except means a
    # regression would otherwise vanish silently)
    assert out.get("decode_spec_tokens_per_step", 0) > 0, out
    # paged-spec row revived on the megakernel path (ISSUE 19) — the
    # r05 row death must fail here first, and the verify program must
    # hold the single-dispatch bound (2 pallas launches per step)
    assert out.get("decode_spec_paged_tokens_per_step", 0) > 0, out
    assert 0 < out.get("decode_spec_paged_launches_per_step", 99) <= 2, \
        out
    # kernel-launch ladder row present on the engine path too
    assert "decode_engine_launches_per_token" in out, out


def test_bench_serve_smoke():
    """BENCH_SERVE ladder (ISSUE 10): the deterministic load generator
    must drive the front-end through every rung, and at sub-saturation
    QPS the scheduler must keep the pipeline fed (fed-occupancy well
    above the 1/slots trickling-singletons floor)."""
    out = bench.bench_serve(jax, jnp, PEAK, smoke=True)
    assert out.get("serve_capacity_tokens_per_sec", 0) > 0, out
    for rung in ("sub25", "sub75", "over2x"):
        assert out.get(f"serve_{rung}_p99_ttft_ms", 0) > 0, (rung, out)
        assert out.get(f"serve_{rung}_goodput_tokens_per_sec", 0) > 0, \
            (rung, out)
        assert out.get(f"serve_{rung}_completed_frac", 0) == 1.0, \
            (rung, out)
    # sub-saturation occupancy floor: when demand exceeded free slots,
    # slots were actually filled (trickling singletons would sit at
    # 1/slots = 0.25 here)
    fed = out.get("serve_sub75_fed_occupancy_mean")
    assert fed is not None and fed >= 0.5, out
    assert out.get("serve_over2x_fed_occupancy_mean", 0) >= 0.5, out
    # sustained backlog must trigger retire-time backfill
    assert out.get("serve_over2x_backfills", 0) > 0, out


def test_bench_serve_disagg_smoke():
    """Disaggregated-serving ladder row (ISSUE 12): both the symmetric
    baseline and the prefill→wire→decode pair must serve the full
    over-saturation workload, the KV transfer must actually compress
    (≥3.5x on the int8 default), and the fleet prefix tail must hit
    cross-replica."""
    out = bench.bench_serve_disagg(jax, jnp, PEAK, smoke=True)
    for label in ("symmetric", "disagg"):
        assert out.get(
            f"serve_disagg_{label}_goodput_tokens_per_sec", 0) > 0, out
        assert out.get(
            f"serve_disagg_{label}_completed_frac", 0) == 1.0, out
        assert out.get(f"serve_disagg_{label}_p99_ttft_ms", 0) > 0, out
    # role-tagged TTFT (ISSUE 13): the prefill engine's first-token
    # samples land in their own serve/prefill_s histogram — present in
    # the disagg row — and never pollute the end-to-end TTFT p99
    assert out.get("serve_disagg_prefill_p99_ms", 0) > 0, out
    assert out.get("serve_disagg_kv_bytes_wire", 0) > 0, out
    assert out.get("serve_disagg_kv_ratio") is not None
    assert out["serve_disagg_kv_ratio"] >= 3.5, out
    assert out.get("serve_disagg_kv_transfer_p99_ms", 0) > 0, out
    assert out.get("serve_disagg_fleet_hit_tokens", 0) > 0, out


def test_bench_fleet_churn_smoke():
    """Fleet-churn ladder row (ISSUE 14): both phases must serve every
    request (the kill's unfinished work redistributes, at-least-once),
    the churn phase must actually have redistributed something, and
    the goodput ratio must be computable."""
    out = bench.bench_fleet_churn(jax, jnp, PEAK, smoke=True)
    for label in ("steady", "churn"):
        assert out.get(
            f"fleet_churn_{label}_goodput_tokens_per_sec", 0) > 0, out
        assert out.get(
            f"fleet_churn_{label}_completed_frac", 0) == 1.0, out
        assert out.get(f"fleet_churn_{label}_p99_ttft_ms", 0) > 0, out
    assert out.get("fleet_churn_redistributed", 0) > 0, out
    assert out.get("fleet_churn_goodput_ratio", 0) > 0, out
    # drain-with-migration phase (ISSUE 16): the drain must have moved
    # live requests, completed everything, and bounded its latency
    assert out.get("fleet_churn_drain_completed_frac", 0) == 1.0, out
    assert out.get("fleet_churn_drain_migrated", 0) > 0, out
    assert out.get("fleet_churn_drain_latency_ms", -1) >= 0, out
    assert "fleet_churn_drain_goodput_dip_frac" in out, out
    # router-failover phase (ISSUE 17): journal replay must complete
    # every request (zero id loss through the simulated router death)
    # with a measurable, bounded recovery
    assert out.get("fleet_churn_failover_completed_frac", 0) == 1.0, out
    assert out.get("fleet_churn_failover_goodput_tokens_per_sec",
                   0) > 0, out
    assert out.get("fleet_churn_failover_recovery_s", -1) >= 0, out
    assert out.get("fleet_churn_failover_republished", -1) >= 0, out
    assert "fleet_churn_failover_goodput_dip_frac" in out, out
    # reshape wall-clock rows (in-HBM vs checkpoint round trip) appear
    # whenever >= 4 devices are visible (conftest forces 8 on CPU)
    if len(jax.devices()) >= 4:
        assert out.get("fleet_churn_reshard_inplace_ms", 0) > 0, out
        assert out.get("fleet_churn_reshard_ckpt_ms", 0) > 0, out


def test_bench_train_quant_comm_smoke():
    out = bench.bench_train_quant_comm(jax, jnp, PEAK, smoke=True)
    assert out.get("train_quant_comm_fp32_step_ms", 0) > 0, out
    assert out.get("train_quant_comm_int8_step_ms", 0) > 0, out
    # the loss trajectory must stay glued to the fp32 run at fixed seed
    assert abs(out.get("train_quant_comm_int8_loss_delta", 1)) < 0.1, out
    # and the wire must actually be narrow (int8 block-256 acceptance)
    assert out.get("train_quant_comm_int8_wire_ratio", 0) >= 3.5, out


def test_bench_train_overlap_smoke():
    out = bench.bench_train_overlap(jax, jnp, PEAK, smoke=True)
    for name in ("fp32_on", "fp32_off", "int8_on", "int8_off"):
        assert out.get(f"train_overlap_{name}_step_ms", 0) > 0, out
    # overlap on vs off must be trajectory-matched (same math, only the
    # collective schedule moves)
    assert abs(out.get("train_overlap_fp32_loss_delta", 1)) < 1e-5, out
    assert abs(out.get("train_overlap_int8_loss_delta", 1)) < 1e-4, out
    # the span-tracer accounting made it into the row, with real
    # collective issue spans measured (multi-device conftest mesh)
    assert 0.0 <= out["train_overlap_overlap_frac"] <= 1.0, out
    assert out["train_overlap_comm_busy_s"] > 0, out
    assert out["train_overlap_exposed_s"] >= 0, out


def test_bench_train_numerics_smoke():
    out = bench.bench_train_numerics(jax, jnp, PEAK, smoke=True)
    for name in ("off", "every1", "every16"):
        assert out.get(f"train_numerics_{name}_step_ms", 0) > 0, out
    assert "train_numerics_overhead_frac" in out, out
    # parity: the in-graph stats never feed back into the update
    assert abs(out.get("train_numerics_loss_delta", 1)) < 1e-6, out


def test_bench_train_sharded_stacked_smoke():
    out = bench.bench_train_sharded_stacked(jax, jnp, PEAK, smoke=True)
    assert out.get("train_sharded_stacked_per_layer_step_ms", 0) > 0, out
    assert out.get("train_sharded_stacked_stacked_step_ms", 0) > 0, out
    # fixed-seed parity: stacked is the SAME program, just pre-stacked
    assert abs(out.get("train_sharded_stacked_loss_delta", 1)) < 1e-4, out


def test_bench_bert_smoke():
    out = bench.bench_bert(jax, jnp, PEAK, smoke=True)
    assert out["bert_base_tokens_per_sec_per_chip"] > 0
    assert "bert_base_mfu" in out


def test_bench_resnet50_smoke():
    out = bench.bench_resnet50(jax, jnp, PEAK, smoke=True)
    assert out["resnet50_imgs_per_sec"] > 0
    assert out["resnet50_batch"] == 2


def test_bench_ppyoloe_smoke():
    out = bench.bench_ppyoloe(jax, jnp, PEAK, smoke=True)
    assert out["ppyoloe_s_imgs_per_sec"] > 0
    assert out["ppyoloe_s_batch"] == 2
    # the one-program eval path (forward + jit matrix-NMS) must run clean
    assert out.get("ppyoloe_s_eval_imgs_per_sec", 0) > 0, out


def test_bench_pp_smoke():
    out = bench.bench_pp(jax, jnp, PEAK, smoke=True)
    assert out["pp2_step_ms"] > 0 and out["pp2_dense_step_ms"] > 0
    assert 0 < out["pp2_bubble_theoretical"] < 1


def test_bench_longctx_smoke():
    out = bench.bench_longctx(jax, jnp, PEAK, smoke=True)
    assert out.get("longctx_64_tokens_per_sec", 0) > 0, out
    assert "longctx_64_mfu" in out


def test_bench_nonsmoke_cpu_guards():
    # driver-mode guards: on CPU the TPU-only sub-benches stay silent
    assert bench.bench_bert(jax, jnp, PEAK) == {}
    assert bench.bench_resnet50(jax, jnp, PEAK) == {}
    assert bench.bench_ppyoloe(jax, jnp, PEAK) == {}
    assert bench.bench_pp(jax, jnp, PEAK) == {}
    assert bench.bench_longctx(jax, jnp, PEAK) == {}
    assert bench.bench_train_sharded_stacked(jax, jnp, PEAK) == {}
    assert bench.bench_train_overlap(jax, jnp, PEAK) == {}
    assert bench.bench_serve_disagg(jax, jnp, PEAK) == {}
    assert bench.bench_train_numerics(jax, jnp, PEAK) == {}


def test_split_params_contract():
    """The (params, buffers) contract bench_resnet50 relies on."""
    from paddle_tpu.vision.models import resnet18
    net = resnet18(num_classes=10)
    params, buffers = net.split_params()
    assert isinstance(buffers, dict)
    # BN running stats are buffers, not trainable params
    assert any("_mean" in k or "mean" in k for k in buffers), \
        list(buffers)[:5]
    assert not (set(params) & set(buffers))
    merged = net.merge_params({**buffers, **params})
    assert merged is not net
