"""Distributed core tests on the virtual 8-device CPU mesh: mesh builder,
collectives-in-shard_map, sharding annotations (SURVEY §5.8 mapping)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

import paddle_tpu.distributed as dist


def test_init_mesh_shapes():
    topo = dist.init_mesh(dp=2, tp=2, fsdp=2)
    assert topo.get_data_parallel_world_size() == 4  # dp * fsdp
    assert topo.get_model_parallel_world_size() == 2
    assert topo.mesh.devices.size == 8


def test_mesh_degree_mismatch():
    with pytest.raises(ValueError):
        dist.init_mesh(dp=3, tp=2)


def test_psum_inside_shard_map():
    topo = dist.init_mesh(dp=8)
    mesh = topo.mesh

    def f(x):
        return dist.all_reduce(x, axis="dp")

    x = jnp.arange(8.0)
    out = jax.shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_all_gather_and_reduce_scatter():
    topo = dist.init_mesh(dp=8)
    mesh = topo.mesh
    x = jnp.arange(16.0)

    def gather(x):
        return dist.all_gather(x, axis="dp")

    out = jax.shard_map(gather, mesh=mesh, in_specs=P("dp"),
                        out_specs=P(), check_vma=False)(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(16.0))

    def rs(x):
        return dist.reduce_scatter(x, axis="dp")

    out2 = jax.shard_map(rs, mesh=mesh, in_specs=P(), out_specs=P("dp"),
                         check_vma=False)(jnp.ones((8,)))
    np.testing.assert_allclose(np.asarray(out2), np.full(8, 8.0))


def test_broadcast_from_src():
    topo = dist.init_mesh(dp=8)

    def f(x):
        return dist.broadcast(x, src=3, axis="dp")

    x = jnp.arange(8.0)
    out = jax.shard_map(f, mesh=topo.mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_ring_permute():
    topo = dist.init_mesh(pp=8)

    def f(x):
        return dist.send_recv_ring(x, axis="pp", shift=1)

    x = jnp.arange(8.0)
    out = jax.shard_map(f, mesh=topo.mesh, in_specs=P("pp"),
                        out_specs=P("pp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_shard_tensor_and_reshard():
    topo = dist.init_mesh(dp=2, tp=4)
    x = jnp.ones((8, 16))
    xs = dist.shard_tensor(x, ("dp", "tp"))
    assert xs.sharding == NamedSharding(topo.mesh, P("dp", "tp"))
    xr = dist.reshard(xs, (None, "tp"))
    assert xr.sharding.spec == P(None, "tp")


def test_sharded_matmul_dp_tp():
    """pjit end-to-end: batch sharded over dp, features over tp — XLA inserts
    the collectives (the whole point vs the reference's manual c_ops)."""
    topo = dist.init_mesh(dp=2, tp=4)
    mesh = topo.mesh
    x = jax.device_put(jnp.ones((8, 32)), NamedSharding(mesh, P("dp", None)))
    w = jax.device_put(jnp.ones((32, 64)) * 0.1,
                       NamedSharding(mesh, P(None, "tp")))

    @jax.jit
    def f(x, w):
        return jnp.tanh(x @ w)

    out = f(x, w)
    np.testing.assert_allclose(np.asarray(out),
                               np.tanh(np.full((8, 64), 3.2)), rtol=1e-5)


def test_shard_module_rules():
    import paddle_tpu.nn as nn
    topo = dist.init_mesh(tp=8)
    m = nn.Linear(16, 32)
    m2 = dist.shard_module(m, {r"weight": (None, "tp")})
    assert m2.weight.sharding.spec == P(None, "tp")


def test_new_group_subgroup_collectives():
    """new_group → axis_index_groups: ranks reduce within their part only
    (≙ paddle.distributed.new_group + group= collectives)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import paddle_tpu.distributed as dist

    devices = jax.devices()[:8]
    mesh = Mesh(np.array(devices), ("dp",))
    g = dist.new_group([0, 1, 2, 3], world=8)
    assert g.nranks == 4 and g.get_group_rank(2) == 2
    assert g.get_group_rank(7) == -1
    assert dist.get_group(g.id) is g

    x = jnp.arange(8.0).reshape(8, 1)

    @jax.jit
    def f(x):
        return shard_map(
            lambda v: dist.group_reduce(v, group=g),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    out = np.asarray(f(jax.device_put(
        x, NamedSharding(mesh, P("dp"))))).reshape(-1)
    # ranks 0-3 sum to 6, ranks 4-7 (the complement part) sum to 22
    np.testing.assert_allclose(out[:4], 6.0)
    np.testing.assert_allclose(out[4:], 22.0)

    @jax.jit
    def ga(x):
        return shard_map(
            lambda v: dist.group_all_gather(v, g),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp", None))(x)

    gout = np.asarray(ga(jax.device_put(
        x, NamedSharding(mesh, P("dp")))))
    # each rank returns its part's (4, 1) rows; P("dp", None) concatenates
    # the 8 ranks into (32, 1)
    assert gout.shape == (32, 1)
    np.testing.assert_allclose(gout[0:4, 0], [0, 1, 2, 3])   # rank 0
    np.testing.assert_allclose(gout[16:20, 0], [4, 5, 6, 7])  # rank 4


def test_group_reduce_dtypes_and_validation():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import paddle_tpu.distributed as dist
    import pytest

    with pytest.raises(ValueError):
        dist.new_group([0, 9], world=8)
    with pytest.raises(ValueError):
        dist.new_group([1, 1], world=8)

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    g = dist.new_group([0, 1, 2, 3], world=8)
    x = jnp.arange(1, 9, dtype=jnp.int32).reshape(8, 1)

    @jax.jit
    def f(x):
        return shard_map(
            lambda v: dist.group_reduce(v, op=dist.ReduceOp.MAX, group=g),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    out = f(jax.device_put(x, NamedSharding(mesh, P("dp"))))
    assert out.dtype == jnp.int32          # no float promotion
    np.testing.assert_array_equal(np.asarray(out).reshape(-1)[:4], 4)

    @jax.jit
    def fp(x):
        return shard_map(
            lambda v: dist.group_reduce(v, op=dist.ReduceOp.PROD, group=g),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    pout = np.asarray(fp(jax.device_put(
        x.astype(jnp.float32), NamedSharding(mesh, P("dp")))))
    np.testing.assert_allclose(pout.reshape(-1)[:4], 24.0)  # 1*2*3*4
