"""ERNIE 3.0 family (BASELINE.md "ERNIE-3.0 / BERT-base finetune" row;
configs per PaddleNLP ernie modeling — shares the tuned Bert trunk)."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.models import ernie


def test_task_embedding_changes_output():
    cfg = ernie.ernie3_micro()
    model = ernie.Ernie(cfg, seed=0)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)), jnp.int32)
    seq0, _ = model(toks, task_type_ids=jnp.zeros((2, 16), jnp.int32))
    seq1, _ = model(toks, task_type_ids=jnp.ones((2, 16), jnp.int32))
    assert not np.allclose(np.asarray(seq0), np.asarray(seq1))
    # default task id 0 == explicit zeros
    seq_d, _ = model(toks)
    np.testing.assert_allclose(np.asarray(seq_d), np.asarray(seq0),
                               rtol=1e-5, atol=1e-5)


def test_finetune_loss_decreases():
    cfg = ernie.ernie3_micro()
    model = ernie.ErnieForSequenceClassification(cfg, num_classes=2,
                                                 seed=0)
    from paddle_tpu.nn import functional as F
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    # learnable signal: label = whether first token id is even
    y = jnp.asarray(np.asarray(toks)[:, 0] % 2, jnp.int32)
    params, _ = model.split_params()
    opt = optim.AdamW(learning_rate=5e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            return F.cross_entropy(model.merge_params(p)(toks), y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, new_s = opt.update(grads, state, params)
        return new_p, new_s, loss

    l0 = None
    for _ in range(30):
        params, state, loss = step(params, state)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0 * 0.5, (l0, float(loss))


def test_ernie_shards_with_bert_rules(mesh8):
    """The shared PARTITION_RULES cover ERNIE's params (wtask included
    via the catch-all; the trunk params hit the Megatron specs)."""
    import re
    from jax.sharding import NamedSharding, PartitionSpec as P
    topo = mesh8
    cfg = ernie.ernie3_micro()
    model = ernie.Ernie(cfg, seed=0)

    def spec_for(path):
        for pat, sp in ernie.PARTITION_RULES:
            if re.search(pat, path):
                return sp
        return P()

    params, _ = model.split_params()
    placed = {k: jax.device_put(v, NamedSharding(topo.mesh, spec_for(k)))
              for k, v in params.items()}
    wqkv = placed["bert.layers.item_0.wqkv"]
    assert not wqkv.sharding.is_fully_replicated
    m = model.merge_params(placed)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, 16)), jnp.int32)
    seq, pooled = jax.jit(lambda t: m(t))(toks)
    assert np.isfinite(np.asarray(pooled)).all()
