"""Spawned worker for eager p2p tests (send/recv/isend/irecv over the
native endpoint)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def worker(rank, port, tmpdir):
    from paddle_tpu.distributed import p2p
    p2p.init_p2p(rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        p2p.send(x, dst=1)
        t = p2p.isend(x * 2, dst=1)
        p2p.wait(t)
        back = p2p.recv(src=1)
        np.testing.assert_allclose(back, x + 1)
    else:
        got = p2p.recv(src=0)
        np.testing.assert_allclose(
            got, np.arange(12, dtype=np.float32).reshape(3, 4))
        t = p2p.irecv(src=0)
        got2 = p2p.wait(t)
        np.testing.assert_allclose(got2, got * 2)
        p2p.send(got + 1, dst=0)
    objs = []
    p2p.all_gather_object(objs, {"rank": rank, "sq": rank * rank})
    assert objs == [{"rank": 0, "sq": 0}, {"rank": 1, "sq": 1}], objs
    from paddle_tpu import stats
    assert stats.get("p2p/send_msgs") > 0
    assert stats.get("p2p/send_bytes") > 0
    assert stats.get("p2p/recv_msgs") > 0
    p2p.destroy_process_group()
    open(os.path.join(tmpdir, f"ok{rank}"), "w").close()
