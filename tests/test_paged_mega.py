"""Single-dispatch paged decode (ISSUE 19): the layer-folded megakernel
with fused sampling epilogue vs the per-layer fused reference.

The invariants:
- greedy token STREAMS are bit-identical to the per-layer fused path
  and to gpt.generate on every geometry (mixed lengths, eos, GQA,
  rope) — the megakernel is an execution-plan change, not a math
  change;
- within one step the KV pools match the reference bit-exactly at
  layer 0 and to float-ulp order at layers >= 1 (the mega kernel folds
  the fresh KV row in page order, the per-layer kernel folds it last —
  same set of numbers, different fold order);
- an INACTIVE slot's writes land in the scratch page only: its mapped
  pages stay bit-identical;
- the dispatch program lowers to <= 2 pallas launches per decode step
  (layer-folded kernel + sampling epilogue) on the plain AND
  speculative paths, while the per-layer reference pays one per layer
  — counted from the AOT jaxpr, so the assert is backend-independent;
- warm prefix admission, poison eviction and pipelined depth-2 all
  behave identically to the per-layer path.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.models import gpt
from paddle_tpu.testing import faults


def _model(max_seq=512, heads=4, kv_heads=None, rope=False, layers=2):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=max_seq,
                        d_model=32, n_layers=layers, n_heads=heads,
                        n_kv_heads=kv_heads, dtype=jnp.float32,
                        rope=rope)
    return gpt.GPT(cfg, seed=0)


def _reference(model, prompt, n_new, eos=None):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new, eos_id=eos)
    got = list(np.asarray(out)[0, len(prompt):])
    if eos is not None and eos in got:
        got = got[:got.index(eos) + 1]
    return got


def _run(model, prompts, n_new, **kw):
    eng = PagedDecodeEngine(model, n_pages=14, max_slots=2,
                            steps_per_call=3, **kw)
    reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.run()
    return eng, [r.tokens for r in reqs]


@pytest.mark.parametrize("rope,kvh", [(False, None), (True, 2)])
def test_mega_streams_match_per_layer_and_generate(rope, kvh):
    model = _model(rope=rope, kv_heads=kvh)
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 170, 23)]
    refs = [_reference(model, p, 9) for p in prompts]
    _, mega = _run(model, prompts, 9, mega=True)
    _, plain = _run(model, prompts, 9, mega=False)
    assert mega == refs, (rope, kvh)
    assert plain == refs, (rope, kvh)


def test_mega_eos_parity():
    model = _model()
    rs = np.random.RandomState(3)
    prompt = list(rs.randint(0, 96, size=31))
    ref = _reference(model, prompt, 24, eos=7)
    eng = PagedDecodeEngine(model, n_pages=14, max_slots=2,
                            steps_per_call=4, mega=True)
    req = eng.submit(prompt, max_new_tokens=24, eos_id=7)
    eng.run()
    assert req.tokens == ref


def _kernel_fixture(rope=False):
    """One-step kernel-level fixture: model, per-layer fused reference
    step and mega step over the SAME randomized pools/table."""
    from paddle_tpu.ops.pallas.decode_megakernel import (
        _WEIGHT_ORDER, mega_decode_layers, mega_logits_sample)
    from paddle_tpu.ops.pallas.paged_attention import paged_append_attend
    from jax import lax

    S, PAGE, P, MX = 4, 128, 12, 4
    model = _model(max_seq=PAGE * MX, kv_heads=2, rope=rope)
    cfg = model.cfg
    head = {"wte": model.wte, "wpe": model.wpe,
            "lnf_scale": model.lnf_scale, "lnf_bias": model.lnf_bias,
            "lm_head": model.lm_head}
    stacked = gpt.stack_block_weights(
        [model.blocks[i] for i in range(cfg.n_layers)])
    weights = {n: getattr(stacked, n) for n in _WEIGHT_ORDER}
    scale = 1.0 / math.sqrt(cfg.head_dim)
    L = cfg.n_layers
    scratch = L * P

    rng = np.random.RandomState(0)
    shape = (L * P + 1, cfg.kv_heads, PAGE, cfg.head_dim)
    kp0 = jnp.asarray(rng.randn(*shape), jnp.float32) * 0.1
    vp0 = jnp.asarray(rng.randn(*shape), jnp.float32) * 0.1
    table = jnp.asarray(
        np.stack([np.arange(i * 3, i * 3 + MX, dtype=np.int32) % P
                  for i in range(S)]))
    lengths = jnp.asarray([5, PAGE - 1, PAGE, 2 * PAGE + 7], jnp.int32)
    last = jnp.asarray([3, 17, 42, 90], jnp.int32)
    active = jnp.asarray([True, True, False, True])

    def per_layer_step(kp, vp):
        x = jnp.take(head["wte"], last, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"], lengths, axis=0)
        x = x[:, None, :]
        pidx = jnp.minimum(lengths // PAGE, MX - 1)
        base = jnp.take_along_axis(table, pidx[:, None], axis=1)[:, 0]

        def body(carry, blk_i):
            h, kp, vp = carry
            blk, i = blk_i
            q, k, v = blk._qkv(h, lengths)
            wpids = jnp.where(active, i * P + base, scratch)
            o, kp, vp = paged_append_attend(
                q[:, 0].astype(kp.dtype), kp, vp,
                k[:, 0].astype(kp.dtype), v[:, 0].astype(vp.dtype),
                i * P + table, wpids, lengths, scale=scale)
            h = blk._block_tail(h, o.astype(h.dtype).reshape(h.shape))
            return (h, kp, vp), None

        (x, kp, vp), _ = lax.scan(body, (x, kp, vp),
                                  (stacked, jnp.arange(L)))
        x = gpt.final_ln(x, head["lnf_scale"], head["lnf_bias"])
        w = head["wte"].T if head["lm_head"] is None else head["lm_head"]
        logits = (x @ w)[:, 0]
        tok = jnp.argmax(logits.astype(jnp.float32), -1)
        return kp, vp, tok.astype(jnp.int32)

    def mega_step(kp, vp):
        x = jnp.take(head["wte"], last, axis=0)
        if head["wpe"] is not None:
            x = x + jnp.take(head["wpe"], lengths, axis=0)
        x, kp, vp = mega_decode_layers(
            x, weights, kp, vp, table, lengths,
            jnp.arange(S, dtype=jnp.int32), active.astype(jnp.int32),
            page=PAGE, n_pages=P, n_heads=cfg.n_heads,
            kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            rope=cfg.rope, rope_theta=cfg.rope_theta, scale=scale)
        w = head["wte"].T if head["lm_head"] is None else head["lm_head"]
        tok, _ = mega_logits_sample(
            x, head["lnf_scale"], head["lnf_bias"], w,
            jnp.zeros((S,), bool))
        return kp, vp, tok

    return (kp0, vp0, table, active, per_layer_step, mega_step,
            dict(S=S, P=P, L=L, scratch=scratch))


def test_mega_pool_parity_one_step():
    """Layer-0 pool slab bit-exact vs the per-layer reference; layers
    >= 1 within float-ulp of the fold-order difference; tokens equal."""
    kp0, vp0, _, _, per_layer, mega, geo = _kernel_fixture()
    kpa, vpa, ta = per_layer(kp0, vp0)
    kpb, vpb, tb = mega(kp0, vp0)
    assert (np.asarray(ta) == np.asarray(tb)).all()
    P, sc = geo["P"], geo["scratch"]
    dk0 = np.abs(np.asarray(kpa)[:P] - np.asarray(kpb)[:P]).max()
    dv0 = np.abs(np.asarray(vpa)[:P] - np.asarray(vpb)[:P]).max()
    assert dk0 == 0.0 and dv0 == 0.0, "layer-0 pool slab not bit-exact"
    dk = np.abs(np.asarray(kpa)[:sc] - np.asarray(kpb)[:sc]).max()
    dv = np.abs(np.asarray(vpa)[:sc] - np.asarray(vpb)[:sc]).max()
    assert dk < 1e-6 and dv < 1e-6, (dk, dv)


def test_mega_inactive_slot_writes_scratch_only():
    """An inactive slot's fresh-KV write must land in the scratch page
    (row L*P): every page the slot's table maps stays bit-identical."""
    kp0, vp0, table, active, _, mega, geo = _kernel_fixture()
    kpb, vpb, _ = mega(kp0, vp0)
    P, L = geo["P"], geo["L"]
    inactive = [s for s in range(geo["S"])
                if not bool(np.asarray(active)[s])]
    assert inactive, "fixture lost its inactive slot"
    for s in inactive:
        for i in range(L):
            rows = i * P + np.asarray(table)[s]
            dk = np.abs(np.asarray(kpb)[rows]
                        - np.asarray(kp0)[rows]).max()
            dv = np.abs(np.asarray(vpb)[rows]
                        - np.asarray(vp0)[rows]).max()
            assert dk == 0.0 and dv == 0.0, (s, i)


def test_mega_launch_counts():
    """Acceptance: the fused paged decode step lowers to <= 2 kernel
    launches per step (megakernel + epilogue) — plain AND speculative —
    vs one per layer on the reference path. Counted from the dispatch
    program's jaxpr (scan-trip weighted), so the assert holds on any
    backend; the model has 3 layers so the counts cannot coincide."""
    from paddle_tpu.observability import devprof
    model = _model(layers=3)

    def per_step(**kw):
        eng = PagedDecodeEngine(model, n_pages=20, max_slots=2,
                                steps_per_call=4, **kw)
        fn, args = eng.dispatch_fn_args()
        return devprof.count_pallas_launches(fn, *args) / eng.chunk

    assert per_step(mega=True) == 2
    assert per_step(mega=True, speculative_k=3) == 2
    assert per_step(mega=False) == model.cfg.n_layers


def test_mega_hlo_custom_call_count_is_countable():
    """The AOT-lowering counter must return a number (0 in CPU
    interpret mode — pallas lowers to inline HLO there; one custom-call
    per launch on TPU)."""
    from paddle_tpu.observability import devprof
    model = _model(layers=3)
    eng = PagedDecodeEngine(model, n_pages=20, max_slots=2,
                            steps_per_call=2, mega=True)
    fn, args = eng.dispatch_fn_args()
    n = devprof.count_hlo_custom_calls(fn, *args)
    assert n is not None and n >= 0


@pytest.mark.parametrize("mega", [True, False])
def test_paged_spec_streams_match_generate(mega):
    """Speculative decode revived on the paged path: prompt-lookup
    drafts + the fused verify step must leave greedy streams
    bit-identical to gpt.generate, megakernel and per-layer alike."""
    model = _model()
    rs = np.random.RandomState(1)
    rep = [7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8]   # drafts actually accept
    prompts = [rep, list(rs.randint(0, 96, size=40))]
    refs = [_reference(model, p, 12) for p in prompts]
    _, got = _run(model, prompts, 12, mega=mega, speculative_k=4)
    assert got == refs, mega


@pytest.mark.parametrize("spec", [0, 4])
def test_mega_pipelined_depth2_identical(spec):
    model = _model()
    rs = np.random.RandomState(2)
    rep = [7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8]
    prompts = [rep, list(rs.randint(0, 96, size=40))]
    _, d1 = _run(model, prompts, 10, mega=True, speculative_k=spec,
                 inflight=1)
    _, d2 = _run(model, prompts, 10, mega=True, speculative_k=spec,
                 inflight=2)
    assert d1 == d2


def test_mega_warm_prefix_admission():
    """Second admission of a long prompt rides the radix cache (suffix-
    only prefill) and must decode identically through the megakernel."""
    model = _model()
    rs = np.random.RandomState(4)
    long_p = list(rs.randint(0, 96, size=200))
    eng = PagedDecodeEngine(model, n_pages=14, max_slots=1,
                            steps_per_call=2, mega=True)
    r1 = eng.submit(long_p, max_new_tokens=8)
    eng.run()
    r2 = eng.submit(long_p, max_new_tokens=8)
    eng.run()
    ref = _reference(model, long_p, 8)
    assert r1.tokens == ref and r2.tokens == ref


def test_mega_poison_eviction_scrubs_and_isolates():
    """Non-finite logits through the fused epilogue evict ONLY the
    poisoned slot; the survivor stream is untouched and the retired
    slot's pages return to the pool (free or refcount-zero cached)."""
    model = _model()
    rs = np.random.RandomState(5)
    pa, pb = (list(rs.randint(0, 96, size=n)) for n in (5, 23))
    eng = PagedDecodeEngine(model, n_pages=14, max_slots=2,
                            steps_per_call=2, mega=True)
    ra = eng.submit(pa, max_new_tokens=8)
    rb = eng.submit(pb, max_new_tokens=8)
    with faults.inject("engine.poison_logits", "nan", slot=0):
        eng.run()
    assert ra.failed and "non-finite" in ra.error
    assert not rb.failed and rb.tokens == _reference(model, pb, 8)
    cached = (eng._prefix.cached_pages if eng._prefix is not None
              else 0)
    assert eng.free_pages + cached == 14
