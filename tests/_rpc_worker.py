"""Spawned-worker module for test_rpc_ps. CPU platform pinned at module
level (spawn start-method imports this before jax can initialize)."""

import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def _square(x):
    return x * x


def _matsum(a, b):
    return np.asarray(a) + np.asarray(b)


def _boom():
    raise ValueError("intentional remote failure")


def worker(rank, world, port, tmpdir):
    from paddle_tpu.distributed import rpc, ps

    names = ["server0", "server1", "trainer0"]
    st = rpc.init_rpc(names[rank], rank=rank, world_size=world,
                      master_endpoint=f"127.0.0.1:{port}")

    if rank == 2:  # the single trainer drives; servers just serve
        # --- plain rpc ---
        assert rpc.rpc_sync("server0", _square, args=(7,)) == 49
        got = rpc.rpc_sync("server1", _matsum,
                           args=(np.ones((2, 3)), np.full((2, 3), 2.0)))
        np.testing.assert_allclose(got, np.full((2, 3), 3.0))
        fa = rpc.rpc_async("server0", _square, args=(3,))
        fb = rpc.rpc_async("server1", _square, args=(4,))
        assert fa.wait(30) + fb.wait(30) == 25
        try:
            rpc.rpc_sync("server0", _boom)
            raise AssertionError("remote exception did not propagate")
        except ValueError as e:
            assert "intentional remote failure" in str(e)
        info = rpc.get_worker_info("server1")
        assert info.rank == 1

        # --- parameter server over rpc ---
        client = ps.PSClient(["server0", "server1"])
        client.create_tables({
            "dense_w": ("dense", (4, 3), {"lr": 0.5, "optimizer": "sgd",
                                          "seed": 1}),
            "emb": ("sparse", 8, {"lr": 0.1, "optimizer": "adagrad",
                                  "seed": 2}),
        })
        w0 = client.pull_dense("dense_w")
        g = np.ones((4, 3), np.float32)
        client.push_dense("dense_w", g)
        client.push_dense("dense_w", g)
        w1 = client.pull_dense("dense_w")
        np.testing.assert_allclose(w1, w0 - 0.5 * 2.0, atol=1e-6)

        ids = np.array([0, 1, 5, 9, 12], np.int64)
        rows0 = client.pull_sparse("emb", ids)
        assert rows0.shape == (5, 8)
        # deterministic lazy init: same id pulls the same row
        np.testing.assert_allclose(client.pull_sparse("emb", ids), rows0)
        client.push_sparse("emb", ids, np.ones((5, 8), np.float32))
        rows1 = client.pull_sparse("emb", ids)
        # adagrad first step: -lr * g / (|g| + eps) ≈ -lr
        np.testing.assert_allclose(rows1, rows0 - 0.1, atol=1e-5)
        assert client.sparse_size("emb") == 5

        # --- disk-backed sparse table over the same protocol ---
        client.create_tables({
            "big_emb": ("ssd_sparse", 4, {"dir": tmpdir, "cache_rows": 3,
                                          "lr": 0.5, "optimizer": "sgd",
                                          "seed": 7})})
        big_ids = np.arange(20, dtype=np.int64)   # >> per-server cache
        b0 = client.pull_sparse("big_emb", big_ids)
        client.push_sparse("big_emb", big_ids,
                           np.ones((20, 4), np.float32))
        b1 = client.pull_sparse("big_emb", big_ids)
        np.testing.assert_allclose(b1, b0 - 0.5, atol=1e-6)
        assert client.sparse_size("big_emb") == 20

        # --- save / mutate / load round trip (save_persistables) ---
        snap = os.path.join(tmpdir, "snap")
        files = client.save(snap)
        assert len(files) >= 3  # dense + emb×2 + big_emb×2 shards
        client.push_dense("dense_w", g)           # diverge after snapshot
        client.push_sparse("big_emb", big_ids,
                           np.ones((20, 4), np.float32))
        client.load(snap)
        np.testing.assert_allclose(client.pull_dense("dense_w"), w1,
                                   atol=1e-6)
        np.testing.assert_allclose(client.pull_sparse("big_emb", big_ids),
                                   b1, atol=1e-6)

        # --- geo-async: two worker replicas exchange deltas ---
        geo_a = ps.GeoSGDClient(client, geo_step=2)
        geo_b = ps.GeoSGDClient(client, geo_step=2)
        wa = geo_a.register_dense("dense_w")
        wb = geo_b.register_dense("dense_w")
        start = wa.copy()
        wa -= 0.25   # worker A's local optimizer steps
        geo_a.step()
        geo_a.step()                 # hits geo_step → pushes delta -0.25
        wb -= 0.5    # worker B trained concurrently on the OLD replica
        geo_b.sync()                 # pushes -0.5, pulls A's too
        np.testing.assert_allclose(wb, start - 0.75, atol=1e-6)
        geo_a.sync()                 # A refreshes: sees B's delta now
        np.testing.assert_allclose(wa, start - 0.75, atol=1e-6)
        # sparse geo: touch, train locally, sync
        ra = geo_a.pull_sparse("emb", [1, 5])
        geo_a.update_sparse("emb", [1, 5], ra + 2.0)
        geo_a.sync()
        np.testing.assert_allclose(client.pull_sparse("emb", [1, 5]),
                                   ra + 2.0, atol=1e-5)

        with open(os.path.join(tmpdir, "ok_trainer"), "w") as f:
            f.write("1")

    rpc.shutdown()
