"""Reference-name parity surface of paddle.distributed (round 4):
alltoall/reduce/scatter/split in-mesh, eager p2p send/recv across real
processes, fleet dataset classes. A dir() diff against the reference's
distributed __all__ comes back empty (checked in
test_all_reference_names_exist)."""

import multiprocessing as mp

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu.distributed as dist
from paddle_tpu import native

import _p2p_worker


def test_all_reference_names_exist():
    for name in ["alltoall", "alltoall_single", "reduce", "scatter",
                 "split", "ParallelMode", "stream", "send", "recv",
                 "isend", "irecv", "wait", "all_gather_object",
                 "destroy_process_group", "InMemoryDataset",
                 "QueueDataset", "CountFilterEntry", "ProbabilityEntry",
                 "ShowClickEntry", "launch", "gloo_barrier",
                 "gloo_init_parallel_env", "gloo_release"]:
        assert hasattr(dist, name), name
    assert dist.stream.all_reduce is dist.collective.all_reduce


def test_scatter_and_alltoall_in_mesh():
    topo = dist.init_mesh(dp=8)

    def body(x):
        sc = dist.scatter(jnp.arange(8.0), src=0, axis="dp")
        a2a = dist.alltoall_single(x, axis="dp", split_axis=1,
                                   concat_axis=0)
        return sc, a2a

    x = jnp.arange(64.0).reshape(8, 8)  # dp-sharded rows
    f = shard_map(body, mesh=topo.mesh, in_specs=P("dp"),
                  out_specs=(P("dp"), P(None, "dp")))
    sc, a2a = f(x)
    # scatter: rank i gets chunk i of 0..7 → concatenated back = 0..7
    np.testing.assert_allclose(np.asarray(sc), np.arange(8.0))
    # alltoall resharding identity: a row-sharded matrix comes back as
    # the SAME matrix column-sharded (the distributed transpose)
    np.testing.assert_allclose(np.asarray(a2a),
                               np.arange(64.0).reshape(8, 8))


def test_reduce_lands_on_dst(mesh8):
    topo = dist.init_mesh(dp=8)

    def body(x):
        return dist.reduce(x, dst=2, axis="dp")

    x = jnp.arange(8.0)
    out = shard_map(body, mesh=topo.mesh, in_specs=P("dp"),
                    out_specs=P("dp"))(x)
    out = np.asarray(out)
    assert out[2] == 28.0          # sum lands on dst
    others = [out[i] for i in range(8) if i != 2]
    np.testing.assert_allclose(others, [i for i in range(8) if i != 2])


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_eager_p2p_send_recv(tmp_path):
    import os
    ctx = mp.get_context("spawn")
    # pid-derived: a previous aborted run's TIME_WAIT socket must not
    # collide with this run's store port
    port = 24100 + (os.getpid() % 400) * 2
    procs = [ctx.Process(target=_p2p_worker.worker,
                         args=(r, port, str(tmp_path))) for r in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=120)
    for r, p in enumerate(procs):
        assert p.exitcode == 0, f"rank {r} exited {p.exitcode}"
        assert (tmp_path / f"ok{r}").exists()


def test_in_memory_dataset(tmp_path):
    f = tmp_path / "data.txt"
    f.write_text("\n".join(f"{i} {i * 2}" for i in range(10)))
    ds = dist.InMemoryDataset()
    ds.init(batch_size=2)
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    ds.global_shuffle()
    batches = list(ds)
    assert len(batches) == 5 and batches[0].shape == (2, 2)
    ds.release_memory()
    assert ds.get_memory_data_size() == 0

    qs = dist.QueueDataset()
    qs.init(batch_size=5)
    qs.set_filelist([str(f)])
    assert len(list(qs)) == 2
    with pytest.raises(RuntimeError):
        qs.load_into_memory()


def test_entries():
    assert dist.CountFilterEntry(3).admit(3)
    assert not dist.CountFilterEntry(3).admit(2)
    import random
    assert dist.ProbabilityEntry(1.0).admit(random.Random(0))
    assert dist.ShowClickEntry(1.0, 2.0).score(3, 4) == 11.0


def test_split_column_and_row_parallel():
    """distributed.split (≙ fleet mpu split): column-parallel matmul with
    gather_out reproduces the dense product; row-parallel psum too."""
    topo = dist.init_mesh(tp=8)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(4, 8), jnp.float32)
    w = jnp.asarray(rs.rand(8, 16), jnp.float32)
    dense = np.asarray(x @ w)

    def col(xv, wv):
        return dist.split(xv, wv, operation="linear", axis=1,
                          gather_out=True)

    out = shard_map(col, mesh=topo.mesh,
                    in_specs=(P(), P(None, "tp")),
                    out_specs=P(), check_rep=False)(x, w)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=1e-5)

    def row(xv, wv):
        return dist.split(xv, wv, operation="linear", axis=0)

    out2 = shard_map(row, mesh=topo.mesh,
                     in_specs=(P(None, "tp"), P("tp", None)),
                     out_specs=P(), check_rep=False)(x, w)
    np.testing.assert_allclose(np.asarray(out2), dense, rtol=1e-5)


def test_split_embedding_vocab_parallel():
    topo = dist.init_mesh(tp=8)
    rs = np.random.RandomState(0)
    table = jnp.asarray(rs.rand(16, 4), jnp.float32)  # vocab 16, dim 4
    ids = jnp.asarray([0, 3, 7, 15, 8, 2], jnp.int32)

    def body(idv, tv):
        return dist.split(idv, tv, operation="embedding")

    out = shard_map(body, mesh=topo.mesh,
                    in_specs=(P(), P("tp", None)),
                    out_specs=P(), check_rep=False)(ids, table)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(table)[np.asarray(ids)],
                               rtol=1e-6)
