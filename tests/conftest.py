"""Test harness config: force an 8-virtual-device CPU platform BEFORE jax
initializes, so sharding/mesh tests run without TPU hardware (SURVEY §7 test
strategy — the reference's analog is multi-process localhost NCCL tests,
test_collective_api_base.py; here a virtual mesh in one process suffices
because collectives are compiler constructs)."""

import os

# The environment pins JAX_PLATFORMS=axon (TPU tunnel) via sitecustomize;
# tests must run on a virtual 8-device CPU platform, so override forcibly.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from tier-1 runs")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / resilience test (fast CPU smoke: "
        "tools/ci.sh faults)")

# attach numpy oracles to every registered op (OpTest backbone, SURVEY §4);
# test-only scaffolding, deliberately NOT run on production import
import paddle_tpu  # noqa: E402,F401
from paddle_tpu.ops import oracles as _oracles  # noqa: E402

_oracles.attach_all()


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.testing import faults
    pt.seed(1234)
    np.random.seed(1234)
    mesh_lib.set_topology(None)  # no cross-test global-mesh leakage
    yield
    faults.clear()               # no fault-rule leakage across tests


@pytest.fixture
def mesh8():
    import paddle_tpu.distributed as dist
    return dist.init_mesh(dp=2, tp=2, fsdp=2)
