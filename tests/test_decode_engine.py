"""Continuous-batching decode engine (≙ fused_multi_transformer serving +
the scheduling the reference leaves to paddle-serving).

Key properties under test:
- parity: ragged continuous batching produces exactly the tokens the
  plain per-request `gpt.generate` loop produces (greedy, fp32);
- zero recompiles across admissions/retirements (static slot shapes);
- chunked prefill for prompts longer than the largest bucket;
- mid-flight admission actually shares steps (continuous, not sequential).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)
from paddle_tpu.models import gpt


def _model(n_layers=2, d_model=32, n_heads=4, vocab=96, max_seq=256):
    cfg = gpt.GPTConfig(vocab_size=vocab, max_seq_len=max_seq,
                        d_model=d_model, n_layers=n_layers,
                        n_heads=n_heads, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _reference_tokens(model, prompt, n_new):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new)
    return list(np.asarray(out)[0, len(prompt):])


def test_parity_with_generate_staggered_admissions():
    model = _model()
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (3, 9, 17, 5)]
    n_new = [6, 4, 8, 5]

    eng = DecodeEngine(model, max_slots=2, max_len=128)
    # two requests up front; two more join while the first are in flight
    r0 = eng.submit(prompts[0], n_new[0])
    r1 = eng.submit(prompts[1], n_new[1])
    eng.step()
    eng.step()
    r2 = eng.submit(prompts[2], n_new[2])
    r3 = eng.submit(prompts[3], n_new[3])
    eng.run()

    for req, p, n in zip((r0, r1, r2, r3), prompts, n_new):
        assert req.done
        assert req.tokens == _reference_tokens(model, p, n), \
            f"prompt {p} diverged"


def test_single_compile_across_admissions():
    model = _model()
    eng = DecodeEngine(model, max_slots=2, max_len=128, buckets=(16,))
    rs = np.random.RandomState(1)
    for n in (4, 7, 12, 3, 9):
        eng.submit(list(rs.randint(0, 96, size=n)), max_new_tokens=4)
    eng.run()
    # the plain path is the chunk=1 instance of the chunked dispatch
    assert eng._multi_fn._cache_size() == 1, "decode step recompiled"
    assert eng._prefill_fn._cache_size() == 1, \
        "prefill recompiled despite a single bucket"


def test_chunked_prefill_long_prompt():
    model = _model()
    rs = np.random.RandomState(2)
    prompt = list(rs.randint(0, 96, size=70))  # > largest bucket (32)
    eng = DecodeEngine(model, max_slots=1, max_len=128, buckets=(16, 32))
    req = eng.submit(prompt, max_new_tokens=5)
    eng.run()
    assert req.tokens == _reference_tokens(model, prompt, 5)


def test_eos_retires_slot_early():
    model = _model()
    prompt = [1, 2, 3]
    ref = _reference_tokens(model, prompt, 8)
    eos = ref[2]  # stop at this token's FIRST occurrence
    cut = ref.index(eos) + 1
    eng = DecodeEngine(model, max_slots=1, max_len=128)
    req = eng.submit(prompt, max_new_tokens=8, eos_id=eos)
    eng.run()
    assert req.done and req.tokens == ref[:cut]
    assert eng.num_active == 0


def test_mid_flight_join_is_continuous():
    # with 2 slots and 3 requests, the third must join as soon as a slot
    # frees — total steps stay well below sequential sum
    model = _model()
    eng = DecodeEngine(model, max_slots=2, max_len=128)
    rs = np.random.RandomState(3)
    reqs = [eng.submit(list(rs.randint(0, 96, size=4)), max_new_tokens=6)
            for _ in range(3)]
    steps = 0
    while any(not r.done for r in reqs):
        eng.step()
        steps += 1
    # sequential would take ~3*5 decode steps; batched+continuous stays
    # well below that (the default lag-one pipeline adds ~1 step of
    # harvest lag per admission/retirement boundary)
    assert steps <= 14
    assert all(r.done for r in reqs)


def test_chunked_steps_parity_with_per_token():
    """steps_per_call>1 runs the decode loop device-side (one dispatch per
    chunk); tokens must be identical to the per-token engine, including
    staggered admissions between chunks."""
    model = _model()
    rs = np.random.RandomState(5)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (3, 9, 17, 5)]
    n_new = [6, 4, 13, 5]
    eng = DecodeEngine(model, max_slots=2, max_len=128, steps_per_call=4)
    r0 = eng.submit(prompts[0], n_new[0])
    r1 = eng.submit(prompts[1], n_new[1])
    eng.step()
    r2 = eng.submit(prompts[2], n_new[2])
    r3 = eng.submit(prompts[3], n_new[3])
    eng.run()
    for req, p, n in zip((r0, r1, r2, r3), prompts, n_new):
        assert req.done
        assert req.tokens == _reference_tokens(model, p, n), \
            f"prompt {p} diverged under chunked stepping"


def test_chunked_steps_fewer_dispatches():
    model = _model()
    rs = np.random.RandomState(6)
    eng = DecodeEngine(model, max_slots=2, max_len=128, steps_per_call=8)
    reqs = [eng.submit(list(rs.randint(0, 96, size=4)),
                       max_new_tokens=16) for _ in range(2)]
    eng.run()
    assert all(r.done for r in reqs)
    # 16 tokens after the prefill-sampled first one → 15 decode steps →
    # 2 chunked dispatches (vs 15 per-token)
    assert eng.steps <= 3
    assert eng._multi_fn._cache_size() == 1, "chunked step recompiled"


def test_chunked_eos_stops_mid_chunk():
    """A slot hitting eos inside a chunk must emit nothing after it, and
    its budget/eos accounting must match the per-token engine."""
    model = _model()
    prompt = [1, 2, 3]
    ref = _reference_tokens(model, prompt, 8)
    eos = ref[2]
    cut = ref.index(eos) + 1
    eng = DecodeEngine(model, max_slots=1, max_len=128, steps_per_call=8)
    req = eng.submit(prompt, max_new_tokens=8, eos_id=eos)
    eng.run()
    assert req.done and req.tokens == ref[:cut]
    assert eng.num_active == 0


def test_tail_chunk_never_overruns_cache():
    """Code-review regression: a 276-token prompt with buckets (16, 256)
    and T=384 used to pick a 256 bucket at start=256 → the write window
    [256, 512) clamped and silently corrupted cache positions 128..275.
    The tail chunk must slide back instead."""
    model = _model(max_seq=512)
    rs = np.random.RandomState(7)
    prompt = list(rs.randint(0, 96, size=276))
    eng = DecodeEngine(model, max_slots=1, max_len=384, buckets=(16, 256))
    req = eng.submit(prompt, max_new_tokens=5)
    eng.run()
    assert req.tokens == _reference_tokens(model, prompt, 5)


def test_cache_never_exceeds_position_table():
    """Code-review regression: with max_seq_len not a 128-multiple, T
    rounded UP past the wpe table and jnp.take silently clamped late
    positions. T must cap at max_seq_len (einsum fallback)."""
    model = _model(max_seq=200)
    eng = DecodeEngine(model, max_slots=1)
    assert eng.T == 200
    rs = np.random.RandomState(8)
    prompt = list(rs.randint(0, 96, size=150))
    req = eng.submit(prompt, max_new_tokens=5)
    eng.run()
    assert req.tokens == _reference_tokens(model, prompt, 5)
    with pytest.raises(ValueError):
        eng.submit(prompt, max_new_tokens=51)  # 150 + 51 > 200


def test_kernel_disabled_under_mesh():
    """Code-review regression: the pallas decode branch must not engage
    when a multi-device mesh is active (no GSPMD partitioning rule for the
    custom call — it would all-gather the tp-sharded cache)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.models.gpt import _use_decode_kernel

    assert _use_decode_kernel(256)
    dist.init_mesh(dp=2, tp=4)
    try:
        assert not _use_decode_kernel(256)
    finally:
        mesh_lib.set_topology(None)
    assert not _use_decode_kernel(255)  # non-128-multiple cache


def test_submit_validtill_cache_bound():
    model = _model()
    eng = DecodeEngine(model, max_slots=1, max_len=128)
    with pytest.raises(ValueError):
        eng.submit(list(range(100)), max_new_tokens=100)  # 200 > 128


def test_roofline_model():
    c = gpt.GPTConfig(vocab_size=50304, max_seq_len=2048, d_model=2048,
                      n_layers=24, n_heads=16)
    one = decode_roofline_tokens_per_sec(c, 1, 1024, 819)
    eight = decode_roofline_tokens_per_sec(c, 8, 1024, 819)
    # weight reads amortize: 8-way batch is >4x the single-stream bound
    assert eight > 4 * one
    # and a longer context can only lower per-step throughput
    assert decode_roofline_tokens_per_sec(c, 8, 2048, 819) < eight


def test_int8_weight_engine_exact_on_grid_model():
    """weight_dtype='int8': snap a model's matmul weights to the int8
    grid first; the int8 engine must then emit EXACTLY the fp engine's
    greedy stream (the quantize/dequantize round-trip is lossless on
    grid weights, so any divergence is a wiring bug)."""
    import jax.numpy as jnp
    from paddle_tpu.quantization import quantize_tensor
    from paddle_tpu.models import gpt as gpt_lib

    cfg = gpt_lib.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                            n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt_lib.GPT(cfg, seed=0)
    for i in range(cfg.n_layers):
        blk = model.blocks[i]
        for name in ("wqkv", "wo", "wup", "wdown"):
            w = getattr(blk, name)
            object.__setattr__(blk, name,
                               quantize_tensor(w, axis=-1).dequantize())

    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (6, 14)]

    fp = DecodeEngine(model, max_slots=2, max_len=64)
    r_fp = [fp.submit(p, max_new_tokens=8) for p in prompts]
    fp.run()

    # model=None + share_weights_with composes with weight_dtype: the
    # int8 copy is quantized FROM the donor's stack without mutating it
    q8 = DecodeEngine(None, max_slots=2, max_len=64,
                      share_weights_with=fp, weight_dtype="int8")
    assert not hasattr(fp._stacked.wqkv, "dequantize")  # donor untouched
    assert hasattr(q8._stacked.wqkv, "dequantize")
    r_q8 = [q8.submit(p, max_new_tokens=8) for p in prompts]
    q8.run()

    for a, b in zip(r_fp, r_q8):
        assert a.tokens == b.tokens, (a.tokens, b.tokens)

    # donor still serves correctly after the int8 engine was built
    fp2 = DecodeEngine(model, max_slots=2, max_len=64)
    r_fp2 = [fp2.submit(p, max_new_tokens=8) for p in prompts]
    fp2.run()
    for a, b in zip(r_fp, r_fp2):
        assert a.tokens == b.tokens


def test_chunked_step_has_no_cache_sized_temps():
    """The no-rebuild property, asserted on XLA's own memory analysis:
    the chunked decode dispatch must not allocate cache-sized
    temporaries (the old scan-ys formulation double-buffered the whole
    KV cache every step; the row-write formulation's temps stay well
    under the cache size)."""
    import jax.numpy as jnp
    from paddle_tpu.models import gpt as gpt_lib

    cfg = gpt_lib.GPTConfig(vocab_size=128, max_seq_len=512, d_model=64,
                            n_layers=4, n_heads=4, dtype=jnp.float32)
    model = gpt_lib.GPT(cfg, seed=0)
    eng = DecodeEngine(model, max_slots=4, max_len=512, steps_per_call=8)
    lowered = eng._multi_fn.lower(
        eng._head, eng._stacked, eng.kc, eng.vc, eng.lengths, eng.last,
        eng.active, jnp.zeros((4,), jnp.int32),
        jnp.zeros((4,), jnp.int32), eng._rng, jnp.zeros((4,), bool))
    ma = lowered.compile().memory_analysis()
    cache = eng.kc.nbytes + eng.vc.nbytes
    assert ma.temp_size_in_bytes < 0.75 * cache, (
        ma.temp_size_in_bytes, cache)


# -- ISSUE 4: pipelined serving runtime --------------------------------------

def _streams(reqs):
    return [list(r.tokens) for r in reqs]


def _run_at_depth(model, depth, *, chunk=1, spec_k=0, stagger=True):
    """Serve a fixed staggered workload at a given in-flight depth and
    return the per-request token streams."""
    rs = np.random.RandomState(11)
    loop = [7, 21, 3]
    prompts = [list(rs.randint(0, 96, size=5)), loop * 8,
               list(rs.randint(0, 96, size=17)), loop * 4]
    n_new = [6, 9, 8, 5]
    eng = DecodeEngine(model, max_slots=2, max_len=160,
                       steps_per_call=chunk, speculative_k=spec_k,
                       inflight=depth)
    assert eng.depth == depth
    reqs = [eng.submit(prompts[0], n_new[0]),
            eng.submit(prompts[1], n_new[1])]
    if stagger:
        eng.step()
    reqs += [eng.submit(prompts[2], n_new[2]),
             eng.submit(prompts[3], n_new[3])]
    eng.run()
    assert all(r.done and not r.failed for r in reqs)
    return _streams(reqs)


@pytest.mark.parametrize("chunk,spec_k", [(1, 0), (4, 0), (2, 3)],
                         ids=["plain", "chunked", "speculative"])
def test_pipelined_depths_bit_identical(chunk, spec_k):
    """The acceptance invariant: depth>=2 (lag-one and deeper) produces
    BYTE-identical token streams to the synchronous depth=1 engine on
    every decode path, including staggered admissions."""
    model = _model()
    base = _run_at_depth(model, 1, chunk=chunk, spec_k=spec_k)
    for depth in (2, 3):
        got = _run_at_depth(model, depth, chunk=chunk, spec_k=spec_k)
        assert got == base, f"depth {depth} diverged from depth 1"


def test_pipeline_defaults_and_env(monkeypatch):
    model = _model()
    assert DecodeEngine(model, max_slots=1, max_len=64).depth == 2
    monkeypatch.setenv("PT_SERVE_INFLIGHT", "5")
    assert DecodeEngine(model, max_slots=1, max_len=64).depth == 5
    assert DecodeEngine(model, max_slots=1, max_len=64,
                        inflight=1).depth == 1
    with pytest.raises(ValueError):
        DecodeEngine(model, max_slots=1, max_len=64, inflight=0)


def test_pipeline_holds_multiple_dispatches_in_flight():
    """At depth 3 the engine must actually keep >1 dispatch enqueued
    (the serve/inflight gauge sees >= 2) and drain() must leave zero."""
    from paddle_tpu import stats

    model = _model()
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=2, max_len=128, inflight=3)
    reqs = [eng.submit([1, 2, 3], max_new_tokens=12),
            eng.submit([4, 5], max_new_tokens=12)]
    peak = 0
    while any(not r.done for r in reqs):
        eng.step()
        peak = max(peak, len(eng._pending))
    assert peak >= 2
    eng.drain()
    assert len(eng._pending) == 0
    assert stats.get("serve/inflight") == 0
    snap = stats.snapshot("serve/")
    assert snap.get("serve/host_gap_s.count", 0) >= 1


def test_pipelined_warmup_pretraces_every_path():
    """warmup=True compiles one prefill per bucket plus the decode
    dispatch at construction; serving afterwards adds NO signatures."""
    model = _model()
    eng = DecodeEngine(model, max_slots=2, max_len=128,
                       buckets=(16, 32), warmup=True)
    assert eng._prefill_fn._cache_size() == 2
    assert eng._multi_fn._cache_size() == 1
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (4, 20)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run()
    assert eng._prefill_fn._cache_size() == 2, "serving recompiled"
    assert eng._multi_fn._cache_size() == 1, "serving recompiled"
    for req, p in zip(reqs, prompts):
        assert req.tokens == _reference_tokens(model, p, 5)

    spec = DecodeEngine(model, max_slots=2, max_len=128, buckets=(16,),
                        speculative_k=3, warmup=True)
    assert spec._verify_fn._cache_size() == 1


def test_prefill_interleaves_with_decode():
    """A long prompt's chunked prefill must not stall live slots: with
    a small per-step prefill budget the live request keeps emitting
    between prefill chunks, and both streams match the reference."""
    from paddle_tpu.observability import trace

    model = _model()
    rs = np.random.RandomState(12)
    long_prompt = list(rs.randint(0, 96, size=100))  # 7 chunks of 16
    short = list(rs.randint(0, 96, size=4))
    eng = DecodeEngine(model, max_slots=2, max_len=128, buckets=(16,),
                       prefill_tokens=16, inflight=2)
    trace.clear(capacity=8192)
    trace.enable()
    try:
        r0 = eng.submit(short, max_new_tokens=12)
        eng.step()
        r1 = eng.submit(long_prompt, max_new_tokens=4)
        eng.run()
        evs, _ = trace.events()
    finally:
        trace.disable()
        trace.clear()
    assert r0.tokens == _reference_tokens(model, short, 12)
    assert r1.tokens == _reference_tokens(model, long_prompt, 4)
    # KV integrity (code-review regression): decode dispatches enqueued
    # between prefill chunks used to write garbage rows into the
    # mid-admission slot at its stale device position; the admitted
    # slot's prompt KV must be bit-identical to a clean solo admission
    solo = DecodeEngine(model, max_slots=1, max_len=128, buckets=(16,))
    solo.submit(long_prompt, max_new_tokens=4)
    solo.run()
    n = len(long_prompt)
    np.testing.assert_array_equal(
        np.asarray(eng.kc[:, 1, :, :n]), np.asarray(solo.kc[:, 0, :, :n]))
    np.testing.assert_array_equal(
        np.asarray(eng.vc[:, 1, :, :n]), np.asarray(solo.vc[:, 0, :, :n]))
    # the trace must show decode dispatches BETWEEN prefill chunks of
    # the long prompt (interleave, not stall)
    names = [e[0] for e in sorted(
        (e for e in evs if e and e[0] in ("serve/prefill",
                                          "serve/dispatch")),
        key=lambda e: e[1])]
    pf_idx = [i for i, n in enumerate(names) if n == "serve/prefill"]
    assert len(pf_idx) == 8  # 1 short chunk + 7 long chunks
    between = names[pf_idx[1]:pf_idx[-1]]
    assert "serve/dispatch" in between, \
        "no decode dispatch interleaved with the long prefill"


def test_serving_metrics_and_request_spans(tmp_path):
    """ISSUE 3: the engine emits the serving observability surface —
    serve/ttft_s histogram (one sample per request), queue-depth and
    batch-occupancy histograms, per-token latency, and (with tracing
    on) nested serve/step → serve/dispatch spans plus one
    serve/request lifetime span per request."""
    import json
    from paddle_tpu import stats
    from paddle_tpu.observability import trace

    stats.reset("serve/")
    trace.clear(capacity=4096)
    trace.enable(str(tmp_path))
    try:
        model = _model()
        eng = DecodeEngine(model, max_slots=2, max_len=128)
        reqs = [eng.submit([1, 2, 3], max_new_tokens=4),
                eng.submit([4, 5], max_new_tokens=3),
                eng.submit([6, 7, 8, 9], max_new_tokens=2)]  # queues
        eng.run()
        assert all(r.done and not r.failed for r in reqs)
        assert all(r.ttft_s is not None and r.ttft_s > 0 for r in reqs)

        snap = stats.snapshot("serve/")
        assert snap["serve/ttft_s.count"] == 3
        assert 0 < snap["serve/ttft_s.p50"] <= snap["serve/ttft_s.p99"]
        assert snap["serve/queue_depth.count"] >= 1
        assert snap["serve/batch_occupancy.count"] >= 1
        assert snap["serve/token_s.count"] >= 1
        assert snap["serve/token_s.p50"] > 0
        # the queue was over capacity at some point: max depth >= 1
        assert snap["serve/queue_depth.max"] >= 1
        assert "serve/ttft_s.p99" in stats.table("serve/")

        path = trace.export(str(tmp_path / "eng.json"))
        with open(path) as f:
            evs = [e for e in json.load(f)["traceEvents"]
                   if e.get("ph") == "X"]
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        assert len(by_name["serve/request"]) == 3
        assert len(by_name["serve/step"]) >= 1
        assert len(by_name["serve/dispatch"]) >= 1
        assert len(by_name["serve/admit"]) == 3
        # dispatch nests under a step span
        step_ids = {e["args"]["span_id"] for e in by_name["serve/step"]}
        assert all(e["args"]["parent_id"] in step_ids
                   for e in by_name["serve/dispatch"])
        # request spans carry token counts and no error
        for e in by_name["serve/request"]:
            assert e["args"]["tokens"] >= 2
            assert e["args"]["error"] is None
    finally:
        trace.disable()
        trace.clear()
        stats.reset("serve/")
