"""ptlint (paddle_tpu.analysis) — per-rule fixtures (true positive,
true negative, suppression, baseline round-trip) and the repo self-lint
gate: the shipped tree must carry ZERO non-baselined findings.

Everything here is pure-AST (no tracing, no device), so the whole file
stays tier-1 fast.
"""

import os
import subprocess
import sys

import pytest

from paddle_tpu.analysis import baseline, default_rules, load_project, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a minimal flags.py so PT005 has a contract registry in fixture trees
FLAGS_SRC = """
def declare_env(name, help="", default=None, owner=""):
    pass

def declare_env_prefix(prefix, help="", owner=""):
    pass

declare_env("PT_DECLARED_KNOB", "a declared knob")
declare_env_prefix("PT_FLAGS_", "flag overrides")
"""


def _lint(tmp_path, sources, rules=None):
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    for name, src in sources.items():
        p = d / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    project = load_project([str(d)], root=str(tmp_path))
    return run(project, rules)


def _rules_hit(findings):
    return {f.rule for f in findings}


# -- PT001: host syncs -------------------------------------------------------

def test_pt001_item_in_jit_positive(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax
import jax.numpy as jnp

def _step(x):
    y = jnp.sum(x)
    return y.item()

step = jax.jit(_step)
"""})
    assert any(f.rule == "PT001" and ".item()" in f.message
               for f in findings)


def test_pt001_scope_negative(tmp_path):
    """The same .item() OUTSIDE any traced/dispatch scope is fine."""
    findings = _lint(tmp_path, {"mod.py": """
import jax.numpy as jnp

def host_summary(x):
    return jnp.sum(x).item()
"""})
    # host_summary is never jitted nor reachable from a dispatch root:
    # .item() there is ordinary host code
    assert "PT001" not in _rules_hit(findings)


def test_pt001_reaches_through_calls(tmp_path):
    """Scope is transitive: a helper CALLED from a jitted function is
    traced code too."""
    findings = _lint(tmp_path, {"mod.py": """
import jax
import numpy as np
import jax.numpy as jnp

def helper(x):
    return float(jnp.max(x))

def _step(x):
    return helper(x)

step = jax.jit(_step)
"""})
    hits = [f for f in findings if f.rule == "PT001"]
    assert hits and "helper" in hits[0].symbol


def test_pt001_metadata_copy_anywhere(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import numpy as np

def plan(x):
    return np.asarray(x).shape[:2]
"""})
    hits = [f for f in findings if f.rule == "PT001"]
    assert hits and "metadata" in hits[0].message


def test_pt001_suppression(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax
import jax.numpy as jnp

def _step(x):
    y = jnp.sum(x)
    # ptlint: disable=PT001 -- deliberate, documented
    return y.item()

step = jax.jit(_step)
"""})
    assert "PT001" not in _rules_hit(findings)


# -- PT002: retrace hazards --------------------------------------------------

def test_pt002_jit_in_loop_positive(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax

def train(fns, xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v + 1)(x))
    return out
"""})
    assert any(f.rule == "PT002" and "loop" in f.message
               for f in findings)


def test_pt002_builder_negative(tmp_path):
    """jit in a build-once function (no loop) is the idiom, not a
    hazard."""
    findings = _lint(tmp_path, {"mod.py": """
import jax

def build_step(fn):
    def step(params, batch):
        return fn(params, batch)
    return jax.jit(step)
"""})
    assert "PT002" not in _rules_hit(findings)


def test_pt002_mutated_global_closure(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax

SCALE = 1.0
BIAS = 0.0

def set_scale(v):
    global SCALE, BIAS
    SCALE = v
    BIAS = v

def _step(x):
    return x * SCALE + BIAS

step = jax.jit(_step)
"""})
    # BOTH hazards in the same jitted fn are reported, not just the first
    assert any(f.rule == "PT002" and "SCALE" in f.message
               for f in findings)
    assert any(f.rule == "PT002" and "BIAS" in f.message
               for f in findings)


def test_pt002_unhashable_static_arg(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax

def f(x, cfg):
    return x

g = jax.jit(f, static_argnums=(1,))

def call(x):
    return g(x, [1, 2, 3])
"""})
    assert any(f.rule == "PT002" and "unhashable" in f.message
               for f in findings)


def test_pt002_shape_key_warning(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
_CACHE = {}

def lookup(x):
    return _CACHE[f"k{x.shape}"]
"""})
    assert any(f.rule == "PT002" and "shape" in f.message
               for f in findings)


# -- PT003: traced side effects ----------------------------------------------

def test_pt003_stats_in_jit_positive(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax
from paddle_tpu import stats

def _step(x):
    stats.add("train/steps")
    return x + 1

step = jax.jit(_step)
"""})
    assert any(f.rule == "PT003" and "stats.add" in f.message
               for f in findings)


def test_pt003_host_side_stats_negative(tmp_path):
    """stats on the host side of the dispatch is the entire point of
    the stats module — never flagged."""
    findings = _lint(tmp_path, {"mod.py": """
import jax
from paddle_tpu import stats

def _step(x):
    return x + 1

step = jax.jit(_step)

def serve_loop(x):
    y = step(x)
    stats.add("serve/steps")
    return y
"""})
    assert "PT003" not in _rules_hit(findings)


def test_pt003_local_append_negative_closure_positive(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax

LEAK = []

def _step(x):
    rows = []
    rows.append(x)      # local: idiomatic trace-time build — fine
    LEAK.append(x)      # closure/global: leaks tracers
    return rows[0]

step = jax.jit(_step)
"""})
    hits = [f for f in findings if f.rule == "PT003"]
    assert len(hits) == 1 and "LEAK" in hits[0].message


def test_pt003_suppression(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax
from paddle_tpu import stats

def _step(x):
    # ptlint: disable=PT003 -- issue-time counter, documented
    stats.add("collective/calls")
    return x

step = jax.jit(_step)
"""})
    assert "PT003" not in _rules_hit(findings)


# -- PT004: collective-order divergence --------------------------------------

def test_pt004_rank_conditional_collective_positive(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
import jax
from jax import lax

def sync(x, rank):
    if rank == 0:
        x = lax.psum(x, "dp")
    return x
"""})
    hits = [f for f in findings if f.rule == "PT004"]
    assert hits and "psum" in hits[0].message


def test_pt004_balanced_arms_negative(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
from jax import lax

def sync(x, rank):
    if rank == 0:
        x = lax.psum(x * 2, "dp")
    else:
        x = lax.psum(x, "dp")
    return x

def rank0_local_work(meta, rank):
    if rank == 0:
        meta = dict(meta)       # local-only work is fine
    return lax.psum(meta["x"], "dp")
"""})
    assert "PT004" not in _rules_hit(findings)


def test_pt004_suppression(tmp_path):
    findings = _lint(tmp_path, {"mod.py": """
from jax import lax

def sync(x, rank):
    if rank == 0:
        # ptlint: disable=PT004 -- single-rank program by construction
        x = lax.psum(x, "dp")
    return x
"""})
    assert "PT004" not in _rules_hit(findings)


# -- PT005: env contract -----------------------------------------------------

def test_pt005_undeclared_positive(tmp_path):
    findings = _lint(tmp_path, {
        "flags.py": FLAGS_SRC,
        "mod.py": """
import os

def knob():
    return os.environ.get("PT_SECRET_KNOB", "0")
"""})
    hits = [f for f in findings if f.rule == "PT005"]
    assert hits and "PT_SECRET_KNOB" in hits[0].message


def test_pt005_declared_and_prefix_negative(tmp_path):
    findings = _lint(tmp_path, {
        "flags.py": FLAGS_SRC,
        "mod.py": """
import os

def knobs():
    a = os.environ.get("PT_DECLARED_KNOB")
    b = os.environ["PT_FLAGS_SCAN_LAYERS"]
    c = os.getenv("HOME")          # non-PT_ names are out of contract
    return a, b, c
"""})
    assert "PT005" not in _rules_hit(findings)


@pytest.fixture(scope="module")
def repo_findings():
    """One full-package lint shared by the self-lint assertions."""
    project = load_project([os.path.join(REPO, "paddle_tpu")], root=REPO)
    return project, run(project)


def test_pt005_package_registry_is_complete(repo_findings):
    """Every PT_* read in the real package is declared in flags.py —
    the knob/doc contract cannot silently fork."""
    _, findings = repo_findings
    assert [f for f in findings if f.rule == "PT005"] == []


def test_env_declared_agrees_with_linter(repo_findings):
    """The runtime helper flags.env_declared() and PT005's AST-parsed
    declared set are two views of one registry — they must agree, or
    runtime checks and the lint gate drift apart."""
    import paddle_tpu.flags as flags
    project, _ = repo_findings
    names, prefixes = project._pt005_declared
    for n in names:
        assert flags.env_declared(n), n
    for p in prefixes:
        assert flags.env_declared(p + "ANYTHING"), p
    assert not flags.env_declared("PT_NOT_IN_THE_CONTRACT")


def test_pt005_tool_prefix_namespace(tmp_path):
    """declare_tool_prefix brings a tool namespace under contract: an
    undeclared PD_* read is flagged, a declared one passes, and names
    under UNregistered prefixes stay out of contract."""
    findings = _lint(tmp_path, {
        "flags.py": FLAGS_SRC + """
def declare_tool_prefix(prefix, help="", owner=""):
    pass

declare_tool_prefix("PD_", "profile_decode knobs")
declare_env("PD_SIZE", "model size")
""",
        "tool.py": """
import os

def knobs():
    a = os.environ.get("PD_SIZE", "tiny")    # declared: clean
    b = os.environ.get("PD_SECRET_KNOB")     # in-namespace, undeclared
    c = os.getenv("FLEETOBS_ANY")            # namespace not registered
    d = os.environ.get("HOME")               # out of contract
    return a, b, c, d
"""})
    hits = [f for f in findings if f.rule == "PT005"]
    assert len(hits) == 1 and "PD_SECRET_KNOB" in hits[0].message


def test_pt005_tools_tree_registry_complete():
    """tools/ is linted under the same contract (ci.sh lints
    paddle_tpu AND tools): every PD_*/FLEETOBS_*/PT_* read there must
    be declared — exercises the subtree fallback that pulls the
    registry off paddle_tpu/flags.py."""
    rules = [r for r in default_rules() if r.id == "PT005"]
    project = load_project([os.path.join(REPO, "tools")], root=REPO)
    findings = run(project, rules)
    assert [f for f in findings if f.rule == "PT005"] == []


# -- baseline round-trip -----------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    src = {"mod.py": """
import jax
import jax.numpy as jnp

def _step(x):
    return jnp.sum(x).item()

step = jax.jit(_step)
"""}
    findings = _lint(tmp_path, src)
    assert findings
    bl_path = str(tmp_path / "baseline.json")
    baseline.write(bl_path, findings)
    again = _lint(tmp_path, src)
    new, known = baseline.partition(again, baseline.load(bl_path))
    assert new == [] and len(known) == len(findings)
    # a NEW finding is not masked by the old baseline
    src["mod.py"] += """
def _other(x):
    return float(jnp.max(x))

other = jax.jit(_other)
"""
    third = _lint(tmp_path, src)
    new, known = baseline.partition(third, baseline.load(bl_path))
    assert len(known) == len(findings) and len(new) >= 1


def test_fingerprints_stable_across_line_shifts(tmp_path):
    src = """
import jax
import jax.numpy as jnp

def _step(x):
    return jnp.sum(x).item()

step = jax.jit(_step)
"""
    f1 = _lint(tmp_path, {"mod.py": src})
    f2 = _lint(tmp_path, {"mod.py": "\n# a comment\n\n" + src})
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert f1[0].line != f2[0].line


# -- repo self-lint gate -----------------------------------------------------

def test_repo_self_lint_zero_new_findings(repo_findings):
    project, findings = repo_findings
    assert project.parse_errors == []
    bl = baseline.load(os.path.join(REPO, "tools",
                                    "ptlint_baseline.json"))
    new, _ = baseline.partition(findings, bl)
    assert new == [], "new ptlint findings:\n" + "\n".join(
        f.format() for f in new)


def test_cli_exit_codes_and_stats(tmp_path):
    """CLI contract: 0 on the shipped tree (with --stats reporting every
    rule family), 1 once a host-sync fixture is planted."""
    cli = os.path.join(REPO, "tools", "ptlint.py")
    r = subprocess.run([sys.executable, cli, "paddle_tpu",
                        "--error-on-new", "--stats"],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    for rule in ("PT001", "PT002", "PT003", "PT004", "PT005"):
        assert rule in r.stdout
    bad = tmp_path / "planted.py"
    bad.write_text("import jax\nimport jax.numpy as jnp\n\n"
                   "def _f(x):\n    return jnp.sum(x).item()\n\n"
                   "g = jax.jit(_f)\n")
    r = subprocess.run([sys.executable, cli, str(bad)],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "PT001" in r.stdout


def test_cli_parse_error_exits_2(tmp_path):
    """An unparseable file means the tree was NOT checked — the lint
    gate must fail loudly (2), not pass green."""
    cli = os.path.join(REPO, "tools", "ptlint.py")
    broken = tmp_path / "broken.py"
    broken.write_text("def oops(:\n")
    r = subprocess.run([sys.executable, cli, str(broken)],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "could not be parsed" in r.stderr
    # --no-error keeps report-only mode green
    r = subprocess.run([sys.executable, cli, str(broken), "--no-error"],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


# -- callgraph alias resolution (ISSUE 20 satellite) -------------------------

def test_alias_does_not_smear_jit_root(tmp_path):
    """``step = self._traced; jax.jit(step)`` must root _traced — NOT
    an unrelated host-side method that happens to be named ``step``
    (the PR 19 false positive)."""
    findings = _lint(tmp_path, {"mod.py": """
import jax
import jax.numpy as jnp


class Engine:
    def _traced(self, x):
        return jnp.sum(x)

    def build(self):
        step = self._traced
        return jax.jit(step)


class Host:
    def step(self, x):
        return jnp.sum(x).item()
"""})
    assert "PT001" not in _rules_hit(findings)


def test_alias_target_still_enters_jit_scope(tmp_path):
    """Positive control: the alias TARGET is the jit root, so a host
    sync inside it is still flagged."""
    findings = _lint(tmp_path, {"mod.py": """
import jax
import jax.numpy as jnp


class Engine:
    def _traced(self, x):
        return jnp.sum(x).item()

    def build(self):
        step = self._traced
        return jax.jit(step)
"""})
    assert any(f.rule == "PT001" and f.symbol.endswith("_traced")
               for f in findings)


def test_module_level_alias_resolves(tmp_path):
    """``run = _impl`` at module level: jitting the alias roots _impl,
    and a same-named function elsewhere in the file stays host code."""
    findings = _lint(tmp_path, {"mod.py": """
import jax
import jax.numpy as jnp


def _impl(x):
    return jnp.sum(x).item()


run = _impl
traced = jax.jit(run)


def run_report(x):
    pass
"""})
    assert any(f.rule == "PT001" and f.symbol.endswith("_impl")
               for f in findings)
