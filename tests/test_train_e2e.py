"""Minimum end-to-end slice (SURVEY §7.2 Phase 3): LeNet on synthetic MNIST —
train loop, eval, checkpoint save/resume; hapi Model.fit path too."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.vision.datasets import SyntheticImages
from paddle_tpu.vision.models import LeNet


def _make_step(model, opt):
    params, _ = model.split_params()
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            out = model.merge_params(p)(x)
            return nn.functional.cross_entropy(out, y)
        loss, g = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(g, opt_state, params)
        return params, opt_state, loss

    return params, opt_state, step


def test_lenet_learns():
    pt.seed(0)
    model = LeNet()
    opt = pt.optimizer.Adam(learning_rate=1e-3)
    ds = SyntheticImages(256, (1, 28, 28), 10, seed=0)
    loader = pt.io.DataLoader(ds, batch_size=64, shuffle=True)
    params, opt_state, step = _make_step(model, opt)
    losses = []
    for epoch in range(8):
        for x, y in loader:
            params, opt_state, loss = step(params, opt_state,
                                           jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_checkpoint_resume(tmp_path):
    pt.seed(0)
    model = LeNet()
    opt = pt.optimizer.Adam(learning_rate=1e-3)
    params, opt_state, step = _make_step(model, opt)
    x = jnp.asarray(np.random.randn(16, 1, 28, 28).astype(np.float32))
    y = jnp.asarray(np.random.randint(0, 10, 16))
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, x, y)
    ck = str(tmp_path / "ck")
    pt.save({"params": params, "opt": opt_state}, ck)
    restored = pt.load(ck)
    # continue training from restored state: must be bitwise identical path
    p1, s1, l1 = step(params, opt_state, x, y)
    p2, s2, l2 = step(restored["params"], restored["opt"], x, y)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-6)


def test_hapi_model_fit():
    pt.seed(0)
    model = pt.Model(LeNet())
    model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
                  loss=nn.functional.cross_entropy,
                  metrics=pt.metric.Accuracy())
    train = SyntheticImages(128, (1, 28, 28), 10, seed=0)
    val = SyntheticImages(64, (1, 28, 28), 10, seed=1)
    hist = model.fit(train, val, batch_size=32, epochs=2, verbose=0)
    assert len(hist) == 2
    res = model.evaluate(val, batch_size=32)
    assert "loss" in res and np.isfinite(res["loss"])


def test_hapi_save_load(tmp_path):
    model = pt.Model(LeNet())
    model.prepare(optimizer=pt.optimizer.SGD(0.1),
                  loss=nn.functional.cross_entropy)
    path = str(tmp_path / "lenet")
    model.save(path)
    model2 = pt.Model(LeNet())
    model2.prepare(optimizer=pt.optimizer.SGD(0.1),
                   loss=nn.functional.cross_entropy)
    model2.load(path)
    w1 = model.network.state_dict()["features.layer_0.weight"]
    w2 = model2.network.state_dict()["features.layer_0.weight"]
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2))
