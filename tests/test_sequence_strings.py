"""Sequence ops (dense+lengths LoD replacement), strings, and the
FasterTokenizer analog (ref: sequence_ops/, phi/kernels/strings/,
operators/string/faster_tokenizer_op.cc).  Value oracles for each sequence
op live in the op suite; here: lengths outputs, chaining, jit, and
behavioural parity (tokenizer vs the HuggingFace BertTokenizer oracle)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.tensor as T
from paddle_tpu import strings
from paddle_tpu.text import BertTokenizer


def _batch():
    rs = np.random.RandomState(7)
    x = rs.randn(3, 5, 2).astype(np.float32)
    lens = np.array([5, 2, 0], np.int32)
    return x, lens


def test_concat_lengths_and_values():
    x, lens = _batch()
    y = np.ones((3, 4, 2), np.float32)
    ylens = np.array([1, 4, 2], np.int32)
    out, olens = T.sequence_concat([x, y], [lens, ylens])
    assert out.shape == (3, 9, 2)
    np.testing.assert_array_equal(np.asarray(olens), [6, 6, 2])
    np.testing.assert_allclose(np.asarray(out)[0, :5], x[0, :5])
    np.testing.assert_allclose(np.asarray(out)[0, 5:6], y[0, :1])
    np.testing.assert_allclose(np.asarray(out)[2, :2], y[2, :2])
    # padding stays zero
    assert float(jnp.abs(out[2, 2:]).sum()) == 0.0


def test_erase_lengths():
    x = np.array([[4, 2, 7, 2, 9], [2, 2, 2, 1, 1]], np.int32)
    lens = np.array([5, 3], np.int32)
    out, olens = T.sequence_erase(x, lens, (2,))
    np.testing.assert_array_equal(np.asarray(olens), [3, 0])
    np.testing.assert_array_equal(np.asarray(out)[0, :3], [4, 7, 9])
    np.testing.assert_array_equal(np.asarray(out)[1], [0, 0, 0, 0, 0])


def test_reshape_lengths_scale():
    x, lens = _batch()
    out, olens = T.sequence_reshape(x, lens, 1)
    assert out.shape == (3, 10, 1)
    np.testing.assert_array_equal(np.asarray(olens), [10, 4, 0])


def test_pad_unpad_roundtrip():
    rows = [np.arange(4, dtype=np.float32).reshape(4, 1),
            np.arange(2, dtype=np.float32).reshape(2, 1)]
    padded, lens = T.sequence_pad(rows, pad_value=-1.0)
    assert padded.shape == (2, 4, 1)
    assert float(padded[1, 3, 0]) == -1.0
    back = T.sequence_unpad(padded, lens)
    for a, b in zip(rows, back):
        np.testing.assert_allclose(a, np.asarray(b))


def test_expand_ragged_batch():
    x, lens = _batch()
    out, olens = T.sequence_expand(x, lens, np.array([2, 0, 1], np.int32))
    assert out.shape == (3, 5, 2)
    np.testing.assert_array_equal(np.asarray(olens), [5, 5, 0])
    np.testing.assert_allclose(np.asarray(out)[1], x[0])


def test_pad_truncation_clamps_lengths():
    padded, lens = T.sequence_pad([np.ones((5, 1), np.float32)], maxlen=3)
    np.testing.assert_array_equal(np.asarray(lens), [3])
    out = T.sequence_pool(padded, lens, "average")
    np.testing.assert_allclose(np.asarray(out), [[1.0]])


def test_expand_as_clamps_lengths_to_maxlen():
    x = np.ones((1, 2), np.float32)
    out, lens = T.sequence_expand_as(x, np.array([5], np.int32), maxlen=3)
    assert out.shape == (1, 3, 2)
    np.testing.assert_array_equal(np.asarray(lens), [3])


def test_reshape_rejects_indivisible_row_lengths():
    x = np.ones((1, 4, 3), np.float32)
    with pytest.raises(ValueError, match="not\\s+divisible"):
        T.sequence_reshape(x, np.array([3], np.int32), 2)


def test_slice_rejects_out_of_range_window():
    x = np.ones((1, 5, 1), np.float32)
    with pytest.raises(ValueError, match="exceeds"):
        T.sequence_slice(x, np.array([5]), np.array([3]), np.array([4]))


def test_sequence_chain_under_jit():
    """reverse→softmax→pool chains as ONE traced program (the point of the
    dense representation: no host offsets between ops)."""
    x, lens = _batch()

    @jax.jit
    def f(x, lens):
        r = T.sequence_reverse(x, lens)
        s = T.sequence_softmax(r, lens)
        return T.sequence_pool(s, lens, "sum")

    out = np.asarray(f(x, lens))
    # softmax sums to 1 over valid steps → pooled sum = 1 per feature
    np.testing.assert_allclose(out[0], np.ones(2), rtol=1e-5)
    np.testing.assert_allclose(out[2], np.zeros(2), atol=1e-7)  # empty row


def test_strings_case_roundtrip():
    texts = ["Hello, World!", "ΣΊΣΥΦΟΣ", "Привет Мир", "mixed ÄöÜ ß"]
    st = strings.to_string_tensor(texts)
    low = strings.lower(st).to_strings()
    upp = strings.upper(st).to_strings()
    for t, l, u in zip(texts, low, upp):
        want_l = "".join(c.lower() if len(c.lower()) == 1 else c
                         for c in t)
        want_u = "".join(c.upper() if len(c.upper()) == 1 else c
                         for c in t)
        assert l == want_l
        assert u == want_u


def test_strings_full_bmp_case_table():
    st = strings.to_string_tensor(["ＡＢＣ", "ꙀꙂ"])  # fullwidth, Cyr Ext-B
    assert strings.lower(st).to_strings() == ["ａｂｃ", "ꙁꙃ"]


def test_strings_equal_and_length():
    a = strings.to_string_tensor(["abc", "defg", ""])
    b = strings.to_string_tensor(["abc", "defx", ""])
    np.testing.assert_array_equal(np.asarray(strings.equal(a, b)),
                                  [True, False, True])
    np.testing.assert_array_equal(np.asarray(strings.length(a)), [3, 4, 0])


def test_strings_lower_is_jit_safe():
    st = strings.to_string_tensor(["ABC", "ÄÖÜ"])
    out = jax.jit(lambda cp, ln: strings.lower(
        strings.StringTensor(cp, ln)).codepoints)(st.codepoints, st.lengths)
    assert strings.StringTensor(out, st.lengths).to_strings() == \
        ["abc", "äöü"]


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "quick",
         "brown", "fox", "jump", "##s", "##ed", "over", "lazy", "dog",
         "un", "##believ", "##able", ",", ".", "!", "ca", "##n't", "'",
         "t", "n", "##ca"]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n")
    return str(p)


@pytest.fixture(scope="module")
def hf(vocab_file):
    try:
        from transformers import BertTokenizer as HFBert
    except Exception:
        pytest.skip("transformers unavailable")
    return HFBert(vocab_file, do_lower_case=True)


def test_tokenizer_matches_huggingface(vocab_file, hf):
    """The wordpiece algorithm (faster_tokenizer_op.h) against the
    canonical implementation, token-for-token."""
    tok = BertTokenizer(vocab_file)
    cases = [
        "The quick brown fox jumps over the lazy dog.",
        "unbelievable!",
        "The UNKNOWNWORD jumped, unbelievably.",
        "the  quick\tbrown\nfox",
        "ÜBER the fox",   # accent strip + lower
    ]
    for text in cases:
        assert tok.tokenize(text) == hf.tokenize(text), text


def test_tokenizer_batch_encoding_matches_huggingface(vocab_file, hf):
    tok = BertTokenizer(vocab_file)
    texts = ["the quick brown fox", "unbelievable!"]
    pairs = ["the lazy dog.", "the fox jumps"]
    enc = tok(texts, pairs, max_seq_len=16)
    for b in range(2):
        want = hf.encode(texts[b], pairs[b])
        n = int(enc["seq_len"][b])
        assert list(enc["input_ids"][b, :n]) == want
        sep1 = want.index(tok.sep_id)
        assert list(enc["token_type_ids"][b, :n]) == \
            [0] * (sep1 + 1) + [1] * (n - sep1 - 1)
    # padding beyond seq_len is pad_id
    assert (enc["input_ids"][0, int(enc["seq_len"][0]):] == 0).all()


def test_tokenizer_empty_pair_matches_huggingface(vocab_file, hf):
    tok = BertTokenizer(vocab_file)
    enc = tok(["the fox"], [""], max_seq_len=8)
    n = int(enc["seq_len"][0])
    assert list(enc["input_ids"][0, :n]) == hf.encode("the fox", "")


def test_tokenizer_truncation_matches_huggingface(vocab_file, hf):
    tok = BertTokenizer(vocab_file)
    text = "the quick brown fox jumps over the lazy dog"
    pair = "unbelievable unbelievable unbelievable"
    enc = tok([text], [pair], max_seq_len=12)
    want = hf.encode(text, pair, truncation="longest_first", max_length=12)
    n = int(enc["seq_len"][0])
    assert n == 12
    assert list(enc["input_ids"][0, :n]) == want


def test_tokenizer_feeds_model_directly(vocab_file):
    """Tokenizer output is the jitted model's feed — the end-to-end
    serving property the reference's in-graph tokenizer op exists for."""
    tok = BertTokenizer(vocab_file)
    enc = tok(["the quick fox", "dog"], max_seq_len=8)

    @jax.jit
    def embed_sum(ids, lens):
        emb = jnp.take(jnp.ones((len(VOCAB), 4)) *
                       jnp.arange(len(VOCAB))[:, None], ids, axis=0)
        m = (jnp.arange(ids.shape[1])[None, :] < lens[:, None])
        return (emb * m[..., None]).sum((1, 2))

    out = embed_sum(enc["input_ids"], enc["seq_len"])
    assert out.shape == (2,) and float(out[0]) > 0
