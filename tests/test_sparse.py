"""Sparse tensor surface vs dense oracles (ref test pattern:
test_sparse_conv_op.py, test_sparse_norm_op.py — dense-conv oracle checked
against the sparse kernel at active sites)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import sparse as S


def _rand_coo(shape, nnz, seed=0, channels=None):
    rs = np.random.RandomState(seed)
    flat = rs.choice(int(np.prod(shape)), size=nnz, replace=False)
    idx = np.stack(np.unravel_index(flat, shape))
    vshape = (nnz,) if channels is None else (nnz, channels)
    vals = rs.normal(size=vshape).astype(np.float32)
    return S.sparse_coo_tensor(idx, vals, shape)


def test_unary_ops_match_dense():
    x = _rand_coo((4, 6), 8, seed=1)
    d = np.asarray(x.to_dense())
    for name in ["sin", "tanh", "square", "expm1", "neg", "abs"]:
        out = getattr(S, name)(x)
        ref = getattr(np, name if name != "neg" else "negative")(d)
        # sparsity-preserving: f(0)=0, so dense application matches
        np.testing.assert_allclose(out.to_dense(), ref, atol=1e-6)


def test_coalesce_sums_duplicates():
    idx = np.array([[0, 0, 1], [2, 2, 3]])
    x = S.sparse_coo_tensor(idx, np.array([1.0, 2.0, 5.0], np.float32),
                            (2, 4))
    c = S.coalesce(x)
    assert c.nnz() == 2
    d = np.asarray(c.to_dense())
    assert d[0, 2] == 3.0 and d[1, 3] == 5.0


def test_transpose_reshape_cast():
    x = _rand_coo((3, 5), 6, seed=2)
    d = np.asarray(x.to_dense())
    np.testing.assert_allclose(S.transpose(x, (1, 0)).to_dense(), d.T)
    np.testing.assert_allclose(S.reshape(x, (5, 3)).to_dense(),
                               d.reshape(5, 3))
    assert S.cast(x, value_dtype=jnp.bfloat16).dtype == jnp.bfloat16


def test_binary_ops_match_dense():
    a = _rand_coo((4, 4), 5, seed=3)
    b = _rand_coo((4, 4), 5, seed=4)
    da, db = np.asarray(a.to_dense()), np.asarray(b.to_dense())
    np.testing.assert_allclose(S.add(a, b).to_dense(), da + db, atol=1e-6)
    np.testing.assert_allclose(S.subtract(a, b).to_dense(), da - db,
                               atol=1e-6)
    np.testing.assert_allclose(S.multiply(a, b).to_dense(), da * db,
                               atol=1e-6)
    assert S.is_same_shape(a, b)


def test_matmul_mv_addmm():
    a = _rand_coo((4, 6), 7, seed=5)
    da = np.asarray(a.to_dense())
    y = np.random.RandomState(6).normal(size=(6, 3)).astype(np.float32)
    np.testing.assert_allclose(S.matmul(a, y), da @ y, atol=1e-5)
    v = y[:, 0]
    np.testing.assert_allclose(S.mv(a, v), da @ v, atol=1e-5)
    base = np.random.RandomState(7).normal(size=(4, 3)).astype(np.float32)
    np.testing.assert_allclose(S.addmm(base, a, y, beta=0.5, alpha=2.0),
                               0.5 * base + 2.0 * (da @ y), atol=1e-5)


def test_csr_roundtrip_and_masked_matmul():
    csr = S.sparse_csr_tensor([0, 2, 3], [0, 2, 1],
                              np.array([1.0, 2.0, 3.0], np.float32), (2, 3))
    d = np.zeros((2, 3), np.float32)
    d[0, 0], d[0, 2], d[1, 1] = 1, 2, 3
    np.testing.assert_allclose(csr.to_dense(), d)
    rs = np.random.RandomState(8)
    x = rs.normal(size=(2, 5)).astype(np.float32)
    y = rs.normal(size=(5, 3)).astype(np.float32)
    out = S.masked_matmul(x, y, csr)
    full = x @ y
    np.testing.assert_allclose(np.asarray(out.to_dense())[d != 0],
                               full[d != 0], atol=1e-5)


def test_sparse_softmax_rows_normalize():
    x = _rand_coo((5, 8), 12, seed=9)
    out = S.nn.functional.softmax(x)
    d = np.asarray(out.to_dense())
    rows_with = np.unique(np.asarray(jax.device_get(x.indices))[0])
    np.testing.assert_allclose(d.sum(axis=1)[rows_with], 1.0, atol=1e-5)


def test_sparse_attention_matches_masked_dense():
    rs = np.random.RandomState(10)
    b, h, s, dd = 2, 2, 8, 4
    q = jnp.asarray(rs.normal(size=(b, h, s, dd)), jnp.float32)
    k = jnp.asarray(rs.normal(size=(b, h, s, dd)), jnp.float32)
    v = jnp.asarray(rs.normal(size=(b, h, s, dd)), jnp.float32)
    # causal pattern as COO
    rows, cols = np.tril_indices(s)
    mask = S.sparse_coo_tensor(np.stack([rows, cols]),
                               np.ones(len(rows), np.float32), (s, s))
    out = S.nn.functional.attention(q, k, v, mask)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(dd)
    dmask = np.asarray(mask.to_dense()) != 0
    logits = np.where(dmask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("subm", [True, False])
def test_sparse_conv3d_matches_dense(subm):
    rs = np.random.RandomState(11)
    shape = (1, 5, 5, 5)  # (N, D, H, W), 4 channels
    x = _rand_coo(shape, 10, seed=11, channels=4)
    w = jnp.asarray(rs.normal(size=(3, 3, 3, 4, 2)), jnp.float32)
    if subm:
        out = S.nn.functional.subm_conv3d(x, w, padding=1)
    else:
        out = S.nn.functional.conv3d(x, w, stride=1, padding=1)
    dense_in = jnp.asarray(x.to_dense())  # (N, D, H, W, C)
    ref = jax.lax.conv_general_dilated(
        dense_in, w, window_strides=(1, 1, 1), padding=[(1, 1)] * 3,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    got = np.asarray(out.to_dense())
    if subm:
        # submanifold: valid only at input active sites
        ii = np.asarray(jax.device_get(x.indices))
        np.testing.assert_allclose(
            got[ii[0], ii[1], ii[2], ii[3]],
            np.asarray(ref)[ii[0], ii[1], ii[2], ii[3]], atol=1e-4)
    else:
        np.testing.assert_allclose(got, ref, atol=1e-4)


def test_sparse_maxpool3d_positive_values():
    x = _rand_coo((1, 4, 4, 4), 9, seed=12, channels=3)
    x = x.with_values(jnp.abs(x.values) + 0.1)  # positive → dense oracle ok
    out = S.nn.functional.max_pool3d(x, 2, stride=2)
    dense_in = np.asarray(x.to_dense())
    ref = np.asarray(jax.lax.reduce_window(
        jnp.asarray(dense_in), -jnp.inf, jax.lax.max,
        (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
    got = np.asarray(out.to_dense())
    active = got != 0
    np.testing.assert_allclose(got[active], ref[active], atol=1e-6)


def test_sparse_layers_and_batchnorm():
    x = _rand_coo((1, 4, 4, 4), 8, seed=13, channels=4)
    assert float(jnp.min(S.nn.ReLU()(x).values)) >= 0.0
    assert float(jnp.max(S.nn.ReLU6()(x).values)) <= 6.0
    conv = S.nn.SubmConv3D(4, 6, 3, padding=1)
    y = conv(x)
    assert y.values.shape == (8, 6)
    assert y.to_dense().shape == (1, 4, 4, 4, 6)
    bn = S.nn.BatchNorm(6)
    bn.train()
    z = bn(y)
    v = np.asarray(z.values)
    np.testing.assert_allclose(v.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(v.std(axis=0), 1.0, atol=1e-2)
    pool = S.nn.MaxPool3D(2, stride=2)
    p = pool(y)
    assert p.to_dense().shape == (1, 2, 2, 2, 6)


def test_batched_sparse_softmax_rows_normalize():
    """review r3: leading sparse dims must join the segment id."""
    idx = np.array([[0, 0, 1, 1],    # batch
                    [0, 0, 0, 0],    # row (same row id in both batches!)
                    [0, 1, 0, 1]])   # col
    x = S.sparse_coo_tensor(idx, np.array([1.0, 2.0, 5.0, 8.0], np.float32),
                            (2, 1, 2))
    out = S.nn.functional.softmax(x)
    d = np.asarray(out.to_dense())
    np.testing.assert_allclose(d.sum(-1), np.ones((2, 1)), atol=1e-5)
    # batches normalized independently: different distributions
    assert abs(d[0, 0, 0] - d[1, 0, 0]) > 1e-3


def test_sparse_batchnorm_running_stats_update():
    """review r3: training must record running-stat updates like dense BN."""
    from paddle_tpu import nn as dense_nn
    bn = S.nn.BatchNorm(3)
    bn = bn.tag_paths()
    bn.train()
    x = _rand_coo((1, 4, 4, 4), 10, seed=21, channels=3)
    x = x.with_values(x.values * 3.0 + 1.0)
    with dense_nn.stateful(training=True) as ctx:
        bn(x)
    assert any("running_mean" in k for k in ctx.updates)
    bn2 = bn.apply_updates(ctx.updates)
    assert float(jnp.sum(jnp.abs(jnp.asarray(bn2.running_mean)))) > 0


def test_sparse_conv_rejects_unsupported():
    x = _rand_coo((1, 4, 4, 4), 6, seed=22, channels=2)
    w = jnp.zeros((3, 3, 3, 2, 2))
    with pytest.raises(NotImplementedError):
        S.nn.functional.conv3d(x, w, dilation=2)
    with pytest.raises(NotImplementedError):
        S.nn.functional.subm_conv3d(x, w, groups=2)
