"""Speculative decoding in the continuous-batching engine.

The invariant that matters: greedy speculative output is BIT-IDENTICAL
to plain greedy decode regardless of acceptance rate (lossless). The
win: repetitive text accepts multi-token runs, so the engine takes
FEWER device steps than tokens emitted — weights/KV read once per run.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.decode_engine import DecodeEngine
from paddle_tpu.models import gpt


def _model(max_seq=256):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=max_seq, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _reference(model, prompt, n_new):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new)
    return list(np.asarray(out)[0, len(prompt):])


def test_lossless_on_random_prompts():
    """Low-acceptance regime: drafts rarely match, output must still be
    exactly the plain greedy stream."""
    model = _model()
    rs = np.random.RandomState(0)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (5, 11, 23)]
    eng = DecodeEngine(model, max_slots=2, max_len=128, speculative_k=4)
    reqs = [eng.submit(p, max_new_tokens=7) for p in prompts]
    eng.run()
    for req, p in zip(reqs, prompts):
        assert req.tokens == _reference(model, p, 7), p


def test_lossless_and_fewer_steps_on_repetitive_prompts():
    """High-acceptance regime: a looping prompt makes the model echo the
    loop; prompt-lookup drafts then accept runs and the engine finishes
    in fewer device steps than tokens."""
    model = _model()
    loop = [7, 21, 3, 42]
    prompt = loop * 8                       # 32 tokens of pure period-4
    n_new = 24
    ref = _reference(model, prompt, n_new)
    eng = DecodeEngine(model, max_slots=1, max_len=256, speculative_k=4)
    req = eng.submit(prompt, max_new_tokens=n_new)
    eng.run()
    assert req.tokens == ref
    # the speed claim, measurable without hardware: device round-trips
    assert eng.steps < eng.tokens_emitted, (eng.steps,
                                            eng.tokens_emitted)


def test_single_compile_and_mixed_slots():
    model = _model()
    eng = DecodeEngine(model, max_slots=2, max_len=128, speculative_k=3)
    rs = np.random.RandomState(1)
    loop = [5, 9]
    reqs = [eng.submit(loop * 10, max_new_tokens=8),
            eng.submit(list(rs.randint(0, 96, size=9)), max_new_tokens=5)]
    eng.step()
    # the no-recompile property: admissions/retirements after the first
    # dispatch must never add compiled signatures (measured as a delta —
    # absolute counts proved sensitive to full-suite interpreter state)
    base = eng._verify_fn._cache_size()
    reqs.append(eng.submit(loop * 6, max_new_tokens=6))
    eng.run()
    assert eng._verify_fn._cache_size() == base
    for req in reqs:
        assert req.tokens == _reference(model, req.prompt,
                                        req.max_new_tokens)


def test_eos_respected_mid_acceptance():
    model = _model()
    prompt = [3, 4] * 10
    ref = _reference(model, prompt, 12)
    eos = ref[4]
    cut = ref.index(eos) + 1
    eng = DecodeEngine(model, max_slots=1, max_len=128, speculative_k=4)
    req = eng.submit(prompt, max_new_tokens=12, eos_id=eos)
    eng.run()
    assert req.done and req.tokens == ref[:cut]


def test_chunked_speculative_lossless():
    """speculative_k composes with steps_per_call: a whole chunk of
    draft/verify/accept iterations per dispatch, still bit-identical to
    plain greedy, in strictly fewer dispatches."""
    model = _model()
    rs = np.random.RandomState(2)
    loop = [11, 4, 37]
    prompts = [loop * 9, list(rs.randint(0, 96, size=13)), loop * 5]
    eng = DecodeEngine(model, max_slots=2, max_len=160, speculative_k=4,
                      steps_per_call=4)
    reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    for req in reqs:
        assert req.tokens == _reference(model, req.prompt, 10), req.prompt
    assert eng._verify_fn._cache_size() == 1
    # 30 tokens total; chunked spec needs only a handful of dispatches
    assert eng.steps < 8, eng.steps


def test_eos_mid_chunk_respected():
    """eos inside an accepted run inside a chunk: emission stops at eos
    (device-side truncation), the slot frees for the next request."""
    model = _model()
    prompt = [3, 4] * 10
    ref = _reference(model, prompt, 12)
    eos = ref[4]
    cut = ref.index(eos) + 1
    eng = DecodeEngine(model, max_slots=1, max_len=128, speculative_k=4,
                      steps_per_call=3)
    req = eng.submit(prompt, max_new_tokens=12, eos_id=eos)
    eng.run()
    assert req.done and req.tokens == ref[:cut]


def test_sampling_rejected():
    with pytest.raises(NotImplementedError):
        DecodeEngine(_model(), speculative_k=4, temperature=0.8)
    with pytest.raises(ValueError):
        DecodeEngine(_model(), speculative_k=1)
