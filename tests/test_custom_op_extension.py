"""The custom-op extension path, end to end (VERDICT r3 item 10).

Reference analog: out-of-tree kernel registration — paddle/phi/capi/ (the
plugin C ABI), framework/custom_operator.cc:713 (RegisterOperatorWithMetaInfo)
and python/paddle/utils/cpp_extension/cpp_extension.py:78 (the user-facing
build path). Here the whole story is Python: a user writes a Pallas kernel,
wires autodiff with jax.custom_vjp, and registers it with
``ops.registry.register_op`` — including its numpy oracle, so the SAME
OpTest discipline that covers built-in ops covers theirs.

This file IS the worked example referenced by README.md §"Custom ops".
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.registry import all_ops, get_op, register_op

# ---------------------------------------------------------------------------
# 1. The kernel: fused softcap  y = cap * tanh(x / cap)
#    (a logits-softcapping op the built-in surface doesn't have)
# ---------------------------------------------------------------------------


def _softcap_kernel(x_ref, o_ref, *, cap):
    x = x_ref[...]
    o_ref[...] = (jnp.tanh(x / cap) * cap).astype(x.dtype)


def _softcap_fwd_impl(x, cap, interpret):
    return pl.pallas_call(
        functools.partial(_softcap_kernel, cap=cap),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


# 2. Autodiff: custom_vjp (≙ the custom op's backward kernel registration)

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def softcap(x, cap=30.0, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _softcap_fwd_impl(jnp.asarray(x), float(cap), interpret)


def _softcap_vjp_fwd(x, cap, interpret):
    y = softcap(x, cap, interpret)
    return y, x


def _softcap_vjp_bwd(cap, interpret, x, g):
    # d/dx [cap * tanh(x/cap)] = 1 - tanh(x/cap)^2
    t = jnp.tanh(x / cap)
    return (g * (1.0 - t * t),)


softcap.defvjp(_softcap_vjp_fwd, _softcap_vjp_bwd)


# 3. Registration WITH the numpy oracle — the op joins the registry like
#    any built-in (category "custom"; np_ref is the OpTest contract)

_SAMPLE = np.random.RandomState(7).randn(4, 16).astype(np.float32) * 3.0

register_op(
    "softcap_example", softcap, "custom",
    np_ref=lambda x: np.tanh(x / 30.0) * 30.0,
    sample_args=lambda: ((_SAMPLE,), {}),
    ref="user extension (≙ phi/capi plugin kernels)",
    differentiable=True)


# ---------------------------------------------------------------------------
# The same three OpTest checks tests/test_op_suite.py runs on every
# registered op, applied to the extension op explicitly (the suite's
# parametrized lists are built at ITS import, before this registration).
# ---------------------------------------------------------------------------


def test_custom_op_is_registered():
    spec = get_op("softcap_example")
    assert spec.category == "custom" and spec.np_ref is not None
    assert any(op.name == "softcap_example" for op in all_ops())


def test_custom_op_eager_matches_oracle():
    spec = get_op("softcap_example")
    args, kwargs = spec.sample_args()
    got = spec.fn(*args, **kwargs)
    want = spec.np_ref(*[np.asarray(a) for a in args])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_custom_op_jit_matches_eager():
    spec = get_op("softcap_example")
    args, kwargs = spec.sample_args()
    eager = spec.fn(*args, **kwargs)
    jitted = jax.jit(lambda a: spec.fn(a, **kwargs))(args[0])
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                               rtol=1e-6, atol=1e-6)


def test_custom_op_grad_matches_finite_difference():
    spec = get_op("softcap_example")
    (x,), kwargs = spec.sample_args()

    def scalar_fn(v):
        return jnp.sum(spec.fn(v, **kwargs) ** 2) / 2

    analytic = np.asarray(jax.grad(scalar_fn)(jnp.asarray(x)))
    eps = 1e-3
    flat = np.asarray(x, np.float32).reshape(-1)
    for i in np.linspace(0, flat.size - 1, 5).astype(int):
        xp, xm = flat.copy(), flat.copy()
        xp[i] += eps
        xm[i] -= eps
        numeric = (float(scalar_fn(jnp.asarray(xp.reshape(x.shape))))
                   - float(scalar_fn(jnp.asarray(xm.reshape(x.shape))))) \
            / (2 * eps)
        np.testing.assert_allclose(analytic.reshape(-1)[i], numeric,
                                   rtol=3e-2, atol=3e-3)


def test_custom_op_composes_with_framework():
    """The extension op drops into a Module forward and trains."""
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.nn import functional as F

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            return softcap(self.fc(x), cap=5.0)

    net = Net()
    params, _ = net.split_params()
    opt = optim.SGD(learning_rate=0.1)
    state = opt.init(params)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 4, (16,)), jnp.int32)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            return F.cross_entropy(net.merge_params(p)(x), y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, new_s = opt.update(grads, state, params)
        return new_p, new_s, loss

    l0 = None
    for _ in range(20):
        params, state, loss = step(params, state)
        l0 = l0 if l0 is not None else float(loss)
    assert float(loss) < l0
