"""Serving front-end (paddle_tpu/serving, ISSUE 10): admission
control, deadline semantics under queue wait, backfill, streaming
bit-identity, dynamic bucket selection, and the deterministic load
generator."""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu import stats
from paddle_tpu.models import gpt
from paddle_tpu.inference.decode_engine import DecodeEngine
from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.serving import (FrontEnd, dynamic_bucket, loadgen,
                                projected_ttft)


def _model():
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=256, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


@pytest.fixture(scope="module")
def model():
    return _model()


def _prompts(n, seed=0, lo=3, hi=30):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(0, 96, size=int(rs.randint(lo, hi))))
            for _ in range(n)]


ENGINES = {
    "plain": lambda m: DecodeEngine(m, max_slots=2, max_len=96),
    "chunked": lambda m: DecodeEngine(m, max_slots=2, max_len=96,
                                      steps_per_call=4),
    "speculative": lambda m: DecodeEngine(m, max_slots=2, max_len=96,
                                          speculative_k=3,
                                          steps_per_call=2),
    "paged": lambda m: PagedDecodeEngine(m, n_pages=24, max_slots=2,
                                         steps_per_call=2),
}


@pytest.mark.parametrize("path", list(ENGINES))
def test_stream_bit_identity_vs_direct_submit(model, path):
    """Acceptance: greedy token streams THROUGH the scheduler are
    byte-identical to direct submit()+run() on every engine path —
    with more requests than slots, so queueing and backfill are
    actually exercised."""
    prompts = _prompts(6, seed=1)
    direct = ENGINES[path](model)
    refs = [direct.submit(p, max_new_tokens=8) for p in prompts]
    direct.run()
    ref_tokens = [list(r.tokens) for r in refs]

    stats.reset("serve/")
    fe = FrontEnd(ENGINES[path](model))
    reqs = [fe.submit(p, max_new_tokens=8) for p in prompts]
    fe.run()
    assert [list(r.tokens) for r in reqs] == ref_tokens
    assert all(r.status == "done" for r in reqs)
    # 6 requests through 2 slots: retirements must have backfilled
    assert stats.get("serve/queue_backfill") > 0


def test_streaming_iterator_matches_final_tokens(model):
    prompts = _prompts(3, seed=2)
    direct = DecodeEngine(model, max_slots=2, max_len=96)
    refs = [direct.submit(p, max_new_tokens=8) for p in prompts]
    direct.run()

    fe = FrontEnd(DecodeEngine(model, max_slots=2, max_len=96))
    reqs = [fe.submit(p, max_new_tokens=8) for p in prompts]
    # iterate the LAST submitted request first: streaming must pump the
    # whole front-end (its peers finish too)
    streamed = list(reqs[-1].stream())
    assert streamed == list(refs[-1].tokens)
    fe.run()
    for got, ref in zip(reqs, refs):
        assert list(got.tokens) == list(ref.tokens)


def test_queued_deadline_rejected_before_prefill(model):
    """Satellite: a request whose short deadline expires while queued
    is rejected with a DISTINCT status, never reaches a prefill, and
    lands on the queue-reject counter — not the eviction counter."""
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=1, max_len=96)
    fe = FrontEnd(eng, admit_ahead=0)
    blocker = fe.submit(_prompts(1, seed=3)[0], max_new_tokens=12)
    doomed = fe.submit(_prompts(1, seed=4)[0], max_new_tokens=12,
                       deadline_s=1e-4)
    time.sleep(0.01)
    fe.run()
    assert blocker.status == "done"
    assert doomed.status == "rejected-deadline"
    assert "while queued" in doomed.error
    assert doomed.engine_req is None          # never admitted
    assert doomed.tokens == []
    assert stats.get("serve/queue_deadline_rejects") == 1
    assert stats.get("serve/deadline_evictions") == 0


def test_mid_decode_eviction_keeps_distinct_counter(model):
    """The OTHER side of the satellite: a deadline passing after
    admission is an eviction (device work abandoned), not a queue
    reject."""
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=1, max_len=160)
    fe = FrontEnd(eng)
    r = fe.submit(_prompts(1, seed=5)[0], max_new_tokens=120,
                  deadline_s=0.05)
    fe.step()                  # admitted and decoding
    time.sleep(0.08)
    fe.run()
    assert r.status == "failed"
    assert "deadline" in r.error and "queued" not in r.error
    assert stats.get("serve/deadline_evictions") == 1
    assert stats.get("serve/queue_deadline_rejects") == 0


def test_queue_full_rejects_at_submit(model):
    stats.reset("serve/")
    fe = FrontEnd(DecodeEngine(model, max_slots=1, max_len=96),
                  queue_depth=2)
    reqs = [fe.submit([5, 6, 7], max_new_tokens=4) for _ in range(4)]
    rejected = [r for r in reqs if r.status == "rejected-queue-full"]
    # first fills the queue head... depth 2 bounds the WAITING set
    assert len(rejected) >= 1
    assert stats.get("serve/queue_rejects") == len(rejected)
    fe.run()
    for r in reqs:
        if r not in rejected:
            assert r.status == "done"


def test_hopeless_deadline_rejected_at_admission(model):
    """Tentpole: once the front-end has observed real TTFTs, a queued
    request whose remaining budget can't plausibly reach a first token
    is rejected at admission instead of admitted-then-evicted."""
    stats.reset("serve/")
    fe = FrontEnd(DecodeEngine(model, max_slots=1, max_len=96))
    warm = fe.submit(_prompts(1, seed=6)[0], max_new_tokens=6)
    fe.run()
    assert warm.status == "done" and fe._ttft_ema is not None
    hopeless = fe.submit(_prompts(1, seed=7)[0], max_new_tokens=6,
                         deadline_s=fe._ttft_ema / 1e3)
    fe.run()
    assert hopeless.status == "rejected-deadline"
    assert "hopeless" in hopeless.error
    assert stats.get("serve/queue_hopeless_rejects") == 1
    assert stats.get("serve/deadline_evictions") == 0


def test_priority_admission_order(model):
    """Priority policy: with one slot, a later high-priority request
    is admitted before an earlier low-priority one."""
    eng = DecodeEngine(model, max_slots=1, max_len=96)
    fe = FrontEnd(eng, admission="priority", admit_ahead=0)
    blocker = fe.submit([1, 2, 3], max_new_tokens=6)
    low = fe.submit([4, 5, 6], max_new_tokens=4, priority=0)
    high = fe.submit([7, 8, 9], max_new_tokens=4, priority=5)
    fe.run()
    assert all(r.status == "done" for r in (blocker, low, high))
    assert high.engine_req.t_first < low.engine_req.t_first


def test_edf_admission_order(model):
    eng = DecodeEngine(model, max_slots=1, max_len=96)
    fe = FrontEnd(eng, admission="edf", admit_ahead=0)
    blocker = fe.submit([1, 2, 3], max_new_tokens=6)
    late = fe.submit([4, 5, 6], max_new_tokens=4, deadline_s=60.0)
    soon = fe.submit([7, 8, 9], max_new_tokens=4, deadline_s=30.0)
    fe.run()
    assert all(r.status == "done" for r in (blocker, late, soon))
    assert soon.engine_req.t_first < late.engine_req.t_first


def test_invalid_request_fails_at_submit(model):
    fe = FrontEnd(DecodeEngine(model, max_slots=1, max_len=64))
    assert fe.engine.T == 128        # 128-multiple rounding
    with pytest.raises(ValueError):
        fe.submit([3] * 120, max_new_tokens=32)
    with pytest.raises(ValueError):
        fe.submit([], max_new_tokens=4)


def test_fed_occupancy_under_backlog(model):
    """With a standing backlog the scheduler must keep slots full:
    fed-occupancy (sampled only on demand>free steps) well above the
    1/slots trickling floor."""
    stats.reset("serve/")
    fe = FrontEnd(DecodeEngine(model, max_slots=4, max_len=96))
    reqs = [fe.submit(p, max_new_tokens=10) for p in _prompts(16, seed=8)]
    fe.run()
    assert all(r.status == "done" for r in reqs)
    snap = stats.snapshot("serve/")
    n = snap.get("serve/fed_occupancy.count", 0)
    assert n > 0
    mean = snap.get("serve/fed_occupancy.sum", 0) / n
    assert mean >= 0.5, mean
    assert stats.get("serve/queue_backfill") > 0
    # queue wait was actually measured
    assert snap.get("serve/queue_wait_s.count", 0) == 16


# -- dynamic bucket selection ----------------------------------------------

def test_dynamic_bucket_idle_picks_covering_bucket(model):
    eng = DecodeEngine(model, max_slots=4, max_len=256)
    assert eng.free_slots == 4
    # idle: a small prompt takes its smallest covering bucket (one
    # chunk, least padding)
    for remaining, want in ((5, 16), (17, 32), (120, 128)):
        assert dynamic_bucket(eng, remaining) == want


def test_dynamic_bucket_monotonic_under_load(model):
    """Occupancy shifts the optimum toward fewer/larger chunks, never
    smaller: every interleaved decode dispatch rides the TTFT path."""
    eng = DecodeEngine(model, max_slots=8, max_len=256,
                       steps_per_call=8)
    idle_choice = dynamic_bucket(eng, 200)
    # simulate 7 live slots (free_slots counts None entries)
    eng._slot_req = [object()] * 7 + [None]
    busy_choice = dynamic_bucket(eng, 200)
    assert busy_choice >= idle_choice
    # the projection itself must charge busy engines more
    assert (projected_ttft(eng, 200, idle_choice)
            > 0)
    eng._slot_req = [None] * 8


def test_bucket_policy_validated(model):
    eng = DecodeEngine(model, max_slots=1, max_len=96)
    eng.bucket_policy = lambda e, r: 13          # not a bucket
    eng.submit([1, 2, 3], max_new_tokens=2)
    with pytest.raises(ValueError):
        eng.step()


# -- load generator ---------------------------------------------------------

def test_poisson_trace_deterministic():
    a = loadgen.poisson_trace(20, qps=50.0, seed=7)
    b = loadgen.poisson_trace(20, qps=50.0, seed=7)
    assert [(x.t, x.prompt, x.max_new_tokens) for x in a] \
        == [(y.t, y.prompt, y.max_new_tokens) for y in b]
    c = loadgen.poisson_trace(20, qps=50.0, seed=8)
    assert [x.prompt for x in a] != [y.prompt for y in c]
    assert a[0].t == 0.0
    assert all(y.t >= x.t for x, y in zip(a, a[1:]))


def test_from_trace_sorts_and_replays(model):
    rows = [{"t": 0.02, "prompt": [4, 5], "max_new_tokens": 3},
            {"t": 0.0, "prompt": [1, 2, 3], "max_new_tokens": 4,
             "priority": 1}]
    arrivals = loadgen.from_trace(rows)
    assert [a.t for a in arrivals] == [0.0, 0.02]
    fe = FrontEnd(DecodeEngine(model, max_slots=2, max_len=96))
    reqs = loadgen.replay(
        arrivals,
        submit=lambda a: fe.submit(a.prompt,
                                   max_new_tokens=a.max_new_tokens,
                                   priority=a.priority),
        pump=fe.step, speed=10.0)
    fe.run()
    assert [r.status for r in reqs] == ["done", "done"]
    assert len(reqs[0].tokens) == 4 and len(reqs[1].tokens) == 3
