"""Gradient-compressed data parallelism (VERDICT r4 item 9)
≙ fleet/meta_optimizers/dgc_optimizer.py + dgc_op.cc: the dp gradient
exchange narrows to bf16/int8 with error feedback; convergence must stay
at parity with full-precision sync on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.compression import (
    build_compressed_dp_step, compressed_psum_mean, init_error_feedback)
from paddle_tpu import optimizer as optim


def _problem(seed=0):
    """Tiny least-squares: params w (8, 4); batch (B, 8) -> targets (B, 4)
    from a fixed true w — loss is exactly minimizable, so convergence
    differences show."""
    rs = np.random.RandomState(seed)
    w_true = rs.randn(8, 4).astype(np.float32)
    x = rs.randn(64, 8).astype(np.float32)
    y = x @ w_true + 0.01 * rs.randn(64, 4).astype(np.float32)
    params = {"w": jnp.zeros((8, 4), jnp.float32)}

    def loss_fn(p, batch):
        xb, yb = batch
        pred = xb @ p["w"]
        return jnp.mean((pred - yb) ** 2)

    return params, loss_fn, (jnp.asarray(x), jnp.asarray(y))


def _run(method, steps=60, lr=0.1, seed=0):
    topo = dist.init_mesh(dp=8)
    try:
        params, loss_fn, batch = _problem(seed)
        opt = optim.SGD(learning_rate=lr)
        opt_state = opt.init(params)
        if method is None:
            strat = fleet.DistributedStrategy()
        else:
            strat = fleet.DistributedStrategy()
            strat.grad_compression = method
        fleet._strategy = strat
        fleet._topo = topo
        step = fleet.build_dp_train_step(loss_fn, opt, strategy=strat)
        ef = init_error_feedback(params, topo.mesh) if method else ()
        losses = []
        for _ in range(steps):
            params, opt_state, ef, loss = step(params, opt_state, ef,
                                               batch)
            losses.append(float(loss))
        return losses
    finally:
        from paddle_tpu.distributed import mesh as mesh_lib
        mesh_lib.set_topology(None)
        fleet._strategy = None
        fleet._topo = None


def test_channel_is_lossy_but_error_feedback_preserves_sum():
    """The int8 channel alone loses information; with error feedback the
    CUMULATIVE dequantized signal tracks the cumulative true signal (the
    DGC residual-accumulation property)."""
    topo = dist.init_mesh(dp=8)
    try:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        rs = np.random.RandomState(0)
        gs = jnp.asarray(rs.randn(30, 8, 16, 8).astype(np.float32)) * 0.1

        def sync(g, e):
            out, new_e = compressed_psum_mean(
                {"w": g[0]}, {"w": e[0]}, "dp", "int8")
            return out["w"], new_e["w"][None]

        smap = shard_map(sync, mesh=topo.mesh,
                         in_specs=(P("dp"), P("dp")), out_specs=(P(), P("dp")),
                         check_vma=False)
        ef = jnp.zeros((8, 16, 8))
        true_cum = np.zeros((16, 8))
        deq_cum = np.zeros((16, 8))
        worst_single = 0.0
        for t in range(30):
            g = gs[t]
            synced, ef = jax.jit(smap)(g, ef)
            true_mean = np.asarray(g).mean(0)
            worst_single = max(worst_single,
                               np.abs(np.asarray(synced) - true_mean).max())
            true_cum += true_mean
            deq_cum += np.asarray(synced)
        # single-step error is real (lossy channel)...
        assert worst_single > 1e-5
        # ...but the residual feeds back: cumulative error stays bounded
        # by ~one quantization step instead of growing with t
        assert np.abs(deq_cum - true_cum).max() < worst_single * 3
    finally:
        from paddle_tpu.distributed import mesh as mesh_lib
        mesh_lib.set_topology(None)


@pytest.mark.parametrize("method", ["bf16", "int8"])
def test_convergence_parity_on_cpu_mesh(method):
    base = _run(None)
    comp = _run(method)
    # both drive the loss down hard
    assert comp[-1] < 0.05 * comp[0], comp[-1]
    # and the compressed trajectory lands at parity with full precision
    assert comp[-1] <= base[-1] * 1.5 + 1e-4, (comp[-1], base[-1])


def test_unknown_method_rejected():
    topo = dist.init_mesh(dp=8)
    try:
        params, loss_fn, _ = _problem()
        with pytest.raises(ValueError):
            build_compressed_dp_step(loss_fn, optim.SGD(0.1), topo.mesh,
                                     "fp4")
    finally:
        from paddle_tpu.distributed import mesh as mesh_lib
        mesh_lib.set_topology(None)
