"""True multi-process (multi-controller) distributed execution over the
framework's own spawn/env plumbing — the DCN story (VERDICT L4/Missing 7:
"no cross-host PP runtime"). Two OS processes, each owning one CPU device,
form one jax.distributed job; collectives and the pipeline's rolling
buffer then cross PROCESS boundaries (gRPC standing in for DCN), exactly
how a multi-host TPU pod runs the same single-program SPMD code.

Reference analog: test_collective_api_base.py:292 check_with_place —
2-rank subprocess collectives compared against local semantics; here the
ranks go through paddle_tpu.distributed.spawn + init_parallel_env.
The worker body lives in tests/_mh_worker.py, whose module top pins the
CPU platform before unpickling can touch jax."""

import pytest

import paddle_tpu.distributed as dist
from paddle_tpu import native

from _mh_worker import worker as _worker


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_cross_process_collectives_and_ring(tmp_path):
    dist.spawn(_worker, args=(str(tmp_path),), nprocs=2,
               master_port=23491)
    assert (tmp_path / "ok_0").exists() and (tmp_path / "ok_1").exists()
