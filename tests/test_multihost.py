"""True multi-process (multi-controller) distributed execution over the
framework's own spawn/env plumbing — the DCN story (VERDICT L4/Missing 7:
"no cross-host PP runtime"). Two OS processes, each owning one CPU device,
form one jax.distributed job; collectives and the pipeline's rolling
buffer then cross PROCESS boundaries (gRPC standing in for DCN), exactly
how a multi-host TPU pod runs the same single-program SPMD code.

Reference analog: test_collective_api_base.py:292 check_with_place —
2-rank subprocess collectives compared against local semantics; here the
ranks go through paddle_tpu.distributed.spawn + init_parallel_env.
The worker body lives in tests/_mh_worker.py, whose module top pins the
CPU platform before unpickling can touch jax."""

import json

import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu import native

import _mh_worker
from _mh_worker import worker as _worker


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_cross_process_collectives_and_ring(tmp_path):
    dist.spawn(_worker, args=(str(tmp_path),), nprocs=2,
               master_port=23491)
    assert (tmp_path / "ok_0").exists() and (tmp_path / "ok_1").exists()


def test_two_controller_gpt_hybrid_parity(tmp_path):
    """VERDICT r4 item 4: the FULL dp×fsdp×tp GPT train step under
    jax.distributed with 2 real processes × 4 virtual CPU devices each,
    loss-parity against the single-controller 8-device run (ref
    test_dist_base.py:901)."""
    from paddle_tpu.distributed import mesh as mesh_lib

    # single-controller reference on the pytest process's 8 devices
    want = _mh_worker.gpt_losses()
    mesh_lib.set_topology(None)

    dist.spawn(_mh_worker.gpt_worker, args=(str(tmp_path),), nprocs=2,
               master_port=23493)
    for rank in range(2):
        got = json.load(open(tmp_path / f"losses_{rank}.json"))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5,
                                   err_msg=f"rank {rank}")


@pytest.mark.skipif(not native.is_available(),
                    reason="native toolchain unavailable")
def test_two_controller_fleet_executor_pp(tmp_path):
    """A FleetExecutor pipeline whose two stages live on the two
    controllers of one jax.distributed job — each stage an SPMD program
    over its local 2×2 (dp, tp) mesh, boundary tensors over the native
    p2p endpoint. Grad + loss parity vs the full-model autodiff oracle."""
    dist.spawn(_mh_worker.fe_worker, args=(str(tmp_path), 23597),
               nprocs=2, master_port=23495)
    ref_loss, ref_grads = _mh_worker.fe_reference()
    g0 = json.load(open(tmp_path / "fe_0.json"))
    g1 = json.load(open(tmp_path / "fe_1.json"))
    np.testing.assert_allclose(g1["loss"], ref_loss, rtol=1e-5)
    np.testing.assert_allclose(
        g0["grad_w_sum"], float(np.asarray(ref_grads[0]["w"]).sum()),
        rtol=1e-4)
    np.testing.assert_allclose(
        g1["grad_w_sum"], float(np.asarray(ref_grads[1]["w"]).sum()),
        rtol=1e-4)
