"""GQA/MQA + rotary positions for the GPT family (Llama-family shapes).

GQA shrinks the KV cache — and therefore the decode HBM roofline — by
n_heads/n_kv_heads; rope replaces the learned position table. Both must
work across every decode path: full forward, cached generate, the
flash-decode kernel, the continuous-batching engine, speculative
decoding, and training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.decode_engine import (
    DecodeEngine, decode_roofline_tokens_per_sec)
from paddle_tpu.models import gpt
from paddle_tpu import optimizer as optim


def _cfg(**kw):
    base = dict(vocab_size=96, max_seq_len=128, d_model=32, n_layers=2,
                n_heads=4, dtype=jnp.float32)
    base.update(kw)
    return gpt.GPTConfig(**base)


@pytest.mark.parametrize("kv,rope", [(2, False), (1, True), (4, True)])
def test_generate_engine_parity(kv, rope):
    """generate (scan path) and the continuous-batching engine must agree
    token-for-token for GQA/MQA/rope configs."""
    model = gpt.GPT(_cfg(n_kv_heads=kv, rope=rope), seed=0)
    rs = np.random.RandomState(0)
    prompt = list(rs.randint(0, 96, size=9))
    ref = list(np.asarray(model.generate(
        jnp.asarray(np.asarray(prompt)[None], jnp.int32),
        max_new_tokens=6, max_len=64))[0, len(prompt):])
    eng = DecodeEngine(model, max_slots=2, max_len=128)
    req = eng.submit(prompt, max_new_tokens=6)
    eng.run()
    assert req.tokens == ref
    # the engine cache really is GQA-sized
    assert eng.kc.shape[2] == kv


def test_gqa_kernel_vs_einsum_path():
    """The flash-decode kernel's GQA grouping must match the einsum
    fallback bit-for-bit on the generate stream."""
    from paddle_tpu import flags
    model = gpt.GPT(_cfg(n_kv_heads=2), seed=0)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 96, (2, 8)),
                       jnp.int32)
    with_kernel = np.asarray(model.generate(toks, max_new_tokens=6,
                                            max_len=128))
    flags.set_flags({"use_pallas_kernels": False})
    try:
        gpt._GEN_CACHE.pop(model, None)
        without = np.asarray(model.generate(toks, max_new_tokens=6,
                                            max_len=128))
    finally:
        flags.set_flags({"use_pallas_kernels": True})
    np.testing.assert_array_equal(with_kernel, without)


def test_rope_is_position_sensitive_and_trains():
    """Rope must (a) make attention position-dependent despite no wpe
    table, (b) train: loss decreases on repeated data."""
    cfg = _cfg(rope=True, n_kv_heads=2)
    model = gpt.GPT(cfg, seed=0)
    assert model.wpe is None
    t1 = jnp.asarray([[5, 7, 5, 7, 9, 11, 13, 15]], jnp.int32)
    t2 = jnp.asarray([[7, 5, 5, 7, 9, 11, 13, 15]], jnp.int32)
    l1 = np.asarray(model(t1))
    l2 = np.asarray(model(t2))
    # same multiset of early tokens, different order → logits at the last
    # position must differ (pure bag-of-words would not)
    assert np.abs(l1[0, -1] - l2[0, -1]).max() > 1e-5

    opt = optim.AdamW(learning_rate=1e-3)
    params, st = gpt.init_train_state(model, opt)
    step = gpt.build_train_step(model, opt)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 96, (4, 32)),
                       jnp.int32)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(8):
        params, st, loss = step(params, st, toks, rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_speculative_with_gqa_rope():
    model = gpt.GPT(_cfg(n_kv_heads=2, rope=True), seed=0)
    loop = [3, 9, 27, 4]
    prompt = loop * 8
    ref = list(np.asarray(model.generate(
        jnp.asarray(np.asarray(prompt)[None], jnp.int32),
        max_new_tokens=12, max_len=len(prompt) + 12))[0, len(prompt):])
    eng = DecodeEngine(model, max_slots=1, max_len=128, speculative_k=4)
    req = eng.submit(prompt, max_new_tokens=12)
    eng.run()
    assert req.tokens == ref
    assert eng.steps < eng.tokens_emitted


def test_param_count_and_roofline_shrink():
    mha = _cfg()
    gqa = _cfg(n_kv_heads=1)
    assert gqa.num_params() < mha.num_params()
    assert gpt.GPT(gqa, seed=0).cfg.kv_heads == 1
    # actual parameter arrays match the formula
    for c in (mha, gqa, _cfg(rope=True)):
        m = gpt.GPT(c, seed=0)
        total = sum(int(v.size) for _, v in m.named_parameters())
        assert total == c.num_params(), (c, total, c.num_params())
    # MQA (kv=1) roofline: 4x less cache traffic → strictly higher bound
    r_mha = decode_roofline_tokens_per_sec(mha, 8, 1024, 819)
    r_mqa = decode_roofline_tokens_per_sec(gqa, 8, 1024, 819)
    assert r_mqa > r_mha


def test_invalid_configs_rejected():
    with pytest.raises(ValueError):
        gpt.GPT(_cfg(n_kv_heads=3), seed=0)   # 4 % 3 != 0