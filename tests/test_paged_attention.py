"""Paged (block-table) flash-decode attention + the page-pool allocator.

The serving memory model the slot-contiguous DecodeEngine cache cannot
express: pages shared across sequences, allocated on demand, freed at
retirement — memory scales with the sum of live lengths. No reference
analog (fused_multi_transformer serves one contiguous CacheKV per
sequence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.paged_attention import (
    PagedKVCache, paged_append_attend, paged_decode_attention,
    paged_decode_attention_reference)


def _pool(rs, P, hkv, page, d, dtype=jnp.float32):
    k = jnp.asarray(rs.randn(P, hkv, page, d), dtype)
    v = jnp.asarray(rs.randn(P, hkv, page, d), dtype)
    return k, v


def test_kernel_matches_gather_oracle():
    rs = np.random.RandomState(0)
    P, hkv, page, d = 12, 4, 128, 32
    b, max_pages = 3, 3
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    # rows own disjoint page lists with ragged lengths
    table = jnp.asarray([[0, 5, 2], [7, 1, 3], [9, 4, 11]], jnp.int32)
    lengths = jnp.asarray([300, 140, 17], jnp.int32)
    got = paged_decode_attention(q, k, v, table, lengths)
    want = paged_decode_attention_reference(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_kernel_stats_fold_fresh_row():
    """return_stats lets a caller fold one extra KV column analytically:
    folding the fresh row into (o, m, l) must equal re-running the
    kernel with the row already written into the pool (lengths + 1) —
    the read-only-pool decode formulation the paged engine uses."""
    rs = np.random.RandomState(3)
    P, hkv, page, d = 10, 2, 128, 32
    group = 3
    hq = hkv * group
    b, max_pages = 3, 2
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    table = jnp.asarray([[0, 5], [7, 1], [9, 4]], jnp.int32)
    lengths = jnp.asarray([130, 128, 0], jnp.int32)  # incl. page edge + empty
    k_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    v_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    o, m, l = paged_decode_attention(q, k, v, table, lengths,
                                     return_stats=True)
    qg = q.reshape(b, hkv, group, d)
    s_new = jnp.einsum("bhgd,bhd->bhg", qg, k_row).reshape(b, hq) * scale
    m2 = jnp.maximum(m, s_new)
    w_pre = l * jnp.exp(m - m2)
    w_new = jnp.exp(s_new - m2)
    v_exp = jnp.repeat(v_row, group, axis=1)
    folded = ((o * w_pre[..., None] + v_exp * w_new[..., None])
              / (w_pre + w_new)[..., None])

    # oracle: write each row at its position, re-run over lengths + 1
    k2, v2 = k, v
    for i in range(b):
        pid = int(table[i, int(lengths[i]) // page])
        off = int(lengths[i]) % page
        k2 = k2.at[pid, :, off, :].set(k_row[i])
        v2 = v2.at[pid, :, off, :].set(v_row[i])
    want = paged_decode_attention(q, k2, v2, table, lengths + 1)
    np.testing.assert_allclose(np.asarray(folded), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def _scatter_oracle(k, v, k_row, v_row, table, write_pids, lengths,
                    page):
    """The pre-fusion formulation: write each row's fresh KV at its
    position with an XLA scatter, then attend over lengths + 1."""
    k2, v2 = k, v
    for i in range(k_row.shape[0]):
        pid = int(write_pids[i])
        off = int(lengths[i]) % page
        k2 = k2.at[pid, :, off, :].set(k_row[i])
        v2 = v2.at[pid, :, off, :].set(v_row[i])
    return k2, v2


@pytest.mark.parametrize("group,cfg", [(1, None), (4, None),
                                       (1, (2, 2))])
def test_fused_append_attend_matches_scatter_then_attend(group, cfg):
    """ISSUE 6 tentpole parity: `paged_append_attend` (fresh KV row
    folded into the online softmax AND written into its pool page
    inside the kernel) must be bit-compatible with the scatter-then-
    attend formulation it replaces — both the attention output and the
    ENTIRE pool (the fused in-kernel write lands exactly one row;
    untouched pages identical). Covers page-edge lengths (write lands
    in a fresh page), an empty row (length 0), GQA, and a non-default
    (pages_per_program, head_block) geometry."""
    rs = np.random.RandomState(11)
    P, hkv, page, d = 10, 2, 128, 32
    b, max_pages = 3, 3
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, hkv * group, d), jnp.float32)
    table = jnp.asarray([[0, 5, 2], [7, 1, 3], [9, 4, 6]], jnp.int32)
    # page edge (write opens page 5), mid-page, empty row
    lengths = jnp.asarray([128, 140, 0], jnp.int32)
    k_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    v_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    wpids = jnp.asarray(
        [int(table[i, int(lengths[i]) // page]) for i in range(b)],
        jnp.int32)

    ppp, hb = cfg if cfg else (None, None)
    o, k_out, v_out = paged_append_attend(
        q, k, v, k_row, v_row, table, wpids, lengths,
        pages_per_program=ppp, head_block=hb)

    k2, v2 = _scatter_oracle(k, v, k_row, v_row, table, wpids, lengths,
                             page)
    want = paged_decode_attention(q, k2, v2, table, lengths + 1)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k2))
    np.testing.assert_array_equal(np.asarray(v_out), np.asarray(v2))


def test_fused_append_attend_jit_and_scratch_page():
    """Under jit (the engine's layer scan) with masked rows pointed at
    a scratch page: the scratch page absorbs the write, every pool page
    a live row owns stays byte-identical to the scatter oracle."""
    rs = np.random.RandomState(12)
    P, hkv, page, d = 6, 2, 128, 16
    b = 2
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, 4 * hkv, d), jnp.float32)
    table = jnp.asarray([[1, 3], [2, 4]], jnp.int32)
    lengths = jnp.asarray([130, 70], jnp.int32)
    k_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    v_row = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    scratch = P - 1                        # row 1 "inactive": write there
    wpids = jnp.asarray([3, scratch], jnp.int32)

    @jax.jit
    def f(q, k, v, k_row, v_row, table, wpids, lengths):
        return paged_append_attend(q, k, v, k_row, v_row, table, wpids,
                                   lengths)

    o, k_out, v_out = f(q, k, v, k_row, v_row, table, wpids, lengths)
    # the kernel ALWAYS folds the fresh row into the softmax (a masked
    # slot's output is discarded by the engine, but must still be
    # well-defined): the attention oracle writes each row at its TRUE
    # position; the pool oracle honors wpids (row 1's write → scratch)
    tpids = jnp.asarray(
        [int(table[i, int(lengths[i]) // page]) for i in range(b)],
        jnp.int32)
    k3, v3 = _scatter_oracle(k, v, k_row, v_row, table, tpids, lengths,
                             page)
    want = paged_decode_attention(q, k3, v3, table, lengths + 1)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    k2, v2 = _scatter_oracle(k, v, k_row, v_row, table, wpids, lengths,
                             page)
    np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k2))
    # row 1's own pages untouched (its write went to scratch)
    for pid in (2, 4):
        np.testing.assert_array_equal(np.asarray(k_out[pid]),
                                      np.asarray(k[pid]))


def test_paged_autotune_cache_roundtrip(tmp_path, monkeypatch):
    """`tune_paged_attention` measures candidates eagerly, persists the
    winner per (page, Hkv, D, dtype, group) key, and the kernels pick
    the tuned config up from the cache at trace time — every candidate
    geometry must also be numerically identical."""
    import paddle_tpu.ops.pallas.autotune as at
    from paddle_tpu.ops.pallas.paged_attention import (
        tune_paged_attention)

    monkeypatch.setattr(at, "_GLOBAL", None)
    monkeypatch.setenv("PT_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    rs = np.random.RandomState(13)
    P, hkv, page, d = 8, 4, 128, 16
    b, max_pages = 2, 2
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, hkv, d), jnp.float32)
    table = jnp.asarray([[0, 5], [7, 1]], jnp.int32)
    lengths = jnp.asarray([200, 140], jnp.int32)

    for fused in (False, True):
        cfg, timings = tune_paged_attention(
            q, k, v, table, lengths, fused=fused, iters=1,
            candidates=[(1, 1), (2, 2), (1, 4)])
        assert cfg in timings and len(timings) == 3
        # cache hit: second call measures nothing
        cfg2, timings2 = tune_paged_attention(
            q, k, v, table, lengths, fused=fused, iters=1,
            candidates=[(1, 1), (2, 2), (1, 4)])
        assert cfg2 == cfg and timings2 == {}

    # tuned config (read from the cache at trace time) == default
    want = paged_decode_attention(q, k, v, table, lengths,
                                  pages_per_program=1, head_block=1)
    got = paged_decode_attention(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_kernel_gqa_and_jit_traced_operands():
    rs = np.random.RandomState(1)
    P, hkv, page, d = 8, 2, 128, 16
    hq = 8                                   # GQA group = 4
    b, max_pages = 2, 2
    k, v = _pool(rs, P, hkv, page, d)
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    table = jnp.asarray([[3, 6], [0, 2]], jnp.int32)
    lengths = jnp.asarray([129, 256], jnp.int32)

    @jax.jit
    def f(q, k, v, table, lengths):
        return paged_decode_attention(q, k, v, table, lengths)

    got = f(q, k, v, table, lengths)
    want = paged_decode_attention_reference(q, k, v, table, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_pool_allocator_lifecycle():
    pool = PagedKVCache(n_layers=2, n_pages=6, kv_heads=2, page_size=128,
                        head_dim=8, dtype=jnp.float32)
    pool.alloc_seq("a", n_tokens=200)       # 2 pages
    pool.alloc_seq("b", n_tokens=100)       # 1 page
    assert pool.free_pages == 3
    # appending across a page boundary allocates on demand
    rows = jnp.ones((2, 2, 30, 8), jnp.float32)
    pool.lengths["b"] = 100
    pool.write_rows("b", rows, rows)
    assert pool.lengths["b"] == 130 and len(pool.tables["b"]) == 2
    pool.free_seq("a")
    assert pool.free_pages == 4              # a's 2 back; b holds 2
    # exhaustion raises; the partial allocation frees cleanly
    with pytest.raises(MemoryError):
        pool.alloc_seq("c", n_tokens=128 * 5)
    pool.free_seq("c")
    pool.free_seq("b")
    assert pool.free_pages == 6              # everything back


def test_pool_write_then_attend_matches_contiguous():
    """Write per-token rows through the allocator, attend via the paged
    kernel, compare against contiguous attention over the same rows."""
    from paddle_tpu.ops.pallas.decode_attention import (
        decode_attention_reference)

    rs = np.random.RandomState(2)
    L, hkv, page, d = 1, 2, 128, 16
    pool = PagedKVCache(n_layers=L, n_pages=5, kv_heads=hkv,
                        page_size=page, head_dim=d, dtype=jnp.float32)
    n_tok = 150                               # straddles two pages
    pool.alloc_seq("s")
    krows = rs.randn(L, hkv, n_tok, d).astype(np.float32)
    vrows = rs.randn(L, hkv, n_tok, d).astype(np.float32)
    pool.write_rows("s", jnp.asarray(krows), jnp.asarray(vrows))

    q = jnp.asarray(rs.randn(1, hkv, d), jnp.float32)
    table, lens, kp, vp = pool.gather_args(["s"], layer=0)
    got = paged_decode_attention(q, kp, vp, table, lens)

    kc = np.zeros((1, hkv, 256, d), np.float32)
    vc = np.zeros((1, hkv, 256, d), np.float32)
    kc[0, :, :n_tok] = krows[0]
    vc[0, :, :n_tok] = vrows[0]
    want = decode_attention_reference(q, jnp.asarray(kc),
                                      jnp.asarray(vc),
                                      jnp.asarray([n_tok], jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_shared_pool_two_sequences_interleaved():
    """Two sequences interleave appends into one pool; each attends only
    to its own pages."""
    rs = np.random.RandomState(3)
    hkv, page, d = 2, 128, 16
    pool = PagedKVCache(n_layers=1, n_pages=4, kv_heads=hkv,
                        page_size=page, head_dim=d, dtype=jnp.float32)
    pool.alloc_seq("x")
    pool.alloc_seq("y")
    kx = rs.randn(1, hkv, 140, d).astype(np.float32)
    ky = rs.randn(1, hkv, 40, d).astype(np.float32)
    # interleaved appends
    pool.write_rows("x", jnp.asarray(kx[:, :, :70]),
                    jnp.asarray(kx[:, :, :70]))
    pool.write_rows("y", jnp.asarray(ky), jnp.asarray(ky))
    pool.write_rows("x", jnp.asarray(kx[:, :, 70:]),
                    jnp.asarray(kx[:, :, 70:]))

    q = jnp.asarray(rs.randn(2, hkv, d), jnp.float32)
    table, lens, kp, vp = pool.gather_args(["x", "y"], layer=0)
    got = paged_decode_attention(q, kp, vp, table, lens)
    want = paged_decode_attention_reference(q, kp, vp, table, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert list(np.asarray(lens)) == [140, 40]
