"""Control-plane fault tolerance units (ISSUE 17): GuardedStore
partition semantics, RouterLink reconnect state machine,
ReplicaSession result buffering/republish, the FrontEnd request
journal, the router endpoint file, the socket KV transport, the
RouterSupervisor failover loop — plus the raw-store lint that keeps
new ``serving/``/``fleet/`` code on the guarded client.

Everything here that can run against a fake in-process store does, so
the partition tests take milliseconds instead of real retry budgets;
the handful that need the native TCPStore/P2P layer are gated on
``native.is_available()``. The real-process acceptance tests (router
SIGKILL mid-traffic, SIGSTOP partitions) live in
tests/test_router_failover.py.
"""

import json
import os
import re
import threading
import time

import pytest

from paddle_tpu import native, stats
from paddle_tpu.distributed import resilience
from paddle_tpu.fleet.controller import RouterSupervisor
from paddle_tpu.serving.router import (ReplicaSession, RouterLink,
                                       read_endpoint_file,
                                       write_endpoint_file)
from paddle_tpu.serving.scheduler import RequestJournal
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# satellite 2: no new raw store call sites in serving/ or fleet/
# ---------------------------------------------------------------------------

# Per-file baseline of raw ``store.<op>(`` call sites. Every one of
# these receives a caller-supplied store that is a
# resilience.GuardedStore at runtime (Router/ReplicaSession wrap it at
# the boundary), so the raw-looking syntax is already deadline-guarded.
# NEW sites must go through the same boundary: take a GuardedStore (or
# a ReplicaSession) from the caller instead of dialing the store
# directly. Ratcheted both ways so the numbers stay honest.
_RAW_STORE_BASELINE = {
    "paddle_tpu/serving/disagg.py": 11,
    "paddle_tpu/serving/kv_transfer.py": 7,
    "paddle_tpu/serving/router.py": 13,
}

_RAW_STORE_RE = re.compile(r"\bstore\.(get|set|add|delete_key|wait)\(")


def test_no_new_raw_store_call_sites():
    """Grep-style lint: serving/ and fleet/ may not grow raw
    ``store.get/set/add/delete_key/wait`` call sites beyond the
    baseline — route new control-plane IO through
    resilience.GuardedStore (see docs/fleet-ha.md)."""
    counts = {}
    for pkg in ("paddle_tpu/serving", "paddle_tpu/fleet"):
        root = os.path.join(REPO, pkg)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            rel = f"{pkg}/{fn}"
            with open(os.path.join(root, fn), encoding="utf-8") as f:
                n = sum(len(_RAW_STORE_RE.findall(line))
                        for line in f
                        if not line.lstrip().startswith("#"))
            if n:
                counts[rel] = n
    for rel, n in counts.items():
        base = _RAW_STORE_BASELINE.get(rel, 0)
        assert n <= base, (
            f"{rel} has {n} raw store.<op>( call sites (baseline "
            f"{base}). New control-plane IO must go through "
            f"resilience.GuardedStore — take the guarded store from "
            f"the caller (Router / ReplicaSession wrap it) instead of "
            f"calling the raw TCPStore client.")
    for rel, base in _RAW_STORE_BASELINE.items():
        n = counts.get(rel, 0)
        assert n == base, (
            f"{rel} has {n} raw store call sites but the baseline "
            f"says {base} — lower the baseline in "
            f"tests/test_fleet_ha.py so the ratchet stays tight.")


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------

class _FakeStore:
    """In-process TCPStore stand-in with the native client's error
    contract: ``get`` raises TimeoutError on an absent key, any op
    raises ConnectionError while ``fail`` is set."""

    def __init__(self):
        self.d = {}
        self.fail = False
        self.lock = threading.Lock()

    def _check(self):
        if self.fail:
            raise ConnectionError("fake store unreachable")

    def get(self, key, timeout=30.0):
        self._check()
        with self.lock:
            if key not in self.d:
                raise TimeoutError(f"get({key!r}) timed out")
            return self.d[key]

    def set(self, key, value):
        self._check()
        if isinstance(value, str):
            value = value.encode()
        with self.lock:
            self.d[key] = value

    def add(self, key, amount):
        self._check()
        with self.lock:
            cur = int(self.d.get(key, b"0"))
            cur += int(amount)
            self.d[key] = str(cur).encode()
            return cur

    def delete_key(self, key):
        self._check()
        with self.lock:
            return self.d.pop(key, None) is not None

    def wait(self, keys, timeout=30.0):
        self._check()

    def close(self):
        pass


def _guarded(fake=None, retry_s=0.3):
    fake = fake if fake is not None else _FakeStore()
    return fake, resilience.GuardedStore(fake, retry_s=retry_s)


# ---------------------------------------------------------------------------
# GuardedStore
# ---------------------------------------------------------------------------

def test_guarded_store_roundtrip_and_key_absent():
    """Normal ops pass through; a key-absent TimeoutError is a MISS,
    not a partition — it must surface immediately (TimeoutError is an
    OSError subclass, the retry filter must not eat it)."""
    _, gs = _guarded()
    gs.set("k", "v")
    assert gs.get("k") == b"v"
    assert gs.add("n", 3) == 3
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        gs.get("absent", timeout=0.01)
    assert time.monotonic() - t0 < 0.25, \
        "key-absent miss was retried against the partition budget"
    gs.close()


def test_guarded_store_retries_transient_failure():
    """A blip shorter than the retry budget is invisible to callers."""
    fake, gs = _guarded(retry_s=2.0)
    fake.fail = True

    def heal():
        time.sleep(0.15)
        fake.fail = False

    t = threading.Thread(target=heal)
    t.start()
    gs.set("k", "v")            # first attempts fail, then heals
    t.join()
    assert gs.get("k") == b"v"
    gs.close()


def test_guarded_store_partition_raises_after_budget():
    before = stats.get("resilience/store_partitions")
    fake, gs = _guarded(retry_s=0.3)
    fake.fail = True
    t0 = time.monotonic()
    with pytest.raises(resilience.StorePartitioned):
        gs.set("k", "v")
    dt = time.monotonic() - t0
    assert 0.2 < dt < 3.0
    assert stats.get("resilience/store_partitions") > before
    gs.close()


def test_guarded_store_grace_recheck_saves_suspended_op():
    """A process-wide freeze (SIGSTOP of a router hosting its OWN
    store) ages an in-flight op past its wall-clock wait while neither
    pump nor server ran; on resume the op lands within milliseconds.
    The post-deadline grace re-check must return the result instead of
    escalating to StorePartitioned — but a genuinely stuck op must
    still reach its verdict just one grace window later."""
    _, gs = _guarded(retry_s=0.3)
    slow = threading.Event()

    def lands_just_late():
        slow.wait(0.15)
        return 7

    # first wait (0.1s) expires mid-op; the 0.3s grace catches it
    assert gs._run_async(lands_just_late, wait=0.1) == 7
    # a black-holed op still partitions, grace included in the bound
    t0 = time.monotonic()
    with pytest.raises(resilience._OpStuck):
        gs._run_async(lambda: time.sleep(5.0), wait=0.1)
    assert time.monotonic() - t0 < 1.5
    gs.close()


def test_guarded_store_fault_site_drop():
    """The ``store.partition`` chaos site fires per attempt inside the
    guard — injecting ``drop`` turns any op into StorePartitioned."""
    _, gs = _guarded(retry_s=0.3)
    with faults.inject("store.partition", "drop"):
        with pytest.raises(resilience.StorePartitioned):
            gs.get("k", timeout=0.01)
        assert gs.probe("serve/router_hb") is None
    # site cleared: back to plain key-absent semantics
    with pytest.raises(TimeoutError):
        gs.get("k", timeout=0.01)
    assert gs.probe("serve/router_hb") == 0
    gs.close()


def test_guarded_store_probe_is_single_attempt():
    """probe() answers "reachable RIGHT NOW" — no retry budget."""
    fake, gs = _guarded()
    assert gs.probe("c") == 0
    gs.add("c", 5)
    assert gs.probe("c") == 5
    fake.fail = True
    t0 = time.monotonic()
    assert gs.probe("c") is None
    assert time.monotonic() - t0 < 0.5
    gs.close()


def test_guarded_store_swap_repoints_and_counts():
    before = stats.get("resilience/store_swaps")
    old, gs = _guarded()
    gs.set("k", "old")
    new = _FakeStore()
    gs.swap(new)
    gs.set("k", "new")
    assert new.d["k"] == b"new"
    assert old.d["k"] == b"old"          # old generation untouched
    assert stats.get("resilience/store_swaps") == before + 1
    gs.close()


# ---------------------------------------------------------------------------
# endpoint file + request journal
# ---------------------------------------------------------------------------

def test_endpoint_file_roundtrip_and_torn(tmp_path):
    path = str(tmp_path / "router.ep")
    assert read_endpoint_file(path) is None          # absent
    assert read_endpoint_file(None) is None
    write_endpoint_file(path, "127.0.0.1", 4242, gen=3, pid=99)
    ep = read_endpoint_file(path)
    assert ep == {"host": "127.0.0.1", "port": 4242, "gen": 3,
                  "pid": 99}
    with open(path, "w") as f:
        f.write('{"host": "127.0.')                  # torn write
    assert read_endpoint_file(path) is None


def test_request_journal_replay_skips_torn_tail(tmp_path):
    path = str(tmp_path / "requests.jsonl")
    j = RequestJournal(path)
    j.append_submit({"id": "rq-1", "prompt": [1, 2], "max_new": 4})
    j.append_submit({"id": "rq-2", "prompt": [3], "max_new": 4})
    j.append_result("rq-1", {"status": "ok", "tokens": [7, 8]})
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"kind": "submit", "id": "rq-torn", "pro')  # SIGKILL
    payloads, results = RequestJournal.replay(path)
    assert set(payloads) == {"rq-1", "rq-2"}
    assert payloads["rq-2"]["prompt"] == [3]
    assert "kind" not in payloads["rq-1"]
    assert results == {"rq-1": {"status": "ok", "tokens": [7, 8]}}
    # outstanding work = journaled submits minus journaled results
    assert [r for r in payloads if r not in results] == ["rq-2"]
    assert RequestJournal.replay(str(tmp_path / "absent.jsonl")) \
        == ({}, {})


# ---------------------------------------------------------------------------
# RouterLink state machine (fake store; reconnect needs native)
# ---------------------------------------------------------------------------

def test_router_link_partition_then_heal():
    fake, gs = _guarded()
    link = RouterLink(gs, endpoint_file=None)
    assert link.check(min_interval_s=0.0) == "ok"
    fake.fail = True
    assert link.check(min_interval_s=0.0) == "partitioned"
    assert link.partitioned
    assert link.check(min_interval_s=0.0) == "partitioned"
    fake.fail = False
    assert link.check(min_interval_s=0.0) == "healed"   # fires once
    assert link.check(min_interval_s=0.0) == "ok"
    assert not link.partitioned


def test_router_link_throttles_checks():
    fake, gs = _guarded()
    link = RouterLink(gs, endpoint_file=None)
    assert link.check(min_interval_s=10.0) == "ok"
    fake.fail = True
    # inside the throttle window: no store IO, reports cached state
    assert link.check(min_interval_s=10.0) == "ok"


@pytest.mark.skipif(not native.is_available(),
                    reason="native TCPStore unavailable")
def test_router_link_reconnects_to_new_generation(tmp_path):
    """A new endpoint-file generation makes the link dial the fresh
    store and swap it in — subsequent ops land on the successor."""
    ep_file = str(tmp_path / "router.ep")
    fake, gs = _guarded()
    link = RouterLink(gs, endpoint_file=ep_file)
    assert link.generation == 0
    successor = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        write_endpoint_file(ep_file, "127.0.0.1", successor.port,
                            gen=1)
        assert link.check(min_interval_s=0.0) == "reconnected"
        assert link.generation == 1
        link.store.set("serve/hello", "v2")
        assert successor.get("serve/hello", timeout=2.0) == b"v2"
        assert "serve/hello" not in fake.d
    finally:
        successor.close()


# ---------------------------------------------------------------------------
# ReplicaSession: buffering through partitions, republish on recovery
# ---------------------------------------------------------------------------

def _mbox_put(fake, rid, i, msg):
    fake.d[f"serve/mbox/{rid}/{i}"] = json.dumps(msg).encode()
    fake.d[f"serve/mbox_n/{rid}"] = str(i).encode()


def test_replica_session_buffers_and_republishes_on_heal():
    fake, gs = _guarded(retry_s=0.2)
    sess = ReplicaSession(gs, "rep0", {"dc": "dc0"})
    sess.announce()
    assert "serve/meta/rep0" in fake.d
    fake.fail = True
    sess.publish("rq-1", {"status": "ok", "tokens": [1]})
    assert sess.partitioned
    sess.publish("rq-2", {"status": "ok", "tokens": [2]})
    assert set(sess._pending) == {"rq-1", "rq-2"}
    assert "serve/done/rq-1" not in fake.d
    # heartbeats/mailbox degrade to no-ops while partitioned
    sess.heartbeat(load={"outstanding": 0})
    assert sess.pump_mailbox() == []
    fake.fail = False
    assert sess.maintain() == "healed"
    assert sess._pending == {}
    assert json.loads(fake.d["serve/done/rq-1"]) \
        == {"status": "ok", "tokens": [1]}
    assert json.loads(fake.d["serve/done/rq-2"]) \
        == {"status": "ok", "tokens": [2]}


def test_replica_session_answers_duplicate_replays():
    """An at-least-once router re-placing an already-served id gets
    the retained result back instead of a second decode."""
    fake, gs = _guarded()
    sess = ReplicaSession(gs, "rep0", {})
    sess.publish("rq-1", {"status": "ok", "tokens": [9]})
    before = stats.get("serve/dup_replays_answered")
    n0 = int(fake.d["serve/done_n/rep0"])
    _mbox_put(fake, "rep0", 1, {"id": "rq-1", "prompt": [1]})
    _mbox_put(fake, "rep0", 2, {"id": "rq-9", "prompt": [2]})
    msgs = sess.pump_mailbox()
    assert [m["id"] for m in msgs] == ["rq-9"]
    assert stats.get("serve/dup_replays_answered") == before + 1
    assert int(fake.d["serve/done_n/rep0"]) == n0 + 1   # re-published


def test_replica_session_partition_does_not_undrain():
    fake, gs = _guarded(retry_s=0.2)
    sess = ReplicaSession(gs, "rep0", {})
    sess.announce()
    sess.set_state("draining")
    fake.fail = True
    sess.link.note_partition()
    assert sess.lifecycle() == "draining"   # local mirror holds


@pytest.mark.skipif(not native.is_available(),
                    reason="native TCPStore unavailable")
def test_replica_session_republishes_to_new_generation(tmp_path):
    """Router failover end-to-end at the session layer: new endpoint
    generation → re-announce + mailbox cursor reset + every retained
    terminal result re-published to the successor store."""
    ep_file = str(tmp_path / "router.ep")
    fake, gs = _guarded()
    sess = ReplicaSession(gs, "rep0", {"role": "decode"},
                          endpoint_file=ep_file)
    sess.announce()
    sess.publish("rq-1", {"status": "ok", "tokens": [5]})
    _mbox_put(fake, "rep0", 1, {"id": "rq-1"})
    sess.pump_mailbox()
    assert sess.seen == 1
    successor = native.TCPStore("127.0.0.1", 0, is_master=True)
    try:
        write_endpoint_file(ep_file, "127.0.0.1", successor.port,
                            gen=1)
        assert sess.maintain() == "reconnected"
        assert sess.seen == 0
        # membership + the retained result exist on the SUCCESSOR
        assert successor.get("serve/meta/rep0", timeout=2.0)
        assert json.loads(successor.get("serve/done/rq-1",
                                        timeout=2.0)) \
            == {"status": "ok", "tokens": [5]}
    finally:
        successor.close()


# ---------------------------------------------------------------------------
# socket KV transport
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native.is_available(),
                    reason="native P2P endpoint unavailable")
def test_kv_transport_roundtrip_miss_and_eviction():
    from paddle_tpu.serving.kv_transfer import KVTransport
    a, b = KVTransport(), KVTransport()
    try:
        host, port = a.locator()
        blob = os.urandom(4096)
        a.offer("serve/kv/rq-1", {"req": "rq-1", "n": 3}, blob)
        hdr, got = b.fetch(host, port, "serve/kv/rq-1", timeout=5.0)
        assert hdr["req"] == "rq-1" and got == blob
        # absent key answers MISS → TimeoutError (same retryable
        # contract as the store path's absent-chunk timeout)
        with pytest.raises(TimeoutError):
            b.fetch(host, port, "serve/kv/nope", timeout=0.5)
        # delete withdraws the offer
        b.delete(host, port, "serve/kv/rq-1")
        deadline = time.monotonic() + 2.0
        while "serve/kv/rq-1" in a.outbox \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "serve/kv/rq-1" not in a.outbox
        # outbox is a bounded LRU: old offers evict, never grow
        for i in range(KVTransport.MAX_OUTBOX + 8):
            a.offer(f"k{i}", {}, b"x")
        assert len(a.outbox) <= KVTransport.MAX_OUTBOX
        assert "k0" not in a.outbox
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# RouterSupervisor
# ---------------------------------------------------------------------------

class _Handle:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


def test_router_supervisor_cold_respawn(tmp_path):
    spawned = []

    def spawn(token):
        spawned.append(token)
        return _Handle()

    sup = RouterSupervisor(spawn, standby=False,
                           restart_backoff_s=0.0,
                           token_dir=str(tmp_path))
    assert spawned == [None]
    assert sup.step() is False
    sup.handle.rc = 1                       # router died
    assert sup.step() is True
    assert sup.restarts == 1
    assert spawned == [None, None]          # cold successor
    assert sup.step() is False              # successor healthy
    sup.shutdown()


def test_router_supervisor_warm_standby_promotion(tmp_path):
    spawned = []

    def spawn(token):
        spawned.append(token)
        return _Handle()

    sup = RouterSupervisor(spawn, standby=True,
                           restart_backoff_s=0.0,
                           token_dir=str(tmp_path))
    assert sup.step() is False              # arms the standby
    assert spawned[1] is not None and not os.path.exists(spawned[1])
    standby_handle = sup._standby[0]
    sup.handle.rc = 1
    assert sup.step() is True
    assert os.path.exists(spawned[1])       # promotion token written
    assert sup.handle is standby_handle
    sup.step()                              # re-arms a fresh standby
    assert sup._standby is not None
    sup.shutdown()


def test_router_supervisor_refuses_crash_loop(tmp_path):
    def spawn(token):
        h = _Handle()
        h.rc = 1                            # dies instantly
        return h

    sup = RouterSupervisor(spawn, standby=False,
                           restart_backoff_s=0.0, max_restarts=2,
                           token_dir=str(tmp_path))
    assert sup.step() is True
    assert sup.step() is True
    with pytest.raises(RuntimeError, match="crash loop"):
        sup.step()


def test_router_supervisor_backoff_blocks_rapid_restart(tmp_path):
    def spawn(token):
        h = _Handle()
        h.rc = 1
        return h

    sup = RouterSupervisor(spawn, standby=False,
                           restart_backoff_s=30.0,
                           token_dir=str(tmp_path))
    assert sup.step(now=100.0) is True
    assert sup.step(now=100.1) is False     # inside backoff window
    assert sup.step(now=131.0) is True
