"""Detection ops vs oracles (ref test pattern: test_roi_pool_op.py,
test_matrix_nms_op.py, test_deform_conv2d.py — deform conv with zero
offsets must equal plain conv; matrix-NMS decay checked on a constructed
overlap case)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.vision import ops as V


def test_roi_pool_and_psroi_pool_shapes_and_max():
    x = jnp.asarray(np.random.RandomState(0).rand(1, 8, 16, 16), jnp.float32)
    boxes = jnp.asarray([[0., 0., 8., 8.], [4., 4., 12., 12.]])
    rp = V.roi_pool(x, boxes, None, 2)
    assert rp.shape == (2, 8, 2, 2)
    # max-pool property: every pooled value appears in the source window
    assert float(jnp.max(rp)) <= float(jnp.max(x)) + 1e-6
    ps = V.psroi_pool(x, boxes, None, 2)  # 8 ch / 4 bins = 2 out channels
    assert ps.shape == (2, 2, 2, 2)


def test_matrix_nms_decays_overlaps_only():
    bb = jnp.asarray([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     jnp.float32)
    sc = jnp.asarray([[[0.9, 0.8, 0.7]]], jnp.float32)
    out, idx, n = V.matrix_nms(bb, sc, score_threshold=0.1, keep_top_k=3)
    vals = sorted(np.asarray(out[:, 1]))
    assert abs(vals[-1] - 0.9) < 1e-5      # top box undecayed
    assert abs(vals[-2] - 0.7) < 1e-3      # non-overlapping box untouched
    assert vals[0] < 0.45                  # overlapping box decayed hard
    # gaussian mode also monotone
    outg, _, _ = V.matrix_nms(bb, sc, score_threshold=0.1, keep_top_k=3,
                              use_gaussian=True)
    gv = sorted(np.asarray(outg[:, 1]))
    assert gv[0] < 0.8


def test_deform_conv_zero_offset_equals_conv():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(2, 2, 8, 8), jnp.float32)
    w = jnp.asarray(rs.rand(3, 2, 3, 3), jnp.float32)
    off = jnp.zeros((2, 18, 6, 6), jnp.float32)
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(V.deform_conv2d(x, off, w), ref, atol=1e-4)
    # DCNv2 mask of ones is a no-op; non-zero offsets change the output
    m1 = jnp.ones((2, 9, 6, 6), jnp.float32)
    np.testing.assert_allclose(V.deform_conv2d(x, off, w, mask=m1), ref,
                               atol=1e-4)
    off2 = jnp.full((2, 18, 6, 6), 0.5, jnp.float32)
    assert not np.allclose(V.deform_conv2d(x, off2, w), ref)


def test_deform_conv_layer_and_grads():
    layer = V.DeformConv2D(2, 3, 3)
    x = jnp.asarray(np.random.RandomState(2).rand(1, 2, 8, 8), jnp.float32)
    off = jnp.zeros((1, 18, 6, 6), jnp.float32)
    out = layer(x, off)
    assert out.shape == (1, 3, 6, 6)
    params, _ = layer.split_params()

    def loss(p):
        return jnp.sum(layer.merge_params(p)(x, off) ** 2)

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())
    assert float(jnp.sum(jnp.abs(g["weight"]))) > 0


def test_prior_box_counts_and_range():
    pb, pv = V.prior_box(jnp.zeros((1, 3, 4, 4)), jnp.zeros((1, 3, 32, 32)),
                         min_sizes=[8.0], aspect_ratios=[1.0, 2.0],
                         flip=True, clip=True)
    # ars: 1, 2, 1/2 → 3 anchors per cell
    assert pb.shape == (4, 4, 3, 4) and pv.shape == pb.shape
    assert float(jnp.min(pb)) >= 0.0 and float(jnp.max(pb)) <= 1.0


def test_generate_proposals_filters_and_clips():
    anchors = jnp.asarray([[0, 0, 10, 10], [5, 5, 15, 15],
                           [18, 18, 19, 19]], jnp.float32)
    scores = jnp.asarray([[[[0.9]], [[0.8]], [[0.99]]]], jnp.float32)
    deltas = jnp.zeros((1, 12, 1, 1), jnp.float32)
    boxes, scr, n = V.generate_proposals(
        scores, deltas, jnp.asarray([20., 20.]), anchors,
        jnp.ones((3, 4)), min_size=2.0)
    # the tiny 1x1 anchor is filtered despite its top score
    assert float(jnp.max(boxes)) <= 20.0
    kept = np.asarray(scr)
    assert 0.99 not in np.round(kept, 2)


def test_psroi_pool_channel_major_layout():
    """review r3: input channel (k*ph + i)*pw + j → (out k, bin (i,j))."""
    x = np.zeros((1, 8, 8, 8), np.float32)
    x[0, 1] = 1.0  # channel 1 = k0, bin (0, 1) under channel-major layout
    out = V.psroi_pool(jnp.asarray(x), jnp.asarray([[0., 0., 8., 8.]]),
                       None, 2)
    o = np.asarray(out[0])  # (co=2, 2, 2)
    assert o[0, 0, 1] == 1.0
    assert o.sum() == 1.0


def test_generate_proposals_spatial_layout():
    """review r3: deltas (1, 4A, H, W) must map channel k to component k
    of the SAME spatial anchor."""
    h = w = 2
    anchors = np.zeros((h, w, 1, 4), np.float32)
    for i in range(h):
        for j in range(w):
            anchors[i, j, 0] = [j * 10, i * 10, j * 10 + 4, i * 10 + 4]
    deltas = np.zeros((1, 4, h, w), np.float32)
    deltas[0, 1, 1, 0] = 0.5  # dy of the anchor at spatial (1, 0)
    scores = np.full((1, 1, h, w), 0.5, np.float32)
    boxes, scr, n = V.generate_proposals(
        jnp.asarray(scores), jnp.asarray(deltas), jnp.asarray([40., 40.]),
        jnp.asarray(anchors), jnp.ones((h * w, 4), np.float32),
        min_size=0.0, nms_thresh=0.99)
    got = np.asarray(boxes)
    base = anchors.reshape(-1, 4)
    # exactly one box moved, and it is the (1,0) anchor, moved in +y
    moved = np.abs(got - base).sum(1) > 1e-4
    assert moved.sum() == 1
    k = int(np.nonzero(moved)[0][0])
    assert np.allclose(base[k], [0, 10, 4, 14])      # spatial (1,0) anchor
    assert got[k][1] > base[k][1] and abs(got[k][0] - base[k][0]) < 1e-4


def test_matrix_nms_excludes_background():
    bb = jnp.asarray([[[0, 0, 10, 10], [20, 20, 30, 30]]], jnp.float32)
    sc = jnp.asarray([[[0.99, 0.98],     # class 0 = background
                       [0.5, 0.4]]], jnp.float32)
    out, _, _ = V.matrix_nms(bb, sc, score_threshold=0.1,
                             background_label=0, keep_top_k=4)
    kept = np.asarray(out)
    kept = kept[kept[:, 1] > 0]
    assert (kept[:, 0] == 1).all()       # only foreground class returned


def test_roi_ops_batched_via_boxes_num():
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.rand(2, 4, 8, 8), jnp.float32)
    boxes = jnp.asarray([[0., 0., 8., 8.], [0., 0., 8., 8.]])
    # same box on both images must pool DIFFERENT features
    out = V.roi_align(x, boxes, jnp.asarray([1, 1]), 2)
    assert not np.allclose(out[0], out[1])
    outp = V.roi_pool(x, boxes, jnp.asarray([1, 1]), 2)
    assert not np.allclose(outp[0], outp[1])
    with pytest.raises(ValueError):
        V.roi_align(x, boxes, None, 2)



def test_roi_pool_exact_max_large_bins():
    """review r3: a peak anywhere in a large bin must be found (the
    4x4-sample approximation missed even coordinates)."""
    x = np.zeros((1, 1, 16, 16), np.float32)
    x[0, 0, 2, 2] = 5.0
    out = V.roi_pool(jnp.asarray(x), jnp.asarray([[0., 0., 16., 16.]]),
                     None, 2)
    assert float(out[0, 0, 0, 0]) == 5.0


def test_prior_box_reference_order():
    """review r3: per-cell anchor order is part of the SSD contract."""
    feat = jnp.zeros((1, 3, 1, 1))
    img = jnp.zeros((1, 3, 32, 32))
    pb, _ = V.prior_box(feat, img, min_sizes=[8.0, 16.0],
                        max_sizes=[16.0, 32.0], aspect_ratios=[1.0, 2.0],
                        min_max_aspect_ratios_order=True)
    w = (np.asarray(pb)[0, 0, :, 2] - np.asarray(pb)[0, 0, :, 0]) * 32
    # per min_size: [min(ar1), max, ar2] → widths 8, sqrt(128), 8*sqrt2,
    #                                      16, sqrt(512), 16*sqrt2
    expect = [8, np.sqrt(8 * 16), 8 * np.sqrt(2),
              16, np.sqrt(16 * 32), 16 * np.sqrt(2)]
    np.testing.assert_allclose(w, expect, rtol=1e-4)


def test_generate_proposals_pixel_offset():
    anchors = jnp.asarray([[0, 0, 1, 1]], jnp.float32)  # 1x1 box
    scores = jnp.asarray([[[[0.9]]]], jnp.float32)
    deltas = jnp.zeros((1, 4, 1, 1), jnp.float32)
    # w = 1 without offset (< min_size 2) but 2 with pixel_offset
    _, _, n0 = V.generate_proposals(scores, deltas, jnp.asarray([20., 20.]),
                                    anchors, jnp.ones((1, 4)), min_size=2.0)
    _, _, n1 = V.generate_proposals(scores, deltas, jnp.asarray([20., 20.]),
                                    anchors, jnp.ones((1, 4)), min_size=2.0,
                                    pixel_offset=True)
    assert int(n0[0]) == 0 and int(n1[0]) == 1


def test_roi_pool_overlapping_bins():
    """review r3: reference bins overlap (floor/ceil) — a peak on the
    shared boundary row must appear in BOTH bins."""
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 2, 2] = 5.0  # ROI rows 0..4, oh=2 → bin0 [0,3), bin1 [2,5)
    out = V.roi_pool(jnp.asarray(x), jnp.asarray([[0., 0., 5., 5.]]),
                     None, 2)
    o = np.asarray(out[0, 0])
    assert o[0, 0] == 5.0 and o[1, 0] == 5.0


def test_prior_box_flip_interleaved():
    feat = jnp.zeros((1, 3, 1, 1))
    img = jnp.zeros((1, 3, 32, 32))
    pb, _ = V.prior_box(feat, img, min_sizes=[8.0],
                        aspect_ratios=[1.0, 2.0, 3.0], flip=True)
    w = (np.asarray(pb)[0, 0, :, 2] - np.asarray(pb)[0, 0, :, 0]) * 32
    # order: ar1, ar2, ar1/2, ar3, ar1/3 (each ratio then its reciprocal)
    expect = [8, 8 * np.sqrt(2), 8 / np.sqrt(2),
              8 * np.sqrt(3), 8 / np.sqrt(3)]
    np.testing.assert_allclose(w, expect, rtol=1e-4)


# ---------------------------------------------------------------------------
# Round-4 parity additions: yolo_loss, image IO, layer wrappers
# (ref: vision/ops.py yolo_loss:52, read_file/decode_jpeg, RoIAlign:1310)
# ---------------------------------------------------------------------------

def test_yolo_loss_trains_and_assigns():
    from paddle_tpu.vision import ops as V
    n, s, cn, h, w = 2, 3, 4, 8, 8
    anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
    mask = [0, 1, 2]
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(n, s * (5 + cn), h, w) * 0.1, jnp.float32)
    gt = jnp.asarray([[[0.5, 0.5, 0.1, 0.15], [0.2, 0.3, 0.05, 0.08]],
                     [[0.7, 0.4, 0.12, 0.1], [0, 0, 0, 0]]], jnp.float32)
    gl = jnp.asarray([[1, 2], [3, 0]], jnp.int32)
    loss = V.yolo_loss(x, gt, gl, anchors, mask, cn, 0.7, 32)
    assert loss.shape == (n,) and np.isfinite(np.asarray(loss)).all()

    def f(xx):
        return jnp.sum(V.yolo_loss(xx, gt, gl, anchors, mask, cn, 0.7, 32))

    g = jax.grad(f)
    xx, l0 = x, float(f(x))
    for _ in range(60):
        xx = xx - 0.1 * g(xx)
    assert float(f(xx)) < l0 * 0.5
    # a gt whose best anchor is OFF this scale contributes no positives:
    # huge box → best anchor 5 (59x119), not in mask [0,1,2]
    big = jnp.asarray([[[0.5, 0.5, 0.9, 0.9]]] * n, jnp.float32)
    l_big = V.yolo_loss(x, big, gl[:, :1], anchors, mask, cn, 0.7, 32)
    l_none = V.yolo_loss(x, jnp.zeros((n, 1, 4)), gl[:, :1], anchors,
                         mask, cn, 0.7, 32)
    # only objectness-ignore handling may differ slightly
    np.testing.assert_allclose(np.asarray(l_big), np.asarray(l_none),
                               rtol=0.05)


def test_read_file_decode_jpeg_roundtrip(tmp_path):
    from PIL import Image
    from paddle_tpu.vision import ops as V
    rs = np.random.RandomState(0)
    img = (rs.rand(16, 12, 3) * 255).astype(np.uint8)
    p = tmp_path / "t.jpg"
    Image.fromarray(img).save(p, quality=95)
    raw = V.read_file(str(p))
    assert raw.dtype == jnp.uint8 and raw.ndim == 1
    dec = V.decode_jpeg(raw, mode="rgb")
    assert dec.shape == (3, 16, 12)
    assert abs(float(jnp.mean(dec.astype(jnp.float32))) - img.mean()) < 10
    gray = V.decode_jpeg(raw, mode="gray")
    assert gray.shape == (1, 16, 12)


def test_roi_layer_wrappers_match_functionals():
    from paddle_tpu.vision import ops as V
    rs = np.random.RandomState(0)
    feat = jnp.asarray(rs.rand(1, 4, 16, 16), jnp.float32)
    boxes = jnp.asarray([[2, 2, 10, 10]], jnp.float32)
    num = jnp.asarray([1], jnp.int32)
    for layer, fn in ((V.RoIAlign(2), V.roi_align),
                      (V.RoIPool(2), V.roi_pool),
                      (V.PSRoIPool(2), V.psroi_pool)):
        got = layer(feat, boxes, num)
        want = fn(feat, boxes, num, 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_conv_norm_activation_block():
    import paddle_tpu.nn as nn
    from paddle_tpu.vision import ops as V
    blk = V.ConvNormActivation(3, 8, kernel_size=3).tag_paths()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 3, 8, 8), jnp.float32)
    with nn.stateful(training=False):
        y = blk(x)
    assert y.shape == (2, 8, 8, 8)
    assert (np.asarray(y) >= 0).all()  # ReLU default
    no_norm = V.ConvNormActivation(3, 8, norm_layer=None,
                                   activation_layer=None)
    with nn.stateful(training=False):
        y2 = no_norm(x)
    assert y2.shape == (2, 8, 8, 8)
