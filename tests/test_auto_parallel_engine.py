"""One-call auto-parallel Engine (VERDICT r4 item 6)
≙ python/paddle/distributed/auto_parallel/engine.py:58 (_plan:618,
_parallel:646, fit:749): plan → mesh → shard → compile → train in a
single ``Engine(module, ...).fit(loader)`` call."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import Engine
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.models import gpt
from paddle_tpu import optimizer as optim


def _gpt_mini():
    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _batches(n, b=8, s=16, vocab=512, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (b, s)).astype(np.int32)
            for _ in range(n)]


def test_engine_fits_gpt_with_planner_chosen_plan(mesh8):
    """The headline: GPT-mini trains through Engine in ONE call, on a
    planner-searched mesh, with sharded params and decreasing loss."""
    model = _gpt_mini()
    eng = Engine(model, optimizer=optim.AdamW(learning_rate=1e-3),
                 hbm_bytes=1e15)
    hist = eng.fit(_batches(6), epochs=2)
    # planner ran and covered all 8 devices
    assert eng.degrees is not None
    world = 1
    for v in eng.degrees.values():
        world *= v
    assert world == 8
    assert len(hist["loss"]) == 12
    assert hist["loss"][-1] < hist["loss"][0]
    assert all(np.isfinite(l) for l in hist["loss"])


def test_engine_respects_pinned_strategy(mesh8):
    """Explicit hybrid degrees skip the search (semi-auto mode, ref
    engine.py's user-annotated path)."""
    strat = DistributedStrategy()
    strat.hybrid_configs["dp_degree"] = 2
    strat.hybrid_configs["mp_degree"] = 4
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 strategy=strat)
    eng.fit(_batches(2), epochs=1)
    assert eng.degrees["dp"] == 2 and eng.degrees["tp"] == 4
    # tp actually applied: the qkv weight must be placed sharded
    wqkv = eng._params["blocks.item_0.wqkv"]
    assert not wqkv.sharding.is_fully_replicated


def test_engine_partial_pin_fills_dp(mesh8):
    """Code-review regression: a lone mp_degree=4 on 8 devices must fill
    dp=2 (fleet.init residual semantics), not crash in init_mesh."""
    strat = DistributedStrategy()
    strat.hybrid_configs["mp_degree"] = 4
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 strategy=strat)
    eng.fit(_batches(1), epochs=1)
    assert eng.degrees == {"dp": 2, "tp": 4, "pp": 1, "fsdp": 1}


def test_engine_evaluate(mesh8):
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 hbm_bytes=1e15)
    eng.fit(_batches(3), epochs=1)
    val = eng.evaluate(_batches(2, seed=7))
    assert np.isfinite(val)


def test_engine_rejects_pp_plan(mesh8):
    """Code-review regression: Engine must refuse a pp plan rather than
    silently replicate blocks across the pp axis (voiding the planner's
    1/pp memory credit)."""
    strat = DistributedStrategy()
    strat.hybrid_configs["pp_degree"] = 2
    strat.hybrid_configs["dp_degree"] = 4
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 strategy=strat)
    with pytest.raises(NotImplementedError):
        eng.fit(_batches(1), epochs=1)


def test_engine_small_batch_placement(mesh8):
    """Code-review regression: batch 4 under dp=4 x fsdp=2 must fall back
    to partial placement (4 % (4*2) != 0), not crash in device_put."""
    strat = DistributedStrategy()
    strat.hybrid_configs["dp_degree"] = 4
    strat.hybrid_configs["sharding_degree"] = 2
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 strategy=strat)
    hist = eng.fit(_batches(2, b=4), epochs=1)
    assert all(np.isfinite(l) for l in hist["loss"])


def test_engine_evaluate_counts_every_batch(mesh8):
    """Code-review regression: evaluate() on a one-shot generator must
    include the prepare() batch in the mean."""
    eng = Engine(_gpt_mini(), optimizer=optim.AdamW(learning_rate=1e-3),
                 hbm_bytes=1e15)
    seen = []

    def gen():
        for b in _batches(3, seed=5):
            seen.append(1)
            yield b

    val = eng.evaluate(gen())
    assert np.isfinite(val) and len(seen) == 3
    assert np.isnan(eng.evaluate([]))


def test_mesh_pp_axis_is_outermost():
    """Code-review regression: the built mesh must place pp outermost so
    the planner's DCN-tier assumption (pp spans hosts, dp/fsdp/tp stay
    within) matches reality on a host-major device list."""
    from paddle_tpu.distributed import mesh as mesh_lib
    topo = mesh_lib.init_mesh(pp=2, dp=2, tp=2, set_global=False)
    arr = np.asarray(topo.mesh.devices)
    ids = np.vectorize(lambda d: d.id)(arr)
    # pp slice 0 = first half of the device list (one "host"), slice 1 =
    # second half — contiguous host-major blocks
    pp_axis = topo.mesh.axis_names.index("pp")
    first = np.take(ids, 0, axis=pp_axis).ravel()
    second = np.take(ids, 1, axis=pp_axis).ravel()
    assert sorted(first) == [0, 1, 2, 3]
    assert sorted(second) == [4, 5, 6, 7]


def test_cost_model_device_kind_strict():
    from paddle_tpu.cost_model import CostModel, _PEAKS
    assert CostModel(device_kind="v5p").peak_flops == _PEAKS["v5p"][0]
    assert CostModel(device_kind="TPU v5 lite").peak_flops == \
        _PEAKS["v5"][0]
    with pytest.raises(ValueError):
        CostModel(device_kind="h100")


def test_engine_requires_loss_for_unknown_module():
    from paddle_tpu import nn

    class Tiny(nn.Module):
        def __init__(self):
            super().__init__()
            self.w = nn.Parameter(jnp.ones((4, 4)))

        def forward(self, x):
            return x @ self.w

    with pytest.raises(ValueError):
        Engine(Tiny())