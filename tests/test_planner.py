"""Auto-parallel planner v0 (VERDICT r2 item 6): structural completion
must reproduce the hand-written GPT and BERT PARTITION_RULES.

Reference analog: unittests/auto_parallel/test_completion* — the GPT
completer test asserts propagated dist attrs equal the annotated plan."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.distributed.planner import (plan_module, memory_report,
                                            suggest_mesh)
from paddle_tpu.models import gpt, bert


def _norm(spec, ndim):
    """Canonical per-dim tuple form padded to ndim (P() == P(None, None))."""
    entries = list(tuple(spec)) + [None] * (ndim - len(tuple(spec)))
    out = []
    for e in entries:
        axes = e if isinstance(e, tuple) else (e,)
        out.append(tuple(a for a in axes if a is not None))
    return tuple(out)


def _assert_plan_matches(model, rule_spec_fn):
    plan = plan_module(model)
    mismatches = []
    for name, v in model.named_parameters():
        want = _norm(rule_spec_fn(name), v.ndim)
        got = _norm(plan[name], v.ndim)
        if want != got:
            mismatches.append(f"{name} {v.shape}: want {want} got {got}")
    assert not mismatches, "\n".join(mismatches)


def test_planner_reproduces_gpt_rules():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_gpt_moe_rules():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, moe_experts=2, moe_every=2,
                        dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_gpt_untied_head():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=1, n_heads=4, tie_embeddings=False,
                        dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_bert_rules():
    cfg = bert.BertConfig(vocab_size=2048, d_model=64, n_layers=2,
                          n_heads=4, max_position=64)
    model = bert.BertForPretraining(cfg, seed=0)

    def rule(p):
        for pat, s in bert.PARTITION_RULES:
            if re.search(pat, p):
                return s
        return jax.sharding.PartitionSpec()

    _assert_plan_matches(model, rule)


def test_auto_shard_module_trains(mesh8):
    """shard_module(auto=True) end-to-end: params actually placed sharded
    and a train step runs."""
    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    sharded = dist.shard_module(model, auto=True)
    wqkv = dict(sharded.named_parameters())["blocks.item_0.wqkv"]
    assert not wqkv.sharding.is_fully_replicated
    from paddle_tpu import optimizer as optim
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = gpt.init_train_state(sharded, opt, mesh8.mesh)
    step = gpt.build_train_step(sharded, opt, mesh8.mesh)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 512, (8, 16)), jnp.int32)
    _, _, loss = step(params, opt_state, tokens, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_planner_divisibility_pruning(mesh8):
    """Axes that do not divide the mapped dim are dropped when a mesh is
    supplied (tp=2 cannot shard a dim of 5)."""
    from paddle_tpu import nn

    class Odd(nn.Module):
        def __init__(self):
            super().__init__()
            self.w = nn.Parameter(jnp.zeros((6, 5)))
            self.b = nn.Parameter(jnp.zeros((5,)))

        def forward(self, x):
            return x @ self.w + self.b

    class Outer(nn.Module):
        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList([Odd(), Odd()])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x

    plan = plan_module(Outer(), mesh=mesh8.mesh)
    spec = _norm(plan["blocks.item_0.w"], 2)
    assert "tp" not in spec[1]  # 5 % 2 != 0 → tp pruned


def test_memory_report_and_suggest_mesh():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    rep = memory_report(model)
    n = rep["n_params"]
    assert n == cfg.num_params()
    # fp32 params + 2 fp32 adam moments = 12 bytes/param
    assert rep["total_bytes"] == pytest.approx(12 * n, rel=0.01)

    deg = suggest_mesh(model, n_devices=8,
                       hbm_bytes=rep["total_bytes"] / 2, budget=0.5)
    assert deg["dp"] * deg["fsdp"] * deg["tp"] == 8
    # memory pressure must trigger sharding, preferring fsdp
    assert deg["fsdp"] >= 4
    big = suggest_mesh(model, n_devices=8, hbm_bytes=1e15)
    assert big == {"dp": 8, "fsdp": 1, "tp": 1}


# ---------------------------------------------------------------------------
# Plan search (VERDICT r3 item 3): enumerate → cost-rank → (optionally)
# measure. Reference analog: tuner/parallel_tuner.py:35 +
# tuner/optimization_tuner.py:188 trial runs.
# ---------------------------------------------------------------------------

def test_enumerate_plans_covers_factorizations():
    from paddle_tpu.distributed.planner import enumerate_plans
    plans = enumerate_plans(8)
    assert all(d["dp"] * d["fsdp"] * d["tp"] == 8 for d in plans)
    # tp in {1,2,4,8} leaves 8/tp for fsdp: 4+3+2+1 assignments
    assert len(plans) == 10
    assert {"dp": 8, "fsdp": 1, "tp": 1} in plans
    assert {"dp": 1, "fsdp": 1, "tp": 8} in plans


def test_rank_plans_orders_by_cost_and_feasibility():
    from paddle_tpu.distributed.planner import rank_plans
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    ranked = rank_plans(model, 8, hbm_bytes=1e15)
    costs = [c for c, _, i in ranked if i["feasible"]]
    assert costs == sorted(costs)
    # with no memory pressure the comm-free pure-dp plan must win
    assert ranked[0][1] == {"dp": 8, "fsdp": 1, "tp": 1}
    # every plan carries the cost-model breakdown the tuner would log
    for _, _, info in ranked:
        assert {"time_s", "comm_bytes", "per_device_bytes",
                "feasible"} <= set(info)

    # under memory pressure infeasible plans sink below feasible ones
    rep = memory_report(model)
    tight = rank_plans(model, 8, hbm_bytes=rep["total_bytes"] / 2,
                       budget=0.5)
    feas = [i["feasible"] for _, _, i in tight]
    assert feas.index(False) >= 1 and all(
        not f for f in feas[feas.index(False):])


def test_suggest_mesh_uses_compute_term():
    """flops_per_step only shifts absolute cost, not the argmin ordering of
    comm — but it must be reflected in plan_cost's compute_s."""
    from paddle_tpu.distributed.planner import plan_cost
    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    a = plan_cost(model, {"dp": 8, "fsdp": 1, "tp": 1},
                  flops_per_step=1e12)
    b = plan_cost(model, {"dp": 8, "fsdp": 1, "tp": 1})
    assert a["compute_s"] > 0 and b["compute_s"] == 0
    assert a["time_s"] > b["time_s"]


def test_enumerate_plans_with_pp():
    from paddle_tpu.distributed.planner import enumerate_plans
    plans = enumerate_plans(8, max_pp=4)
    pp_plans = [p for p in plans if p.get("pp", 1) > 1]
    assert pp_plans, "max_pp>1 must emit pipeline plans"
    assert all(
        p["dp"] * p["fsdp"] * p["tp"] * p.get("pp", 1) == 8 for p in plans)
    assert {"dp": 2, "fsdp": 1, "tp": 2, "pp": 2} in plans
    # default stays pp-free (backward compatible)
    assert all("pp" not in p for p in enumerate_plans(8))


def test_pp_bubble_and_memory_terms():
    """pp inflates compute by (m+pp-1)/m and deflates block memory by pp
    (≙ estimate_cost.py's pipeline terms)."""
    from paddle_tpu.distributed.planner import plan_cost
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=4, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    flat = plan_cost(model, {"dp": 8, "fsdp": 1, "tp": 1},
                     flops_per_step=1e12)
    pipe = plan_cost(model, {"dp": 4, "fsdp": 1, "tp": 1, "pp": 2},
                     flops_per_step=1e12, microbatches=8)
    assert pipe["bubble_frac"] == pytest.approx((8 + 2 - 1) / 8 - 1)
    assert pipe["compute_s"] > flat["compute_s"]  # bubble-inflated
    assert pipe["pp_p2p_bytes"] > 0
    # block weights split across stages → lower static floor than pure dp
    assert pipe["per_device_bytes"] < flat["per_device_bytes"]


def test_planner_picks_pp_for_cross_host():
    """Phase-A reproduction at the cost-model level: on 2 hosts with the
    model too big for one host's worth of pure-dp replication, the search
    must put pp on the cross-host (DCN) axis — boundary activations are
    orders of magnitude lighter than cross-host gradient all-reduce
    (≙ comm_op_cost.py cross-machine links; dryrun phase A's hand-picked
    pp=2 mesh)."""
    from paddle_tpu.cost_model import CostModel
    from paddle_tpu.distributed.planner import suggest_mesh
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=256, d_model=256,
                        n_layers=8, n_heads=8, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    # plan for a real v5 chip (the test runs on CPU whose 1e11 peak would
    # distort every compute-vs-comm trade)
    cm = CostModel(device_kind="v5")
    flops = 6 * cfg.num_params() * 2048  # true step FLOPs at 2048 tok
    deg = suggest_mesh(model, n_devices=8, hbm_bytes=1e15,
                       flops_per_step=flops, max_pp=4, n_hosts=2,
                       tokens_per_step=2048, cost_model=cm)
    assert deg.get("pp", 1) >= 2, deg
    # sanity: single-host AND compute-bound (large batch), the bubble
    # outweighs any comm saving — no pipeline
    big_tok = 65536
    one = suggest_mesh(model, n_devices=8, hbm_bytes=1e15,
                       flops_per_step=6 * cfg.num_params() * big_tok,
                       max_pp=4, n_hosts=1, tokens_per_step=big_tok,
                       cost_model=cm)
    assert one.get("pp", 1) == 1, one


def test_measured_search_beats_heuristic(mesh8):
    """Trial-run re-ranking: the searched plan's MEASURED step time must
    not lose to the memory-only heuristic's choice (tuner's promise)."""
    import time as _time
    from paddle_tpu.distributed.planner import suggest_mesh, rank_plans
    from paddle_tpu import optimizer as optim

    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    measured = {}

    def measure(degrees):
        topo = mesh_lib.init_mesh(**degrees, set_global=False)
        params, opt_state = gpt.init_train_state(model, opt, topo.mesh)
        step = gpt.build_train_step(model, opt, topo.mesh)
        tokens = jnp.asarray(np.random.RandomState(0).randint(
            0, 512, (8, 16)), jnp.int32)
        key = jax.random.PRNGKey(0)
        p, o, loss = step(params, opt_state, tokens, key)  # compile
        float(loss)
        t0 = _time.perf_counter()
        for _ in range(3):
            p, o, loss = step(p, o, tokens, key)
        float(loss)
        dt = (_time.perf_counter() - t0) / 3
        measured[tuple(sorted(degrees.items()))] = dt
        return dt

    chosen = suggest_mesh(model, 8, hbm_bytes=1e15, measure_fn=measure)
    # the memory-only heuristic (pre-search behavior): first plan that fits
    heuristic = {"dp": 8, "fsdp": 1, "tp": 1}
    t_heur = measured.get(tuple(sorted(heuristic.items())))
    if t_heur is None:
        t_heur = measure(heuristic)
    t_chosen = measured[tuple(sorted(chosen.items()))]
    assert t_chosen <= t_heur * 1.05, (chosen, t_chosen, t_heur)
