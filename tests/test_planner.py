"""Auto-parallel planner v0 (VERDICT r2 item 6): structural completion
must reproduce the hand-written GPT and BERT PARTITION_RULES.

Reference analog: unittests/auto_parallel/test_completion* — the GPT
completer test asserts propagated dist attrs equal the annotated plan."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.distributed.planner import (plan_module, memory_report,
                                            suggest_mesh)
from paddle_tpu.models import gpt, bert


def _norm(spec, ndim):
    """Canonical per-dim tuple form padded to ndim (P() == P(None, None))."""
    entries = list(tuple(spec)) + [None] * (ndim - len(tuple(spec)))
    out = []
    for e in entries:
        axes = e if isinstance(e, tuple) else (e,)
        out.append(tuple(a for a in axes if a is not None))
    return tuple(out)


def _assert_plan_matches(model, rule_spec_fn):
    plan = plan_module(model)
    mismatches = []
    for name, v in model.named_parameters():
        want = _norm(rule_spec_fn(name), v.ndim)
        got = _norm(plan[name], v.ndim)
        if want != got:
            mismatches.append(f"{name} {v.shape}: want {want} got {got}")
    assert not mismatches, "\n".join(mismatches)


def test_planner_reproduces_gpt_rules():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_gpt_moe_rules():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, moe_experts=2, moe_every=2,
                        dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_gpt_untied_head():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=1, n_heads=4, tie_embeddings=False,
                        dtype=jnp.float32)
    _assert_plan_matches(gpt.GPT(cfg, seed=0), gpt.partition_spec)


def test_planner_reproduces_bert_rules():
    cfg = bert.BertConfig(vocab_size=2048, d_model=64, n_layers=2,
                          n_heads=4, max_position=64)
    model = bert.BertForPretraining(cfg, seed=0)

    def rule(p):
        for pat, s in bert.PARTITION_RULES:
            if re.search(pat, p):
                return s
        return jax.sharding.PartitionSpec()

    _assert_plan_matches(model, rule)


def test_auto_shard_module_trains(mesh8):
    """shard_module(auto=True) end-to-end: params actually placed sharded
    and a train step runs."""
    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    sharded = dist.shard_module(model, auto=True)
    wqkv = dict(sharded.named_parameters())["blocks.item_0.wqkv"]
    assert not wqkv.sharding.is_fully_replicated
    from paddle_tpu import optimizer as optim
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = gpt.init_train_state(sharded, opt, mesh8.mesh)
    step = gpt.build_train_step(sharded, opt, mesh8.mesh)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 512, (8, 16)), jnp.int32)
    _, _, loss = step(params, opt_state, tokens, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_planner_divisibility_pruning(mesh8):
    """Axes that do not divide the mapped dim are dropped when a mesh is
    supplied (tp=2 cannot shard a dim of 5)."""
    from paddle_tpu import nn

    class Odd(nn.Module):
        def __init__(self):
            super().__init__()
            self.w = nn.Parameter(jnp.zeros((6, 5)))
            self.b = nn.Parameter(jnp.zeros((5,)))

        def forward(self, x):
            return x @ self.w + self.b

    class Outer(nn.Module):
        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList([Odd(), Odd()])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x

    plan = plan_module(Outer(), mesh=mesh8.mesh)
    spec = _norm(plan["blocks.item_0.w"], 2)
    assert "tp" not in spec[1]  # 5 % 2 != 0 → tp pruned


def test_memory_report_and_suggest_mesh():
    cfg = gpt.GPTConfig(vocab_size=2048, max_seq_len=64, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    rep = memory_report(model)
    n = rep["n_params"]
    assert n == cfg.num_params()
    # fp32 params + 2 fp32 adam moments = 12 bytes/param
    assert rep["total_bytes"] == pytest.approx(12 * n, rel=0.01)

    deg = suggest_mesh(model, n_devices=8,
                       hbm_bytes=rep["total_bytes"] / 2, budget=0.5)
    assert deg["dp"] * deg["fsdp"] * deg["tp"] == 8
    # memory pressure must trigger sharding, preferring fsdp
    assert deg["fsdp"] >= 4
    big = suggest_mesh(model, n_devices=8, hbm_bytes=1e15)
    assert big == {"dp": 8, "fsdp": 1, "tp": 1}
