"""Flash-decode Pallas kernel vs the naive masked-softmax oracle
(decode half of fused_multi_transformer_op.cu; SURVEY §4 OpTest style —
kernel output compared elementwise against an independent reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.decode_attention import (
    decode_attention, decode_attention_reference)


def _mk(b, hq, hkv, T, d, dtype, seed=0):
    rs = np.random.RandomState(seed)
    q = rs.randn(b, hq, d).astype(np.float32)
    k = rs.randn(b, hkv, T, d).astype(np.float32)
    v = rs.randn(b, hkv, T, d).astype(np.float32)
    return (jnp.asarray(q, dtype), jnp.asarray(k, dtype),
            jnp.asarray(v, dtype))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matches_oracle_ragged_lengths(dtype):
    b, hq, T, d = 4, 4, 256, 64
    q, k, v = _mk(b, hq, hq, T, d, dtype)
    lengths = jnp.asarray([1, 17, 128, 256], jnp.int32)
    got = decode_attention(q, k, v, lengths, block_k=128)
    want = decode_attention_reference(q, k, v, lengths)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=tol,
                               rtol=tol)


def test_gqa_grouping():
    # 8 query heads over 2 KV heads: query head h must read kv head h // 4
    b, hq, hkv, T, d = 2, 8, 2, 128, 32
    q, k, v = _mk(b, hq, hkv, T, d, jnp.float32)
    lengths = jnp.asarray([77, 128], jnp.int32)
    got = decode_attention(q, k, v, lengths, block_k=128)
    want = decode_attention_reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_block_shrinks_to_divide_cache():
    # T=384 is not divisible by the default 512 block; the kernel must
    # shrink to a dividing lane-multiple block, not crash or pad the cache
    b, h, T, d = 2, 2, 384, 64
    q, k, v = _mk(b, h, h, T, d, jnp.float32)
    lengths = jnp.asarray([5, 384], jnp.int32)
    got = decode_attention(q, k, v, lengths)
    want = decode_attention_reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_zero_length_row_is_finite():
    # an empty slot (length 0, the free-slot case in the decode engine)
    # must produce zeros, not NaN
    b, h, T, d = 2, 2, 128, 32
    q, k, v = _mk(b, h, h, T, d, jnp.float32)
    lengths = jnp.asarray([0, 128], jnp.int32)
    got = np.asarray(decode_attention(q, k, v, lengths))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got[0], 0.0, atol=0)


def test_generate_kernel_path_matches_einsum_path():
    """With a 128-multiple cache, GPT decode routes through the Pallas
    kernel (gpt.GPTBlock.forward_cached L==1 branch); greedy tokens must
    match the einsum path bit-for-bit disabled via the flag."""
    from paddle_tpu import flags
    from paddle_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    with_kernel = np.asarray(
        model.generate(tokens, max_new_tokens=6, max_len=128))
    flags.set_flags({"use_pallas_kernels": False})
    try:
        gpt._GEN_CACHE.pop(model, None)  # force a re-trace on the flag flip
        without = np.asarray(
            model.generate(tokens, max_new_tokens=6, max_len=128))
    finally:
        flags.set_flags({"use_pallas_kernels": True})
    np.testing.assert_array_equal(with_kernel, without)


def test_jit_and_traced_lengths():
    # lengths arrive traced inside the engine's jitted step
    b, h, T, d = 2, 4, 128, 32
    q, k, v = _mk(b, h, h, T, d, jnp.float32)

    @jax.jit
    def f(q, k, v, lengths):
        return decode_attention(q, k, v, lengths)

    lengths = jnp.asarray([3, 100], jnp.int32)
    got = f(q, k, v, lengths)
    want = decode_attention_reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_return_stats_fold_extra_column():
    """(o, m, l) stats let a caller fold an extra KV column into the
    softmax analytically — must equal attention over the extended
    cache. This is the decode engine's kernel route for long caches."""
    rs = np.random.RandomState(7)
    b, h, T, d = 2, 4, 128, 32
    q = jnp.asarray(rs.randn(b, h, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, T, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, T, d), jnp.float32)
    lengths = jnp.asarray([5, 90], jnp.int32)
    k_new = jnp.asarray(rs.randn(b, h, d), jnp.float32)
    v_new = jnp.asarray(rs.randn(b, h, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    o, m, l = decode_attention(q, k, v, lengths, return_stats=True)
    s_new = jnp.einsum("bhd,bhd->bh", q, k_new) * scale
    m2 = jnp.maximum(m, s_new)
    w_pre = l * jnp.exp(m - m2)
    w_new = jnp.exp(s_new - m2)
    got = (o * w_pre[..., None] + v_new * w_new[..., None]) \
        / (w_pre + w_new)[..., None]

    # oracle: extend the cache by one column at each row's position
    def put(c, new, pos):
        return jax.lax.dynamic_update_slice(c, new[:, None], (0, pos, 0))
    k2 = jax.vmap(put)(k, k_new, lengths)
    v2 = jax.vmap(put)(v, v_new, lengths)
    want = decode_attention_reference(q, k2, v2, lengths + 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_decode_rows_kernel_route_matches_einsum():
    """GPTBlock.decode_rows: kernel route (long caches) == dense einsum
    route, ragged lengths, GQA and MHA."""
    from paddle_tpu import flags
    from paddle_tpu.models import gpt

    for kvh in (4, 2):
        cfg = gpt.GPTConfig(vocab_size=64, max_seq_len=256, d_model=128,
                            n_layers=1, n_heads=4, n_kv_heads=kvh,
                            dtype=jnp.float32)
        model = gpt.GPT(cfg, seed=0)
        blk = model.blocks[0]
        rs = np.random.RandomState(3)
        b, T = 2, 256
        x = jnp.asarray(rs.randn(b, 1, cfg.d_model), jnp.float32)
        kc = jnp.asarray(rs.randn(b, kvh, T, cfg.head_dim), jnp.float32)
        vc = jnp.asarray(rs.randn(b, kvh, T, cfg.head_dim), jnp.float32)
        pos = jnp.asarray([7, 201], jnp.int32)

        flags.set_flags({"decode_kernel_min_t": 128})
        try:
            y_k, krow_k, vrow_k = blk.decode_rows(x, (kc, vc), pos)
        finally:
            flags.set_flags({"decode_kernel_min_t": 1024})
        y_e, krow_e, vrow_e = blk.decode_rows(x, (kc, vc), pos)
        np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_e),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_array_equal(np.asarray(krow_k),
                                      np.asarray(krow_e))
