"""Serving-side degradation (ISSUE 2 tentpole, part 4): per-request
deadlines and the non-finite-logit guard must evict ONLY the affected
request — batch peers keep decoding and produce exactly the tokens an
undisturbed run produces."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import stats
from paddle_tpu.inference.decode_engine import DecodeEngine
from paddle_tpu.inference.paged_engine import PagedDecodeEngine
from paddle_tpu.models import gpt
from paddle_tpu.testing import faults

pytestmark = pytest.mark.faults


def _model(max_seq=256):
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=max_seq, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _reference_tokens(model, prompt, n_new):
    toks = jnp.asarray(np.asarray(prompt, np.int32)[None])
    out = model.generate(toks, max_new_tokens=n_new,
                         max_len=len(prompt) + n_new)
    return list(np.asarray(out)[0, len(prompt):])


@pytest.mark.parametrize("engine_cls", ["contiguous", "paged"])
def test_expired_deadline_evicts_only_that_request(engine_cls):
    model = _model()
    if engine_cls == "contiguous":
        eng = DecodeEngine(model, max_slots=4, max_len=128)
    else:
        eng = PagedDecodeEngine(model, n_pages=16, max_slots=4)
    stats.reset("serve/")
    rs = np.random.RandomState(0)
    p_ok = list(rs.randint(0, 96, size=5))
    p_dead = list(rs.randint(0, 96, size=5))
    r_ok = eng.submit(p_ok, max_new_tokens=6)
    r_dead = eng.submit(p_dead, max_new_tokens=6, deadline_s=0.0)
    eng.run()
    assert r_dead.done and r_dead.failed
    assert "deadline" in r_dead.error
    assert r_dead.tokens == []
    assert r_ok.done and not r_ok.failed
    assert r_ok.tokens == _reference_tokens(model, p_ok, 6)
    # expired while still in the admission queue: the queue-reject
    # counter, not the mid-decode eviction counter (ISSUE 10)
    assert stats.get("serve/queue_deadline_rejects") == 1
    assert stats.get("serve/deadline_evictions") == 0


def test_live_request_deadline_evicts_mid_flight():
    """A request whose deadline passes AFTER admission is evicted on
    the next step; its slot frees for waiting work."""
    model = _model()
    eng = DecodeEngine(model, max_slots=1, max_len=128)
    rs = np.random.RandomState(1)
    r1 = eng.submit(list(rs.randint(0, 96, size=4)), max_new_tokens=50,
                    deadline_s=1e-4)
    r2 = eng.submit(list(rs.randint(0, 96, size=4)), max_new_tokens=3)
    eng.step()            # admits r1 (deadline checked at NEXT entry)
    import time
    time.sleep(0.01)
    eng.run()
    assert r1.failed and "deadline" in r1.error
    assert r2.done and not r2.failed and len(r2.tokens) == 3


@pytest.mark.parametrize("chunk", [1, 4])
def test_poisoned_logits_evict_only_poisoned_request(chunk):
    model = _model()
    rs = np.random.RandomState(2)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=7))
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=2, max_len=128,
                       steps_per_call=chunk)
    r0 = eng.submit(p0, max_new_tokens=6)
    r1 = eng.submit(p1, max_new_tokens=6)
    eng.step()            # both admitted + first decode dispatch, clean
    with faults.inject("engine.poison_logits", "nan", slot=1, count=1):
        eng.step()        # slot 1's logits go NaN this dispatch
    eng.run()
    assert r1.failed and r1.error == "non-finite logits"
    assert r0.done and not r0.failed
    assert r0.tokens == _reference_tokens(model, p0, 6)
    assert stats.get("serve/nonfinite_evictions") == 1
    # the poisoned request emitted nothing from the bad dispatch on
    assert len(r1.tokens) < 6


def test_poisoned_logits_paged_engine():
    model = _model()
    rs = np.random.RandomState(3)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=6))
    stats.reset("serve/")
    eng = PagedDecodeEngine(model, n_pages=16, max_slots=2)
    r0 = eng.submit(p0, max_new_tokens=6)
    r1 = eng.submit(p1, max_new_tokens=6)
    free_before = None
    eng.step()
    free_before = eng.free_pages
    with faults.inject("engine.poison_logits", "nan", slot=1, count=1):
        eng.step()
    eng.drain()   # pipelined: the poisoned dispatch lands at harvest
    assert r1.failed and r1.error == "non-finite logits"
    eng.run()
    assert r0.done and not r0.failed
    assert r0.tokens == _reference_tokens(model, p0, 6)
    assert stats.get("serve/nonfinite_evictions") == 1
    # the evicted request's pages went back to the pool
    assert eng.free_pages > free_before


def test_poisoned_logits_speculative_path():
    model = _model()
    rs = np.random.RandomState(4)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=5))
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=2, max_len=128, speculative_k=3)
    r0 = eng.submit(p0, max_new_tokens=6)
    r1 = eng.submit(p1, max_new_tokens=6)
    eng.step()
    with faults.inject("engine.poison_logits", "nan", slot=1, count=1):
        eng.step()
    eng.run()
    assert r1.failed and r1.error == "non-finite logits"
    assert r0.done and not r0.failed
    assert r0.tokens == _reference_tokens(model, p0, 6)


# -- ISSUE 4: degradation under the pipelined (depth >= 2) runtime -----------

@pytest.mark.parametrize("chunk,spec_k", [(1, 0), (4, 0), (2, 3)],
                         ids=["plain", "chunked", "speculative"])
def test_midpipeline_poison_eviction_matches_depth1(chunk, spec_k):
    """PT_FAULTS-style nan poison landing while dispatches are in
    flight: the poisoned request is evicted at harvest, the survivor's
    stream is BYTE-identical to the synchronous depth=1 engine's."""
    model = _model()
    rs = np.random.RandomState(7)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=7))

    def run(depth):
        stats.reset("serve/")
        # no faults.clear() needed between depths: inject() resets the
        # per-site call index on entry
        eng = DecodeEngine(model, max_slots=2, max_len=160,
                           steps_per_call=chunk, speculative_k=spec_k,
                           inflight=depth)
        r0 = eng.submit(p0, max_new_tokens=8)
        r1 = eng.submit(p1, max_new_tokens=8)
        eng.step()
        with faults.inject("engine.poison_logits", "nan", slot=1,
                           count=1):
            eng.step()
        eng.run()
        assert r1.failed and r1.error == "non-finite logits"
        assert not r0.failed
        assert stats.get("serve/nonfinite_evictions") == 1
        return list(r0.tokens)

    base = run(1)
    assert base == _reference_tokens(model, p0, 8)
    for depth in (2, 3):
        assert run(depth) == base, f"depth {depth} survivor diverged"


def test_midpipeline_deadline_eviction_drains_first():
    """A live request expiring while dispatches are in flight: the
    pipeline drains (in-flight tokens applied), the expired request is
    evicted alone, and the surviving peer still matches the
    reference."""
    import time
    model = _model()
    rs = np.random.RandomState(8)
    p_ok = list(rs.randint(0, 96, size=5))
    p_dead = list(rs.randint(0, 96, size=5))
    eng = DecodeEngine(model, max_slots=2, max_len=128, inflight=3)
    r_ok = eng.submit(p_ok, max_new_tokens=20)
    # a budget far beyond what fits in the deadline window, so the
    # request can never finish before the sweep evicts it
    r_dead = eng.submit(p_dead, max_new_tokens=100, deadline_s=0.02)
    eng.step()
    eng.step()          # pipeline holds in-flight dispatches now
    time.sleep(0.03)
    eng.run()
    assert r_dead.failed and "deadline" in r_dead.error
    assert len(eng._pending) == 0
    assert r_ok.done and not r_ok.failed
    assert r_ok.tokens == _reference_tokens(model, p_ok, 20)


def test_pt_faults_env_nan_poison_pipelined(monkeypatch):
    """The PT_FAULTS env route (subprocess contract) composes with the
    pipeline: a nan rule installed from the environment evicts exactly
    one request at harvest; peers serve the reference stream."""
    model = _model()
    rs = np.random.RandomState(11)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=6))
    monkeypatch.setenv("PT_FAULTS",
                       "engine.poison_logits:nan:slot=1,after=1,count=1")
    faults.clear()
    assert faults.install_from_env() == 1
    try:
        stats.reset("serve/")
        eng = DecodeEngine(model, max_slots=2, max_len=128, inflight=2)
        r0 = eng.submit(p0, max_new_tokens=6)
        r1 = eng.submit(p1, max_new_tokens=6)
        eng.run()
        assert r1.failed and r1.error == "non-finite logits"
        assert stats.get("serve/nonfinite_evictions") == 1
        assert r0.done and not r0.failed
        assert r0.tokens == _reference_tokens(model, p0, 6)
    finally:
        faults.clear()


def test_deadline_eviction_mid_admission_abandons_prefill():
    """A request evicted while its chunked prefill is still dispatching
    (interleaved admission) must be abandoned cleanly: no tokens, its
    open prefill job dropped, and the slot re-admits the next request
    which serves exactly."""
    import time
    model = _model()
    rs = np.random.RandomState(10)
    long_p = list(rs.randint(0, 96, size=120))   # 8 chunks of 16
    nxt_p = list(rs.randint(0, 96, size=6))
    eng = DecodeEngine(model, max_slots=1, max_len=160, buckets=(16,),
                       prefill_tokens=16, inflight=2)
    r_dead = eng.submit(long_p, max_new_tokens=5, deadline_s=0.01)
    r_ok = eng.submit(nxt_p, max_new_tokens=5)
    eng.step()          # admission opens; one chunk dispatched
    time.sleep(0.02)
    eng.run()
    assert r_dead.failed and "deadline" in r_dead.error
    assert r_dead.tokens == []
    assert not eng._admitting
    assert r_ok.done and not r_ok.failed
    assert r_ok.tokens == _reference_tokens(model, nxt_p, 5)


def test_paged_pipelined_poison_and_parity():
    """Paged-engine parity under the pipeline: depth 3 serves the same
    streams as depth 1, and a poisoned request's pages return to the
    pool at harvest without disturbing peers."""
    model = _model()
    rs = np.random.RandomState(9)
    p0 = list(rs.randint(0, 96, size=5))
    p1 = list(rs.randint(0, 96, size=6))

    def run(depth):
        stats.reset("serve/")
        # no faults.clear() needed between depths: inject() resets the
        # per-site call index on entry
        eng = PagedDecodeEngine(model, n_pages=16, max_slots=2,
                                steps_per_call=2, inflight=depth)
        r0 = eng.submit(p0, max_new_tokens=8)
        r1 = eng.submit(p1, max_new_tokens=8)
        eng.step()
        with faults.inject("engine.poison_logits", "nan", slot=1,
                           count=1):
            eng.step()
        eng.run()
        assert r1.failed and r1.error == "non-finite logits"
        assert eng.free_pages == 16   # every page back in the pool
        return list(r0.tokens)

    base = run(1)
    assert base == _reference_tokens(model, p0, 8)
    assert run(3) == base


def test_clean_run_unaffected_by_guards():
    """With no faults and no deadlines the guards must be inert: exact
    parity with gpt.generate, zero degradation counters."""
    model = _model()
    stats.reset("serve/")
    eng = DecodeEngine(model, max_slots=2, max_len=128)
    rs = np.random.RandomState(5)
    prompts = [list(rs.randint(0, 96, size=n)) for n in (3, 8)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run()
    for req, p in zip(reqs, prompts):
        assert not req.failed
        assert req.tokens == _reference_tokens(model, p, 5)
    assert stats.get("serve/deadline_evictions") == 0
    assert stats.get("serve/nonfinite_evictions") == 0
