"""Lazy-graph static Program/Executor (VERDICT r1/r2 weak: static was an
API shell). The canonical ported reference program — static.data +
static.nn.fc + append_backward + minimize + exe.run(feed, fetch_list) —
must construct, train, and fetch grads (ref fluid/framework.py:5220,
backward.py:1726, executor.py:1378)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu.static as static
from paddle_tpu import optimizer as optim


def _linreg_data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(13, 1).astype(np.float32)
    x = rs.randn(n, 13).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def test_static_linear_regression_trains():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 13])
        y = static.data("y", [-1, 1])
        pred = static.nn.fc(x, 1)
        loss = static.call(jnp.mean, (pred - y) ** 2)
        static.minimize(optim.SGD(learning_rate=0.05), loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    xs, ys = _linreg_data()
    losses = []
    for _ in range(60):
        (lv,) = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_default_program_and_guard():
    base = static.default_main_program()
    prog = static.Program()
    with static.program_guard(prog):
        assert static.default_main_program() is prog
        v = static.data("a", [2, 2])
        assert v.program is prog
    assert static.default_main_program() is base


def test_append_backward_grad_fetch():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 3])
        pred = static.nn.fc(x, 1, name="head")
        loss = static.call(jnp.mean, pred ** 2)
        grads = static.append_backward(loss)
    assert any(g[1].endswith("@GRAD") for g in grads)
    exe = static.Executor()
    xs = np.ones((4, 3), np.float32)
    wname = [n for n in prog.params if n.endswith(".w")][0]
    lv, gw = exe.run(prog, feed={"x": xs},
                     fetch_list=[loss, f"{wname}@GRAD"])
    assert gw.shape == prog.params[wname].shape
    # analytic check: d/dw mean((xw+b)^2) = 2*mean(pred*x) per column
    w = np.asarray(prog.params[wname])
    b = np.asarray(prog.params[wname.replace(".w", ".b")])
    pred = xs @ w + b
    expect = 2 * (xs * pred).mean(axis=0, keepdims=True).T
    np.testing.assert_allclose(gw, expect, rtol=1e-5, atol=1e-6)


def test_variable_arithmetic_and_apply():
    prog = static.Program()
    with static.program_guard(prog):
        a = static.data("a", [2, 2])
        b = static.data("b", [2, 2])
        c = (2.0 * a + b / 2 - 1.0) @ b
        d = c.apply(jnp.tanh)
    exe = static.Executor()
    av = np.ones((2, 2), np.float32)
    bv = np.full((2, 2), 2.0, np.float32)
    (out,) = exe.run(prog, feed={"a": av, "b": bv}, fetch_list=[d])
    expect = np.tanh((2 * av + bv / 2 - 1) @ bv)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_clone_shares_scope():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 4])
        pred = static.nn.fc(x, 2)
    test_prog = prog.clone(for_test=True)
    assert test_prog.params is prog.params
    exe = static.Executor()
    xs = np.ones((3, 4), np.float32)
    (a,) = exe.run(prog, feed={"x": xs}, fetch_list=[pred])
    (b,) = exe.run(test_prog, feed={"x": xs},
                   fetch_list=[test_prog.vars[pred.name]])
    np.testing.assert_allclose(a, b)


def test_executor_recompiles_on_new_shapes():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 2])
        out = x * 3.0
    exe = static.Executor()
    (a,) = exe.run(prog, feed={"x": np.ones((2, 2), np.float32)},
                   fetch_list=[out])
    (b,) = exe.run(prog, feed={"x": np.ones((5, 2), np.float32)},
                   fetch_list=[out])
    assert a.shape == (2, 2) and b.shape == (5, 2)


# ---------------------------------------------------------------------------
# static.nn breadth (VERDICT r3 item 9): conv2d/pool2d/embedding/
# batch_norm/dropout/cross_entropy on the lazy Program, and the
# recognize-digits "book" script end-to-end
# (≙ fluid/tests/book/test_recognize_digits.py).
# ---------------------------------------------------------------------------

def _digits(n=256, seed=0):
    """Synthetic 4-class 'digits': class k lights rows 2k..2k+1."""
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 4, (n,)).astype(np.int64)
    x = rs.randn(n, 1, 8, 8).astype(np.float32) * 0.25
    for i, cls in enumerate(y):
        x[i, 0, 2 * cls:2 * cls + 2, :] += 1.5
    return x, y


def test_static_nn_layer_shapes():
    prog = static.Program()
    with static.program_guard(prog):
        img = static.data("img", [-1, 1, 8, 8])
        ids = static.data("ids", [-1, 3], dtype=np.int32)
        conv = static.nn.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, activation="relu")
        pooled = static.nn.pool2d(conv, pool_size=2, pool_type="max")
        bn = static.nn.batch_norm(pooled)
        drop = static.nn.dropout(bn, dropout_prob=0.3)
        emb = static.nn.embedding(ids, size=(16, 5))
    exe = static.Executor()
    x = np.random.RandomState(0).rand(2, 1, 8, 8).astype(np.float32)
    i = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
    c, p, b, d, e = exe.run(prog, feed={"img": x, "ids": i},
                            fetch_list=[conv, pooled, bn, drop, emb])
    assert c.shape == (2, 4, 8, 8) and (c >= 0).all()
    assert p.shape == (2, 4, 4, 4)
    assert b.shape == (2, 4, 4, 4)
    assert d.shape == (2, 4, 4, 4)
    assert e.shape == (2, 3, 5)


def test_static_batch_norm_train_vs_test_modes():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 2, 4, 4])
        out = static.nn.batch_norm(x, momentum=0.5)
    test_prog = prog.clone(for_test=True)
    exe = static.Executor()
    rs = np.random.RandomState(0)
    xv = (rs.randn(8, 2, 4, 4) * 3 + 1).astype(np.float32)

    # training run: output uses batch stats (≈ zero mean), buffers move
    (tr,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    assert abs(tr.mean()) < 0.1
    mean_name = [k for k in prog.buffers if k.endswith(".mean") or
                 "mean" in k][0]
    assert np.abs(np.asarray(prog.buffers[mean_name])).max() > 0

    # eval run (cloned program): running stats, buffers frozen
    before = {k: np.asarray(v) for k, v in prog.buffers.items()}
    (ev,) = exe.run(test_prog, feed={"x": xv},
                    fetch_list=[test_prog.vars[out.name]])
    for k in before:
        np.testing.assert_array_equal(before[k],
                                      np.asarray(prog.buffers[k]))
    assert not np.allclose(tr, ev)  # different normalization stats


def test_static_dropout_modes():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 64])
        out = static.nn.dropout(x, dropout_prob=0.5)
    test_prog = prog.clone(for_test=True)
    exe = static.Executor()
    xv = np.ones((4, 64), np.float32)
    (tr,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    assert (tr == 0).mean() > 0.2          # ~half dropped
    assert abs(tr.mean() - 1.0) < 0.35     # upscale_in_train
    (ev,) = exe.run(test_prog, feed={"x": xv},
                    fetch_list=[test_prog.vars[out.name]])
    np.testing.assert_array_equal(ev, xv)  # identity in eval


def test_book_recognize_digits_convnet_trains():
    """The book script: conv→pool→bn→conv→pool→fc(softmax), cross_entropy
    loss, SGD minimize, Executor.run epochs → accuracy, then eval through
    clone(for_test=True) (≙ fluid/tests/book/test_recognize_digits.py
    conv_net path)."""
    from paddle_tpu import optimizer as optim

    prog = static.Program()
    with static.program_guard(prog):
        img = static.data("img", [-1, 1, 8, 8])
        label = static.data("label", [-1, 1], dtype=np.int64)
        conv1 = static.nn.conv2d(img, num_filters=8, filter_size=3,
                                 padding=1, activation="relu")
        pool1 = static.nn.pool2d(conv1, pool_size=2, pool_type="max")
        bn = static.nn.batch_norm(pool1)
        conv2 = static.nn.conv2d(bn, num_filters=8, filter_size=3,
                                 padding=1, activation="relu")
        pool2 = static.nn.pool2d(conv2, pool_size=2, pool_type="avg")
        flat = static.nn.flatten(pool2)
        pred = static.nn.fc(flat, size=4, activation="softmax")
        ce = static.nn.cross_entropy(pred, label)
        loss = ce.apply(lambda v: v.mean())
    test_prog = prog.clone(for_test=True)
    static.minimize(optim.Momentum(learning_rate=0.1, momentum=0.9), loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    x, y = _digits(256)
    losses = []
    for epoch in range(25):
        (lv,) = exe.run(prog, feed={"img": x, "label": y.reshape(-1, 1)},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses[::6]

    xt, yt = _digits(128, seed=1)
    (probs,) = exe.run(test_prog, feed={"img": xt},
                       fetch_list=[test_prog.vars[pred.name]])
    acc = (probs.argmax(-1) == yt).mean()
    assert acc > 0.9, acc
