"""Lazy-graph static Program/Executor (VERDICT r1/r2 weak: static was an
API shell). The canonical ported reference program — static.data +
static.nn.fc + append_backward + minimize + exe.run(feed, fetch_list) —
must construct, train, and fetch grads (ref fluid/framework.py:5220,
backward.py:1726, executor.py:1378)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu.static as static
from paddle_tpu import optimizer as optim


def _linreg_data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(13, 1).astype(np.float32)
    x = rs.randn(n, 13).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def test_static_linear_regression_trains():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 13])
        y = static.data("y", [-1, 1])
        pred = static.nn.fc(x, 1)
        loss = static.call(jnp.mean, (pred - y) ** 2)
        static.minimize(optim.SGD(learning_rate=0.05), loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    xs, ys = _linreg_data()
    losses = []
    for _ in range(60):
        (lv,) = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_default_program_and_guard():
    base = static.default_main_program()
    prog = static.Program()
    with static.program_guard(prog):
        assert static.default_main_program() is prog
        v = static.data("a", [2, 2])
        assert v.program is prog
    assert static.default_main_program() is base


def test_append_backward_grad_fetch():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 3])
        pred = static.nn.fc(x, 1, name="head")
        loss = static.call(jnp.mean, pred ** 2)
        grads = static.append_backward(loss)
    assert any(g[1].endswith("@GRAD") for g in grads)
    exe = static.Executor()
    xs = np.ones((4, 3), np.float32)
    wname = [n for n in prog.params if n.endswith(".w")][0]
    lv, gw = exe.run(prog, feed={"x": xs},
                     fetch_list=[loss, f"{wname}@GRAD"])
    assert gw.shape == prog.params[wname].shape
    # analytic check: d/dw mean((xw+b)^2) = 2*mean(pred*x) per column
    w = np.asarray(prog.params[wname])
    b = np.asarray(prog.params[wname.replace(".w", ".b")])
    pred = xs @ w + b
    expect = 2 * (xs * pred).mean(axis=0, keepdims=True).T
    np.testing.assert_allclose(gw, expect, rtol=1e-5, atol=1e-6)


def test_variable_arithmetic_and_apply():
    prog = static.Program()
    with static.program_guard(prog):
        a = static.data("a", [2, 2])
        b = static.data("b", [2, 2])
        c = (2.0 * a + b / 2 - 1.0) @ b
        d = c.apply(jnp.tanh)
    exe = static.Executor()
    av = np.ones((2, 2), np.float32)
    bv = np.full((2, 2), 2.0, np.float32)
    (out,) = exe.run(prog, feed={"a": av, "b": bv}, fetch_list=[d])
    expect = np.tanh((2 * av + bv / 2 - 1) @ bv)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_clone_shares_scope():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 4])
        pred = static.nn.fc(x, 2)
    test_prog = prog.clone(for_test=True)
    assert test_prog.params is prog.params
    exe = static.Executor()
    xs = np.ones((3, 4), np.float32)
    (a,) = exe.run(prog, feed={"x": xs}, fetch_list=[pred])
    (b,) = exe.run(test_prog, feed={"x": xs},
                   fetch_list=[test_prog.vars[pred.name]])
    np.testing.assert_allclose(a, b)


def test_executor_recompiles_on_new_shapes():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 2])
        out = x * 3.0
    exe = static.Executor()
    (a,) = exe.run(prog, feed={"x": np.ones((2, 2), np.float32)},
                   fetch_list=[out])
    (b,) = exe.run(prog, feed={"x": np.ones((5, 2), np.float32)},
                   fetch_list=[out])
    assert a.shape == (2, 2) and b.shape == (5, 2)
