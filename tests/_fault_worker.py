"""Spawned workers for fault-injection p2p tests (ISSUE 2 satellite:
recv timeout rollback regression)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def recv_timeout_worker(rank, port, tmpdir):
    """Rank 0: a recv that times out (nothing sent yet) must roll its
    sequence claim back and bump p2p/recv_timeouts exactly once; the
    two messages rank 1 then sends must arrive IN ORDER on the retried
    recvs (a leaked claim would make recv wait on seq 2/3 while the
    sender used 1/2 — permanent desync)."""
    from paddle_tpu import stats
    from paddle_tpu.distributed import p2p

    p2p.init_p2p(rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        try:
            p2p.recv(src=1, timeout=0.5)
            raise AssertionError("recv should have timed out")
        except TimeoutError:
            pass
        assert stats.get("p2p/recv_timeouts") == 1, \
            stats.snapshot("p2p/")
        # barrier: releases rank 1 to send only after the timeout
        p2p.all_gather_object([], {"r": rank})
        first = p2p.recv(src=1, timeout=30.0)
        second = p2p.recv(src=1, timeout=30.0)
        np.testing.assert_array_equal(first, np.arange(3))
        np.testing.assert_array_equal(second, np.arange(3) * 10)
        assert stats.get("p2p/recv_timeouts") == 1  # exactly once
    else:
        p2p.all_gather_object([], {"r": rank})
        p2p.send(np.arange(3), dst=0)
        p2p.send(np.arange(3) * 10, dst=0)
    p2p.destroy_process_group()
    open(os.path.join(tmpdir, f"ok{rank}"), "w").close()
