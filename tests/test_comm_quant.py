"""Block-scaled quantized collectives (ISSUE 7): the wire must carry
int8/fp8 END TO END (no hidden int32/fp32 upcast — asserted on the
jaxpr), the byte counters must show the real volume cut, convergence
must stay at parity with fp32 sync under error feedback, the stage-3
quantized weight gather must sit inside the block-scaling tolerance of
the fp32 gather, and a bitflipped block scale must fail loudly on every
rank (mirroring the PR 6 ``paged.shared_page`` pattern)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu import flags as pt_flags
from paddle_tpu import optimizer as optim
from paddle_tpu import stats
from paddle_tpu.distributed import compression as C
from paddle_tpu.distributed import planner
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.testing import faults


@pytest.fixture
def dp_mesh():
    topo = dist.init_mesh(dp=8)
    yield topo
    from paddle_tpu.distributed import mesh as mesh_lib
    mesh_lib.set_topology(None)


@pytest.fixture
def fsdp_mesh():
    topo = dist.init_mesh(fsdp=4, dp=2)
    yield topo
    from paddle_tpu.distributed import mesh as mesh_lib
    mesh_lib.set_topology(None)


def _problem(seed=0, din=8, dout=4):
    rs = np.random.RandomState(seed)
    w_true = rs.randn(din, dout).astype(np.float32)
    x = rs.randn(64, din).astype(np.float32)
    y = x @ w_true + 0.01 * rs.randn(64, dout).astype(np.float32)
    params = {"w": jnp.zeros((din, dout), jnp.float32)}

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    return params, loss_fn, (jnp.asarray(x), jnp.asarray(y))


# -- the wire codec ----------------------------------------------------------

@pytest.mark.parametrize("method,rel", [("int8", 0.5 / 127 + 1e-6),
                                        ("fp8", 1.0 / 16 + 1e-3)])
def test_roundtrip_bound_per_block(method, rel):
    """Quant→dequant error of each BLOCK is bounded by its own amax times
    the format's half-step (int8: 1/254; fp8-e4m3: 3 mantissa bits →
    2^-4) — the block scaling property per-tensor scaling lacks."""
    rs = np.random.RandomState(1)
    # mixed magnitudes per block: per-tensor scaling would lose the
    # small blocks entirely
    v = jnp.asarray((rs.randn(16, 256) *
                     (10.0 ** rs.randint(-3, 2, (16, 1)))
                     ).astype(np.float32))
    payload, scales, n = C.quantize_blocks(v, method, 256)
    assert payload.dtype == (jnp.int8 if method == "int8"
                             else jnp.float8_e4m3fn)
    deq = C.dequantize_blocks(payload, scales, n, v.shape)
    err = jnp.abs(deq - v).reshape(16, 256).max(axis=1)
    amax = jnp.abs(v).reshape(16, 256).max(axis=1)
    assert bool(jnp.all(err <= amax * rel)), (err / amax)


def test_roundtrip_pads_ragged_tail():
    v = jnp.asarray(np.random.RandomState(2).randn(1000).astype(np.float32))
    payload, scales, n = C.quantize_blocks(v, "int8", 256)
    assert payload.shape == (4, 256) and n == 1000
    deq = C.dequantize_blocks(payload, scales, n, v.shape)
    assert deq.shape == v.shape
    assert float(jnp.max(jnp.abs(deq - v))) <= float(
        jnp.max(jnp.abs(v))) / 127


# -- wire dtype + byte counters ---------------------------------------------

def _collective_eqns(jaxpr):
    """(primitive name, input avals) for every collective in the jaxpr,
    recursing through shard_map/pjit/scan bodies."""
    out = []

    def walk(jx):
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            if eqn.primitive.name in ("all_gather", "all_to_all", "psum",
                                      "psum_scatter", "reduce_scatter",
                                      "ppermute", "pmax", "pmin", "pmean"):
                out.append((eqn.primitive.name,
                            [v.aval for v in eqn.invars
                             if hasattr(v, "aval")]))
            for v in eqn.params.values():
                for cand in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                        walk(cand)

    walk(jaxpr.jaxpr)
    return out


@pytest.mark.parametrize("method", ["int8", "fp8"])
@pytest.mark.parametrize("two_shot", [False, True])
def test_wire_dtype_end_to_end(dp_mesh, method, two_shot):
    """Acceptance: the compressed path's payload collectives carry the
    narrow dtype — no int32/fp32 upcast hiding on the wire (the legacy
    psum bug). Checked on the traced jaxpr."""
    n_elems = 8 * 4096
    two_shot_min = 1 if two_shot else 1 << 30

    def sync(g, e):
        out, ef, ok = C.compressed_mean_allgather(
            {"w": g[0]}, {"w": e[0]}, "dp", method,
            two_shot_min=two_shot_min)
        return out["w"], ef["w"][None], ok

    sm = shard_map(sync, mesh=dp_mesh.mesh, in_specs=(P("dp"), P("dp")),
                   out_specs=(P(), P("dp"), P()), check_vma=False)
    g = jnp.zeros((8, n_elems), jnp.float32)
    e = jnp.zeros((8, n_elems), jnp.float32)
    eqns = _collective_eqns(jax.make_jaxpr(sm)(g, e))
    wire_dt = jnp.int8 if method == "int8" else jnp.float8_e4m3fn
    # each rank's local leaf is the full n_elems (the leading dp dim is
    # the replica stack); payload collectives carry at least a chunk
    payload = [(n, a) for n, a in eqns
               if a and a[0].dtype == wire_dt
               and a[0].size >= n_elems // 8]
    assert payload, f"no narrow-payload collective in {eqns}"
    # nothing tensor-sized crosses wide: any int32/fp32 collective must
    # be scalar bookkeeping (guard pmax) or the 1/block-rate scales
    for name, avals in eqns:
        for a in avals:
            if a.dtype in (jnp.int32, jnp.float32) and \
                    a.size > n_elems // 64:
                raise AssertionError(
                    f"wide {a.dtype} {name} of size {a.size} on the "
                    f"compressed wire: {eqns}")


def test_bytes_wire_ratio_int8_block256(dp_mesh):
    """Acceptance: comm/bytes_wire reports ≥3.5x reduction vs
    comm/bytes_logical for int8 at block 256."""
    stats.reset("comm/")

    def sync(g, e):
        out, ef, ok = C.compressed_mean_allgather(
            {"w": g[0]}, {"w": e[0]}, "dp", "int8", block=256)
        return out["w"], ef["w"][None], ok

    sm = shard_map(sync, mesh=dp_mesh.mesh, in_specs=(P("dp"), P("dp")),
                   out_specs=(P(), P("dp"), P()), check_vma=False)
    g = jnp.zeros((8, 64, 256), jnp.float32)
    jax.jit(sm).lower(g, jnp.zeros_like(g))   # counters tick at trace
    wire = stats.get("comm/bytes_wire")
    logical = stats.get("comm/bytes_logical")
    assert wire > 0
    assert logical / wire >= 3.5, (logical, wire)
    assert stats.get("comm/compression_ratio") >= 3.5


def test_uncompressed_collectives_keep_ratio_one(dp_mesh):
    stats.reset("comm/")
    from paddle_tpu.distributed import collective as coll

    def body(x):
        return coll.all_gather(x, "dp")

    sm = shard_map(body, mesh=dp_mesh.mesh, in_specs=(P("dp"),),
                   out_specs=P(), check_vma=False)
    jax.jit(sm).lower(jnp.zeros((8, 16), jnp.float32))
    assert stats.get("comm/bytes_wire") == stats.get("comm/bytes_logical")


# -- convergence parity ------------------------------------------------------

def _run_dp(method, dp_mesh, steps=60, lr=0.1, **kw):
    params, loss_fn, batch = _problem()
    opt = optim.SGD(learning_rate=lr)
    opt_state = opt.init(params)
    step = C.build_compressed_dp_step(loss_fn, opt, dp_mesh.mesh, method,
                                      **kw)
    ef = C.init_error_feedback(params, dp_mesh.mesh) if method else ()
    losses = []
    for _ in range(steps):
        params, opt_state, ef, loss = step(params, opt_state, ef, batch)
        losses.append(float(loss))
    return losses, ef


@pytest.mark.parametrize("method", ["int8", "fp8"])
def test_convergence_parity_quantized_wire(dp_mesh, method):
    """Acceptance: quantized and fp32 dp sync reach the same loss within
    tolerance over N steps, with the error feedback asserted nonzero (the
    channel IS lossy; the residual is what keeps parity)."""
    base, _ = _run_dp(None, dp_mesh)
    comp, ef = _run_dp(method, dp_mesh)
    assert comp[-1] < 0.05 * comp[0], comp[-1]
    assert comp[-1] <= base[-1] * 1.5 + 1e-4, (comp[-1], base[-1])
    ef_mag = float(jnp.max(jnp.abs(ef["w"])))
    assert ef_mag > 0.0, "error feedback never engaged — lossless wire?"


def test_two_shot_matches_one_shot_trajectory(dp_mesh):
    one, _ = _run_dp("int8", dp_mesh, two_shot_min=1 << 30)
    two, _ = _run_dp("int8", dp_mesh, two_shot_min=1)
    assert two[-1] <= one[-1] * 1.5 + 1e-4, (two[-1], one[-1])


def test_psum_legacy_path_kept_as_parity_reference(dp_mesh):
    """PT_COMM_QUANT_PSUM=1 restores the old int32-upcast psum wire; it
    must still converge (it is the parity oracle)."""
    losses, _ = _run_dp("int8", dp_mesh, use_psum=True)
    assert losses[-1] < 0.05 * losses[0]
    with pytest.raises(ValueError, match="psum"):
        C.build_compressed_dp_step(
            lambda p, b: 0.0, optim.SGD(0.1), dp_mesh.mesh, "fp8",
            use_psum=True)


# -- stage-3 quantized weight gather ----------------------------------------

def test_stage3_gather_bit_tolerance_vs_fp32(fsdp_mesh):
    """The quantized pre-forward param all-gather must reproduce the fp32
    gather within the per-block half-step bound — parity-tested dequant
    on the weight path."""
    rs = np.random.RandomState(3)
    w = jnp.asarray(rs.randn(16, 64).astype(np.float32))

    def gather(shard):
        q, ok = C.quantized_all_gather_dequant(shard, "fsdp", "int8",
                                               block=64, dim=0)
        f = lax.all_gather(shard, "fsdp", axis=0, tiled=True)
        return q, f, ok

    sm = shard_map(gather, mesh=fsdp_mesh.mesh, in_specs=(P("fsdp"),),
                   out_specs=(P(), P(), P()), check_vma=False)
    q, f, ok = jax.jit(sm)(w)
    assert bool(ok)
    np.testing.assert_allclose(np.asarray(f), np.asarray(w), rtol=0,
                               atol=0)
    err = np.abs(np.asarray(q) - np.asarray(w)).max()
    bound = float(jnp.max(jnp.abs(w))) * (0.5 / 127) + 1e-7
    assert err <= bound, (err, bound)


@pytest.mark.parametrize("level", ["os_g", "p_g_os"])
@pytest.mark.parametrize("method", ["int8", "fp8"])
def test_group_sharded_quantized_parity(fsdp_mesh, level, method):
    """Stage-2/3 training over the quantized wire lands at parity with
    the GSPMD fp32 path on the same seed."""
    rs = np.random.RandomState(0)
    w_true = rs.randn(16, 8).astype(np.float32)
    x = rs.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 8), jnp.float32),
              "b": jnp.zeros((8,), jnp.float32),
              "tiny": jnp.zeros((3,), jnp.float32)}  # indivisible → pmean

    def loss_fn(p, xb, yb):
        return (jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)
                + jnp.sum(p["tiny"] ** 2))

    def run(cq):
        sp, st, step = group_sharded_parallel(
            params, optim.AdamW(learning_rate=3e-2), loss_fn,
            fsdp_mesh.mesh, level=level, comm_quant=cq)
        if cq != "none":
            assert "comm_ef" in st
        for _ in range(40):
            sp, st, loss = step(sp, st, jnp.asarray(x), jnp.asarray(y))
        return float(loss), st

    base, _ = run("none")
    comp, st = run(method)
    assert comp <= base * 1.5 + 1e-3, (comp, base)
    ef_mag = max(float(jnp.max(jnp.abs(v)))
                 for v in st["comm_ef"].values())
    assert ef_mag > 0.0


def test_group_sharded_quantized_wire_dtype(fsdp_mesh):
    """Stage-3 explicit step: the traced program's big collectives carry
    int8 — both the pre-forward gather and the grad reduce-scatter leg."""
    params = {"w": jnp.zeros((16, 64), jnp.float32)}

    def loss_fn(p, xb):
        return jnp.mean((xb @ p["w"]) ** 2)

    sp, st, step = group_sharded_parallel(
        params, optim.SGD(learning_rate=0.1), loss_fn, fsdp_mesh.mesh,
        level="p_g_os", comm_quant="int8", comm_block=64)
    xb = jnp.asarray(np.random.RandomState(0).randn(8, 16),
                     jnp.float32)
    eqns = _collective_eqns(jax.make_jaxpr(
        lambda p, s, b: step(p, s, b))(sp, st, xb))
    narrow = [(n, a) for n, a in eqns
              if a and a[0].dtype == jnp.int8 and a[0].size >= 16 * 64 // 4]
    assert any(n == "all_gather" for n, _ in narrow), eqns
    assert any(n == "all_to_all" for n, _ in narrow), eqns


# -- fail-loud fault site ----------------------------------------------------

def test_bitflipped_scale_fails_every_rank_loudly(dp_mesh):
    """``collective.quant_payload`` bitflip on a block scale: the wire
    guard must detect it and the step must RAISE — never silently steer
    the model (mirrors paged.shared_page)."""
    params, loss_fn, batch = _problem()
    opt = optim.SGD(learning_rate=0.1)
    opt_state = opt.init(params)
    ef = C.init_error_feedback(params, dp_mesh.mesh)
    with faults.inject("collective.quant_payload", "bitflip", bit=30):
        step = C.build_compressed_dp_step(loss_fn, opt, dp_mesh.mesh,
                                          "int8")
        with pytest.raises(RuntimeError, match="quant_payload"):
            step(params, opt_state, ef, batch)
    faults.clear()


def test_bitflipped_payload_detected_or_bounded(dp_mesh):
    """Payload bitflips stay inside the block's scale envelope (a flipped
    int8 stays a valid code), so the guard may pass — but the synced
    value must then still be inside the quantization tolerance, i.e. the
    corruption cannot exceed what the format already admits."""
    rs = np.random.RandomState(4)
    g = jnp.asarray(rs.randn(8, 32, 32).astype(np.float32))

    def sync(gl, el):
        out, ef, ok = C.compressed_mean_allgather(
            {"w": gl[0]}, {"w": el[0]}, "dp", "int8", block=64)
        return out["w"], ok

    sm = shard_map(sync, mesh=dp_mesh.mesh, in_specs=(P("dp"), P("dp")),
                   out_specs=(P(), P()), check_vma=False)
    with faults.inject("collective.quant_payload", "bitflip",
                       target="payload", bit=6, offset=5):
        out, ok = jax.jit(sm)(g, jnp.zeros_like(g))
    faults.clear()
    true = np.asarray(g).mean(0)
    err = np.abs(np.asarray(out) - true).max()
    # the baked flip runs in the SPMD program, so EVERY rank's code moves
    # by ±2^6; the mean of 8 flipped codes moves one element by at most
    # (2^6/127)·amax — still inside the block's scale envelope
    assert err <= float(jnp.max(jnp.abs(g))) * (64 / 127) + 0.02


def test_sharded_step_poisons_on_corruption(fsdp_mesh):
    """The group-sharded quantized step NaN-poisons params + loss on a
    tripped guard — corruption is loud on every rank even without the
    host-side raise."""
    params = {"w": jnp.ones((16, 8), jnp.float32)}

    def loss_fn(p, xb):
        return jnp.mean((xb @ p["w"]) ** 2)

    with faults.inject("collective.quant_payload", "bitflip", bit=30):
        sp, st, step = group_sharded_parallel(
            params, optim.SGD(learning_rate=0.1), loss_fn,
            fsdp_mesh.mesh, level="p_g_os", comm_quant="int8")
        xb = jnp.asarray(np.random.RandomState(0).randn(8, 16),
                         jnp.float32)
        sp, st, loss = step(sp, st, xb)
    faults.clear()
    assert not np.isfinite(float(loss))
    assert not np.all(np.isfinite(np.asarray(sp["w"])))


# -- policy / flags / lint ---------------------------------------------------

def test_comm_env_contract_declared():
    for name in ("PT_COMM_QUANT", "PT_COMM_BLOCK", "PT_COMM_QUANT_PSUM"):
        assert pt_flags.env_declared(name), name


def test_planner_comm_quant_policy():
    degrees = {"dp": 4, "fsdp": 2, "tp": 2}
    # single host: everything rides ICI → no quantization
    assert planner.comm_quant_policy(degrees, n_hosts=1) == {
        "dp": None, "fsdp": None}
    # 4 hosts of 4 chips: dp (outermost, stride 4, deg 4 → 16 > 4)
    # crosses hosts; fsdp (stride 2, deg 2 → 4 ≤ 4) stays on-chip
    pol = planner.comm_quant_policy(degrees, n_hosts=4)
    assert pol["dp"] == "int8" and pol["fsdp"] is None


def test_resolve_comm_quant_env_and_auto(monkeypatch):
    monkeypatch.setenv("PT_COMM_QUANT", "fp8")
    assert C.resolve_comm_quant("dp", degrees={"dp": 8}) == "fp8"
    monkeypatch.setenv("PT_COMM_QUANT", "none")
    assert C.resolve_comm_quant("dp", degrees={"dp": 8}) is None
    monkeypatch.setenv("PT_COMM_QUANT", "auto")
    monkeypatch.setenv("PT_NNODES", "2")
    assert C.resolve_comm_quant("dp", degrees={"dp": 8}) == "int8"
    monkeypatch.setenv("PT_NNODES", "1")
    assert C.resolve_comm_quant("dp", degrees={"dp": 8}) is None
    monkeypatch.setenv("PT_COMM_QUANT", "int4")
    with pytest.raises(ValueError):
        C.resolve_comm_quant("dp", degrees={"dp": 8})


def test_direct_step_builder_never_auto_quantizes(fsdp_mesh, monkeypatch):
    """Regression (review finding): build_group_sharded_step called the
    documented way — group_sharded_specs + init_group_sharded_state,
    NO comm_ef attached — must stay on the GSPMD path even when the
    environment would auto-resolve to a quantized format (multi-host +
    PT_COMM_QUANT=auto). Only group_sharded_parallel, which owns the
    state and attaches the residual, auto-resolves."""
    from paddle_tpu.distributed.sharding import (
        build_group_sharded_step, group_sharded_specs,
        init_group_sharded_state)
    monkeypatch.setenv("PT_COMM_QUANT", "auto")
    monkeypatch.setenv("PT_NNODES", "4")
    params = {"w": jnp.ones((16, 8), jnp.float32)}

    def loss_fn(p, xb):
        return jnp.mean((xb @ p["w"]) ** 2)

    specs = group_sharded_specs(params, fsdp_mesh.mesh, level="p_g_os")
    sp, st = init_group_sharded_state(
        params, optim.SGD(learning_rate=0.1), specs)
    step = build_group_sharded_step(
        loss_fn, optim.SGD(learning_rate=0.1), specs)
    xb = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    sp, st, loss = step(sp, st, xb)     # crashed pre-fix: no comm_ef
    assert np.isfinite(float(loss))
    # the same env DOES quantize through the one-call API (which owns
    # the state): comm_ef present and the step still runs
    sp2, st2, step2 = group_sharded_parallel(
        params, optim.SGD(learning_rate=0.1), loss_fn, fsdp_mesh.mesh,
        level="p_g_os")
    assert "comm_ef" in st2
    sp2, st2, loss2 = step2(sp2, st2, xb)
    assert np.isfinite(float(loss2))


def test_auto_policy_falls_back_for_unsupported_configs(fsdp_mesh,
                                                        monkeypatch):
    """Regression (review finding): an AUTO-resolved quantized policy
    must never turn a previously-valid setup into a build-time error —
    grad_clip / level='os' configs quietly keep the GSPMD path. An
    EXPLICIT format still raises loudly for them."""
    from paddle_tpu.optimizer import clip
    monkeypatch.setenv("PT_COMM_QUANT", "auto")
    monkeypatch.setenv("PT_NNODES", "4")   # fsdp tier resolves to dcn
    params = {"w": jnp.ones((16, 8), jnp.float32)}

    def loss_fn(p, xb):
        return jnp.mean((xb @ p["w"]) ** 2)

    xb = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    # grad_clip: unsupported on the explicit path → auto falls back
    sp, st, step = group_sharded_parallel(
        params, optim.AdamW(learning_rate=1e-2,
                            grad_clip=clip.ClipGradByGlobalNorm(1.0)),
        loss_fn, fsdp_mesh.mesh, level="p_g_os")
    assert "comm_ef" not in st
    sp, st, loss = step(sp, st, xb)
    assert np.isfinite(float(loss))
    # level os: no reduce-scatter to quantize → auto falls back
    sp, st, step = group_sharded_parallel(
        params, optim.SGD(learning_rate=0.1), loss_fn, fsdp_mesh.mesh,
        level="os")
    assert "comm_ef" not in st
    # ...but asking for the format explicitly still fails loudly
    with pytest.raises(ValueError, match="grad_clip"):
        group_sharded_parallel(
            params, optim.AdamW(learning_rate=1e-2,
                                grad_clip=clip.ClipGradByGlobalNorm(1.0)),
            loss_fn, fsdp_mesh.mesh, level="p_g_os", comm_quant="int8")


def test_quantized_step_splits_batch_over_dp(fsdp_mesh):
    """The explicit path must not replicate compute over a dp axis: the
    batch splits over dp (mean losses unchanged) — asserted by feeding a
    batch whose dp halves differ and checking the loss equals the
    full-batch mean, not either half's."""
    params = {"w": jnp.zeros((16, 8), jnp.float32)}
    rs = np.random.RandomState(1)
    xb = jnp.asarray(rs.randn(8, 16), jnp.float32)
    yb = jnp.asarray(np.concatenate(
        [np.zeros((4, 8)), np.ones((4, 8))]), jnp.float32)

    def loss_fn(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    sp, st, step = group_sharded_parallel(
        params, optim.SGD(learning_rate=0.0), loss_fn, fsdp_mesh.mesh,
        level="p_g_os", comm_quant="int8")
    _, _, loss = step(sp, st, xb, yb)
    want = float(jnp.mean((xb @ params["w"] - yb) ** 2))
    assert abs(float(loss) - want) < 1e-6, (float(loss), want)


def test_ptlint_pt004_clean_on_comm_modules():
    """The new collectives must be unconditionally ordered across ranks:
    PT004 (rank-divergent collective order) stays silent on the whole
    quantized-comm stack."""
    from paddle_tpu.analysis import load_project, run
    from paddle_tpu.analysis.rules_collectives import CollectiveOrderRule
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, "paddle_tpu", "distributed", f)
             for f in ("compression.py", "sharding.py", "collective.py")]
    project = load_project(paths, root=root)
    findings = list(run(project, [CollectiveOrderRule()]))
    assert not findings, findings
