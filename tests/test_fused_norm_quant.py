"""Fused LayerNorm + int8 matmul Pallas kernels vs XLA oracles.

Mirrors the reference's fused-op tests
(test_fused_bias_dropout_residual_layer_norm_op.py pattern: oracle
composition checked against the fused kernel for output AND grads).
Runs in Pallas interpret mode on the CPU test platform.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.layer_norm import (fused_layer_norm,
                                              dropout_keep_mask)
from paddle_tpu.ops.pallas.quant_matmul import int8_matmul
from paddle_tpu import quantization as quant


def _ln_oracle(x, gamma, beta, residual=None, bias=None, dropout_p=0.0,
               seed=0, eps=1e-5):
    pre = jnp.asarray(x, jnp.float32)
    if bias is not None:
        pre = pre + jnp.asarray(bias, jnp.float32)
    if dropout_p > 0.0:
        x2 = pre.reshape(-1, pre.shape[-1])
        keep = dropout_keep_mask(seed, 0, x2.shape[1], x2.shape, dropout_p)
        pre = jnp.where(keep.reshape(pre.shape),
                        pre / (1.0 - dropout_p), 0.0)
    if residual is not None:
        pre = pre + jnp.asarray(residual, jnp.float32)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    var = jnp.var(pre, axis=-1, keepdims=True)
    y = (pre - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return y, pre


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).normal(size=shape), dtype)


@pytest.mark.parametrize("shape", [(4, 128), (2, 16, 256), (3, 384)])
def test_fused_ln_forward(shape):
    x = _rand(shape, 0)
    gamma = _rand(shape[-1:], 1) + 1.0
    beta = _rand(shape[-1:], 2)
    y, pre = fused_layer_norm(x, gamma, beta, interpret=True)
    ref_y, ref_pre = _ln_oracle(x, gamma, beta)
    np.testing.assert_allclose(y, ref_y, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(pre, ref_pre, atol=1e-6, rtol=1e-6)


def test_fused_ln_residual_bias():
    x = _rand((6, 256), 0)
    res = _rand((6, 256), 3)
    bias = _rand((256,), 4)
    gamma = _rand((256,), 1) + 1.0
    beta = _rand((256,), 2)
    y, pre = fused_layer_norm(x, gamma, beta, residual=res, bias=bias,
                              interpret=True)
    ref_y, ref_pre = _ln_oracle(x, gamma, beta, residual=res, bias=bias)
    np.testing.assert_allclose(y, ref_y, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(pre, ref_pre, atol=1e-6, rtol=1e-6)


def test_fused_ln_dropout_deterministic():
    x = _rand((16, 128), 0)
    gamma = jnp.ones((128,))
    beta = jnp.zeros((128,))
    res = _rand((16, 128), 5)
    y1, pre1 = fused_layer_norm(x, gamma, beta, residual=res, dropout_p=0.3,
                                dropout_seed=11, interpret=True)
    y2, pre2 = fused_layer_norm(x, gamma, beta, residual=res, dropout_p=0.3,
                                dropout_seed=11, interpret=True)
    np.testing.assert_array_equal(y1, y2)
    ref_y, ref_pre = _ln_oracle(x, gamma, beta, residual=res, dropout_p=0.3,
                                seed=11)
    np.testing.assert_allclose(y1, ref_y, atol=1e-5, rtol=1e-5)
    # a different seed must give a different mask
    y3, _ = fused_layer_norm(x, gamma, beta, residual=res, dropout_p=0.3,
                             dropout_seed=12, interpret=True)
    assert not np.allclose(y1, y3)
    # dropped fraction ≈ rate (pre minus residual is zero where dropped)
    dropped = np.mean(np.asarray(pre1 - res) == 0.0)
    assert 0.2 < dropped < 0.4


def test_fused_ln_grads_match_oracle():
    x = _rand((8, 128), 0)
    res = _rand((8, 128), 3)
    bias = _rand((128,), 4)
    gamma = _rand((128,), 1) + 1.0
    beta = _rand((128,), 2)
    cy = _rand((8, 128), 6)
    cpre = _rand((8, 128), 7)

    def loss_fused(x, gamma, beta, bias, res):
        y, pre = fused_layer_norm(x, gamma, beta, residual=res, bias=bias,
                                  dropout_p=0.25, dropout_seed=9,
                                  interpret=True)
        return jnp.sum(y * cy) + jnp.sum(pre * cpre)

    def loss_ref(x, gamma, beta, bias, res):
        y, pre = _ln_oracle(x, gamma, beta, residual=res, bias=bias,
                            dropout_p=0.25, seed=9)
        return jnp.sum(y * cy) + jnp.sum(pre * cpre)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(
        x, gamma, beta, bias, res)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
        x, gamma, beta, bias, res)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(gf, gr, atol=1e-4, rtol=1e-4)


def test_fused_ln_jit_traced_seed():
    # per-step seeds must not retrace: seed is an operand, not a constant
    x = _rand((4, 128), 0)
    gamma = jnp.ones((128,))
    beta = jnp.zeros((128,))

    @jax.jit
    def f(x, seed):
        y, _ = fused_layer_norm(x, gamma, beta, dropout_p=0.5,
                                dropout_seed=seed, interpret=True)
        return y

    a = f(x, jnp.int32(1))
    b = f(x, jnp.int32(2))
    assert not np.allclose(a, b)


@pytest.mark.parametrize("shape", [((4, 256), (256, 384)),
                                   ((2, 7, 128), (128, 256)),
                                   ((5, 100), (100, 130))])
def test_int8_matmul_matches_dequant(shape):
    xs, ws = shape
    x = _rand(xs, 0)
    w = _rand(ws, 1)
    qt = quant.quantize_tensor(w, axis=-1)
    out = int8_matmul(x, qt.q, qt.scale.reshape(1, -1), interpret=True)
    ref = x @ qt.dequantize()
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-3)


def test_int8_matmul_bf16_activation():
    x = _rand((8, 256), 0, jnp.bfloat16)
    w = _rand((256, 128), 1)
    qt = quant.quantize_tensor(w, axis=-1, )
    out = int8_matmul(x, qt.q, qt.scale.reshape(1, -1), interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = (x.astype(jnp.float32) @ qt.dequantize().astype(jnp.float32))
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=0.15,
                               rtol=0.1)
