"""Vision model zoo forward/backward smoke (ref test pattern:
python/paddle/tests/test_vision_models.py — every family constructs and
produces logits of the right shape)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.vision import models as M
from paddle_tpu import nn

# (name, ctor, img_size, check_grad) — grads only for the light families:
# big-zoo CPU grad compiles (densenet121's 58 concat layers, inception's
# factorized stacks) take minutes each and add no coverage beyond one
# representative per op family
FAMILIES = [
    ("squeezenet1_1", lambda: M.squeezenet1_1(num_classes=10), 64, True),
    ("shufflenet_v2_x0_25", lambda: M.shufflenet_v2_x0_25(num_classes=10),
     64, True),
    ("densenet121", lambda: M.densenet121(num_classes=10), 64, False),
    ("googlenet", lambda: M.googlenet(num_classes=10), 64, False),
    ("inception_v3", lambda: M.inception_v3(num_classes=10), 96, False),
    ("mobilenet_v3_small", lambda: M.mobilenet_v3_small(num_classes=10),
     64, True),
    ("mobilenet_v3_large", lambda: M.mobilenet_v3_large(num_classes=10),
     64, False),
]


@pytest.mark.parametrize("name,ctor,img,check_grad", FAMILIES,
                         ids=[f[0] for f in FAMILIES])
def test_forward_and_grad(name, ctor, img, check_grad):
    model = ctor().tag_paths()
    x = jnp.asarray(np.random.RandomState(0).rand(2, 3, img, img),
                    jnp.float32)
    with nn.stateful(training=True, rng=jax.random.PRNGKey(0)):
        out = model(x)
    assert out.shape == (2, 10), (name, out.shape)
    assert np.isfinite(np.asarray(out)).all()
    # eval mode (running BN stats) must work too
    out_e = model.eval()(x)
    assert np.isfinite(np.asarray(out_e)).all()

    if not check_grad:
        return
    model.train()
    params, buffers = model.split_params()

    def loss(p):
        m = model.merge_params({**buffers, **p})
        with nn.stateful(training=True, rng=jax.random.PRNGKey(0)):
            return jnp.sum(m(x).astype(jnp.float32) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(np.isfinite(np.asarray(v)).all() for v in leaves)


def test_family_count_vs_reference():
    """Reference ships 12 families (SURVEY §2.3 Domains); ours must match
    or exceed, counting the detector."""
    families = {"LeNet", "AlexNet", "VGG", "ResNet", "MobileNetV1",
                "MobileNetV2", "MobileNetV3Small", "SqueezeNet",
                "ShuffleNetV2", "DenseNet", "GoogLeNet", "InceptionV3",
                "PPYOLOE"}
    for f in families:
        assert hasattr(M, f), f
    assert len(families) >= 12
