"""Sequence/context parallelism tests on the 8-virtual-device CPU mesh.

The reference has NO sequence parallelism (SURVEY §5.7) — oracle here is the
single-device attention_reference, the same numpy-oracle-×-execution-modes
pattern as the reference's collective tests
(test_collective_api_base.py:292 check_with_place)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.ring_attention import (
    ring_attention, sequence_parallel_attention, ulysses_attention)
from paddle_tpu.nn.functional.attention import attention_reference


def _qkv(b, s, h, d, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp8_matches_reference(mode, causal):
    topo = dist.init_mesh(sp=8)
    q, k, v = _qkv(2, 64, 8, 16)
    out = sequence_parallel_attention(q, k, v, topo.mesh, causal=causal,
                                      mode=mode)
    ref = attention_reference(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_hybrid_mesh_sp_with_dp_tp(mode):
    topo = dist.init_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(2, 32, 4, 8, seed=1)
    out = sequence_parallel_attention(q, k, v, topo.mesh, causal=True,
                                      mode=mode)
    ref = attention_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_grads_match_reference(mode):
    topo = dist.init_mesh(dp=2, sp=4)
    q, k, v = _qkv(2, 32, 4, 8, seed=2)
    cot = jnp.asarray(np.random.RandomState(3).normal(size=q.shape),
                      jnp.float32)

    def loss_sp(q, k, v):
        return jnp.sum(sequence_parallel_attention(
            q, k, v, topo.mesh, causal=True, mode=mode) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, is_causal=True) * cot)

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_sp, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4, err_msg=f"d{name}")


def test_ring_inside_jitted_train_like_step():
    """ring attention composes with jit + other sharded computation."""
    topo = dist.init_mesh(sp=8)
    q, k, v = _qkv(1, 64, 2, 8, seed=4)

    @jax.jit
    def f(q, k, v):
        o = sequence_parallel_attention(q, k, v, topo.mesh, causal=True)
        return jnp.mean(o * o)

    val = f(q, k, v)
    ref = jnp.mean(attention_reference(q, k, v, is_causal=True) ** 2)
    np.testing.assert_allclose(float(val), float(ref), atol=1e-5, rtol=1e-5)


def test_replication_explicit():
    """The shard_map wrapper disables jax 0.4.37's replication checker
    (false positive on the causal ring's cond — see
    sequence_parallel_attention). This asserts the property the checker
    would have proven, explicitly: a replicated (out_specs P()) loss
    reduced from the ring output is BIT-IDENTICAL on every device —
    no rank's online-softmax ring diverged."""
    from jax import lax
    from jax.sharding import PartitionSpec as P
    topo = dist.init_mesh(sp=8)
    q, k, v = _qkv(2, 64, 8, 16, seed=5)
    spec = P(("dp", "fsdp"), "sp", "tp", None)

    def body(q, k, v):
        from paddle_tpu.distributed.ring_attention import ring_attention
        o = ring_attention(q, k, v, "sp", causal=True)
        return lax.psum(jnp.sum(o * o), "sp")

    loss = jax.jit(jax.shard_map(
        body, mesh=topo.mesh, in_specs=(spec, spec, spec),
        out_specs=P(), check_vma=False))(q, k, v)
    shards = [np.asarray(s.data) for s in loss.addressable_shards]
    assert len(shards) == 8
    for s in shards[1:]:
        np.testing.assert_array_equal(s, shards[0])
    # and the replicated value is the true global reduction
    ref = float(jnp.sum(attention_reference(q, k, v, is_causal=True) ** 2))
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_grads_through_causal_ring_train_step():
    """Regression for the dryrun phase-C signature: jax.grad through the
    causal ring (the exact path the replication checker used to reject
    with "mismatched replication types") must run and match the dense
    reference."""
    topo = dist.init_mesh(sp=8)
    q, k, v = _qkv(1, 64, 2, 8, seed=6)

    def loss_sp(q):
        return jnp.mean(sequence_parallel_attention(
            q, k, v, topo.mesh, causal=True) ** 2)

    def loss_ref(q):
        return jnp.mean(attention_reference(q, k, v, is_causal=True) ** 2)

    g = jax.grad(loss_sp)(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-5, rtol=1e-4)


def test_ulysses_rejects_bad_heads():
    topo = dist.init_mesh(sp=8)
    q, k, v = _qkv(1, 64, 4, 8)  # 4 heads not divisible by sp=8
    with pytest.raises(ValueError, match="not divisible"):
        sequence_parallel_attention(q, k, v, topo.mesh, causal=False,
                                    mode="ulysses")
