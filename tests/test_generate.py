"""KV-cache decoding + Predictor serving (VERDICT r1 item 2).

Oracle: incremental decode logits must equal full-forward logits at every
step (≙ the reference's fused_multi_transformer CacheKV correctness
contract)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt


@pytest.fixture(scope="module")
def model():
    return gpt.GPT(gpt.gpt_tiny(), seed=0)


def test_incremental_decode_matches_full_forward(model):
    cfg = model.cfg
    rs = np.random.RandomState(0)
    b, s0, steps = 2, 8, 5
    prompt = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s0)), jnp.int32)

    # greedy rollout via the cache
    cache = model.init_cache(b, cfg.max_seq_len)
    logits, cache = jax.jit(model.forward_cached, static_argnums=()) \
        (prompt, cache, 0)
    seq = prompt
    for t in range(steps):
        # oracle: full forward on the whole sequence so far
        full = model(seq)
        np.testing.assert_allclose(
            np.asarray(logits[:, -1], np.float32),
            np.asarray(full[:, -1], np.float32), rtol=2e-4, atol=2e-4,
            err_msg=f"step {t}: cached logits diverge from full forward")
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = model.forward_cached(nxt[:, None], cache,
                                             seq.shape[1] - 1)


def test_generate_greedy_matches_manual_rollout(model):
    cfg = model.cfg
    rs = np.random.RandomState(1)
    prompt = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, 6)), jnp.int32)
    out = model.generate(prompt, max_new_tokens=4)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :6]),
                                  np.asarray(prompt))
    # manual greedy rollout with full forwards
    seq = prompt
    for _ in range(4):
        nxt = jnp.argmax(model(seq)[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generate_sampling_reproducible_and_topk(model):
    cfg = model.cfg
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    rng = jax.random.PRNGKey(7)
    a = model.generate(prompt, max_new_tokens=6, temperature=0.8,
                       top_p=0.9, top_k=16, rng=rng)
    b = model.generate(prompt, max_new_tokens=6, temperature=0.8,
                       top_p=0.9, top_k=16, rng=rng)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (1, 10)
    assert np.all(np.asarray(a) >= 0) and np.all(
        np.asarray(a) < cfg.vocab_size)


def test_generate_eos_padding(model):
    cfg = model.cfg
    prompt = jnp.asarray([[5, 6]], jnp.int32)
    out = model.generate(prompt, max_new_tokens=8, eos_id=0)
    arr = np.asarray(out)[0, 2:]
    hits = np.where(arr == 0)[0]
    if hits.size:  # after first eos everything must be eos
        assert np.all(arr[hits[0]:] == 0)


def test_predictor_pads_and_batches(tmp_path, model):
    from paddle_tpu import jit as ptjit
    from paddle_tpu.inference import Config, Predictor, create_predictor
    from paddle_tpu.static import InputSpec

    cfg = model.cfg
    params, _ = model.split_params()

    def fwd(tokens):
        return model.merge_params(params)(tokens)

    path = str(tmp_path / "gpt_tiny")
    ptjit.save(fwd, path,
               input_spec=[InputSpec([4, 8], "int32", "tokens")])

    pred = Predictor(path)
    assert pred._batch == 4
    rs = np.random.RandomState(3)
    reqs = rs.randint(0, cfg.vocab_size, (6, 8)).astype(np.int32)
    out = pred.run(reqs)  # 6 requests over batch-4 program → 2 sub-batches
    assert out.shape == (6, 8, cfg.vocab_size)
    ref = np.asarray(fwd(jnp.asarray(reqs[:4])))
    np.testing.assert_allclose(out[:4], ref, rtol=1e-4, atol=1e-5)

    c = Config(path)
    p2 = create_predictor(c)
    one = p2.predict(reqs[0])
    np.testing.assert_allclose(one, out[0], rtol=1e-4, atol=1e-5)
