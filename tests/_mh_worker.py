"""Spawned-worker module for test_multihost. Pins the CPU platform at
MODULE level: multiprocessing's spawn start-method unpickles the target
function by importing this module, so these lines run before any jax
backend can initialize (two workers must not both claim the single
tunneled TPU)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def _set_cpu_device_count(n):
    """Per-process CPU device count, pre-backend-init. jax >= 0.5 has a
    config option; older jax only honors the XLA flag (these lines run
    before any backend initializes, so mutating XLA_FLAGS still takes)."""
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)


def worker(tmpdir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    # federate the per-process CPU devices into one global backend
    # (cross-process CPU collectives run over gloo; on TPU pods the ICI/
    # DCN fabric takes this role and no flag is needed). One device per
    # process — conftest's xla_force_host_platform_device_count=8 leaks
    # into spawned children through the environment.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _set_cpu_device_count(1)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    import paddle_tpu.distributed as dist

    dist.init_parallel_env()      # PT_* env → jax.distributed.initialize
    rank = dist.get_rank()
    world = jax.process_count()
    assert world == 2, world
    devices = jax.devices()       # global view: one device per process
    assert len(devices) == 2

    mesh = Mesh(np.array(devices), ("dp",))

    # cross-process psum through shard_map (the NCCL-allreduce analog on
    # the DCN plane)
    @jax.jit
    def allreduce(x):
        return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P("dp"))(x)

    local = jnp.full((1, 4), float(rank + 1))
    glob = jax.make_array_from_single_device_arrays(
        (2, 4), NamedSharding(mesh, P("dp")),
        [jax.device_put(local, devices[rank])])
    out = allreduce(glob)
    got = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(got, np.full((1, 4), 3.0))  # 1 + 2

    # cross-process pipeline tick: roll(+1) as collective-permute BETWEEN
    # THE TWO PROCESSES — the PP-over-DCN mechanism (≙ FleetExecutor's
    # cross-rank interceptor sends)
    @jax.jit
    def ring_shift(x):
        return shard_map(
            lambda v: jax.lax.ppermute(
                v, "dp", perm=[(i, (i + 1) % 2) for i in range(2)]),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    shifted = ring_shift(glob)
    got = np.asarray(shifted.addressable_shards[0].data)
    expect = np.full((1, 4), float(((rank - 1) % 2) + 1))
    np.testing.assert_allclose(got, expect)

    with open(os.path.join(tmpdir, f"ok_{rank}"), "w") as f:
        f.write("1")


def obs_worker(tmpdir):
    """Per-rank tracing body for the trace-merge test: each spawned
    process (PT_PROCESS_ID set by dist.spawn's env contract) records a
    nested span tree and exports its own trace_rank{N}.json — the
    parent test merges them and asserts distinct rank lanes. No jax
    needed: the tracer is pure host-side."""
    from paddle_tpu import stats
    from paddle_tpu.observability import span, trace

    rank = int(os.environ["PT_PROCESS_ID"])
    trace.enable(os.path.join(tmpdir, f"trace_rank{rank}.json"),
                 capacity=256)
    with span("mh/work", rank=rank):
        with span("mh/inner"):
            stats.observe("mh/latency_s", 0.001 * (rank + 1))
    path = trace.export()
    # worker-side stats export rides a sidecar file, the way launch-side
    # aggregation would scrape statsz: the parent merges both ranks
    import json
    with open(os.path.join(tmpdir, f"stats_{rank}.json"), "w") as f:
        json.dump(stats.export(rank=rank), f)
    assert path is not None


# ---------------------------------------------------------------------------
# Two-controller GPT hybrid step (VERDICT r4 item 4): 2 processes x 4
# virtual CPU devices = one 8-device jax.distributed job running the FULL
# dp x fsdp x tp GPT train step; losses must match the single-controller
# 8-device run bit-for-tolerance. Ref: test_dist_base.py:901 (subprocess
# hybrid suites), test_collective_api_base.py:292.
# ---------------------------------------------------------------------------

GPT_MESH = {"dp": 2, "fsdp": 2, "tp": 2}
GPT_STEPS = 3


def _gpt_mini():
    import jax.numpy as jnp
    from paddle_tpu.models import gpt
    cfg = gpt.GPTConfig(vocab_size=512, max_seq_len=16, d_model=32,
                        n_layers=2, n_heads=2, dtype=jnp.float32)
    return gpt.GPT(cfg, seed=0)


def _gpt_tokens():
    return np.random.RandomState(0).randint(0, 512, (8, 16)).astype(
        np.int32)


def gpt_losses(mesh_degrees=GPT_MESH, steps=GPT_STEPS):
    """Run the hybrid GPT step on the CURRENT backend's 8 devices; works
    single-controller (pytest process) and multi-controller (each process
    passes identical replicated inputs, jit computes the same global
    program). Returns the loss sequence."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt
    from paddle_tpu import optimizer as optim

    topo = dist.init_mesh(**mesh_degrees)
    model = _gpt_mini()
    opt = optim.AdamW(learning_rate=1e-3)
    params, _ = model.split_params()
    # multi-controller-safe placement: device_put cannot target
    # non-addressable devices, but a jitted identity with out_shardings
    # can produce globally-sharded outputs on every controller
    shardings = gpt.param_shardings(params, topo.mesh)
    params = jax.jit(lambda p: p, out_shardings=shardings)(params)
    opt_state = jax.jit(opt.init)(params)
    step = gpt.build_train_step(model, opt)
    tokens = jnp.asarray(_gpt_tokens())
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, rng)
        losses.append(float(loss))  # fully-replicated scalar
    return losses


def gpt_worker(tmpdir):
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _set_cpu_device_count(4)

    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    assert jax.process_count() == 2
    assert jax.device_count() == 8 and len(jax.local_devices()) == 4

    losses = gpt_losses()
    with open(os.path.join(tmpdir, f"losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


# ---------------------------------------------------------------------------
# FleetExecutor pipeline split across the two controllers: each process
# owns ONE stage as its own jitted program over its LOCAL 4-device mesh
# (in-stage dp x tp SPMD), boundary activations cross controllers over the
# native P2P endpoint — DCN-PP composed with ICI-SPMD, the way a real
# 2-host pod splits NCCL (intra) from brpc (inter) in the reference.
# ---------------------------------------------------------------------------

FE_D, FE_H, FE_MICRO, FE_B = 8, 16, 4, 4


def _fe_data():
    rs = np.random.RandomState(7)
    x = rs.normal(size=(FE_MICRO, FE_B, FE_D)).astype(np.float32)
    y = rs.normal(size=(FE_MICRO, FE_B, FE_D)).astype(np.float32)
    return x, y


def _fe_params(stage):
    rs = np.random.RandomState(10 + stage)
    din, dout = (FE_D, FE_H) if stage == 0 else (FE_H, FE_D)
    return {"w": rs.normal(size=(din, dout)).astype(np.float32) * 0.3}


def fe_reference():
    """Single-process full-model oracle for the 2-stage MLP."""
    import jax
    import jax.numpy as jnp
    x, y = _fe_data()
    ps = [_fe_params(0), _fe_params(1)]

    def loss_fn(ps):
        total = 0.0
        for mb in range(FE_MICRO):
            h = jnp.maximum(x[mb] @ ps[0]["w"], 0.0)
            pred = h @ ps[1]["w"]
            total = total + jnp.mean(jnp.square(pred - y[mb]))
        return total / FE_MICRO

    return float(loss_fn(ps)), jax.grad(loss_fn)(ps)


def fe_worker(tmpdir, store_port):
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _set_cpu_device_count(4)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu import native
    from paddle_tpu.distributed.fleet_executor import (
        FleetExecutor, rendezvous_endpoints)

    dist.init_parallel_env()
    rank = dist.get_rank()
    # in-stage SPMD over THIS controller's local devices only
    local = Mesh(np.array(jax.local_devices()).reshape(2, 2),
                 ("dp", "tp"))

    def constrain(h):
        return jax.lax.with_sharding_constraint(
            h, NamedSharding(local, P("dp", "tp")))

    if rank == 0:
        def stage(params, x):
            return jnp.maximum(constrain(x @ params["w"]), 0.0)
    else:
        def stage(params, x, label):
            pred = constrain(x @ params["w"])
            return jnp.mean(jnp.square(pred - label))

    store = native.TCPStore("127.0.0.1", store_port,
                            is_master=(rank == 0), timeout=60.0)
    ep, peers = rendezvous_endpoints(store, rank, 2)
    fe = FleetExecutor(stage, rank, 2, ep, peers, schedule="1f1b")
    try:
        x, y = _fe_data()
        params = _fe_params(rank)
        grads, loss = fe.run(
            params,
            microbatches=list(x) if rank == 0 else None,
            labels=list(y) if rank == 1 else None,
            n_micro=FE_MICRO)
        rec = {"grad_w_sum": float(np.asarray(grads["w"]).sum())}
        if loss is not None:
            rec["loss"] = float(loss)
        with open(os.path.join(tmpdir, f"fe_{rank}.json"), "w") as f:
            json.dump(rec, f)
    finally:
        fe.close()
