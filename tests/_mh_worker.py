"""Spawned-worker module for test_multihost. Pins the CPU platform at
MODULE level: multiprocessing's spawn start-method unpickles the target
function by importing this module, so these lines run before any jax
backend can initialize (two workers must not both claim the single
tunneled TPU)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def worker(tmpdir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    # federate the per-process CPU devices into one global backend
    # (cross-process CPU collectives run over gloo; on TPU pods the ICI/
    # DCN fabric takes this role and no flag is needed). One device per
    # process — conftest's xla_force_host_platform_device_count=8 leaks
    # into spawned children through the environment.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.config.update("jax_num_cpu_devices", 1)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    import paddle_tpu.distributed as dist

    dist.init_parallel_env()      # PT_* env → jax.distributed.initialize
    rank = dist.get_rank()
    world = jax.process_count()
    assert world == 2, world
    devices = jax.devices()       # global view: one device per process
    assert len(devices) == 2

    mesh = Mesh(np.array(devices), ("dp",))

    # cross-process psum through shard_map (the NCCL-allreduce analog on
    # the DCN plane)
    @jax.jit
    def allreduce(x):
        return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                         in_specs=P("dp"), out_specs=P("dp"))(x)

    local = jnp.full((1, 4), float(rank + 1))
    glob = jax.make_array_from_single_device_arrays(
        (2, 4), NamedSharding(mesh, P("dp")),
        [jax.device_put(local, devices[rank])])
    out = allreduce(glob)
    got = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(got, np.full((1, 4), 3.0))  # 1 + 2

    # cross-process pipeline tick: roll(+1) as collective-permute BETWEEN
    # THE TWO PROCESSES — the PP-over-DCN mechanism (≙ FleetExecutor's
    # cross-rank interceptor sends)
    @jax.jit
    def ring_shift(x):
        return shard_map(
            lambda v: jax.lax.ppermute(
                v, "dp", perm=[(i, (i + 1) % 2) for i in range(2)]),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)

    shifted = ring_shift(glob)
    got = np.asarray(shifted.addressable_shards[0].data)
    expect = np.full((1, 4), float(((rank - 1) % 2) + 1))
    np.testing.assert_allclose(got, expect)

    with open(os.path.join(tmpdir, f"ok_{rank}"), "w") as f:
        f.write("1")
