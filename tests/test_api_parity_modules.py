"""Top-level API-parity modules: device, reader/batch, legacy dataset,
utils, sysconfig, regularizer, distribution transforms, geometric
reindex/sampling (ref modules of the same names; reindex example is the
reference docstring's own)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import distribution as D
from paddle_tpu import geometric as G


class TestDevice:
    def test_queries(self):
        assert pt.device.is_compiled_with_tpu()
        assert not pt.device.is_compiled_with_cuda()
        assert pt.device.device_count() >= 1
        assert ":" in pt.device.get_device()
        assert "cpu" in pt.device.get_all_device_type()
        pt.device.synchronize()

    def test_set_device_errors_on_unknown(self):
        with pytest.raises(ValueError):
            pt.device.set_device("npu")

    def test_event_stream_api(self):
        e1, e2 = pt.device.cuda.Event(), pt.device.cuda.Event()
        e1.record()
        e2.record()
        assert e1.elapsed_time(e2) >= 0
        s = pt.device.cuda.current_stream()
        s.synchronize()
        s.record_event()
        assert pt.device.cuda.memory_allocated() >= 0
        pt.device.cuda.empty_cache()


class TestReader:
    def test_batch_and_decorators(self):
        b = pt.batch(lambda: iter(range(10)), 3)
        sizes = [len(x) for x in b()]
        assert sizes == [3, 3, 3, 1]
        b2 = pt.batch(lambda: iter(range(10)), 3, drop_last=True)
        assert [len(x) for x in b2()] == [3, 3, 3]
        assert list(pt.reader.firstn(lambda: iter(range(9)), 4)()) \
            == [0, 1, 2, 3]
        assert sorted(pt.reader.shuffle(lambda: iter(range(6)), 3)()) \
            == list(range(6))
        assert list(pt.reader.chain(lambda: iter([1]),
                                    lambda: iter([2]))()) == [1, 2]
        assert list(pt.reader.buffered(lambda: iter([1, 2, 3]), 2)()) \
            == [1, 2, 3]
        got = list(pt.reader.xmap_readers(lambda v: v + 1,
                                          lambda: iter([1, 2]), 2, 2)())
        assert got == [2, 3]


class TestLegacyDataset:
    def test_schemas(self):
        x, y = next(pt.dataset.uci_housing.train()())
        assert x.shape == (13,) and y.shape == (1,)
        img, label = next(pt.dataset.cifar.train()())
        assert img.shape == (3072,) and 0 <= label < 10
        img, label = next(pt.dataset.cifar.test100()())
        assert 0 <= label < 100
        words, lab = next(pt.dataset.imdb.train()())
        assert isinstance(words, list) and lab in (0, 1)
        gram = next(pt.dataset.imikolov.train()())
        assert len(gram) == 5
        rec = next(pt.dataset.movielens.train()())
        assert len(rec) == 7
        src, tin, tout = next(pt.dataset.wmt16.train()())
        assert len(tin) == len(tout)
        img, seg = next(pt.dataset.voc2012.train()())
        assert seg.shape == (32, 32)

    def test_composes_with_reader(self):
        b = pt.batch(pt.dataset.uci_housing.train(), 32)
        first = next(b())
        assert len(first) == 32

    def test_deterministic(self):
        a = list(pt.dataset.uci_housing.test()())
        b = list(pt.dataset.uci_housing.test()())
        np.testing.assert_array_equal(a[0][0], b[0][0])


class TestUtils:
    def test_unique_name_and_guard(self):
        with pt.utils.unique_name.guard("g_"):
            assert pt.utils.unique_name.generate("w") == "g_w_0"
            assert pt.utils.unique_name.generate("w") == "g_w_1"

    def test_deprecated_warns(self):
        @pt.utils.deprecated(update_to="new_fn", since="2.0")
        def old_fn():
            return 7
        with pytest.warns(DeprecationWarning):
            assert old_fn() == 7

    def test_try_import(self):
        assert pt.utils.try_import("math").sqrt(4) == 2
        with pytest.raises(ImportError):
            pt.utils.try_import("definitely_not_a_module_xyz")

    def test_require_version_and_sysconfig(self):
        assert pt.utils.require_version("0.0.1")
        with pytest.raises(RuntimeError):
            pt.utils.require_version("99.0")
        assert pt.sysconfig.get_lib().endswith("native")

    def test_download_gated(self):
        with pytest.raises(RuntimeError):
            pt.utils.download("https://example.com/x.tgz")


class TestRegularizer:
    def test_l1_l2_in_optimizer(self):
        from paddle_tpu import optimizer as optim, regularizer
        params = {"w": jnp.asarray([2.0, -2.0])}
        opt = optim.SGD(learning_rate=1.0,
                        weight_decay=regularizer.L1Decay(0.5))
        new_p, _ = opt.update({"w": jnp.zeros(2)}, opt.init(params), params)
        np.testing.assert_allclose(new_p["w"], [1.5, -1.5])
        opt2 = optim.SGD(learning_rate=1.0,
                         weight_decay=regularizer.L2Decay(0.1))
        new_p2, _ = opt2.update({"w": jnp.zeros(2)}, opt2.init(params),
                                params)
        np.testing.assert_allclose(new_p2["w"], [1.8, -1.8])


class TestDistributionTransforms:
    def test_exp_transform_equals_lognormal(self):
        td = D.TransformedDistribution(D.Normal(0.0, 1.0),
                                       [D.ExpTransform()])
        y = jnp.asarray([0.5, 1.0, 2.0])
        np.testing.assert_allclose(td.log_prob(y),
                                   D.LogNormal(0.0, 1.0).log_prob(y),
                                   atol=1e-5)

    def test_chain_and_affine(self):
        t = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                              D.TanhTransform()])
        x = jnp.asarray([0.1, -0.3])
        np.testing.assert_allclose(t.inverse(t.forward(x)), x, atol=1e-5)

    def test_stickbreaking_simplex(self):
        sb = D.StickBreakingTransform()
        x = jnp.asarray([[0.4, -1.0, 0.2]])
        y = sb.forward(x)
        np.testing.assert_allclose(np.asarray(y).sum(-1), 1.0, atol=1e-6)
        np.testing.assert_allclose(sb.inverse(y), x, atol=1e-4)

    def test_independent_sums_event_dims(self):
        ind = D.Independent(D.Normal(jnp.zeros(3), jnp.ones(3)), 1)
        lp = ind.log_prob(jnp.zeros((5, 3)))
        assert lp.shape == (5,)
        np.testing.assert_allclose(
            lp, 3 * D.Normal(0.0, 1.0).log_prob(jnp.zeros(())), atol=1e-5)

    def test_sigmoid_power_logdet(self):
        for t in (D.SigmoidTransform(), D.PowerTransform(2.0),
                  D.ExpTransform()):
            x = jnp.asarray([0.5, 1.5])
            import jax
            num = jnp.log(jnp.abs(jax.vmap(jax.grad(
                lambda v: t.forward(v)))(x)))
            np.testing.assert_allclose(t.forward_log_det_jacobian(x), num,
                                       atol=1e-4)


class TestGeometric:
    def test_reindex_reference_example(self):
        src, dst, out = G.reindex_graph(
            np.array([0, 1, 2]), np.array([8, 9, 0, 4, 7, 6, 7]),
            np.array([2, 3, 2]))
        np.testing.assert_array_equal(src, [3, 4, 0, 5, 6, 7, 6])
        np.testing.assert_array_equal(dst, [0, 0, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(out, [0, 1, 2, 8, 9, 4, 7, 6])

    def test_sample_neighbors_csc(self):
        row = np.array([1, 2, 0, 0, 1])
        colptr = np.array([0, 2, 3, 5])
        nb, cnt = G.sample_neighbors(row, colptr, np.array([0]),
                                     sample_size=-1)
        np.testing.assert_array_equal(nb, [1, 2])
        nb, cnt, eids = G.sample_neighbors(
            row, colptr, np.array([2]), sample_size=1,
            eids=np.arange(5), return_eids=True)
        assert len(nb) == 1 and int(eids[0]) in (3, 4)

    def test_heter_reindex_shares_numbering(self):
        srcs, dsts, out = G.reindex_heter_graph(
            np.array([0, 1]), [np.array([5, 6]), np.array([6, 7])],
            [np.array([1, 1]), np.array([1, 1])])
        # node 6 appears in both edge types → same renumbered id
        assert int(srcs[0][1]) == int(srcs[1][0])
        assert len(out) == 5


class TestReviewRegressions:
    def test_adamw_with_regularizer_object(self):
        from paddle_tpu import optimizer as optim, regularizer
        params = {"w": jnp.asarray([2.0, -2.0])}
        opt = optim.AdamW(learning_rate=0.0,
                          weight_decay=regularizer.L2Decay(0.5))
        st = opt.init(params)
        new_p, _ = opt.update({"w": jnp.zeros(2)}, st, params)
        # lr=0 → adam update is 0, decay term too (decoupled scales by lr)
        np.testing.assert_allclose(new_p["w"], [2.0, -2.0])
        opt2 = optim.AdamW(learning_rate=1.0, beta1=0.0, beta2=0.0,
                           weight_decay=regularizer.L2Decay(0.25))
        new_p2, _ = opt2.update({"w": jnp.zeros(2)}, opt2.init(params),
                                params)
        # zero grads → pure decoupled decay: p - lr*coeff*p
        np.testing.assert_allclose(new_p2["w"], [1.5, -1.5], atol=1e-6)

    def test_compose_detects_mismatch_both_orders(self):
        long_r = lambda: iter([1, 2, 3])  # noqa: E731
        short_r = lambda: iter([10, 20])  # noqa: E731
        for a, b in ((long_r, short_r), (short_r, long_r)):
            with pytest.raises(ValueError):
                list(pt.reader.compose(a, b)())
        ok = list(pt.reader.compose(short_r, short_r)())
        assert ok == [(10, 10), (20, 20)]
        # None is a legal sample, not an end marker
        none_r = lambda: iter([None, None])  # noqa: E731
        assert len(list(pt.reader.compose(none_r, short_r)())) == 2

    def test_sample_neighbors_empty_nodes_with_eids(self):
        row = np.array([1, 2, 0])
        colptr = np.array([0, 2, 3, 3])
        nb, cnt, eids = G.sample_neighbors(
            row, colptr, np.array([], np.int32), eids=np.arange(3),
            return_eids=True)
        assert len(nb) == 0 and len(cnt) == 0 and len(eids) == 0

    def test_sparse_softmax_rejects_other_axis(self):
        from paddle_tpu import sparse as S
        x = S.sparse_coo_tensor(np.array([[0, 1], [0, 1]]),
                                np.ones(2, np.float32), (2, 2))
        with pytest.raises(NotImplementedError):
            S.nn.functional.softmax(x, axis=0)


class TestASP:
    def test_decorate_keeps_24_sparsity(self):
        from paddle_tpu import incubate, optimizer as optim
        from paddle_tpu import nn
        net = nn.Linear(8, 8).tag_paths()
        net = incubate.asp.prune_model(net)
        params, _ = net.split_params()
        assert incubate.asp.calculate_density(params["weight"]) <= 0.5 + 1e-6
        opt = incubate.asp.decorate(optim.SGD(learning_rate=0.1))
        st = opt.init(params)
        grads = {k: jnp.ones_like(v) for k, v in params.items()}
        new_p, st = opt.update(grads, st, params)
        # mask survives the update: still exactly 2-of-4 per group
        assert incubate.asp.check_mask_2d(np.asarray(new_p["weight"]) != 0)
        # bias (1-D) updated freely
        assert float(np.abs(np.asarray(new_p["bias"])).sum()) > 0

    def test_excluded_layers(self):
        from paddle_tpu import incubate
        from paddle_tpu import nn
        net = nn.Linear(4, 4).tag_paths()
        incubate.asp.set_excluded_layers(["weight"])
        try:
            pruned = incubate.asp.prune_model(net)
            d = incubate.asp.calculate_density(
                pruned.split_params()[0]["weight"])
            assert d == 1.0  # excluded → untouched
        finally:
            incubate.asp.reset_excluded_layers()


class TestCostModel:
    def test_static_and_measured(self):
        import jax
        from paddle_tpu.cost_model import CostModel
        cm = CostModel()

        def f(a, b):
            return a @ b

        x = jnp.ones((256, 256))
        data = cm.static_cost_data(f, x, x)
        assert data.get("flops", 0) >= 2 * 256**3 * 0.9
        t_static = cm.get_static_op_time(f, x, x)
        assert t_static > 0
        t_bwd = cm.get_static_op_time(f, x, x, forward=False)
        assert t_bwd > t_static
        t_real = cm.profile_measure(f, x, x)
        assert t_real > 0


class TestIncubateOptimizer:
    def test_lookahead_sync_every_k(self):
        from paddle_tpu import optimizer as optim
        from paddle_tpu.incubate.optimizer import LookAhead
        la = LookAhead(optim.SGD(learning_rate=1.0), alpha=0.5, k=2)
        params = {"w": jnp.asarray([0.0])}
        st = la.init(params)
        g = {"w": jnp.asarray([-1.0])}        # fast moves +1 per step
        p1, st = la.update(g, st, params)     # fast=1, no sync
        np.testing.assert_allclose(p1["w"], [1.0])
        p2, st = la.update(g, st, p1)         # fast=2 → sync: slow=1, fast=1
        np.testing.assert_allclose(p2["w"], [1.0])
        np.testing.assert_allclose(st["slow"]["w"], [1.0])

    def test_model_average(self):
        from paddle_tpu.incubate.optimizer import ModelAverage
        ma = ModelAverage()
        params = {"w": jnp.asarray([1.0])}
        st = ma.init(params)
        for v in (1.0, 2.0, 3.0):
            st = ma.accumulate(st, {"w": jnp.asarray([v])})
        avg = ma.apply(st, params)
        np.testing.assert_allclose(avg["w"], [2.0])

    def test_new_initializers(self):
        from paddle_tpu.nn import initializer as I
        w = I.Dirac()((4, 4, 3, 3))
        # identity-preserving: center tap of channel i→i is 1
        assert float(w[0, 0, 1, 1]) == 1.0 and float(jnp.sum(w)) == 4.0
        b = I.Bilinear()((2, 2, 4, 4))
        assert float(jnp.max(b)) <= 1.0 and float(jnp.sum(b)) > 0
        import math
        assert abs(I.calculate_gain("relu") - math.sqrt(2)) < 1e-9
        assert I.calculate_gain("tanh") == 5.0 / 3.0

    def test_model_average_window_rotation(self):
        from paddle_tpu.incubate.optimizer import ModelAverage
        ma = ModelAverage(average_window_rate=1.0, min_average_window=2,
                          max_average_window=3)
        params = {"w": jnp.asarray([0.0])}
        st = ma.init(params)
        for v in (1.0, 2.0, 3.0, 4.0, 10.0):
            st = ma.accumulate(st, {"w": jnp.asarray([v])})
        # block rotation at W=3: prev block {1,2,3} (sum 6, n 3), current
        # block {4,10} (sum 14, n 2) → (6+14)/5 = 4.0 exactly. The old
        # reset-on-overflow code gives 7.0, so the exact value pins the
        # rotation semantics.
        avg = float(ma.apply(st, params)["w"][0])
        np.testing.assert_allclose(avg, 4.0)


class TestSavedTensorsHooks:
    def test_pack_unpack_roundtrip_through_pylayer(self):
        import jax
        from paddle_tpu import autograd

        calls = {"pack": 0, "unpack": 0}

        def pack(t):
            calls["pack"] += 1
            return np.asarray(t)          # "offload" to host

        def unpack(t):
            calls["unpack"] += 1
            return jnp.asarray(t)

        class Cube(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x ** 3

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                return 3 * x ** 2 * dy

        with autograd.saved_tensors_hooks(pack, unpack):
            g = jax.grad(lambda x: Cube.apply(x).sum())(jnp.asarray([2.0]))
        np.testing.assert_allclose(g, [12.0])
        assert calls["pack"] >= 1 and calls["unpack"] >= 1
        # outside the context hooks are inactive
        g2 = jax.grad(lambda x: Cube.apply(x).sum())(jnp.asarray([2.0]))
        np.testing.assert_allclose(g2, [12.0])

    def test_pylayer_plain_grad_and_extra(self):
        """PyLayer residuals must be jax types (ctx object never crosses
        the custom_vjp boundary) — this was latent-broken and untested."""
        import jax
        from paddle_tpu import autograd

        class Scale(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x, factor):
                ctx.save_for_backward(x)
                ctx.extra["factor"] = 2.0
                return x * factor

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                f = ctx.extra["factor"]
                return dy * f, jnp.zeros(())

        g = jax.grad(lambda x: Scale.apply(x, jnp.asarray(2.0)).sum())(
            jnp.asarray([1.0, 1.0]))
        np.testing.assert_allclose(g, [2.0, 2.0])

    def test_pylayer_multiple_applications_distinct_metadata(self):
        """review r3: two applications in ONE grad must each see their
        own ctx.extra (a single class cell handed both the last one)."""
        import jax
        from paddle_tpu import autograd

        class Mul(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x, k):
                ctx.extra["k"] = float(k)
                return x * k

            @staticmethod
            def backward(ctx, dy):
                return dy * ctx.extra["k"], jnp.zeros(())

        def f(x):
            return (Mul.apply(x, jnp.asarray(2.0))
                    + Mul.apply(x, jnp.asarray(5.0))).sum()

        g = jax.grad(f)(jnp.asarray([1.0]))
        np.testing.assert_allclose(g, [7.0])

    def test_pylayer_out_of_order_pullbacks(self):
        """review r3: pullbacks invoked in NON-LIFO order must still pair
        with their own application's metadata (static-aux residual id)."""
        import jax
        from paddle_tpu import autograd

        class Mul(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x, k):
                ctx.extra["k"] = float(k)
                return x * k

            @staticmethod
            def backward(ctx, dy):
                return dy * ctx.extra["k"], jnp.zeros(())

        x = jnp.asarray([1.0])
        _, pb1 = jax.vjp(lambda v: Mul.apply(v, jnp.asarray(2.0)), x)
        _, pb2 = jax.vjp(lambda v: Mul.apply(v, jnp.asarray(5.0)), x)
        g1 = pb1(jnp.asarray([1.0]))[0]     # called FIRST-created first
        g2 = pb2(jnp.asarray([1.0]))[0]
        np.testing.assert_allclose(g1, [2.0])
        np.testing.assert_allclose(g2, [5.0])

    def test_pylayer_jit_primal_with_hooks(self):
        """review r3: pack hooks must not run in the undifferentiated
        primal path (np.asarray on a tracer would crash jit)."""
        import jax
        from paddle_tpu import autograd

        class Sq(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x ** 2

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor
                return 2 * x * dy

        with autograd.saved_tensors_hooks(np.asarray, jnp.asarray):
            out = jax.jit(Sq.apply)(jnp.asarray([3.0]))
        np.testing.assert_allclose(out, [9.0])

    def test_pylayer_pullback_called_twice(self):
        """review r3: re-invoking the same pullback must work (metadata
        is read, not consumed)."""
        import jax
        from paddle_tpu import autograd

        class Mul(autograd.PyLayer):
            @staticmethod
            def forward(ctx, x, k):
                ctx.extra["k"] = float(k)
                return x * k

            @staticmethod
            def backward(ctx, dy):
                return dy * ctx.extra["k"], jnp.zeros(())

        _, pb = jax.vjp(lambda v: Mul.apply(v, jnp.asarray(3.0)),
                        jnp.asarray([1.0]))
        np.testing.assert_allclose(pb(jnp.asarray([1.0]))[0], [3.0])
        np.testing.assert_allclose(pb(jnp.asarray([2.0]))[0], [6.0])
