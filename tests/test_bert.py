"""BERT family (BASELINE.md "ERNIE-3.0 / BERT-base finetune" row;
VERDICT r1 item 3)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.models import bert


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_lib.set_topology(None)


def _mlm_batch(cfg, b=4, s=32, seed=0):
    rs = np.random.RandomState(seed)
    tokens = rs.randint(4, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = np.full((b, s), -100, np.int32)
    mask_pos = rs.rand(b, s) < 0.15
    labels[mask_pos] = tokens[mask_pos]
    tokens[mask_pos] = 3  # [MASK]
    type_ids = np.zeros((b, s), np.int32)
    nsp = rs.randint(0, 2, (b,)).astype(np.int32)
    return (jnp.asarray(tokens), jnp.asarray(type_ids),
            jnp.ones((b, s), jnp.int32), jnp.asarray(labels),
            jnp.asarray(nsp))


def test_pretrain_step_decreases_loss():
    cfg = bert.bert_tiny()
    model = bert.BertForPretraining(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    params, opt_state = bert.init_train_state(model, opt)
    step = bert.build_pretrain_step(model, opt)
    batch = _mlm_batch(cfg)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, *batch, rng)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_attention_mask_blocks_padding():
    """Padding positions must not influence other positions' outputs."""
    cfg = bert.bert_tiny()
    model = bert.Bert(cfg, seed=0)
    rs = np.random.RandomState(1)
    toks = rs.randint(4, cfg.vocab_size, (1, 8)).astype(np.int32)
    full = jnp.asarray(np.concatenate(
        [toks, rs.randint(4, cfg.vocab_size, (1, 4)).astype(np.int32)], 1))
    mask = jnp.asarray([[1] * 8 + [0] * 4], jnp.int32)
    seq_masked, _ = model(full, attention_mask=mask)
    # garbage in the padding positions must not change the first 8 outputs
    full2 = full.at[:, 8:].set(5)
    seq_masked2, _ = model(full2, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(seq_masked[:, :8]),
                               np.asarray(seq_masked2[:, :8]),
                               rtol=1e-5, atol=1e-5)


def test_finetune_classification_converges():
    """e2e finetune: tiny BERT + classification head separates a synthetic
    token-presence task."""
    cfg = bert.bert_tiny()
    model = bert.BertForSequenceClassification(cfg, num_classes=2, seed=0)
    opt = optim.AdamW(learning_rate=2e-3)
    params, opt_state = bert.init_train_state(model, opt)

    def step(params, opt_state, toks, labels):
        def loss_fn(p):
            logits = model.merge_params(p)(toks)
            from paddle_tpu.nn import functional as F
            return F.cross_entropy(logits.astype(jnp.float32), labels)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(grads, opt_state, params)
        return params, opt_state, loss

    step = jax.jit(step)
    rs = np.random.RandomState(0)
    toks = rs.randint(4, cfg.vocab_size, (32, 16)).astype(np.int32)
    labels = (rs.rand(32) < 0.5).astype(np.int32)
    toks[labels == 1, 0] = 7  # class signal in [CLS]-adjacent position
    toks, labels = jnp.asarray(toks), jnp.asarray(labels)
    losses = []
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state, toks, labels)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])
    logits = model.merge_params(params)(toks)
    acc = float(jnp.mean((jnp.argmax(logits, -1) == labels)))
    assert acc > 0.9, acc


def test_tp_sharded_pretrain_matches_dense():
    cfg = bert.bert_tiny()
    model = bert.BertForPretraining(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-3)
    batch = _mlm_batch(cfg)
    rng = jax.random.PRNGKey(0)

    params_d, opt_d = bert.init_train_state(model, opt)
    step_d = bert.build_pretrain_step(model, opt, donate=False)
    _, _, loss_d = step_d(params_d, opt_d, *batch, rng)

    topo = dist.init_mesh(dp=2, tp=2, fsdp=2)
    params_t, opt_t = bert.init_train_state(model, opt, topo.mesh)
    step_t = bert.build_pretrain_step(model, opt, topo.mesh, donate=False)
    _, _, loss_t = step_t(params_t, opt_t, *batch, rng)
    np.testing.assert_allclose(float(loss_t), float(loss_d), rtol=2e-5,
                               atol=2e-5)


def test_gathered_mlm_matches_full_loss():
    """max_predictions gathering must not change the pretrain loss when the
    cap covers every masked position (VERDICT r3: BERT MFU via masked-
    position vocab head)."""
    import numpy as np
    from paddle_tpu import optimizer as optim

    cfg = bert.BertConfig(vocab_size=128, d_model=32, n_layers=2,
                          n_heads=2, max_position=32, dropout=0.0,
                          dtype=jnp.float32)
    model = bert.BertForPretraining(cfg, seed=0)
    opt = optim.SGD(learning_rate=0.0)
    params, opt_state = bert.init_train_state(model, opt)
    b, s = 4, 32
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(0, 128, (b, s)), jnp.int32)
    types = jnp.zeros((b, s), jnp.int32)
    attn = jnp.ones((b, s), jnp.int32)
    labels = jnp.asarray(
        np.where(rs.rand(b, s) < 0.2, rs.randint(0, 128, (b, s)), -100),
        jnp.int32)
    nsp = jnp.asarray(rs.randint(0, 2, (b,)), jnp.int32)
    rng = jax.random.PRNGKey(0)

    full = bert.build_pretrain_step(model, opt, donate=False)
    gathered = bert.build_pretrain_step(model, opt, donate=False,
                                        max_predictions=16)
    _, _, loss_full = full(params, opt_state, tokens, types, attn,
                           labels, nsp, rng)
    _, _, loss_g = gathered(params, opt_state, tokens, types, attn,
                            labels, nsp, rng)
    assert int((np.asarray(labels) != -100).sum(axis=1).max()) <= 16
    np.testing.assert_allclose(float(loss_g), float(loss_full), rtol=1e-5)
