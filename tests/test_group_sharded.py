"""GroupSharded/ZeRO stage 1-3 tests on the 8-virtual-device CPU mesh.

Oracle = single-device training with the identical optimizer (the
reference's pattern: TestDistBase asserts multi-rank losses match the
single-process run, test_dist_base.py:901)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel, group_sharded_specs)
from paddle_tpu.models import gpt


def _setup(level, steps=3, clip=None):
    topo = dist.init_mesh(dp=2, fsdp=4)
    mesh = topo.mesh
    cfg = gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 32)), jnp.int32)

    def loss_fn(p, tok):
        return gpt.lm_loss(model.merge_params(p)(tok), tok)

    def make_opt():
        return optim.AdamW(learning_rate=1e-2, weight_decay=0.01,
                           grad_clip=clip)

    sp, st, step = group_sharded_parallel(
        params, make_opt(), loss_fn, mesh, level=level,
        rules=gpt.partition_spec)
    losses = []
    for _ in range(steps):
        sp, st, loss = step(sp, st, tokens)
        losses.append(float(loss))

    # single-device oracle
    from paddle_tpu.distributed import mesh as mesh_lib
    mesh_lib.set_topology(None)
    opt = make_opt()
    p1 = {k: jnp.copy(v) for k, v in model.split_params()[0].items()}
    s1 = opt.init(p1)
    ref_losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_fn)(p1, tokens)
        p1, s1 = opt.update(grads, s1, p1)
        ref_losses.append(float(loss))
    return sp, st, losses, ref_losses, mesh


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_matches_single_device(level):
    _, _, losses, ref, _ = _setup(level)
    np.testing.assert_allclose(losses, ref, atol=1e-4, rtol=1e-4)


def test_global_norm_clip_matches_single_device():
    """≙ HybridParallelClipGrad: global-norm clip across sharded grads."""
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm
    _, _, losses, ref, _ = _setup("p_g_os",
                                  clip=ClipGradByGlobalNorm(0.05))
    np.testing.assert_allclose(losses, ref, atol=1e-4, rtol=1e-4)


def test_stage_sharding_policies():
    topo = dist.init_mesh(fsdp=8)
    mesh = topo.mesh
    cfg = gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()

    for level, p_has_fsdp in (("os", False), ("os_g", False),
                              ("p_g_os", True)):
        specs = group_sharded_specs(params, mesh, level=level,
                                    rules=gpt.partition_spec)
        wqkv_p = specs.param["blocks.item_0.wqkv"]
        wqkv_o = specs.opt_slot["blocks.item_0.wqkv"]
        flat_p = [a for e in wqkv_p if e
                  for a in (e if isinstance(e, tuple) else (e,))]
        flat_o = [a for e in wqkv_o if e
                  for a in (e if isinstance(e, tuple) else (e,))]
        assert ("fsdp" in flat_p) == p_has_fsdp, (level, wqkv_p)
        assert "fsdp" in flat_o, (level, wqkv_o)


def test_opt_state_is_physically_sharded():
    """Stage 1: params replicated but each device holds 1/8 of the slots."""
    topo = dist.init_mesh(fsdp=8)
    cfg = gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()

    def loss_fn(p, tok):
        return gpt.lm_loss(model.merge_params(p)(tok), tok)

    sp, st, _ = group_sharded_parallel(
        params, optim.Adam(learning_rate=1e-3), loss_fn, topo.mesh,
        level="os", rules=gpt.partition_spec)
    m_slot = st["slots"]["blocks.item_0.wqkv"][0]
    local = m_slot.addressable_shards[0].data.size
    assert local * 8 == m_slot.size, (local, m_slot.size)
    # params replicated: every device holds the full array
    wqkv = sp["blocks.item_0.wqkv"]
    assert wqkv.addressable_shards[0].data.size == wqkv.size


def test_ensure_axis_spreads_small_params():
    topo = dist.init_mesh(fsdp=8)
    cfg = gpt.gpt_tiny(max_seq_len=32, d_model=64, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()
    specs = group_sharded_specs(params, topo.mesh, level="os",
                                rules=gpt.partition_spec)
    # ln scales are P(None) in the base rules but (64,) is divisible by 8
    assert specs.opt_slot["blocks.item_0.ln1_scale"] == P("fsdp")


def test_bad_level_raises():
    topo = dist.init_mesh(fsdp=8)
    with pytest.raises(ValueError, match="level"):
        group_sharded_specs({}, topo.mesh, level="zero9")
