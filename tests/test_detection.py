"""PP-YOLOE-style detector e2e on synthetic COCO-shaped data (VERDICT r2
item 10 / BASELINE row 5): one jitted static-shape train step over padded
ground truth, loss decreases, inference postprocess returns boxes."""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as optim
from paddle_tpu.vision.models import ppyoloe


def _synthetic_coco(b=2, img=64, max_boxes=4, classes=6, seed=0):
    """Padded COCO-shaped batch: big axis-aligned colored rectangles whose
    class == color bucket, so the loss is actually learnable."""
    rs = np.random.RandomState(seed)
    images = rs.rand(b, 3, img, img).astype(np.float32) * 0.1
    boxes = np.zeros((b, max_boxes, 4), np.float32)
    labels = np.zeros((b, max_boxes), np.int32)
    valid = np.zeros((b, max_boxes), bool)
    for i in range(b):
        n = rs.randint(1, max_boxes + 1)
        for j in range(n):
            w, h = rs.randint(16, 40, 2)
            x1 = rs.randint(0, img - w)
            y1 = rs.randint(0, img - h)
            c = rs.randint(0, classes)
            images[i, c % 3, y1:y1 + h, x1:x1 + w] += 0.8
            boxes[i, j] = (x1, y1, x1 + w, y1 + h)
            labels[i, j] = c
            valid[i, j] = True
    return (jnp.asarray(images), jnp.asarray(boxes), jnp.asarray(labels),
            jnp.asarray(valid))


def test_assignment_masks_padded_gt():
    model = ppyoloe.ppyoloe_s(num_classes=6)
    images, boxes, labels, valid = _synthetic_coco()
    cls, reg, centers, strides = model.tag_paths()(images)
    a = centers.shape[0]
    assert cls.shape == (2, a, 6) and reg.shape == (2, a, 4, 17)
    assigned, pos = ppyoloe._assign(centers, strides, boxes[0], valid[0])
    # padded gt slots never assigned
    n_valid = int(valid[0].sum())
    assert set(np.unique(np.asarray(assigned[np.asarray(pos)]))) <= \
        set(range(n_valid))
    # no-gt image: nothing positive
    _, pos_none = ppyoloe._assign(centers, strides, boxes[0],
                                  jnp.zeros_like(valid[0]))
    assert not bool(pos_none.any())


def test_detection_trains_on_synthetic_coco():
    model = ppyoloe.ppyoloe_s(num_classes=6).tag_paths()
    opt = optim.AdamW(learning_rate=2e-3)
    params, buffers = model.split_params()
    opt_state = opt.init(params)
    step = ppyoloe.build_train_step(model, opt)
    images, boxes, labels, valid = _synthetic_coco()
    key = jax.random.PRNGKey(0)
    losses = []
    for i in range(12):
        params, opt_state, updates, loss, parts = step(
            params, buffers, opt_state, images, boxes, labels, valid,
            jax.random.fold_in(key, i))
        buffers = {**buffers, **updates}
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses
    assert float(parts["n_pos"]) > 0


def test_decode_predictions_shape():
    model = ppyoloe.ppyoloe_s(num_classes=6).tag_paths().eval()
    images, *_ = _synthetic_coco()
    cls, reg, centers, strides = model(images)
    dets = ppyoloe.decode_predictions(cls, reg, centers, strides,
                                      score_thresh=0.0, top_k=10)
    assert len(dets) == 2
    for d in dets:
        assert d["boxes"].shape[1] == 4
        assert len(d["scores"]) == len(d["labels"]) == len(d["boxes"])
        assert len(d["boxes"]) <= 10
