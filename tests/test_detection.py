"""PP-YOLOE-style detector e2e on synthetic COCO-shaped data (VERDICT r2
item 10 / BASELINE row 5): one jitted static-shape train step over padded
ground truth, loss decreases, inference postprocess returns boxes."""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as optim
from paddle_tpu.vision.models import ppyoloe


def _synthetic_coco(b=2, img=64, max_boxes=4, classes=6, seed=0):
    """Padded COCO-shaped batch: big axis-aligned colored rectangles whose
    class == color bucket, so the loss is actually learnable."""
    rs = np.random.RandomState(seed)
    images = rs.rand(b, 3, img, img).astype(np.float32) * 0.1
    boxes = np.zeros((b, max_boxes, 4), np.float32)
    labels = np.zeros((b, max_boxes), np.int32)
    valid = np.zeros((b, max_boxes), bool)
    for i in range(b):
        n = rs.randint(1, max_boxes + 1)
        for j in range(n):
            w, h = rs.randint(16, 40, 2)
            x1 = rs.randint(0, img - w)
            y1 = rs.randint(0, img - h)
            c = rs.randint(0, classes)
            images[i, c % 3, y1:y1 + h, x1:x1 + w] += 0.8
            boxes[i, j] = (x1, y1, x1 + w, y1 + h)
            labels[i, j] = c
            valid[i, j] = True
    return (jnp.asarray(images), jnp.asarray(boxes), jnp.asarray(labels),
            jnp.asarray(valid))


def test_assignment_masks_padded_gt():
    model = ppyoloe.ppyoloe_s(num_classes=6)
    images, boxes, labels, valid = _synthetic_coco()
    cls, reg, centers, strides = model.tag_paths()(images)
    a = centers.shape[0]
    assert cls.shape == (2, a, 6) and reg.shape == (2, a, 4, 17)
    assigned, pos = ppyoloe._assign(centers, strides, boxes[0], valid[0])
    # padded gt slots never assigned
    n_valid = int(valid[0].sum())
    assert set(np.unique(np.asarray(assigned[np.asarray(pos)]))) <= \
        set(range(n_valid))
    # no-gt image: nothing positive
    _, pos_none = ppyoloe._assign(centers, strides, boxes[0],
                                  jnp.zeros_like(valid[0]))
    assert not bool(pos_none.any())


def test_detection_trains_on_synthetic_coco():
    model = ppyoloe.ppyoloe_s(num_classes=6).tag_paths()
    opt = optim.AdamW(learning_rate=2e-3)
    params, buffers = model.split_params()
    opt_state = opt.init(params)
    step = ppyoloe.build_train_step(model, opt)
    images, boxes, labels, valid = _synthetic_coco()
    key = jax.random.PRNGKey(0)
    losses = []
    for i in range(12):
        params, opt_state, updates, loss, parts = step(
            params, buffers, opt_state, images, boxes, labels, valid,
            jax.random.fold_in(key, i))
        buffers = {**buffers, **updates}
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses
    assert float(parts["n_pos"]) > 0


def test_decode_predictions_shape():
    model = ppyoloe.ppyoloe_s(num_classes=6).tag_paths().eval()
    images, *_ = _synthetic_coco()
    cls, reg, centers, strides = model(images)
    dets = ppyoloe.decode_predictions(cls, reg, centers, strides,
                                      score_thresh=0.0, top_k=10)
    assert len(dets) == 2
    for d in dets:
        assert d["boxes"].shape[1] == 4
        assert len(d["scores"]) == len(d["labels"]) == len(d["boxes"])
        assert len(d["boxes"]) <= 10


def _synthetic_head(m=16, c=3, r=7):
    """Controllable head outputs: 4x4 grid of 32px-spaced centers, reg
    one-hot at bin 2 (16px distances at stride 8 => 32x32 boxes)."""
    grid = np.stack(np.meshgrid(np.arange(4), np.arange(4)),
                    -1).reshape(-1, 2).astype(np.float32) * 32 + 16
    centers = jnp.asarray(grid)
    strides = jnp.full((m,), 8.0)
    reg = np.full((1, m, 4, r + 1), -20.0, np.float32)
    reg[..., 2] = 20.0
    cls = np.full((1, m, c), -20.0, np.float32)
    return centers, strides, jnp.asarray(reg), cls


def test_decode_predictions_jit_matches_host_path():
    """VERDICT r4 item 7: the jit-safe matrix-NMS decode must keep the
    same detections as the host greedy path on separated boxes and kill
    an exact duplicate identically (IoU=1 -> linear decay 0)."""
    centers, strides, reg, cls = _synthetic_head()
    cls[0, 0, 0] = 10.0     # three clear, well-separated detections
    cls[0, 5, 1] = 10.0
    cls[0, 10, 2] = 10.0
    cls[0, 6, 1] = 8.0      # same class as anchor 5...
    centers = centers.at[6].set(centers[5])  # ...and the SAME box => dup
    cls = jnp.asarray(cls)

    host = ppyoloe.decode_predictions(cls, reg, centers, strides,
                                      score_thresh=0.3, iou_thresh=0.5,
                                      top_k=8)[0]
    jfn = jax.jit(functools.partial(
        ppyoloe.decode_predictions_jit, score_thresh=0.3,
        post_thresh=0.3, top_k=8, pre_nms=16))
    boxes, scores, labels, valid = jfn(cls, reg, centers, strides)
    nv = int(valid[0].sum())
    got = {(int(l), tuple(np.round(np.asarray(b), 3)))
           for l, b, v in zip(np.asarray(labels[0]), np.asarray(boxes[0]),
                              np.asarray(valid[0])) if v}
    want = {(int(l), tuple(np.round(np.asarray(b), 3)))
            for l, b in zip(host["labels"], host["boxes"])}
    assert got == want and nv == len(host["boxes"]) == 3
    # scores agree on the survivors (no decay among separated boxes)
    np.testing.assert_allclose(np.sort(np.asarray(scores[0])[:nv]),
                               np.sort(host["scores"]), rtol=1e-5)


def test_decode_predictions_jit_one_program():
    """Forward + decode must compile as ONE jitted program (the property
    the host path cannot have)."""
    model = ppyoloe.ppyoloe_s(num_classes=6).tag_paths().eval()
    images, *_ = _synthetic_coco()

    @jax.jit
    def eval_fn(im):
        cls, reg, centers, strides = model(im)
        return ppyoloe.decode_predictions_jit(cls, reg, centers, strides,
                                              score_thresh=0.0,
                                              post_thresh=0.0, top_k=10)

    boxes, scores, labels, valid = eval_fn(images)
    assert boxes.shape == (2, 10, 4) and scores.shape == (2, 10)
    assert labels.shape == (2, 10) and valid.shape == (2, 10)
    assert np.isfinite(np.asarray(boxes)).all()

    # (B, top_k) contract holds even when top_k exceeds the anchor count
    # (code-review regression: outputs used to shrink to min(top_k, M))
    centers, strides, reg, cls = _synthetic_head()
    big = ppyoloe.decode_predictions_jit(jnp.asarray(cls), reg, centers,
                                         strides, top_k=50, pre_nms=16)
    assert big[0].shape == (1, 50, 4) and big[1].shape == (1, 50)
    assert not bool(big[3][0, 16:].any())  # padded slots are invalid
