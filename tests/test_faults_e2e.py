"""End-to-end resilience under fault injection (ISSUE 2 acceptance):
a worker killed mid-training auto-resumes from the last VERIFIED
checkpoint via ``launch.py --max_restarts``; a corrupted newest
checkpoint falls back to the previous one with no manual intervention;
an interrupted save's orphan .tmp dir is GC'd on the resumed run.

Subprocess-driven through the real launcher (the reference's own test
pattern — test_parallel_dygraph_dataparallel.py shells out through the
launch CLI)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Training script under test: restore-or-init, train to epoch 6, verify
# the final state in-process. Fault rules (PT_FAULTS) are installed on
# the FIRST attempt only — relaunches run clean, so each test's recovery
# path is exercised exactly once and deterministically.
TRAIN_BODY = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax.numpy as jnp
from paddle_tpu.distributed.checkpoint import AutoCheckpoint
from paddle_tpu.testing import faults

attempt = int(os.environ.get("PT_RESTART_ATTEMPT", "0"))
if attempt == 0:
    faults.install_from_env()

ck = AutoCheckpoint(r"{root}", job_id="job", keep=4)
state = ck.restore() or {{"w": jnp.zeros((4,)), "epoch": -1}}
for epoch in range(ck.next_epoch, 6):
    faults.fire("train.step")
    state = {{"w": state["w"] + 1.0, "epoch": epoch}}
    ck.save(state, epoch)

final = ck.restore()
assert int(final["epoch"]) == 5, final
np.testing.assert_allclose(np.asarray(final["w"]), np.full((4,), 6.0))
open(r"{marker}", "w").close()
"""


def _run_launch(tmp_path, extra_env=None, max_restarts="1"):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(TRAIN_BODY).format(
        root=str(tmp_path / "ckpts"), marker=str(tmp_path / "done")))
    env = dict(os.environ, PYTHONPATH=REPO)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", max_restarts,
         str(script)],
        env=env, capture_output=True, text=True, timeout=240)


def test_kill_mid_training_autoresumes_from_verified_checkpoint(tmp_path):
    """Worker killed at epoch 3 (PT_FAULTS kill); the relaunch must
    restore epoch 2's verified state and finish epochs 3..5 — final
    state identical to an uninterrupted run."""
    r = _run_launch(tmp_path,
                    extra_env={"PT_FAULTS": "train.step:kill:after=3"})
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    assert (tmp_path / "done").exists()
    assert "restart 1/1" in r.stderr
    # epochs 0..2 came from attempt 0, 3..5 from the resumed attempt
    assert sorted(os.listdir(tmp_path / "ckpts" / "job")) == [
        "epoch_2", "epoch_3", "epoch_4", "epoch_5"]


def test_corrupt_newest_checkpoint_falls_back_without_intervention(
        tmp_path):
    """Run to completion, corrupt the newest checkpoint's shard (disk
    rot while the job was down), rerun: restore must skip the damaged
    epoch_5, fall back to epoch_4, and re-train epoch 5 — no operator
    action, final state still correct."""
    import glob
    r1 = _run_launch(tmp_path, max_restarts="0")
    assert r1.returncode == 0, r1.stderr[-2000:]
    (tmp_path / "done").unlink()
    shard, = glob.glob(str(tmp_path / "ckpts/job/epoch_5/data/*.npy"))
    with open(shard, "r+b") as f:
        f.truncate(8)
    r2 = _run_launch(tmp_path, max_restarts="0")
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert (tmp_path / "done").exists()
    assert "falling back" in r2.stderr


def test_kill_during_commit_orphan_tmp_is_gcd_on_resume(tmp_path):
    """Kill between save_state(tmp) and the commit rename (site
    ckpt.tmp_saved): epoch 2's .tmp dir is orphaned; the relaunch must
    GC it, resume from the last committed epoch, and finish."""
    r = _run_launch(tmp_path,
                    extra_env={"PT_FAULTS": "ckpt.tmp_saved:kill:after=2"})
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    assert (tmp_path / "done").exists()
    assert "GC'd orphaned .tmp_epoch_2" in r.stderr
    leftovers = [d for d in os.listdir(tmp_path / "ckpts" / "job")
                 if d.startswith(".tmp_")]
    assert leftovers == []
