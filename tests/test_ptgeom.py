"""ptgeom (ISSUE 20) — static kernel-geometry verification.

Per-rule fixtures for PT006–PT009 over hand-built KernelSpecs, the
inline-suppression and baseline round-trips, harvest parity against
hand-computed block bytes for the megakernel, the planted over-budget
kernel the CLI must catch BY NAME, the repo self-sweep zero-new gate,
and the autotune geometry-refusal contract.

Everything traces under ``jax.eval_shape`` (CPU, nothing executes), so
the whole file stays tier-1 fast.
"""

import functools
import importlib.util
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from paddle_tpu.analysis import baseline, engine, rules_tpu
from paddle_tpu.analysis import kernelmodel as km

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PTGEOM = os.path.join(REPO, "tools", "ptgeom.py")


# -- fixture helpers ---------------------------------------------------------

def _project(tmp_path, src=None):
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    (d / "k.py").write_text(src or ("x = 1\n" * 30))
    return engine.load_project([str(d)], root=str(tmp_path))


def _op(role="in", index=0, shape=(1024, 1024), dtype="float32",
        block=(128, 128), space="vmem", deps=None, probes=None,
        map_id=None):
    return km.OperandSpec(role=role, index=index, shape=shape,
                          dtype=dtype, block=block, space=space,
                          deps=deps, probes=probes or {},
                          map_id=map_id)


def _spec(line=3, **kw):
    defaults = dict(body="kern", path="pkg/k.py", abspath="", line=line,
                    grid=(4,), num_scalar_prefetch=0, inputs=[],
                    outputs=[], scratch=[], aliases={}, kernel="kern",
                    geometry="tiny", config="c0")
    defaults.update(kw)
    return km.KernelSpec(**defaults)


def _run(tmp_path, specs, src=None, rules=None):
    project = _project(tmp_path, src)
    project.geom_specs = specs
    return engine.run(project, rules or rules_tpu.geom_rules())


def _rules_hit(findings):
    return {f.rule for f in findings}


# -- PT006: VMEM budget ------------------------------------------------------

def test_pt006_over_budget_names_worst_geometry(tmp_path):
    small = _spec(geometry="tiny", config="bk128",
                  inputs=[_op(block=(128, 128))])
    big = _spec(geometry="r06", config="bk4096",
                inputs=[_op(shape=(8192, 8192), block=(4096, 4096))])
    findings = _run(tmp_path, [small, big])
    f = [f for f in findings if f.rule == "PT006"]
    assert len(f) == 1
    assert "kern" in f[0].message and f[0].severity == "error"
    # the worst (geometry, config) pair is named, not just the site
    assert "r06" in f[0].message and "bk4096" in f[0].message


def test_pt006_within_budget_clean(tmp_path):
    spec = _spec(inputs=[_op(block=(256, 512))],
                 outputs=[_op(role="out", block=(256, 512))],
                 scratch=[km.ScratchSpec(shape=(256, 512),
                                         dtype="float32")])
    assert "PT006" not in _rules_hit(_run(tmp_path, [spec]))


def test_vmem_estimate_double_buffers_and_skips_aliased():
    blocked = _op(index=0, block=(128, 128))              # 64 KiB
    aliased = _op(index=1, block=(128, 128))
    anyspace = _op(index=2, block=None, space="any",
                   shape=(1 << 20,))
    out = _op(role="out", index=0, block=(128, 128))
    spec = _spec(inputs=[blocked, aliased, anyspace], outputs=[out],
                 aliases={1: 0},
                 scratch=[km.ScratchSpec(shape=(128, 128),
                                         dtype="float32")])
    # 2 blocked (1 in + 1 out) x 64 KiB x double-buffer + scratch;
    # the aliased input shares the output's buffer, ANY stays in HBM
    want = 2 * (128 * 128 * 4) * km.DOUBLE_BUFFER + 128 * 128 * 4
    assert km.vmem_estimate(spec) == want


# -- PT007: tiling alignment -------------------------------------------------

def test_pt007_sublane_and_lane_misalignment(tmp_path):
    spec = _spec(inputs=[_op(index=0, block=(100, 128)),     # sublane
                         _op(index=1, block=(128, 120))])    # lane
    f = [f for f in _run(tmp_path, [spec]) if f.rule == "PT007"]
    assert len(f) == 1
    assert "sublane" in f[0].message and "lane" in f[0].message


def test_pt007_aligned_and_full_dims_clean(tmp_path):
    spec = _spec(inputs=[
        _op(index=0, block=(128, 512)),
        # trailing dim == full array extent: not a chosen tile
        _op(index=1, shape=(24, 96), block=(8, 96)),
        # block dim 1 = degenerate row-streaming: inherently padded,
        # deliberately not flagged (megakernel per-layer slabs)
        _op(index=2, shape=(24, 2048), block=(1, 2048),
            dtype="bfloat16"),
    ])
    assert "PT007" not in _rules_hit(_run(tmp_path, [spec]))


# -- PT008: aliasing contracts -----------------------------------------------

def test_pt008_unaliased_any_pool(tmp_path):
    pool_in = _op(index=0, shape=(64, 2, 128, 32), block=None,
                  space="any")
    pool_out = _op(role="out", index=0, shape=(64, 2, 128, 32),
                   block=None, space="any")
    spec = _spec(inputs=[pool_in], outputs=[pool_out], aliases={})
    f = [f for f in _run(tmp_path, [spec]) if f.rule == "PT008"]
    assert len(f) == 1 and "not input_output_aliased" in f[0].message


def test_pt008_aliased_pool_clean(tmp_path):
    pool_in = _op(index=0, shape=(64, 2, 128, 32), block=None,
                  space="any")
    pool_out = _op(role="out", index=0, shape=(64, 2, 128, 32),
                   block=None, space="any")
    spec = _spec(inputs=[pool_in], outputs=[pool_out], aliases={0: 0})
    assert "PT008" not in _rules_hit(_run(tmp_path, [spec]))


def test_pt008_diverging_index_maps(tmp_path):
    inp = _op(index=0, block=(128, 128), deps=(0,),
              probes={(1,): (1, 0)}, map_id=1)
    outp = _op(role="out", index=0, block=(128, 128), deps=(0,),
               probes={(1,): (2, 0)}, map_id=2)
    spec = _spec(inputs=[inp], outputs=[outp], aliases={0: 0})
    f = [f for f in _run(tmp_path, [spec]) if f.rule == "PT008"]
    assert len(f) == 1 and "diverge" in f[0].message


def test_pt008_same_map_object_shortcut(tmp_path):
    # identical map_id (the paged fused path reuses ONE index-map
    # callable for the aliased pair) short-circuits the probe compare
    inp = _op(index=0, block=(128, 128), deps=None, map_id=7)
    outp = _op(role="out", index=0, block=(128, 128), deps=None,
               map_id=7)
    spec = _spec(inputs=[inp], outputs=[outp], aliases={0: 0})
    assert "PT008" not in _rules_hit(_run(tmp_path, [spec]))


# -- PT009: grid-cost sanity -------------------------------------------------

def test_pt009_reread_flagged(tmp_path):
    # grid (8, 4) row-major; operand depends only on the LAST grid dim:
    # fetched 32x, 4 distinct blocks -> 8x re-read, 28 extra fetches
    op = _op(index=0, shape=(1024, 1024), block=(128, 128), deps=(1,))
    spec = _spec(grid=(8, 4), inputs=[op])
    f = [f for f in _run(tmp_path, [spec]) if f.rule == "PT009"]
    assert len(f) == 1
    assert "8x re-read" in f[0].message


def test_pt009_streaming_and_small_rereads_clean(tmp_path):
    spec = _spec(grid=(8, 4), inputs=[
        # depends on the trailing dim's run: fetched once per step but
        # every block distinct (normal streaming)
        _op(index=0, block=(128, 128), deps=(0, 1)),
        # constant map: one block, fetched once (suffix run covers all)
        _op(index=1, block=(128, 128), deps=()),
        # re-read but tiny: a (8, 128) f32 scale strip stays under the
        # PT009_MIN_EXTRA_BYTES floor
        _op(index=2, shape=(64, 1024), block=(8, 128), deps=(1,)),
        # data-dependent map (scalar-prefetch driven): unanalyzable
        _op(index=3, block=(128, 128), deps=None),
    ])
    assert "PT009" not in _rules_hit(_run(tmp_path, [spec]))


# -- suppression + baseline --------------------------------------------------

def test_inline_suppression_at_launch_site(tmp_path):
    src = ("x = 1\n"
           "# ptlint: disable=PT006 -- planted slab, see docs\n"
           "y = 2\n")
    spec = _spec(line=3,
                 inputs=[_op(shape=(8192, 8192), block=(4096, 4096))])
    assert "PT006" not in _rules_hit(_run(tmp_path, [spec], src=src))


def test_geom_baseline_roundtrip(tmp_path):
    spec = _spec(inputs=[_op(shape=(8192, 8192), block=(4096, 4096))])
    findings = _run(tmp_path, [spec])
    assert findings
    bl = tmp_path / "geom_baseline.json"
    baseline.write(str(bl), findings)
    new, known = baseline.partition(findings, baseline.load(str(bl)))
    assert not new and len(known) == len(findings)


# -- harvest parity ----------------------------------------------------------

def test_mega_harvest_parity_hand_computed():
    """mega_decode_layers at tiny geometry, L=3: the harvested spec
    must agree with hand-computed grid/prefetch/alias/block facts."""
    from paddle_tpu.ops.pallas.decode_megakernel import \
        mega_decode_layers
    p = km.LADDER["tiny"]
    dm, hq, hkv = p["dm"], p["heads"], p["kv_heads"]
    d, dt, page, L, B = dm // hq, p["dtype"], p["page"], 3, 8
    P = max(1, p["seq"] // page)
    weights = {
        "ln1_scale": km.sds((L, dm), dt),
        "ln1_bias": km.sds((L, dm), dt),
        "wqkv": km.sds((L, dm, (hq + 2 * hkv) * d), dt),
        "wo": km.sds((L, hq * d, dm), dt),
        "ln2_scale": km.sds((L, dm), dt),
        "ln2_bias": km.sds((L, dm), dt),
        "wup": km.sds((L, dm, 4 * dm), dt),
        "wdown": km.sds((L, 4 * dm, dm), dt),
    }
    x = km.sds((B, dm), dt)
    pool = km.sds((L * P + 1, hkv, page, d), dt)
    table = km.sds((B, P), "int32")
    rows = km.sds((B,), "int32")

    specs = km.harvest(
        lambda: jax.eval_shape(
            functools.partial(mega_decode_layers, page=page, n_pages=P,
                              n_heads=hq, kv_heads=hkv, head_dim=d),
            x, weights, pool, pool, table, rows, rows, rows),
        root=REPO)
    assert len(specs) == 1
    spec = specs[0]
    assert spec.grid == (L,)
    assert spec.num_scalar_prefetch == 4
    # both KV pools alias their output pools (in-place append)
    assert spec.aliases and len(spec.aliases) == 2
    assert sorted(spec.aliases.values()) == [1, 2]
    for gi in spec.aliases:
        inp = next(op for op in spec.inputs if op.index == gi)
        assert inp.space == "any" and inp.shape == pool.shape
    # the wqkv slab streams ONE layer per grid step
    wqkv = [op for op in spec.inputs
            if op.shape == (L, dm, (hq + 2 * hkv) * d)]
    assert len(wqkv) == 1
    assert wqkv[0].block == (1, dm, (hq + 2 * hkv) * d)
    assert wqkv[0].block_bytes() == dm * (hq + 2 * hkv) * d * 4
    assert wqkv[0].deps == (0,)    # layer-indexed: re-read never flags
    assert spec.path == "paddle_tpu/ops/pallas/decode_megakernel.py"
    assert km.vmem_estimate(spec) <= km.vmem_budget_bytes()


# -- CLI ---------------------------------------------------------------------

HOG_SRC = '''
import jax
from jax.experimental import pallas as pl


def _copy(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def vmem_hog(x):
    return pl.pallas_call(
        _copy,
        grid=(4,),
        in_specs=[pl.BlockSpec((4096, 4096), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((4096, 4096), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def ptgeom_cases():
    from paddle_tpu.analysis import kernelmodel as km

    def run():
        jax.eval_shape(vmem_hog, km.sds((4096, 4096), "float32"))
    return [km.GeomCase(kernel="vmem_hog", geometry="tiny",
                        config="full", run=run)]
'''


def test_cli_catches_planted_over_budget_kernel(tmp_path):
    hog = tmp_path / "hog_kernels.py"
    hog.write_text(HOG_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PTGEOM_GEOMS", None)
    proc = subprocess.run(
        [sys.executable, PTGEOM, "--extra", str(hog),
         "--kernels", "vmem_hog", "--no-table"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = proc.stdout + proc.stderr
    assert "vmem_hog" in out and "PT006" in out


def _ptgeom_main():
    spec = importlib.util.spec_from_file_location("_ptgeom_cli", PTGEOM)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_repo_self_sweep_zero_new_findings(monkeypatch, capsys):
    """The shipped tree must sweep clean: every deliberate geometry
    fact carries an inline rationale, the baseline stays EMPTY."""
    monkeypatch.delenv("PTGEOM_GEOMS", raising=False)
    monkeypatch.delenv("PT_VMEM_BUDGET_MB", raising=False)
    rc = _ptgeom_main()(["--no-table", "--stats"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "baselined: 0" in out


# -- autotune refusal --------------------------------------------------------

def test_autotune_geom_check_refuses_before_building(tmp_path):
    from paddle_tpu.ops.pallas import autotune as at
    cache = at.AutotuneCache(path=str(tmp_path / "cache.json"))
    built = []

    def build_and_run(cfg):
        built.append(cfg)

    def geom_check(cfg):
        return "PT006: slab over budget" if cfg == 128 else None

    best, timings = at.tune("k", "key1", [128, 64], build_and_run,
                            warmup=0, iters=1, cache=cache,
                            geom_check=geom_check)
    assert best == 64
    assert 128 not in built          # refused candidates never build
    assert 128 not in {c for c in timings}

    with pytest.raises(ValueError, match="geometry-refused"):
        at.tune("k", "key2", [128], build_and_run, cache=cache,
                geom_check=geom_check)


def test_resolve_vb_clamped_by_vmem_budget(monkeypatch):
    """The epilogue vocab tile self-clamps: a 2048-wide request at
    r06 scale (dm=2048, bf16) resolves to the largest 128-multiple
    whose double-buffered slab fits half the budget."""
    monkeypatch.delenv("PT_VMEM_BUDGET_MB", raising=False)
    from paddle_tpu.ops.pallas.decode_megakernel import _resolve_vb
    import jax.numpy as jnp
    assert _resolve_vb(2048, 2048, 50304, jnp.bfloat16, 24, 128) == 896
    assert _resolve_vb(2048, 1024, 50304, jnp.bfloat16, 24, 128) == 1920
    # small tiles pass through untouched
    assert _resolve_vb(256, 2048, 50304, jnp.bfloat16, 24, 128) == 256
