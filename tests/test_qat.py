"""Quantization-aware training (VERDICT r3 item 5).

Reference analog: fluid/contrib/slim/tests/test_imperative_qat.py — train a
LeNet with ImperativeQuantAware, assert accuracy parity with fp32, then
convert for inference and assert the quantized model still predicts."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.nn import functional as F
from paddle_tpu.quantization import qat
from paddle_tpu.quantization.qat import (QuantedConv2D, QuantedLinear,
                                         fake_quant)
from paddle_tpu.vision.models import LeNet


def test_fake_quant_is_ste():
    x = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
    absmax = jnp.max(jnp.abs(x))
    y = fake_quant(x, absmax, bits=8)
    # on-grid: 255 levels over [-absmax, absmax]
    scale = absmax / 127.0
    np.testing.assert_allclose(np.asarray(y / scale),
                               np.round(np.asarray(y / scale)), atol=1e-4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                               atol=float(scale) / 2 + 1e-6)
    # straight-through: gradient of sum is exactly ones
    g = jax.grad(lambda v: jnp.sum(fake_quant(v, absmax)))(x)
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x), atol=1e-6)


def test_quantize_aware_swaps_layers_and_keeps_paths():
    model = LeNet(num_classes=10)
    qmodel = qat.quantize_aware(model)
    mods = dict(qmodel.named_modules())
    assert isinstance(mods["features.layer_0"], QuantedConv2D)
    assert isinstance(mods["fc.layer_0"], QuantedLinear)
    # original weight paths survive (checkpoints stay loadable)
    p0 = dict(model.named_parameters())
    p1 = dict(qmodel.named_parameters())
    assert set(p0) == set(p1)
    for k in p0:
        np.testing.assert_array_equal(np.asarray(p0[k]), np.asarray(p1[k]))
    # the original model is untouched (deep copy)
    assert not any(isinstance(m, (QuantedConv2D, QuantedLinear))
                   for m in model.sublayers())
    # EMA range buffers exist
    assert any(k.endswith("act_absmax") for k, _ in qmodel.named_buffers())


def _toy_data(n=256, seed=0):
    """Linearly-separable-ish 8x8 'digit' images: class k lights row k."""
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 4, (n,))
    x = rs.randn(n, 1, 8, 8).astype(np.float32) * 0.3
    for i, cls in enumerate(y):
        x[i, 0, cls * 2, :] += 2.0
    return jnp.asarray(x), jnp.asarray(y, jnp.int32)


class _TinyNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 8, 3, padding=1)
        self.fc = nn.Linear(8 * 8 * 8, 4)

    def forward(self, x):
        x = F.relu(self.conv(x))
        return self.fc(x.reshape(x.shape[0], -1))


def _train(model, steps=60, lr=0.05):
    model = model.tag_paths()
    opt = optim.Momentum(learning_rate=lr, momentum=0.9)
    params, buffers = model.split_params()
    opt_state = opt.init(params)
    x, y = _toy_data()

    @jax.jit
    def step(params, buffers, opt_state, key):
        def loss_fn(p):
            m = model.merge_params({**buffers, **p})
            with nn.stateful(training=True, rng=key) as ctx:
                out = m(x)
                loss = F.cross_entropy(out, y)
            return loss, ctx.updates
        (loss, updates), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_p, new_s = opt.update(grads, opt_state, params)
        return new_p, new_s, updates, loss

    key = jax.random.PRNGKey(0)
    for i in range(steps):
        params, opt_state, updates, loss = step(
            params, buffers, opt_state, jax.random.fold_in(key, i))
        buffers = {**buffers, **updates}
    return model.merge_params({**buffers, **params}), float(loss)


def _accuracy(model, seed=1):
    x, y = _toy_data(seed=seed)
    model = model.eval()
    out = model(x)
    return float((jnp.argmax(out, -1) == y).mean())


def test_qat_reaches_fp32_parity_and_converts():
    fp32, _ = _train(_TinyNet())
    acc_fp32 = _accuracy(fp32)
    assert acc_fp32 > 0.9, acc_fp32

    qmodel = qat.quantize_aware(_TinyNet())
    qtrained, _ = _train(qmodel)
    acc_qat = _accuracy(qtrained)
    assert acc_qat >= acc_fp32 - 0.05, (acc_qat, acc_fp32)

    # EMA ranges actually trained
    absmaxes = [v for k, v in qtrained.named_buffers()
                if k.endswith("act_absmax")]
    assert absmaxes and all(float(v) > 0 for v in absmaxes)

    # convert → plain layers + int8 QuantTensor weights via the PTQ path
    served = qat.convert(qtrained)
    from paddle_tpu.quantization import QuantTensor
    qweights = [v for _, v in served.named_parameters()
                if isinstance(v, QuantTensor)]
    assert len(qweights) == 2
    acc_int8 = _accuracy(served)
    assert acc_int8 >= acc_qat - 0.05, (acc_int8, acc_qat)

    # convert(for_inference=False) keeps float weights but bakes QDQ
    plain = qat.convert(qtrained, for_inference=False)
    assert not any(isinstance(v, QuantTensor)
                   for _, v in plain.named_parameters())
    acc_plain = _accuracy(plain)
    assert acc_plain >= acc_qat - 0.05, (acc_plain, acc_qat)


def test_qat_lenet_end_to_end_smoke():
    """Full LeNet swap trains one step and converts (shape plumbing)."""
    model = qat.quantize_aware(LeNet(num_classes=10)).tag_paths()
    opt = optim.Adam(learning_rate=1e-3)
    params, buffers = model.split_params()
    opt_state = opt.init(params)
    x = jnp.asarray(np.random.RandomState(0).rand(4, 1, 28, 28), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)

    def loss_fn(p):
        m = model.merge_params({**buffers, **p})
        with nn.stateful(training=True, rng=jax.random.PRNGKey(0)) as ctx:
            loss = F.cross_entropy(m(x), y)
        return loss, ctx.updates
    (loss, updates), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    assert np.isfinite(float(loss))
    assert any(k.endswith("act_absmax") for k in updates)
    new_p, _ = opt.update(grads, opt_state, params)
    trained = model.merge_params({**buffers, **updates, **new_p})
    served = qat.convert(trained)
    out = served.eval()(x)
    assert out.shape == (4, 10)
