"""In-process PS table semantics: SSD-backed sparse table (eviction,
fault-in, persistence, parity with the RAM table) and geo-delta /
state-snapshot plumbing (≙ ssd_sparse_table.cc, GeoCommunicator,
save_persistables).  The cross-process protocol is test_rpc_ps.py."""

import numpy as np

from paddle_tpu.distributed.ps import (DenseTable, SparseTable,
                                       SSDSparseTable)


def test_ssd_matches_ram_table_through_eviction(tmp_path):
    """Same ids, same pushes → same rows, even when the SSD table's hot
    cache (4 rows) is a fraction of the 32-row working set."""
    ram = SparseTable(8, lr=0.1, optimizer="adagrad", seed=3)
    ssd = SSDSparseTable(8, str(tmp_path / "t.sqlite"), cache_rows=4,
                         lr=0.1, optimizer="adagrad", seed=3)
    rs = np.random.RandomState(0)
    for _ in range(10):
        ids = rs.randint(0, 32, size=6)
        g = rs.randn(6, 8).astype(np.float32)
        np.testing.assert_allclose(ram.pull(ids), ssd.pull(ids), atol=1e-6)
        ram.push(ids, g)
        ssd.push(ids, g)
    allids = np.arange(32)
    np.testing.assert_allclose(ram.pull(allids), ssd.pull(allids),
                               atol=1e-6)
    assert ssd.size() == ram.size() == 32
    assert len(ssd.rows) <= 4  # the LRU actually bounded RAM


def test_ssd_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "p.sqlite")
    t1 = SSDSparseTable(4, path, cache_rows=2, lr=0.5, optimizer="sgd",
                        seed=1)
    ids = np.array([1, 2, 3])
    before = t1.pull(ids)
    t1.push(ids, np.ones((3, 4), np.float32))
    after = t1.pull(ids)
    t1.flush()
    # a NEW table over the same file sees the trained rows, not lazy init
    t2 = SSDSparseTable(4, path, cache_rows=2, lr=0.5, optimizer="sgd",
                        seed=1)
    np.testing.assert_allclose(t2.pull(ids), after, atol=1e-6)
    assert not np.allclose(after, before)


def test_ssd_evictions_survive_without_flush(tmp_path):
    """Code-review regression: evicted rows must be COMMITTED at eviction
    time — crash persistence can't depend on a clean flush()."""
    path = str(tmp_path / "c.sqlite")
    t1 = SSDSparseTable(4, path, cache_rows=2, lr=0.5, optimizer="sgd",
                        seed=1)
    ids = np.arange(8)
    t1.pull(ids)
    t1.push(ids, np.ones((8, 4), np.float32))
    trained = t1.pull(ids)
    # NO flush: a second connection (≙ the restarted server) must still
    # see every evicted row
    t2 = SSDSparseTable(4, path, cache_rows=8, lr=0.5, optimizer="sgd",
                        seed=1)
    evicted = [i for i in range(8) if i not in t1.rows]
    assert len(evicted) >= 6
    np.testing.assert_allclose(t2.pull(evicted),
                               trained[np.asarray(evicted)], atol=1e-6)


def test_state_snapshot_roundtrip(tmp_path):
    for make in (lambda: SparseTable(4, seed=2),
                 lambda: SSDSparseTable(
                     4, str(tmp_path / f"s{np.random.randint(1e9)}.sqlite"),
                     cache_rows=2, seed=2)):
        t = make()
        ids = np.array([0, 5, 9])
        t.push(ids, np.full((3, 4), 2.0, np.float32))
        want = t.pull(ids)
        st = t.state()
        fresh = make()
        fresh.load_state(st)
        np.testing.assert_allclose(fresh.pull(ids), want, atol=1e-6)

    d = DenseTable((3, 2), lr=0.1, seed=4)
    d.push(np.ones((3, 2), np.float32))
    st = d.state()
    d2 = DenseTable((3, 2), lr=0.1, seed=9)
    d2.load_state(st)
    np.testing.assert_allclose(d2.pull(), d.pull())


def test_geo_delta_application():
    d = DenseTable((2, 2), lr=0.1, seed=0)
    w0 = d.pull()
    d.apply_delta(np.full((2, 2), 0.5, np.float32))
    np.testing.assert_allclose(d.pull(), w0 + 0.5)
    s = SparseTable(3, seed=0)
    r0 = s.pull([7])
    s.apply_delta([7], np.full((1, 3), -1.0, np.float32))
    np.testing.assert_allclose(s.pull([7]), r0 - 1.0)
