"""Optimizer suite tests: rosenbrock-ish convergence + API parity."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt_mod
from paddle_tpu.optimizer import lr as lr_mod


def quad_loss(params):
    return jnp.sum((params["w"] - 3.0) ** 2) + jnp.sum((params["b"] + 1.0) ** 2)


OPTS = [
    opt_mod.SGD(learning_rate=0.1),
    opt_mod.Momentum(learning_rate=0.05, momentum=0.9),
    opt_mod.Adam(learning_rate=0.3),
    opt_mod.AdamW(learning_rate=0.3, weight_decay=0.0),
    opt_mod.Adamax(learning_rate=0.3),
    opt_mod.Adagrad(learning_rate=1.0),
    opt_mod.Adadelta(learning_rate=5.0),
    opt_mod.RMSProp(learning_rate=0.1),
    opt_mod.Lamb(learning_rate=0.05, lamb_weight_decay=0.0),
    opt_mod.Lars(learning_rate=0.05),
]


@pytest.mark.parametrize("opt", OPTS, ids=lambda o: type(o).__name__)
def test_convergence(opt):
    params = {"w": jnp.zeros((3,)), "b": jnp.zeros((2,))}
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        g = jax.grad(quad_loss)(params)
        return opt.update(g, state, params)

    for _ in range(600):
        params, state = step(params, state)
    assert float(quad_loss(params)) < 5e-2, float(quad_loss(params))


def test_grad_clip_global_norm():
    clip = opt_mod.ClipGradByGlobalNorm(1.0)
    g = {"a": jnp.full((10,), 10.0), "b": jnp.full((10,), -10.0)}
    clipped = clip(g)
    total = np.sqrt(sum(float(jnp.sum(jnp.square(v)))
                        for v in clipped.values()))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_lr_scheduler_in_jit():
    sched = lr_mod.LinearWarmup(
        lr_mod.CosineAnnealingDecay(0.1, T_max=100), warmup_steps=10,
        start_lr=0.0, end_lr=0.1)
    opt = opt_mod.Adam(learning_rate=sched)
    params = {"w": jnp.zeros((3,))}
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        g = jax.grad(lambda p: jnp.sum(p["w"] ** 2))(params)
        return opt.update(g, state, params)

    for _ in range(5):
        params, state = step(params, state)
    # value_at at step 5 should be mid-warmup
    v = float(sched.value_at(jnp.asarray(5)))
    np.testing.assert_allclose(v, 0.05, rtol=1e-5)


@pytest.mark.parametrize("sched_fn", [
    lambda: lr_mod.ExponentialDecay(0.1, 0.9),
    lambda: lr_mod.PolynomialDecay(0.1, 100),
    lambda: lr_mod.PiecewiseDecay([10, 20], [0.1, 0.05, 0.01]),
    lambda: lr_mod.StepDecay(0.1, 10),
    lambda: lr_mod.MultiStepDecay(0.1, [10, 20]),
    lambda: lr_mod.NoamDecay(128, 100),
    lambda: lr_mod.OneCycleLR(0.1, 100),
    lambda: lr_mod.CyclicLR(0.01, 0.1, 20),
], ids=lambda f: type(f()).__name__)
def test_scheduler_values_finite(sched_fn):
    s = sched_fn()
    for step in [0, 1, 5, 50, 150]:
        v = float(s.value_at(jnp.asarray(step)))
        assert np.isfinite(v) and v >= 0


def test_multi_precision_master_weights():
    opt = opt_mod.Adam(learning_rate=0.1, multi_precision=True)
    params = {"w": jnp.zeros((4,), jnp.bfloat16)}
    state = opt.init(params)
    g = {"w": jnp.full((4,), 1e-3, jnp.bfloat16)}
    params, state = opt.update(g, state, params)
    assert params["w"].dtype == jnp.bfloat16
    assert state["slots"]["w"][0].dtype == jnp.float32


def test_parameters_kwarg_with_checkpoint_resume():
    """review r3: deferred bind must survive set_state_dict-before-step
    (checkpoint resume) and get_lr/state_dict before the first step."""
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu import optimizer as optim
    params = {"w": jnp.ones((3,))}
    opt = optim.Adam(learning_rate=0.1, parameters=params)
    assert abs(opt.get_lr() - 0.1) < 1e-6  # before any step: step-0 LR
    sd = opt.state_dict()               # materializes state, not a crash
    opt2 = optim.Adam(learning_rate=0.1, parameters=params)
    opt2.set_state_dict(sd)             # resume BEFORE first step
    new_p = opt2.step({"w": jnp.ones((3,))})
    assert np.isfinite(np.asarray(new_p["w"])).all()
    assert int(opt2.state_dict()["state"]["step"]) == 1
