"""Device-time attribution (ISSUE 15 tentpole): the roofline math is
pinned against hand-computed configs, the launch-tax calibration is a
real positive number cached per process, the step decomposition is
exact interval algebra, and the AOT capture pulls nonzero cost data for
a real jitted program.
"""

import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import stats
from paddle_tpu.observability import devprof
from paddle_tpu.inference.decode_engine import (
    decode_roofline_tokens_per_sec)


def _ev(name, t0_s, dur_s):
    """A minimal trace-event tuple (name, t0_ns, dur_ns) — the fields
    comm.span_intervals reads."""
    return (name, int(t0_s * 1e9), int(dur_s * 1e9))


# -- roofline math (pinned) ---------------------------------------------------

def test_roofline_formula_pinned_compute_bound():
    # 1 TF/s, 10 GB/s peaks; 2 TF + 5 GB per call: compute limb 2.0 s
    # dominates the 0.5 s memory limb -> 128 tokens / 2.0 s = 64 tok/s
    cap = devprof.CostCapture("x", flops=2.0e12, hbm_bytes=5.0e9)
    peaks = (1.0e12, 1.0e10)
    assert cap.analytic_seconds(peaks) == pytest.approx(2.0)
    assert devprof.roofline_tokens_per_sec(cap, 128, peaks=peaks) \
        == pytest.approx(64.0)


def test_roofline_formula_pinned_memory_bound():
    # 1 GF + 5 GB: memory limb 0.5 s dominates -> 100 / 0.5 = 200 tok/s
    cap = devprof.CostCapture("y", flops=1.0e9, hbm_bytes=5.0e9)
    assert devprof.roofline_tokens_per_sec(
        cap, 100, peaks=(1.0e12, 1.0e10)) == pytest.approx(200.0)


def test_roofline_empty_capture_is_no_bound():
    cap = devprof.CostCapture("z", flops=0.0, hbm_bytes=0.0)
    assert devprof.roofline_tokens_per_sec(
        cap, 100, peaks=(1e12, 1e10)) == 0.0


def test_decode_roofline_hand_computed():
    """The engine-side analytic HBM bound against longhand arithmetic:
    weights read once per step + each sequence's KV prefix."""
    class Cfg:
        n_layers = 2
        n_heads = 4
        head_dim = 8

        def num_params(self):
            return 1000

    # kv bytes/seq = 2 caches * 2 layers * 4 heads * 8 dim * 16 ctx * 2B
    # step bytes   = 1000 params * 2B + 2 seqs * 2048 * 2B = 10192
    # steps/s at 1 GB/s = 1e9 / 10192 ; tok/s = 2 * that
    want = 2 * 1e9 / (1000 * 2 + 2 * (2 * 2 * 4 * 8 * 16) * 2)
    got = decode_roofline_tokens_per_sec(Cfg(), batch=2, context=16,
                                         hbm_gbps=1.0)
    assert got == pytest.approx(want)


def test_peak_specs_env_override(monkeypatch):
    monkeypatch.setenv("PT_PROF_PEAK_FLOPS", "2.5e12")
    monkeypatch.setenv("PT_PROF_PEAK_HBM_GBPS", "100")
    f, b = devprof.peak_specs()
    assert f == pytest.approx(2.5e12)
    assert b == pytest.approx(100e9)


def test_record_roofline_gauges():
    frac = devprof.record_roofline("t_path", 50.0, 200.0)
    assert frac == pytest.approx(0.25)
    assert stats.get("prof/roofline_frac/t_path") == pytest.approx(0.25)
    assert stats.get("prof/roofline_tps/t_path") == pytest.approx(200.0)
    assert devprof.record_roofline("t_none", 50.0, 0.0) == 0.0


# -- launch tax ---------------------------------------------------------------

def test_launch_tax_calibrates_and_caches(monkeypatch):
    monkeypatch.setattr(devprof, "_launch_cache", {})
    monkeypatch.setenv("PT_PROF_LAUNCH_ITERS", "8")
    tax = devprof.launch_tax_s()
    assert 0.0 < tax < 1.0   # a no-op dispatch is not free nor seconds
    assert stats.get("prof/launch_tax_s") == pytest.approx(tax)
    # cached: the second call must not re-time
    assert devprof.launch_tax_s() == tax
    assert devprof._launch_cache["jit"] == tax


def test_pallas_launch_tax_none_off_tpu(monkeypatch):
    monkeypatch.setattr(devprof, "_launch_cache", {})
    if jax.default_backend() != "tpu":
        assert devprof.pallas_launch_tax_s() is None


def test_launch_tax_fraction_clamps_and_records():
    assert devprof.launch_tax_fraction(1000, 0.001, tax_s=1.0) == 1.0
    assert devprof.launch_tax_fraction(10, 0.0, tax_s=1.0) == 0.0
    f = devprof.launch_tax_fraction(10, 2.0, tax_s=0.01, name="t")
    assert f == pytest.approx(0.05)
    assert stats.get("prof/launch_tax_frac/t") == pytest.approx(0.05)


# -- step decomposition -------------------------------------------------------

def test_step_fractions_exact_split():
    evs = [_ev("serve/dispatch", 0.0, 4.0), _ev("serve/harvest", 6.0, 2.0)]
    out = devprof.step_fractions(evs)
    # window [0, 8]: device busy = [0,4] u [6,8] = 6s, harvest 2s
    assert out["wall_s"] == pytest.approx(8.0)
    assert out["device_frac"] == pytest.approx(0.75)
    assert out["queue_frac"] == pytest.approx(0.25)
    assert out["host_frac"] == pytest.approx(0.25)
    assert out["host_bound"] == 0.0
    assert stats.get("prof/device_frac") == pytest.approx(0.75)


def test_step_fractions_overlapping_spans_union_once():
    # overlapping dispatches + an abutting harvest must not double-count
    evs = [_ev("serve/dispatch", 0.0, 4.0),
           _ev("serve/dispatch", 2.0, 4.0),
           _ev("serve/harvest", 5.0, 3.0)]
    out = devprof.step_fractions(evs)
    assert out["device_frac"] == pytest.approx(1.0)
    assert out["host_frac"] == pytest.approx(0.0)


def test_step_fractions_flags_host_bound():
    evs = [_ev("serve/dispatch", 0.0, 1.0), _ev("serve/harvest", 9.0, 1.0)]
    out = devprof.step_fractions(evs)
    assert out["host_frac"] == pytest.approx(0.8)
    assert out["host_bound"] == 1.0


def test_step_fractions_empty_window():
    assert devprof.step_fractions([]) == {}
    assert devprof.step_fractions([_ev("other/span", 0, 1)]) == {}


# -- AOT capture --------------------------------------------------------------

def test_capture_jit_pulls_real_cost_and_records():
    f = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((64, 64), jnp.float32)
    cap = devprof.capture_jit(f, x, x, name="mm_test")
    # 64^3 MACs = 2*64^3 flops; XLA may fuse but never reports zero
    assert cap.flops > 0
    assert cap.hbm_bytes > 0
    assert stats.get("prof/flops/mm_test") == pytest.approx(cap.flops)
    assert stats.get("prof/hbm_bytes/mm_test") == pytest.approx(
        cap.hbm_bytes)


def test_engine_dispatch_cost_capture():
    """The engine hook lowers the real decode dispatch: nonzero cost,
    and the engine still serves afterwards (lowering must not consume
    the donated buffers)."""
    from paddle_tpu.models import gpt
    from paddle_tpu.inference.decode_engine import DecodeEngine
    cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=64, d_model=32,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    eng = DecodeEngine(gpt.GPT(cfg, seed=0), max_slots=2, max_len=32,
                       steps_per_call=2)
    r = eng.submit([1, 2, 3, 4], max_new_tokens=4)
    eng.run()
    cap = eng.dispatch_cost()
    assert cap.name == "decode"
    assert cap.flops > 0 and cap.hbm_bytes > 0
    r2 = eng.submit([5, 6, 7, 8], max_new_tokens=4)
    eng.run()
    assert len(r.tokens) == 4 and len(r2.tokens) == 4
